"""Kernel registry — the tunable-kernel contract (ISSUE 14, layer 1).

A :class:`KernelSpec` is everything the measurement harness needs to
search one kernel's config space safely:

* ``candidates(shape, bound)`` — the config space, ORDERED by the
  roofline verdict: a memory-bound region wants layout candidates
  (smaller blocks / different row blocking — less VMEM residency per
  byte moved) tried first, a compute-bound region wants block-size
  candidates (bigger MXU tiles) first.  The hard-coded default config
  is always a candidate, which is what makes the tuned-never-slower
  fallback guarantee structural: the winner is a min over a set that
  contains the default.
* ``constraint(shape, config)`` — the VMEM-budget/legality gate
  (:mod:`apex_tpu.tune.space`), applied BEFORE timing; an illegal
  candidate is rejected, never compiled.
* ``build(shape, interpret)`` — a :class:`TuneCase`: deterministic
  representative inputs plus a jitted ``run(config)`` closure the
  harness times, and the oracle policy (``exact`` kernels must match
  the default config's output BITWISE — row/tile partitioning that
  does not change per-element math; flash attention's online-softmax
  recurrence reorders with the KV block, so it checks to tolerance).
* ``regions`` — roofline-ledger region-name fragments that map ledger
  rows back to this kernel (:func:`apex_tpu.tune.measure.bound_from_ledger`).
* ``version`` — mirrors the kernel module's ``TUNE_VERSION``; bumping
  it invalidates every cached config for the kernel.

The six builtin kernels register from :mod:`apex_tpu.tune.kernels`
(imported lazily by :func:`load_builtin` so the kernel modules — which
themselves import ``tune.space``/``tune.dispatch`` for their dispatch
consult — never see an import cycle).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional, Tuple

__all__ = ["KernelSpec", "TuneCase", "register", "get_spec", "all_specs",
           "load_builtin"]


@dataclass
class TuneCase:
    """One concrete tuning problem: ``run(config)`` executes the kernel
    end to end (fwd+bwd where the kernel has a custom VJP) on fixed
    representative inputs and returns its outputs as a pytree; the
    harness times it and compares candidates' outputs against the
    default config's."""
    run: Callable[[Dict[str, int]], object]
    #: oracle tolerance for non-exact kernels (rtol, atol)
    tol: Tuple[float, float] = (2e-2, 2e-3)


@dataclass
class KernelSpec:
    name: str
    version: int
    #: config keys the kernel understands (the dispatch-consult filter)
    params: Tuple[str, ...]
    #: which side of the roofline the kernel's default workload stresses
    #: (the candidate-order default when no ledger verdict is supplied)
    kind: str                                    # "compute" | "memory"
    #: True: candidates must match the default config bitwise
    exact: bool
    defaults: Callable[[Mapping], Dict[str, int]]
    candidates: Callable[[Mapping, Optional[str]], List[Dict[str, int]]]
    constraint: Callable[[Mapping, Dict[str, int]], bool]
    build: Callable[[Mapping, bool], TuneCase]
    bucket: Callable[[Mapping], str]
    #: optional priority key ``(shape, config, bound) -> float``: the
    #: harness visits candidates in ascending key order (stable over a
    #: seeded shuffle, so equal-priority configs land in seeded order).
    #: This is where the ledger verdict steers the search — e.g. bigger
    #: MXU tiles first when compute-bound, smaller blocks first when
    #: memory-bound.  None: pure seeded order.
    priority: Optional[Callable[[Mapping, Dict[str, int], Optional[str]],
                                float]] = None
    #: optional ``(shape, config) -> hashable`` mapping a config to the
    #: EFFECTIVE block the kernel will actually run after its budget
    #: clamps — the harness dedupes candidates on this key, so two
    #: configs that clamp onto the same program are never both timed
    #: (and a clamped twin of the default can never be persisted as a
    #: noise "win").  None: dedupe on the raw config.
    effective: Optional[Callable[[Mapping, Dict[str, int]],
                                 object]] = None
    #: representative on-chip shape (bench / CLI default)
    example_shape: Dict[str, object] = field(default_factory=dict)
    #: small shape for interpret-mode probes (CPU CI, tests)
    small_shape: Dict[str, object] = field(default_factory=dict)
    #: roofline-ledger region-name fragments attributable to this kernel
    regions: Tuple[str, ...] = ()


_REGISTRY: Dict[str, KernelSpec] = {}
_BUILTIN_LOADED = False


def register(spec: KernelSpec) -> KernelSpec:
    """Add (or replace — re-registration is idempotent by name) one
    kernel spec; returns it so modules can keep a handle."""
    if spec.kind not in ("compute", "memory"):
        raise ValueError(f"spec.kind must be 'compute' or 'memory', "
                         f"got {spec.kind!r}")
    _REGISTRY[spec.name] = spec
    return spec


def get_spec(name: str) -> KernelSpec:
    """The registered spec, loading the builtins on first miss."""
    if name not in _REGISTRY:
        load_builtin()
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"no tunable kernel {name!r} registered; known: "
            f"{sorted(_REGISTRY)}") from None


def all_specs() -> List[KernelSpec]:
    """Every registered spec (builtins loaded), sorted by name."""
    load_builtin()
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def registered_versions() -> Dict[str, int]:
    """``{kernel: version}`` of everything registered — the
    :func:`apex_tpu.tune.store.prune_stale` input."""
    load_builtin()
    return {s.name: s.version for s in _REGISTRY.values()}


def load_builtin() -> None:
    """Import the builtin registrations (flash_attention,
    fused_layer_norm, bn_relu_residual, xentropy, quantized_matmul).
    Idempotent; kernels keep importing fine without it — this is the
    tuner/CLI side only."""
    global _BUILTIN_LOADED
    if _BUILTIN_LOADED:
        return
    from . import kernels as _kernels        # noqa: F401  (registers)
    _BUILTIN_LOADED = True
