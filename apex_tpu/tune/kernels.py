"""Builtin kernel registrations — every Pallas kernel family in the
repo declares its config space here (ISSUE 14).

Imported lazily by :func:`apex_tpu.tune.registry.load_builtin` (the
tuner/CLI side); the kernel modules themselves only import the light
``tune.space``/``tune.dispatch`` halves, so there is no import cycle.

Per-spec notes:

* **flash_attention** (fwd+bwd) — ``block_q``/``block_k`` over the
  MXU-friendly multiples of 128 that tile the sequence; the tune case
  runs ``value_and_grad`` through the custom VJP so the dq/dkv backward
  kernels are half the measured clock, exactly as in training.  The
  online-softmax recurrence reorders with the KV block, so the oracle
  checks to tolerance, not bitwise.
* **fused_layer_norm / bn_relu_residual / xentropy** — ``row_block``
  sweeps; row partitioning never changes per-row math, so candidates
  must match the default config BITWISE.
* **quantized_matmul** — ``block_m``/``block_n`` tiles; each output
  element is an int32 dot over the full K regardless of tile, so the
  oracle is bitwise too.
* **conv2d** — ``block_m`` (im2col row tile) / ``block_n`` (output
  channels); the tap loop is static and each tap contracts the FULL
  input-channel axis in one dot, so partitioning never reorders an
  output element's reduction: bitwise across configs.  The case runs
  ``value_and_grad`` through the fused conv+bn_relu_residual custom
  VJP so dgrad/wgrad are part of the measured clock.  kind="memory":
  the r05 resnet ledger calls the stage1/stage2 conv regions
  memory-bound, so small blocks visit first.

Candidate priority (the ledger hook): memory-bound verdicts visit
smaller blocks first (layout/pipelining candidates — more grid steps,
less VMEM residency per byte), compute-bound verdicts visit bigger
tiles first (amortize the per-block epilogue over more MXU work —
the r4 flash sweep's measured gradient).
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional

import jax
import jax.numpy as jnp

from .registry import KernelSpec, TuneCase, register
from . import space as _space

# The kernel packages re-export their public functions from __init__
# (``apex_tpu.ops.flash_attention`` the ATTRIBUTE is the function), so
# module access goes through importlib.
import importlib


def _mod(name):
    return importlib.import_module("apex_tpu." + name)

__all__ = ["FLASH_ATTENTION", "FUSED_LAYER_NORM", "BN_RELU_RESIDUAL",
           "XENTROPY", "QUANTIZED_MATMUL", "CONV2D"]

#: generous flash-kernel VMEM estimate budget (operand + score blocks +
#: scratch; the proven-on-chip 1024x1024 default must pass)
_FLASH_VMEM_BUDGET = int(14e6)


def _area_priority(area: float, bound: Optional[str]) -> float:
    # ascending visit order: memory-bound -> small blocks first,
    # compute-bound (and None) -> big tiles first
    return area if bound == "memory" else -area


# -- flash attention (fwd + bwd) ----------------------------------------------

def _flash_dims(shape: Mapping):
    return (int(shape.get("batch", 1)), int(shape.get("heads", 2)),
            int(shape.get("q_len", 1024)), int(shape.get("kv_len", 1024)),
            int(shape.get("head_dim", 64)),
            bool(shape.get("causal", True)),
            jnp.dtype(shape.get("dtype", "float32")))


def _flash_block_legal(t: int, blk: int) -> bool:
    fa = _mod("ops.flash_attention")
    return fa._pick_block(t, blk) == (blk if t > blk else t)


def _flash_fits(shape: Mapping, cfg: Dict[str, int]) -> bool:
    _, _, tq, tk, d, _, dtype = _flash_dims(shape)
    bq, bk = int(cfg["block_q"]), int(cfg["block_k"])
    if not (_flash_block_legal(tq, bq) and _flash_block_legal(tk, bk)):
        return False
    isz = dtype.itemsize
    # two live fp32 [bq, bk] score/prob blocks + fp32 acc + operand
    # blocks + the [bq, 1] row stats
    est = (8 * bq * bk + 4 * bq * d + isz * (bq + 2 * bk) * d + 8 * bq)
    return est <= _FLASH_VMEM_BUDGET


def _flash_defaults(shape: Mapping) -> Dict[str, int]:
    fa = _mod("ops.flash_attention")
    _, _, tq, tk, _, _, _ = _flash_dims(shape)
    bq = fa._pick_block(tq, fa._DEFAULT_BLOCK_Q)
    bk = fa._pick_block(tk, fa._DEFAULT_BLOCK_K)
    return {"block_q": int(bq or min(tq, fa._DEFAULT_BLOCK_Q)),
            "block_k": int(bk or min(tk, fa._DEFAULT_BLOCK_K))}


def _flash_candidates(shape: Mapping, bound: Optional[str]
                      ) -> List[Dict[str, int]]:
    _, _, tq, tk, _, _, _ = _flash_dims(shape)
    sizes = (128, 256, 512, 1024, 2048)
    out = []
    for bq in sizes:
        if bq > tq:
            continue
        for bk in sizes:
            if bk > tk:
                continue
            cfg = {"block_q": bq, "block_k": bk}
            if _flash_fits(shape, cfg):
                out.append(cfg)
    return out


def _flash_case(shape: Mapping, interpret: bool) -> TuneCase:
    import jax.random as jrandom
    flash_attention = _mod("ops.flash_attention").flash_attention
    b, h, tq, tk, d, causal, dtype = _flash_dims(shape)
    kq, kk, kv = jrandom.split(jrandom.PRNGKey(0), 3)
    q = (jrandom.normal(kq, (b, tq, h, d), jnp.float32) * 0.3).astype(dtype)
    k = (jrandom.normal(kk, (b, tk, h, d), jnp.float32) * 0.3).astype(dtype)
    v = (jrandom.normal(kv, (b, tk, h, d), jnp.float32) * 0.3).astype(dtype)
    fns: Dict[tuple, object] = {}

    def run(cfg):
        key = (int(cfg["block_q"]), int(cfg["block_k"]))
        f = fns.get(key)
        if f is None:
            bq, bk = key

            def loss(q, k, v):
                o = flash_attention(q, k, v, causal=causal, block_q=bq,
                                    block_k=bk, interpret=interpret)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            f = fns[key] = jax.jit(
                jax.value_and_grad(loss, argnums=(0, 1, 2)))
        return f(q, k, v)

    return TuneCase(run=run, tol=(2e-2, 2e-3))


def _flash_bucket(shape: Mapping) -> str:
    fa = _mod("ops.flash_attention")
    _, _, tq, tk, d, causal, _ = _flash_dims(shape)
    return fa.tune_bucket(tq, tk, d, causal, False, False)


def _flash_version() -> int:
    fa = _mod("ops.flash_attention")
    return fa.TUNE_VERSION


def _flash_effective(shape: Mapping, cfg: Dict[str, int]):
    fa = _mod("ops.flash_attention")
    _, _, tq, tk, _, _, _ = _flash_dims(shape)
    return (fa._pick_block(tq, int(cfg["block_q"])),
            fa._pick_block(tk, int(cfg["block_k"])))


FLASH_ATTENTION = register(KernelSpec(
    name="flash_attention", version=_flash_version(),
    params=("block_q", "block_k"), kind="compute", exact=False,
    defaults=_flash_defaults, candidates=_flash_candidates,
    constraint=_flash_fits, build=_flash_case, bucket=_flash_bucket,
    priority=lambda shape, cfg, bound: _area_priority(
        cfg["block_q"] * cfg["block_k"], bound),
    effective=_flash_effective,
    example_shape={"batch": 1, "heads": 8, "q_len": 4096, "kv_len": 4096,
                   "head_dim": 64, "causal": True, "dtype": "bfloat16"},
    small_shape={"batch": 1, "heads": 2, "q_len": 256, "kv_len": 256,
                 "head_dim": 64, "causal": True, "dtype": "float32"},
    regions=("attention", "flash", "attn")))


# -- row-blocked elementwise kernels ------------------------------------------

def _rows_priority(cfg, bound):
    return _area_priority(cfg["row_block"], bound)


def _ln_dims(shape: Mapping):
    return (int(shape.get("n1", 8192)), int(shape.get("n2", 1024)),
            jnp.dtype(shape.get("dtype", "float32")))


def _ln_candidates(shape: Mapping, bound: Optional[str]):
    n1, n2, dtype = _ln_dims(shape)
    # the backward block is the worst case (g, x, dx + 4 fp32 temps)
    blocks = _space.row_block_candidates(n1, n2, 3 * dtype.itemsize + 16)
    return [{"row_block": b} for b in blocks]


def _ln_constraint(shape: Mapping, cfg: Dict[str, int]) -> bool:
    _, n2, dtype = _ln_dims(shape)
    return cfg["row_block"] % _space.SUBLANE_ROWS == 0 \
        and _space.floor_block_fits(n2, 3 * dtype.itemsize + 16)


def _ln_case(shape: Mapping, interpret: bool) -> TuneCase:
    import jax.random as jrandom
    fused_layer_norm = _mod("normalization.fused_layer_norm").fused_layer_norm
    n1, n2, dtype = _ln_dims(shape)
    x = (jrandom.normal(jrandom.PRNGKey(0), (n1, n2), jnp.float32)
         ).astype(dtype)
    w = jnp.linspace(0.5, 1.5, n2, dtype=jnp.float32)
    b = jnp.linspace(-0.1, 0.1, n2, dtype=jnp.float32)
    fns: Dict[int, object] = {}

    def run(cfg):
        rb = int(cfg["row_block"])
        f = fns.get(rb)
        if f is None:
            def loss(x, w, b):
                o = fused_layer_norm(x, (n2,), w, b, impl="pallas",
                                     row_block=rb, interpret=interpret)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            f = fns[rb] = jax.jit(jax.value_and_grad(loss,
                                                     argnums=(0, 1, 2)))
        return f(x, w, b)

    return TuneCase(run=run)


def _ln_bucket(shape: Mapping) -> str:
    fln = _mod("normalization.fused_layer_norm")
    n1, n2, dtype = _ln_dims(shape)
    return fln.tune_bucket(n1, n2, dtype.itemsize)


def _ln_version() -> int:
    fln = _mod("normalization.fused_layer_norm")
    return fln.TUNE_VERSION


def _ln_effective(shape: Mapping, cfg: Dict[str, int]):
    n1, n2, dtype = _ln_dims(shape)
    isz = dtype.itemsize
    # (fwd, bwd) effective blocks — both clamps must agree for two
    # configs to be the same program
    return (_space.pick_rows(n1, n2, 2 * isz + 12,
                             row_block=cfg["row_block"]),
            _space.pick_rows(n1, n2, 3 * isz + 16,
                             row_block=cfg["row_block"]))


FUSED_LAYER_NORM = register(KernelSpec(
    name="fused_layer_norm", version=_ln_version(),
    params=("row_block",), kind="memory", exact=True,
    defaults=lambda shape: {"row_block": 256},
    candidates=_ln_candidates, constraint=_ln_constraint,
    build=_ln_case, bucket=_ln_bucket,
    priority=lambda shape, cfg, bound: _rows_priority(cfg, bound),
    effective=_ln_effective,
    example_shape={"n1": 8192, "n2": 1024, "dtype": "bfloat16"},
    small_shape={"n1": 64, "n2": 128, "dtype": "float32"},
    regions=("layer_norm", "layernorm", "ln")))


def _bn_dims(shape: Mapping):
    return (int(shape.get("rows", 16384)), int(shape.get("channels", 256)),
            bool(shape.get("residual", True)),
            jnp.dtype(shape.get("dtype", "float32")))


def _bn_candidates(shape: Mapping, bound: Optional[str]):
    rows, c, has_z, dtype = _bn_dims(shape)
    blocks = _space.row_block_candidates(rows, c, 4 * dtype.itemsize + 12)
    return [{"row_block": b} for b in blocks]


def _bn_constraint(shape: Mapping, cfg: Dict[str, int]) -> bool:
    _, c, _, dtype = _bn_dims(shape)
    return cfg["row_block"] % _space.SUBLANE_ROWS == 0 \
        and _space.floor_block_fits(c, 3 * dtype.itemsize + 8)


def _bn_case(shape: Mapping, interpret: bool) -> TuneCase:
    import jax.random as jrandom
    bn_relu_residual = _mod("normalization.fused_bn_act").bn_relu_residual
    rows, c, has_z, dtype = _bn_dims(shape)
    keys = jrandom.split(jrandom.PRNGKey(0), 2)
    x = (jrandom.normal(keys[0], (rows, c), jnp.float32)).astype(dtype)
    z = (jrandom.normal(keys[1], (rows, c), jnp.float32)).astype(dtype) \
        if has_z else None
    mean = jnp.linspace(-0.2, 0.2, c, dtype=jnp.float32)
    invstd = jnp.linspace(0.8, 1.2, c, dtype=jnp.float32)
    scale = jnp.linspace(0.5, 1.5, c, dtype=jnp.float32)
    bias = jnp.linspace(-0.1, 0.1, c, dtype=jnp.float32)
    fns: Dict[int, object] = {}

    def run(cfg):
        rb = int(cfg["row_block"])
        f = fns.get(rb)
        if f is None:
            argnums = (0, 1, 2, 3, 4) + ((5,) if has_z else ())

            def loss(x, mean, invstd, scale, bias, *rest):
                o = bn_relu_residual(x, mean, invstd, scale, bias,
                                     z=(rest[0] if has_z else None),
                                     impl="pallas", interpret=interpret,
                                     row_block=rb)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            f = fns[rb] = jax.jit(jax.value_and_grad(loss,
                                                     argnums=argnums))
        args = (x, mean, invstd, scale, bias) + ((z,) if has_z else ())
        return f(*args)

    return TuneCase(run=run)


def _bn_bucket(shape: Mapping) -> str:
    fba = _mod("normalization.fused_bn_act")
    rows, c, has_z, dtype = _bn_dims(shape)
    return fba.tune_bucket(rows, c, dtype.itemsize, has_z)


def _bn_version() -> int:
    fba = _mod("normalization.fused_bn_act")
    return fba.TUNE_VERSION


def _bn_effective(shape: Mapping, cfg: Dict[str, int]):
    rows, c, _, dtype = _bn_dims(shape)
    isz = dtype.itemsize
    return (_space.pick_rows(rows, c, 3 * isz + 8,
                             row_block=cfg["row_block"]),
            _space.pick_rows(rows, c, 4 * isz + 12,
                             row_block=cfg["row_block"]))


BN_RELU_RESIDUAL = register(KernelSpec(
    name="bn_relu_residual", version=_bn_version(),
    params=("row_block",), kind="memory", exact=True,
    defaults=lambda shape: {"row_block": 256},
    candidates=_bn_candidates, constraint=_bn_constraint,
    build=_bn_case, bucket=_bn_bucket,
    priority=lambda shape, cfg, bound: _rows_priority(cfg, bound),
    effective=_bn_effective,
    example_shape={"rows": 16384, "channels": 256, "residual": True,
                   "dtype": "bfloat16"},
    small_shape={"rows": 64, "channels": 128, "residual": True,
                 "dtype": "float32"},
    regions=("bn", "batchnorm", "stage", "downsample")))


def _xe_dims(shape: Mapping):
    return (int(shape.get("rows", 4096)), int(shape.get("vocab", 8192)))


def _xe_candidates(shape: Mapping, bound: Optional[str]):
    xe = _mod("contrib.xentropy")
    n, h = _xe_dims(shape)
    out, seen = [], set()
    for blk in (8, 16, 32, 64, 128, 256, 512):
        eff = xe._row_block(n, h, blk)
        if eff in seen:
            continue
        seen.add(eff)
        out.append({"row_block": blk})
    return out


def _xe_constraint(shape: Mapping, cfg: Dict[str, int]) -> bool:
    xe = _mod("contrib.xentropy")
    _, h = _xe_dims(shape)
    return cfg["row_block"] % _space.SUBLANE_ROWS == 0 \
        and xe._pallas_fits(h)


def _xe_case(shape: Mapping, interpret: bool) -> TuneCase:
    import jax.random as jrandom
    xe = _mod("contrib.xentropy")
    n, h = _xe_dims(shape)
    logits = jrandom.normal(jrandom.PRNGKey(0), (n, h), jnp.float32)
    labels = jrandom.randint(jrandom.PRNGKey(1), (n,), 1, h, jnp.int32)
    g = jnp.linspace(0.5, 1.5, n, dtype=jnp.float32)
    fns: Dict[int, object] = {}

    def run(cfg):
        rb = int(cfg["row_block"])
        f = fns.get(rb)
        if f is None:
            def both(logits, g):
                losses, mlse = xe._fwd_pallas(logits, labels, 0.1,
                                              interpret, rb)
                dx = xe._bwd_pallas(g, logits, mlse, labels, 0.1,
                                    interpret, rb)
                return losses, mlse, dx

            f = fns[rb] = jax.jit(both)
        return f(logits, g)

    return TuneCase(run=run)


def _xe_bucket(shape: Mapping) -> str:
    xe = _mod("contrib.xentropy")
    n, h = _xe_dims(shape)
    return xe.tune_bucket(n, h)


def _xe_version() -> int:
    xe = _mod("contrib.xentropy")
    return xe.TUNE_VERSION


def _xe_effective(shape: Mapping, cfg: Dict[str, int]):
    xe = _mod("contrib.xentropy")
    n, h = _xe_dims(shape)
    return xe._row_block(n, h, cfg["row_block"])


XENTROPY = register(KernelSpec(
    name="xentropy", version=_xe_version(),
    params=("row_block",), kind="memory", exact=True,
    defaults=lambda shape: {"row_block": 128},
    candidates=_xe_candidates, constraint=_xe_constraint,
    build=_xe_case, bucket=_xe_bucket,
    priority=lambda shape, cfg, bound: _rows_priority(cfg, bound),
    effective=_xe_effective,
    example_shape={"rows": 4096, "vocab": 8192},
    small_shape={"rows": 32, "vocab": 128},
    regions=("xent", "loss", "softmax", "cross_entropy")))


# -- quantized matmul ---------------------------------------------------------

def _qmm_dims(shape: Mapping):
    return (int(shape.get("m", 8192)), int(shape.get("k", 4096)),
            int(shape.get("n", 4096)),
            jnp.dtype(shape.get("dtype", "bfloat16")))


def _qmm_candidates(shape: Mapping, bound: Optional[str]):
    m, k, n, dtype = _qmm_dims(shape)
    out = []
    for bm in (64, 128, 256, 512):
        for bn in (128, 256, 512):
            cfg = {"block_m": bm, "block_n": bn}
            if _qmm_constraint(shape, cfg):
                out.append(cfg)
    return out


def _qmm_constraint(shape: Mapping, cfg: Dict[str, int]) -> bool:
    qk = _mod("quant.kernels")
    m, k, n, dtype = _qmm_dims(shape)
    bm = qk._pick_block(m, int(cfg["block_m"]), 8)
    bn = qk._pick_block(n, int(cfg["block_n"]), 128)
    return qk._kernel_fits(bm, bn, k, dtype.itemsize)


def _qmm_case(shape: Mapping, interpret: bool) -> TuneCase:
    import jax.random as jrandom
    quantized_matmul = _mod("quant.kernels").quantized_matmul
    m, k, n, dtype = _qmm_dims(shape)
    x = (jrandom.normal(jrandom.PRNGKey(0), (m, k), jnp.float32) * 0.05
         ).astype(dtype)
    w = (jrandom.normal(jrandom.PRNGKey(1), (k, n), jnp.float32) * 0.05
         ).astype(dtype)
    # frozen calibration constant for the synthetic normal(0, 0.05)
    # activations (amax ~5 sigma); NOT a per-call absmax — J014's rule
    x_scale = 0.25 / 127.0
    fns: Dict[tuple, object] = {}

    def run(cfg):
        key = (int(cfg["block_m"]), int(cfg["block_n"]))
        f = fns.get(key)
        if f is None:
            bm, bn = key

            def loss(x, w):
                o = quantized_matmul(x, w, x_scale=x_scale, impl="pallas",
                                     interpret=interpret, block_m=bm,
                                     block_n=bn)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            f = fns[key] = jax.jit(jax.value_and_grad(loss,
                                                      argnums=(0, 1)))
        return f(x, w)

    return TuneCase(run=run)


def _qmm_bucket(shape: Mapping) -> str:
    qk = _mod("quant.kernels")
    m, k, n, dtype = _qmm_dims(shape)
    return qk.tune_bucket(m, k, n, dtype.itemsize)


def _qmm_version() -> int:
    qk = _mod("quant.kernels")
    return qk.TUNE_VERSION


def _qmm_effective(shape: Mapping, cfg: Dict[str, int]):
    qk = _mod("quant.kernels")
    m, k, n, _ = _qmm_dims(shape)
    return (qk._pick_block(m, int(cfg["block_m"]), 8),
            qk._pick_block(n, int(cfg["block_n"]), 128))


QUANTIZED_MATMUL = register(KernelSpec(
    name="quantized_matmul", version=_qmm_version(),
    params=("block_m", "block_n"), kind="compute", exact=True,
    defaults=lambda shape: {"block_m": 256, "block_n": 256},
    candidates=_qmm_candidates, constraint=_qmm_constraint,
    build=_qmm_case, bucket=_qmm_bucket,
    priority=lambda shape, cfg, bound: _area_priority(
        cfg["block_m"] * cfg["block_n"], bound),
    effective=_qmm_effective,
    example_shape={"m": 8192, "k": 4096, "n": 4096, "dtype": "bfloat16"},
    small_shape={"m": 64, "k": 128, "n": 128, "dtype": "float32"},
    regions=("quant", "qmm", "dense", "proj", "mlp")))


# -- pallas conv2d (implicit GEMM + fused epilogue) ---------------------------

def _conv_dims(shape: Mapping):
    return (int(shape.get("batch", 32)), int(shape.get("h", 28)),
            int(shape.get("w", 28)), int(shape.get("cin", 128)),
            int(shape.get("cout", 128)), int(shape.get("kh", 3)),
            int(shape.get("kw", 3)), int(shape.get("stride", 1)),
            jnp.dtype(shape.get("dtype", "bfloat16")),
            bool(shape.get("residual", True)))


def _conv_candidates(shape: Mapping, bound: Optional[str]):
    out = []
    for bm in (128, 256, 512, 1024):
        for bn in (128, 256, 512):
            cfg = {"block_m": bm, "block_n": bn}
            if _conv_constraint(shape, cfg):
                out.append(cfg)
    return out


def _conv_constraint(shape: Mapping, cfg: Dict[str, int]) -> bool:
    cv = _mod("ops.conv")
    n, h, w, cin, cout, kh, kw, s, dtype, res = _conv_dims(shape)
    padding = cv._norm_padding("SAME", h, w, kh, kw, s, s, 1, 1)
    # want_preact=True: the training forward (epilogue + custom VJP)
    # also streams the saved pre-activation block, the worst case.
    return cv._fwd_fits(h, w, padding, cin, cout, kh, kw, s, s, 1, 1,
                        int(cfg["block_m"]), int(cfg["block_n"]),
                        dtype.itemsize, res, True)


def _conv_case(shape: Mapping, interpret: bool) -> TuneCase:
    import jax.random as jrandom
    cv = _mod("ops.conv")
    n, h, w, cin, cout, kh, kw, s, dtype, res = _conv_dims(shape)
    x = (jrandom.normal(jrandom.PRNGKey(0), (n, h, w, cin), jnp.float32)
         ).astype(dtype)
    wt = (jrandom.normal(jrandom.PRNGKey(1), (kh, kw, cin, cout),
                         jnp.float32) * 0.05).astype(dtype)
    mean = jnp.zeros((cout,), jnp.float32)
    invstd = jnp.ones((cout,), jnp.float32)
    scale = jnp.ones((cout,), jnp.float32)
    bias = jnp.zeros((cout,), jnp.float32)
    oh, ow = -(-h // s), -(-w // s)
    z = (jnp.ones((n, oh, ow, cout), jnp.float32).astype(dtype)
         if res else None)
    fns: Dict[tuple, object] = {}

    def run(cfg):
        key = (int(cfg["block_m"]), int(cfg["block_n"]))
        f = fns.get(key)
        if f is None:
            bm, bn = key

            def loss(x, wt, mean, invstd, scale, bias):
                o = cv.conv2d(x, wt, stride=s, padding="SAME",
                              mean=mean, invstd=invstd, scale=scale,
                              bias=bias, z=z, relu=True, impl="pallas",
                              interpret=interpret, block_m=bm,
                              block_n=bn)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            f = fns[key] = jax.jit(jax.value_and_grad(
                loss, argnums=(0, 1, 2, 3, 4, 5)))
        return f(x, wt, mean, invstd, scale, bias)

    return TuneCase(run=run)


def _conv_bucket(shape: Mapping) -> str:
    cv = _mod("ops.conv")
    n, h, w, cin, cout, kh, kw, s, dtype, res = _conv_dims(shape)
    oh, ow = -(-h // s), -(-w // s)
    return cv.tune_bucket(n, oh, ow, cin, cout, kh, kw, s, s, 1, 1,
                          dtype.itemsize, True, res)


def _conv_version() -> int:
    return _mod("ops.conv").TUNE_VERSION


def _conv_effective(shape: Mapping, cfg: Dict[str, int]):
    cv = _mod("ops.conv")
    n, h, w, cin, cout, kh, kw, s, dtype, res = _conv_dims(shape)
    oh, ow = -(-h // s), -(-w // s)
    return (cv._pick_boh(oh, ow, int(cfg["block_m"])),
            cv._pick_block(cout, int(cfg["block_n"]), 128))


CONV2D = register(KernelSpec(
    name="conv2d", version=_conv_version(),
    params=("block_m", "block_n"), kind="memory", exact=True,
    defaults=lambda shape: {"block_m": 512, "block_n": 256},
    candidates=_conv_candidates, constraint=_conv_constraint,
    build=_conv_case, bucket=_conv_bucket,
    priority=lambda shape, cfg, bound: _area_priority(
        cfg["block_m"] * cfg["block_n"], bound),
    effective=_conv_effective,
    example_shape={"batch": 32, "h": 28, "w": 28, "cin": 128,
                   "cout": 128, "kh": 3, "kw": 3, "stride": 1,
                   "dtype": "bfloat16", "residual": True},
    small_shape={"batch": 2, "h": 8, "w": 8, "cin": 8, "cout": 16,
                 "kh": 3, "kw": 3, "stride": 1, "dtype": "float32",
                 "residual": True},
    regions=("conv", "stage", "downsample")))
