"""Dispatch-time config consult — the hot-path half of the autotuner.

Every registered kernel calls :func:`kernel_config` at TRACE time (the
block constants are Python ints baked into the program, so this runs
once per compiled signature, never per step) when the caller left the
block arguments at their defaults and the Pallas path was chosen.  A
hit returns the persisted per-device config; a miss — or ANY cache
problem — returns None and the kernel uses its hard-coded defaults,
so the tuner can only ever make dispatch faster, never break it.

CPU/interpret paths never tune: this module only *reads*; measurement
lives in :mod:`apex_tpu.tune.measure` and runs explicitly (CLI or API).

Telemetry: each consult refreshes the ``tuned_kernel_pct`` gauge on the
active recorder's metrics registry (the fraction of distinct consulted
kernels whose latest lookup hit the cache — exported live through the
existing Prometheus path), and the FIRST consult of each (kernel,
bucket) emits one ``tune`` event with ``phase="dispatch"`` so a
timeline shows which kernels ran tuned and which fell back.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from . import store

__all__ = ["kernel_config", "dispatch_stats", "reset_stats"]

_lock = threading.Lock()
#: latest consult outcome per kernel name (True = tuned config served)
_LATEST: Dict[str, bool] = {}
#: cumulative consult counters per kernel
_COUNTS: Dict[str, Dict[str, int]] = {}
#: (kernel, bucket) pairs already announced via a ``tune`` event
_ANNOUNCED: set = set()


def kernel_config(kernel: str, version: int, bucket: str, *,
                  params: Tuple[str, ...] = ()
                  ) -> Optional[Dict[str, int]]:
    """The tuned config for ``(device kind, kernel, version, bucket)``
    or None (use the hard-coded defaults).  ``params`` names the config
    keys the kernel understands; an entry whose key set differs — extra
    keys from a hand-edited file or a future kernel's richer space, OR
    missing keys from a partially-written entry — is rejected as a miss
    rather than passed through to ``pallas_call`` (kernels index the
    config unconditionally, so a one-sided check would let a partial
    entry crash dispatch).  Never raises.
    """
    cfg = store.lookup(kernel, version, bucket)
    if cfg is not None and params:
        # bool is an int subclass: a hand-edited JSON `true` would pass
        # a bare isinstance(int) check and reach _pick_block as 1
        if set(cfg) != set(params) \
                or not all(isinstance(v, int)
                           and not isinstance(v, bool) and v > 0
                           for v in cfg.values()):
            cfg = None
    hit = cfg is not None
    with _lock:
        _LATEST[kernel] = hit
        c = _COUNTS.setdefault(kernel, {"hits": 0, "misses": 0})
        c["hits" if hit else "misses"] += 1
        pct = 100.0 * sum(_LATEST.values()) / len(_LATEST)
        announce = (kernel, bucket) not in _ANNOUNCED
        if announce:
            _ANNOUNCED.add((kernel, bucket))
    try:
        from ..telemetry import get_recorder
        rec = get_recorder()
        if rec is not None:
            rec.metrics.gauge("tuned_kernel_pct").set(pct)
            if announce:
                rec.event("tune", phase="dispatch", kernel=kernel,
                          bucket=bucket, hit=hit,
                          config=(dict(cfg) if cfg else None))
    except Exception:           # telemetry must never break dispatch
        pass
    return cfg


def dispatch_stats() -> Dict[str, object]:
    """Consult counters: ``{"tuned_kernel_pct", "by_kernel": {name:
    {"hits", "misses", "tuned"}}}`` — what the gauge reports, readable
    without a recorder (the examples' exit line, tests)."""
    with _lock:
        by = {k: {"hits": v["hits"], "misses": v["misses"],
                  "tuned": _LATEST.get(k, False)}
              for k, v in _COUNTS.items()}
        pct = (100.0 * sum(_LATEST.values()) / len(_LATEST)
               if _LATEST else None)
    return {"tuned_kernel_pct": pct, "by_kernel": by}


def reset_stats() -> None:
    """Clear consult counters/announcements (test isolation)."""
    with _lock:
        _LATEST.clear()
        _COUNTS.clear()
        _ANNOUNCED.clear()
