"""apex_tpu.tune — roofline-driven Pallas kernel autotuner (ISSUE 14).

Every Pallas kernel in the repo used to ship hand-picked block constants
from a single v5e sweep (``_DEFAULT_BLOCK_Q/_K`` in flash attention,
``_ROW_BLOCK`` in the normalization epilogues, ``_BLOCK_M/_N`` in the
quantized matmuls).  This package replaces those frozen sweeps with a
measured, per-device search:

* :mod:`~apex_tpu.tune.registry` — each tunable kernel declares its
  config space (block sizes / grid layouts), VMEM-budget constraint,
  correctness oracle, and which roofline-ledger regions it lives in.
  flash_attention (fwd+bwd), fused_layer_norm, bn_relu_residual,
  contrib xentropy, and the quantized matmuls all register.
* :mod:`~apex_tpu.tune.measure` — times candidate configs on-device
  (min-of-K with explicit sync, compile excluded; candidates failing
  the oracle or the VMEM gate are rejected before timing) and
  prioritizes the search by a roofline ledger's compute-vs-memory
  boundedness verdicts (:func:`~apex_tpu.tune.measure.bound_from_ledger`).
* :mod:`~apex_tpu.tune.store` — persistent config cache keyed by
  ``(device kind, kernel name, kernel version, shape bucket)``, stored
  beside :mod:`apex_tpu.cache`'s XLA compilation cache
  (:func:`apex_tpu.cache.enable` points both at the same directory).
* :mod:`~apex_tpu.tune.dispatch` — the zero-cost consult every
  registered kernel makes at dispatch time; a miss (or any cache
  problem) falls back to the kernel's hard-coded defaults.  CPU and
  interpret paths never tune — tuning is always an explicit
  :func:`~apex_tpu.tune.measure.tune_kernel` / CLI run.
* :mod:`~apex_tpu.tune.space` — the shared VMEM-budget / row-block
  math both the normalization kernels and the tuner's constraint
  checker use (hoisted out of ``fused_layer_norm``/``fused_bn_act``).

CLI::

    python -m apex_tpu.tune kernel flash_attention        # tune one
    python -m apex_tpu.tune ledger LEDGER.json            # ledger-driven
    python -m apex_tpu.tune show                          # cached table

Telemetry: the tuner emits ``tune`` events and dispatch maintains a
``tuned_kernel_pct`` gauge (exported through the existing Prometheus
path).  See ``docs/tune.md``.
"""

from . import space                                     # noqa: F401
from .dispatch import kernel_config, dispatch_stats     # noqa: F401
from .store import lookup, put, entries, cache_path     # noqa: F401

__all__ = ["space", "kernel_config", "dispatch_stats", "lookup", "put",
           "entries", "cache_path", "KernelSpec", "register", "get_spec",
           "all_specs", "load_builtin", "tune_kernel", "tune_from_ledger",
           "bound_from_ledger", "TuneResult"]

# The registry/measure layers import the kernel modules (which in turn
# import tune.space/tune.dispatch) — load them lazily so the kernel
# modules can import this package without a cycle.
_LAZY = {
    "KernelSpec": ("registry", "KernelSpec"),
    "register": ("registry", "register"),
    "get_spec": ("registry", "get_spec"),
    "all_specs": ("registry", "all_specs"),
    "load_builtin": ("registry", "load_builtin"),
    "tune_kernel": ("measure", "tune_kernel"),
    "tune_from_ledger": ("measure", "tune_from_ledger"),
    "bound_from_ledger": ("measure", "bound_from_ledger"),
    "TuneResult": ("measure", "TuneResult"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod_name, attr = _LAZY[name]
        mod = importlib.import_module("." + mod_name, __name__)
        val = getattr(mod, attr)
        globals()[name] = val
        return val
    raise AttributeError(
        "module 'apex_tpu.tune' has no attribute {!r}".format(name))
