"""Persistent per-device kernel-config cache (ISSUE 14 tentpole, layer 3).

One JSON file, ``tune_configs.json``, living beside the XLA compilation
cache (:func:`apex_tpu.cache.enable` points both stores at the same
directory; without it the default is ``~/.cache/apex_tpu``, and
``APEX_TPU_TUNE_CACHE`` overrides either).  Entries are keyed by

    ``(device kind, kernel name, kernel version, shape bucket)``

so a cache tuned on a v5e never feeds a v4, and a kernel that changes
its blocking math bumps its ``TUNE_VERSION`` and every stale entry
silently stops matching (:func:`prune_stale` garbage-collects them).

Failure policy — the cache must never be able to break a training run:

* a corrupt or partially-written file **falls back to defaults
  loudly-once** (one stderr line per path per process, then silence);
* every read path swallows unexpected errors and returns "no entry";
* writes are read-modify-write with an atomic ``os.replace`` so a
  concurrent reader never sees a torn file.

The in-memory view is memoized per path — the dispatch-time consult
(:mod:`apex_tpu.tune.dispatch`) costs two dict lookups after the first
load.  :func:`load` with ``reload=True`` drops the memo (what a process
restart does implicitly; the cache-lifecycle tests use it to prove the
persisted file alone reproduces the lookups).
"""

from __future__ import annotations

import json
import os
import sys
import threading
from typing import Any, Dict, List, Optional

__all__ = ["CACHE_FILENAME", "SCHEMA", "cache_path", "set_default_dir",
           "device_kind", "load", "lookup", "put", "entries",
           "prune_stale", "key_for"]

CACHE_FILENAME = "tune_configs.json"
#: schema of the on-disk file; a future major is treated as corrupt
#: (defaults-with-warning) rather than mis-read.
SCHEMA = 1

_lock = threading.Lock()
_STATE: Dict[str, Any] = {
    "dir": None,          # set_default_dir() override (cache.enable)
    "memo_path": None,    # path the memoized data was loaded from
    "memo": None,         # {"schema": 1, "entries": {...}}
    "warned": set(),      # paths already warned about (loudly-once)
}


def set_default_dir(path: Optional[str]) -> None:
    """Point the default cache location at ``path`` (a directory).
    :func:`apex_tpu.cache.enable` calls this so the tune configs land
    beside the persistent XLA compilation cache.  Drops the memo when
    the location actually changes."""
    with _lock:
        path = os.path.abspath(os.path.expanduser(path)) if path else None
        if _STATE["dir"] != path:
            _STATE["dir"] = path
            _STATE["memo_path"] = None
            _STATE["memo"] = None


def cache_path(path: Optional[str] = None) -> str:
    """Resolve the cache file path: an explicit ``path`` (file, or a
    directory to hold :data:`CACHE_FILENAME`) wins, then the
    ``APEX_TPU_TUNE_CACHE`` env var, then the directory installed by
    :func:`set_default_dir`, then ``~/.cache/apex_tpu``."""
    cand = path or os.environ.get("APEX_TPU_TUNE_CACHE") or _STATE["dir"] \
        or os.path.join("~", ".cache", "apex_tpu")
    cand = os.path.abspath(os.path.expanduser(cand))
    if os.path.isdir(cand) or not cand.endswith(".json"):
        cand = os.path.join(cand, CACHE_FILENAME)
    return cand


def device_kind() -> str:
    """Normalized accelerator kind of the default backend (the cache key
    prefix): ``jax.devices()[0].device_kind`` with spaces collapsed —
    e.g. ``TPU_v5_lite`` — or ``cpu`` when no accelerator (or no jax)
    is reachable."""
    try:
        import jax
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", None) or dev.platform
        return str(kind).strip().replace(" ", "_")
    except Exception:
        return "cpu"


def key_for(kernel: str, version: int, bucket: str,
            dev_kind: Optional[str] = None) -> str:
    """The flat entry key: ``device|kernel|vN|bucket``."""
    return "|".join([dev_kind or device_kind(), kernel,
                     f"v{int(version)}", bucket])


def _warn_once(path: str, msg: str) -> None:
    if path in _STATE["warned"]:
        return
    _STATE["warned"].add(path)
    print(f"apex_tpu.tune: {msg} ({path}) — falling back to built-in "
          f"default configs", file=sys.stderr)


def _read_file(path: str) -> Dict[str, Any]:
    """Parse the cache file; corrupt/partial/future-schema content is
    reported loudly-once and treated as empty."""
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except FileNotFoundError:
        return {"schema": SCHEMA, "entries": {}}
    except (OSError, ValueError) as e:
        _warn_once(path, f"config cache unreadable/corrupt "
                         f"({type(e).__name__}: {e})")
        return {"schema": SCHEMA, "entries": {}}
    if not isinstance(raw, dict) or not isinstance(raw.get("entries"), dict):
        _warn_once(path, "config cache has no entries table")
        return {"schema": SCHEMA, "entries": {}}
    if int(raw.get("schema", 0)) > SCHEMA:
        _warn_once(path, f"config cache schema {raw.get('schema')} is "
                         f"newer than this build understands ({SCHEMA})")
        return {"schema": SCHEMA, "entries": {}}
    # partial entries (no config dict) are skipped, not fatal
    ents = {}
    for key, ent in raw["entries"].items():
        if isinstance(ent, dict) and isinstance(ent.get("config"), dict):
            ents[key] = ent
    if len(ents) != len(raw["entries"]):
        _warn_once(path, f"{len(raw['entries']) - len(ents)} partial "
                         f"config-cache entr(ies) skipped")
    return {"schema": SCHEMA, "entries": ents}


def load(path: Optional[str] = None, *, reload: bool = False
         ) -> Dict[str, Any]:
    """The cache's in-memory view (memoized per path).  ``reload=True``
    re-reads from disk — the restart-survival probe."""
    p = cache_path(path)
    with _lock:
        if not reload and _STATE["memo_path"] == p \
                and _STATE["memo"] is not None:
            return _STATE["memo"]
        data = _read_file(p)
        _STATE["memo_path"], _STATE["memo"] = p, data
        return data


def lookup(kernel: str, version: int, bucket: str, *,
           dev_kind: Optional[str] = None,
           path: Optional[str] = None) -> Optional[Dict[str, int]]:
    """The tuned config for this key, or None (miss, stale version,
    wrong device kind, corrupt cache — all collapse to the defaults
    fallback).  Never raises."""
    try:
        data = load(path)
        ent = data["entries"].get(key_for(kernel, version, bucket, dev_kind))
        return dict(ent["config"]) if ent else None
    except Exception:           # the cache must never break dispatch
        return None


def put(kernel: str, version: int, bucket: str,
        config: Dict[str, int], *,
        meta: Optional[Dict[str, Any]] = None,
        dev_kind: Optional[str] = None,
        path: Optional[str] = None) -> str:
    """Persist one tuned config (read-modify-write + atomic replace);
    returns the entry key.  The memo is refreshed in place so the
    writing process dispatches its own result immediately."""
    p = cache_path(path)
    with _lock:
        data = _read_file(p)
        key = key_for(kernel, version, bucket, dev_kind)
        data["entries"][key] = {
            "kernel": kernel, "version": int(version), "bucket": bucket,
            "device_kind": dev_kind or device_kind(),
            "config": {k: v for k, v in config.items()},
            "meta": dict(meta or {}),
        }
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, p)
        _STATE["memo_path"], _STATE["memo"] = p, data
        return key


def entries(path: Optional[str] = None,
            dev_kind: Optional[str] = None) -> List[Dict[str, Any]]:
    """All cached entries (optionally filtered to one device kind),
    sorted by key — the CLI's ``show`` table."""
    data = load(path)
    out = []
    for key in sorted(data["entries"]):
        ent = dict(data["entries"][key])
        if dev_kind and ent.get("device_kind") != dev_kind:
            continue
        ent["key"] = key
        out.append(ent)
    return out


def prune_stale(current_versions: Dict[str, int],
                path: Optional[str] = None) -> int:
    """Drop entries whose kernel appears in ``current_versions`` with a
    DIFFERENT version (the bump-invalidation garbage collector; stale
    entries already never match lookups).  Returns how many were
    removed."""
    p = cache_path(path)
    with _lock:
        data = _read_file(p)
        stale = [k for k, e in data["entries"].items()
                 if e.get("kernel") in current_versions
                 and int(e.get("version", -1))
                 != int(current_versions[e["kernel"]])]
        for k in stale:
            del data["entries"][k]
        if stale:
            os.makedirs(os.path.dirname(p), exist_ok=True)
            tmp = p + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(data, f, indent=1, sort_keys=True)
            os.replace(tmp, p)
        _STATE["memo_path"], _STATE["memo"] = p, data
        return len(stale)
