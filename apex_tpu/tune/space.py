"""Shared VMEM-budget / block-shape math (ISSUE 14 satellite).

One home for the sizing rules the row-blocked Pallas kernels and the
tuner's constraint checker must agree on.  Previously
``normalization/fused_bn_act.py`` imported the private
``_SUBLANE_ROWS``/``_VMEM_BUDGET_BYTES`` from ``fused_layer_norm.py``
and re-implemented ``_pick_rows``; both kernels now call these helpers,
and :mod:`apex_tpu.tune.measure` uses the same functions to reject
candidate configs that cannot fit scoped VMEM **before** timing them.

The model: a row-blocked kernel holds ``rows x width`` blocks whose
per-element footprint is ``bytes_per_elem`` (the caller sums its live
operand/output/temporary widths — e.g. the LayerNorm backward holds
g, x, dx at the input itemsize plus four fp32 row-major temporaries,
``3*isz + 16``).  Blocks must be sublane multiples (8 rows) and the
whole block must fit a conservative slice of the ~16 MB scoped-VMEM
budget, leaving room for Mosaic's own pipelining copies.
"""

from __future__ import annotations

from typing import List, Optional

__all__ = ["VMEM_BUDGET_BYTES", "SUBLANE_ROWS", "LANE_COLS", "pick_rows",
           "floor_block_fits", "max_width", "row_block_candidates",
           "pow2_bucket", "nhwc_bucket"]

#: scoped-VMEM budget a single kernel block may claim (conservative
#: slice of the ~16 MB scoped limit; measured r5 — see fused_layer_norm)
VMEM_BUDGET_BYTES = int(12e6)
#: the sublane tile: the smallest legal row-block granularity
SUBLANE_ROWS = 8
#: the lane tile: last-dim block granularity for matmul-style kernels
LANE_COLS = 128


def pick_rows(n_rows: int, width: int, bytes_per_elem: int, *,
              row_block: int = 256,
              budget: int = VMEM_BUDGET_BYTES) -> int:
    """Row-block size capped at ``row_block`` that keeps a
    ``rows x width`` block of ``bytes_per_elem``-byte elements inside
    ``budget``: rounded down to the sublane multiple, floored at
    :data:`SUBLANE_ROWS`, and never exceeding ``n_rows``.

    ``row_block`` is the tunable knob (the autotuner's ``row_block``
    config); the budget clamp below it is a hard constraint, so any
    tuned value stays VMEM-legal by construction — and the cap itself
    is rounded to a legal sublane multiple first, so an out-of-band
    cache value (a hand-edited 100, a hostile 3) can never reach
    ``pallas_call`` as an illegal block shape.
    """
    cap = max(SUBLANE_ROWS,
              (int(row_block) // SUBLANE_ROWS) * SUBLANE_ROWS)
    budget_rows = budget // (bytes_per_elem * width)
    rows = min(cap,
               max(SUBLANE_ROWS,
                   (budget_rows // SUBLANE_ROWS) * SUBLANE_ROWS))
    return min(rows, n_rows)


def floor_block_fits(width: int, bytes_per_elem: int, *,
                     budget: int = VMEM_BUDGET_BYTES) -> bool:
    """Whether even the 8-row floor block fits the budget — the width
    gate: beyond it NO row count is legal and the caller must route to
    the jnp path rather than OOM Mosaic at compile."""
    return SUBLANE_ROWS * width * bytes_per_elem <= budget


def max_width(bytes_per_elem: int, *,
              budget: int = VMEM_BUDGET_BYTES) -> int:
    """Widest row the floor block admits for this per-element footprint
    (the inverse of :func:`floor_block_fits`)."""
    return budget // (bytes_per_elem * SUBLANE_ROWS)


def row_block_candidates(n_rows: int, width: int, bytes_per_elem: int, *,
                         budget: int = VMEM_BUDGET_BYTES,
                         blocks=(8, 16, 32, 64, 128, 256, 512, 1024)
                         ) -> List[int]:
    """Legal ``row_block`` candidates for a ``[n_rows, width]`` kernel:
    sublane multiples from ``blocks`` whose budget-clamped block is not
    degenerate (a candidate larger than what the budget admits would
    collapse onto the same clamped block as a smaller one — dedup so
    the tuner never times the same effective config twice)."""
    seen = set()
    out: List[int] = []
    for blk in blocks:
        if blk % SUBLANE_ROWS:
            continue
        eff = pick_rows(n_rows, width, bytes_per_elem,
                        row_block=blk, budget=budget)
        if eff in seen:
            continue
        seen.add(eff)
        out.append(blk)
    return out


def pow2_bucket(n: int) -> int:
    """Round ``n`` up to the next power of two — the shape-bucket
    granularity of the config cache keys (two batch sizes in the same
    pow2 bucket share a tuned config; re-tuning per exact shape would
    fragment the cache for no measured benefit)."""
    n = max(1, int(n))
    b = 1
    while b < n:
        b <<= 1
    return b


def nhwc_bucket(n: int, h: int, w: int, c: int) -> str:
    """Shape bucket for a 4-D NHWC conv operand (ISSUE 18 satellite).

    Batch and the JOINT spatial extent ``h*w`` round to powers of two —
    a conv kernel blocks over flattened output rows, so it is the
    ``h*w`` product that selects a block shape, and bucketing ``h`` and
    ``w`` separately would split e.g. ``56x56`` and ``64x49`` (same row
    count, same winning config) into distinct one-entry cache keys.
    Channels stay exact: they set the matmul contraction width and the
    lane-tiled VMEM footprint, where off-by-one-bucket reuse is wrong.
    """
    return f"n{pow2_bucket(n)}_s{pow2_bucket(h * w)}_c{int(c)}"
