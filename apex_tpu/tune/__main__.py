"""``python -m apex_tpu.tune`` — the autotuner CLI (ISSUE 14).

Three subcommands::

    # tune one registered kernel (its representative shape, or --shape)
    python -m apex_tpu.tune kernel flash_attention --shape q_len=8192,kv_len=8192

    # tune every registered kernel, candidate priority driven by a
    # roofline MFU ledger's compute-vs-memory verdicts
    python -m apex_tpu.tune ledger LEDGER.json

    # print the persisted per-device config table
    python -m apex_tpu.tune show

    # drop entries stranded by kernel TUNE_VERSION bumps (stale entries
    # already never match lookups; this garbage-collects the file)
    python -m apex_tpu.tune prune

Results persist into the config cache (``--cache`` overrides the
location; by default it sits beside the XLA compilation cache — see
``docs/tune.md``), keyed by (device kind, kernel, version, shape
bucket), and every registered kernel consults them at dispatch time.
Measurement requires a TPU; ``--interpret`` runs an explicit
interpreter-mode probe (CPU CI determinism tests) — dispatch itself
never tunes.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from . import measure, registry, store

__all__ = ["main"]


def _parse_shape(specs) -> dict:
    """``k=v[,k=v...]`` (repeatable) -> shape dict; ints/bools/floats
    parsed, anything else kept as a string (dtype names)."""
    out = {}
    for spec in specs or ():
        for part in spec.split(","):
            if not part.strip():
                continue
            key, _, val = part.partition("=")
            if not _:
                raise SystemExit(f"--shape expects k=v, got {part!r}")
            v = val.strip()
            if v.lower() in ("true", "false"):
                out[key.strip()] = v.lower() == "true"
            else:
                try:
                    out[key.strip()] = int(v)
                except ValueError:
                    try:
                        out[key.strip()] = float(v)
                    except ValueError:
                        out[key.strip()] = v
    return out


def _result_row(res) -> dict:
    return {"kernel": res.kernel, "version": res.version,
            "bucket": res.bucket, "device_kind": res.device_kind,
            "bound": res.bound, "config": res.config,
            "default_config": res.default_config,
            "best_ms": res.best_ms, "default_ms": res.default_ms,
            "tuned_over_default": res.tuned_over_default,
            "candidates": res.candidates,
            "rejected_constraint": res.rejected_constraint,
            "rejected_oracle": res.rejected_oracle,
            "truncated": res.truncated,
            "stored": res.stored, "source": res.source}


def _print_result(res) -> None:
    print(f"{res.kernel} [{res.bucket}] on {res.device_kind} "
          f"({res.bound}-bound priority, {res.source}):")
    print(f"  default {res.default_config} -> {res.default_ms} ms")
    print(f"  tuned   {res.config} -> {res.best_ms} ms "
          f"({res.tuned_over_default}x default; {res.candidates} "
          f"measured, {res.rejected_constraint} constraint-rejected, "
          f"{res.rejected_oracle} oracle-rejected)")


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.tune",
        description="Roofline-driven Pallas kernel autotuner.")
    sub = ap.add_subparsers(dest="cmd", required=True)

    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--cache", default=None, metavar="PATH",
                        help="config-cache file or directory (default: "
                             "beside the XLA compilation cache)")
    common.add_argument("--json", action="store_true")

    tune_common = argparse.ArgumentParser(add_help=False, parents=[common])
    tune_common.add_argument("--shape", action="append", default=[],
                             metavar="K=V[,K=V...]",
                             help="shape overrides (repeatable)")
    tune_common.add_argument("--iters", type=int, default=5)
    tune_common.add_argument("--reps", type=int, default=3)
    tune_common.add_argument("--seed", type=int, default=0,
                             help="candidate-order seed")
    tune_common.add_argument("--max-candidates", type=int, default=None)
    tune_common.add_argument("--interpret", action="store_true",
                             help="interpreter-mode probe (CPU CI; "
                                  "measurement otherwise requires TPU)")
    tune_common.add_argument("--no-store", action="store_true",
                             help="measure and report only")

    pk = sub.add_parser("kernel", parents=[tune_common],
                        help="tune one registered kernel")
    pk.add_argument("name", help="registered kernel name "
                                 "(see `show` / the registry)")
    pk.add_argument("--bound", choices=("compute", "memory"), default=None,
                    help="candidate-priority override")

    pl_ = sub.add_parser("ledger", parents=[tune_common],
                         help="tune every registered kernel, priority "
                              "from a roofline MFU ledger")
    pl_.add_argument("path", help="mfu_ledger JSON "
                                  "(python -m apex_tpu.prof.roofline "
                                  "--json output)")

    sub.add_parser("show", parents=[common],
                   help="print the persisted config table")

    sub.add_parser("prune", parents=[common],
                   help="drop entries whose kernel has bumped its "
                        "registered TUNE_VERSION (they already never "
                        "match lookups; this garbage-collects the file)")

    args = ap.parse_args(argv)

    if args.cmd == "prune":
        n = store.prune_stale(registry.registered_versions(),
                              path=args.cache)
        msg = {"pruned": n, "cache": store.cache_path(args.cache)}
        print(json.dumps(msg) if args.json
              else f"pruned {n} stale entr(ies) from {msg['cache']}")
        return 0

    if args.cmd == "show":
        rows = store.entries(args.cache)
        if args.json:
            print(json.dumps(rows, indent=1))
            return 0
        if not rows:
            print(f"no tuned configs at {store.cache_path(args.cache)}")
            return 0
        print(f"config cache: {store.cache_path(args.cache)}")
        print("{:<22} {:<16} {:>3}  {:<26} {}".format(
            "device", "kernel", "ver", "bucket", "config"))
        for row in rows:
            meta = row.get("meta") or {}
            extra = ""
            if meta.get("best_ms") is not None:
                extra = (f"  [{meta.get('default_ms')} -> "
                         f"{meta.get('best_ms')} ms, {meta.get('source')}]")
            print("{:<22} {:<16} {:>3}  {:<26} {}{}".format(
                row.get("device_kind", "?"), row.get("kernel", "?"),
                row.get("version", "?"), row.get("bucket", "?"),
                json.dumps(row.get("config")), extra))
        return 0

    kwargs = dict(seed=args.seed, iters=args.iters, reps=args.reps,
                  max_candidates=args.max_candidates,
                  interpret=args.interpret,
                  store_result=not args.no_store, path=args.cache)
    shape = _parse_shape(args.shape) or None
    if args.cmd == "ledger" and shape is not None:
        # one shape dict cannot parameterize five kernels with disjoint
        # key vocabularies — and it would silently disable every spec's
        # small_shape interpret fallback.  Per-kernel shapes go through
        # `kernel NAME --shape ...`.
        print("error: --shape applies to `kernel NAME`, not `ledger` "
              "(each registered kernel has its own shape keys)",
              file=sys.stderr)
        return 2

    try:
        if args.cmd == "kernel":
            results = [measure.tune_kernel(args.name, shape,
                                           bound=args.bound, **kwargs)]
        else:
            with open(args.path, encoding="utf-8") as f:
                ledger = json.load(f)
            registry.load_builtin()
            results = measure.tune_from_ledger(ledger, shape=shape,
                                               **kwargs)
    except (RuntimeError, KeyError, OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps([_result_row(r) for r in results], indent=1))
    else:
        for r in results:
            _print_result(r)
        if not args.no_store:
            print(f"persisted to {store.cache_path(args.cache)}")
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(main())
