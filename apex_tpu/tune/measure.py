"""Measurement harness — on-device candidate timing (ISSUE 14, layer 2).

The discipline every number in ``bench.py`` already follows, applied to
kernel configs:

* **compile excluded** — each candidate's jitted case runs once (and is
  synced) before any clock starts;
* **min-of-K** — ``reps`` timed passes of ``iters`` calls each, fenced
  with an explicit ``jax.block_until_ready`` on the last output (async
  dispatch means an unfenced clock measures enqueue, not compute —
  jaxlint J009's whole reason to exist), and the minimum taken (the
  least-interfered pass, the honest estimator on a noisy tunnel);
* **reject before timing** — candidates failing the spec's VMEM/
  legality constraint never compile; candidates whose outputs fail the
  oracle against the default config (bitwise for ``exact`` kernels,
  tolerance for flash attention's reordered online softmax) are
  measured-then-discarded, so a "fast but wrong" config can never win;
* **ledger-driven priority** — :func:`bound_from_ledger` maps a
  roofline MFU ledger's compute-vs-memory verdicts onto a kernel's
  regions, and the spec orders its candidate space accordingly
  (memory-bound → layout candidates first, compute-bound → block-size
  candidates first).  With a candidate budget (``max_candidates``) the
  ordering decides WHAT gets measured at all.

CPU/interpret paths never tune implicitly: :func:`tune_kernel` refuses
to measure off-TPU unless the caller explicitly opts into
``interpret=True`` (the CPU CI determinism tests, marked as such in the
stored meta) or ``allow_non_tpu=True``.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

import jax
import numpy as np

from . import store
from .registry import KernelSpec, all_specs, get_spec

__all__ = ["TuneResult", "time_case", "tune_kernel", "bound_from_ledger",
           "tune_from_ledger"]


@dataclass
class TuneResult:
    kernel: str
    version: int
    bucket: str
    device_kind: str
    bound: str
    config: Dict[str, int]                 # the winner (may == default)
    default_config: Dict[str, int]
    best_ms: Optional[float]
    default_ms: Optional[float]
    candidates: int                        # measured (constraint-passing)
    rejected_constraint: int
    rejected_oracle: int
    truncated: int = 0                     # dropped by max_candidates
    order: List[Dict[str, int]] = field(default_factory=list)
    stored: bool = False
    source: str = "device"                 # "device" | "interpret"

    @property
    def tuned_over_default(self) -> Optional[float]:
        if not self.best_ms or not self.default_ms:
            return None
        return round(self.best_ms / self.default_ms, 4)


def time_case(run: Callable[[], Any], *, iters: int = 5,
              reps: int = 3) -> float:
    """Seconds per call, min-of-``reps`` over ``iters``-call passes.
    ``run`` must already be warm (compiled); the fence is one
    ``block_until_ready`` on the final output per pass."""
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        out = None
        for _ in range(max(1, iters)):
            out = run()
        jax.block_until_ready(out)  # jaxlint: disable=J001 -- timing fence: the measurement is invalid without draining the dispatched candidates
        best = min(best, (time.perf_counter() - t0) / max(1, iters))
    return best


def _tree_equal_bitwise(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    if len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        ax, ay = np.asarray(x), np.asarray(y)  # jaxlint: disable=J008 -- oracle compare IS the host boundary: both trees are finished candidate outputs, fetched once outside any hot loop
        if ax.dtype != ay.dtype or ax.shape != ay.shape \
                or not np.array_equal(ax.reshape(-1).view(np.uint8),
                                      ay.reshape(-1).view(np.uint8)):
            return False
    return True


def _tree_close(a, b, rtol: float, atol: float) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    if len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        ax = np.asarray(x, dtype=np.float32)  # jaxlint: disable=J008 -- oracle compare IS the host boundary (see _tree_equal_bitwise)
        ay = np.asarray(y, dtype=np.float32)  # jaxlint: disable=J008 -- oracle compare IS the host boundary (see _tree_equal_bitwise)
        if ax.shape != ay.shape or not np.allclose(ax, ay, rtol=rtol,
                                                   atol=atol):
            return False
    return True


def _oracle_ok(spec: KernelSpec, case, ref, out) -> bool:
    if spec.exact:
        return _tree_equal_bitwise(ref, out)
    rtol, atol = case.tol
    return _tree_close(ref, out, rtol, atol)


def _config_key(spec: KernelSpec, shape: Mapping,
                cfg: Dict[str, int]) -> object:
    """Dedupe key: the EFFECTIVE block when the spec can compute one
    (two configs clamping onto the same program must only be timed
    once), else the raw config."""
    if spec.effective is not None:
        try:
            return ("eff", repr(spec.effective(shape, cfg)))
        except Exception:
            pass
    return tuple(sorted(cfg.items()))


def _dedupe(spec: KernelSpec, shape: Mapping,
            configs: Sequence[Dict[str, int]]) -> List[Dict[str, int]]:
    seen, out = set(), []
    for c in configs:
        key = _config_key(spec, shape, c)
        if key not in seen:
            seen.add(key)
            out.append(dict(c))
    return out


def tune_kernel(spec_or_name, shape: Optional[Mapping] = None, *,
                bound: Optional[str] = None,
                seed: int = 0,
                iters: int = 5, reps: int = 3,
                max_candidates: Optional[int] = None,
                interpret: bool = False,
                allow_non_tpu: bool = False,
                measure: Optional[Callable[[Dict[str, int],
                                            Callable[[], Any]],
                                           float]] = None,
                store_result: bool = True,
                path: Optional[str] = None) -> TuneResult:
    """Search one kernel's config space on this device and (by default)
    persist the winner into the config cache.

    ``shape`` defaults to the spec's representative on-chip shape (its
    ``small_shape`` under ``interpret``).  ``bound`` overrides the
    candidate-priority verdict (normally from
    :func:`bound_from_ledger`); ``seed`` fixes the candidate visit
    order (the default-config candidate always measures first, the rest
    are deterministically shuffled — two equal-seed runs measure the
    same list in the same order, the CPU-determinism contract).

    ``measure`` injects a timing function ``(config, run) -> seconds``
    (tests substitute a deterministic model; the default is
    :func:`time_case` on the real device clock).  Off-TPU measurement
    requires ``interpret=True`` (stored with ``source="interpret"``) or
    ``allow_non_tpu=True`` — dispatch never calls this; CPU/interpret
    paths never tune implicitly.
    """
    spec = spec_or_name if isinstance(spec_or_name, KernelSpec) \
        else get_spec(spec_or_name)
    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu and not (interpret or allow_non_tpu):
        raise RuntimeError(
            f"tune_kernel({spec.name!r}) measures on-device and the "
            f"default backend is {jax.default_backend()!r} — tuning "
            f"only runs on TPU (pass interpret=True for an explicit "
            f"interpreter-mode probe, e.g. in CPU CI)")
    if shape is None:
        shape = (spec.small_shape or spec.example_shape) \
            if (interpret and not on_tpu) else spec.example_shape
    shape = dict(shape)
    bound = bound or spec.kind
    bucket = spec.bucket(shape)
    default = spec.defaults(shape)

    cands = _dedupe(spec, shape,
                    [default] + list(spec.candidates(shape, bound)))
    # Seeded candidate order (the CPU-determinism contract): the tail is
    # shuffled by ``seed``, then STABLY sorted by the spec's priority key
    # — the ledger-driven visit order survives, equal-priority configs
    # land in seeded order, and two equal-seed runs visit the same list.
    rng = random.Random(seed)
    tail = cands[1:]
    rng.shuffle(tail)
    if spec.priority is not None:
        tail.sort(key=lambda c: spec.priority(shape, c, bound))
    cands = [cands[0]] + tail
    kept, rejected_constraint = [], 0
    for c in cands:
        if c == default or spec.constraint(shape, c):
            kept.append(c)
        else:
            rejected_constraint += 1
    # the measurement budget is its own counter — a truncated candidate
    # passed the constraint and must not read as "VMEM-illegal"
    truncated = 0
    if max_candidates is not None:
        truncated = max(0, len(kept) - max(1, int(max_candidates)))
        kept = kept[:max(1, int(max_candidates))]

    case = spec.build(shape, interpret and not on_tpu)
    timer = measure or (lambda cfg, run: time_case(run, iters=iters,
                                                   reps=reps))

    # default first: its output is the oracle reference and its time the
    # fallback bound every candidate must beat to displace it.
    ref = case.run(default)
    jax.block_until_ready(ref)  # jaxlint: disable=J001 -- warmup fence: the default config's compile must finish before any candidate clock starts
    default_ms = 1e3 * float(timer(default, lambda: case.run(default)))  # jaxlint: disable=J001 -- the timer's return is a host float by contract, not a device value

    best_cfg, best_ms = dict(default), default_ms
    rejected_oracle = 0
    measured = 1
    for cfg in kept:
        if cfg == default:
            continue
        try:
            out = case.run(cfg)
            jax.block_until_ready(out)  # jaxlint: disable=J001 -- per-candidate warmup fence: compile + oracle fetch happen before this candidate's clock, excluded by design
        except Exception:
            rejected_constraint += 1         # did not even compile/run
            continue
        if not _oracle_ok(spec, case, ref, out):
            rejected_oracle += 1
            continue
        ms = 1e3 * float(timer(cfg, lambda: case.run(cfg)))
        measured += 1
        if ms < best_ms:
            best_cfg, best_ms = dict(cfg), ms

    dev = store.device_kind()
    res = TuneResult(
        kernel=spec.name, version=spec.version, bucket=bucket,
        device_kind=dev, bound=bound, config=best_cfg,
        default_config=dict(default),
        best_ms=round(best_ms, 6), default_ms=round(default_ms, 6),
        candidates=measured, rejected_constraint=rejected_constraint,
        rejected_oracle=rejected_oracle, truncated=truncated, order=kept,
        source=("interpret" if (interpret and not on_tpu) else "device"))
    if store_result:
        store.put(spec.name, spec.version, bucket, best_cfg,
                  meta={"best_ms": res.best_ms,
                        "default_ms": res.default_ms,
                        "default_config": res.default_config,
                        "bound": bound, "seed": seed,
                        "source": res.source},
                  path=path)
        res.stored = True
    try:
        from ..telemetry import get_recorder
        rec = get_recorder()
        if rec is not None:
            rec.event("tune", phase="result", kernel=spec.name,
                      bucket=bucket, bound=bound, config=res.config,
                      default_ms=res.default_ms, best_ms=res.best_ms,
                      candidates=res.candidates,
                      rejected_constraint=res.rejected_constraint,
                      rejected_oracle=res.rejected_oracle,
                      truncated=res.truncated,
                      stored=res.stored, source=res.source)
    except Exception:
        pass
    return res


# -- roofline-ledger priority -------------------------------------------------

def bound_from_ledger(ledger: Mapping, spec: KernelSpec) -> Optional[str]:
    """The boundedness verdict for this kernel read off an
    :func:`apex_tpu.prof.roofline.mfu_ledger` result: region rows whose
    name contains any of the spec's ``regions`` fragments vote with
    their modeled-ms weight (falling back to GFLOPs when the ledger has
    no measured clock).  Returns ``"compute"``/``"memory"``, or None
    when no region matches (the spec's own ``kind`` then decides)."""
    votes = {"compute": 0.0, "memory": 0.0}
    matched = False
    for row in (ledger.get("regions") or []):
        name = str(row.get("region", "")).lower()
        if not any(frag in name for frag in spec.regions):
            continue
        matched = True
        weight = float(row.get("modeled_ms") or row.get("flops_g") or 1.0)
        side = row.get("bound")
        if side in votes:
            votes[side] += weight
    if not matched:
        return None
    return "memory" if votes["memory"] >= votes["compute"] else "compute"


def tune_from_ledger(ledger: Mapping, *,
                     specs: Optional[Sequence[KernelSpec]] = None,
                     **kwargs) -> List[TuneResult]:
    """Tune every registered kernel, candidate priority driven by the
    ledger's verdicts; kwargs forward to :func:`tune_kernel`."""
    out = []
    for spec in (specs if specs is not None else all_specs()):
        out.append(tune_kernel(spec,
                               bound=bound_from_ledger(ledger, spec),
                               **kwargs))
    return out
