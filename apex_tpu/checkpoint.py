"""Checkpoint / resume for train states and amp state.

Reference recipe (SURVEY.md §5, README "Checkpointing"): save model /
optimizer / amp dicts, restore *after* ``amp.initialize`` with the same
opt_level; resumed training is bitwise identical
(``tests/L0/run_amp/test_checkpointing.py:73-199``).

TPU-native form: any pytree (e.g. ``training.TrainState`` — params, opt
state, scaler state, batch stats) serializes to one ``.npz`` via the native
flatten path; structure is recorded as key paths so the checkpoint is
readable without the original treedef.  O2 keeps fp32 masters as the stored
source of truth, so checkpoints are precision-portable by construction (the
reference needs an ``O2StateDictHook`` to fake this —
``_initialize.py:129-138``).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


_DTYPE_TAG = "@dtype="


def _encode(arr: np.ndarray):
    """npz cannot store ml_dtypes (bfloat16 → '|V2'); store such arrays as
    raw uint bits with the true dtype recorded in the key."""
    if arr.dtype.kind == "V" or arr.dtype.name not in np.sctypeDict:
        bits = {1: np.uint8, 2: np.uint16, 4: np.uint32}[arr.dtype.itemsize]
        return arr.view(bits), arr.dtype.name
    return arr, None


def _decode(arr: np.ndarray, dtype_name):
    if dtype_name is None:
        return arr
    import ml_dtypes  # ships with jax
    return arr.view(np.dtype(getattr(ml_dtypes, dtype_name, dtype_name)))


def _flatten_with_paths(tree):
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        arr, tag = _encode(np.asarray(jax.device_get(leaf)))  # jaxlint: disable=J001 -- checkpoint serialization materializes host arrays by contract
        if tag is not None:
            key = key + _DTYPE_TAG + tag
        out[key] = arr
    return out


def save_checkpoint(path: str, state, amp_state: Optional[dict] = None,
                    **extra) -> None:
    """Serialize ``state`` (any pytree) + optional amp ``state_dict`` to
    ``path`` (.npz)."""
    arrays = _flatten_with_paths(state)
    if amp_state:
        for k, v in _flatten_with_paths(amp_state).items():
            arrays["__amp__/" + k] = v
    for k, v in extra.items():
        arrays["__extra__/" + k] = np.asarray(v)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)         # atomic publish


def load_checkpoint(path: str, like):
    """Restore a pytree shaped like ``like`` from ``path``; returns
    ``(state, amp_state_dict, extra_dict)``.  Dtypes/shapes must match the
    template (same opt_level rule as the reference recipe)."""
    with np.load(path, allow_pickle=False) as data:
        arrays = {k: data[k] for k in data.files}
    amp_state = {}
    extra = {}
    plain = {}
    for k, v in arrays.items():
        if _DTYPE_TAG in k:
            k, tag = k.split(_DTYPE_TAG, 1)
            v = _decode(v, tag)
        if k.startswith("__amp__/"):
            amp_state[k[len("__amp__/"):]] = v
        elif k.startswith("__extra__/"):
            extra[k[len("__extra__/"):]] = v
        else:
            plain[k] = v

    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    consumed = set()
    leaves = []
    for path_elems, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path_elems)
        if key not in plain:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        consumed.add(key)
        arr = plain[key]
        # jaxlint: disable=J001 -- restore-time dtype validation reads the template leaf once per checkpoint load
        want_dtype = np.asarray(jax.device_get(leaf)).dtype \
            if hasattr(leaf, "dtype") else None
        if want_dtype is not None and arr.dtype != want_dtype:
            raise ValueError(
                f"dtype mismatch for {key!r}: checkpoint {arr.dtype}, "
                f"template {want_dtype} — restore with the same opt_level "
                f"used at save time (reference checkpointing rule)")
        leaves.append(jax.numpy.asarray(arr))
    unconsumed = set(plain) - consumed
    if unconsumed:
        # A checkpoint from a larger/renamed model would otherwise appear to
        # load while silently dropping state (ADVICE r1 #5).
        raise KeyError(
            "checkpoint holds {} array(s) with no matching template leaf "
            "(e.g. {!r}) — the template pytree does not match the model "
            "that was saved".format(len(unconsumed),
                                    sorted(unconsumed)[0]))
    state = jax.tree_util.tree_unflatten(
        treedef, leaves)
    return state, amp_state, extra
