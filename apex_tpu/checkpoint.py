"""Checkpoint / resume: single-file states and the async sharded engine.

Reference recipe (SURVEY.md §5, README "Checkpointing"): save model /
optimizer / amp dicts, restore *after* ``amp.initialize`` with the same
opt_level; resumed training is bitwise identical
(``tests/L0/run_amp/test_checkpointing.py:73-199``).

Two tiers live here (ISSUE 9):

* the **v1 single-file path** — :func:`save_checkpoint` /
  :func:`load_checkpoint` serialize any pytree (e.g.
  ``training.TrainState``) to one ``.npz`` with key-path structure, the
  simple synchronous recipe for small states and unit tests;
* the **v2 elastic engine** — :class:`CheckpointManager` snapshots the
  live state to host at a window boundary (non-blocking ``device_get``:
  every leaf's D2H copy is *started* before the first one is awaited),
  then serializes + fsyncs on a background writer thread with atomic
  rename, per-host sharded files, a JSON manifest (tree paths, dtypes,
  world shape, per-file checksums, flat-bucket layout), and a retention
  policy — the Check-N-Run decoupled snapshot-then-persist shape, so
  the train loop stalls only for the copy trigger (gated in
  ``bench.py`` self-validation: async stall <= 20% of the synchronous
  write).  :func:`load_checkpoint_dir` restores the newest *valid*
  checkpoint (corrupt / truncated / mid-write ``.tmp`` remains are
  skipped, falling back to the previous step) and reshards zero1
  ``bucketed=True`` flat buckets on read when the resume world's shard
  count differs from the save world's — the first concrete elastic
  resize path.

O2 keeps fp32 masters as the stored source of truth, so checkpoints are
precision-portable by construction (the reference needs an
``O2StateDictHook`` to fake this — ``_initialize.py:129-138``).

Usage (the examples' ``--checkpoint-dir/--checkpoint-every/--resume``)::

    mgr = checkpoint.CheckpointManager(dir, keep=3, every_steps=500)
    restored = mgr.restore(like=init_state)      # None on a fresh start
    ...
    for window ...:
        state, metrics = pipe.step_window(state, window, n)
        mgr.maybe_save(step, state, loader_state=stream.state_dict())
    mgr.save(step, state, block=True)            # final, synchronous
    mgr.close()
"""

from __future__ import annotations

import json
import os
import queue
import re
import shutil
import threading
import time
import uuid
import zlib
from typing import Any, NamedTuple, Optional, Tuple

import jax
import numpy as np

from . import telemetry as _telemetry

__all__ = ["save_checkpoint", "load_checkpoint", "CheckpointManager",
           "Restored", "load_checkpoint_dir", "latest_checkpoint",
           "list_checkpoints", "bucket_layout", "CheckpointError"]


_DTYPE_TAG = "@dtype="
_JSON_PREFIX = "__extrajson__/"
_STEP_DIR_RE = re.compile(r"^step_(\d{8,})$")
_MANIFEST_VERSION = 1


class CheckpointError(RuntimeError):
    """A checkpoint could not be written or no valid one could be read."""


def _encode(arr: np.ndarray):
    """npz cannot store ml_dtypes (bfloat16 → '|V2'); store such arrays as
    raw uint bits with the true dtype recorded in the key."""
    if arr.dtype.kind == "V" or arr.dtype.name not in np.sctypeDict:
        bits = {1: np.uint8, 2: np.uint16, 4: np.uint32}[arr.dtype.itemsize]
        return arr.view(bits), arr.dtype.name
    return arr, None


def _decode(arr: np.ndarray, dtype_name):
    if dtype_name is None:
        return arr
    import ml_dtypes  # ships with jax
    return arr.view(np.dtype(getattr(ml_dtypes, dtype_name, dtype_name)))


def _path_key(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)


def _flatten_with_paths(tree):
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in flat:
        key = _path_key(path)
        arr, tag = _encode(np.asarray(jax.device_get(leaf)))  # jaxlint: disable=J001 -- checkpoint serialization materializes host arrays by contract
        if tag is not None:
            key = key + _DTYPE_TAG + tag
        out[key] = arr
    return out


def _snapshot_with_paths(tree, own=None):
    """Host snapshot of ``tree``'s leaves with the v1 key encoding, but
    with every owned leaf's device→host copy STARTED before the first
    one is awaited (``copy_to_host_async``), so the total stall is one
    overlapped transfer instead of a serial per-leaf drain.  ``own``
    filters leaves by flat index (per-host sharding); None takes all.

    Cross-process global arrays (a mesh spanning hosts — ISSUE 12)
    cannot be ``device_get``-ed piecemeal: their fetch is a COLLECTIVE
    (``multihost_utils.process_allgather``), so when any leaf is not
    fully addressable every process walks ALL leaves in the same order
    (participating in each gather) and ``own`` filters only what this
    host then WRITES — the write bytes still divide per host."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    cross_process = any(
        hasattr(leaf, "is_fully_addressable")
        and not leaf.is_fully_addressable for _, leaf in flat)
    if cross_process:
        from jax.experimental import multihost_utils
        out = {}
        for i, (path, leaf) in enumerate(flat):
            if hasattr(leaf, "is_fully_addressable") \
                    and not leaf.is_fully_addressable:
                val = np.asarray(  # jaxlint: disable=J001 -- checkpoint snapshot: the cross-process COLLECTIVE fetch is the sanctioned materialization
                    multihost_utils.process_allgather(leaf, tiled=True))
            else:
                val = np.asarray(jax.device_get(leaf))  # jaxlint: disable=J001 -- checkpoint snapshot: sanctioned host materialization
            if own is not None and not own(i):
                continue
            key = _path_key(path)
            arr, tag = _encode(val)
            if tag is not None:
                key = key + _DTYPE_TAG + tag
            out[key] = arr
        return out
    picked = [(i, _path_key(path), leaf)
              for i, (path, leaf) in enumerate(flat)
              if own is None or own(i)]
    for _, _, leaf in picked:
        start = getattr(leaf, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception:
                pass            # the blocking fetch below still works
    out = {}
    for _, key, leaf in picked:
        arr, tag = _encode(np.asarray(jax.device_get(leaf)))  # jaxlint: disable=J001 -- the checkpoint snapshot IS the sanctioned host materialization; copies were started async above
        if tag is not None:
            key = key + _DTYPE_TAG + tag
        out[key] = arr
    return out


# -- extras: explicit scalar/str round-trip (ISSUE 9 satellite) ---------------

def _encode_extra(key: str, value):
    """Encode one ``**extra`` value for npz storage.

    Arrays and bare numeric scalars keep the historical array path
    (``int(extra["step"])`` round-trips exactly as before); ``str`` /
    ``bool`` / ``None`` and nested dicts/lists travel as tagged JSON —
    ``np.asarray`` on those either crashes under ``allow_pickle=False``
    (None → object array) or munges the python type on reload.  Returns
    ``(npz_key, array)``; raises ``TypeError`` for values that fit
    neither route."""
    if isinstance(value, (bool, str)) or value is None \
            or isinstance(value, (dict, list, tuple)):
        try:
            payload = json.dumps(value)
        except (TypeError, ValueError) as e:
            raise TypeError(
                f"checkpoint extra {key!r} is not serializable: {e} — "
                f"pass arrays, numeric scalars, or JSON-compatible "
                f"values") from e
        return (_JSON_PREFIX + key,
                np.frombuffer(payload.encode("utf-8"), np.uint8))
    arr = np.asarray(value)
    if arr.dtype == object:
        raise TypeError(
            f"checkpoint extra {key!r} has object dtype "
            f"({type(value).__name__}) — pass arrays, numeric scalars, "
            f"or JSON-compatible values")
    return key, arr


def _decode_extras(raw: dict) -> dict:
    out = {}
    for k, v in raw.items():
        if k.startswith(_JSON_PREFIX):
            out[k[len(_JSON_PREFIX):]] = json.loads(
                bytes(np.asarray(v, np.uint8)).decode("utf-8"))
        else:
            out[k] = v
    return out


def _place_like(arr: np.ndarray, leaf):
    """Device-place a restored host array onto the template leaf's
    sharding (ISSUE 9 satellite): a resumed mesh run must get its state
    back SHARDED, not silently un-sharded host numpy.  Only committed
    shardings are honored — an uncommitted default-device leaf keeps the
    old behavior (plain ``jnp.asarray``).  A sharding spanning other
    hosts (multi-host mesh restore, ISSUE 12) goes through
    ``make_array_from_callback`` — every host holds the full value, each
    transfers only its addressable shards."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None and getattr(leaf, "committed", False):
        if not getattr(sharding, "is_fully_addressable", True):
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx])
        return jax.device_put(arr, sharding)
    return jax.numpy.asarray(arr)


def save_checkpoint(path: str, state, amp_state: Optional[dict] = None,
                    **extra) -> None:
    """Serialize ``state`` (any pytree) + optional amp ``state_dict`` to
    ``path`` (.npz).  ``extra`` values may be arrays, numeric scalars,
    or JSON-compatible python values (str/bool/None/dict/list) — all
    round-trip through :func:`load_checkpoint` with their python types
    intact."""
    arrays = _flatten_with_paths(state)
    if amp_state:
        for k, v in _flatten_with_paths(amp_state).items():
            arrays["__amp__/" + k] = v
    for k, v in extra.items():
        ek, ev = _encode_extra(k, v)
        arrays["__extra__/" + ek] = ev
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)         # atomic publish


def _split_raw_arrays(arrays: dict):
    """Split a loaded key->array dict into (plain, amp, extra_raw)."""
    amp_state, extra_raw, plain = {}, {}, {}
    for k, v in arrays.items():
        if _DTYPE_TAG in k:
            k, tag = k.split(_DTYPE_TAG, 1)
            v = _decode(v, tag)
        if k.startswith("__amp__/"):
            amp_state[k[len("__amp__/"):]] = v
        elif k.startswith("__extra__/"):
            extra_raw[k[len("__extra__/"):]] = v
        else:
            plain[k] = v
    return plain, amp_state, extra_raw


def _padded_flat_len(size: int, num_shards: int) -> int:
    """The zero1 flat-bucket padding rule — delegated to
    :func:`apex_tpu.multi_tensor.buckets.padded_shard_len`, the single
    definition state init and reshard-on-read both use."""
    from .multi_tensor.buckets import padded_shard_len
    return padded_shard_len(size, num_shards)


def _maybe_reshard_flat(arr: np.ndarray, want_shape, key: str,
                        buckets: Optional[dict]):
    """Reshard a zero1 flat-bucket leaf on read: a checkpoint saved at
    shard count N stores each bucket's optimizer-state leaves padded to
    ``_padded_flat_len(size, N)``; restoring at M != N re-slices to the
    bucket's TRUE size (from the manifest's bucket layout) and re-pads
    to the template's length.  Returns the resharded array, or None when
    the mismatch is not a recorded bucket (caller raises).

    The bucket is identified by its INDEX parsed from the leaf's key
    path when possible (``.../inner/<i>/...`` — zero1 keeps one inner
    state per bucket, in store order): two buckets whose true sizes
    collide under the old padding would otherwise match the wrong size
    and silently zero real moment values.  The padded-size scan is only
    the fallback for layouts whose paths carry no index."""
    if not buckets or arr.ndim != 1 or len(want_shape) != 1:
        return None
    old_n = int(buckets.get("num_shards", 0))
    if old_n < 1:
        return None
    want = int(want_shape[0])
    sizes = [int(s) for s in buckets.get("sizes", ())]

    def _fits(true_size):
        return (_padded_flat_len(true_size, old_n) == arr.size
                and want >= true_size)

    candidates = []
    for seg in key.split("/"):
        if seg.isdigit() and int(seg) < len(sizes):
            candidates.append(sizes[int(seg)])
    candidates += sizes                 # fallback: padded-size scan
    for true_size in candidates:
        if _fits(true_size):
            out = arr[:true_size]
            if want > true_size:
                out = np.concatenate(
                    [out, np.zeros((want - true_size,), arr.dtype)])
            return out
    return None


def _rebuild(plain: dict, like, *, buckets: Optional[dict] = None,
             context: str = "checkpoint"):
    """Match ``plain`` (key -> host array) against the template ``like``
    and rebuild the pytree, validating dtypes, resharding flat buckets,
    and device-placing each leaf onto the template's sharding."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    consumed = set()
    leaves = []
    for path_elems, leaf in flat:
        key = _path_key(path_elems)
        if key not in plain:
            raise KeyError(f"{context} missing leaf {key!r}")
        consumed.add(key)
        arr = plain[key]
        # Validation reads only the template's STATIC aval (dtype/shape)
        # — never its values, so a donated-and-deleted template leaf or
        # a jax.ShapeDtypeStruct template validates fine and the load
        # pays no D2H transfer of the template tree.
        want_dtype = (np.dtype(leaf.dtype) if hasattr(leaf, "dtype")
                      else None)
        if want_dtype is not None and arr.dtype != want_dtype:
            raise ValueError(
                f"dtype mismatch for {key!r}: checkpoint {arr.dtype}, "
                f"template {want_dtype} — restore with the same opt_level "
                f"used at save time (reference checkpointing rule)")
        want_shape = (tuple(leaf.shape) if hasattr(leaf, "shape")
                      else None)
        if want_shape is not None and arr.shape != want_shape:
            resharded = _maybe_reshard_flat(arr, want_shape, key, buckets)
            if resharded is None:
                raise ValueError(
                    f"shape mismatch for {key!r}: checkpoint {arr.shape}, "
                    f"template {want_shape} — not a recorded flat bucket, "
                    f"so elastic resharding cannot apply")
            arr = resharded
        leaves.append(_place_like(arr, leaf))
    unconsumed = set(plain) - consumed
    if unconsumed:
        # A checkpoint from a larger/renamed model would otherwise appear
        # to load while silently dropping state (ADVICE r1 #5).
        raise KeyError(
            "{} holds {} array(s) with no matching template leaf "
            "(e.g. {!r}) — the template pytree does not match the model "
            "that was saved".format(context, len(unconsumed),
                                    sorted(unconsumed)[0]))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def load_checkpoint(path: str, like):
    """Restore a pytree shaped like ``like`` from ``path``; returns
    ``(state, amp_state_dict, extra_dict)``.  Dtypes/shapes must match
    the template (same opt_level rule as the reference recipe); every
    restored leaf is device-placed onto the template leaf's sharding
    when that sharding is committed, so resuming on a mesh keeps the
    state sharded."""
    with np.load(path, allow_pickle=False) as data:
        arrays = {k: data[k] for k in data.files}
    plain, amp_state, extra_raw = _split_raw_arrays(arrays)
    state = _rebuild(plain, like)
    return state, amp_state, _decode_extras(extra_raw)


# -- v2: sharded directory layout ---------------------------------------------

def _step_dir_name(step: int) -> str:
    return f"step_{int(step):08d}"


def _shard_file_name(shard: int, n_shards: int) -> str:
    return f"shard_{shard:05d}_of_{n_shards:05d}.npz"


def _manifest_file_name(shard: int, n_shards: int) -> str:
    return f"manifest_{shard:05d}_of_{n_shards:05d}.json"


def _crc32_file(path: str) -> str:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return f"{crc & 0xFFFFFFFF:08x}"


def bucket_layout(store, num_shards: int) -> dict:
    """Manifest descriptor of a zero1 ``bucketed=True`` run's flat
    buckets: the per-bucket TRUE element counts (pre-padding) plus the
    shard count the optimizer state was padded for.  Recorded by
    :meth:`CheckpointManager.save` so :func:`load_checkpoint_dir` can
    re-slice the buckets when the resume world's shard count differs —
    build it from the SAME :class:`~apex_tpu.multi_tensor.BucketStore`
    the optimizer packs with (delegates to
    :meth:`~apex_tpu.multi_tensor.BucketStore.shard_layout`)."""
    return store.shard_layout(num_shards)


class Restored(NamedTuple):
    """One restored v2 checkpoint."""
    state: Any
    amp_state: dict
    extra: dict
    loader_state: Optional[dict]
    step: int
    run_id: Optional[str] = None


def list_checkpoints(directory: str):
    """Sorted ``(step, step_dir)`` pairs found under ``directory``
    (no validation — see :func:`latest_checkpoint`)."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _STEP_DIR_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def _validate_step_dir(step_dir: str) -> Optional[dict]:
    """Validate one step directory: every manifest part present, every
    shard file present with a matching checksum.  Returns the merged
    manifest dict, or None when the checkpoint is unusable (mid-write
    crash leaving ``.tmp`` files, truncated/corrupted shards, missing
    parts)."""
    manifests = []
    try:
        names = os.listdir(step_dir)
    except OSError:
        return None
    for name in names:
        if name.startswith("manifest_") and name.endswith(".json"):
            try:
                with open(os.path.join(step_dir, name),
                          encoding="utf-8") as f:
                    manifests.append(json.load(f))
            except (OSError, json.JSONDecodeError):
                return None
    if not manifests:
        return None
    n_shards = manifests[0].get("n_shards")
    if len(manifests) != n_shards:
        return None               # a host's part never landed
    merged = {"parts": sorted(manifests, key=lambda m: m.get("shard", 0)),
              "step": manifests[0].get("step"),
              "version": manifests[0].get("version")}
    if merged["version"] is None or merged["version"] > _MANIFEST_VERSION:
        return None
    for part in merged["parts"]:
        fpath = os.path.join(step_dir, part.get("file", ""))
        if not os.path.isfile(fpath):
            return None
        try:
            if _crc32_file(fpath) != part.get("file_crc32"):
                return None
        except OSError:
            return None
    return merged


def _find_latest_valid(directory: str):
    """Newest valid step dir AND its merged manifest (so callers that
    immediately load don't pay a second full-CRC validation pass)."""
    for step, step_dir in reversed(list_checkpoints(directory)):
        manifest = _validate_step_dir(step_dir)
        if manifest is not None:
            return step_dir, manifest
    return None, None


def latest_checkpoint(directory: str) -> Optional[str]:
    """Newest VALID step directory under ``directory`` (manifest parts
    complete, shard checksums pass), or None.  Invalid newest steps —
    a mid-write crash's ``.tmp`` leftovers, a truncated shard — fall
    back to the previous valid step instead of failing the resume."""
    return _find_latest_valid(directory)[0]


def load_checkpoint_dir(path: str, like, *, step: Optional[int] = None):
    """Restore a :class:`Restored` from a v2 checkpoint directory.

    ``path`` may be the checkpoint root (the newest valid step is
    chosen, or ``step`` pins one) or a single ``step_*`` directory.
    Every shard file is read and merged, leaves are validated against
    ``like`` (dtype + shape), flat buckets recorded in the manifest's
    bucket layout are resharded when the template's padded length
    differs (elastic zero1 resume), and each leaf is device-placed onto
    the template's committed sharding."""
    step_dir, manifest = path, None
    if not _STEP_DIR_RE.match(os.path.basename(os.path.normpath(path))):
        if step is not None:
            step_dir = os.path.join(path, _step_dir_name(step))
        else:
            # newest-valid search hands back the manifest it already
            # built, so the shards are CRC-read once here, not twice
            step_dir, manifest = _find_latest_valid(path)
            if step_dir is None:
                raise CheckpointError(
                    f"no valid checkpoint under {path!r}")
    if manifest is None:
        manifest = _validate_step_dir(step_dir)
    if manifest is None:
        raise CheckpointError(
            f"checkpoint {step_dir!r} is missing, incomplete, or fails "
            f"its checksums")
    arrays: dict = {}
    for part in manifest["parts"]:
        fpath = os.path.join(step_dir, part["file"])
        with np.load(fpath, allow_pickle=False) as data:
            for k in data.files:
                arrays[k] = data[k]
    plain, amp_state, extra_raw = _split_raw_arrays(arrays)
    part0 = manifest["parts"][0]
    buckets = part0.get("buckets")
    state = _rebuild(plain, like, buckets=buckets,
                     context=f"checkpoint {os.path.basename(step_dir)}")
    extra = dict(part0.get("extra") or {})
    extra.update(_decode_extras(extra_raw))
    return Restored(state=state, amp_state=amp_state, extra=extra,
                    loader_state=part0.get("loader"),
                    step=int(manifest["step"]),
                    run_id=part0.get("run_id"))


class _Pending(NamedTuple):
    step: int
    arrays: dict              # key -> host np array (this shard's leaves)
    manifest: dict            # this shard's manifest part (sans checksums)
    done: threading.Event
    t_enqueue: float


class CheckpointManager:
    """Async, sharded, elastic checkpoint engine (ISSUE 9 tentpole).

    * **Async snapshot** — :meth:`save` copies the state to host (every
      leaf's D2H copy started before the first await) and returns; a
      background writer thread serializes, fsyncs, and atomically
      publishes (``.tmp`` → ``os.replace``, manifest last), so the train
      loop stalls only for the copy trigger.  ``block=True`` (or
      ``async_write=False``) keeps the whole write on the caller — the
      final drain checkpoint and the bench's sync baseline.
    * **Per-host sharded layout** — with ``procs=(index, count)`` (default
      ``jax.process_index()/process_count()``) each host writes only the
      leaves it owns (round-robin over the flat leaf order) as
      ``shard_<i>_of_<n>.npz`` plus its manifest part; a checkpoint is
      valid only when every part landed and every checksum passes.
    * **Retention** — ``keep`` newest valid checkpoints survive; older
      step directories are pruned after each successful publish.
    * **Elastic resume** — pass ``bucket_layout=``
      (:func:`bucket_layout`) on save so a zero1 ``bucketed=True``
      state restores at a different shard count (the manifest records
      each bucket's true size; :func:`load_checkpoint_dir` re-slices).

    Telemetry: with a recorder active, every save emits ``checkpoint``
    span events (``phase`` = snapshot / serialize / commit / error /
    backlog) the watchdog's ``checkpoint_stall`` / ``checkpoint_failed``
    rules fold (``docs/telemetry.md``).

    Writer errors never kill the training loop mid-save: they are
    recorded (and emitted as ``checkpoint`` ``phase="error"`` events)
    and re-raised from the next :meth:`save` / :meth:`wait` /
    :meth:`close` so the failure is surfaced on the caller's thread.
    """

    def __init__(self, directory: str, *, keep: int = 3,
                 every_steps: Optional[int] = None,
                 async_write: bool = True,
                 procs: Optional[Tuple[int, int]] = None,
                 run_id: Optional[str] = None,
                 max_pending: int = 2, fsync: bool = True,
                 telemetry=None):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        if every_steps is not None and every_steps < 1:
            raise ValueError(
                f"every_steps must be >= 1, got {every_steps}")
        self.directory = directory
        self.keep = int(keep)
        self.every_steps = every_steps
        self.async_write = bool(async_write)
        if procs is None:
            # One source of process identity (ISSUE 12 satellite): the
            # multiproc helper prefers the initialized distributed
            # runtime but falls back to the launcher env, so a spawned
            # worker writes ITS shard even before jax.distributed is up.
            from .parallel.multiproc import process_identity
            procs = process_identity()
        index, count = int(procs[0]), int(procs[1])  # jaxlint: disable=J001 -- procs is a (index, count) pair of host ints, never a device value
        if not 0 <= index < count:
            raise ValueError(f"procs index {index} not in [0, {count})")
        self.procs = (index, count)
        if run_id is None:
            rec = _telemetry.get_recorder()
            run_id = getattr(rec, "run_id", None) or uuid.uuid4().hex[:12]
        self.run_id = run_id
        self.max_pending = max(1, int(max_pending))
        self.fsync = bool(fsync)
        self._telemetry = telemetry
        self._last_saved: Optional[int] = None
        self._error: Optional[BaseException] = None
        self._q: "queue.Queue[Optional[_Pending]]" = queue.Queue()
        self._writer: Optional[threading.Thread] = None
        self._closed = False
        # step dirs already proven valid: a committed checkpoint is
        # immutable, so prune never re-reads (re-CRCs) its shards —
        # without this every publish would re-checksum `keep`
        # checkpoints' worth of bytes off disk.
        self._known_valid: set = set()
        os.makedirs(directory, exist_ok=True)

    # -- telemetry ----------------------------------------------------------
    def _rec(self):
        return (self._telemetry if self._telemetry is not None
                else _telemetry.get_recorder())

    def _event(self, phase: str, **fields) -> None:
        rec = self._rec()
        if rec is not None:
            rec.event("checkpoint", phase=phase, **fields)
            # live gauges for the Prometheus exporter (ISSUE 10): a
            # dashboard watches writer backlog and the freshest
            # recovery point without parsing the stream.
            if phase == "backlog":
                rec.metrics.gauge("checkpoint_backlog").set(
                    fields.get("value", 0))
            elif phase == "commit":
                rec.metrics.gauge("checkpoint_backlog").set(0)
                if fields.get("step") is not None:
                    rec.metrics.gauge("checkpoint_last_step").set(
                        fields["step"])
            elif phase == "error":
                rec.metrics.counter("checkpoint_errors").inc()

    # -- cadence ------------------------------------------------------------
    @property
    def last_saved(self) -> Optional[int]:
        return self._last_saved

    def maybe_save(self, step: int, state, **kw) -> bool:
        """Save iff ``every_steps`` is set and ``step`` has advanced at
        least that far past the last save (the StepPipeline window-hook
        cadence; a fresh run's cadence anchors at step 0, so the first
        save lands AT ``every_steps``, keeping save steps on the same
        grid across kill/resume cycles).  Returns True when a save was
        triggered."""
        if self.every_steps is None:
            return False
        if step - (self._last_saved or 0) < self.every_steps:
            return False
        self.save(step, state, **kw)
        return True

    # -- save ---------------------------------------------------------------
    def save(self, step: int, state, *, amp_state: Optional[dict] = None,
             loader_state: Optional[dict] = None,
             bucket_layout: Optional[dict] = None,
             block: bool = False, **extra) -> None:
        """Checkpoint ``state`` at ``step``.

        The caller pays only the host snapshot (overlapped D2H copies of
        this host's leaves); serialization, fsync, atomic publish, and
        retention pruning run on the writer thread.  ``block=True``
        forces the whole write on the caller (the drain checkpoint).
        ``extra`` round-trips like :func:`save_checkpoint` extras."""
        self._raise_pending_error()
        if self._closed:
            raise CheckpointError("CheckpointManager is closed")
        index, count = self.procs
        t0 = time.perf_counter()
        arrays = _snapshot_with_paths(
            state, own=(None if count == 1
                        else (lambda i: i % count == index)))
        if amp_state and index == 0:
            for k, v in _flatten_with_paths(amp_state).items():
                arrays["__amp__/" + k] = v
        if index == 0:
            for k, v in extra.items():
                ek, ev = _encode_extra(k, v)
                arrays["__extra__/" + ek] = ev
        snap_s = time.perf_counter() - t0
        nbytes = int(sum(a.nbytes for a in arrays.values()))
        self._event("snapshot", step=int(step), dur=round(snap_s, 6),
                    bytes=nbytes, shard=index)
        manifest = {
            "format": "apex_tpu-ckpt-v2",
            "version": _MANIFEST_VERSION,
            "step": int(step),
            "shard": index, "n_shards": count,
            "file": _shard_file_name(index, count),
            "run_id": self.run_id,
            "world": {"process_count": count,
                      "device_count": jax.device_count()},
            "wall_time": time.time(),
            "leaves": {k: {"shape": list(v.shape), "dtype": v.dtype.name}
                       for k, v in arrays.items()},
        }
        if index == 0:
            manifest["loader"] = loader_state
            manifest["buckets"] = bucket_layout
            # JSON-safe extras ride in the manifest too (human-readable
            # `cat manifest.json`); the npz keys stay authoritative.
            manifest["extra"] = {
                k: v for k, v in extra.items()
                if isinstance(v, (str, bool, int, float, type(None)))}
        pending = _Pending(step=int(step), arrays=arrays,
                           manifest=manifest, done=threading.Event(),
                           t_enqueue=time.perf_counter())
        self._last_saved = int(step)
        if block or not self.async_write:
            self.wait()            # order after (and never race) the
            self._write_one(pending)   # writer thread's pending steps
            self._raise_pending_error()
            return
        self._ensure_writer()
        backlog = self._q.qsize()
        if backlog >= self.max_pending:
            # Bound host memory: a writer that cannot keep up with the
            # save cadence stalls the trigger here — visible to the
            # watchdog as a checkpoint backlog.
            self._event("backlog", step=int(step), value=backlog)
            while self._q.qsize() >= self.max_pending \
                    and self._writer is not None \
                    and self._writer.is_alive():
                time.sleep(0.005)
        self._q.put(pending)

    def _ensure_writer(self) -> None:
        if self._writer is None or not self._writer.is_alive():
            self._writer = threading.Thread(
                target=self._writer_loop, daemon=True,
                name="apex-tpu-ckpt-writer")
            self._writer.start()

    def _writer_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            if item.manifest.get("__fence__"):
                item.done.set()    # a wait() marker, nothing to write
                continue
            try:
                self._write_one(item)
            except BaseException as e:   # surfaced on the caller's thread
                self._error = e
                self._event("error", step=item.step,
                            error=f"{type(e).__name__}: {e}")
            finally:
                item.done.set()

    def _fsync(self, f) -> None:
        if self.fsync:
            f.flush()
            os.fsync(f.fileno())

    def _write_one(self, pending: _Pending) -> None:
        index, count = pending.manifest["shard"], pending.manifest["n_shards"]
        step_dir = os.path.join(self.directory,
                                _step_dir_name(pending.step))
        os.makedirs(step_dir, exist_ok=True)
        t0 = time.perf_counter()
        shard_path = os.path.join(step_dir, pending.manifest["file"])
        tmp = shard_path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **pending.arrays)
            self._fsync(f)
        os.replace(tmp, shard_path)
        manifest = dict(pending.manifest)
        manifest["file_bytes"] = os.path.getsize(shard_path)
        manifest["file_crc32"] = _crc32_file(shard_path)
        self._event("serialize", step=pending.step,
                    dur=round(time.perf_counter() - t0, 6),
                    bytes=manifest["file_bytes"], shard=index)
        mpath = os.path.join(step_dir, _manifest_file_name(index, count))
        mtmp = mpath + ".tmp"
        with open(mtmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f, indent=1)
            self._fsync(f)
        os.replace(mtmp, mpath)     # the commit point for this shard
        self._event("commit", step=pending.step, shard=index,
                    dur=round(time.perf_counter() - pending.t_enqueue, 6))
        if index == 0:
            self._prune()
        pending.done.set()

    def _prune(self) -> None:
        """Keep the ``keep`` newest VALID checkpoints; drop the rest
        (and any step directory older than the newest valid ones that
        never became valid — a crashed write's debris)."""
        entries = list_checkpoints(self.directory)
        valid = []
        for s, sd in entries:
            if sd in self._known_valid \
                    or _validate_step_dir(sd) is not None:
                self._known_valid.add(sd)
                valid.append((s, sd))
        if not valid:
            return
        survivors = valid[-self.keep:]
        oldest_kept = survivors[0][0]
        keep_dirs = {sd for _, sd in survivors}
        for s, step_dir in entries:
            # Only prune strictly OLDER than the retention window: an
            # invalid NEWER dir may be a checkpoint another host is
            # still committing, never debris to delete from here.
            if step_dir in keep_dirs or s >= oldest_kept:
                continue
            try:
                shutil.rmtree(step_dir)
                self._known_valid.discard(step_dir)
                self._event("prune", path=os.path.basename(step_dir))
            except OSError:
                pass

    # -- restore ------------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        found = latest_checkpoint(self.directory)
        if found is None:
            return None
        return int(_STEP_DIR_RE.match(os.path.basename(found)).group(1))

    def restore(self, like, *, step: Optional[int] = None,
                required: bool = False) -> Optional[Restored]:
        """Restore the newest valid checkpoint (or ``step``) against the
        template ``like``; returns None when the directory holds no
        valid checkpoint (fresh start) unless ``required``."""
        self.wait()
        t0 = time.perf_counter()
        try:
            restored = load_checkpoint_dir(self.directory, like, step=step)
        except CheckpointError:
            if required:
                raise
            return None
        self._event("restore", step=restored.step,
                    dur=round(time.perf_counter() - t0, 6))
        self._last_saved = restored.step
        if restored.run_id:
            # Adopt the saved run's identity: subsequent saves (and the
            # caller's telemetry stream, if it copies mgr.run_id) stay
            # attributable to ONE logical run across interruptions.
            self.run_id = restored.run_id
        return restored

    # -- lifecycle ----------------------------------------------------------
    def _raise_pending_error(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise CheckpointError(
                f"checkpoint writer failed: {type(err).__name__}: {err}"
            ) from err

    @property
    def pending(self) -> int:
        """Writes enqueued but not yet published."""
        return self._q.qsize()

    #: how long wait()/close() give the writer before declaring it
    #: wedged (hung storage) — a silent timeout here would let a drain
    #: report "checkpoint saved" with nothing published.
    drain_timeout_s: float = 300.0

    def wait(self) -> None:
        """Block until every enqueued write has published; re-raises a
        writer failure on this thread, and raises if the writer is
        wedged (no progress within ``drain_timeout_s`` — hung NFS and
        the like) instead of returning as if the write landed."""
        if self._writer is not None and self._writer.is_alive():
            fence = threading.Event()
            self._q.put(_Pending(step=-1, arrays={}, manifest={
                "shard": self.procs[0], "n_shards": self.procs[1],
                "file": "", "__fence__": True}, done=fence,
                t_enqueue=time.perf_counter()))
            if not fence.wait(timeout=self.drain_timeout_s):
                raise CheckpointError(
                    f"checkpoint writer did not drain within "
                    f"{self.drain_timeout_s:.0f}s — storage is hung or "
                    f"the writer is wedged; pending checkpoints are NOT "
                    f"published")
        self._raise_pending_error()

    def close(self) -> None:
        """Drain pending writes and stop the writer thread.  Idempotent;
        re-raises a writer failure, and raises if the writer never
        exits (wedged storage) rather than pretending the drain
        finished."""
        if self._closed:
            return
        self._closed = True
        if self._writer is not None and self._writer.is_alive():
            self._q.put(None)
            self._writer.join(timeout=self.drain_timeout_s)
            if self._writer.is_alive():
                raise CheckpointError(
                    f"checkpoint writer still running after "
                    f"{self.drain_timeout_s:.0f}s at close — storage is "
                    f"hung; pending checkpoints are NOT published")
        self._raise_pending_error()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
