"""apex_tpu — a TPU-native mixed-precision & distributed training framework.

Brand-new implementation of the capabilities of NVIDIA Apex (reference
snapshot surveyed in SURVEY.md), designed TPU-first:

* bfloat16 mixed precision (``apex_tpu.amp``) — opt levels O0-O3 with
  static-by-default loss scaling (bf16 has fp32's exponent range).
* data parallelism over ``jax.sharding.Mesh`` with XLA collectives
  (``apex_tpu.parallel``) — the DDP contract without buckets/streams.
* fused optimizers (``apex_tpu.optimizers``) — whole-model single-program
  updates (Adam, LAMB, NovoGrad, SGD) via XLA fusion + Pallas kernels.
* fused normalization (``apex_tpu.normalization``) — Pallas LayerNorm.
* multi-tensor engine (``apex_tpu.multi_tensor``) — pytree-wide scaled
  copies / axpby / norms with a device-side overflow flag.
* profiling (``apex_tpu.prof``) — named-scope capture + per-op flops/bytes
  analysis of jaxprs (the pyprof analog).
* run telemetry (``apex_tpu.telemetry``) — structured JSONL event stream
  + metrics registry for live runs; offline analysis via
  ``python -m apex_tpu.prof.timeline``.
* warm start (``apex_tpu.cache``) — persistent XLA compilation cache +
  AOT warmup of the step-pipeline device loop (zero compiles after
  step 0).
* kernel autotuning (``apex_tpu.tune``) — roofline-driven block/layout
  search for every Pallas kernel with a persistent per-device config
  cache consulted at dispatch time (``python -m apex_tpu.tune``).
* legacy surfaces: ``bf16_utils`` (= reference fp16_utils), ``RNN``,
  ``reparameterization``, ``contrib``.
"""

__version__ = "0.1.0"

from . import amp            # noqa: F401
from . import multi_tensor   # noqa: F401

# Subpackages with heavier imports are lazy, mirroring the reference's lazy
# optimizers/normalization imports (apex/__init__.py:1-19).
import importlib as _importlib

_LAZY = ("optimizers", "normalization", "parallel", "bf16_utils", "fp16_utils",
         "RNN", "reparameterization", "contrib", "prof", "training", "models",
         "runtime", "data", "telemetry", "cache", "tune")


def __getattr__(name):
    if name in _LAZY:
        mod = _importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError("module 'apex_tpu' has no attribute {!r}".format(name))
