"""apex_tpu.reparameterization — weight reparameterizations over pytrees.

Re-design of reference ``apex/reparameterization/`` (hooks-based module
mutation, reparameterization.py:4-151, weight_norm.py:22-78).  In JAX,
parameters are pytrees and the forward is pure, so a reparameterization is a
**pair of pure functions**:

* ``apply_*(params, ...)``  — split selected weights ``w`` into auxiliary
  params (e.g. ``{name}_g``/``{name}_v``), returning the new pytree.
* ``reconstruct(params)``   — rebuild the original weights from the auxiliary
  params.  Compose with any apply_fn: ``model.apply(reconstruct(p), x)``;
  the recomputation happens inside the traced step exactly like the
  reference's pre-forward hook recompute, and autograd flows to g/v.

``remove_*`` folds the reparameterization back into plain weights
(reference ``remove_reparameterization``, __init__.py:96-123).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

__all__ = ["Reparameterization", "WeightNorm", "apply_weight_norm",
           "remove_weight_norm", "apply_reparameterization",
           "remove_reparameterization", "reconstruct"]


def _norm_except_dim(v, dim):
    """Norm over all dims except ``dim`` (reference weight_norm.py:7-18);
    ``dim=None`` → scalar full-tensor norm."""
    v32 = v.astype(jnp.float32)
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(v32)))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v32), axis=axes, keepdims=True))


class Reparameterization:
    """Base: subclasses define ``reparameterize(w) -> aux_dict`` and
    ``compute_weight(aux_dict) -> w`` (reference reparameterization.py:28-56)."""

    name = "reparam"

    def __init__(self, dim: Optional[int] = 0):
        self.dim = dim

    def reparameterize(self, weight):
        raise NotImplementedError

    def compute_weight(self, aux):
        raise NotImplementedError


class WeightNorm(Reparameterization):
    """w = g * v / ‖v‖ (reference weight_norm.py:22-78).  The fused CUDA
    kernel (``Fused_Weight_Norm``) dissolves: XLA fuses the norm + scale
    into the consumer matmul's epilogue."""

    name = "weight_norm"

    def reparameterize(self, weight):
        return {"g": _norm_except_dim(weight, self.dim).astype(jnp.float32),
                "v": weight}

    def compute_weight(self, aux):
        v, g = aux["v"], aux["g"]
        w = g * v.astype(jnp.float32) / (_norm_except_dim(v, self.dim) + 1e-12)
        return w.astype(v.dtype)


_MARKER = "__reparam__"


@jax.tree_util.register_static
class _Kind:
    """Static (leafless) pytree marker recording the reparameterization name
    and its ``dim`` — safe to carry through jit/grad, unlike a raw string
    leaf, and self-describing so ``reconstruct`` needs no side channel."""

    def __init__(self, name: str, dim=0):
        self.name = name
        self.dim = dim

    def __eq__(self, other):
        return (isinstance(other, _Kind) and other.name == self.name
                and other.dim == self.dim)

    def __hash__(self):
        return hash(("_Kind", self.name, self.dim))

    def __repr__(self):
        return f"_Kind({self.name!r}, dim={self.dim})"


def _match(path_str: str, name: str) -> bool:
    if not name:
        # default: every kernel/weight leaf (reference name='' applies to all
        # weight-named params in the module tree, __init__.py:24-43)
        return bool(re.search(r"(kernel|weight)$", path_str))
    return name in path_str


def apply_reparameterization(params, reparameterization: Reparameterization,
                             name: str = "", dim: int = 0):
    """Replace matching weight leaves with ``{_MARKER: cls, aux...}`` subtrees."""
    rep = reparameterization

    def transform(tree, prefix=""):
        if isinstance(tree, dict):
            new = {}
            for k, v in tree.items():
                path = f"{prefix}/{k}" if prefix else k
                if isinstance(v, dict):
                    new[k] = transform(v, path)
                elif hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating) \
                        and v.ndim >= 2 and _match(path, name):
                    aux = rep.reparameterize(v)
                    aux[_MARKER] = _Kind(rep.name, rep.dim)
                    new[k] = aux
                else:
                    new[k] = v
            return new
        return tree

    return transform(_to_plain_dict(params))


def _to_plain_dict(tree):
    """FrozenDict / dict normalization."""
    if hasattr(tree, "unfreeze"):
        tree = tree.unfreeze()
    if isinstance(tree, dict):
        return {k: _to_plain_dict(v) for k, v in tree.items()}
    return tree


_REGISTRY = {}


def _register(cls):
    _REGISTRY[cls.name] = cls
    return cls


_register(WeightNorm)


def reconstruct(params, name: str = ""):
    """Rebuild plain weights from reparameterized subtrees — call on the
    params pytree before (or inside) ``model.apply``; this is the pre-forward
    recompute hook (reference reparameterization.py:139-146) as a pure fn.
    The kind and dim come from each subtree's marker (recorded at apply
    time), so no side-channel arguments are needed; ``name`` restricts the
    fold-back to matching paths (reference per-name removal)."""
    def walk(tree, prefix=""):
        if isinstance(tree, dict):
            if _MARKER in tree:
                if name and name not in prefix:
                    return tree
                kind = tree[_MARKER]
                rep = _REGISTRY[kind.name](dim=kind.dim)
                return rep.compute_weight(
                    {k: v for k, v in tree.items() if k != _MARKER})
            return {k: walk(v, f"{prefix}/{k}" if prefix else k)
                    for k, v in tree.items()}
        return tree
    return walk(_to_plain_dict(params))


def remove_reparameterization(params, name: str = ""):
    """Fold aux params back into plain weights (reference __init__.py:96-123);
    ``name`` limits removal to matching paths."""
    return reconstruct(params, name=name)


def apply_weight_norm(params, name: str = "", dim: int = 0):
    """Weight-normalize matching weights (reference __init__.py:4-49)."""
    return apply_reparameterization(params, WeightNorm(dim=dim), name=name,
                                    dim=dim)


def remove_weight_norm(params, name: str = "", dim: int = 0):
    del dim  # recorded in each marker at apply time
    return remove_reparameterization(params, name=name)
