"""Shared Pallas/shard_map compatibility helpers.

Lives at the package root (not under ``ops``/``normalization``) because
both import it and ``ops`` ↔ ``normalization`` already depend on each
other through the kernel gating.
"""

from __future__ import annotations

import jax

__all__ = ["sds_with_vma", "align_vma"]


def align_vma(*arrays):
    """``pcast`` every array up to the union of all the arrays' vma
    (varying-manual-axes) sets.

    ``pallas_call`` under ``shard_map``'s default ``check_vma=True``
    requires its operands to agree on how they vary; mixed operands are
    common at kernel boundaries — e.g. rank-varying dynamic offsets
    (functions of ``lax.axis_index``) next to replicated zero biases, or
    replicated scalars next to sharded activations.  Broadcasting the
    union onto every operand is semantically a no-op (each shard already
    holds the value it would hold) and unblocks the kernel path without
    ``check_vma=False`` (VERDICT r2 weak #2).  Off shard_map / with
    tracking disabled this returns the inputs unchanged."""
    from jax import lax

    union = set()
    for x in arrays:
        try:
            union |= set(jax.typeof(x).vma)
        except AttributeError:
            pass
    if not union:
        return arrays
    out = []
    for x in arrays:
        missing = tuple(sorted(union - set(jax.typeof(x).vma)))
        out.append(lax.pcast(x, missing, to="varying") if missing else x)
    return tuple(out)


def sds_with_vma(shape, dtype, *like):
    """``ShapeDtypeStruct`` whose vma (varying-manual-axes) is the union
    of the operands' — required for ``pallas_call`` outputs inside
    ``shard_map`` with ``check_vma=True``; harmless (plain struct)
    outside or on older jax without the ``vma`` kwarg."""
    vma = None
    for x in like:
        try:
            v = jax.typeof(x).vma
        except AttributeError:
            continue
        vma = v if vma is None else (vma | v)
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:       # older jax: no vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)
