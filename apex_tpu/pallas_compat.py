"""Shared Pallas/shard_map compatibility helpers.

Lives at the package root (not under ``ops``/``normalization``) because
both import it and ``ops`` ↔ ``normalization`` already depend on each
other through the kernel gating.
"""

from __future__ import annotations

import jax

__all__ = ["sds_with_vma"]


def sds_with_vma(shape, dtype, *like):
    """``ShapeDtypeStruct`` whose vma (varying-manual-axes) is the union
    of the operands' — required for ``pallas_call`` outputs inside
    ``shard_map`` with ``check_vma=True``; harmless (plain struct)
    outside or on older jax without the ``vma`` kwarg."""
    vma = None
    for x in like:
        try:
            v = jax.typeof(x).vma
        except AttributeError:
            continue
        vma = v if vma is None else (vma | v)
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:       # older jax: no vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)
