"""Scale calibration: observe → freeze → serve (ISSUE 13, layer 2).

int8 activations need a RANGE before the first quantized step: symmetric
absmax scaling maps ``[-amax, amax]`` onto ``[-127, 127]``, so the whole
accuracy story is "how good is your amax".  This module implements the
two standard recipes over the machinery the repo already has:

* **observation phase** — run a handful of real batches through the
  model in ``mode="observe"`` (the :class:`~apex_tpu.quant.layers.
  QuantDenseGeneral` sites fold a running absmax into a flax
  ``quant_stats`` collection; one fetch per batch, at the boundary the
  caller already owns).  :meth:`Calibrator.harvest` feeds each fetch
  into a bounded per-site **amax history** and mirrors it into the
  telemetry :class:`~apex_tpu.telemetry.metrics.MetricsRegistry`
  (``quant_absmax/<site>`` high-water gauges + ``quant_amax/<site>``
  histograms), so calibration is observable through the exact same
  Prometheus export as everything else;
* **freeze** — :meth:`Calibrator.freeze` collapses each history into
  one frozen scale: ``mode="max"`` is the delayed-amax-history scaling
  of FP8 training (Micikevicius et al. — the max over the last H
  observations, robust to a single quiet batch), ``mode=<percentile>``
  clips outliers LLM.int8()-style (e.g. ``99.9`` ignores the one-in-a-
  thousand spike that would otherwise waste the int8 grid on empty
  range).

The frozen :class:`Calibration` is a plain host object: scales embed in
the traced step as CONSTANTS (recalibrating means one deliberate
retrace, never a per-step recompute — jaxlint J014 flags the latter),
and it serializes through :class:`~apex_tpu.checkpoint.CheckpointManager`
extras (``state_dict()`` is tagged-JSON-compatible) so a serving process
restores the exact training-time scales::

    mgr.save(step, state, quant_calibration=calib.state_dict())
    ...
    restored = load_checkpoint_dir(d, like=state)
    calib = Calibration.from_state_dict(restored.extra["quant_calibration"])

At runtime :meth:`Calibration.note_saturation` reports observed
range overflows into the telemetry stream (``kind="quant"`` events) for
the ``quant_scale_saturation`` watchdog rule — the "your calibration
went stale" alarm.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, Optional

__all__ = ["Calibrator", "Calibration"]

#: the quantized range half-width (mirrors kernels.QMAX without a jax
#: import — calibration is pure host code).
_QMAX = 127.0

#: flax collection name the observe-mode layers write into.
STATS_COLLECTION = "quant_stats"


def _active_registry():
    """The active recorder's MetricsRegistry, or None — calibration
    mirrors into telemetry only when a run is recording."""
    from .. import telemetry as _telemetry
    rec = _telemetry.get_recorder()
    return rec.metrics if rec is not None else None


def _flatten_stats(tree, prefix=()) -> Dict[str, float]:
    """Flatten a ``quant_stats`` collection (nested dicts of ``amax``
    leaves) into ``{"block_0/mlp_up": amax_float}`` — the same
    ``/``-joined naming the layers use for scale lookup."""
    out: Dict[str, float] = {}
    if hasattr(tree, "items"):
        for k, v in tree.items():
            if k == "amax":
                out["/".join(str(p) for p in prefix)] = float(v)
            else:
                out.update(_flatten_stats(v, prefix + (str(k),)))
        return out
    # a bare array leaf (caller passed {"name": amax})
    out["/".join(str(p) for p in prefix)] = float(tree)
    return out


class Calibration:
    """Frozen per-site activation scales (the observe phase's output).

    ``scales``: ``{site_name: x_scale}`` (floats, ``amax / 127``);
    ``amax``: the amax each scale froze from, kept for the saturation
    check and for human inspection.  ``get``/``x_scale_for`` return
    None for unknown sites — the layer hook then falls back to the
    plain (bitwise-O2) dense path, so a missing calibration NEVER
    changes numerics silently."""

    def __init__(self, scales: Dict[str, float],
                 amax: Optional[Dict[str, float]] = None,
                 meta: Optional[dict] = None):
        self.scales = {str(k): float(v) for k, v in scales.items()}
        self.amax = {str(k): float(v) for k, v in (amax or {}).items()}
        self.meta = dict(meta or {})
        self._saturations: Dict[str, int] = {}

    def x_scale_for(self, name: str) -> Optional[float]:
        return self.scales.get(name)

    get = x_scale_for

    def __len__(self) -> int:
        return len(self.scales)

    def __contains__(self, name: str) -> bool:
        return name in self.scales

    def __repr__(self) -> str:
        return (f"Calibration({len(self.scales)} site(s), "
                f"mode={self.meta.get('mode')!r})")

    # -- checkpoint round-trip ------------------------------------------------
    def state_dict(self) -> dict:
        """JSON-compatible dict for checkpoint ``extra`` round-trip
        (``CheckpointManager.save(..., quant_calibration=...)``)."""
        return {"version": 1, "scales": dict(self.scales),
                "amax": dict(self.amax), "meta": dict(self.meta)}

    @classmethod
    def from_state_dict(cls, sd: dict) -> "Calibration":
        if int(sd.get("version", 1)) != 1:
            raise ValueError(
                f"unknown quant calibration version {sd.get('version')!r}")
        return cls(sd.get("scales") or {}, sd.get("amax") or {},
                   sd.get("meta") or {})

    # -- runtime saturation reporting ----------------------------------------
    def note_saturation(self, name: str, exceeded: int, *,
                        window: Optional[int] = None,
                        recorder=None) -> None:
        """Report that ``exceeded`` elements (or steps) overflowed the
        calibrated range for ``name`` in the last observation window
        (:func:`apex_tpu.quant.kernels.saturation_count` produces the
        device-side count; fetch it at a boundary you already pay).
        Emits a ``kind="quant"`` telemetry event the
        ``quant_scale_saturation`` watchdog rule folds, and bumps the
        ``quant_saturations/<name>`` counter."""
        from .. import telemetry as _telemetry
        exceeded = int(exceeded)
        self._saturations[name] = self._saturations.get(name, 0) + exceeded
        rec = recorder if recorder is not None else _telemetry.get_recorder()
        if rec is None or exceeded <= 0:
            return
        rec.event("quant", phase="saturation", name=name,
                  exceeded=exceeded,
                  amax=self.amax.get(name),
                  **({"window": int(window)} if window else {}))
        rec.metrics.counter(f"quant_saturations/{name}").inc(exceeded)

    @property
    def saturations(self) -> Dict[str, int]:
        return dict(self._saturations)


class Calibrator:
    """Bounded amax-history accumulator for the observation phase.

    ``history`` bounds the delayed-amax window (FP8-style: freeze
    against the max of the last H observations, so one early warmup
    batch cannot pin the range forever); ``registry`` overrides the
    telemetry mirror target (defaults to the ACTIVE recorder's
    MetricsRegistry, a no-op when nothing records)."""

    def __init__(self, *, history: int = 16, registry=None):
        self.history = max(1, int(history))
        self._hist: Dict[str, deque] = {}
        self._registry = registry

    def observe(self, name: str, amax: float) -> None:
        """Fold one site's observed absmax (a HOST float — fetch device
        values at a boundary you already pay, e.g. the per-batch stats
        fetch of the observe phase)."""
        amax = float(amax)
        name = str(name)
        h = self._hist.get(name)
        if h is None:
            h = self._hist[name] = deque(maxlen=self.history)
        h.append(amax)
        reg = self._registry if self._registry is not None \
            else _active_registry()
        if reg is not None:
            reg.gauge(f"quant_absmax/{name}").set_max(amax)
            reg.histogram(f"quant_amax/{name}").observe(amax)

    def harvest(self, stats) -> "Calibrator":
        """Fold one fetched ``quant_stats`` collection (the nested dict
        ``model.apply(..., mutable=["quant_stats"])`` returns, already
        device_get'd by the caller) — one :meth:`observe` per site."""
        for name, amax in _flatten_stats(stats).items():
            self.observe(name, amax)
        return self

    @property
    def sites(self):
        return sorted(self._hist)

    def freeze(self, mode: Any = "max") -> Calibration:
        """Collapse each site's history into one frozen scale.

        ``mode="max"``: delayed amax history — the max over the last
        ``history`` observations (the FP8 recipe; also the safe
        default).  ``mode=<float percentile>`` (e.g. ``99.9``): the
        nearest-rank percentile over the history, clipping outlier
        spikes LLM.int8()-style.
        """
        from ..telemetry.metrics import nearest_rank_percentiles

        if not self._hist:
            raise ValueError(
                "Calibrator has no observations — run an observation "
                "phase (mode='observe' + harvest) before freeze()")
        scales, amaxes = {}, {}
        for name, h in self._hist.items():
            vals = list(h)
            if mode == "max":
                amax = max(vals)
            else:
                q = float(mode)
                if not 0.0 < q <= 100.0:
                    raise ValueError(
                        f"percentile mode must be in (0, 100], got {q}")
                amax = nearest_rank_percentiles(vals, (q,))[0]
            amaxes[name] = float(amax)
            scales[name] = (float(amax) / _QMAX) if amax > 0 else 1.0
        return Calibration(scales, amaxes,
                           meta={"mode": str(mode),
                                 "history": self.history,
                                 "observations": {
                                     k: len(v)
                                     for k, v in self._hist.items()}})
