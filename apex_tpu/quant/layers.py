"""Model-side quantization hook: drop-in dense layers (ISSUE 13, layer 3).

The amp O4 opt level routes ANNOTATED matmuls through the int8 kernels
while everything else keeps exact O2 semantics.  The annotation lives
here: :class:`QuantDenseGeneral` is a parameter-compatible stand-in for
``nn.Dense`` / ``nn.DenseGeneral`` (same ``kernel``/``bias`` names,
shapes, AND initializer draws — flax's flat-shape ``lecun_normal`` wrap
is reproduced exactly, so an O2 checkpoint drops into an O4 model and
vice versa), selected by the ``quant=`` factory hook the model families
grew (``models/gpt.py`` / ``models/bert.py`` — the same pattern as PR
7's ``norm_cls`` ResNet factory).

Three modes, driven by one :class:`QuantConfig`:

========== ==============================================================
``off``     plain dense math (flax-bitwise — promote_dtype + the same
            ``dot_general`` dimension numbers)
``observe`` plain dense math + a running per-site absmax folded into a
            flax ``quant_stats`` collection (run with
            ``mutable=["quant_stats"]``; feed each fetch to
            :meth:`~apex_tpu.quant.calibrate.Calibrator.harvest`)
``quant``   sites with a frozen calibration scale dispatch
            :func:`~apex_tpu.quant.kernels.quantized_matmul`; sites
            WITHOUT one fall back to the plain path — a missing or
            partial calibration degrades to bitwise O2, never to silent
            garbage
========== ==============================================================
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import dtypes as _flax_dtypes

from . import kernels as K
from .calibrate import STATS_COLLECTION

__all__ = ["QuantConfig", "QuantDenseGeneral"]


@dataclasses.dataclass(frozen=True)
class QuantConfig:
    """One quantization policy for a model build.

    ``mode``: ``"off"`` / ``"observe"`` / ``"quant"`` (table in the
    module docstring); ``scales``: a
    :class:`~apex_tpu.quant.calibrate.Calibration` or a plain
    ``{site: x_scale}`` mapping (site names are ``/``-joined module
    paths, e.g. ``"block_0/mlp_up"``); ``impl``/``interpret`` forward
    to :func:`~apex_tpu.quant.kernels.quantized_matmul` (tests run the
    real kernel on CPU via ``interpret=True``)."""

    mode: str = "quant"
    scales: Any = None
    impl: Optional[str] = None
    interpret: bool = False

    def __post_init__(self):
        if self.mode not in ("off", "observe", "quant"):
            raise ValueError(f"QuantConfig mode must be 'off', 'observe' "
                             f"or 'quant', got {self.mode!r}")

    @classmethod
    def observe(cls) -> "QuantConfig":
        """The observation-phase config (no scales yet)."""
        return cls(mode="observe")

    @classmethod
    def frozen(cls, calibration, **kw) -> "QuantConfig":
        """A serving/training config over a frozen calibration."""
        return cls(mode="quant", scales=calibration, **kw)

    def scale_for(self, name: str) -> Optional[float]:
        s = self.scales
        if s is None:
            return None
        if hasattr(s, "x_scale_for"):
            return s.x_scale_for(name)
        return s.get(name)


def _tup(v) -> Tuple[int, ...]:
    return (v,) if isinstance(v, int) else tuple(v)


class QuantDenseGeneral(nn.Module):
    """Parameter-compatible quantized ``nn.Dense``/``nn.DenseGeneral``.

    ``features``/``axis`` follow the flax contract (scalar-or-tuple
    features; ``axis`` the contracting input dims, default ``-1``);
    params are created with flax's exact names, shapes, and initializer
    draws, so swapping this in for the plain module is a checkpoint
    no-op.  Dispatch per :class:`QuantConfig` mode — see the module
    docstring."""

    features: Union[int, Tuple[int, ...]]
    axis: Union[int, Tuple[int, ...]] = -1
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    use_bias: bool = True
    quant: Optional[QuantConfig] = None

    @nn.compact
    def __call__(self, x):
        features = _tup(self.features)
        axis = tuple(a % x.ndim for a in _tup(self.axis))
        in_shape = tuple(x.shape[a] for a in axis)
        n_in = 1
        for s in in_shape:
            n_in *= s
        n_out = 1
        for s in features:
            n_out *= s

        # flax DenseGeneral draws the kernel on the FLAT (n_in, n_out)
        # shape and reshapes — reproduce it so init values are bitwise
        # identical to the module this one replaces.
        def kernel_init(rng, shape, dtype):
            flat = nn.initializers.lecun_normal()(rng, (n_in, n_out),
                                                  dtype)
            return jnp.reshape(flat, shape)

        kernel = self.param("kernel", kernel_init, in_shape + features,
                            self.param_dtype)
        bias = (self.param("bias", nn.initializers.zeros_init(), features,
                           self.param_dtype)
                if self.use_bias else None)

        cfg = self.quant if self.quant is not None else QuantConfig("off")
        site = self._site_name()
        if cfg.mode == "observe":
            # running absmax per site; create-only on the init trace
            # (the has_variable-before-variable pattern of the decode
            # cache) so the init batch never pollutes the statistics
            live = self.has_variable(STATS_COLLECTION, "amax")
            amax = self.variable(STATS_COLLECTION, "amax",
                                 lambda: jnp.zeros((), jnp.float32))
            if live:
                amax.value = jnp.maximum(
                    amax.value,
                    jnp.max(jnp.abs(x)).astype(jnp.float32))
            return self._plain(x, kernel, bias, axis, features)
        if cfg.mode == "quant":
            x_scale = cfg.scale_for(site)
            if x_scale is not None:
                return self._quantized(x, kernel, bias, axis, features,
                                       x_scale, cfg)
        return self._plain(x, kernel, bias, axis, features)

    def _site_name(self) -> str:
        try:
            path = self.path
        except Exception:                       # pragma: no cover - old flax
            path = self.scope.path if self.scope is not None else ()
        return "/".join(str(p) for p in path)

    def _plain(self, x, kernel, bias, axis, features):
        """The exact flax DenseGeneral computation (promote_dtype +
        the same dot_general dimension numbers + the same bias
        broadcast) — the bitwise O2 fallback."""
        x, kernel, bias = _flax_dtypes.promote_dtype(
            x, kernel, bias, dtype=self.dtype)
        contract = tuple(range(len(axis)))
        out = jax.lax.dot_general(x, kernel, ((axis, contract), ((), ())))
        if bias is not None:
            out = out + jnp.reshape(
                bias, (1,) * (out.ndim - len(features)) + features)
        return out

    def _quantized(self, x, kernel, bias, axis, features, x_scale, cfg):
        """Flatten to 2-D, run the int8 kernel, restore dims; bias adds
        in the compute dtype like the plain path."""
        x, kernel, bias = _flax_dtypes.promote_dtype(
            x, kernel, bias, dtype=self.dtype)
        # contracting dims must be trailing for the 2-D flatten; the
        # model family only uses axis=-1 and axis=(-2, -1), both
        # already trailing.
        if axis != tuple(range(x.ndim - len(axis), x.ndim)):
            return self._plain(x, kernel, bias, axis, features)
        n_in = 1
        for a in axis:
            n_in *= x.shape[a]
        n_out = 1
        for s in features:
            n_out *= s
        lead = x.shape[:x.ndim - len(axis)]
        x2d = x.reshape(-1, n_in)
        k2d = kernel.reshape(n_in, n_out)
        out = K.quantized_matmul(x2d, k2d, x_scale=x_scale,
                                 impl=cfg.impl, interpret=cfg.interpret)
        out = out.reshape(*lead, *features)
        if bias is not None:
            out = out + jnp.reshape(
                bias, (1,) * (out.ndim - len(features)) + features)
        return out
