"""apex_tpu.quant — int8/fp8-style low-precision engine (ISSUE 13).

The layer below bf16: calibrated symmetric-absmax int8 quantization for
the matmuls that dominate the step, wired through four existing layers —

* :mod:`.kernels` — Pallas quantize → int8×int8→int32 matmul →
  dequantize-fused epilogue, custom VJP with a bf16 straight-through
  backward (the ``fused_bn_act``/xentropy kernel pattern: jnp reference
  as CPU fallback + oracle, ``interpret=True`` for CPU tests);
* :mod:`.calibrate` — absmax/percentile observation through the
  telemetry MetricsRegistry, delayed-amax-history freeze, checkpoint
  round-trip of the frozen scales;
* :mod:`.layers` — :class:`~apex_tpu.quant.layers.QuantDenseGeneral`,
  the parameter-compatible dense stand-in the model families'
  ``quant=`` hook selects (amp opt level **O4** = O2 semantics +
  these sites quantized);
* the serving engine's int8 KV cache lives with its substrate in
  :mod:`apex_tpu.serving.kv_cache` (``cache_dtype=jnp.int8``).

Recipe (docs/quant.md walks it end to end)::

    from apex_tpu import quant

    cal = quant.Calibrator()
    obs = model_cls(..., quant=quant.QuantConfig.observe())
    for batch in observation_batches:
        _, stats = obs.apply({"params": params}, batch,
                             mutable=["quant_stats"])
        cal.harvest(jax.device_get(stats["quant_stats"]))
    calibration = cal.freeze()                    # delayed amax history

    q_model = model_cls(..., quant=quant.QuantConfig.frozen(calibration))
    init_fn, step_fn = training.make_train_step(loss_fn, tx,
                                                opt_level="O4")
"""

from .calibrate import Calibration, Calibrator      # noqa: F401
from .kernels import (amax_to_scale, channel_scale, dequantize,  # noqa: F401
                      quantize, quantized_matmul, quantized_matmul_ref,
                      saturation_count)
from .layers import QuantConfig, QuantDenseGeneral  # noqa: F401

__all__ = ["Calibration", "Calibrator", "QuantConfig",
           "QuantDenseGeneral", "amax_to_scale", "channel_scale",
           "dequantize", "quantize", "quantized_matmul",
           "quantized_matmul_ref", "saturation_count"]
