"""Quantized matmul kernels: Pallas quantize → int8×int8→int32 →
dequantize-fused epilogue (ISSUE 13 tentpole, layer 1).

The amp pillar's bf16 matmuls stream 2 bytes/element through the MXU;
int8 halves that again and the MXU's int8 path doubles the MAC rate —
the "next 2x after bf16" the ROADMAP names.  The numerics recipe is
LLM.int8()-style symmetric absmax scaling (Dettmers et al.):

* **activations** quantize per-tensor against a FROZEN calibration
  scale (``apex_tpu.quant.calibrate`` harvests absmax over an
  observation phase; recomputing ``abs().max()`` per step is the
  anti-pattern jaxlint J014 flags);
* **weights** quantize per-channel (one scale per output column) from
  their CURRENT values — weights are known exactly at trace time, so
  per-step channel scales cost one cheap reduction and track training;
* the kernel quantizes the activation block in VMEM, runs the
  int8×int8→int32 dot on the MXU, and applies the dequantize epilogue
  (``acc * x_scale * w_scale[n]``) before the store — ONE pass over the
  activation bytes, no materialized int8 copy in HBM;
* the **backward stays in bf16** via a custom VJP (the straight-through
  estimator): ``dx = g @ w.T``, ``dw = x.T @ g`` on the saved
  full-precision operands — the same pattern as
  ``normalization/fused_bn_act.py`` and contrib xentropy, including the
  jnp reference that doubles as CPU fallback + test oracle and
  ``interpret=True`` running the REAL kernel in CPU tests.

Scale convention: ``dequant(q) = q * scale`` with ``scale = amax / 127``
(symmetric, no zero point).  A zero-amax channel (an all-zero weight
column) gets scale 1.0 so it quantizes to — and dequantizes from —
exact zeros instead of dividing by zero.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..normalization.fused_layer_norm import _use_pallas
from ..pallas_compat import align_vma as _align_vma
from ..pallas_compat import sds_with_vma as _sds
from ..tune.dispatch import kernel_config as _tuned_config
from ..tune.space import pow2_bucket as _pow2

__all__ = ["amax_to_scale", "quantize", "dequantize", "channel_scale",
           "quantized_matmul", "quantized_matmul_ref", "saturation_count",
           "QMAX"]

#: config-cache version of this kernel's blocking scheme (ISSUE 14).
TUNE_VERSION = 1

#: symmetric int8 range: quantized values live in [-QMAX, QMAX].
QMAX = 127.0


def amax_to_scale(amax):
    """``scale = amax / 127`` with the zero-amax guard (scale 1.0 for
    all-zero tensors/channels, so they round-trip as exact zeros)."""
    amax = jnp.asarray(amax, jnp.float32)
    return jnp.where(amax > 0, amax / QMAX, jnp.float32(1.0))


def channel_scale(w):
    """Per-output-channel scales ``[N]`` for a ``[K, N]`` weight matrix:
    absmax over each column, through :func:`amax_to_scale`."""
    return amax_to_scale(jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0))


def quantize(x, scale):
    """Symmetric int8 quantization: ``clip(round(x / scale), ±127)``.
    ``scale`` must broadcast against ``x`` (scalar per-tensor, or a
    per-channel vector pre-shaped by the caller).  Round-to-nearest-even
    (``jnp.round``) in fp32 — the ONE rounding definition the Pallas
    kernel, the jnp reference, and the KV-cache path all share."""
    q = jnp.round(x.astype(jnp.float32) * (1.0 / scale))
    return jnp.clip(q, -QMAX, QMAX).astype(jnp.int8)


def dequantize(q, scale, dtype=jnp.float32):
    """``q * scale`` back to ``dtype``."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def saturation_count(x, x_scale):
    """Elements of ``x`` whose magnitude exceeds the calibrated range
    ``127 * x_scale`` — they clip under :func:`quantize`.  A device-side
    int32 scalar; feed the fetched value to
    :meth:`apex_tpu.quant.calibrate.Calibration.note_saturation` so the
    ``quant_scale_saturation`` watchdog rule sees it."""
    limit = QMAX * jnp.asarray(x_scale, jnp.float32)
    return jnp.sum(jnp.abs(x.astype(jnp.float32)) > limit).astype(jnp.int32)


# -- reference math (jnp fallback + oracle) -----------------------------------

def _matmul_ref(x2d, qw, x_scale, w_scale, out_dtype):
    """The jnp reference: same quantize / int8-dot / dequant ops as the
    kernel, so interpret-mode parity is exact."""
    qx = quantize(x2d, x_scale)
    acc = jax.lax.dot_general(qx, qw, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (x_scale * w_scale)[None, :]
    return out.astype(out_dtype)


def quantized_matmul_ref(x, w, *, x_scale, w_scale=None):
    """Public jnp reference of :func:`quantized_matmul` (the test
    oracle): quantize both operands, int8×int8→int32, dequantize."""
    if w_scale is None:
        w_scale = channel_scale(w)
    x_scale = jnp.asarray(x_scale, jnp.float32)
    w_scale = jnp.asarray(w_scale, jnp.float32)
    qw = quantize(w, w_scale[None, :])
    lead = x.shape[:-1]
    out = _matmul_ref(x.reshape(-1, x.shape[-1]), qw, x_scale, w_scale,
                      x.dtype)
    return out.reshape(*lead, w.shape[-1])


# -- pallas kernel ------------------------------------------------------------
#
# Grid over (M blocks, N blocks), full K per block: the quantize of the
# x block, the int8 dot, and the dequant epilogue all happen in VMEM in
# one grid step.  Projection Ks in the model family (<= a few thousand)
# fit comfortably; _kernel_fits gates the rest back to the jnp path.

_BLOCK_M = 256
_BLOCK_N = 256
_QMM_VMEM_BUDGET = 8 * 1024 * 1024


def _pick_block(total: int, block: int, unit: int) -> int:
    b = min(block, max(unit, (total + unit - 1) // unit * unit))
    return min(b, total) if total >= unit else total


def _kernel_fits(bm: int, bn: int, k: int, x_itemsize: int) -> bool:
    # x block + qx int8 + w int8 block + f32 acc/out (+ slack already in
    # the budget)
    need = bm * k * (x_itemsize + 1) + k * bn + 2 * bm * bn * 4
    return need <= _QMM_VMEM_BUDGET


def _qmm_kernel(x_ref, qw_ref, xs_ref, ws_ref, out_ref):
    # quantize the activation block in VMEM (fp32 math, RTNE — identical
    # ops to quantize())
    xs = xs_ref[0, 0]                                   # scalar x_scale
    q = jnp.round(x_ref[:].astype(jnp.float32) * (1.0 / xs))
    qx = jnp.clip(q, -QMAX, QMAX).astype(jnp.int8)
    acc = jax.lax.dot_general(qx, qw_ref[:], (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    # dequantize-fused epilogue: per-channel scale broadcast over rows
    out = acc.astype(jnp.float32) * (xs * ws_ref[:])    # [1, bn] bcast
    out_ref[:] = out.astype(out_ref.dtype)


def tune_bucket(m: int, k: int, n: int, x_itemsize: int) -> str:
    """Config-cache shape bucket: K/N exact (they set the VMEM math),
    rows rounded to a power of two."""
    return f"m{_pow2(m)}_k{k}_n{n}_i{x_itemsize}"


def _pallas_qmm(x2d, qw, x_scale, w_scale, out_dtype, interpret,
                block_m=None, block_n=None):
    m, k = x2d.shape
    n = qw.shape[1]
    bm = _pick_block(m, block_m or _BLOCK_M, 8)
    bn = _pick_block(n, block_n or _BLOCK_N, 128)
    grid = (pl.cdiv(m, bm), pl.cdiv(n, bn))
    xs2d = jnp.reshape(x_scale.astype(jnp.float32), (1, 1))
    ws2d = jnp.reshape(w_scale.astype(jnp.float32), (1, n))
    operands = _align_vma(x2d, qw, xs2d, ws2d)
    return pl.pallas_call(
        _qmm_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
                  pl.BlockSpec((k, bn), lambda i, j: (0, j)),
                  pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=_sds((m, n), out_dtype, *operands),
        interpret=interpret,
    )(*operands)


# -- dispatch -----------------------------------------------------------------

# Below this the custom-call boundary costs more than the int8 saving
# (the fused_layer_norm crossover lesson); benchmark-shape projections
# (batch*seq x hidden) sit far above.
_JNP_MAX_ELEMENTS = 1 * 1024 * 1024


def _dispatch_pallas(m: int, k: int, n: int, impl: Optional[str],
                     x_itemsize: int) -> bool:
    if impl not in (None, "pallas", "jnp"):
        raise ValueError(f"impl must be None, 'pallas', or 'jnp'; "
                         f"got {impl!r}")
    bm = _pick_block(m, _BLOCK_M, 8)
    bn = _pick_block(n, _BLOCK_N, 128)
    if not _use_pallas() or not _kernel_fits(bm, bn, k, x_itemsize):
        return False
    if impl is not None:
        return impl == "pallas"
    return m * k >= _JNP_MAX_ELEMENTS


# -- public op with custom VJP ------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _qmm(x2d, w2d, x_scale, w_scale, use_pallas, interpret, block_m,
         block_n):
    qw = quantize(w2d, w_scale[None, :])
    if use_pallas:
        return _pallas_qmm(x2d, qw, x_scale, w_scale, x2d.dtype, interpret,
                           block_m, block_n)
    return _matmul_ref(x2d, qw, x_scale, w_scale, x2d.dtype)


def _qmm_fwd(x2d, w2d, x_scale, w_scale, use_pallas, interpret, block_m,
             block_n):
    out = _qmm(x2d, w2d, x_scale, w_scale, use_pallas, interpret, block_m,
               block_n)
    return out, (x2d, w2d, x_scale, w_scale)


def _qmm_bwd(use_pallas, interpret, block_m, block_n, res, g):
    # Straight-through backward in the operands' own (bf16) precision:
    # the quantization is treated as identity, so gradients see the
    # full-precision matmul — the LLM.int8()/FP8-training recipe.  The
    # int8 path never appears in the backward program.
    x2d, w2d, x_scale, w_scale = res
    gx = g.astype(x2d.dtype)
    dx = jnp.dot(gx, w2d.T.astype(x2d.dtype)).astype(x2d.dtype)
    dw = jnp.dot(x2d.T.astype(w2d.dtype),
                 g.astype(w2d.dtype)).astype(w2d.dtype)
    return dx, dw, jnp.zeros_like(x_scale), jnp.zeros_like(w_scale)


_qmm.defvjp(_qmm_fwd, _qmm_bwd)


def quantized_matmul(x, w, *, x_scale, w_scale=None,
                     impl: Optional[str] = None,
                     interpret: bool = False,
                     block_m: Optional[int] = None,
                     block_n: Optional[int] = None):
    """int8 quantized matmul ``x @ w`` with a dequantize-fused epilogue.

    ``x``: ``[..., K]`` activations (bf16/fp32); ``w``: ``[K, N]``
    weights; ``x_scale``: the FROZEN per-tensor activation scale
    (``amax / 127`` from :mod:`apex_tpu.quant.calibrate` — do not pass a
    freshly computed ``abs(x).max()`` from the step function, that is
    recalibration-per-step and jaxlint J014 territory); ``w_scale``:
    per-channel ``[N]`` weight scales, computed from ``w`` when omitted.
    Returns ``x.dtype``, shaped ``[..., N]``.

    ``impl``: ``None`` picks pallas-vs-jnp by size (pallas only on TPU);
    ``"pallas"``/``"jnp"`` force a path.  ``interpret=True`` runs the
    Pallas kernel in interpreter mode (CPU tier-parity tests);
    ``impl="jnp"`` wins over it — that combination is the explicit
    "reference on this exact call" A/B probe.

    ``block_m``/``block_n``: explicit kernel tile overrides; left
    ``None`` the per-device config cache (:mod:`apex_tpu.tune`) is
    consulted with the hard-coded 256x256 defaults as the fallback
    (a tuned tile that fails the VMEM fit gate is ignored).

    Differentiable in ``x`` and ``w`` (straight-through, bf16 backward);
    the scales receive zero cotangents.
    """
    k = x.shape[-1]
    if w.ndim != 2 or w.shape[0] != k:
        raise ValueError(f"w must be [K={k}, N], got {w.shape}")
    if w_scale is None:
        w_scale = channel_scale(w)
    x_scale = jnp.reshape(jnp.asarray(x_scale, jnp.float32), ())
    w_scale = jnp.reshape(jnp.asarray(w_scale, jnp.float32), (w.shape[1],))
    lead = x.shape[:-1]
    x2d = x.reshape(-1, k)
    # _dispatch_pallas also validates impl; interpret forces the kernel
    # (interpreter mode) only when impl doesn't explicitly ask for the
    # jnp reference — impl="jnp" + interpret=True is the A/B probe
    # "reference on this exact call" and must stay honored.
    isz = jnp.dtype(x2d.dtype).itemsize
    use_pallas = _dispatch_pallas(x2d.shape[0], k, w.shape[1], impl, isz)
    if interpret and impl != "jnp":
        use_pallas = True
    if use_pallas and block_m is None and block_n is None:
        cfg = _tuned_config("quantized_matmul", TUNE_VERSION,
                            tune_bucket(x2d.shape[0], k, w.shape[1], isz),
                            params=("block_m", "block_n"))
        if cfg:
            tbm = _pick_block(x2d.shape[0], cfg.get("block_m", _BLOCK_M), 8)
            tbn = _pick_block(w.shape[1], cfg.get("block_n", _BLOCK_N), 128)
            if _kernel_fits(tbm, tbn, k, isz):
                block_m = cfg.get("block_m")
                block_n = cfg.get("block_n")
    out = _qmm(x2d, w, x_scale, w_scale, use_pallas, bool(interpret),
               block_m, block_n)
    return out.reshape(*lead, w.shape[1])
