"""Flat-bucket parameter engine: O(buckets) hot paths over whole models.

The reference's ``multi_tensor_apply`` (``csrc/multi_tensor_apply.cuh``)
exists to make whole-model elementwise work cost O(1) kernel launches
instead of O(tensors).  The pytree port preserved the *dispatch* half of
that capability (one jitted program), but the program itself — and the
jit call boundary — still scaled with the leaf count: one HLO reduction
per leaf in ``tree_finite``, one update subgraph per leaf in
``adam_update``, and ~22 us of per-argument marshalling for every
master/momentum buffer on every call (measured: 16.9 ms wall vs 4.8 ms
device for the ~790-leaf BERT FusedAdam step).

:class:`BucketStore` collapses that to O(buckets): the float leaves of a
pytree are packed into a few large 1-D buffers, one per ``(dtype,
weight-decay-flag)`` key — the same trick apex's DDP Reducer and
PyTorch's ``_flatten_dense_tensors`` use for bucketed allreduce.  The
index map (offset/size/shape per leaf) is built once from the tree's
static structure, so ``pack``/``unpack``/``view`` are pure jit-safe
functions: an optimizer can keep its state (and fp32 masters) *as
buckets* across steps, an overflow check is one ``isfinite``+reduce per
bucket, a gradient all-reduce is one ``psum`` per bucket, and LAMB's
per-tensor trust ratios come from one segment-reduction per bucket over
the index map.

Design points:

* **Exact dtype preservation.**  Buckets are keyed by dtype, so a
  ``pack``/``unpack`` round trip is the identity (bitwise) — no silent
  upcasting of bf16 leaves into an fp32 pool.
* **Donation friendliness.**  :class:`Packed` is a plain pytree of a
  few large arrays; donating it at a jit boundary aliases whole buckets
  in place, exactly like the reference's in-place multi-tensor kernels.
* **Non-float passthrough.**  Integer/bool/other leaves travel in
  ``Packed.rest`` untouched, so any params-shaped tree packs.
* **Static index map.**  Only ``.shape``/``.dtype`` are read at build
  time — a :class:`BucketStore` can be constructed from concrete
  arrays, tracers, or ``jax.ShapeDtypeStruct`` templates alike.

A ``BucketStore`` instance is hashable by identity, so it can ride
through ``jax.jit`` as a static argument; the jitted ``pack_jit``/
``unpack_jit`` conveniences cache one compiled program per store.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["BucketStore", "Packed", "cached_store", "padded_shard_len"]


def padded_shard_len(size: int, num_shards: int) -> int:
    """Length of a flat bucket padded to divide evenly over
    ``num_shards`` — THE padding rule shared by ``zero1`` state init,
    the checkpoint manifest's bucket layout, and elastic
    reshard-on-read (``apex_tpu.checkpoint``).  A single definition:
    a drift between writer and reader would corrupt resumed moments."""
    return -(-int(size) // int(num_shards)) * int(num_shards)


def cached_store(cell: dict, template, **kwargs) -> "BucketStore":
    """Memoized :class:`BucketStore` construction: one store per
    (tree structure, shapes, dtypes) signature, cached in the caller's
    ``cell`` dict.  Lazy-store callers (``training.adam(bucketed=True)``,
    ``zero1(bucketed=True)``) share this so a reused optimizer object —
    two models, or a resized one — never packs against a stale index
    map.  ``kwargs`` (e.g. ``decay_mask``) participate in construction
    but not the key: pass a fresh ``cell`` per configuration."""
    key = (jax.tree_util.tree_structure(template),
           tuple((tuple(jnp.shape(l)), str(getattr(l, "dtype", "-")))
                 for l in jax.tree_util.tree_leaves(template)))
    store = cell.get(key)
    if store is None:
        store = cell[key] = BucketStore(template, **kwargs)
    return store


class Packed(NamedTuple):
    """A pytree packed by a :class:`BucketStore`.

    ``data`` holds one 1-D array per bucket (the store's bucket order);
    ``rest`` holds the non-float leaves in their flattened-tree order.
    A ``Packed`` is itself a pytree, so it jits, donates, scans and
    ``device_get``/``tree_map``-s like any other carry.
    """
    data: Tuple[Any, ...]
    rest: Tuple[Any, ...]


class _Bucket(NamedTuple):
    """Static index map of one bucket (never traced)."""
    dtype: Any                       # numpy dtype of the bucket buffer
    decay: bool                      # weight-decay flag for this bucket
    leaf_ids: Tuple[int, ...]        # indices into the float-leaf list
    offsets: Tuple[int, ...]         # element offset of each leaf segment
    sizes: Tuple[int, ...]           # element count of each leaf segment
    shapes: Tuple[Tuple[int, ...], ...]
    size: int                        # total elements in the bucket


def _leaf_dtype(x):
    dt = getattr(x, "dtype", None)
    return None if dt is None else jnp.dtype(dt)


def _is_float_leaf(x) -> bool:
    dt = _leaf_dtype(x)
    return dt is not None and jnp.issubdtype(dt, jnp.floating)


class BucketStore:
    """Static index map packing a pytree's float leaves into per-(dtype,
    decay) 1-D buckets.

    ``decay_mask`` (optional) is a pytree of Python bools matching
    ``template``: leaves marked ``False`` land in separate no-decay
    buckets, so a bucketed optimizer applies weight decay as a
    per-bucket compile-time constant instead of a per-leaf branch.
    Without a mask every bucket carries ``decay=True`` (decay applies
    wherever the optimizer's ``weight_decay`` says, matching the
    leafwise behavior).

    ``max_bucket_elems`` (optional) caps each bucket's element count,
    splitting a ``(dtype, decay)`` group into several buckets in leaf
    order — the apex-DDP ``message_size`` analog.  One giant bucket is
    a *barrier*: its collective cannot start until every grad in it is
    final, i.e. until the whole backward is done.  Chunked buckets give
    :func:`apex_tpu.parallel.reduce_gradients` per-chunk psums whose
    data dependencies close as backward progresses, so XLA's
    latency-hiding scheduler overlaps wire time with the remaining
    backward compute (ISSUE 7).  A leaf larger than the cap gets its
    own bucket (leaves are never split).
    """

    def __init__(self, template, *, decay_mask=None,
                 max_bucket_elems: Optional[int] = None):
        leaves, treedef = jax.tree_util.tree_flatten(template)
        self.treedef = treedef
        self.n_leaves = len(leaves)
        if decay_mask is None:
            mask = [True] * len(leaves)
        else:
            mask = jax.tree_util.tree_leaves(decay_mask)
            if len(mask) != len(leaves):
                raise ValueError(
                    f"decay_mask has {len(mask)} leaves, template has "
                    f"{len(leaves)}")
            mask = [bool(m) for m in mask]

        if max_bucket_elems is not None and max_bucket_elems < 1:
            raise ValueError(
                f"max_bucket_elems must be >= 1, got {max_bucket_elems}")
        self.max_bucket_elems = max_bucket_elems

        # float_slot[i] = (bucket_id, segment index within bucket) for
        # flat leaf i; None marks a passthrough (non-float) leaf.
        self._slots: list = [None] * len(leaves)
        order: dict = {}                        # key -> bucket build dict
        chunk_of: dict = {}                     # (dtype, decay) -> chunk idx
        self._rest_ids: list = []
        for i, leaf in enumerate(leaves):
            if not _is_float_leaf(leaf):
                self._slots[i] = ("rest", len(self._rest_ids))
                self._rest_ids.append(i)
                continue
            shape = tuple(int(s) for s in jnp.shape(leaf))
            size = int(np.prod(shape, dtype=np.int64)) if shape else 1
            group = (jnp.dtype(leaf.dtype), mask[i])
            key = (group, chunk_of.setdefault(group, 0))
            b = order.get(key)
            if (b is not None and max_bucket_elems is not None
                    and b["total"] and b["total"] + size > max_bucket_elems):
                # start a fresh chunk; an oversized single leaf still
                # lands alone in its own bucket (never split).
                chunk_of[group] += 1
                key = (group, chunk_of[group])
                b = None
            if b is None:
                b = order.setdefault(key, dict(leaf_ids=[], offsets=[],
                                               sizes=[], shapes=[],
                                               total=0))
            b["leaf_ids"].append(i)
            b["offsets"].append(b["total"])
            b["sizes"].append(size)
            b["shapes"].append(shape)
            b["total"] += size
        self.buckets: Tuple[_Bucket, ...] = tuple(
            _Bucket(dtype=key[0][0], decay=key[0][1],
                    leaf_ids=tuple(b["leaf_ids"]),
                    offsets=tuple(b["offsets"]),
                    sizes=tuple(b["sizes"]),
                    shapes=tuple(b["shapes"]),
                    size=b["total"])
            for key, b in order.items())
        # final slot map: leaf index -> ("bucket", bucket_id, seg) or
        # ("rest", rest_pos)
        for bi, b in enumerate(self.buckets):
            for seg, leaf_id in enumerate(b.leaf_ids):
                self._slots[leaf_id] = ("bucket", bi, seg)
        self._jit_cache: dict = {}

    # -- introspection -------------------------------------------------------
    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    @property
    def decay_flags(self) -> Tuple[bool, ...]:
        return tuple(b.decay for b in self.buckets)

    @property
    def dtypes(self) -> Tuple[Any, ...]:
        return tuple(b.dtype for b in self.buckets)

    @property
    def sizes(self) -> Tuple[int, ...]:
        return tuple(b.size for b in self.buckets)

    def __repr__(self):
        segs = ", ".join(
            f"{b.dtype.name}{'[wd]' if b.decay else '[nowd]'}x"
            f"{len(b.leaf_ids)}={b.size}" for b in self.buckets)
        return (f"BucketStore({self.n_leaves} leaves -> "
                f"{self.n_buckets} bucket(s): {segs})")

    # -- pack / unpack / view ------------------------------------------------
    def _check_tree(self, tree):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if treedef != self.treedef:
            raise ValueError(
                f"tree structure does not match this BucketStore's "
                f"template:\n  got      {treedef}\n  expected "
                f"{self.treedef}")
        return leaves

    def pack(self, tree, *, dtype=None, cast: bool = False) -> Packed:
        """Pack ``tree`` (template-structured) into bucket buffers.

        ``dtype``: cast every bucket to this dtype (e.g. ``float32``
        when packing model-dtype grads into fp32 master-grad buckets).
        ``cast=True``: cast each segment to its *bucket's* dtype (e.g.
        repacking fp32 values into a bf16-keyed store).  With neither,
        leaf dtypes must match their bucket dtype exactly — a silent
        upcast is never performed.
        """
        leaves = self._check_tree(tree)
        data = []
        for b in self.buckets:
            out_dt = jnp.dtype(dtype) if dtype is not None else b.dtype
            segs = []
            for seg, leaf_id in enumerate(b.leaf_ids):
                leaf = leaves[leaf_id]
                ldt = _leaf_dtype(leaf)
                if dtype is None and not cast and ldt != b.dtype:
                    raise ValueError(
                        f"leaf {leaf_id} has dtype {ldt}, bucket expects "
                        f"{b.dtype}; pass dtype=... or cast=True to cast "
                        f"explicitly")
                if tuple(int(s) for s in jnp.shape(leaf)) != b.shapes[seg]:
                    raise ValueError(
                        f"leaf {leaf_id} has shape {jnp.shape(leaf)}, "
                        f"bucket segment expects {b.shapes[seg]} — build "
                        f"the BucketStore from a same-shaped template")
                segs.append(jnp.ravel(jnp.asarray(leaf, out_dt)))
            data.append(segs[0] if len(segs) == 1
                        else jnp.concatenate(segs))
        rest = tuple(leaves[i] for i in self._rest_ids)
        return Packed(data=tuple(data), rest=rest)

    def unpack(self, packed: Packed, *, cast: bool = False):
        """Rebuild the template-structured pytree from ``packed``.

        ``cast=True`` casts each bucket to its store dtype first (one op
        per bucket) — the bucket-level master->model copy.  Otherwise
        leaves come out in the bucket buffer's dtype (the exact packed
        dtype round-trips bitwise).
        """
        if len(packed.data) != self.n_buckets:
            raise ValueError(f"Packed has {len(packed.data)} buckets, "
                             f"store has {self.n_buckets}")
        if len(packed.rest) != len(self._rest_ids):
            raise ValueError(f"Packed has {len(packed.rest)} passthrough "
                             f"leaves, store has {len(self._rest_ids)}")
        leaves: list = [None] * self.n_leaves
        for b, buf in zip(self.buckets, packed.data):
            if cast:
                buf = jnp.asarray(buf, b.dtype)
            for off, size, shape, leaf_id in zip(b.offsets, b.sizes,
                                                 b.shapes, b.leaf_ids):
                leaves[leaf_id] = jax.lax.slice_in_dim(
                    buf, off, off + size).reshape(shape)
        for pos, leaf_id in enumerate(self._rest_ids):
            leaves[leaf_id] = packed.rest[pos]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def view(self, packed: Packed, leaf_index: int):
        """One leaf of ``packed`` (flattened-tree index), reshaped; a
        static slice, so it folds into the surrounding program."""
        slot = self._slots[leaf_index]
        if slot[0] == "rest":
            return packed.rest[slot[1]]
        _, bi, seg = slot
        b = self.buckets[bi]
        return jax.lax.slice_in_dim(
            packed.data[bi], b.offsets[seg],
            b.offsets[seg] + b.sizes[seg]).reshape(b.shapes[seg])

    def zeros(self, dtype=jnp.float32) -> Packed:
        """Zero buckets with this store's segmentation (optimizer moment
        init); ``rest`` is empty — moment trees have no passthrough."""
        return Packed(
            data=tuple(jnp.zeros((b.size,), dtype) for b in self.buckets),
            rest=())

    # -- segment reductions (per-tensor norms over the index map) ------------
    def segment_ids(self, bucket_index: int):
        """int32 [size] array mapping each bucket element to its segment
        (local leaf position).  Generated on device at trace time (an
        iota+repeat, fused by XLA) — never materialized host-side."""
        b = self.buckets[bucket_index]
        return jnp.repeat(jnp.arange(len(b.leaf_ids), dtype=jnp.int32),
                          jnp.asarray(b.sizes, jnp.int32),
                          total_repeat_length=b.size)

    def per_leaf_sq_sums(self, data: Sequence[Any]) -> Tuple[Any, ...]:
        """Per-leaf sum-of-squares, one fp32 ``[n_leaves_in_bucket]``
        array per bucket — ONE segment reduction per bucket instead of
        one reduction per leaf (LAMB trust ratios, NovoGrad norms)."""
        out = []
        for bi, buf in enumerate(data):
            b = self.buckets[bi]
            x = jnp.asarray(buf, jnp.float32)
            out.append(jax.ops.segment_sum(
                jnp.square(x), self.segment_ids(bi),
                num_segments=len(b.leaf_ids)))
        return tuple(out)

    def per_leaf_max_abs(self, data: Sequence[Any]) -> Tuple[Any, ...]:
        """Per-leaf max-|x| per bucket (NovoGrad's inf-norm mode)."""
        out = []
        for bi, buf in enumerate(data):
            b = self.buckets[bi]
            x = jnp.abs(jnp.asarray(buf, jnp.float32))
            out.append(jax.ops.segment_max(
                x, self.segment_ids(bi), num_segments=len(b.leaf_ids)))
        return tuple(out)

    def spread(self, bucket_index: int, per_leaf_vals):
        """Broadcast a ``[n_leaves_in_bucket]`` vector back to bucket
        elements (``take`` over the segment map) — turns per-tensor
        scalars (trust ratios, norm denominators) into elementwise
        multipliers in one gather."""
        return jnp.take(per_leaf_vals, self.segment_ids(bucket_index))

    def reverse_topological_order(self) -> Tuple[int, ...]:
        """Bucket indices in the order their gradients become *final*
        during backward (ISSUE 7 collective/compute overlap).

        Backward differentiates the forward in reverse: the grad of
        flat leaf ``i`` is finalized roughly at backward time
        ``n_leaves - i`` (flattened-tree order tracks forward use for
        the standard top-down module layout).  A bucket is ready for
        its psum once its *last*-finalizing grad — its minimum leaf id
        — is done, so buckets are issued by DESCENDING min leaf id:
        deepest-layer chunks first, each psum's data dependencies
        closing while earlier layers are still differentiating.
        :func:`apex_tpu.parallel.reduce_gradients` issues the
        per-bucket collectives in this order."""
        return tuple(sorted(
            range(len(self.buckets)),
            key=lambda bi: -min(self.buckets[bi].leaf_ids)))

    def shard_layout(self, num_shards: int) -> dict:
        """Checkpoint-manifest descriptor of this store's buckets for a
        zero1 run sharded ``num_shards`` ways: the per-bucket TRUE
        element counts plus the shard count the optimizer state is
        padded for (:func:`padded_shard_len`).  Recorded at save time so
        ``apex_tpu.checkpoint`` can re-slice the flat buckets when the
        resume world's shard count differs (elastic resize)."""
        return {"sizes": [int(s) for s in self.sizes],
                "num_shards": int(num_shards)}

    def leaf_order(self) -> Tuple[int, ...]:
        """Float-leaf indices in flattened-tree order — for reassembling
        per-leaf results (e.g. per-tensor norms) in the leafwise order
        the multi_tensor API documents."""
        return tuple(i for i, s in enumerate(self._slots)
                     if s[0] == "bucket")

    # -- cached jitted conveniences ------------------------------------------
    def pack_jit(self, tree, *, dtype=None, cast: bool = False) -> Packed:
        """``pack`` as ONE cached compiled program (for eager callers:
        packing a ~800-leaf tree op-by-op would cost ~800 dispatches)."""
        key = ("pack", None if dtype is None else jnp.dtype(dtype), cast)
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = jax.jit(lambda t: self.pack(t, dtype=dtype, cast=cast))
            self._jit_cache[key] = fn
        return fn(tree)

    def unpack_jit(self, packed: Packed, *, cast: bool = False):
        """``unpack`` as ONE cached compiled program."""
        key = ("unpack", cast)
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = jax.jit(lambda p: self.unpack(p, cast=cast))
            self._jit_cache[key] = fn
        return fn(packed)
