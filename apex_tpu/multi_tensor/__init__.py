"""Multi-tensor engine: whole-model fused elementwise ops with a device-side
overflow flag.

TPU-native re-design of the reference's ``amp_C`` multi-tensor-apply stack
(``csrc/multi_tensor_apply.cuh``, ``csrc/multi_tensor_*_kernel.cu`` and the
Python shim ``apex/multi_tensor_apply/multi_tensor_apply.py``).

On CUDA the problem is *launch overhead*: updating N parameter tensors costs N
kernel launches, so apex packs chunk pointers into one kernel argument struct.
On TPU under XLA the launch problem dissolves — a jitted function over a whole
parameter pytree compiles to one fused program.  What must be preserved is the
*capability*:

* operate on every tensor of a model in O(1) dispatches,
* carry a **device-side** overflow flag (no host sync on the hot path),
* honor mixed in/out dtypes (bf16 grads → fp32 masters etc.).

Each op here is a pure function over pytrees, safe under jit/grad/shard_map,
plus a thin ``multi_tensor_applier`` shim for reference API parity
(``apex/multi_tensor_apply/multi_tensor_apply.py:3-30``).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from .buckets import BucketStore, Packed

__all__ = [
    "multi_tensor_scale", "multi_tensor_axpby", "multi_tensor_l2norm",
    "multi_tensor_maxnorm", "multi_tensor_lamb_stage1",
    "multi_tensor_lamb_stage2", "tree_finite", "MultiTensorApply",
    "multi_tensor_applier", "flatten", "unflatten",
    "BucketStore", "Packed",
]


def _is_float_leaf(x) -> bool:
    # Inspect ``x.dtype`` directly — no jnp.asarray round-trip just to
    # read metadata; non-array leaves (no dtype) fall through unchanged.
    dt = getattr(x, "dtype", None)
    return dt is not None and jnp.issubdtype(dt, jnp.floating)


def _float_leaves(tree):
    return [x for x in jax.tree_util.tree_leaves(tree) if _is_float_leaf(x)]


def _as_packed(tree, store: BucketStore):
    """(packed, was_packed) — route a pytree or an already-Packed value
    through a store."""
    if isinstance(tree, Packed):
        return tree, True
    if store is None:
        raise ValueError(
            "mixing a Packed operand with a pytree operand requires the "
            "store= that packed it (the index map to pack the other side)")
    return store.pack(tree), False


def tree_finite(tree, store: Optional[BucketStore] = None) -> jnp.ndarray:
    """Device-side bool: every float leaf of ``tree`` is finite.

    With ``store`` (or an already-:class:`Packed` ``tree``) the check is
    ONE ``isfinite``+reduce per *bucket* instead of per leaf — the
    O(leaves)->O(buckets) overflow check.
    """
    if store is not None or isinstance(tree, Packed):
        packed = tree if isinstance(tree, Packed) else store.pack(tree)
        # BucketStore puts EVERY float leaf in a bucket; .rest is
        # non-float by construction, so the buckets are the whole check.
        flags = [jnp.all(jnp.isfinite(b)) for b in packed.data]
        if not flags:
            return jnp.asarray(True)
        return jnp.all(jnp.stack(flags))
    leaves = _float_leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    return jnp.all(jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]))


def multi_tensor_scale(tree, scale, out_dtype=None,
                       store: Optional[BucketStore] = None
                       ) -> Tuple[Any, jnp.ndarray]:
    """``out = in * scale`` over every float leaf; returns (out, overflow).

    Equivalent of ``amp_C.multi_tensor_scale`` (``csrc/
    multi_tensor_scale_kernel.cu:18-77``): the scaled value is checked for
    finiteness and a device-side flag raised on inf/NaN.  Used for loss
    unscaling and master<->model copies (scale=1.0).

    With ``store`` (or a :class:`Packed` input, which also returns
    Packed) the scale and the overflow check run per bucket.
    """
    if store is not None or isinstance(tree, Packed):
        packed, was_packed = _as_packed(tree, store)
        data, flags = [], []
        for x in packed.data:
            y = jnp.asarray(x, jnp.float32) * scale
            y = y.astype(out_dtype or x.dtype)
            data.append(y)
            flags.append(jnp.all(jnp.isfinite(y)))
        out = Packed(data=tuple(data), rest=packed.rest)
        overflow = (jnp.logical_not(jnp.all(jnp.stack(flags)))
                    if flags else jnp.asarray(False))
        if not was_packed:
            out = store.unpack(out)
        return out, overflow

    def one(x):
        if not _is_float_leaf(x):
            return x
        y = jnp.asarray(x, jnp.float32) * scale
        return y.astype(out_dtype or x.dtype)
    out = jax.tree_util.tree_map(one, tree)
    return out, jnp.logical_not(tree_finite(out))


def multi_tensor_axpby(x_tree, y_tree, a, b, out_dtype=None,
                       store: Optional[BucketStore] = None
                       ) -> Tuple[Any, jnp.ndarray]:
    """``out = a*x + b*y`` leafwise, overflow-checked.

    Equivalent of ``amp_C.multi_tensor_axpby``
    (``csrc/multi_tensor_axpby_kernel.cu:16-90``) — the gradient-accumulation
    unscale (new_grad/scale + stashed_grad).  ``store`` routes the sweep
    and the overflow check through buckets.
    """
    if store is not None or isinstance(x_tree, Packed):
        px, was_packed = _as_packed(x_tree, store)
        py, _ = _as_packed(y_tree, store)
        data, flags = [], []
        for x, y in zip(px.data, py.data):
            o = a * jnp.asarray(x, jnp.float32) + b * jnp.asarray(y, jnp.float32)
            o = o.astype(out_dtype or x.dtype)
            data.append(o)
            flags.append(jnp.all(jnp.isfinite(o)))
        out = Packed(data=tuple(data), rest=px.rest)
        overflow = (jnp.logical_not(jnp.all(jnp.stack(flags)))
                    if flags else jnp.asarray(False))
        if not was_packed:
            out = store.unpack(out)
        return out, overflow

    def one(x, y):
        if not _is_float_leaf(x):
            return x
        out = a * jnp.asarray(x, jnp.float32) + b * jnp.asarray(y, jnp.float32)
        return out.astype(out_dtype or x.dtype)
    out = jax.tree_util.tree_map(one, x_tree, y_tree)
    return out, jnp.logical_not(tree_finite(out))


def multi_tensor_l2norm(tree, per_tensor: bool = False,
                        store: Optional[BucketStore] = None):
    """Global L2 norm over all float leaves; optionally per-tensor norms too.

    Equivalent of ``amp_C.multi_tensor_l2norm``
    (``csrc/multi_tensor_l2norm_kernel.cu:16-77, 237``).  Accumulation is
    fp32 regardless of leaf dtype, like the reference's float accumulators.

    With ``store`` the global norm is one reduction per bucket, and the
    per-tensor norms come from one segment reduction per bucket over the
    index map (returned in flattened-leaf order, like the leafwise path).

    Returns ``global_norm`` or ``(global_norm, per_tensor_norms_list)``.
    """
    if store is not None or isinstance(tree, Packed):
        if per_tensor and store is None:
            raise ValueError("per_tensor norms over a Packed input need "
                             "the store (the per-leaf index map)")
        packed = tree if isinstance(tree, Packed) else store.pack(tree)
        if not packed.data:
            zero = jnp.float32(0)
            return (zero, []) if per_tensor else zero
        if not per_tensor:
            sq = [jnp.sum(jnp.square(jnp.asarray(x, jnp.float32)))
                  for x in packed.data]
            return jnp.sqrt(jnp.sum(jnp.stack(sq)))
        seg_sums = store.per_leaf_sq_sums(packed.data)
        total = jnp.sqrt(jnp.sum(jnp.stack([jnp.sum(s) for s in seg_sums])))
        by_leaf = {}
        for b, sums in zip(store.buckets, seg_sums):
            for pos, leaf_id in enumerate(b.leaf_ids):
                by_leaf[leaf_id] = jnp.sqrt(sums[pos])
        return total, [by_leaf[i] for i in store.leaf_order()]
    leaves = _float_leaves(tree)
    if not leaves:
        zero = jnp.float32(0)
        return (zero, []) if per_tensor else zero
    sq = [jnp.sum(jnp.square(jnp.asarray(x, jnp.float32))) for x in leaves]
    total = jnp.sqrt(jnp.sum(jnp.stack(sq)))
    if per_tensor:
        return total, [jnp.sqrt(s) for s in sq]
    return total


def multi_tensor_maxnorm(tree, per_tensor: bool = False):
    """Global max-abs (infinity) norm, optionally per-tensor.

    Equivalent of ``MaxNormFunctor``
    (``csrc/multi_tensor_l2norm_kernel.cu:79-140``), used by NovoGrad's
    ``norm_type=inf`` mode.
    """
    leaves = _float_leaves(tree)
    if not leaves:
        zero = jnp.float32(0)
        return (zero, []) if per_tensor else zero
    m = [jnp.max(jnp.abs(jnp.asarray(x, jnp.float32))) for x in leaves]
    total = jnp.max(jnp.stack(m))
    if per_tensor:
        return total, m
    return total


# -- legacy two-stage LAMB entry points ---------------------------------------

def multi_tensor_lamb_stage1(grads, params, exp_avg, exp_avg_sq,
                             per_tensor_decay, *, beta1, beta2,
                             beta1_correction, beta2_correction,
                             epsilon, clipped_global_grad_norm):
    """Stage 1 of the legacy two-stage LAMB decomposition.

    Equivalent of ``amp_C.multi_tensor_lamb_stage1_cuda``
    (``csrc/multi_tensor_lamb_stage_1.cu``): per leaf,
    ``scaled_g = g / clipped_global_grad_norm``, Adam moment EMAs, and
    ``update = m_hat / (sqrt(v_hat) + eps) + decay * p`` with an explicit
    per-tensor decay array (flattened-leaf order).

    Returns ``(updates, new_exp_avg, new_exp_avg_sq)`` as pytrees.
    """
    leaves_g, treedef = jax.tree_util.tree_flatten(grads)
    leaves_p = jax.tree_util.tree_leaves(params)
    leaves_m = jax.tree_util.tree_leaves(exp_avg)
    leaves_v = jax.tree_util.tree_leaves(exp_avg_sq)
    if len(per_tensor_decay) != len(leaves_g):
        raise ValueError("per_tensor_decay must have one entry per leaf "
                         f"({len(per_tensor_decay)} != {len(leaves_g)})")
    upd, new_m, new_v = [], [], []
    for g, p, m, v, decay in zip(leaves_g, leaves_p, leaves_m, leaves_v,
                                 per_tensor_decay):
        sg = jnp.asarray(g, jnp.float32) / clipped_global_grad_norm
        m_n = beta1 * jnp.asarray(m, jnp.float32) + (1.0 - beta1) * sg
        v_n = (beta2 * jnp.asarray(v, jnp.float32)
               + (1.0 - beta2) * jnp.square(sg))
        m_hat = m_n / beta1_correction
        v_hat = v_n / beta2_correction
        u = m_hat / (jnp.sqrt(v_hat) + epsilon) \
            + decay * jnp.asarray(p, jnp.float32)
        upd.append(u)
        new_m.append(m_n)
        new_v.append(v_n)
    return (treedef.unflatten(upd), treedef.unflatten(new_m),
            treedef.unflatten(new_v))


def multi_tensor_lamb_stage2(params, updates, per_tensor_param_norm,
                             per_tensor_update_norm, learning_rate):
    """Stage 2 of the legacy two-stage LAMB decomposition.

    Equivalent of ``amp_C.multi_tensor_lamb_stage2_cuda``
    (``csrc/multi_tensor_lamb_stage_2.cu``):
    ``ratio = lr * (p_norm / u_norm)`` when both norms are nonzero, plain
    ``lr`` otherwise; ``p -= ratio * update``.  Norm arrays are in
    flattened-leaf order (use ``multi_tensor_l2norm(..., per_tensor=True)``).
    """
    leaves_p, treedef = jax.tree_util.tree_flatten(params)
    leaves_u = jax.tree_util.tree_leaves(updates)
    new_p = []
    for p, u, pn, un in zip(leaves_p, leaves_u, per_tensor_param_norm,
                            per_tensor_update_norm):
        pn = jnp.asarray(pn, jnp.float32)
        un = jnp.asarray(un, jnp.float32)
        ratio = jnp.where((pn != 0.0) & (un != 0.0),
                          learning_rate * (pn / un), learning_rate)
        p32 = jnp.asarray(p, jnp.float32) - ratio * jnp.asarray(u, jnp.float32)
        new_p.append(p32.astype(jnp.asarray(p).dtype))
    return treedef.unflatten(new_p)


# -- flatten / unflatten ------------------------------------------------------

def flatten(tensors):
    """Concatenate a list of arrays into one flat fp-preserving buffer.

    Equivalent of ``apex_C.flatten`` (``csrc/flatten_unflatten.cpp``), the
    flat communication buffer used by DDP.  On TPU flat buffers are rarely
    needed (XLA lays out collectives itself) but the capability is kept for
    the Reducer/bucket APIs and for host-side checkpoint packing (which has a
    true native C++ path, see ``apex_tpu/csrc``).
    """
    return jnp.concatenate([jnp.ravel(t) for t in tensors])


def unflatten(flat, like):
    """Split ``flat`` back into arrays shaped like the entries of ``like``."""
    sizes = [int(jnp.size(t)) for t in like]
    offsets = [0]
    for s in sizes:
        offsets.append(offsets[-1] + s)
    return [jax.lax.dynamic_slice_in_dim(flat, offsets[i], sizes[i]).reshape(
        jnp.shape(like[i])).astype(jnp.asarray(like[i]).dtype)
        for i in range(len(like))]


# -- reference-parity shim -----------------------------------------------------

class MultiTensorApply:
    """API-parity shim for ``multi_tensor_applier(op, noop_buf, lists, *args)``.

    The reference shim forwards to a CUDA kernel with a chunk size
    (``multi_tensor_apply.py:3-30``).  Here ``op`` is one of the pure
    functions above; the noop flag is *returned* rather than written into a
    caller buffer, and chunking is XLA's job.  ``available`` is always True —
    there is no optional native extension to import.
    """
    available = True
    warned = False

    def __init__(self, chunk_size=2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag, tensor_lists, *args):
        return op(*tensor_lists, *args)


multi_tensor_applier = MultiTensorApply(2048 * 32)
