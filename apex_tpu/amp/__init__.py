"""apex_tpu.amp — automatic mixed precision for TPU (bf16-first).

TPU-native re-design of ``apex/amp``; see SURVEY.md §2.1 for the component
map.  Public surface mirrors the reference (``apex/amp/__init__.py``):
``initialize``, ``scale_loss``, ``state_dict``/``load_state_dict``,
``master_params``, the O1 registries and decorators, plus the functional
pieces (``Policy`` casting helpers, jit-safe ``LossScaler``) that are the
idiomatic JAX path.
"""

from .properties import Properties, opt_levels, AmpOptionError  # noqa: F401
from .frontend import initialize, state_dict, load_state_dict   # noqa: F401
from .handle import scale_loss, disable_casts, AmpHandle, NoOpHandle  # noqa: F401
from .loss_scaler import LossScaler, LossScalerState, all_finite  # noqa: F401
from ._amp_state import master_params, _amp_state  # noqa: F401
from .policy import (applier, to_type, convert_params, wrap_forward,  # noqa: F401
                     make_master, master_to_model, default_norm_predicate)
from .autocast import (init, shutdown,  # noqa: F401
                       register_half_function, register_float_function,
                       register_promote_function, register_banned_function,
                       half_function, float_function, promote_function)
