"""Loss scaling: static and dynamic, as jit-safe functional state.

TPU-native re-design of the reference scaler (``apex/amp/scaler.py:33-217``).
The semantics preserved exactly:

* dynamic: init 2**16 (capped by ``max_loss_scale`` default 2**24), doubled
  every ``scale_window`` (2000) clean steps, halved on overflow, optional
  ``min_loss_scale`` floor (reference ``scaler.py:38-56, 197-217``).
* ``unscale`` divides grads by the scale and raises a *device-side* overflow
  flag if any grad is non-finite (reference multi_tensor_scale writes a GPU
  int buffer; here the flag is a traced jnp scalar — zero host syncs unless
  the caller asks for one).
* per-loss scalers (``num_losses``/``loss_id``) and ``state_dict`` fields
  ``loss_scale`` + ``unskipped`` round-trip (reference ``frontend.py:361-400``).

TPU-first difference: because the default half type is bfloat16 (fp32 exponent
range), the default loss scale is **static 1.0** — the whole state machine then
compiles away to a no-op.  The dynamic machine is fully functional for fp16
users and for checkpoint parity.

The class is registered as a pytree so a ``LossScalerState`` can live inside a
jitted train step: ``update_scale`` is pure (returns a new state) and the
"skip step" decision is a traced boolean the optimizer consumes as a mask —
no data-dependent Python control flow (reference ``handle.py:126-151`` patches
``optimizer.step``; the TPU equivalent is a select, see
``apex_tpu/optimizers``).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from .. import multi_tensor as mta


class LossScalerState(NamedTuple):
    """Traced state of one loss scaler (a valid jit carry)."""
    loss_scale: jnp.ndarray      # f32 scalar
    unskipped: jnp.ndarray       # i32 scalar — clean steps since last overflow
    overflow: jnp.ndarray        # bool scalar — overflow seen this step


# Imperative-path fast lanes (r5): called OUTSIDE a jitted step, the
# per-leaf unscale/axpby sweeps used to run as ~100 eager dispatches per
# backward — at ~0.8 ms per eager dispatch through a tunneled chip that
# was ~77 ms per scale_loss context and the dominant cost of the DCGAN
# imperative loop (measured: full loop 261 -> ~40 ms/iter after this).
# jit makes each sweep ONE cached program per tree structure; calling
# them during an outer trace is also fine (jit inlines).
@functools.partial(jax.jit, static_argnames=("store",))
def _unscale_fp32(tree, scale, store=None):
    return mta.multi_tensor_scale(tree, 1.0 / scale, out_dtype=jnp.float32,
                                  store=store)


@functools.partial(jax.jit, static_argnames=("store",))
def _axpby_fp32(new, stashed, scale, store=None):
    return mta.multi_tensor_axpby(new, stashed, 1.0 / scale, 1.0,
                                  out_dtype=jnp.float32, store=store)


@functools.lru_cache(maxsize=None)
def _update_scale_lane(dynamic, scale_factor, scale_window,
                       min_loss_scale, max_loss_scale):
    """One compiled update-scale program per CONFIG (not per scaler
    instance): DCGAN's three identical scalers share a single compile
    instead of paying the tunnel's multi-second trace+compile three
    times."""
    def update(state):
        if not dynamic:
            return state._replace(overflow=jnp.asarray(False))
        overflow = state.overflow
        shrunk = state.loss_scale / scale_factor
        if min_loss_scale is not None:
            shrunk = jnp.maximum(shrunk, min_loss_scale)
        window_full = (state.unskipped + 1) >= scale_window
        grown = jnp.minimum(state.loss_scale * scale_factor,
                            max_loss_scale)
        new_scale = jnp.where(
            overflow, shrunk,
            jnp.where(window_full, grown, state.loss_scale))
        new_unskipped = jnp.where(
            jnp.logical_or(overflow, window_full), 0, state.unskipped + 1)
        return LossScalerState(
            loss_scale=new_scale.astype(jnp.float32),
            unskipped=new_unskipped.astype(jnp.int32),
            overflow=jnp.asarray(False),
        )
    return jax.jit(update)


def all_finite(tree, store=None) -> jnp.ndarray:
    """Device-side AND-reduction of isfinite over a grad tree (no host
    sync); with ``store`` (or a Packed tree), one reduce per bucket."""
    return mta.tree_finite(tree, store=store)


class LossScaler:
    """Static or dynamic loss scaler.

    Functional usage (the idiomatic path — everything stays on device)::

        scaler = LossScaler("dynamic")
        state = scaler.init()
        ...inside jit...
        loss = scaler.scale_loss(loss, state)
        grads, state = scaler.unscale(grads, state)   # sets state.overflow
        state = scaler.update_scale(state)            # adjust scale, reset flag
        # optimizer consumes state.overflow as a skip mask

    Imperative usage (API parity with the reference) keeps an internal state
    and exposes ``loss_scale()`` / ``update_scale()`` like
    ``apex/amp/scaler.py``.
    """

    warned_unscaling_non_fp32_grad = False

    def __init__(self,
                 loss_scale,
                 init_scale=2.**16,
                 scale_factor=2.,
                 scale_window=2000,
                 min_loss_scale=None,
                 max_loss_scale=2.**24):
        if loss_scale == "dynamic":
            self.dynamic = True
            self._initial_scale = min(max_loss_scale, init_scale)
        else:
            self.dynamic = False
            self._initial_scale = float(loss_scale)
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._min_loss_scale = min_loss_scale
        self._max_loss_scale = max_loss_scale
        self._imp_steps = 0      # imperative update count (telemetry)
        self._state = self.init()

    # -- functional core -----------------------------------------------------
    def init(self) -> LossScalerState:
        return LossScalerState(
            loss_scale=jnp.float32(self._initial_scale),
            unskipped=jnp.int32(0),
            overflow=jnp.asarray(False),
        )

    def scale_loss(self, loss, state: LossScalerState = None):
        state = self._state if state is None else state
        if not self.dynamic and self._initial_scale == 1.0:
            return loss  # fast path, reference handle.py:93-102
        return jnp.asarray(loss, jnp.float32) * state.loss_scale

    def unscale(self, grads, state: LossScalerState = None, *, scale=None,
                store=None):
        """Divide grads by the scale; record overflow in the returned state.

        Equivalent of ``LossScaler.unscale`` → multi_tensor_scale with the
        device-side noop flag (reference ``scaler.py:57-117``).  Grads are
        unscaled in fp32 (master-grad dtype).

        ``store`` (a :class:`~apex_tpu.multi_tensor.BucketStore`) routes
        the sweep and the overflow check through flat buckets — one
        ``isfinite``+reduce per bucket instead of per leaf; a ``Packed``
        ``grads`` value stays packed in the output.
        """
        explicit = state is not None
        state = self._state if state is None else state
        s = state.loss_scale if scale is None else scale
        out, overflow = _unscale_fp32(grads, s, store=store)
        if self.dynamic:
            new_state = state._replace(overflow=jnp.logical_or(state.overflow, overflow))
        else:
            new_state = state
        if not explicit:
            self._state = new_state
        return out, new_state

    def unscale_with_stashed(self, new_grads, stashed_grads,
                             state: LossScalerState = None, *, scale=None,
                             store=None):
        """Gradient accumulation: out = new/scale + stashed, overflow-checked.

        Equivalent of the fused axpby path (reference ``scaler.py:152-189``);
        ``store`` routes it through flat buckets.
        """
        explicit = state is not None
        state = self._state if state is None else state
        s = state.loss_scale if scale is None else scale
        out, overflow = _axpby_fp32(new_grads, stashed_grads, s, store=store)
        if self.dynamic:
            new_state = state._replace(overflow=jnp.logical_or(state.overflow, overflow))
        else:
            new_state = state
        if not explicit:
            self._state = new_state
        return out, new_state

    def clear_overflow_state(self, state: LossScalerState = None):
        explicit = state is not None
        state = self._state if state is None else state
        new_state = state._replace(overflow=jnp.asarray(False))
        if not explicit:
            self._state = new_state
        return new_state

    def update_scale(self, state: LossScalerState = None):
        """Adjust the scale from the overflow flag; pure and traceable
        (the compiled state machine is shared per config, see
        :func:`_update_scale_lane` — the eager jnp.where chain was ~6
        dispatches + a host->device upload of the False constant per
        call).

        Reference ``scaler.py:197-217``: on overflow, scale/2 (clamped at
        ``min_loss_scale``) and reset the window; every ``scale_window`` clean
        steps, scale*2 (clamped at ``max_loss_scale``).
        """
        explicit = state is not None
        state = self._state if state is None else state
        fn = _update_scale_lane(self.dynamic, self._scale_factor,
                                self._scale_window, self._min_loss_scale,
                                self._max_loss_scale)
        new_state = fn(state)
        if not explicit:
            self._state = new_state
        return new_state

    # -- imperative / checkpoint API (reference parity) ----------------------
    def loss_scale(self):
        return float(jax.device_get(self._state.loss_scale))  # jaxlint: disable=J001 -- imperative API parity (reference scaler.py loss_scale()); jitted paths read state.loss_scale on device

    def update_scale_sync(self) -> bool:
        """Imperative update: ONE host sync per step, like the reference's
        ``overflow_buf.item()`` (``scaler.py:199-200``).  Returns
        ``should_skip`` for the step-skipping contract."""
        should_skip = bool(jax.device_get(self._state.overflow)) and self.dynamic  # jaxlint: disable=J001 -- the documented ONE sync per imperative step (reference overflow_buf.item()); prefer update_scale_deferred to batch it
        self._state = self.update_scale(self._state)
        self._imp_steps += 1
        if should_skip:
            # Telemetry (ISSUE 5): the imperative twin of the scale
            # events the recorder derives from fetched window metrics on
            # the functional path.  The overflow flag was just read
            # above — no extra sync.
            from .. import telemetry as _telemetry
            rec = _telemetry.get_recorder()
            if rec is not None:
                rec.metrics.counter("loss_scale_skips").inc()
                rec.event("scale", event="skip", step=self._imp_steps - 1,
                          source="imperative")
        return should_skip

    def update_scale_deferred(self):
        """Imperative update with the host read DEFERRED: runs the same
        device-side scale state machine as :meth:`update_scale_sync` but
        returns the pre-update overflow flag as a DEVICE scalar (or None
        for static scalers, which never skip) instead of reading it.

        The caller batches the reads —
        ``FusedOptimizer._resolve_pending_overflows`` (``optimizers/
        base.py``, called from ``step``) stacks every pending scaler's
        flag into ONE device->host transfer, so a multi-loss iteration
        (e.g. DCGAN's three scalers) pays one round-trip per optimizer
        step instead of one per scaler.  On GPU
        the reference's per-scaler read costs microseconds; through a
        tunneled chip each read is ~0.1-0.3 s, which made this the
        dominant cost of the imperative path.  Skip/step decisions are
        bit-identical to the sync path — only WHEN the host learns the
        flag changes."""
        flag = self._state.overflow if self.dynamic else None
        self._state = self.update_scale(self._state)
        self._imp_steps += 1
        return flag

    @property
    def state(self) -> LossScalerState:
        return self._state

    @state.setter
    def state(self, s: LossScalerState):
        self._state = s

    def state_dict(self):
        """Reference serializes ``loss_scale`` + ``unskipped``
        (``frontend.py:361-370``)."""
        return {"loss_scale": float(jax.device_get(self._state.loss_scale)),
                "unskipped": int(jax.device_get(self._state.unskipped))}

    def load_state_dict(self, sd):
        self._state = LossScalerState(
            loss_scale=jnp.float32(sd["loss_scale"]),
            unskipped=jnp.int32(sd["unskipped"]),
            overflow=jnp.asarray(False))
