"""Global amp state singleton.

Reference: ``apex/amp/_amp_state.py:17-70``.  Holds the active ``Properties``,
the per-loss ``LossScaler`` list, verbosity, and the O1 handle.  Rank-0-aware
printing uses ``jax.process_index()`` instead of the WORLD_SIZE env sniffing
(reference ``:38-40``).
"""

from __future__ import annotations

import jax


class AmpState:
    def __init__(self):
        self.hard_override = False
        self.allow_incoming_model_not_fp32 = False
        self.verbosity = 1
        self.opt_properties = None
        self.loss_scalers = []
        self.handle = None
        # O1 autocast: consulted by wrapped functions and apex_tpu layers.
        self.autocast_enabled = False
        self.autocast_dtype = None


_amp_state = AmpState()


def warn_or_err(msg):
    if _amp_state.hard_override:
        print("Warning: " + msg)
    else:
        raise RuntimeError(msg)


def maybe_print(msg, rank0=False):
    if _amp_state.verbosity > 0:
        if not rank0 or jax.process_index() == 0:
            print(msg)


def master_params(optimizer):
    """Generator over the fp32 master weights held by an amp-wired optimizer
    (reference ``_amp_state.py:61-70``)."""
    for leaf in jax.tree_util.tree_leaves(optimizer.master_params
                                          if getattr(optimizer, "master_params", None)
                                          is not None else optimizer.params):
        yield leaf
