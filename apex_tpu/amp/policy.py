"""Dtype policies and recursive casting utilities.

TPU-native analog of the reference's input/model casting machinery:

* ``applier`` — recursive caster over nested containers
  (reference ``apex/amp/_initialize.py:35-57``).
* model conversion with keep-batchnorm-fp32
  (reference ``apex/fp16_utils/fp16util.py:74-86`` used by O2).
* patched-forward input/output casting (reference ``_initialize.py:181-219``)
  becomes :func:`wrap_forward`, a pure function wrapper that casts inputs to the
  compute dtype and outputs back to fp32 — jit-traceable, no monkey patching.

In JAX, parameters are pytrees, so "convert the network" is a pytree map with a
per-leaf dtype rule.  Normalization-scale parameters are detected by path name
(``scale``/``bias`` under a ``*Norm``/``bn`` collection — flax convention) so
keep_batchnorm_fp32 works for flax models out of the box; users can pass a
custom predicate for exotic layouts.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

# Leaves considered "floating" for casting purposes.  Integer/bool leaves
# (embedding ids, masks, rng keys) always pass through untouched.
def _is_float(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def applier(value: Any, fn: Callable[[Any], Any]) -> Any:
    """Recursively apply ``fn`` to every array leaf of ``value``.

    Mirrors reference ``_initialize.py:35-57`` (which walks
    strings/mappings/iterables and respects custom ``.to()``); here the pytree
    protocol already covers dicts/lists/tuples/custom nodes, so this is
    ``jax.tree_util.tree_map`` with non-array leaves passed through.
    """
    return jax.tree_util.tree_map(
        lambda x: fn(x) if hasattr(x, "dtype") else x, value)


def to_type(dtype, value):
    """Cast every *floating* array leaf of ``value`` to ``dtype``.

    Reference ``_initialize.py:17-32`` warns when an input is not fp32;
    integer leaves are left alone for the same reason (indices stay indices).
    """
    def cast(x):
        return x.astype(dtype) if _is_float(x) else x
    return applier(value, cast)


# -- keep-batchnorm-fp32 model conversion ------------------------------------

# Flax linen convention: BatchNorm/LayerNorm/GroupNorm parameters live under a
# module path containing one of these markers.  ``convert_params`` keeps any
# matching leaf in fp32 when keep_norm_fp32 is set.
_NORM_PATH_RE = re.compile(r"(?:^|[/._])(?:bn|batchnorm|batch_norm|norm|ln|layernorm|"
                           r"layer_norm|groupnorm|group_norm|batch_stats)(?:$|[/._\d])",
                           re.IGNORECASE)


def default_norm_predicate(path: str) -> bool:
    """True if a parameter path looks like it belongs to a normalization layer."""
    return bool(_NORM_PATH_RE.search(path))


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def convert_params(params,
                   dtype,
                   keep_norm_fp32: bool = True,
                   norm_predicate: Optional[Callable[[str], bool]] = None):
    """Cast a parameter pytree to ``dtype``, optionally keeping norm params fp32.

    TPU-native equivalent of ``convert_network`` (reference
    ``apex/fp16_utils/fp16util.py:74-86``): walk the module tree, convert
    every float leaf, but skip affine BatchNorm parameters so their small
    per-channel scale/shift math stays in fp32.
    """
    if norm_predicate is None:
        norm_predicate = default_norm_predicate

    def cast(path, x):
        if not _is_float(x):
            return x
        if keep_norm_fp32 and norm_predicate(_path_str(path)):
            return x.astype(jnp.float32)
        return x.astype(dtype)

    return jax.tree_util.tree_map_with_path(cast, params)


def wrap_forward(apply_fn: Callable,
                 cast_input_type=None,
                 cast_output_type=jnp.float32) -> Callable:
    """Wrap a model apply function so inputs are cast to the compute dtype and
    outputs back to fp32 (or ``cast_output_type``).

    Reference behavior: O2/O3 patch ``model.forward`` to cast ``*args``
    /``**kwargs`` to ``cast_model_type`` and outputs to fp32 /
    ``cast_model_outputs`` (``_initialize.py:181-219``).  Here the wrapper is a
    pure function — safe under jit, grad, vmap, shard_map.
    """
    def wrapped(*args, **kwargs):
        if cast_input_type is not None:
            args = to_type(cast_input_type, args)
            kwargs = to_type(cast_input_type, kwargs)
        out = apply_fn(*args, **kwargs)
        if cast_output_type is not None:
            out = to_type(cast_output_type, out)
        return out
    return wrapped


# -- master weights ----------------------------------------------------------

def make_master(params):
    """fp32 master copy of a (possibly reduced-precision) parameter tree.

    Reference: ``param.detach().clone().float()``
    (``apex/amp/_process_optimizer.py:43-51``).
    """
    return applier(params, lambda x: x.astype(jnp.float32) if _is_float(x) else x)


def master_to_model(master_params, model_params):
    """Cast fp32 masters back onto the model's dtypes (the post-step copy,
    reference ``_process_optimizer.py:345-356`` via multi_tensor_scale(1.0))."""
    return jax.tree_util.tree_map(
        lambda m, p: m.astype(p.dtype) if _is_float(p) else m,
        master_params, model_params)
