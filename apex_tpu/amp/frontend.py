"""``amp.initialize`` and amp checkpoint state.

TPU-native re-design of reference ``apex/amp/frontend.py:195-400`` +
``apex/amp/_initialize.py``.  In JAX a "model" is a parameter pytree (plus a
pure apply function), so ``initialize`` consumes and returns *param trees* and
``apex_tpu.optimizers`` objects rather than mutating modules in place:

* O2/O3: params are cast to bf16 (keep-batchnorm-fp32 honored via path
  heuristics — ``policy.convert_params``), the optimizer is wired with fp32
  master weights, and the returned params are the *model* (bf16) copy.
* O4: identical storage handling to O2 (bf16 cast, fp32 masters, loss
  scaling); the int8 routing itself is a MODEL property — build the
  model with the ``quant=`` hook (``apex_tpu.quant``, ISSUE 13) and the
  annotated matmuls dispatch the quantized kernels inside the step.
* O1: the autocast policy over jnp/lax is enabled (``autocast.init``),
  params stay fp32.
* O0: everything fp32, loss scale 1.0.

``state_dict``/``load_state_dict`` serialize every loss scaler's
``loss_scale`` and ``unskipped`` exactly like reference
``frontend.py:361-400``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from . import autocast
from ._amp_state import _amp_state, maybe_print, warn_or_err
from .loss_scaler import LossScaler
from .policy import convert_params, wrap_forward  # noqa: F401  (re-exported)
from .properties import AmpOptionError, Properties, opt_levels


def initialize(models=None,
               optimizers=None,
               enabled: bool = True,
               opt_level: str = "O1",
               cast_model_type=None,
               patch_functions=None,
               keep_batchnorm_fp32=None,
               master_weights=None,
               loss_scale=None,
               cast_model_outputs=None,
               num_losses: int = 1,
               verbosity: int = 1,
               min_loss_scale=None,
               max_loss_scale: float = 2.**24,
               norm_predicate=None):
    """Initialize mixed precision.  Returns ``(models, optimizers)`` shaped
    like the inputs (single objects in → single objects out, reference
    ``_initialize.py:245-260``).

    ``models`` are parameter pytrees (or a list of them); ``optimizers`` are
    ``apex_tpu.optimizers`` instances (or a list).  Either may be None.
    """
    _amp_state.verbosity = verbosity

    if not enabled:
        # Full teardown: restore any patched jnp/lax entry points so a
        # disabled amp leaves the process pristine (reference
        # _initialize.py:42-56 returns everything untouched).
        autocast.shutdown()
        _amp_state.opt_properties = Properties()
        # Inputs pass through untouched and keep their exact shape —
        # including lists, which must not be collapsed to their first
        # element (reference _initialize.py:42-56).
        return _unlistify(models, optimizers,
                          models_was_list=True, optimizers_was_list=True,
                          had_models=models is not None,
                          had_optimizers=optimizers is not None)

    if opt_level not in opt_levels:
        raise AmpOptionError(
            "Unexpected optimization level {!r}; options are 'O0', 'O1', "
            "'O2', 'O3', 'O4'. Note the 'O' is the letter O.".format(
                opt_level))

    properties = opt_levels[opt_level]()
    maybe_print("apex_tpu.amp: opt_level {}".format(opt_level), True)

    overrides = dict(cast_model_type=cast_model_type,
                     patch_functions=patch_functions,
                     keep_batchnorm_fp32=keep_batchnorm_fp32,
                     master_weights=master_weights,
                     loss_scale=loss_scale,
                     cast_model_outputs=cast_model_outputs)
    for k, v in overrides.items():
        if v is not None:
            setattr(properties, k, v)
    _amp_state.opt_properties = properties

    # Loss scalers, one per loss (reference _initialize.py:224-228).
    _amp_state.loss_scalers = [
        LossScaler(properties.loss_scale,
                   min_loss_scale=min_loss_scale,
                   max_loss_scale=max_loss_scale)
        for _ in range(num_losses)
    ]

    models_was_list = isinstance(models, (list, tuple))
    optimizers_was_list = isinstance(optimizers, (list, tuple))
    model_list = list(models) if models_was_list else ([models] if models is not None else [])
    opt_list = list(optimizers) if optimizers_was_list else ([optimizers] if optimizers is not None else [])

    _check_models(model_list)
    _check_optimizers(opt_list)
    if opt_level != "O3":
        _check_params_fp32(model_list)

    for opt in opt_list:
        if getattr(opt, "_amp_wired", False):
            warn_or_err("An optimizer was passed to amp.initialize twice; "
                        "call initialize once with all models and optimizers.")

    # O2/O3: whole-model cast (reference _initialize.py:173-179 via
    # convert_network / .to(dtype)).
    cast_type = properties.cast_model_type
    if cast_type is not None and jnp.dtype(cast_type) != jnp.dtype(jnp.float32):
        keep_bn = properties.keep_batchnorm_fp32
        keep_bn = True if keep_bn is None else keep_bn
        model_list = [convert_params(m, cast_type, keep_norm_fp32=keep_bn,
                                     norm_predicate=norm_predicate)
                      for m in model_list]

    # O1: enable the jnp/lax autocast policy (reference _initialize.py:230-243
    # calling amp.init()).
    if properties.patch_functions:
        autocast.init(enabled=True, verbose=(verbosity >= 2))
    else:
        _amp_state.autocast_enabled = False

    # Wire optimizers: master weights + loss scaler handshake
    # (reference _process_optimizer.py injected methods).
    for i, opt in enumerate(opt_list):
        scaler = _amp_state.loss_scalers[min(i, num_losses - 1)]
        if hasattr(opt, "_amp_wire"):
            new_params = model_list[i] if i < len(model_list) else None
            opt._amp_wire(properties, scaler, cast_params=new_params,
                          norm_predicate=norm_predicate)

    return _unlistify(model_list if models is not None else None,
                      opt_list if optimizers is not None else None,
                      models_was_list, optimizers_was_list,
                      models is not None, optimizers is not None)


def _check_models(model_list):
    """Reject already-wrapped models (reference ``_initialize.py:60-72``
    ``check_models``)."""
    from ..parallel.distributed import DistributedDataParallel as _DDP
    for model in model_list:
        if isinstance(model, _DDP):
            raise RuntimeError(
                "Incoming model is an instance of "
                "apex_tpu.parallel.DistributedDataParallel. "
                "Parallel wrappers should only be applied to the model(s) "
                "AFTER \nthe model(s) have been returned from "
                "amp.initialize.")


def _check_params_fp32(model_list):
    """Warn-or-error on reduced-precision incoming params (reference
    ``_initialize.py:75-112`` ``check_params_fp32``)."""
    import jax

    for model in model_list:
        for path, leaf in jax.tree_util.tree_flatten_with_path(model)[0]:
            if (hasattr(leaf, "dtype")
                    and jnp.issubdtype(leaf.dtype, jnp.floating)
                    and leaf.dtype != jnp.dtype(jnp.float32)):
                warn_or_err(
                    "Found param {} with dtype {}, expected float32.\n"
                    "When using amp.initialize, you do not need to cast "
                    "your model to\nreduced precision before passing it, no "
                    "matter what optimization level\nyou choose.".format(
                        jax.tree_util.keystr(path), leaf.dtype))


def _check_optimizers(opt_list):
    """Reject pre-wrapped FP16 optimizers (reference
    ``_initialize.py:115-126`` ``check_optimizers``)."""
    from ..bf16_utils.fp16_optimizer import FP16_Optimizer as _FP16_general
    from ..optimizers.fp16_optimizer import FP16_Optimizer as _FP16_fused
    for optim in opt_list:
        bad_optim_type = None
        if isinstance(optim, _FP16_general):
            bad_optim_type = "apex_tpu.bf16_utils.FP16_Optimizer"
        if isinstance(optim, _FP16_fused):
            bad_optim_type = "apex_tpu.optimizers.FP16_Optimizer"
        if bad_optim_type is not None:
            raise RuntimeError(
                "An incoming optimizer is an instance of {}. ".format(
                    bad_optim_type) +
                "The optimizer(s) passed to amp.initialize() must be bare \n"
                "instances of apex_tpu fused optimizers (master weights are "
                "wired in by\namp.initialize itself).\n")


def _unlistify(models, optimizers, models_was_list=False, optimizers_was_list=False,
               had_models=True, had_optimizers=True):
    m = models if models_was_list else (models[0] if isinstance(models, list) and models else models)
    o = optimizers if optimizers_was_list else (optimizers[0] if isinstance(optimizers, list) and optimizers else optimizers)
    if had_models and had_optimizers:
        return m, o
    if had_models:
        return m
    if had_optimizers:
        return o
    return None


def state_dict(destination=None):
    """Serialize amp state: one entry per loss scaler
    (reference ``frontend.py:361-370``)."""
    if destination is None:
        destination = {}
    for idx, ls in enumerate(_amp_state.loss_scalers):
        destination["loss_scaler%d" % idx] = ls.state_dict()
    return destination


def load_state_dict(sd):
    """Restore amp state (reference ``frontend.py:373-400``), warning on
    scaler-count mismatch like the reference."""
    n_src, n_dst = len(sd), len(_amp_state.loss_scalers)
    if n_src != n_dst:
        print("Warning: state dict has {} loss scalers, amp has {}; loading "
              "the overlap.".format(n_src, n_dst))
    for idx, ls in enumerate(_amp_state.loss_scalers):
        key = "loss_scaler%d" % idx
        if key in sd:
            ls.load_state_dict(sd[key])
