"""O1 autocast engine: per-op cast policy, function registries, decorators.

TPU-native re-design of the reference O1 patch engine
(``apex/amp/amp.py:30-177``, ``apex/amp/wrap.py``, ``apex/amp/lists/``).

The reference monkey-patches ``torch.*`` / ``torch.Tensor.*`` /
``torch.nn.functional.*`` at ``amp.init()`` time.  The same mechanism works for
``jax.numpy`` / ``jax.lax`` entry points — a wrapper that casts array
arguments and calls the original is perfectly traceable under ``jit`` — with
one documented caveat: the enabled flag is read at *trace* time, so toggling
it (``disable_casts``) only affects functions traced afterwards.  That matches
how jit-compiled training steps should consume amp anyway: decide the policy
before compiling.

Cast lists (reference ``apex/amp/lists/torch_overrides.py`` and
``functional_overrides.py``) translated to the jnp/lax namespace:

* half (bf16) list — MXU ops: matmul family and convolutions.
* fp32 list — transcendentals, reductions, norms, losses, softmax.
* promote list — binary ops whose operands must agree: jnp promotes
  bf16+fp32→fp32 natively, so only ``cat``/``stack``-style sequence promotion
  needs handling.
* banned — none: ``binary_cross_entropy`` is banned in the reference because
  fp16 logs overflow (``functional_overrides.py:59-70``); bf16 shares fp32's
  range so the TPU policy runs it in fp32 instead of raising.  The banning
  machinery exists (``err_if_banned``) for API parity and fp16 users.

User registries keep the reference API verbatim: ``register_half_function``,
``register_float_function``, ``register_promote_function`` and the decorator
forms ``half_function`` / ``float_function`` / ``promote_function``
(reference ``amp.py:30-64``).
"""

from __future__ import annotations

import functools
import itertools
from typing import Callable

import jax
import jax.numpy as jnp

from ._amp_state import _amp_state, maybe_print


def _is_float_array(x):
    return hasattr(x, "dtype") and jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


# -- weight-cast cache ---------------------------------------------------------
# Reference utils.py:88-117: fp32->fp16 casts of *parameters* are cached so a
# weight is cast once per step; the cache is cleared at scale_loss exit
# (handle.py:153-155).  Under jit XLA CSEs duplicate casts, so the cache only
# matters for eager use; keyed on array object identity.
_cast_cache = {}


def clear_cast_cache():
    _cast_cache.clear()


def cached_cast(dtype, x):
    if not _is_float_array(x):
        return x
    if jnp.asarray(x).dtype == jnp.dtype(dtype):
        return x
    key = (id(x), jnp.dtype(dtype).name)
    hit = _cast_cache.get(key)
    if hit is not None and hit[0] is x:
        return hit[1]
    out = jnp.asarray(x).astype(dtype)
    if isinstance(x, jax.Array) and not isinstance(x, jax.core.Tracer):
        # Store the SOURCE alongside the cast: the key is id(x), and ids are
        # reused once an array is collected — without pinning x, a later
        # array at the same address would silently receive this stale cast
        # (observed as shape corruption in the DCGAN multi-model loop).
        _cast_cache[key] = (x, out)
    return out


def _cast_args(dtype, args, kwargs):
    args = tuple(cached_cast(dtype, a) if _is_float_array(a)
                 else (type(a)(cached_cast(dtype, x) if _is_float_array(x) else x for x in a)
                       if isinstance(a, (list, tuple)) else a)
                 for a in args)
    kwargs = {k: cached_cast(dtype, v) if _is_float_array(v) else v
              for k, v in kwargs.items()}
    return args, kwargs


# -- wrapper factories (reference wrap.py) ------------------------------------

def make_cast_wrapper(orig_fn: Callable, cast_dtype, verbose_name=None):
    """Wrap ``orig_fn`` so float array args are cast to ``cast_dtype`` when
    autocast is enabled (reference ``wrap.py:10-29``)."""
    name = verbose_name or getattr(orig_fn, "__name__", "fn")

    @functools.wraps(orig_fn)
    def wrapper(*args, **kwargs):
        if not _amp_state.autocast_enabled:
            return orig_fn(*args, **kwargs)
        dtype = cast_dtype
        if dtype == "half":
            dtype = _amp_state.autocast_dtype or jnp.bfloat16
        if _amp_state.verbosity >= 2:
            maybe_print("amp: casting args of {} to {}".format(name, jnp.dtype(dtype).name))
        cargs, ckwargs = _cast_args(dtype, args, kwargs)
        return orig_fn(*cargs, **ckwargs)
    wrapper.__amp_original__ = orig_fn
    return wrapper


def make_promote_wrapper(orig_fn: Callable):
    """Promote all float args to the widest float dtype among them
    (reference ``wrap.py:65-91``)."""
    @functools.wraps(orig_fn)
    def wrapper(*args, **kwargs):
        if not _amp_state.autocast_enabled:
            return orig_fn(*args, **kwargs)
        floats = [jnp.asarray(a).dtype for a in _flat_arrays(args) if _is_float_array(a)]
        if not floats:
            return orig_fn(*args, **kwargs)
        widest = functools.reduce(jnp.promote_types, floats)
        cargs, ckwargs = _cast_args(widest, args, kwargs)
        return orig_fn(*cargs, **ckwargs)
    wrapper.__amp_original__ = orig_fn
    return wrapper


def _flat_arrays(args):
    for a in args:
        if isinstance(a, (list, tuple)):
            for x in a:
                yield x
        else:
            yield a


def make_banned_wrapper(orig_fn: Callable, name: str):
    """Raise on use under fp16 autocast unless allow_banned
    (reference ``wrap.py:114-127`` / ``amp.py`` banned handling).  Under the
    bf16 default policy the function is run in fp32 instead."""
    @functools.wraps(orig_fn)
    def wrapper(*args, **kwargs):
        if _amp_state.autocast_enabled:
            if _amp_state.autocast_dtype == jnp.float16 and not getattr(
                    _amp_state, "allow_banned", False):
                raise NotImplementedError(
                    "amp does not work out-of-the-box with {} under float16 "
                    "because it requires the full float range; use bfloat16, "
                    "a safe replacement loss, or allow_banned=True.".format(name))
            cargs, ckwargs = _cast_args(jnp.float32, args, kwargs)
            return orig_fn(*cargs, **ckwargs)
        return orig_fn(*args, **kwargs)
    wrapper.__amp_original__ = orig_fn
    return wrapper


# -- cast lists ---------------------------------------------------------------
# (module, attribute-name) pairs; resolved lazily at init() so wrapping is
# reversible and import order does not matter.

import jax.lax as lax       # noqa: E402
import jax.nn as jnn        # noqa: E402
import jax.numpy.linalg as jla  # noqa: E402

from ..ops import losses as _ops_losses  # noqa: E402

# MXU ops -> half (reference torch_overrides.py FP16_FUNCS: conv*/BLAS
# mm/matmul/addmm/bmm/... + functional_overrides FP16 conv/linear).
_HALF_LIST = [
    (jnp, "dot"), (jnp, "matmul"), (jnp, "vdot"), (jnp, "inner"),
    (jnp, "outer"), (jnp, "tensordot"), (jnp, "einsum"), (jnp, "kron"),
    (jla, "multi_dot"),
    (lax, "dot"), (lax, "dot_general"),
    (lax, "conv"), (lax, "conv_general_dilated"), (lax, "conv_transpose"),
]

# Transcendentals / reductions / norms / losses -> fp32
# (reference torch_overrides.py FP32_FUNCS :28-60 — acos/asin/cosh/erf/
# gamma-family/log*/pow/reductions/norm/renorm — and functional_overrides
# FP32_FUNCS :22-57 — softmax family, norm layers, losses).
_FP32_LIST = [
    # transcendentals
    (jnp, "exp"), (jnp, "exp2"), (jnp, "expm1"), (jnp, "log"), (jnp, "log1p"),
    (jnp, "log2"), (jnp, "log10"), (jnp, "logaddexp"), (jnp, "logaddexp2"),
    (jnp, "cosh"), (jnp, "sinh"), (jnp, "tan"),
    (jnp, "arccos"), (jnp, "arcsin"), (jnp, "arccosh"), (jnp, "arcsinh"),
    (jnp, "arctanh"),
    (jnp, "power"), (jnp, "float_power"), (jnp, "reciprocal"),
    (lax, "erf"), (lax, "erfc"), (lax, "erf_inv"), (lax, "lgamma"),
    (lax, "digamma"), (lax, "rsqrt"),
    # reductions
    (jnp, "sum"), (jnp, "prod"), (jnp, "cumsum"), (jnp, "cumprod"),
    (jnp, "var"), (jnp, "std"), (jnp, "mean"), (jnp, "median"),
    (jnp, "trapezoid"),
    # norms / linalg solvers (reference FP32: cholesky/inverse/norm/...)
    (jla, "norm"), (jla, "cholesky"), (jla, "inv"), (jla, "pinv"),
    (jla, "svd"), (jla, "eigh"), (jla, "qr"), (jla, "solve"),
    (jla, "lstsq"), (jla, "det"), (jla, "slogdet"), (jla, "matrix_power"),
    (jla, "cond"),
    # softmax family / exp-based activations (functional_overrides FP32)
    (jnn, "softmax"), (jnn, "log_softmax"), (jnn, "logsumexp"),
    (jnn, "standardize"), (jnn, "softplus"), (jnn, "soft_sign"),
    (jnn, "sigmoid"), (jnn, "log_sigmoid"), (jnn, "silu"), (jnn, "swish"),
    (jnn, "gelu"), (jnn, "celu"), (jnn, "elu"), (jnn, "selu"), (jnn, "glu"),
    # losses (safe logit-space BCE stays fp32-wrapped, never banned).
    # Both the defining module and the package re-export are patched —
    # a name bound at import time in ops/__init__ would otherwise bypass
    # the wrappers.
    (_ops_losses, "binary_cross_entropy_with_logits"),
]

# Sequence promotion (reference SEQUENCE_CASTS = cat/stack) + multi-arg ops
# whose operands must agree (reference CASTS promote list).
_PROMOTE_LIST = [
    (jnp, "concatenate"), (jnp, "stack"), (jnp, "hstack"), (jnp, "vstack"),
    (jnp, "dstack"), (jnp, "column_stack"), (jnp, "append"),
    (jnp, "where"), (jnp, "cross"),
]

# Probability-space BCE needs the full float range: banned under fp16
# (reference functional_overrides.py:59-70), run in fp32 under bf16.
_BANNED_LIST = [
    (_ops_losses, "binary_cross_entropy"),
]

from .. import ops as _ops_pkg  # noqa: E402  (package re-exports)
_FP32_LIST.append((_ops_pkg, "binary_cross_entropy_with_logits"))
_BANNED_LIST.append((_ops_pkg, "binary_cross_entropy"))

_patched = []  # (module, name, original)


def init(enabled=True, verbose=False, allow_banned=False, half_dtype=jnp.bfloat16):
    """Enable the O1 autocast policy and patch the jnp/lax cast lists.

    Reference ``apex/amp/amp.py:68-177`` (``amp.init``).  Idempotent.
    """
    _amp_state.autocast_enabled = enabled
    _amp_state.autocast_dtype = half_dtype
    _amp_state.allow_banned = allow_banned
    if verbose:
        _amp_state.verbosity = 2
    if _patched:
        return

    def _entries(lst):
        # Tolerate jax-version drift: skip absent entry points.
        return ((mod, name, getattr(mod, name)) for mod, name in lst
                if hasattr(mod, name))

    for mod, name, orig in _entries(_HALF_LIST):
        setattr(mod, name, make_cast_wrapper(orig, "half", name))
        _patched.append((mod, name, orig))
    for mod, name, orig in _entries(_FP32_LIST):
        setattr(mod, name, make_cast_wrapper(orig, jnp.float32, name))
        _patched.append((mod, name, orig))
    for mod, name, orig in _entries(_PROMOTE_LIST):
        setattr(mod, name, make_promote_wrapper(orig))
        _patched.append((mod, name, orig))
    for mod, name, orig in _entries(_BANNED_LIST):
        setattr(mod, name, make_banned_wrapper(orig, name))
        _patched.append((mod, name, orig))


def shutdown():
    """Undo ``init``: restore originals and disable the policy."""
    _amp_state.autocast_enabled = False
    while _patched:
        mod, name, orig = _patched.pop()
        setattr(mod, name, orig)


class disable_casts:
    """Context manager disabling the autocast policy (reference
    ``handle.py:160-164``).  Trace-time only — see module docstring."""
    def __enter__(self):
        self._saved = _amp_state.autocast_enabled
        _amp_state.autocast_enabled = False
        return self

    def __exit__(self, *exc):
        _amp_state.autocast_enabled = self._saved
        return False


# -- user registries ----------------------------------------------------------

def register_half_function(module, name):
    """Wrap ``module.name`` to run in the half dtype under autocast
    (reference ``amp.py:46-51``)."""
    orig = getattr(module, name)
    setattr(module, name, make_cast_wrapper(orig, "half", name))
    _patched.append((module, name, orig))


def register_float_function(module, name):
    orig = getattr(module, name)
    setattr(module, name, make_cast_wrapper(orig, jnp.float32, name))
    _patched.append((module, name, orig))


def register_promote_function(module, name):
    orig = getattr(module, name)
    setattr(module, name, make_promote_wrapper(orig))
    _patched.append((module, name, orig))


def register_banned_function(module, name):
    orig = getattr(module, name)
    setattr(module, name, make_banned_wrapper(orig, name))
    _patched.append((module, name, orig))


# Decorator forms (reference amp.py:30-42).
def half_function(fn):
    return make_cast_wrapper(fn, "half")


def float_function(fn):
    return make_cast_wrapper(fn, jnp.float32)


def promote_function(fn):
    return make_promote_wrapper(fn)
