"""``amp.scale_loss`` — the backward context, imperative API.

TPU-native equivalent of reference ``apex/amp/handle.py:13-155``.  In JAX
gradients come from ``jax.grad`` rather than ``.backward()`` side effects, so
the context manager yields a scaled loss value and the user delivers the
gradients of that scaled loss to the optimizer inside the block::

    loss, grads = optimizer.value_and_grad(loss_fn)(batch)   # grads pre-scaled
    with amp.scale_loss(loss, optimizer) as scaled_loss:
        optimizer.backward(grads)
    optimizer.step()

On exit the context runs each optimizer's ``_post_amp_backward`` (unscale
bf16 grads into fp32 master grads — reference ``_process_optimizer.py:
153-194``), updates the loss scale, and on overflow arms a one-shot skip of
``optimizer.step`` (reference ``handle.py:126-151`` patches ``step``; here the
optimizer holds a ``_skip_next_step`` latch that restores itself after one
step).

The fully-jitted path does not use this context at all — see
``apex_tpu.training.make_train_step`` where scaling, unscale, scale update and
the masked (skip-aware) optimizer update compile into one XLA program.
"""

from __future__ import annotations

import contextlib

from ._amp_state import _amp_state, maybe_print
from . import autocast


@contextlib.contextmanager
def scale_loss(loss,
               optimizers,
               loss_id: int = 0,
               model=None,
               delay_unscale: bool = False,
               delay_overflow_check: bool = False):
    """Scale ``loss`` by the current loss scale and manage the unscale /
    scale-update / skip-step epilogue.

    ``delay_unscale`` / ``delay_overflow_check`` support gradient
    accumulation exactly like the reference (only unscale+update on the final
    micro-batch).
    """
    if _amp_state.opt_properties is None or not _amp_state.opt_properties.enabled:
        yield loss
        return

    if isinstance(optimizers, (list, tuple)):
        opt_list = list(optimizers)
    else:
        opt_list = [optimizers]

    loss_scaler = _amp_state.loss_scalers[loss_id]

    for opt in opt_list:
        if hasattr(opt, "_prepare_amp_backward"):
            opt._prepare_amp_backward()

    yield loss_scaler.scale_loss(loss)

    if delay_unscale:
        # Grad accumulation: leave scaled grads stashed (reference
        # handle.py:103-108 commentary); nothing else to do this micro-step.
        return

    for opt in opt_list:
        if hasattr(opt, "_post_amp_backward"):
            opt._post_amp_backward(loss_scaler)

    if not delay_overflow_check:
        # The scale state machine updates on device NOW; the host READ of
        # the overflow flag is deferred to each optimizer's step(), which
        # batches all pending scalers' flags into one transfer (the
        # reference reads per scaler, scaler.py:199-200 — microseconds on
        # GPU, a whole round-trip each on a tunneled chip).  Optimizers
        # without the deferral hook fall back to an immediate read.
        flag = loss_scaler.update_scale_deferred()
        if flag is not None:
            deferrable = all(hasattr(opt, "_note_pending_overflow")
                             for opt in opt_list)
            if deferrable:
                for opt in opt_list:
                    opt._note_pending_overflow(flag, loss_id)
            else:
                # Any optimizer without the deferral hook forces a read
                # NOW — and once the flag is on the host there is nothing
                # left to batch, so arm the hooked optimizers eagerly too
                # rather than paying a second read at their step().
                import jax

                if bool(jax.device_get(flag)):  # jaxlint: disable=J001 -- fallback for optimizers without the deferral hook: the flag must be host-side NOW to arm the skip latch
                    for opt in opt_list:
                        if hasattr(opt, "_arm_skip_step"):
                            opt._arm_skip_step()
                    maybe_print(
                        "Gradient overflow.  Skipping step, loss scaler "
                        "{} reducing loss scale to {}".format(
                            loss_id, loss_scaler.loss_scale()))

    # Weight-cast cache dropped once per iteration (reference handle.py:153-155).
    autocast.clear_cast_cache()


# Re-export for `from apex_tpu.amp import disable_casts` parity.
disable_casts = autocast.disable_casts


class AmpHandle:
    """Legacy handle API (reference ``handle.py:167-270``)."""

    def __init__(self, loss_scale="dynamic", enable_caching=True, verbose=False):
        self._enable_caching = enable_caching
        self._verbose = verbose
        from .loss_scaler import LossScaler
        self._loss_scaler = LossScaler(loss_scale)
        self._default_scaler = self._loss_scaler
        self._is_active = True
        self._all_wrappers = []

    def is_active(self):
        return self._is_active

    @contextlib.contextmanager
    def _disable_casts(self):
        with autocast.disable_casts():
            yield

    def wrap_optimizer(self, optimizer, num_loss=1):
        self._default_scaler = None
        from .opt import OptimWrapper
        return OptimWrapper(optimizer, self, num_loss)

    @contextlib.contextmanager
    def scale_loss(self, loss, optimizer):
        if not self.is_active():
            yield loss
            return
        yield self._loss_scaler.scale_loss(loss)
        if hasattr(optimizer, "_post_amp_backward"):
            optimizer._post_amp_backward(self._loss_scaler)
        self._loss_scaler.update_scale_sync()
        if not self._enable_caching:
            autocast.clear_cast_cache()

    @property
    def loss_scale(self):
        return self._loss_scaler.loss_scale()

    def _clear_cache(self):
        autocast.clear_cast_cache()

    def _deactivate(self):
        self._is_active = False


class NoOpHandle:
    def is_active(self):
        return False

    @contextlib.contextmanager
    def _disable_casts(self):
        yield

    def wrap_optimizer(self, optimizer, num_loss=1):
        return optimizer

    @contextlib.contextmanager
    def scale_loss(self, loss, optimizer):
        yield loss

    @property
    def loss_scale(self):
        return 1.0

    def _deactivate(self):
        pass
