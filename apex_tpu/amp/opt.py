"""Legacy ``OptimWrapper`` (reference ``apex/amp/opt.py:9-103``).

Old handle-based API: per-loss scalers with cached gradients between losses.
Kept for drop-in compatibility; new code should use ``amp.initialize`` +
``amp.scale_loss`` or the fully-jitted ``apex_tpu.training`` path.
"""

from __future__ import annotations

import contextlib

from ._amp_state import maybe_print
from .loss_scaler import LossScaler


class OptimWrapper:
    def __init__(self, optimizer, amp_handle, num_loss):
        self._optimizer = optimizer
        self._amp_handle = amp_handle
        self._num_loss = num_loss
        self._loss_idx = 0
        self._skip_next = [False] * num_loss
        self._loss_scaler = [LossScaler("dynamic") for _ in range(num_loss)]

    @contextlib.contextmanager
    def scale_loss(self, loss):
        if not self._amp_handle.is_active():
            yield loss
            return

        scaler = self._loss_scaler[self._loss_idx]
        yield scaler.scale_loss(loss)

        if hasattr(self._optimizer, "_post_amp_backward"):
            self._optimizer._post_amp_backward(scaler)
        self._skip_next[self._loss_idx] = scaler.update_scale_sync()
        self._loss_idx = (self._loss_idx + 1) % self._num_loss

    def step(self, closure=None):
        if not self._amp_handle.is_active():
            return self._optimizer.step(closure)
        if any(self._skip_next):
            maybe_print("Gradient overflow, skipping update")
            self._skip_next = [False] * self._num_loss
            return None
        return self._optimizer.step(closure)

    # Delegation ------------------------------------------------------------
    def __getattr__(self, attr):
        return getattr(self._optimizer, attr)

    @property
    def loss_scale(self):
        if self._num_loss == 1:
            return self._loss_scaler[0].loss_scale()
        raise NotImplementedError("Current loss scale is ambiguous with "
                                  "multiple losses")
