"""Opt-level frontend: the ``Properties`` option struct and the O0-O3 presets.

TPU-native re-design of the reference's opt-level state machine
(``apex/amp/frontend.py:7-191``).  Semantics preserved:

* ``Properties`` validates every attribute assignment and cross-checks
  incompatible combinations (reference ``frontend.py:31-97``).
* ``O0``..``O3`` are preset objects; ``amp.initialize`` starts from a preset and
  applies user overrides on top (reference ``frontend.py:102-191``).

TPU-first differences (deliberate, not omissions):

* The half type is **bfloat16**, not float16.  bf16 shares float32's exponent
  range, so *static* loss scaling (scale=1) is numerically safe and is the
  default for every opt level; the full dynamic-scaler state machine is retained
  for API/checkpoint parity and for users who opt into float16.
* ``patch_torch_functions`` becomes ``patch_functions``: O1 on TPU is a dtype
  *policy* consulted by ``apex_tpu`` ops and user-registered functions (see
  ``apex_tpu/amp/autocast.py``) rather than runtime monkey-patching, which is
  hostile to ``jax.jit`` tracing.
"""

from __future__ import annotations

import jax.numpy as jnp


class AmpOptionError(ValueError):
    pass


_DTYPE_NAMES = {
    "float32": jnp.float32,
    "fp32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "bf16": jnp.bfloat16,
    "float16": jnp.float16,
    "fp16": jnp.float16,
}


def _canonical_dtype(value):
    """Accept jnp dtypes, numpy dtypes or string names; return a jnp dtype or None."""
    if value is None or value is False:
        return None
    if isinstance(value, str):
        try:
            return _DTYPE_NAMES[value.lower()]
        except KeyError:
            raise AmpOptionError(
                "Unsupported cast type {!r}; expected one of {}".format(
                    value, sorted(_DTYPE_NAMES)))
    return jnp.dtype(value).type


class Properties:
    """Mutable option struct with consistency checking on every assignment.

    Mirrors reference ``apex/amp/frontend.py:7-97``: unknown options raise,
    and a handful of combinations are rejected eagerly so failures are timely
    rather than appearing as silent misbehavior mid-training.
    """

    _FIELDS = (
        "enabled",
        "opt_level",
        "cast_model_type",
        "patch_functions",
        "keep_batchnorm_fp32",
        "master_weights",
        "loss_scale",
        "cast_model_outputs",
        "quantize",
    )

    def __init__(self):
        self.__dict__["options"] = {
            "enabled": False,
            "opt_level": None,
            "cast_model_type": None,
            "patch_functions": False,
            "keep_batchnorm_fp32": None,
            "master_weights": None,
            "loss_scale": 1.0,
            "cast_model_outputs": None,
            "quantize": False,
        }

    # -- access -------------------------------------------------------------
    def _update_options_dict(self, new_options):
        for k, v in new_options.items():
            if k in self.options:
                setattr(self, k, v)
            else:
                raise AmpOptionError("Tried to set unexpected option {!r}".format(k))

    def __getattr__(self, name):
        if "options" in self.__dict__ and name in self.__dict__["options"]:
            return self.__dict__["options"][name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name not in self.__dict__.get("options", {}):
            raise AmpOptionError("Tried to set unexpected option {!r}".format(name))
        if name == "cast_model_type":
            value = _canonical_dtype(value)
            if self.opt_level == "O1" and value is not None:
                raise AmpOptionError(
                    "O1 inserts casts around individual ops rather than casting the "
                    "model; cast_model_type is not allowed with opt_level O1.")
        elif name == "patch_functions":
            if value and self.opt_level in ("O2", "O3", "O4"):
                raise AmpOptionError(
                    "patch_functions (the O1 autocast policy) cannot be combined "
                    "with a whole-model cast (O2/O3/O4).")
            if value and self.options.get("quantize"):
                raise AmpOptionError(
                    "patch_functions (the O1 autocast policy) cannot be "
                    "combined with quantize (the O4 int8 path composes "
                    "with a whole-model cast, O2 semantics).")
        elif name == "keep_batchnorm_fp32":
            if isinstance(value, str):
                if value.lower() not in ("true", "false"):
                    raise AmpOptionError(
                        "keep_batchnorm_fp32 must be a bool or the strings "
                        "'True'/'False', got {!r}".format(value))
                value = value.lower() == "true"
            if value is not None and not isinstance(value, bool):
                raise AmpOptionError(
                    "keep_batchnorm_fp32 must be a bool, a 'True'/'False' string, "
                    "or None, got {!r}".format(value))
        elif name == "loss_scale":
            if value != "dynamic" and value is not None:
                value = float(value)
                if value <= 0.0:
                    raise AmpOptionError("loss_scale must be positive")
        elif name == "cast_model_outputs":
            value = _canonical_dtype(value)
        elif name == "quantize":
            if not isinstance(value, bool):
                raise AmpOptionError(
                    "quantize must be a bool, got {!r}".format(value))
            if value and self.patch_functions:
                raise AmpOptionError(
                    "quantize (the O4 int8 path) composes with a "
                    "whole-model cast (O2 semantics), not with the O1 "
                    "autocast policy.")
        self.__dict__["options"][name] = value

    def __repr__(self):
        return "Properties({})".format(
            ", ".join("{}={!r}".format(k, v) for k, v in self.options.items()))

    # Convenience predicates used throughout the package.
    @property
    def half_dtype(self):
        """The reduced-precision dtype in play (cast_model_type for O2/O3,
        bfloat16 for the O1 policy), or None for O0."""
        if self.cast_model_type is not None:
            return self.cast_model_type
        if self.patch_functions:
            return jnp.bfloat16
        return None


def _make_preset(name, doc, **opts):
    def build():
        p = Properties()
        p.__dict__["options"]["enabled"] = True
        p.__dict__["options"]["opt_level"] = name
        for k, v in opts.items():
            setattr(p, k, v)
        return p
    build.__name__ = name
    build.__doc__ = doc
    return build


# Presets (reference frontend.py:102-191).  Note bf16 + static scale defaults.
O4 = _make_preset(
    "O4", "Calibrated int8 mixed precision (ISSUE 13): EXACT O2 storage "
          "semantics — bf16 model cast, fp32 batchnorm, fp32 master "
          "weights, loss scaling — plus annotated matmuls (the models' "
          "quant= hook) running the int8 quantized kernels.  Without a "
          "frozen calibration every site falls back bitwise to O2.",
    cast_model_type=jnp.bfloat16,
    patch_functions=False,
    keep_batchnorm_fp32=True,
    master_weights=True,
    loss_scale=1.0,
    quantize=True,
)

O3 = _make_preset(
    "O3", "Pure reduced precision (bf16). Fast but no fp32 batchnorm safety net.",
    cast_model_type=jnp.bfloat16,
    patch_functions=False,
    keep_batchnorm_fp32=False,
    master_weights=False,
    loss_scale=1.0,
)

O2 = _make_preset(
    "O2", "'Almost bf16' mixed precision: bf16 model with fp32 batchnorm, "
          "fp32 master weights, static loss scale 1.0 (dynamic on request).",
    cast_model_type=jnp.bfloat16,
    patch_functions=False,
    keep_batchnorm_fp32=True,
    master_weights=True,
    loss_scale=1.0,
)

O1 = _make_preset(
    "O1", "Insert casts per-op via the autocast policy: matmul/conv run bf16, "
          "reductions and losses run fp32. Model weights stay fp32.",
    cast_model_type=None,
    patch_functions=True,
    keep_batchnorm_fp32=None,
    master_weights=False,
    loss_scale=1.0,
)

O0 = _make_preset(
    "O0", "Pure fp32 baseline.",
    cast_model_type=jnp.float32,
    patch_functions=False,
    keep_batchnorm_fp32=None,
    master_weights=False,
    loss_scale=1.0,
)

opt_levels = {"O4": O4, "O3": O3, "O2": O2, "O1": O1, "O0": O0}
