"""Loader for the native C++ runtime (``apex_tpu/csrc/apex_runtime.cpp``).

Mirrors the reference's two-tier install contract (SURVEY.md §1: "a
Python-only install must remain fully functional"): the .so is built on
first use with g++ if available; every entry point has a numpy fallback, and
``available`` reports which tier is active — the analog of
``multi_tensor_applier.available``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_CSRC = os.path.join(os.path.dirname(__file__), "csrc")
_SO = os.path.join(_CSRC, "build", "libapex_tpu_runtime.so")
_lock = threading.Lock()
_lib = None
available = False


def _build() -> Optional[str]:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    src = os.path.join(_CSRC, "apex_runtime.cpp")
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
           src, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _SO
    except Exception:
        return None


def _load():
    global _lib, available
    with _lock:
        if _lib is not None or available is None:
            return _lib
        if os.environ.get("APEX_TPU_DISABLE_NATIVE"):
            # Force the Python tier (install-matrix / docker/run_matrix.sh
            # tiers 2 and 4): without this the lazy builder would simply
            # rebuild a deleted .so whenever g++ is present, making a
            # "no-native" tier silently native again.
            available = False
            _lib = False
            return None
        path = _SO if os.path.exists(_SO) else _build()
        if path is None:
            available = False
            _lib = False
            return None
        try:
            lib = ctypes.CDLL(path)
            assert lib.apex_runtime_abi_version() == 1
        except Exception:
            available = False
            _lib = False
            return None
        lib.apex_flatten.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int]
        lib.apex_unflatten.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int]
        lib.apex_u8_to_f32_nhwc.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int]
        _lib = lib
        available = True
        return lib


_DEFAULT_THREADS = max(1, (os.cpu_count() or 1) - 1)


def flatten(arrays: Sequence[np.ndarray], threads: int = _DEFAULT_THREADS
            ) -> np.ndarray:
    """Pack host arrays into one contiguous byte buffer (reference
    ``apex_C.flatten``, csrc/flatten_unflatten.cpp)."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    sizes = np.array([a.nbytes for a in arrays], np.int64)
    out = np.empty(int(sizes.sum()), np.uint8)
    lib = _load()
    if lib:
        srcs = (ctypes.c_void_p * len(arrays))(
            *[a.ctypes.data for a in arrays])
        lib.apex_flatten(srcs, sizes.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64)), len(arrays),
            out.ctypes.data_as(ctypes.c_void_p), threads)
    else:
        off = 0
        for a, n in zip(arrays, sizes):
            out[off:off + n] = a.view(np.uint8).reshape(-1)
            off += int(n)
    return out


def unflatten(flat: np.ndarray, like: Sequence[np.ndarray],
              threads: int = _DEFAULT_THREADS) -> List[np.ndarray]:
    """Split a flat byte buffer back into arrays shaped like ``like``
    (reference ``apex_C.unflatten``)."""
    flat = np.ascontiguousarray(flat.view(np.uint8).reshape(-1))
    outs = [np.empty(a.shape, a.dtype) for a in like]
    sizes = np.array([a.nbytes for a in outs], np.int64)
    if int(sizes.sum()) != flat.nbytes:
        raise ValueError(f"flat buffer has {flat.nbytes} bytes, "
                         f"targets need {int(sizes.sum())}")
    lib = _load()
    if lib:
        dsts = (ctypes.c_void_p * len(outs))(
            *[o.ctypes.data for o in outs])
        lib.apex_unflatten(flat.ctypes.data_as(ctypes.c_void_p),
                           sizes.ctypes.data_as(
                               ctypes.POINTER(ctypes.c_int64)),
                           len(outs), dsts, threads)
    else:
        off = 0
        for o, n in zip(outs, sizes):
            o.view(np.uint8).reshape(-1)[:] = flat[off:off + int(n)]
            off += int(n)
    return outs


def u8_to_f32_nhwc(images: np.ndarray, mean: Sequence[float],
                   std: Sequence[float],
                   threads: int = _DEFAULT_THREADS) -> np.ndarray:
    """Normalize a uint8 NHWC batch to float32: ``(x/255 - mean)/std`` —
    the input-pipeline decode epilogue (the reference's examples lean on
    DALI for this)."""
    images = np.ascontiguousarray(images, np.uint8)
    n, h, w, c = images.shape
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if mean.size != c or std.size != c:
        raise ValueError("mean/std length must equal channel count")
    out = np.empty((n, h, w, c), np.float32)
    lib = _load()
    if lib:
        lib.apex_u8_to_f32_nhwc(
            images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, h * w, c,
            mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), threads)
    else:
        out[:] = (images.astype(np.float32) / 255.0 - mean) / std
    return out
