"""Loader for the native C++ runtime (``apex_tpu/csrc/apex_runtime.cpp``).

Mirrors the reference's two-tier install contract (SURVEY.md §1: "a
Python-only install must remain fully functional"): the .so is built on
first use with g++ if available; every entry point has a numpy fallback, and
``available`` reports which tier is active — the analog of
``multi_tensor_applier.available``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_CSRC = os.path.join(os.path.dirname(__file__), "csrc")
_SO = os.path.join(_CSRC, "build", "libapex_tpu_runtime.so")
_ABI_VERSION = 2      # v2: synth_u8 + crop_flip_norm (ISSUE 3)
_lock = threading.Lock()
_lib = None
available = False


def _build() -> Optional[str]:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    src = os.path.join(_CSRC, "apex_runtime.cpp")
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
           src, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _SO
    except Exception:
        return None


def _load():
    global _lib, available
    with _lock:
        if _lib is not None or available is None:
            return _lib
        if os.environ.get("APEX_TPU_DISABLE_NATIVE"):
            # Force the Python tier (install-matrix / docker/run_matrix.sh
            # tiers 2 and 4): without this the lazy builder would simply
            # rebuild a deleted .so whenever g++ is present, making a
            # "no-native" tier silently native again.
            available = False
            _lib = False
            return None
        src = os.path.join(_CSRC, "apex_runtime.cpp")
        try:
            stale = (not os.path.exists(_SO)
                     or os.path.getmtime(_SO) < os.path.getmtime(src))
        except OSError:
            # Prebuilt .so shipped without the source: nothing to
            # compare against (or rebuild from) — trust the ABI check.
            stale = not os.path.exists(_SO)
        path = _build() if stale else _SO
        if path is None and os.path.exists(_SO):
            # mtime said stale but no compiler is available (prebuilt
            # .so shipped without g++; checkouts don't preserve mtimes):
            # trust the ABI-version check below to judge the existing
            # build rather than silently dropping to the numpy tier.
            path = _SO
        if path is None:
            available = False
            _lib = False
            return None
        try:
            lib = ctypes.CDLL(path)
            if lib.apex_runtime_abi_version() != _ABI_VERSION:
                # A stale build dir from an older checkout (mtime lies
                # across git checkouts): rebuild once, then give up.
                # Unlink first — rebuilding IN PLACE keeps the inode,
                # and dlopen dedups by (st_dev, st_ino), so a second
                # CDLL of the same path would return the stale handle.
                try:
                    os.remove(_SO)
                except OSError:
                    pass
                path = _build()
                lib = ctypes.CDLL(path) if path else None
                assert lib is not None \
                    and lib.apex_runtime_abi_version() == _ABI_VERSION
        except Exception:
            available = False
            _lib = False
            return None
        lib.apex_flatten.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int]
        lib.apex_unflatten.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int]
        lib.apex_u8_to_f32_nhwc.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int]
        lib.apex_synth_u8.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_int]
        lib.apex_crop_flip_norm_u8_f32.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int]
        _lib = lib
        available = True
        return lib


_DEFAULT_THREADS = max(1, (os.cpu_count() or 1) - 1)


def flatten(arrays: Sequence[np.ndarray], threads: int = _DEFAULT_THREADS
            ) -> np.ndarray:
    """Pack host arrays into one contiguous byte buffer (reference
    ``apex_C.flatten``, csrc/flatten_unflatten.cpp)."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    sizes = np.array([a.nbytes for a in arrays], np.int64)
    out = np.empty(int(sizes.sum()), np.uint8)
    lib = _load()
    if lib:
        srcs = (ctypes.c_void_p * len(arrays))(
            *[a.ctypes.data for a in arrays])
        lib.apex_flatten(srcs, sizes.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64)), len(arrays),
            out.ctypes.data_as(ctypes.c_void_p), threads)
    else:
        off = 0
        for a, n in zip(arrays, sizes):
            out[off:off + n] = a.view(np.uint8).reshape(-1)
            off += int(n)
    return out


def unflatten(flat: np.ndarray, like: Sequence[np.ndarray],
              threads: int = _DEFAULT_THREADS) -> List[np.ndarray]:
    """Split a flat byte buffer back into arrays shaped like ``like``
    (reference ``apex_C.unflatten``)."""
    flat = np.ascontiguousarray(flat.view(np.uint8).reshape(-1))
    outs = [np.empty(a.shape, a.dtype) for a in like]
    sizes = np.array([a.nbytes for a in outs], np.int64)
    if int(sizes.sum()) != flat.nbytes:
        raise ValueError(f"flat buffer has {flat.nbytes} bytes, "
                         f"targets need {int(sizes.sum())}")
    lib = _load()
    if lib:
        dsts = (ctypes.c_void_p * len(outs))(
            *[o.ctypes.data for o in outs])
        lib.apex_unflatten(flat.ctypes.data_as(ctypes.c_void_p),
                           sizes.ctypes.data_as(
                               ctypes.POINTER(ctypes.c_int64)),
                           len(outs), dsts, threads)
    else:
        off = 0
        for o, n in zip(outs, sizes):
            o.view(np.uint8).reshape(-1)[:] = flat[off:off + int(n)]
            off += int(n)
    return outs


def u8_to_f32_nhwc(images: np.ndarray, mean: Sequence[float],
                   std: Sequence[float],
                   threads: int = _DEFAULT_THREADS) -> np.ndarray:
    """Normalize a uint8 NHWC batch to float32: ``(x/255 - mean)/std`` —
    the input-pipeline decode epilogue (the reference's examples lean on
    DALI for this)."""
    images = np.ascontiguousarray(images, np.uint8)
    n, h, w, c = images.shape
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if mean.size != c or std.size != c:
        raise ValueError("mean/std length must equal channel count")
    out = np.empty((n, h, w, c), np.float32)
    lib = _load()
    if lib:
        lib.apex_u8_to_f32_nhwc(
            images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, h * w, c,
            mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), threads)
    else:
        out[:] = (images.astype(np.float32) / 255.0 - mean) / std
    return out


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 over a uint64 lattice — the numpy mirror of
    the C++ generator, bit-identical by construction."""
    z = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def synth_bytes(nbytes: int, seed: int,
                threads: int = _DEFAULT_THREADS) -> np.ndarray:
    """Counter-based pseudorandom byte stream: block ``i`` of 8 bytes is
    ``splitmix64(seed + i)``.  Native tier fills the buffer in parallel
    with zero GIL time; the numpy fallback computes the same lattice
    (both little-endian — asserted below, not assumed).  This is the
    synthetic-batch generator backing :func:`apex_tpu.data.
    synthetic_imagenet` (ISSUE 3: Python-side ``np.random`` generation
    was a measurable producer-side GIL burn)."""
    if nbytes < 0:
        raise ValueError(f"nbytes must be >= 0, got {nbytes}")
    import sys
    assert sys.byteorder == "little", \
        "synth_bytes assumes a little-endian host (the C++ tier memcpys " \
        "uint64 blocks); add a byteswap for big-endian targets"
    seed = int(seed) & 0xFFFFFFFFFFFFFFFF
    out = np.empty(nbytes, np.uint8)
    lib = _load()
    if lib:
        lib.apex_synth_u8(
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            nbytes, ctypes.c_uint64(seed), threads)
    else:
        blocks = (nbytes + 7) // 8
        lattice = np.arange(blocks, dtype=np.uint64) + np.uint64(seed)
        with np.errstate(over="ignore"):
            words = _splitmix64(lattice)
        out[:] = words.view(np.uint8)[:nbytes]
    return out


def crop_flip_normalize(images: np.ndarray, out_size: int,
                        offsets: np.ndarray, flips: np.ndarray,
                        mean: Sequence[float], std: Sequence[float],
                        threads: int = _DEFAULT_THREADS) -> np.ndarray:
    """Fused augmentation epilogue: per-image ``out_size`` crop at
    ``offsets[i] = (oy, ox)``, horizontal flip where ``flips[i]``, and
    the ``(x/255 - mean)/std`` normalize — ONE pass over the output
    pixels (the reference delegates exactly this fusion to DALI).
    ``images`` is uint8 NHWC; returns float32 ``[n, out, out, c]``.
    Randomness is the CALLER's job (pass offsets/flips), so both tiers
    are deterministic and bit-comparable."""
    images = np.ascontiguousarray(images, np.uint8)
    n, h, w, c = images.shape
    oh = ow = int(out_size)
    if oh > h or ow > w:
        raise ValueError(f"crop {oh}x{ow} exceeds image {h}x{w}")
    offsets = np.ascontiguousarray(offsets, np.int32).reshape(n, 2)
    if (offsets[:, 0] < 0).any() or (offsets[:, 0] > h - oh).any() \
            or (offsets[:, 1] < 0).any() or (offsets[:, 1] > w - ow).any():
        raise ValueError("crop offsets out of bounds")
    flips = np.ascontiguousarray(flips, np.uint8).reshape(n)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if mean.size != c or std.size != c:
        raise ValueError("mean/std length must equal channel count")
    out = np.empty((n, oh, ow, c), np.float32)
    lib = _load()
    if lib:
        lib.apex_crop_flip_norm_u8_f32(
            images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, h, w, c, oh, ow,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            flips.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), threads)
    else:
        for i in range(n):
            oy, ox = int(offsets[i, 0]), int(offsets[i, 1])
            crop = images[i, oy:oy + oh, ox:ox + ow]
            if flips[i]:
                crop = crop[:, ::-1]
            out[i] = (crop.astype(np.float32) / 255.0 - mean) / std
    return out
