"""RNN cells as flax modules — one step: ``(carry, x) -> (carry, out)``.

Re-design of reference ``apex/RNN/cells.py`` + the cell zoo consumed by
``apex/RNN/RNNBackend.py:232-365`` (torch ``LSTMCell``/``GRUCell``/
``RNNReLUCell``/``RNNTanhCell`` + the multiplicative ``mLSTMCell``
``cells.py:12-81``).  The reference relies on cuDNN fused pointwise kernels;
under XLA the gate math fuses automatically, and the time loop is
``lax.scan`` (see models.py) so the whole sequence compiles to one program.

Gate matmuls run in the module dtype (bf16 on TPU → MXU); the cell state is
carried in fp32 for additive stability, matching the reference's fp32
hidden-state init (RNNBackend.py:309-328).
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp


def _dense(features, use_bias, dtype, name):
    return nn.Dense(features, use_bias=use_bias, dtype=dtype,
                    param_dtype=jnp.float32, name=name)


class RNNReLUCell(nn.Module):
    """h' = relu(W_ih x + W_hh h + b)."""
    hidden_size: int
    bias: bool = True
    dtype: Any = jnp.float32
    act = staticmethod(nn.relu)

    @nn.compact
    def __call__(self, carry, x):
        (h,) = carry
        g = (_dense(self.hidden_size, self.bias, self.dtype, "ih")(x)
             + _dense(self.hidden_size, self.bias, self.dtype, "hh")(
                 h.astype(self.dtype)))
        h = self.act(g).astype(jnp.float32)
        return (h,), h

    @staticmethod
    def n_hidden_states():
        return 1


class RNNTanhCell(RNNReLUCell):
    act = staticmethod(nn.tanh)


class LSTMCell(nn.Module):
    hidden_size: int
    bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, carry, x):
        h, c = carry
        gates = (_dense(4 * self.hidden_size, self.bias, self.dtype, "ih")(x)
                 + _dense(4 * self.hidden_size, self.bias, self.dtype, "hh")(
                     h.astype(self.dtype)))
        i, f, g, o = jnp.split(gates.astype(jnp.float32), 4, axis=-1)
        c = nn.sigmoid(f) * c + nn.sigmoid(i) * nn.tanh(g)
        h = nn.sigmoid(o) * nn.tanh(c)
        return (h, c), h

    @staticmethod
    def n_hidden_states():
        return 2


class GRUCell(nn.Module):
    hidden_size: int
    bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, carry, x):
        (h,) = carry
        hd = h.astype(self.dtype)
        ri = _dense(2 * self.hidden_size, self.bias, self.dtype, "ih_rz")(x)
        rh = _dense(2 * self.hidden_size, self.bias, self.dtype, "hh_rz")(hd)
        r, z = jnp.split(nn.sigmoid((ri + rh).astype(jnp.float32)), 2, axis=-1)
        n = nn.tanh(
            _dense(self.hidden_size, self.bias, self.dtype, "ih_n")(x)
            .astype(jnp.float32)
            + r * _dense(self.hidden_size, self.bias, self.dtype, "hh_n")(hd)
            .astype(jnp.float32))
        h = (1.0 - z) * n + z * h
        return (h,), h

    @staticmethod
    def n_hidden_states():
        return 1


class mLSTMCell(nn.Module):
    """Multiplicative LSTM (reference ``mLSTMCell`` cells.py:55-81):
    ``m = (W_mih x) * (W_mhh h)``; gates = ``W_ih x + W_hh m``."""
    hidden_size: int
    bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, carry, x):
        h, c = carry
        hd = h.astype(self.dtype)
        m = (_dense(self.hidden_size, False, self.dtype, "mih")(x)
             * _dense(self.hidden_size, False, self.dtype, "mhh")(hd))
        gates = (_dense(4 * self.hidden_size, self.bias, self.dtype, "ih")(x)
                 + _dense(4 * self.hidden_size, self.bias, self.dtype, "hh")(m))
        i, f, g, o = jnp.split(gates.astype(jnp.float32), 4, axis=-1)
        c = nn.sigmoid(f) * c + nn.sigmoid(i) * nn.tanh(g)
        h = nn.sigmoid(o) * nn.tanh(c)
        return (h, c), h

    @staticmethod
    def n_hidden_states():
        return 2
