"""Stacked / bidirectional RNNs with factory functions.

Re-design of reference ``apex/RNN/models.py:20-54`` (LSTM/GRU/ReLU/Tanh/
mLSTM factories) and ``apex/RNN/RNNBackend.py`` (``stackedRNN:90-231``,
``bidirectionalRNN:25-88``).  The reference loops over time steps in Python
with per-module mutable hidden state; here the time loop is ``nn.scan``
(→ ``lax.scan``, one compiled loop, static shapes, TPU-friendly) and hidden
state is explicit — pass ``initial_states`` and get final states back, the
functional version of ``init_hidden``/``detach_hidden``/``reset_hidden``.

Layout: time-major ``[T, B, F]`` like the reference (``batch_first=True``
transposes at the boundary).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Type

import flax.linen as nn
import jax.numpy as jnp

from .cells import GRUCell, LSTMCell, RNNReLUCell, RNNTanhCell, mLSTMCell


class stackedRNN(nn.Module):
    """num_layers cells stacked, scanned over time (reference
    ``stackedRNN.forward`` RNNBackend.py:122-196, incl. inter-layer
    dropout and the reverse flag used by the bidirectional wrapper)."""
    cell_cls: Type[nn.Module]
    hidden_size: int
    num_layers: int = 1
    bias: bool = True
    dropout: float = 0.0
    output_size: Optional[int] = None
    batch_first: bool = False
    dtype: Any = jnp.float32

    def _zero_carry(self, bsz):
        n = self.cell_cls.n_hidden_states()
        zeros = jnp.zeros((bsz, self.hidden_size), jnp.float32)
        return tuple(zeros for _ in range(n))

    @nn.compact
    def __call__(self, inputs, initial_states: Optional[Sequence] = None,
                 *, reverse: bool = False, train: bool = False,
                 collect_hidden: bool = False):
        """``inputs`` [T,B,F] (or [B,T,F] if batch_first).  Returns
        ``(outputs, final_states)`` — outputs [T,B,H], final_states a list
        of per-layer carries (hy[, cy]).  With ``collect_hidden=True``,
        final_states instead holds every timestep's states per layer
        (each leaf [T,B,H] — reference ``stackedRNN.forward``
        RNNBackend.py:122-196 collect_hidden semantics)."""
        if self.batch_first:
            inputs = jnp.swapaxes(inputs, 0, 1)
        if reverse:
            inputs = jnp.flip(inputs, axis=0)
        bsz = inputs.shape[1]
        if initial_states is None:
            initial_states = [self._zero_carry(bsz)
                              for _ in range(self.num_layers)]

        def body(cell, carry, x):
            new_carry, out = cell(carry, x)
            # Per-step carries are scanned out only when collecting; the
            # flag is static so the unused path traces away.
            return new_carry, (out, new_carry if collect_hidden else None)

        scan = nn.scan(
            body,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0, out_axes=0)

        x = inputs
        finals = []
        for layer in range(self.num_layers):
            cell = self.cell_cls(hidden_size=self.hidden_size,
                                 bias=self.bias, dtype=self.dtype,
                                 name=f"layer{layer}")
            carry, (x, all_states) = scan(
                cell, tuple(initial_states[layer]), x)
            finals.append(all_states if collect_hidden else carry)
            if self.dropout > 0 and train and layer < self.num_layers - 1:
                x = nn.Dropout(self.dropout, deterministic=not train)(x)

        if self.output_size is not None and self.output_size != self.hidden_size:
            # reference RNNCell w_ho projection (RNNBackend.py:264-271, :348+)
            x = nn.Dense(self.output_size, dtype=self.dtype,
                         param_dtype=jnp.float32, name="proj")(x)
        if reverse:
            x = jnp.flip(x, axis=0)
        if self.batch_first:
            x = jnp.swapaxes(x, 0, 1)
        return x, finals


class bidirectionalRNN(nn.Module):
    """Forward + reverse stacks, feature-concatenated (reference
    ``bidirectionalRNN`` RNNBackend.py:25-88)."""
    cell_cls: Type[nn.Module]
    hidden_size: int
    num_layers: int = 1
    bias: bool = True
    dropout: float = 0.0
    output_size: Optional[int] = None
    batch_first: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, inputs, initial_states=None, *, train: bool = False):
        kw = dict(cell_cls=self.cell_cls, hidden_size=self.hidden_size,
                  num_layers=self.num_layers, bias=self.bias,
                  dropout=self.dropout, output_size=self.output_size,
                  batch_first=self.batch_first, dtype=self.dtype)
        fwd_init = rev_init = None
        if initial_states is not None:
            fwd_init, rev_init = initial_states
        out_f, fin_f = stackedRNN(**kw, name="fwd")(
            inputs, fwd_init, train=train)
        out_r, fin_r = stackedRNN(**kw, name="bwd")(
            inputs, rev_init, reverse=True, train=train)
        return jnp.concatenate([out_f, out_r], axis=-1), (fin_f, fin_r)


def _factory(cell_cls, input_size, hidden_size, num_layers, bias=True,
             batch_first=False, dropout=0.0, bidirectional=False,
             output_size=None, dtype=jnp.float32):
    # input_size is inferred from data by flax; kept as an arg for reference
    # signature parity (models.py:19-54).
    del input_size
    cls = bidirectionalRNN if bidirectional else stackedRNN
    return cls(cell_cls=cell_cls, hidden_size=hidden_size,
               num_layers=num_layers, bias=bias, dropout=dropout,
               output_size=output_size, batch_first=batch_first, dtype=dtype)


def LSTM(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size=None,
         dtype=jnp.float32):
    return _factory(LSTMCell, input_size, hidden_size, num_layers, bias,
                    batch_first, dropout, bidirectional, output_size, dtype)


def GRU(input_size, hidden_size, num_layers, bias=True, batch_first=False,
        dropout=0.0, bidirectional=False, output_size=None,
        dtype=jnp.float32):
    return _factory(GRUCell, input_size, hidden_size, num_layers, bias,
                    batch_first, dropout, bidirectional, output_size, dtype)


def ReLU(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size=None,
         dtype=jnp.float32):
    return _factory(RNNReLUCell, input_size, hidden_size, num_layers, bias,
                    batch_first, dropout, bidirectional, output_size, dtype)


def Tanh(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size=None,
         dtype=jnp.float32):
    return _factory(RNNTanhCell, input_size, hidden_size, num_layers, bias,
                    batch_first, dropout, bidirectional, output_size, dtype)


def mLSTM(input_size, hidden_size, num_layers, bias=True, batch_first=False,
          dropout=0.0, bidirectional=False, output_size=None,
          dtype=jnp.float32):
    return _factory(mLSTMCell, input_size, hidden_size, num_layers, bias,
                    batch_first, dropout, bidirectional, output_size, dtype)
