"""apex_tpu.RNN — scan-based RNN stack (reference ``apex/RNN``)."""

from .models import LSTM, GRU, ReLU, Tanh, mLSTM          # noqa: F401
from .models import stackedRNN, bidirectionalRNN          # noqa: F401
from .cells import (LSTMCell, GRUCell, RNNReLUCell,       # noqa: F401
                    RNNTanhCell, mLSTMCell)
