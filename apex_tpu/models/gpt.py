"""GPT-style decoder-only causal LM (flax, TPU-first).

Beyond-parity model family (the reference ships no model code): the
long-context training model that exercises the framework's causal flash
attention (``apex_tpu/ops/flash_attention.py``), FusedLayerNorm, the
fused xentropy loss and — through ``attention_impl="ring"`` — sequence
parallelism.  Pre-LN residual blocks, learned positions, weight-tied LM
head; bf16 matmuls with fp32 softmax/norm/loss (the O1 cast-list split,
hard-wired where it matters).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..normalization import FusedLayerNorm


class GPTBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.float32
    attention_impl: str = "flash"
    sp_axis: Optional[str] = None
    num_kv_heads: Optional[int] = None   # GQA: kv heads shared across q heads
    window: Optional[int] = None         # sliding-window local attention
    decode: bool = False                 # KV-cache single-token decode
    cache_len: int = 0
    quant: Any = None                    # ISSUE 13 int8 projection hook

    @nn.compact
    def __call__(self, x, *, kv_cache=None, positions=None):
        d = x.shape[-1]
        h = FusedLayerNorm(normalized_shape=d, name="ln1")(x).astype(x.dtype)
        from .bert import BertSelfAttention, _dense_factory
        attn = BertSelfAttention(self.num_heads, self.dtype,
                                 attention_impl=self.attention_impl,
                                 sp_axis=self.sp_axis, causal=True,
                                 num_kv_heads=self.num_kv_heads,
                                 window=self.window,
                                 decode=self.decode,
                                 cache_len=self.cache_len,
                                 quant=self.quant,
                                 name="attention")
        new_cache = None
        if kv_cache is not None:
            h, new_cache = attn(h, kv_cache=kv_cache, positions=positions)
        else:
            h = attn(h)
        x = x + h
        h = FusedLayerNorm(normalized_shape=d, name="ln2")(x).astype(x.dtype)
        mlp = _dense_factory(self.quant, self.dtype)
        h = mlp("mlp_up", self.mlp_dim)(h)
        h = nn.gelu(h.astype(jnp.float32)).astype(x.dtype)
        h = mlp("mlp_down", d)(h)
        if new_cache is not None:
            return x + h, new_cache
        return x + h


class GPT(nn.Module):
    """Decoder-only LM.  ``__call__(input_ids) -> logits [B, T, V]`` (fp32,
    weight-tied to the token embedding)."""
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 1024
    dtype: Any = jnp.float32
    attention_impl: str = "flash"   # full | blockwise | flash | ring | ulysses
    sp_axis: Optional[str] = None
    num_kv_heads: Optional[int] = None   # GQA (llama-style); None = MHA
    window: Optional[int] = None         # sliding-window local attention
    decode: bool = False                 # KV-cache autoregressive decode
    quant: Any = None                    # ISSUE 13 int8 projection hook

    @nn.compact
    def __call__(self, input_ids, *, kv_caches=None, positions=None):
        b, t = input_ids.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (self.vocab_size, self.hidden_size), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (self.max_len, self.hidden_size), jnp.float32)
        if kv_caches is not None:
            # Incremental forward over externally-owned caches (ISSUE
            # 11): ``kv_caches`` is one ``(k, v)`` dense view per layer
            # ([B, L, n_kv, head_dim] — :func:`init_cache` builds them,
            # the serving engine gathers them from its page pool) and
            # ``positions`` [B] int32 the per-sequence position of the
            # first fresh token.  T may be 1 (decode) or a prompt
            # bucket (prefill).  Returns ``(logits [B, T, V],
            # new_caches)`` — the caller owns persisting the updates.
            if len(kv_caches) != self.num_layers:
                raise ValueError(
                    f"kv_caches has {len(kv_caches)} entries for "
                    f"{self.num_layers} layers")
            if positions is None:
                positions = jnp.zeros((b,), jnp.int32)
            pos = positions[:, None] + jnp.arange(t)[None, :]    # [B, T]
            x = (wte[input_ids] + wpe[pos]).astype(self.dtype)
            new_caches = []
            for i in range(self.num_layers):
                x, c = GPTBlock(self.num_heads, self.mlp_dim, self.dtype,
                                attention_impl=self.attention_impl,
                                sp_axis=None,
                                num_kv_heads=self.num_kv_heads,
                                window=self.window,
                                quant=self.quant,
                                name=f"block_{i}")(
                                    x, kv_cache=kv_caches[i],
                                    positions=positions)
                new_caches.append(c)
            x = FusedLayerNorm(normalized_shape=self.hidden_size,
                               name="ln_f")(x)
            logits = (x.astype(jnp.float32) @ wte.T).astype(jnp.float32)
            return logits, new_caches
        # Checked at trace time — JAX gather clamps out-of-range indices,
        # so an oversized (global) sequence would silently reuse the last
        # position embedding instead of erroring.
        from ..parallel.distributed import _axis_size
        sp = 1 if self.sp_axis is None else _axis_size(self.sp_axis)
        if not self.decode and sp * t > self.max_len:
            raise ValueError(
                f"global sequence {sp} shard(s) x {t} tokens = {sp * t} "
                f"exceeds max_len={self.max_len}")
        if self.decode:
            # single-token step: position = tokens consumed so far.  The
            # caller must bound total steps by max_len (generate() clamps;
            # past it, positions/cache writes saturate silently).
            if t != 1:
                raise ValueError(f"decode consumes ONE token per call, "
                                 f"got {t}")
            live_step = self.has_variable("cache", "pos_index")
            pi = self.variable("cache", "pos_index",
                               lambda: jnp.zeros((), jnp.int32))
            pos = pi.value[None]
            if live_step:           # init trace only creates the counter
                pi.value = pi.value + 1
        else:
            pos = jnp.arange(t)
            if self.sp_axis is not None:
                # Sequence-sharded: this shard's global positions.
                pos = pos + jax.lax.axis_index(self.sp_axis) * t
        x = (wte[input_ids] + wpe[pos][None]).astype(self.dtype)
        for i in range(self.num_layers):
            x = GPTBlock(self.num_heads, self.mlp_dim, self.dtype,
                         attention_impl=self.attention_impl,
                         sp_axis=self.sp_axis,
                         num_kv_heads=self.num_kv_heads,
                         window=self.window,
                         decode=self.decode,
                         cache_len=self.max_len,
                         quant=self.quant,
                         name=f"block_{i}")(x)
        x = FusedLayerNorm(normalized_shape=self.hidden_size,
                           name="ln_f")(x)
        return (x.astype(jnp.float32) @ wte.T).astype(jnp.float32)


def gpt2_small(**kw):
    return GPT(**kw)


def gpt_tiny(**kw):
    kw.setdefault("vocab_size", 1024)
    kw.setdefault("hidden_size", 128)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("mlp_dim", 256)
    kw.setdefault("max_len", 256)
    return GPT(**kw)


def init_cache(model: GPT, batch_size: int, *,
               cache_len: Optional[int] = None, dtype=None):
    """Zeroed external KV-cache views for the incremental forward
    (ISSUE 11): one ``(k, v)`` pair per layer, each
    ``[batch_size, cache_len, n_kv_heads, head_dim]``.

    This is the DENSE view shape ``model.apply(..., kv_caches=...,
    positions=...)`` consumes; the serving engine's paged pool gathers
    into (and scatters out of) exactly this shape per step.  GQA models
    cache only the kv heads — the memory saving is real.  ``cache_len``
    defaults to ``model.max_len`` and must not exceed it (positions past
    it have no learned embedding).  ``dtype`` defaults to the model's
    compute dtype."""
    cache_len = model.max_len if cache_len is None else int(cache_len)
    if cache_len > model.max_len:
        raise ValueError(f"cache_len {cache_len} exceeds the model's "
                         f"max_len {model.max_len}")
    n_kv = model.num_kv_heads or model.num_heads
    head_dim = model.hidden_size // model.num_heads
    dt = model.dtype if dtype is None else dtype
    shape = (batch_size, cache_len, n_kv, head_dim)
    return [(jnp.zeros(shape, dt), jnp.zeros(shape, dt))
            for _ in range(model.num_layers)]


def generate(model: GPT, params, prompt_ids, max_new_tokens: int, *,
             temperature: float = 0.0, rng=None):
    """Autoregressive generation with a KV cache (r3; the reference has no
    model/inference code — SURVEY §5 long-context scope).

    One compiled ``lax.scan`` drives both prefill and generation: each
    step feeds one token (teacher-forced from the prompt while it lasts,
    sampled afterwards) through the ``decode=True`` clone of ``model``,
    whose per-layer caches live in a flax "cache" collection threaded as
    scan carry.  Greedy when ``temperature == 0``, else softmax sampling.

    Returns ``[B, P + max_new_tokens]`` token ids (prompt included),
    truncated at ``model.max_len``.
    """
    import jax.random as jrandom

    if model.sp_axis is not None:
        raise ValueError("generate() decodes full sequences; build the "
                         "model without sp_axis for inference")
    dec = model.clone(decode=True)
    b, p = prompt_ids.shape
    total = min(p + max_new_tokens, model.max_len)
    if rng is None:
        rng = jrandom.PRNGKey(0)

    # cache buffers are zeros by construction — build them from shapes
    # only (a real dec.init would PRNG-initialize a full second parameter
    # set just to throw it away)
    shapes = jax.eval_shape(dec.init, jrandom.PRNGKey(0),
                            jnp.zeros((b, 1), jnp.int32))["cache"]
    cache0 = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    prompt = jnp.asarray(prompt_ids)

    def step(carry, t):
        cache, tok, key = carry
        logits, upd = dec.apply({"params": params, "cache": cache},
                                tok[:, None], mutable=["cache"])
        logits = logits[:, 0]                       # [B, V]
        key, sub = jrandom.split(key)
        if temperature == 0.0:
            sampled = jnp.argmax(logits, axis=-1)
        else:
            sampled = jrandom.categorical(sub, logits / temperature,
                                          axis=-1)
        # teacher-force while the prompt lasts: the NEXT input token
        in_prompt = t + 1 < p
        nxt = jnp.where(
            in_prompt,
            prompt[:, jnp.minimum(t + 1, p - 1)],
            sampled)
        return (upd["cache"], nxt, key), nxt

    (_, _, _), toks = jax.lax.scan(
        step, (cache0, prompt[:, 0], rng), jnp.arange(total - 1))
    return jnp.concatenate([prompt[:, :1], toks.T], axis=1)
