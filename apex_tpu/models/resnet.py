"""ResNet family (flax, NHWC, TPU-first) — the flagship benchmark model.

The reference benchmarks apex with torchvision ResNet-50
(``examples/imagenet/main_amp.py``); this is the TPU-native equivalent:
channels-last (the natural TPU conv layout), bf16-friendly (norm layers
created fp32 via the keep-bn-fp32 path convention — parameters live under
``bn``-prefixed names so ``amp.convert_params`` keeps them fp32), and
SyncBatchNorm-pluggable for the ``--sync_bn`` flow
(``main_amp.py:141-146``).

**Fused conv epilogues (ISSUE 7).**  Every residual block is a chain of
``conv -> bn -> relu`` with a trailing ``bn -> (+residual) -> relu``; on
the memory-bound amp-O2 step those elementwise tails are where the HBM
bytes go (r05 ledger: ~93% of HBM peak, MXU 25% busy).  The blocks
therefore route each chain through a *norm-factory hook*: when the norm
module supports the apex ``bn_relu``/``bn_add_relu`` contract
(``fuse_relu=`` ctor flag + ``z=`` residual call arg — SyncBatchNorm and
``contrib.groupbn.BatchNorm2d_NHWC`` both do, backed by the Pallas
:func:`apex_tpu.normalization.bn_relu_residual` epilogue), the whole
chain becomes ONE fused epilogue; plain ``nn.BatchNorm`` keeps the
explicit ``relu(bn(y) + residual)`` statements.  ``norm_cls`` injects an
external factory (e.g. ``functools.partial(BatchNorm2d_NHWC,
bn_group=...)``); ``fused_epilogue`` forces the routing on (error if
unsupported) or off.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..parallel import SyncBatchNorm

ModuleDef = Any


def _norm_factory_cls(norm) -> Any:
    """The module class under a (possibly nested) functools.partial."""
    while isinstance(norm, functools.partial):
        norm = norm.func
    return norm


def norm_supports_epilogue(norm) -> bool:
    """True when ``norm`` builds modules with the fused-epilogue contract
    (``fuse_relu`` ctor flag, ``z=`` residual call arg) — the hook the
    blocks key their ``bn -> relu -> (+residual)`` routing on."""
    return hasattr(_norm_factory_cls(norm), "fuse_relu")


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    #: fused bn(+z)+relu factory (``fuse_relu=True`` pre-bound), or None
    #: for the explicit relu/add statements (plain ``nn.BatchNorm``).
    norm_act: Optional[ModuleDef] = None

    def _bn_relu(self, y, name):
        if self.norm_act is not None:
            return self.norm_act(name=name)(y)
        return nn.relu(self.norm(name=name)(y))

    def _bn_add_relu(self, y, residual, name, **kw):
        """The trailing ``bn -> (+residual) -> relu`` chain — the apex
        ``bn_add_relu`` epilogue when the norm supports it."""
        if self.norm_act is not None:
            return self.norm_act(name=name, **kw)(y, residual)
        return nn.relu(residual + self.norm(name=name, **kw)(y))

    @nn.compact
    def __call__(self, x):
        # checkpoint_name is an identity outside jax.checkpoint; under
        # ResNet(remat="conv_out") the policy saves exactly these values
        # and recomputes the BN/ReLU chain from them in the backward.
        from jax.ad_checkpoint import checkpoint_name
        residual = x
        y = self.conv(self.filters, (1, 1), name="conv1")(x)
        y = checkpoint_name(y, "conv_out")
        y = self._bn_relu(y, "bn1")
        y = self.conv(self.filters, (3, 3), self.strides, name="conv2")(y)
        y = checkpoint_name(y, "conv_out")
        y = self._bn_relu(y, "bn2")
        y = self.conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = checkpoint_name(y, "conv_out")
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="downsample_conv")(residual)
            residual = checkpoint_name(residual, "conv_out")
            residual = self.norm(name="downsample_bn")(residual)
        return self._bn_add_relu(y, residual, "bn3",
                                 scale_init=nn.initializers.zeros)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    norm_act: Optional[ModuleDef] = None

    _bn_relu = BottleneckBlock._bn_relu
    _bn_add_relu = BottleneckBlock._bn_add_relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides, name="conv1")(x)
        y = self._bn_relu(y, "bn1")
        y = self.conv(self.filters, (3, 3), name="conv2")(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return self._bn_add_relu(y, residual, "bn2",
                                 scale_init=nn.initializers.zeros)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    sync_bn: bool = False
    axis_name: Optional[str] = None
    bn_process_group: Optional[Sequence[Sequence[int]]] = None
    bn_momentum: float = 0.1
    #: external norm factory (a module class or functools.partial over
    #: one), e.g. ``functools.partial(contrib.groupbn.BatchNorm2d_NHWC,
    #: bn_group=2, axis_name="data", world_size=8)``.  The factory is
    #: called per site as ``norm(name=..., [scale_init=...])`` and must
    #: accept ``use_running_average``; when it carries the fused-epilogue
    #: contract the blocks route their chains through it.  Overrides
    #: ``sync_bn``.
    norm_cls: Any = None
    #: external conv factory (a module class or functools.partial over
    #: one) mirroring ``norm_cls``, e.g. ``apex_tpu.ops.PallasConv``.
    #: Must match the ``nn.Conv`` signature and parameter pytree so the
    #: swap changes no checkpoint; shapes the factory cannot serve fall
    #: back per site inside the factory itself.  None = ``nn.Conv``.
    conv_cls: Any = None
    #: route ``bn -> relu -> (+residual)`` chains through the norm's
    #: fused epilogue: None = auto (fuse when the norm supports it),
    #: True = require it (ValueError if the norm can't), False = keep
    #: the explicit relu/add statements.
    fused_epilogue: Optional[bool] = None
    # Rematerialization per residual block (jax.checkpoint), an HBM-
    # traffic experiment knob for the bandwidth-bound O2 step (~93% of
    # HBM peak, MXU ~25% busy — r5 bytes ledger):
    #   False      — save everything (XLA default; measured 46.9 ms dev)
    #   "full"     — nothing_saveable: recompute whole blocks from their
    #                inputs.  Measured WORSE (57.8 ms dev, conv traffic
    #                28.0 -> 30.2 GB): the recompute is itself convs.
    #   "conv_out" — save only conv outputs; recompute the BN/ReLU
    #                elementwise chains from them in the backward.
    remat: Any = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(self.conv_cls or nn.Conv, use_bias=False,
                                 dtype=self.dtype, param_dtype=jnp.float32)
        if self.norm_cls is not None:
            norm = functools.partial(self.norm_cls,
                                     use_running_average=not train)
        elif self.sync_bn:
            norm = functools.partial(
                SyncBatchNorm, momentum=self.bn_momentum,
                axis_name=self.axis_name if train else None,
                process_group=self.bn_process_group,
                use_running_average=not train)
        else:
            norm = functools.partial(
                nn.BatchNorm, use_running_average=not train,
                momentum=1.0 - self.bn_momentum, epsilon=1e-5,
                dtype=self.dtype, param_dtype=jnp.float32)

        fused = self.fused_epilogue
        if fused is None:
            fused = norm_supports_epilogue(norm)
        elif fused and not norm_supports_epilogue(norm):
            raise ValueError(
                f"fused_epilogue=True but norm factory "
                f"{_norm_factory_cls(norm).__name__} has no fuse_relu/z "
                f"contract — use SyncBatchNorm / contrib.groupbn."
                f"BatchNorm2d_NHWC or pass fused_epilogue=False")
        norm_act = functools.partial(norm, fuse_relu=True) if fused else None

        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        if norm_act is not None:
            x = norm_act(name="bn_init")(x)
        else:
            x = norm(name="bn_init")(x)
            x = nn.relu(x)  # jaxlint: disable=J011 -- this IS the deliberate unfused fallback (fused_epilogue=False / plain nn.BatchNorm); the fused routing is the branch above
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        block_cls = self.block_cls
        if self.remat:
            # `train` reaches the block through the norm partials
            # (closure), so the block itself takes only x.
            if self.remat == "conv_out":
                policy = jax.checkpoint_policies.save_only_these_names(
                    "conv_out")
            elif self.remat in (True, "full"):
                policy = jax.checkpoint_policies.nothing_saveable
            else:
                raise ValueError(
                    f"remat must be False, 'full', or 'conv_out'; got "
                    f"{self.remat!r}")
            block_cls = nn.remat(block_cls, policy=policy)
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(self.num_filters * 2 ** i, strides,
                              conv=conv, norm=norm, norm_act=norm_act,
                              name=f"stage{i + 1}_block{j + 1}")(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckBlock)
