"""apex_tpu.models — benchmark model zoo (BASELINE.md configs)."""

from .resnet import (ResNet, ResNet18, ResNet34, ResNet50,  # noqa: F401
                     ResNet101, ResNet152, BottleneckBlock, BasicBlock)
from .bert import BertEncoder, bert_base, bert_tiny         # noqa: F401
from .dcgan import Generator, Discriminator                 # noqa: F401
from .gpt import GPT, gpt2_small, gpt_tiny, init_cache      # noqa: F401
