"""DCGAN generator/discriminator (flax, NHWC) — the multi-model/multi-loss
benchmark (BASELINE.md config 5; reference ``examples/dcgan/main_amp.py``
exercises amp with 2 models, 2 optimizers, 3 loss scalers)."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class Generator(nn.Module):
    ngf: int = 64
    nc: int = 3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, z, train: bool = True):
        # z: [B, nz] -> [B, 4, 4, ngf*8] -> ... -> [B, 64, 64, nc]
        norm = lambda name: nn.BatchNorm(use_running_average=not train,
                                         dtype=self.dtype,
                                         param_dtype=jnp.float32, name=name)
        x = nn.Dense(4 * 4 * self.ngf * 8, dtype=self.dtype,
                     param_dtype=jnp.float32, name="project")(z)
        x = x.reshape(z.shape[0], 4, 4, self.ngf * 8)
        x = nn.relu(norm("bn0")(x))  # jaxlint: disable=J011 -- generator activations are 4x4..32x32 (far below the epilogue's dispatch crossover); the fused-epilogue rewire is the imagenet path's, tracked for dcgan in ROADMAP
        for i, mult in enumerate((4, 2, 1)):
            x = nn.ConvTranspose(self.ngf * mult, (4, 4), (2, 2),
                                 padding="SAME", dtype=self.dtype,
                                 param_dtype=jnp.float32,
                                 name=f"deconv{i + 1}")(x)
            x = nn.relu(norm(f"bn{i + 1}")(x))  # jaxlint: disable=J011 -- same: tiny generator maps sit below the fused epilogue's crossover
        x = nn.ConvTranspose(self.nc, (4, 4), (2, 2), padding="SAME",
                             dtype=self.dtype, param_dtype=jnp.float32,
                             name="deconv_out")(x)
        return jnp.tanh(x.astype(jnp.float32))


class Discriminator(nn.Module):
    ndf: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = lambda name: nn.BatchNorm(use_running_average=not train,
                                         dtype=self.dtype,
                                         param_dtype=jnp.float32, name=name)
        x = x.astype(self.dtype)
        x = nn.leaky_relu(nn.Conv(self.ndf, (4, 4), (2, 2), padding="SAME",
                                  dtype=self.dtype, param_dtype=jnp.float32,
                                  name="conv1")(x), 0.2)
        for i, mult in enumerate((2, 4, 8)):
            x = nn.Conv(self.ndf * mult, (4, 4), (2, 2), padding="SAME",
                        dtype=self.dtype, param_dtype=jnp.float32,
                        name=f"conv{i + 2}")(x)
            x = nn.leaky_relu(norm(f"bn{i + 2}")(x), 0.2)
        x = jnp.mean(x, axis=(1, 2))
        logit = nn.Dense(1, dtype=self.dtype, param_dtype=jnp.float32,
                         name="head")(x)
        return logit.astype(jnp.float32)
