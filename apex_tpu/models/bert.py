"""BERT encoder (flax, TPU-first) — the FusedAdam/FusedLAMB benchmark model.

BASELINE.md config 4: BERT-base fine-tune with FusedAdam + FusedLAMB.  The
reference has no model code (apex is a library); this is the standard
transformer encoder built on apex_tpu components: ``FusedLayerNorm``
(pallas), bf16 matmuls on the MXU, fp32 softmax/reductions — exactly the O1
cast-list split, hard-wired where it matters.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp
import jax

from ..normalization import FusedLayerNorm


def _dense_factory(quant, dtype):
    """The ISSUE 13 projection-factory hook (the ``norm_cls`` pattern of
    PR 7, matmul edition): returns ``dense(name, features, axis=-1)``.
    With a :class:`~apex_tpu.quant.layers.QuantConfig` attached every
    projection builds as the parameter-compatible
    :class:`~apex_tpu.quant.layers.QuantDenseGeneral` (int8 kernels on
    calibrated sites, bitwise fallback elsewhere); without one it builds
    the exact flax module it always was — the one place holding that
    conditional for every model family."""
    qd = None
    if quant is not None:
        import functools

        from ..quant.layers import QuantDenseGeneral
        qd = functools.partial(QuantDenseGeneral, quant=quant)

    def dense(name, features, axis=-1):
        if qd is not None:
            return qd(features, axis=axis, dtype=dtype, name=name)
        if axis == -1 and isinstance(features, int):
            return nn.Dense(features, dtype=dtype,
                            param_dtype=jnp.float32, name=name)
        return nn.DenseGeneral(features, axis=axis, dtype=dtype,
                               param_dtype=jnp.float32, name=name)
    return dense


class BertSelfAttention(nn.Module):
    """Self-attention with a pluggable compute strategy.

    ``attention_impl``: ``"full"`` (materialized scores, the oracle),
    ``"blockwise"`` (flash-style online softmax in jnp, O(T) memory),
    ``"flash"`` (the Pallas TPU kernel of
    ``apex_tpu/ops/flash_attention.py``; falls back to blockwise off-TPU),
    ``"ring"`` (ring attention over sequence shards — call inside
    shard_map with the sequence split over ``sp_axis``), ``"ring_flash"``
    (ring attention with the Pallas flash kernels as the local op), or
    ``"ulysses"`` (all-to-all head resharding).  Ring/Ulysses are the long-context
    paths; they take the padding mask only via causal=False
    full-visibility (use blockwise/flash bias for padding within a
    shard-local setting).
    """
    num_heads: int
    dtype: Any = jnp.float32
    attention_impl: str = "full"
    sp_axis: Optional[str] = None
    causal: bool = False
    # Grouped-query attention (r3): kv projections produce only this many
    # heads, shared across num_heads / num_kv_heads query heads.  The
    # flash kernel shares KV via its index maps (no repeat); other impls
    # repeat KV heads (correct, not bandwidth-saving).  None = MHA.
    num_kv_heads: Optional[int] = None
    # Sliding-window local attention (flash impl only, needs causal).
    window: Optional[int] = None
    # Autoregressive decode mode (r3): one token per call, KV cached in a
    # flax "cache" collection sized ``cache_len`` (GPT passes max_len).
    # Decode is bandwidth-bound single-token work — plain jnp attention
    # over the cache buffer, no kernel.  GQA caches only the kv heads.
    #
    # HARD BOUND (ADVICE r3): a decode ``apply()`` is only valid while
    # ``cache_index < cache_len``.  Past it, ``dynamic_update_slice``
    # clamps the cache write and positions saturate, silently producing
    # garbage logits — there is no jit-safe error without checkify.
    # ``GPT.generate()`` clamps its step count to respect this; callers
    # driving ``apply()`` directly must bound their own loop.
    decode: bool = False
    cache_len: int = 0
    # quantization hook (ISSUE 13): a quant.QuantConfig routes the
    # q/k/v/out projections through the int8 kernels (_dense_factory).
    quant: Any = None

    @nn.compact
    def __call__(self, x, mask=None, *, kv_cache=None, positions=None):
        d = x.shape[-1]
        head_dim = d // self.num_heads
        n_kv = self.num_kv_heads or self.num_heads
        if self.num_heads % n_kv:
            raise ValueError(f"num_kv_heads {n_kv} must divide "
                             f"num_heads {self.num_heads}")
        proj = _dense_factory(self.quant, self.dtype)
        dense = lambda name, heads: proj(name, (heads, head_dim))
        out_proj = lambda: proj("out", d, axis=(-2, -1))
        q = dense("query", self.num_heads)(x)
        k = dense("key", n_kv)(x)
        v = dense("value", n_kv)(x)
        if kv_cache is not None:
            # External-cache incremental forward (ISSUE 11): the serving
            # engine owns the cache buffers (paged, donated) and threads
            # PER-SEQUENCE positions — unlike the flax "cache" collection
            # path below, whose single scalar cache_index forces every
            # sequence in the batch to the same position (useless for
            # continuous batching).  Params are byte-identical to the
            # training tree, so a hot-swapped training checkpoint drops
            # straight in.
            ctx, kf, vf = self._incremental(q, k, v, kv_cache, positions,
                                            mask)
            ctx = ctx.astype(x.dtype)
            out = out_proj()(ctx)
            return out, (kf, vf)
        if n_kv != self.num_heads and self.attention_impl not in (
                "flash", "blockwise", "full"):
            raise ValueError(
                f"num_kv_heads is supported by the flash/blockwise/full "
                f"paths, not {self.attention_impl!r}")
        if self.window is not None and not self.decode and (
                self.attention_impl != "flash" or not self.causal):
            raise ValueError(
                f"window (sliding-window local attention) needs "
                f"attention_impl='flash' and causal=True; got "
                f"impl={self.attention_impl!r}, causal={self.causal}")
        if (n_kv != self.num_heads and not self.decode
                and self.attention_impl in ("blockwise", "full")):
            # decode caches the UN-repeated kv heads (the GQA memory win)
            k = jnp.repeat(k, self.num_heads // n_kv, axis=2)
            v = jnp.repeat(v, self.num_heads // n_kv, axis=2)
        if self.decode:
            if not self.causal or mask is not None:
                raise ValueError("decode mode is causal-only and takes no "
                                 "padding mask — batch equal-length "
                                 "prompts (padding is unsupported: cached "
                                 "pad KV would be attended to)")
            if x.shape[1] != 1:
                raise ValueError(f"decode consumes ONE token per call, got "
                                 f"sequence length {x.shape[1]}")
            b_ = x.shape[0]
            # has_variable BEFORE self.variable: False exactly on the init
            # trace, where the cache must only be CREATED — persisting the
            # dummy token's kv (and bumping the index) there would make
            # every real sequence start with a ghost entry at position 0.
            live_step = self.has_variable("cache", "cached_key")
            ck = self.variable("cache", "cached_key", jnp.zeros,
                               (b_, self.cache_len, n_kv, head_dim), k.dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               (b_, self.cache_len, n_kv, head_dim), v.dtype)
            ci = self.variable("cache", "cache_index",
                               lambda: jnp.zeros((), jnp.int32))
            i = ci.value
            # NOTE the caller must bound steps by cache_len (generate()
            # clamps): past it, dynamic_update_slice clamps the write and
            # positions saturate — garbage, not an error (jit-safe guards
            # would need checkify).
            kf = jax.lax.dynamic_update_slice(ck.value, k, (0, i, 0, 0))
            vf = jax.lax.dynamic_update_slice(cv.value, v, (0, i, 0, 0))
            if live_step:
                ck.value, cv.value, ci.value = kf, vf, i + 1
            # Grouped einsums keep the cache UN-repeated on the memory bus
            # (decode is bandwidth-bound; repeating [B,L,n_kv,hd] to
            # num_heads would multiply per-step HBM traffic by the group).
            grp_ = self.num_heads // n_kv
            qg = q.reshape(b_, 1, n_kv, grp_, head_dim)
            s = jnp.einsum("bqhgd,bkhd->bhgqk", qg.astype(jnp.float32),
                           kf.astype(jnp.float32)) * (head_dim ** -0.5)
            pos = jnp.arange(self.cache_len)
            live = pos <= i
            if self.window is not None:
                live = jnp.logical_and(live, pos > i - self.window)
            s = jnp.where(live[None, None, None, None, :], s, -1e30)
            att = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bhgqk,bkhd->bqhgd", att,
                             vf.astype(jnp.float32))
            ctx = ctx.reshape(b_, 1, self.num_heads, head_dim)
        elif self.attention_impl in ("ring", "ring_flash", "ulysses"):
            if mask is not None:
                raise ValueError(
                    "ring/ulysses attention paths take no padding mask; pad "
                    "to shard boundaries or use attention_impl='blockwise'")
            from ..parallel.ring_attention import (ring_attention,
                                                   ring_flash_attention,
                                                   ulysses_attention)
            fn = {"ring": ring_attention,
                  "ring_flash": ring_flash_attention,
                  "ulysses": ulysses_attention}[self.attention_impl]
            ctx = fn(q, k, v, self.sp_axis, causal=self.causal)
        elif self.attention_impl == "flash":
            from ..ops.flash_attention import flash_attention
            kb = None
            if mask is not None:
                kb = jnp.where(mask, 0.0, -1e9)
            ctx = flash_attention(q, k, v, causal=self.causal,
                                  window=self.window,
                                  key_padding_bias=kb)
        elif self.attention_impl == "blockwise":
            from ..ops.attention import blockwise_attention
            bias = None
            if mask is not None:
                bias = jnp.where(mask[:, None, None, :], 0.0, -1e9)
            ctx = blockwise_attention(q, k, v, causal=self.causal, bias=bias)
        else:
            # The numerics oracle in ops.attention (bf16 QK^T on the MXU,
            # fp32 softmax — the cast-list split lives there).
            from ..ops.attention import dot_product_attention
            bias = None
            if mask is not None:
                bias = jnp.where(mask[:, None, None, :], 0.0, -1e9)
            ctx = dot_product_attention(q, k, v, causal=self.causal,
                                        bias=bias)
        ctx = ctx.astype(x.dtype)
        return out_proj()(ctx)

    def _incremental(self, q, k, v, kv_cache, positions, mask):
        """Incremental attention over an externally-owned dense cache
        view (ISSUE 11): write the fresh tokens' k/v at each sequence's
        own position, attend causally over everything written so far.

        ``kv_cache``: ``(k, v)`` dense views ``[B, L, n_kv, head_dim]``
        (the serving engine gathers these from its page pool);
        ``positions``: ``[B]`` int32, the global position of each
        sequence's FIRST fresh token.  Returns ``(ctx, k_full, v_full)``
        with the updated dense views — the caller scatters the written
        rows back to its pages.  The caller bounds ``positions + T`` by
        the cache length (past it ``dynamic_update_slice`` clamps
        silently, same contract as the flax-cache decode path)."""
        from ..ops.flash_attention import flash_attention
        if not self.causal or mask is not None:
            raise ValueError("the external-cache incremental path is "
                             "causal-only and takes no padding mask")
        ck, cv = kv_cache
        b_, t_ = q.shape[0], q.shape[1]
        cache_len = ck.shape[1]
        positions = jnp.asarray(positions, jnp.int32)
        write = jax.vmap(
            lambda c, fresh, p: jax.lax.dynamic_update_slice(
                c, fresh.astype(c.dtype), (p, 0, 0)))
        kf = write(ck, k, positions)
        vf = write(cv, v, positions)
        key_pos = jnp.arange(cache_len)
        if t_ == 1:
            # decode: one fresh token per sequence — the suffix-aligned
            # decode path of flash_attention; key_padding_bias masks the
            # dead cache tail (and the out-of-window past).
            live = key_pos[None, :] <= positions[:, None]
            if self.window is not None:
                live = jnp.logical_and(
                    live, key_pos[None, :] > positions[:, None] - self.window)
            kb = jnp.where(live, 0.0, -1e9)
            ctx = flash_attention(q, kf, vf, causal=True,
                                  key_padding_bias=kb)
        else:
            # prefill: per-sequence position offsets need a per-row
            # causal frontier — an explicit [B, T, L] visibility bias.
            qpos = positions[:, None] + jnp.arange(t_)[None, :]   # [B, T]
            visible = key_pos[None, None, :] <= qpos[:, :, None]
            if self.window is not None:
                visible = jnp.logical_and(
                    visible,
                    key_pos[None, None, :] > qpos[:, :, None] - self.window)
            bias = jnp.where(visible, 0.0, -1e9)
            ctx = flash_attention(q, kf, vf, causal=False, bias=bias)
        return ctx, kf, vf


class BertLayer(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.float32
    attention_impl: str = "full"
    sp_axis: Optional[str] = None
    num_kv_heads: Optional[int] = None
    quant: Any = None

    @nn.compact
    def __call__(self, x, mask=None):
        d = x.shape[-1]
        mlp = _dense_factory(self.quant, self.dtype)
        attn = BertSelfAttention(self.num_heads, self.dtype,
                                 attention_impl=self.attention_impl,
                                 sp_axis=self.sp_axis,
                                 num_kv_heads=self.num_kv_heads,
                                 quant=self.quant,
                                 name="attention")(x, mask)
        x = FusedLayerNorm(normalized_shape=d, name="attention_ln")(
            x + attn).astype(x.dtype)
        h = mlp("intermediate", self.mlp_dim)(x)
        h = nn.gelu(h.astype(jnp.float32)).astype(x.dtype)
        h = mlp("output", d)(h)
        return FusedLayerNorm(normalized_shape=d, name="output_ln")(
            x + h).astype(x.dtype)


class BertEncoder(nn.Module):
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    type_vocab_size: int = 2
    num_classes: Optional[int] = 2     # fine-tune head; None = features
    dtype: Any = jnp.float32
    attention_impl: str = "full"   # full | blockwise | flash | ring | ulysses
    sp_axis: Optional[str] = None      # mesh axis for ring/ulysses
    num_kv_heads: Optional[int] = None  # GQA; flash/blockwise/full impls
    quant: Any = None                  # ISSUE 13 int8 projection hook

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        b, s = input_ids.shape
        emb = nn.Embed(self.vocab_size, self.hidden_size,
                       param_dtype=jnp.float32, name="word_embeddings")(
                           input_ids)
        pos_ids = jnp.arange(s)[None, :]
        if self.sp_axis is not None:
            # Sequence-sharded: this shard's global positions start at
            # rank * local_len.
            pos_ids = pos_ids + jax.lax.axis_index(self.sp_axis) * s
        pos = nn.Embed(self.max_len, self.hidden_size,
                       param_dtype=jnp.float32, name="position_embeddings")(
                           pos_ids)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        typ = nn.Embed(self.type_vocab_size, self.hidden_size,
                       param_dtype=jnp.float32, name="token_type_embeddings")(
                           token_type_ids)
        x = FusedLayerNorm(normalized_shape=self.hidden_size,
                           name="embeddings_ln")(emb + pos + typ)
        x = x.astype(self.dtype)
        for i in range(self.num_layers):
            x = BertLayer(self.num_heads, self.mlp_dim, self.dtype,
                          attention_impl=self.attention_impl,
                          sp_axis=self.sp_axis,
                          num_kv_heads=self.num_kv_heads,
                          quant=self.quant,
                          name=f"layer_{i}")(x, attention_mask)
        if self.num_classes is None:
            return x.astype(jnp.float32)
        if self.sp_axis is not None:
            # Sequence-sharded: only sp-rank 0 holds the true [CLS] token.
            # Recover it exactly on every rank with a masked psum, so the
            # sp and non-sp modes compute the SAME function and params are
            # interchangeable between them.
            is_rank0 = (jax.lax.axis_index(self.sp_axis) == 0)
            contrib = jnp.where(is_rank0, x[:, 0].astype(jnp.float32), 0.0)
            pool_in = jax.lax.psum(contrib, self.sp_axis).astype(x.dtype)
        else:
            pool_in = x[:, 0]
        pooled = jnp.tanh(nn.Dense(self.hidden_size, dtype=self.dtype,
                                   param_dtype=jnp.float32,
                                   name="pooler")(pool_in))
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          param_dtype=jnp.float32, name="classifier")(pooled)
        return logits.astype(jnp.float32)


def bert_base(**kw):
    return BertEncoder(**kw)


def bert_tiny(**kw):
    kw.setdefault("hidden_size", 128)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("mlp_dim", 512)
    kw.setdefault("vocab_size", 1024)
    kw.setdefault("max_len", 128)
    return BertEncoder(**kw)
