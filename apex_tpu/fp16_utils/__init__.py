"""apex_tpu.fp16_utils — alias of :mod:`apex_tpu.bf16_utils` for reference
API compatibility (``apex/fp16_utils``): on TPU "fp16" means bfloat16."""

from ..bf16_utils import *            # noqa: F401,F403
from ..bf16_utils import (            # noqa: F401
    to_bf16, to_half, BN_convert_float, network_to_half, convert_module,
    convert_network, BF16Model, FP16Model, prep_param_lists,
    model_grads_to_master_grads, master_params_to_model_params,
    clip_grad_norm, LossScaler, DynamicLossScaler, FP16_Optimizer,
)
