"""apex_tpu.normalization — fused normalization layers (SURVEY.md §2.5)."""

from .fused_layer_norm import (FusedLayerNorm, fused_layer_norm,  # noqa: F401
                               fused_layer_norm_affine)
from .fused_bn_act import (bn_relu_residual,  # noqa: F401
                           bn_act_epilogue_ref)
