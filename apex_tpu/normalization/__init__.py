"""apex_tpu.normalization — fused normalization layers (SURVEY.md §2.5)."""

from .fused_layer_norm import (FusedLayerNorm, fused_layer_norm,  # noqa: F401
                               fused_layer_norm_affine)
