"""Fused conv-side BN epilogue: normalize + affine + residual-add + ReLU.

The conv-path analog of :mod:`.fused_layer_norm` (ISSUE 7).  The r05
roofline ledger puts ResNet-50 amp O2 at ~93% of HBM peak with the MXU
only ~25% busy: the step is *memory*-bound, and a large share of the
traffic is the elementwise ``bn -> relu -> (+residual)`` chains between
convolutions — each a separate read-modify-write sweep over the
activation tensor when left to generic fusion.  The reference attacks
exactly this with the apex contrib ``groupbn`` persistent NHWC kernels
(``bn_relu`` / ``bn_add_relu`` epilogues, ``csrc/groupbn/*``); the
TPU-native equivalent is ONE Pallas pass:

    ``y = relu((x - mean) * invstd * scale + bias [+ z])``

Statistics (batch mean/var, the cross-replica psum, running-stat
updates) stay in XLA — they are channel reductions XLA schedules well
and they carry the SyncBatchNorm collective contract; the kernel owns
only the elementwise epilogue, where the bytes are.

Structure mirrors ``fused_layer_norm.py``/``contrib/xentropy``: a jnp
reference (``_fwd_ref``/``_bwd_ref``) that doubles as the CPU fallback
and the test oracle, Pallas forward/backward kernels with a
``custom_vjp`` around them, and interpreter mode (``interpret=True``)
so CPU tests exercise the REAL kernel against the reference
(tier-parity, ISSUE 7 satellite).

The backward treats ``mean``/``invstd`` as independent differentiable
inputs: their cotangents flow back into the XLA-side statistics
computation, so autodiff of the *whole* BN (stats + epilogue) remains
exact — the kernel never needs the Welford transpose.  Per-channel
reductions (d_scale, d_bias, d_mean, d_invstd) are column sums XLA
already does optimally and stay as jnp ops fused into the same program;
the kernel computes the two activation-sized outputs (dx, dz) in one
pass.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..pallas_compat import align_vma as _align_vma
from ..pallas_compat import sds_with_vma as _sds
from ..tune import space as _space
from ..tune.dispatch import kernel_config as _tuned_config
from .fused_layer_norm import _use_pallas

__all__ = ["bn_relu_residual", "bn_act_epilogue_ref"]

#: config-cache version of this kernel's blocking scheme (ISSUE 14).
TUNE_VERSION = 1


# -- reference math (jnp fallback + oracle) -----------------------------------
#
# Kept op-for-op identical to the tail SyncBatchNorm historically inlined
# (normalize fp32, affine, + z, relu, cast back) so routing the module
# through this function is a bitwise no-op on the jnp path.

def _fwd_ref(x, mean, invstd, scale, bias, z, relu):
    out = (x.astype(jnp.float32) - mean) * invstd
    if scale is not None:
        out = out * scale + bias
    if z is not None:
        out = out + z.astype(jnp.float32)
    if relu:
        out = jax.nn.relu(out)
    return out.astype(x.dtype)


def bn_act_epilogue_ref(x, mean, invstd, scale=None, bias=None, z=None,
                        relu=True):
    """Public alias of the jnp reference epilogue (the test oracle);
    same optional-affine signature as :func:`bn_relu_residual`."""
    return _fwd_ref(x, mean, invstd, scale, bias, z, relu)


def _bwd_ref(g, x, mean, invstd, scale, bias, z, relu):
    """Activation-sized grads (dx, dz) + per-channel reductions.

    With ``y = relu(xhat * scale + bias + z)`` and ``xhat = (x - mean) *
    invstd`` (mean/invstd independent inputs):

    * ``dx = g' * scale * invstd``          (``g' = g`` masked by y > 0)
    * ``dz = g'``
    * ``d_scale = sum(g' * xhat)``; ``d_bias = sum(g')``   (per channel)
    * ``d_mean = -sum(g' * scale) * invstd``
    * ``d_invstd = sum(g' * scale * (x - mean))``
    """
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    if relu:
        pre = (xf - mean) * invstd
        if scale is not None:
            pre = pre * scale + bias
        if z is not None:
            pre = pre + z.astype(jnp.float32)
        gf = jnp.where(pre > 0, gf, 0.0)
    s = scale if scale is not None else jnp.float32(1.0)
    dx = (gf * s * invstd).astype(x.dtype)
    dz = gf.astype(z.dtype) if z is not None else None
    red = tuple(range(x.ndim - 1))          # all but the channel axis
    xmu = xf - mean
    d_scale = (jnp.sum(gf * xmu * invstd, axis=red)
               if scale is not None else None)
    d_bias = jnp.sum(gf, axis=red) if bias is not None else None
    d_mean = -jnp.sum(gf * s, axis=red) * jnp.ravel(invstd)
    d_invstd = jnp.sum(gf * s * xmu, axis=red)
    return dx, d_mean, d_invstd, d_scale, d_bias, dz


# -- pallas kernels -----------------------------------------------------------
#
# NHWC input reshaped to [rows = N*H*W, C]; per-channel vectors ride as
# [C] blocks replicated across grid steps (the fused_layer_norm w/b
# pattern, transposed: here the broadcast is per COLUMN).

_ROW_BLOCK = 256


def _pick_rows(n_rows: int, c: int, bytes_per_elem: int,
               row_block: Optional[int] = None) -> int:
    # shared VMEM/row-block math (ISSUE 14 satellite): one home in
    # apex_tpu.tune.space for this kernel, fused_layer_norm, and the
    # autotuner's constraint checker; row_block is the tuned cap.
    return _space.pick_rows(n_rows, c, bytes_per_elem,
                            row_block=row_block or _ROW_BLOCK)


def _kernel_fits(c: int, itemsize: int) -> bool:
    """Even the 8-row floor block must fit the scoped-VMEM budget (the
    fused_layer_norm width gate, per-channel edition)."""
    # fwd worst case: x, z, out at itemsize + ~2 fp32 temporaries
    return _space.floor_block_fits(c, 3 * itemsize + 8)


def tune_bucket(n_rows: int, c: int, itemsize: int, has_z: bool) -> str:
    """Config-cache shape bucket: rows round to a power of two; channel
    width, itemsize, and the residual flag (an extra activation-sized
    operand per block) are exact."""
    return f"r{_space.pow2_bucket(n_rows)}_c{c}_i{itemsize}_z{int(has_z)}"


def _fwd_kernel(x_ref, mean_ref, invstd_ref, w_ref, b_ref, z_ref, out_ref,
                *, affine, has_z, relu):
    xf = x_ref[:].astype(jnp.float32)                    # [R, C]
    out = (xf - mean_ref[:]) * invstd_ref[:]             # [C] broadcasts
    if affine:
        out = out * w_ref[:] + b_ref[:]
    if has_z:
        out = out + z_ref[:].astype(jnp.float32)
    if relu:
        out = jnp.maximum(out, 0.0)
    out_ref[:] = out.astype(out_ref.dtype)


def _bwd_kernel(g_ref, x_ref, mean_ref, invstd_ref, w_ref, b_ref, z_ref,
                dx_ref, dz_ref, *, affine, has_z, relu):
    xf = x_ref[:].astype(jnp.float32)
    gf = g_ref[:].astype(jnp.float32)
    if relu:
        pre = (xf - mean_ref[:]) * invstd_ref[:]
        if affine:
            pre = pre * w_ref[:] + b_ref[:]
        if has_z:
            pre = pre + z_ref[:].astype(jnp.float32)
        gf = jnp.where(pre > 0, gf, 0.0)
    s = w_ref[:] if affine else 1.0
    dx_ref[:] = (gf * s * invstd_ref[:]).astype(dx_ref.dtype)
    if has_z:
        dz_ref[:] = gf.astype(dz_ref.dtype)
    else:
        dz_ref[:] = jnp.zeros_like(dz_ref)


def _as_2d(v, c):
    """Per-channel vector as a [1, C] fp32 block (Mosaic wants lane-tiled
    >= 2-D operands, like the xentropy kernel's [R, 1] columns)."""
    return jnp.reshape(jnp.asarray(v, jnp.float32), (1, c))


def _pallas_fwd(x2d, mean, invstd, scale, bias, z2d, relu, interpret,
                row_block=None):
    n, c = x2d.shape
    isz = jnp.dtype(x2d.dtype).itemsize
    rows = _pick_rows(n, c, 3 * isz + 8, row_block)
    grid = (pl.cdiv(n, rows),)
    affine = scale is not None
    has_z = z2d is not None
    w = _as_2d(scale if affine else jnp.zeros((c,)), c)
    b = _as_2d(bias if affine else jnp.zeros((c,)), c)
    zz = z2d if has_z else jnp.zeros((1, c), x2d.dtype)
    vec = pl.BlockSpec((1, c), lambda i: (0, 0))
    mat = pl.BlockSpec((rows, c), lambda i: (i, 0))
    kernel = functools.partial(_fwd_kernel, affine=affine, has_z=has_z,
                               relu=relu)
    # Mosaic under shard_map(check_vma=True) needs operands agreeing on
    # how they vary — replicated per-channel vectors next to sharded
    # activations are the textbook mix (see pallas_compat.align_vma).
    operands = _align_vma(x2d, _as_2d(mean, c), _as_2d(invstd, c), w, b,
                          zz)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[mat, vec, vec, vec, vec,
                  mat if has_z else vec],
        out_specs=mat,
        out_shape=_sds((n, c), x2d.dtype, *operands),
        interpret=interpret,
    )(*operands)


def _pallas_bwd(g2d, x2d, mean, invstd, scale, bias, z2d, relu, interpret,
                row_block=None):
    n, c = x2d.shape
    isz = jnp.dtype(x2d.dtype).itemsize
    rows = _pick_rows(n, c, 4 * isz + 12,      # g, x, dx, dz + temporaries
                      row_block)
    grid = (pl.cdiv(n, rows),)
    affine = scale is not None
    has_z = z2d is not None
    w = _as_2d(scale if affine else jnp.zeros((c,)), c)
    b = _as_2d(bias if affine else jnp.zeros((c,)), c)
    zz = z2d if has_z else jnp.zeros((1, c), x2d.dtype)
    vec = pl.BlockSpec((1, c), lambda i: (0, 0))
    mat = pl.BlockSpec((rows, c), lambda i: (i, 0))
    kernel = functools.partial(_bwd_kernel, affine=affine, has_z=has_z,
                               relu=relu)
    dz_dtype = z2d.dtype if has_z else x2d.dtype
    operands = _align_vma(g2d, x2d, _as_2d(mean, c), _as_2d(invstd, c),
                          w, b, zz)
    dx, dz = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[mat, mat, vec, vec, vec, vec,
                  mat if has_z else vec],
        out_specs=[mat, mat],
        out_shape=[_sds((n, c), x2d.dtype, *operands),
                   _sds((n, c), dz_dtype, *operands)],
        interpret=interpret,
    )(*operands)
    return dx, (dz if has_z else None)


# -- dispatch -----------------------------------------------------------------

# In-context crossover, same lesson as fused_layer_norm's: below a few
# million elements the custom call is a fusion barrier that costs more
# than it saves.  Conv-side activations at benchmark shapes (b128 x 56^2
# x 256 = ~100M elements) sit far above it.
_JNP_MAX_ELEMENTS = 2 * 1024 * 1024


def _dispatch_pallas(n_rows: int, c: int, impl: Optional[str],
                     itemsize: int) -> bool:
    if impl not in (None, "pallas", "jnp"):
        raise ValueError(
            f"impl must be None, 'pallas', or 'jnp'; got {impl!r}")
    if not _use_pallas() or not _kernel_fits(c, itemsize):
        return False
    if impl is not None:
        return impl == "pallas"
    return n_rows * c >= _JNP_MAX_ELEMENTS


# -- public op with custom VJP ------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9))
def _epilogue(x2d, mean, invstd, scale, bias, z2d, relu, use_pallas,
              interpret, row_block):
    if use_pallas:
        return _pallas_fwd(x2d, mean, invstd, scale, bias, z2d, relu,
                           interpret, row_block)
    return _fwd_ref(x2d, mean, invstd, scale, bias, z2d, relu)


def _epilogue_fwd(x2d, mean, invstd, scale, bias, z2d, relu, use_pallas,
                  interpret, row_block):
    out = _epilogue(x2d, mean, invstd, scale, bias, z2d, relu, use_pallas,
                    interpret, row_block)
    return out, (x2d, mean, invstd, scale, bias, z2d)


def _epilogue_bwd(relu, use_pallas, interpret, row_block, res, g):
    x2d, mean, invstd, scale, bias, z2d = res
    if use_pallas:
        dx, dz = _pallas_bwd(g, x2d, mean, invstd, scale, bias, z2d, relu,
                             interpret, row_block)
        # Per-channel reductions recompute the relu mask in jnp — column
        # sums XLA fuses with the kernel's outputs; the activation-sized
        # work stayed in the Pallas pass.
        _, d_mean, d_invstd, d_scale, d_bias, _ = _bwd_ref(
            g, x2d, mean, invstd, scale, bias, z2d, relu)
    else:
        dx, d_mean, d_invstd, d_scale, d_bias, dz = _bwd_ref(
            g, x2d, mean, invstd, scale, bias, z2d, relu)
    # mean/invstd cotangents keep their input shapes ([1, C] rows here).
    d_mean = jnp.reshape(d_mean, jnp.shape(mean)).astype(
        jnp.asarray(mean).dtype)
    d_invstd = jnp.reshape(d_invstd, jnp.shape(invstd)).astype(
        jnp.asarray(invstd).dtype)
    if scale is not None:
        d_scale = jnp.reshape(d_scale, jnp.shape(scale)).astype(
            jnp.asarray(scale).dtype)
        d_bias = jnp.reshape(d_bias, jnp.shape(bias)).astype(
            jnp.asarray(bias).dtype)
    return dx, d_mean, d_invstd, d_scale, d_bias, dz


_epilogue.defvjp(_epilogue_fwd, _epilogue_bwd)


def bn_relu_residual(x, mean, invstd, scale=None, bias=None, z=None,
                     relu=True, impl: Optional[str] = None,
                     interpret: bool = False,
                     row_block: Optional[int] = None):
    """Fused BN epilogue: ``relu((x - mean) * invstd * scale + bias + z)``.

    ``x`` is channels-last (``[..., C]``); ``mean``/``invstd`` and the
    optional affine ``scale``/``bias`` are per-channel ``[C]`` (or any
    shape broadcastable to it — stat-shaped ``[1, 1, 1, C]`` inputs are
    flattened); ``z`` is an optional residual with ``x``'s shape, added
    BEFORE the ReLU (the apex ``bn_add_relu`` contract).  Returns
    ``x.dtype``; all arithmetic accumulates in fp32.

    ``impl``: ``None`` picks pallas-vs-jnp by size (pallas only on TPU);
    ``"pallas"``/``"jnp"`` force a path.  ``interpret=True`` runs the
    Pallas kernel in interpreter mode (CPU tier-parity tests).

    Differentiable in ``x``, ``mean``, ``invstd``, ``scale``, ``bias``
    and ``z`` — statistics computed outside (XLA reductions, psums for
    SyncBatchNorm) receive exact cotangents, so wrapping only the
    epilogue keeps full-BN autodiff correct.

    ``row_block``: explicit kernel row-block cap; left ``None`` the
    per-device config cache (:mod:`apex_tpu.tune`) is consulted with
    the hard-coded 256-row default as the fallback.
    """
    c = x.shape[-1]
    n_rows = 1
    for s in x.shape[:-1]:
        n_rows *= s
    x2d = x.reshape(n_rows, c)
    z2d = z.reshape(n_rows, c) if z is not None else None
    mean = jnp.ravel(jnp.asarray(mean, jnp.float32))
    invstd = jnp.ravel(jnp.asarray(invstd, jnp.float32))
    if scale is not None:
        scale = jnp.ravel(jnp.asarray(scale, jnp.float32))
        bias = jnp.ravel(jnp.asarray(bias, jnp.float32))
    isz = jnp.dtype(x2d.dtype).itemsize
    use_pallas = _dispatch_pallas(n_rows, c, impl, isz)
    if interpret and impl != "jnp":
        use_pallas = True
    if use_pallas and row_block is None:
        cfg = _tuned_config("bn_relu_residual", TUNE_VERSION,
                            tune_bucket(n_rows, c, isz, z is not None),
                            params=("row_block",))
        if cfg:
            row_block = cfg["row_block"]
    out = _epilogue(x2d, mean, invstd, scale, bias, z2d, bool(relu),
                    use_pallas, bool(interpret), row_block)
    return out.reshape(x.shape)
