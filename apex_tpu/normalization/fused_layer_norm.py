"""FusedLayerNorm — Pallas TPU kernel with custom VJP.

TPU-native re-design of the reference ``apex/normalization/fused_layer_norm.py``
+ ``csrc/layer_norm_cuda_kernel.cu``:

* semantics match ``nn.LayerNorm`` (normalized_shape / eps /
  elementwise_affine), reference ``fused_layer_norm.py:70-165``;
* the forward returns (output, mean, invvar) and saves mean/invvar for the
  backward — the memory-saving trick of ``cuApplyLayerNorm``
  (``layer_norm_cuda_kernel.cu:280-402``);
* input shape is split into (n1, n2) = (rows, normalized elements) exactly
  like ``compute_n1_n2`` (``layer_norm_cuda.cpp:7-27``);
* reduced-precision inputs accumulate in fp32 (reference promote semantics).

The Pallas kernel processes a block of rows per grid step: mean/var via a
single pass (mean of x and of x**2 — the Welford recombination of the CUDA
kernel is only needed because CUDA reduces across *threads*; a VPU row
reduction is single-pass), normalize, apply affine.  The backward kernel
computes grad_input in one pass from saved mean/invvar; grad_weight/grad_bias
are column reductions XLA already does optimally, so they stay as jnp ops
fused into the same program.

Off-TPU (CPU tests) the same math runs as pure jnp — this doubles as the
reference oracle, mirroring the reference's python-fallback-vs-kernel testing
strategy.
"""

from __future__ import annotations

import functools
import math
import numbers
import os
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..pallas_compat import sds_with_vma as _sds
from ..tune import space as _space
from ..tune.dispatch import kernel_config as _tuned_config

try:  # TPU-only import; absent on CPU-only installs.
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

#: config-cache version of this kernel's blocking scheme (ISSUE 14) —
#: bump when the row-block semantics change so persisted tuned configs
#: for the old scheme stop matching.
TUNE_VERSION = 1


def _use_pallas() -> bool:
    if os.environ.get("APEX_TPU_DISABLE_PALLAS"):
        return False
    # Respect an explicit non-TPU default device (e.g. the CPU test mesh):
    # Mosaic kernels only lower on the TPU backend.
    default_dev = jax.config.jax_default_device
    if default_dev is not None and getattr(default_dev, "platform", None) != "tpu":
        return False
    return jax.default_backend() == "tpu" and pltpu is not None


# In-context pallas-vs-jnp crossover, measured on the v5e inside the
# jitted BERT-base O2 train step (r5, device-loop ms/step, interleaved
# min-of-5 pairs): at LN rows x width [2048, 768] (b16 x s128) the jnp
# path wins the WHOLE STEP by ~9% (15.8-16.0 vs 17.4-17.6 ms) — the
# custom call is a fusion barrier, and ~50 launches/step of fixed
# overhead cannot amortize over 1.5M elements; at [8192, 768] (s512)
# the kernel wins by ~0.6% (72.2 vs 72.6 ms).  Same lesson as the
# attention dispatch (ops/flash_attention.py): below the crossover the
# XLA-fused jnp math IS the fast path.  Isolated microbenches understate
# the jnp side (they can't see cross-op fusion), so the threshold is set
# from the in-context pairs: dispatch to jnp under ~4M LN elements.
_JNP_MAX_ELEMENTS = 4 * 1024 * 1024


# Kernel VMEM sizing: the scoped budget the row blocks must fit, and the
# 8-row sublane floor (the smallest legal block).  The backward block is
# the per-element worst case: g, x, dx at the input itemsize plus four
# fp32 row-major temporaries (3*isz + 16 B/element; see _pick_rows).
# The math itself lives in apex_tpu.tune.space (ISSUE 14 satellite: one
# home shared by this kernel, fused_bn_act, and the autotuner's
# constraint checker); the module-level names stay as aliases.
_VMEM_BUDGET_BYTES = _space.VMEM_BUDGET_BYTES
_SUBLANE_ROWS = _space.SUBLANE_ROWS


def _kernel_max_width(itemsize: int) -> int:
    """Widest normalized dim the kernel can block for this input
    itemsize: beyond it even the 8-row floor block overflows the scoped
    VMEM budget, so NO row count is legal — route to jnp even under
    impl="pallas" rather than OOM Mosaic at compile.  Derived from the
    actual itemsize (ADVICE r5): the old fp32-tuned constant let a
    near-max fp64 width pass the gate with a ~17 MB floor block."""
    return _space.max_width(3 * itemsize + 16)


# fp32 worst case among the supported compute dtypes (~53k columns) —
# the default for callers that gate before the input dtype is known.
_KERNEL_MAX_WIDTH = _kernel_max_width(4)


def _dispatch_pallas(n1: int, n2: int, impl: Optional[str],
                     itemsize: int = 4) -> bool:
    """True when the pallas kernel should run: explicit ``impl`` wins,
    otherwise the measured in-context crossover decides.  Widths beyond
    ``_kernel_max_width(itemsize)`` always take the jnp path (no legal
    block); ``itemsize`` defaults to the fp32 worst case."""
    if impl not in (None, "pallas", "jnp"):
        raise ValueError(
            f"impl must be None, 'pallas', or 'jnp'; got {impl!r}")
    if not _use_pallas() or n2 > _kernel_max_width(itemsize):
        return False          # hard gates: no Mosaic off-TPU / no block
    if impl is not None:
        return impl == "pallas"
    return n1 * n2 >= _JNP_MAX_ELEMENTS


def _normalize_shape(normalized_shape) -> Tuple[int, ...]:
    if isinstance(normalized_shape, numbers.Integral):
        return (int(normalized_shape),)
    return tuple(int(s) for s in normalized_shape)


def _compute_n1_n2(shape, normalized_shape):
    """Split input shape into outer rows n1 and normalized cols n2
    (reference ``layer_norm_cuda.cpp:7-27``)."""
    ns = _normalize_shape(normalized_shape)
    if tuple(shape[len(shape) - len(ns):]) != ns:
        raise ValueError(
            "Expected the trailing dims of input shape {} to equal "
            "normalized_shape {}".format(shape, ns))
    n2 = math.prod(ns) if ns else 1
    n1 = math.prod(shape) // n2
    return n1, n2


# -- reference math (jnp; CPU fallback and autodiff oracle) -------------------

def _fwd_ref(x2d, weight, bias, eps):
    xf = x2d.astype(jnp.float32)
    mean = jnp.mean(xf, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(xf), axis=1, keepdims=True) - jnp.square(mean)
    invvar = jax.lax.rsqrt(var + eps)
    xhat = (xf - mean) * invvar
    out = xhat
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x2d.dtype), mean[:, 0], invvar[:, 0]


def _bwd_input_ref(g2d, x2d, mean, invvar, weight):
    """grad wrt input (reference ``cuComputeGradInput``,
    ``layer_norm_cuda_kernel.cu:523-639``)."""
    n2 = x2d.shape[1]
    gf = g2d.astype(jnp.float32)
    if weight is not None:
        gf = gf * weight.astype(jnp.float32)
    xf = x2d.astype(jnp.float32)
    mean = mean[:, None]
    invvar = invvar[:, None]
    xhat = (xf - mean) * invvar
    sum_g = jnp.sum(gf, axis=1, keepdims=True)
    sum_gx = jnp.sum(gf * xhat, axis=1, keepdims=True)
    dx = (gf - sum_g / n2 - xhat * sum_gx / n2) * invvar
    return dx.astype(x2d.dtype)



# -- pallas kernels -----------------------------------------------------------

_ROW_BLOCK = 256


def _pick_rows(n1: int, n2: int, bytes_per_elem: int,
               row_block: Optional[int] = None) -> int:
    """Row-block size that keeps the kernel's VMEM footprint bounded.

    ``bytes_per_elem`` is the per-[rows, n2]-element footprint of the
    calling kernel: the backward block holds g, x, dx at the input
    itemsize plus four fp32 row-major temporaries (3*isz + 16 — 22 B at
    bf16), the forward x, out plus ~3 fp32 temporaries (2*isz + 12).  A
    fixed 256-row block OOMs scoped VMEM (16 MB) once n2 reaches ~4k
    (measured r5: [32768, 4096] bf16 bwd asked for 20.25 MB); budget
    ~12 MB and round down to the sublane multiple
    (:func:`apex_tpu.tune.space.pick_rows`).  ``row_block`` overrides
    the 256-row cap — the autotuner's knob; the budget clamp below it
    keeps any tuned value VMEM-legal.
    """
    return _space.pick_rows(n1, n2, bytes_per_elem,
                            row_block=row_block or _ROW_BLOCK)


def tune_bucket(n1: int, n2: int, itemsize: int) -> str:
    """Config-cache shape bucket: rows round up to a power of two (the
    row block depends only weakly on n1), width and itemsize exact
    (they set the budget math)."""
    return f"r{_space.pow2_bucket(n1)}_w{n2}_i{itemsize}"


def _fwd_kernel(x_ref, w_ref, b_ref, out_ref, mean_ref, invvar_ref, *,
                eps, affine, has_bias):
    xf = x_ref[:].astype(jnp.float32)
    n2 = xf.shape[1]
    mean = jnp.sum(xf, axis=1, keepdims=True) / n2
    var = jnp.sum(xf * xf, axis=1, keepdims=True) / n2 - mean * mean
    invvar = jax.lax.rsqrt(var + eps)
    xhat = (xf - mean) * invvar
    if affine:
        xhat = xhat * w_ref[:].astype(jnp.float32)
        if has_bias:
            xhat = xhat + b_ref[:].astype(jnp.float32)
    out_ref[:] = xhat.astype(out_ref.dtype)
    mean_ref[:] = mean
    invvar_ref[:] = invvar


def _bwd_kernel(g_ref, x_ref, mean_ref, invvar_ref, w_ref, dx_ref, *, affine):
    gf = g_ref[:].astype(jnp.float32)
    if affine:
        gf = gf * w_ref[:].astype(jnp.float32)
    xf = x_ref[:].astype(jnp.float32)
    n2 = xf.shape[1]
    mean = mean_ref[:]
    invvar = invvar_ref[:]
    xhat = (xf - mean) * invvar
    sum_g = jnp.sum(gf, axis=1, keepdims=True) / n2
    sum_gx = jnp.sum(gf * xhat, axis=1, keepdims=True) / n2
    dx_ref[:] = ((gf - sum_g - xhat * sum_gx) * invvar).astype(dx_ref.dtype)


def _pallas_fwd(x2d, weight, bias, eps, interpret=False, row_block=None):
    n1, n2 = x2d.shape
    isz = jnp.dtype(x2d.dtype).itemsize
    rows = _pick_rows(n1, n2, 2 * isz + 12, row_block)
    grid = (pl.cdiv(n1, rows),)
    affine = weight is not None
    has_bias = bias is not None
    w = weight if affine else jnp.zeros((n2,), x2d.dtype)
    b = bias if has_bias else jnp.zeros((n2,), x2d.dtype)
    kernel = functools.partial(_fwd_kernel, eps=eps, affine=affine,
                               has_bias=has_bias)
    out, mean, invvar = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, n2), lambda i: (i, 0)),
            pl.BlockSpec((n2,), lambda i: (0,)),
            pl.BlockSpec((n2,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((rows, n2), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            _sds((n1, n2), x2d.dtype, x2d),
            _sds((n1, 1), jnp.float32, x2d),
            _sds((n1, 1), jnp.float32, x2d),
        ],
        interpret=interpret,
    )(x2d, w, b)
    return out, mean[:, 0], invvar[:, 0]


def _pallas_bwd_input(g2d, x2d, mean, invvar, weight, interpret=False,
                      row_block=None):
    n1, n2 = x2d.shape
    isz = jnp.dtype(x2d.dtype).itemsize
    rows = _pick_rows(n1, n2, 3 * isz + 16, row_block)
    grid = (pl.cdiv(n1, rows),)
    affine = weight is not None
    w = weight if affine else jnp.zeros((n2,), x2d.dtype)
    kernel = functools.partial(_bwd_kernel, affine=affine)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, n2), lambda i: (i, 0)),
            pl.BlockSpec((rows, n2), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((n2,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((rows, n2), lambda i: (i, 0)),
        out_shape=_sds((n1, n2), x2d.dtype, x2d, g2d),
        interpret=interpret,
    )(g2d, x2d, mean[:, None], invvar[:, None], w)


# -- public functional API with custom VJP ------------------------------------

def _fwd_impl(x2d, weight, bias, eps, use_pallas, interpret, row_block):
    if use_pallas:
        return _pallas_fwd(x2d, weight, bias, eps, interpret, row_block)
    return _fwd_ref(x2d, weight, bias, eps)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _layer_norm(x2d, weight, bias, eps, use_pallas, interpret, row_block):
    out, _, _ = _fwd_impl(x2d, weight, bias, eps, use_pallas, interpret,
                          row_block)
    return out


def _layer_norm_fwd(x2d, weight, bias, eps, use_pallas, interpret,
                    row_block):
    out, mean, invvar = _fwd_impl(x2d, weight, bias, eps, use_pallas,
                                  interpret, row_block)
    return out, (x2d, weight, bias, mean, invvar)


def _layer_norm_bwd(eps, use_pallas, interpret, row_block, res, g):
    x2d, weight, bias, mean, invvar = res
    if use_pallas:
        dx = _pallas_bwd_input(g, x2d, mean, invvar, weight, interpret,
                               row_block)
    else:
        dx = _bwd_input_ref(g, x2d, mean, invvar, weight)
    if weight is not None:
        xhat = ((x2d.astype(jnp.float32) - mean[:, None]) * invvar[:, None])
        dw = jnp.sum(g.astype(jnp.float32) * xhat, axis=0).astype(weight.dtype)
    else:
        dw = None
    if bias is not None:
        db = jnp.sum(g.astype(jnp.float32), axis=0).astype(bias.dtype)
    else:
        db = None
    return dx, dw, db


_layer_norm.defvjp(_layer_norm_fwd, _layer_norm_bwd)


def fused_layer_norm(x, normalized_shape, weight=None, bias=None, eps=1e-5,
                     impl: Optional[str] = None,
                     row_block: Optional[int] = None,
                     interpret: bool = False):
    """Functional fused layer norm (reference ``fused_layer_norm.py:64-68``
    ``fused_layer_norm``/``fused_layer_norm_affine``).

    ``impl``: ``None`` (default) picks pallas-vs-jnp by the measured
    in-context crossover (see ``_JNP_MAX_ELEMENTS``); ``"pallas"`` /
    ``"jnp"`` force a path (pallas still requires the TPU backend).

    ``row_block``: explicit row-block cap for the Pallas kernel; left
    ``None`` the per-device config cache (:mod:`apex_tpu.tune`) is
    consulted with the hard-coded 256-row default as the fallback.
    ``interpret=True`` runs the Pallas kernel in interpreter mode (CPU
    tier-parity tests and tune probes).
    """
    n1, n2 = _compute_n1_n2(x.shape, normalized_shape)
    x2d = x.reshape(n1, n2)
    w = weight.reshape(n2) if weight is not None else None
    b = bias.reshape(n2) if bias is not None else None
    isz = jnp.dtype(x2d.dtype).itemsize
    # interpret forces the (interpreter-mode) kernel unless the caller
    # explicitly asked for the jnp reference — the same A/B-probe
    # contract as quant.quantized_matmul.
    use_pallas = _dispatch_pallas(n1, n2, impl, isz)
    if interpret and impl != "jnp":
        use_pallas = True
    if use_pallas and row_block is None:
        cfg = _tuned_config("fused_layer_norm", TUNE_VERSION,
                            tune_bucket(n1, n2, isz),
                            params=("row_block",))
        if cfg:
            row_block = cfg["row_block"]
    out = _layer_norm(x2d, w, b, float(eps), use_pallas, bool(interpret),
                      row_block)
    return out.reshape(x.shape)


def fused_layer_norm_affine(x, weight, bias, normalized_shape, eps=1e-5,
                            impl: Optional[str] = None,
                            row_block: Optional[int] = None,
                            interpret: bool = False):
    return fused_layer_norm(x, normalized_shape, weight, bias, eps, impl,
                            row_block, interpret)


# -- flax module --------------------------------------------------------------

import flax.linen as nn  # noqa: E402


class FusedLayerNorm(nn.Module):
    """Drop-in ``nn.LayerNorm``-semantics module backed by the Pallas kernel
    (reference ``FusedLayerNorm`` module, ``fused_layer_norm.py:70-165``).

    Parameters are created fp32 (keep-norm-fp32 friendly); inputs of any
    float dtype are handled with fp32 accumulation.
    """
    normalized_shape: Union[int, Sequence[int]] = None
    eps: float = 1e-5
    elementwise_affine: bool = True
    impl: Optional[str] = None      # None = measured crossover dispatch

    @nn.compact
    def __call__(self, x):
        ns = _normalize_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("scale", nn.initializers.ones, ns, jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, ns, jnp.float32)
        else:
            weight = bias = None
        return fused_layer_norm(x, ns, weight, bias, self.eps, self.impl)
