"""Multi-host telemetry merge + straggler attribution — the fleet view
of the run-telemetry engine (ISSUE 10 tentpole, piece 1).

On a multi-host mesh every host records its own JSONL stream
(:class:`apex_tpu.telemetry.Recorder`, one per process), and each is an
island: different file, different relative clock, no way to say *which
host* made the whole fleet wait.  SPMD makes the islands joinable —
every host dispatches the SAME global step sequence, so the per-window
dispatch indices are a shared ruler:

* **merge** (:func:`load_fleet`) — N per-host streams (paths, globs, or
  rotated sets — :func:`apex_tpu.telemetry.expand_stream_paths`), each
  attributed by the ``run`` event's ``process_index`` stamp;
* **clock alignment** (:func:`align_clocks`) — coarse alignment from
  each stream's ``anchor_unix`` wall-clock anchor, refined by matching
  window dispatch starts per step index across hosts (the median
  start-time difference vs the reference host IS the residual clock
  skew: in lock-step SPMD the collective fabric keeps true dispatch
  starts together, so a systematic offset is the clock, not the work);
* **straggler attribution** (:func:`analyze_fleet`) — per-host step-time
  skew vs the fleet median, the slowest host per window (and whether one
  host is the *consistent* straggler — the machine you should drain),
  a modeled per-collective wait-vs-wire split (wire = bytes / link
  bandwidth; wait = the aligned dispatch-start spread the slowest host
  imposes on everyone else's collectives), and loader-stall asymmetry
  (one host's input engine throttling the whole mesh);
* **fleet timeline** — the Chrome exporter emits ONE ``pid`` lane per
  host on the aligned clock
  (:func:`apex_tpu.telemetry.events.chrome_events`), so a merged trace
  opens in Perfetto as a fleet timeline.

Pure host-side JSON (no jax import needed beyond package init) — run it
anywhere the streams can be copied to::

    python -m apex_tpu.prof.fleet 'run_host*.jsonl'
    python -m apex_tpu.prof.fleet host0.jsonl host1.jsonl --chrome fleet.json
    python -m apex_tpu.prof.fleet 'run_host*.jsonl' --json

:func:`synthetic_fleet` generates the deterministic 4-host fixture the
tests and ``bench.py`` self-validation drive the attribution with (an
injected slow host must be named on EVERY window).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Any, Dict, List, Optional, Sequence

from ..telemetry.events import (_iter_events, chrome_events,
                                expand_stream_paths)
from .timeline import SCHEMA_VERSION, analyze as _analyze_timeline

__all__ = ["HostStream", "load_fleet", "align_clocks", "analyze_fleet",
           "to_fleet_chrome_trace", "synthetic_fleet", "format_report",
           "main", "DEFAULT_ICI_GB_S"]

#: fallback inter-chip link bandwidth for the modeled wire half of the
#: wait-vs-wire split (v5e ICI ballpark, per direction per host);
#: override with ``--ici-gb-s`` / ``analyze_fleet(ici_gb_s=...)``.
DEFAULT_ICI_GB_S = 100.0


class HostStream:
    """One host's loaded stream: events + the identity its ``run``
    event stamped (``process_index``/``process_count``/``anchor_unix``/
    ``run_id``).  ``host`` falls back to load order when the stream
    predates the stamps (or two streams claim the same index)."""

    def __init__(self, path: Optional[str], events: List[dict],
                 fallback_index: int):
        self.path = path
        self.events = events
        run = next((e for e in events if e.get("kind") == "run"), {})
        self.run_id = run.get("run_id")
        self.anchor_unix: Optional[float] = run.get("anchor_unix")
        pi = run.get("process_index")
        self.host: int = int(pi) if pi is not None else fallback_index
        pc = run.get("process_count")
        self.process_count: Optional[int] = (int(pc) if pc is not None
                                             else None)
        #: window step -> (dispatch start in STREAM time, dur, n_valid)
        self.windows: Dict[int, tuple] = {}
        for e in events:
            if e.get("kind") != "window":
                continue
            t = float(e.get("t", 0.0))
            dur = float(e.get("dur", 0.0))
            self.windows[int(e.get("step", 0))] = (
                t - dur, dur, int(e.get("n_valid", 1)))

    def abs_start(self, step: int) -> Optional[float]:
        """Window dispatch start on the anchor-based absolute clock
        (stream time when the stream has no anchor)."""
        w = self.windows.get(step)
        if w is None:
            return None
        return (self.anchor_unix or 0.0) + w[0]


def load_fleet(paths_or_globs: Sequence[str]) -> List[HostStream]:
    """Load N per-host streams.  Each argument may be a concrete path,
    a glob (``'run_host*.jsonl'``), or any member of a rotated set;
    rotated segments group back onto their base stream.  Returns
    streams sorted by host index.  Raises ``ValueError`` when nothing
    matched, and de-duplicates host indices by load order (a re-used
    index would silently fold two hosts into one skew row)."""
    bases: List[str] = []
    seen = set()
    for arg in paths_or_globs:
        for seg in expand_stream_paths(arg):
            base = seg
            m = re.match(r"^(.+)\.(\d+)$", seg)
            if m:
                base = m.group(1)
            if base not in seen:
                seen.add(base)
                bases.append(base)
    streams: List[HostStream] = []
    for i, base in enumerate(bases):
        try:
            events = _iter_events(base)
        except OSError:
            continue         # an unmatched glob resolves to no streams
        if events:
            streams.append(HostStream(base, events, fallback_index=i))
    if not streams:
        raise ValueError(
            f"no telemetry events found under {list(paths_or_globs)!r}")
    used: set = set()
    for i, s in enumerate(streams):
        if s.host in used:          # duplicate stamp: keep streams apart
            s.host = max(used) + 1
        used.add(s.host)
    streams.sort(key=lambda s: s.host)
    return streams


def align_clocks(streams: List[HostStream]) -> Dict[int, Dict[str, Any]]:
    """Per-host clock correction onto the reference host's clock.

    Coarse: each stream's ``anchor_unix`` maps stream time onto the
    wall clock.  Fine: for every window step both hosts dispatched, the
    difference of anchor-based dispatch starts vs the reference host is
    collected; its MEDIAN is that host's residual clock skew (median,
    not mean — a straggler window shifts the tail, not the middle), and
    is subtracted by the aligned clock.  Returns ``{host: {"offset_s"
    (add to the host's absolute time), "clock_skew_s", "common_windows",
    "anchored"}}``."""
    if not streams:
        return {}
    ref = streams[0]
    out: Dict[int, Dict[str, Any]] = {}
    for s in streams:
        deltas: List[float] = []
        for step, (_t0, _dur, _n) in s.windows.items():
            r = ref.abs_start(step)
            mine = s.abs_start(step)
            if r is not None and mine is not None:
                deltas.append(r - mine)
        deltas.sort()
        skew = deltas[len(deltas) // 2] if deltas else 0.0
        out[s.host] = {
            "offset_s": skew,
            "clock_skew_s": round(-skew, 6) if s is not ref else 0.0,
            "common_windows": len(deltas),
            "anchored": s.anchor_unix is not None,
        }
    return out


def analyze_fleet(streams: List[HostStream], *,
                  ici_gb_s: float = DEFAULT_ICI_GB_S) -> Dict[str, Any]:
    """Distill N aligned host streams into the fleet attribution dict
    (``--json`` / :func:`format_report` / the bench gate).

    Sections: ``hosts`` (per-host timeline analysis joined with clock
    skew), ``windows`` (per common window: the slowest host, its
    dispatch dur, and the skew it imposed), ``straggler`` (who was
    slowest how often, and whether one host is the consistent
    straggler), ``collectives`` (per-op wait-vs-wire split), and
    ``loader`` (stall asymmetry).
    """
    align = align_clocks(streams)
    per_host: List[Dict[str, Any]] = []
    for s in streams:
        tl = _analyze_timeline(s.events)
        att = tl.get("attribution") or {}
        st = tl.get("step_time") or {}
        per_host.append({
            "host": s.host,
            "run_id": s.run_id,
            "path": s.path,
            "steps": tl.get("steps", 0),
            "windows": tl.get("windows", 0),
            "steps_per_s": tl.get("steps_per_s"),
            "step_time_mean_ms": st.get("mean_ms"),
            "step_time_p90_ms": st.get("p90_ms"),
            "dispatch_pct": att.get("dispatch_pct"),
            "loader_stall_pct": att.get("loader_stall_pct", 0.0),
            "clock_skew_ms": round(
                1e3 * align[s.host]["clock_skew_s"], 3),
            "alerts": (tl.get("alerts") or {}).get("total", 0),
        })

    # -- per-window straggler attribution ------------------------------------
    common = set(streams[0].windows)
    for s in streams[1:]:
        common &= set(s.windows)
    windows: List[Dict[str, Any]] = []
    slow_counts: Dict[int, int] = {}
    arrival_skews: List[float] = []
    for step in sorted(common):
        durs = {s.host: s.windows[step][1] for s in streams}
        starts = {s.host: (s.abs_start(step) or 0.0)
                  + align[s.host]["offset_s"] for s in streams}
        slowest = max(durs, key=lambda h: durs[h])
        ds = sorted(durs.values())
        median_dur = ds[len(ds) // 2]
        arrival = max(starts.values()) - min(starts.values())
        arrival_skews.append(arrival)
        slow_counts[slowest] = slow_counts.get(slowest, 0) + 1
        windows.append({
            "step": step,
            "slowest_host": slowest,
            "slowest_dur_ms": round(durs[slowest] * 1e3, 3),
            "median_dur_ms": round(median_dur * 1e3, 3),
            "skew_ms": round((durs[slowest] - median_dur) * 1e3, 3),
            "arrival_skew_ms": round(arrival * 1e3, 3),
        })
    straggler: Dict[str, Any] = {"by_host": {str(h): n for h, n
                                             in sorted(slow_counts.items())}}
    if windows:
        top_host, top_n = max(slow_counts.items(), key=lambda kv: kv[1])
        straggler.update({
            "host": top_host,
            "windows_slowest": top_n,
            "windows_total": len(windows),
            "fraction": round(top_n / len(windows), 3),
            # one machine losing >= 2/3 of the races is a machine
            # problem, not noise — the drain candidate
            "consistent": top_n >= max(2, (2 * len(windows)) // 3),
            "mean_skew_ms": round(
                sum(w["skew_ms"] for w in windows) / len(windows), 3),
        })

    # -- per-collective wait-vs-wire split -----------------------------------
    # Host streams cannot time the fabric; the split is MODELED, and
    # says so: wire = bytes / link bandwidth (the unavoidable floor),
    # wait = the mean aligned dispatch-start spread (the slowest host's
    # lateness, which every collective in the window inherits — in
    # lock-step SPMD a collective cannot complete before its last
    # participant arrives).  wait >> wire means buy scheduling, not
    # bandwidth.
    mean_arrival = (sum(arrival_skews) / len(arrival_skews)
                    if arrival_skews else 0.0)
    coll_groups: Dict[tuple, Dict[str, Any]] = {}
    for s in streams:
        tl_coll: Dict[tuple, dict] = {}
        for e in s.events:
            if e.get("kind") != "collective":
                continue
            key = (e.get("op"), json.dumps(e.get("axis")),
                   int(e.get("bytes", 0)))
            tl_coll[key] = e                 # one per compile; last wins
        for key, e in tl_coll.items():
            g = coll_groups.setdefault(key, {
                "op": e.get("op"), "axis": e.get("axis"),
                "bytes_per_step": int(e.get("bytes", 0)),
                "participants": e.get("participants"),
                "hosts": 0})
            g["hosts"] += 1
    collectives: List[Dict[str, Any]] = []
    for g in coll_groups.values():
        # topology-aware wire bytes per host (ring schedules): an N-way
        # all-reduce moves ~2(N-1)/N x the payload per link, a
        # reduce-scatter / all-gather (N-1)/N; participants rides each
        # collective event from parallel._note_collective exactly for
        # this (review finding — the field was collected but unused).
        p = g.get("participants")
        if p and p > 1:
            factor = ((p - 1) / p if g["op"] in ("psum_scatter",
                                                 "reduce_scatter",
                                                 "all_gather")
                      else 2.0 * (p - 1) / p)
        else:
            factor = 1.0
        wire_s = g["bytes_per_step"] * factor / (ici_gb_s * 1e9)
        g["wire_factor"] = round(factor, 3)
        wait_s = mean_arrival
        g.update({
            "wire_ms_modeled": round(wire_s * 1e3, 4),
            "wait_ms_modeled": round(wait_s * 1e3, 4),
            "wait_pct": round(100.0 * wait_s / (wait_s + wire_s), 1)
            if (wait_s + wire_s) > 0 else None,
        })
        collectives.append(g)
    collectives.sort(key=lambda c: -c["bytes_per_step"])
    # Per-mesh-axis traffic split (ISSUE 12): the axis names riding
    # every collective event from parallel._note_collective attribute
    # wire/wait per mesh dimension (dp vs fsdp vs tp) — "is the FSDP
    # gather or the DP psum eating the step" becomes one read.
    from .timeline import axis_label
    by_axis: Dict[str, Dict[str, Any]] = {}
    for g in collectives:
        d = by_axis.setdefault(axis_label(g.get("axis")), {
            "bytes_per_step": 0, "wire_ms_modeled": 0.0, "ops": set()})
        d["bytes_per_step"] += g["bytes_per_step"]
        d["wire_ms_modeled"] = round(
            d["wire_ms_modeled"] + (g.get("wire_ms_modeled") or 0.0), 4)
        d["ops"].add(g["op"])
    by_axis = {k: {"bytes_per_step": v["bytes_per_step"],
                   "wire_ms_modeled": v["wire_ms_modeled"],
                   "ops": sorted(v["ops"])}
               for k, v in sorted(by_axis.items())}

    # -- loader-stall asymmetry ----------------------------------------------
    stalls = {h["host"]: float(h["loader_stall_pct"] or 0.0)
              for h in per_host}
    loader: Dict[str, Any] = {"by_host": {str(h): round(v, 2) for h, v
                                          in sorted(stalls.items())}}
    if stalls:
        worst = max(stalls, key=lambda h: stalls[h])
        spread = max(stalls.values()) - min(stalls.values())
        loader.update({
            "worst_host": worst,
            "spread_pct_points": round(spread, 2),
            # one host stalling while the rest stream is an input-path
            # asymmetry (bad disk, hot shard, noisy neighbor) — the
            # whole lock-step mesh runs at that host's pace
            "asymmetric": spread > 10.0,
        })

    return {
        "schema_version": SCHEMA_VERSION,
        "n_hosts": len(streams),
        "hosts": per_host,
        "alignment": {str(h): a for h, a in sorted(align.items())},
        "windows": windows,
        "straggler": straggler,
        "collectives": {"ici_gb_s_modeled": ici_gb_s,
                        "mean_arrival_skew_ms": round(mean_arrival * 1e3,
                                                      4),
                        "by_op": collectives,
                        "by_axis": by_axis},
        "loader": loader,
    }


def to_fleet_chrome_trace(streams: List[HostStream], out_path: str) -> int:
    """Merged Chrome trace: one ``pid`` lane per host, all on the
    aligned clock (earliest aligned event is ``ts == 0``).  Open in
    Perfetto — the fleet timeline the per-host files could never
    show."""
    align = align_clocks(streams)
    bases = []
    for s in streams:
        bases.append((s.anchor_unix or 0.0) + align[s.host]["offset_s"])
    t0 = min(bases) if bases else 0.0
    out: List[dict] = []
    n = 0
    for s, base in zip(streams, bases):
        evs = chrome_events(
            s.events, pid=s.host,
            host=f"host {s.host}"
                 + (f" of {s.process_count}" if s.process_count else ""),
            t_offset_s=base - t0)
        n += sum(1 for e in evs if e["ph"] != "M")
        out.extend(evs)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)
    return n


# -- synthetic fixture --------------------------------------------------------

def synthetic_fleet(n_hosts: int = 4, n_windows: int = 12, k: int = 4,
                    *, slow_host: int = 2, slow_factor: float = 1.6,
                    base_dur_s: float = 0.040,
                    clock_err_s: Optional[Sequence[float]] = None,
                    stall_host: Optional[int] = None,
                    seed: int = 0,
                    dir: Optional[str] = None):
    """Deterministic N-host stream fixture (tests + bench
    self-validation): host ``slow_host`` dispatches every window
    ``slow_factor`` x slower, ``stall_host`` (default: the slow host)
    reports an asymmetric loader stall, and each host's wall-clock
    anchor carries an injected error (``clock_err_s``, default ±40 ms
    alternating) the aligner must recover.  Jitter is seeded — the same
    fixture analyses identically everywhere.

    Returns a list of per-host event lists, or (with ``dir``) writes
    ``host<i>.jsonl`` files and returns their paths."""
    import random
    rng = random.Random(seed)
    if clock_err_s is None:
        clock_err_s = [((-1) ** h) * 0.040 * (1 + h // 2)
                       for h in range(n_hosts)]
    if stall_host is None:
        stall_host = slow_host
    anchor_base = 1_700_000_000.0       # any fixed epoch; never "now"
    fleet: List[List[dict]] = []
    global_t = [0.5]                     # true time the window starts
    for h in range(n_hosts):
        events: List[dict] = []
        anchor = anchor_base + clock_err_s[h]

        def ev(t_global, kind, **fields):
            # stream time is true time since this host's recorder
            # opened; the ANCHOR carries the clock error, exactly as a
            # skewed time.time() would
            events.append({"t": round(t_global, 6), "kind": kind,
                           **fields})
        ev(0.0, "run", run_id=f"fleet-fixture-{seed}",
           meta={"example": "synthetic"}, process_index=h,
           process_count=n_hosts, anchor_unix=round(anchor, 6),
           segment=0)
        ev(0.2, "collective", op="psum", axis="data",
           bytes=4_000_000, n=2, dtype="float32", participants=n_hosts)
        fleet.append(events)

    t = global_t[0]
    for w in range(n_windows):
        durs = []
        for h in range(n_hosts):
            dur = base_dur_s * (slow_factor if h == slow_host else 1.0)
            dur *= 1.0 + 0.02 * rng.random()       # 2% jitter, seeded
            durs.append(dur)
        for h in range(n_hosts):
            start = t + 0.001 * rng.random()       # dispatch jitter
            end = start + durs[h]
            fleet[h].append({"t": round(end, 6), "kind": "window",
                             "step": w * k, "k": k, "n_valid": k,
                             "dur": round(durs[h], 6),
                             "gap": 0.002, "program": "hot"})
            if h == stall_host:
                fleet[h].append({"t": round(end + 0.001, 6),
                                 "kind": "loader_wait",
                                 "dur": round(0.35 * durs[h], 6),
                                 "qdepth": 0})
        # the fleet advances at the SLOWEST host's pace (lock-step SPMD)
        t += max(durs) + 0.004
    for h in range(n_hosts):
        stall_pct = 35.0 if h == stall_host else 4.0
        fleet[h].append({"t": round(t, 6), "kind": "loader",
                         "phase": "exhausted",
                         "stats": {"loader_stall_pct": stall_pct,
                                   "consumer_wait_s": 0.0,
                                   "produce_s": 0.1, "stage_s": 0.05,
                                   "mean_queue_depth": 1.5,
                                   "batches": n_windows}})
        fleet[h].append({"t": round(t + 0.01, 6), "kind": "summary",
                         "events": {"window": n_windows}, "metrics": {}})
    if dir is None:
        return fleet
    import os
    paths = []
    for h, events in enumerate(fleet):
        p = os.path.join(dir, f"host{h}.jsonl")
        with open(p, "w", encoding="utf-8") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        paths.append(p)
    return paths


# -- report / CLI -------------------------------------------------------------

def _fmt(v, unit="", width=8, prec=2):
    if v is None:
        return " " * (width - 3) + "n/a"
    return f"{v:{width}.{prec}f}{unit}"


def format_report(a: Dict[str, Any]) -> str:
    """Human-readable fleet report (the CLI's default output)."""
    lines: List[str] = []
    lines.append(f"fleet timeline — {a['n_hosts']} hosts, "
                 f"{len(a.get('windows') or [])} common windows")
    lines.append("{:<6} {:>8} {:>10} {:>10} {:>10} {:>10} {:>7}".format(
        "host", "steps", "steps/s", "step ms", "stall %", "skew ms",
        "alerts"))
    for h in a.get("hosts", []):
        lines.append("{:<6} {:>8} {:>10} {:>10} {:>10} {:>10} {:>7}".format(
            h["host"], h["steps"],
            h["steps_per_s"] if h["steps_per_s"] is not None else "n/a",
            h["step_time_mean_ms"] if h["step_time_mean_ms"] is not None
            else "n/a",
            h["loader_stall_pct"], h["clock_skew_ms"], h["alerts"]))
    st = a.get("straggler") or {}
    if st.get("windows_total"):
        verdict = ("CONSISTENT straggler — drain/replace candidate"
                   if st.get("consistent") else "no consistent straggler")
        lines.append(
            f"straggler: host {st['host']} slowest in "
            f"{st['windows_slowest']}/{st['windows_total']} windows "
            f"({100 * st['fraction']:.0f}%) — {verdict}")
        by = ", ".join(f"host {h}: {n}"
                       for h, n in (st.get("by_host") or {}).items())
        lines.append(f"  slowest-per-window counts: {by}")
    co = a.get("collectives") or {}
    if co.get("by_op"):
        lines.append(
            f"collectives (modeled @ {co['ici_gb_s_modeled']} GB/s link, "
            f"arrival skew {co['mean_arrival_skew_ms']} ms):")
        for c in co["by_op"][:8]:
            lines.append(
                f"  {c['op']:<14} {c['bytes_per_step'] / 1e6:8.3f} MB/step"
                f"  wire {c['wire_ms_modeled']} ms"
                f"  wait {c['wait_ms_modeled']} ms"
                f"  ({c['wait_pct']}% wait)")
    lo = a.get("loader") or {}
    if lo.get("by_host"):
        flag = (" — ASYMMETRIC input path"
                if lo.get("asymmetric") else "")
        lines.append(
            f"loader stall by host: {lo['by_host']} "
            f"(spread {lo.get('spread_pct_points')} pts, worst host "
            f"{lo.get('worst_host')}){flag}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.prof.fleet",
        description="Merge N per-host telemetry streams: clock "
                    "alignment, straggler attribution, wait-vs-wire, "
                    "loader asymmetry, fleet Chrome trace.")
    p.add_argument("streams", nargs="+",
                   help="per-host .jsonl paths / globs / rotated sets "
                        "(quote globs so the shell does not pre-expand "
                        "rotated segments into duplicates)")
    p.add_argument("--json", action="store_true",
                   help="emit the analysis as JSON instead of the report")
    p.add_argument("--chrome", metavar="OUT",
                   help="write a merged Chrome trace_event file with "
                        "one pid lane per host (Perfetto)")
    p.add_argument("--ici-gb-s", type=float, default=DEFAULT_ICI_GB_S,
                   help=f"modeled link bandwidth for the wire half of "
                        f"the wait-vs-wire split "
                        f"(default {DEFAULT_ICI_GB_S})")
    args = p.parse_args(argv)
    try:
        streams = load_fleet(args.streams)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if len(streams) < 2:
        print(f"warning: only {len(streams)} stream(s) matched — the "
              f"fleet view needs one per host (single-stream analysis: "
              f"python -m apex_tpu.prof.timeline)", file=sys.stderr)
    a = analyze_fleet(streams, ici_gb_s=args.ici_gb_s)
    if args.chrome:
        n = to_fleet_chrome_trace(streams, args.chrome)
        print(f"wrote {n} chrome trace events "
              f"({len(streams)} pid lanes) to {args.chrome}",
              file=sys.stderr)
    try:
        if args.json:
            print(json.dumps(a, indent=1))
        else:
            print(format_report(a))
    except BrokenPipeError:
        try:
            sys.stdout.close()
        except Exception:
            pass
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(main())
