"""Per-op roofline attribution — the PyProf ``prof`` stage joined with
the run's own clock (ISSUE 6 tentpole).

The reference's PyProf maps every kernel in a profile back to the op
that launched it and reports FLOPs, bytes, and silicon efficiency per
op (``pyprof/prof/prof.py``).  The TPU-native equivalent has three
inputs, all already in this repo, and this module is the join:

1. **cost harvest** (:func:`harvest_costs`) — per-computation FLOP/byte
   totals at trace time from ``jit(fn).lower(*args).cost_analysis()``
   (falling back to ``.compile().cost_analysis()``, and on old jax to
   the :func:`apex_tpu.prof.analysis.profile_function` jaxpr walk).
   Harvesting uses its OWN ``jax.jit`` instance, so it never touches —
   and never retraces — the training step's jitted callable.
2. **region attribution** — the jaxpr walk carries every op's
   ``named_scope`` path (:func:`apex_tpu.prof.capture.scope` /
   ``annotate`` names); :func:`apex_tpu.prof.capture.region_path` peels
   jax's transform wrappers so forward and backward ops of one region
   land in the same row.  Harvested FLOPs/bytes are grouped per region.
3. **MFU ledger** (:func:`mfu_ledger`) — the harvest joined with
   measured time: each region gets a roofline time model
   (``max(flops/peak_flops, bytes/peak_bw)``), a compute-vs-memory
   boundedness classification against measured peaks (the
   ``BENCH_EXTRA.json`` calibration written next to ``BASELINE.json``
   — :func:`load_peaks`), modeled-time share of the measured step, and
   achieved FLOP/s; the run-level gap section splits the
   steady-vs-best-window distance into compile, loader stall, dispatch
   gap, and other host time read from a
   :func:`apex_tpu.prof.timeline.analyze` result.

CLI::

    python -m apex_tpu.prof.roofline --fn mymod:make_step \\
        --timeline run.jsonl --peaks BENCH_EXTRA.json [--json]

``bench.py`` records this ledger per benchmark workload in
``BENCH_EXTRA.json`` and replaces its hand-coded BERT FLOPs estimate
with the harvested ``matmul_flops`` (old formula kept as a 10%
cross-check gate).
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax

from .capture import region_path
from .ledger import COMPUTE_OPS

__all__ = ["CostHarvest", "harvest_costs", "mfu_ledger", "load_peaks",
           "DEFAULT_HBM_GB_S", "main"]

#: fallback HBM bandwidth when no measured number is available (v5e
#: spec sheet ballpark — the same fallback ``bench._bert_mfu_bound``
#: documents); every ledger records which source its bandwidth used.
DEFAULT_HBM_GB_S = 800.0


@dataclass
class CostHarvest:
    """One computation's harvested costs (one call of ``fn(*args)``).

    ``flops``/``bytes`` are the totals from XLA's cost analysis when
    available (``source`` says which path produced them), else the
    jaxpr-walk totals.  ``matmul_flops`` is ALWAYS the jaxpr walk's
    dot/conv-only count (:data:`apex_tpu.prof.ledger.COMPUTE_OPS`) —
    the MFU numerator, deliberately independent of XLA's op costing so
    cross-round comparisons stay stable.  ``by_region`` maps each
    :func:`~apex_tpu.prof.capture.region_path` region to its
    ``{"flops", "bytes", "matmul_flops", "ops"}`` row.
    """
    flops: float
    bytes: Optional[float]
    source: str                      # "xla_lowered" | "xla_compiled" | "jaxpr"
    matmul_flops: float
    jaxpr_flops: float               # fallback totals (XLA cross-check)
    jaxpr_bytes: float
    by_region: Dict[str, Dict[str, float]] = field(default_factory=dict)

    @property
    def coverage_pct(self) -> float:
        """How much of the harvested total the region rows account for
        (jaxpr-attributed flops / reported total) — the acceptance
        number ("ledger accounts for >= 90% of the step FLOPs")."""
        if not self.flops:
            return 0.0
        attributed = sum(r["flops"] for r in self.by_region.values())
        return 100.0 * attributed / self.flops


def _xla_cost(fn, *args, **kwargs) -> Optional[Dict[str, float]]:
    """XLA's own cost analysis for one call, cheapest path first:
    ``Lowered.cost_analysis()`` (HLO-level, no backend compile), then
    ``Compiled.cost_analysis()``.  Returns ``{"flops", "bytes",
    "source"}`` or None when neither API exists (old jax) or yields a
    usable flops count.  Kept as its own function so tests can
    monkeypatch it to force the old-jax fallback."""
    try:
        lowered = jax.jit(fn).lower(*args, **kwargs)
    except Exception:
        return None
    try:
        cost = _first(lowered.cost_analysis())
    except Exception:
        cost = None
    if cost and cost.get("flops"):
        return {"flops": float(cost["flops"]),
                "bytes": (float(cost["bytes accessed"])
                          if cost.get("bytes accessed") else None),
                "source": "xla_lowered"}
    try:
        cost = _first(lowered.compile().cost_analysis())
    except Exception:
        cost = None
    if cost and cost.get("flops"):
        return {"flops": float(cost["flops"]),
                "bytes": (float(cost["bytes accessed"])
                          if cost.get("bytes accessed") else None),
                "source": "xla_compiled"}
    return None


def _first(cost):
    if isinstance(cost, (list, tuple)):
        return cost[0] if cost else None
    return cost


def harvest_costs(fn, *args, xla: bool = True, region_depth: int = 1,
                  prof=None, **kwargs) -> CostHarvest:
    """Harvest FLOP/byte estimates for ONE call of ``fn(*args)``.

    Totals come from XLA's cost analysis when ``xla=True`` and the API
    is available (``jit(fn).lower(...).cost_analysis()``, then the
    compiled fallback); otherwise — and always for the per-region and
    matmul splits — from the static jaxpr walk
    (:func:`~apex_tpu.prof.analysis.profile_function`), which needs no
    compile and runs on any backend.  ``region_depth`` controls how many
    leading :func:`~apex_tpu.prof.capture.scope` components form a
    region key; ``prof`` reuses an existing ``profile_function`` result
    (the jaxpr trace of a full train step is seconds of host work —
    ``bench.py`` shares one across its ledgers).

    Pure trace-time analysis: nothing executes on the device, no buffer
    is donated or consumed, and the training step's own jit cache is
    untouched (pin with :func:`apex_tpu.prof.assert_trace_count`).
    """
    from .analysis import profile_function

    if prof is None:
        prof = profile_function(fn, *args, xla_cost=False, **kwargs)
    by_region: Dict[str, Dict[str, float]] = {}
    matmul = 0.0
    for r in prof.records:
        row = by_region.setdefault(
            region_path(r.name, depth=region_depth),
            {"flops": 0.0, "bytes": 0.0, "matmul_flops": 0.0, "ops": 0})
        row["flops"] += r.flops * r.count
        row["bytes"] += r.bytes * r.count
        row["ops"] += r.count
        if r.op in COMPUTE_OPS:
            row["matmul_flops"] += r.flops * r.count
            matmul += r.flops * r.count
    jaxpr_flops = prof.total_flops
    jaxpr_bytes = prof.total_bytes
    cost = _xla_cost(fn, *args, **kwargs) if xla else None
    if cost is not None:
        return CostHarvest(
            flops=cost["flops"], bytes=cost["bytes"], source=cost["source"],
            matmul_flops=matmul, jaxpr_flops=jaxpr_flops,
            jaxpr_bytes=jaxpr_bytes, by_region=by_region)
    return CostHarvest(
        flops=jaxpr_flops, bytes=jaxpr_bytes, source="jaxpr",
        matmul_flops=matmul, jaxpr_flops=jaxpr_flops,
        jaxpr_bytes=jaxpr_bytes, by_region=by_region)


# -- measured peaks -----------------------------------------------------------

def load_peaks(path: Optional[str] = None) -> Dict[str, Any]:
    """Measured roofline ceilings: ``{"flops": peak FLOP/s,
    "hbm_gb_s": bandwidth, "source": where they came from}``.

    Reads the ``BENCH_EXTRA.json`` calibration artifact committed next
    to ``BASELINE.json`` (the serial-chain ``measured_matmul_tflops`` is
    the honest MFU denominator on a tunneled chip; the nameplate
    ``peak_bf16_tflops`` is the fallback).  ``path`` may name the file
    or a directory containing it; with no path the repo root (three
    levels up from this module) and the CWD are searched."""
    candidates: List[str] = []
    if path:
        candidates = [os.path.join(path, "BENCH_EXTRA.json")
                      if os.path.isdir(path) else path]
    else:
        root = os.path.abspath(os.path.join(
            os.path.dirname(__file__), os.pardir, os.pardir))
        candidates = [os.path.join(root, "BENCH_EXTRA.json"),
                      os.path.join(os.getcwd(), "BENCH_EXTRA.json")]
    for cand in candidates:
        try:
            with open(cand) as f:
                extra = json.load(f)
        except Exception:
            continue
        tflops = extra.get("measured_matmul_tflops") \
            or extra.get("peak_bf16_tflops")
        if not tflops:
            continue
        src = ("measured_matmul_tflops"
               if extra.get("measured_matmul_tflops") else
               "peak_bf16_tflops")
        # Prefer a measured loop-fusion bandwidth from the trace rows
        # when present (same preference as bench._bert_mfu_bound).
        bw, bw_src = DEFAULT_HBM_GB_S, "fallback_v5e_hbm"
        prof = (extra.get("resnet50") or {}).get("prof_measured") or {}
        for row in prof.get("by_category", []):
            if row.get("category") == "loop fusion" and row.get("gb_per_s"):
                bw, bw_src = float(row["gb_per_s"]), "measured_loop_fusion"
                break
        return {"flops": float(tflops) * 1e12, "hbm_gb_s": bw,
                "source": f"{os.path.basename(cand)}:{src}",
                "bw_source": bw_src}
    return {"flops": 197e12, "hbm_gb_s": DEFAULT_HBM_GB_S,
            "source": "default_v5e_nameplate",
            "bw_source": "fallback_v5e_hbm"}


# -- the MFU ledger -----------------------------------------------------------

def mfu_ledger(harvest: CostHarvest, *, step_time_s: Optional[float] = None,
               timeline: Optional[Dict[str, Any]] = None,
               peaks: Optional[Dict[str, Any]] = None,
               best_window_step_s: Optional[float] = None,
               top: Optional[int] = None,
               memory=None) -> Dict[str, Any]:
    """Join one :class:`CostHarvest` with measured time into the
    per-region MFU ledger.

    ``step_time_s`` is the measured wall seconds per step; with a
    ``timeline`` (an :func:`apex_tpu.prof.timeline.analyze` result) it
    defaults to the stream's ``elapsed / steps``.  ``peaks`` is a
    :func:`load_peaks`-shaped dict (defaults to loading one).

    ``memory`` (ISSUE 10) is a
    :class:`apex_tpu.prof.memory.MemoryHarvest` of the SAME step: the
    ledger gains a ``memory`` section (peak-HBM totals + top
    allocations) and each region row a ``peak_hbm_mb`` column from the
    walk's live-set-at-peak attribution — FLOPs, wire bytes, and HBM
    residency finally read off one table.

    Each region row models its roofline time as
    ``max(flops/peak_flops, bytes/peak_bw)`` and is classified
    ``compute``- or ``memory``-bound by which side dominates; modeled
    times are normalized so they sum to the measured step, giving every
    region a modeled-ms share and an achieved FLOP/s.  The run-level
    ``gap`` section attributes the distance between the steady step and
    its best window (``best_window_step_s``) — and, from the timeline,
    the compile seconds (retrace-event dispatch durations), loader
    stall, dispatch gap, and other host time.
    """
    peaks = dict(peaks or load_peaks())
    peak_f = float(peaks.get("flops") or 197e12)
    peak_bw = float(peaks.get("hbm_gb_s") or DEFAULT_HBM_GB_S) * 1e9
    if step_time_s is None and timeline:
        steps = timeline.get("steps") or 0
        elapsed = timeline.get("elapsed_s") or 0.0
        if steps and elapsed:
            step_time_s = elapsed / steps

    mem_by_region: Dict[str, float] = {}
    if memory is not None:
        mem_by_region = dict(getattr(memory, "by_region", None)
                             or (memory.get("by_region", {})
                                 if isinstance(memory, dict) else {}))

    regions: List[Dict[str, Any]] = []
    modeled_total = 0.0
    for name, row in harvest.by_region.items():
        t_compute = row["flops"] / peak_f
        t_memory = row["bytes"] / peak_bw if row["bytes"] else 0.0
        modeled = max(t_compute, t_memory)
        modeled_total += modeled
        entry = {
            "region": name,
            "flops_g": round(row["flops"] / 1e9, 6),
            "matmul_flops_g": round(row["matmul_flops"] / 1e9, 6),
            "bytes_gb": round(row["bytes"] / 1e9, 6),
            "ops": int(row["ops"]),
            "intensity": (round(row["flops"] / row["bytes"], 2)
                          if row["bytes"] else None),
            "bound": ("compute" if t_compute >= t_memory else "memory"),
            "_modeled_s": modeled,
        }
        if name in mem_by_region:
            # this region's buffers live at the walk's peak-HBM moment
            entry["peak_hbm_mb"] = round(mem_by_region[name] / 1e6, 3)
        regions.append(entry)
    # Normalize the roofline time model onto the measured clock: the
    # scale factor is also a diagnostic — how far the real schedule sits
    # from the no-overlap roofline ideal (> 1: slower than ideal).
    model_scale = ((step_time_s / modeled_total)
                   if step_time_s and modeled_total else None)
    for r in regions:
        modeled = r.pop("_modeled_s")
        if model_scale:
            t = modeled * model_scale
            r["modeled_ms"] = round(t * 1e3, 3)
            r["share_pct"] = round(100.0 * modeled * model_scale
                                   / step_time_s, 1) if step_time_s else None
            r["achieved_tflops"] = (round(r["flops_g"] / 1e3 / t, 4)
                                    if t > 0 else None)
            # MFU numerator is the region's MATMUL flops — same
            # definition as total.mfu_pct, so an elementwise-dominated
            # region (optimizer sweep) cannot report phantom MXU use.
            r["mfu_pct"] = (round(100.0 * r["matmul_flops_g"] * 1e9
                                  / t / peak_f, 1)
                            if t > 0 else None)
    regions.sort(key=lambda r: -(r.get("modeled_ms") or r["flops_g"]))
    if top:
        dropped = max(0, len(regions) - top)
        regions = regions[:top]
    else:
        dropped = 0

    out: Dict[str, Any] = {
        # versioned with the analyzer's schema: regress.py diffs these
        "schema_version": _schema_version(),
        "source": harvest.source,
        "peaks": {"tflops": round(peak_f / 1e12, 1),
                  "hbm_gb_s": round(peak_bw / 1e9, 1),
                  "ridge_intensity": round(peak_f / peak_bw, 1),
                  "source": peaks.get("source"),
                  "bw_source": peaks.get("bw_source")},
        "total": {
            "flops_g": round(harvest.flops / 1e9, 6),
            "matmul_flops_g": round(harvest.matmul_flops / 1e9, 6),
            "bytes_gb": (round(harvest.bytes / 1e9, 6)
                         if harvest.bytes else None),
            "intensity": (round(harvest.flops / harvest.bytes, 2)
                          if harvest.bytes else None),
        },
        "coverage_pct": round(harvest.coverage_pct, 1),
        "regions": regions,
        "regions_dropped": dropped,
    }
    if memory is not None:
        get = (lambda k: getattr(memory, k, None)
               if not isinstance(memory, dict) else memory.get(k))
        peak_b = float(get("peak_bytes") or 0)
        out["total"]["peak_hbm_gb"] = round(peak_b / 1e9, 6)
        out["memory"] = {
            "peak_hbm_gb": round(peak_b / 1e9, 6),
            "source": get("source"),
            "argument_gb": round(float(get("argument_bytes") or 0)
                                 / 1e9, 6),
            "output_gb": round(float(get("output_bytes") or 0) / 1e9, 6),
            "temp_gb": round(float(get("temp_bytes") or 0) / 1e9, 6),
            "walk_peak_gb": round(float(get("walk_peak_bytes") or 0)
                                  / 1e9, 6),
            "top_allocations": list(get("top_allocations") or [])[:8],
        }
    if step_time_s:
        out["total"]["step_ms"] = round(step_time_s * 1e3, 3)
        out["total"]["achieved_tflops"] = round(
            harvest.flops / step_time_s / 1e12, 4)
        out["total"]["mfu_pct"] = round(
            100.0 * harvest.matmul_flops / step_time_s / peak_f, 1)
        out["model_scale"] = (round(model_scale, 2) if model_scale else None)

    gap: Dict[str, Any] = {}
    if best_window_step_s and step_time_s:
        gap["steady_vs_best_pct"] = round(
            max(0.0, 100.0 * (1.0 - best_window_step_s / step_time_s)), 1)
    if timeline:
        att = timeline.get("attribution") or {}
        rt = timeline.get("retraces") or {}
        elapsed = float(timeline.get("elapsed_s") or 0.0)
        compile_s = float(rt.get("compile_s") or 0.0)
        gap.update({
            # where the non-device wall time went, % of the stream's wall
            "compile_pct": (round(100.0 * compile_s / elapsed, 2)
                            if elapsed else None),
            "loader_stall_pct": att.get("loader_stall_pct"),
            "dispatch_gap_pct": att.get("dispatch_gap_pct"),
            # host time between dispatches NOT explained by the loader:
            # metric fetches, python glue, GC — the "host sync" bucket
            "host_other_pct": att.get("gap_minus_loader_pct"),
        })
    if gap:
        out["gap"] = gap
    return out


def _schema_version() -> str:
    from .timeline import SCHEMA_VERSION
    return SCHEMA_VERSION


def _fmt_g(v) -> str:
    return f"{v:10.3f}" if v is not None else "       n/a"


def format_ledger(ledger: Dict[str, Any]) -> str:
    """Human-readable ledger (the CLI's default output)."""
    lines: List[str] = []
    t = ledger["total"]
    pk = ledger["peaks"]
    lines.append(
        f"roofline ledger ({ledger['source']}; peaks {pk['tflops']} TFLOP/s"
        f" / {pk['hbm_gb_s']} GB/s [{pk['source']}])")
    head = (f"total: {t['flops_g']} GFLOP ({t['matmul_flops_g']} matmul)"
            + (f", {t['bytes_gb']} GB" if t.get("bytes_gb") else ""))
    if t.get("step_ms"):
        head += (f" in {t['step_ms']} ms -> {t['achieved_tflops']} TFLOP/s"
                 f" ({t['mfu_pct']}% MFU vs measured peak)")
    lines.append(head)
    mem = ledger.get("memory")
    if mem:
        lines.append(
            f"peak HBM: {mem['peak_hbm_gb']} GB [{mem['source']}] "
            f"(args {mem['argument_gb']}, outputs {mem['output_gb']}, "
            f"temps {mem['temp_gb']}; walk {mem['walk_peak_gb']})")
    lines.append(f"region coverage: {ledger['coverage_pct']}% of total flops")
    lines.append("{:<26} {:>10} {:>10} {:>8} {:>9} {:>7}  {}".format(
        "region", "GFLOP", "GB", "ms", "TFLOP/s", "MFU%", "bound"))
    for r in ledger["regions"]:
        lines.append("{:<26} {} {} {:>8} {:>9} {:>7}  {}".format(
            r["region"][:26], _fmt_g(r["flops_g"]), _fmt_g(r["bytes_gb"]),
            r.get("modeled_ms", ""), r.get("achieved_tflops", ""),
            r.get("mfu_pct", ""), r["bound"]))
    if ledger.get("regions_dropped"):
        lines.append(f"... {ledger['regions_dropped']} smaller regions "
                     f"not shown")
    gap = ledger.get("gap")
    if gap:
        parts = [f"{k.replace('_pct', '')} {v}%"
                 for k, v in gap.items() if v is not None]
        lines.append("gap attribution: " + ", ".join(parts))
    return "\n".join(lines)


# -- CLI ----------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m apex_tpu.prof.roofline`` — harvest one target's costs
    and print its MFU ledger, optionally joined with a telemetry stream
    (for step timing + gap attribution) and a measured-peaks file.

    The target follows the ``prof.analysis`` convention: ``--fn
    module:callable`` where a zero-argument callable returns
    ``(fn, example_args)`` (``__graft_entry__:entry`` works out of the
    box)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.prof.roofline",
        description="Per-region roofline attribution / MFU ledger.")
    ap.add_argument("--fn", default="__graft_entry__:entry",
                    help="module:callable returning (fn, example_args)")
    ap.add_argument("--timeline", default=None, metavar="RUN_JSONL",
                    help="telemetry stream: step timing + gap attribution")
    ap.add_argument("--peaks", default=None,
                    help="BENCH_EXTRA.json (or a dir holding it) with "
                         "measured peaks; default: repo root / CWD")
    ap.add_argument("--step-ms", type=float, default=None,
                    help="measured step time (overrides --timeline)")
    ap.add_argument("--region-depth", type=int, default=1)
    ap.add_argument("--top", type=int, default=None)
    ap.add_argument("--no-xla", action="store_true",
                    help="skip XLA cost analysis (jaxpr totals only)")
    ap.add_argument("--memory", action="store_true",
                    help="also harvest the peak-HBM ledger "
                         "(prof.memory) and join it as the ledger's "
                         "memory section / peak_hbm columns")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    from .analysis import _load_target

    fn, ex = _load_target(args.fn)()
    harvest = harvest_costs(fn, *ex, xla=not args.no_xla,
                            region_depth=args.region_depth)
    mem = None
    if args.memory:
        from . import memory as memory_mod
        mem = memory_mod.harvest_memory(fn, *ex, xla=not args.no_xla,
                                        region_depth=args.region_depth)
    tl = None
    if args.timeline:
        from . import timeline as timeline_mod
        tl = timeline_mod.analyze(timeline_mod.load_events(args.timeline))
    ledger = mfu_ledger(
        harvest,
        step_time_s=(args.step_ms / 1e3 if args.step_ms else None),
        timeline=tl, peaks=load_peaks(args.peaks), top=args.top,
        memory=mem)
    if args.json:
        print(json.dumps(ledger, indent=1))
    else:
        print(format_ledger(ledger))
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(main())
