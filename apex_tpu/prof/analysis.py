"""Per-op FLOPs/bytes analysis of traced computations — the ``pyprof.prof``
stage (reference ``apex/pyprof/prof/``: per-op analyzer classes computing
bytes/flops from captured shapes, e.g. ``conv.py:190-233``).

The reference reconstructs op shapes from NVTX markers recorded in a CUPTI
SQLite DB.  On TPU the compiler already *has* the whole program: we walk the
jaxpr of the jitted function (recursing through pjit/scan/cond/custom-vjp
calls) and emit one :class:`OpRecord` per primitive with analytic FLOPs and
memory traffic, and cross-check totals against XLA's own
``compiled.cost_analysis()`` — the profiler-DB role is played by the
compiler, with no host-side capture overhead at all.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import core as jcore


@dataclass
class OpRecord:
    """One primitive invocation (reference ``pyprof/prof/data.py`` Data)."""
    index: int
    op: str                     # primitive name
    name: str                   # named_scope path if present
    in_shapes: list
    in_dtypes: list
    out_shapes: list
    out_dtypes: list
    flops: float                # analytic floating ops
    bytes: float                # analytic HBM traffic (read + write)
    count: int = 1              # multiplicity (e.g. scan length)

    @property
    def intensity(self) -> float:
        """Arithmetic intensity flop/byte — the roofline coordinate."""
        return self.flops / self.bytes if self.bytes else 0.0


def _size(aval) -> int:
    return math.prod(aval.shape) if aval.shape else 1


def _bytesize(aval) -> int:
    return _size(aval) * jnp.dtype(aval.dtype).itemsize


def _dot_general_flops(eqn) -> float:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = math.prod(lhs.shape[d] for d in lb) if lb else 1
    contract = math.prod(lhs.shape[d] for d in lc) if lc else 1
    m = math.prod(s for i, s in enumerate(lhs.shape)
                  if i not in lc and i not in lb)
    n = math.prod(s for i, s in enumerate(rhs.shape)
                  if i not in rc and i not in rb)
    return 2.0 * batch * m * n * contract


def _conv_flops(eqn) -> float:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    out = eqn.outvars[0].aval
    # 2 * output elements * (kernel spatial * in_features / groups)
    groups = eqn.params.get("feature_group_count", 1)
    dn = eqn.params["dimension_numbers"]
    k_spatial = math.prod(rhs.shape[i] for i in dn.rhs_spec[2:])
    cin = rhs.shape[dn.rhs_spec[1]]
    return 2.0 * _size(out) * k_spatial * cin  # cin already per-group


_ELEMENTWISE = {
    "add", "sub", "mul", "div", "max", "min", "neg", "exp", "log", "tanh",
    "logistic", "rsqrt", "sqrt", "pow", "integer_pow", "abs", "sign",
    "floor", "ceil", "round", "erf", "select_n", "clamp", "and", "or",
    "xor", "not", "eq", "ne", "ge", "gt", "le", "lt",
    "erf_inv", "expm1", "log1p", "cos", "sin", "tan", "atan2", "cbrt",
    "real", "imag", "nextafter",
}
# Pure data movement (dtype casts, layout/shape changes, identities): 0
# flops — they cost bytes, not ALU work (ADVICE r1 #2).  They still produce
# OpRecords so bandwidth accounting sees them.
# (convert_element_type / squeeze / copy / stop_gradient intentionally NOT
# in _ELEMENTWISE.)

_REDUCTIONS = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
               "reduce_and", "reduce_or", "argmax", "argmin",
               "cumsum", "cumprod", "cummax", "cummin"}

_TRANSCENDENTAL_COST = {"exp": 1, "log": 1, "tanh": 1, "logistic": 1,
                        "erf": 1, "pow": 1}

_CALL_PRIMS = {"pjit", "closed_call", "core_call", "custom_jvp_call",
               "custom_vjp_call", "custom_vjp_call_jaxpr", "remat", "checkpoint",
               "custom_lin", "named_call"}


def _inner_jaxpr(eqn):
    p = eqn.params
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in p and p[key] is not None:
            j = p[key]
            return j.jaxpr if hasattr(j, "jaxpr") else j
    return None


def _flops_bytes(eqn):
    """Analytic (flops, bytes) for one primitive."""
    prim = eqn.primitive.name
    in_bytes = sum(_bytesize(v.aval) for v in eqn.invars
                   if hasattr(v, "aval"))
    out_bytes = sum(_bytesize(v.aval) for v in eqn.outvars)
    total_bytes = in_bytes + out_bytes
    out_elems = sum(_size(v.aval) for v in eqn.outvars)

    if prim == "dot_general":
        return _dot_general_flops(eqn), total_bytes
    if prim == "conv_general_dilated":
        return _conv_flops(eqn), total_bytes
    if prim in _REDUCTIONS:
        in_elems = sum(_size(v.aval) for v in eqn.invars
                       if hasattr(v, "aval"))
        return float(in_elems), total_bytes
    if prim in _ELEMENTWISE:
        return float(out_elems), total_bytes
    # data movement (reshape/transpose/slice/gather/...): 0 flops
    return 0.0, total_bytes


class Profile:
    """Result of :func:`profile_function` — records + totals + summary."""

    def __init__(self, records: List[OpRecord],
                 xla_cost: Optional[dict] = None):
        self.records = records
        self.xla_cost = xla_cost or {}

    @property
    def total_flops(self) -> float:
        return sum(r.flops * r.count for r in self.records)

    @property
    def total_bytes(self) -> float:
        return sum(r.bytes * r.count for r in self.records)

    def by_op(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for r in self.records:
            out[r.op] = out.get(r.op, 0.0) + r.flops * r.count
        return out

    def summary(self, top: int = 20) -> str:
        """Tabular report (reference ``pyprof/prof/output.py`` columns:
        op, params, flops, bytes, tensor-core/MXU eligibility)."""
        rows = sorted(self.records, key=lambda r: -(r.flops * r.count))[:top]
        lines = ["{:<5} {:<22} {:>14} {:>14} {:>9} {:>5}  {}".format(
            "idx", "op", "flops", "bytes", "intens", "MXU", "shapes")]
        for r in rows:
            mxu = "yes" if r.op in ("dot_general",
                                    "conv_general_dilated") else ""
            lines.append("{:<5} {:<22} {:>14.3g} {:>14.3g} {:>9.2f} {:>5}  {}"
                         .format(r.index, r.op, r.flops * r.count,
                                 r.bytes * r.count, r.intensity, mxu,
                                 "{}->{}".format(r.in_shapes, r.out_shapes)))
        lines.append("TOTAL flops={:.4g} bytes={:.4g}  (xla: flops={} "
                     "bytes accessed={})".format(
                         self.total_flops, self.total_bytes,
                         self.xla_cost.get("flops", "n/a"),
                         self.xla_cost.get("bytes accessed", "n/a")))
        return "\n".join(lines)


def _walk(jaxpr, records: List[OpRecord], scope: str, mult: int):
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        inner = _inner_jaxpr(eqn)
        if prim == "scan":
            length = eqn.params.get("length", 1)
            _walk(inner, records, scope + f"/scan", mult * length)
            continue
        if prim == "while":
            # Trip count is data-dependent and unknowable statically: body
            # ops are counted ONCE (multiplicity 1) and tagged with a
            # "/while" scope so totals are recognizably lower bounds for
            # while-based programs (scan, with its static length, is exact).
            body = eqn.params.get("body_jaxpr")
            body = body.jaxpr if hasattr(body, "jaxpr") else body
            if body is not None:
                _walk(body, records, scope + "/while", mult)
            continue
        if prim == "cond":
            for br in eqn.params.get("branches", ()):
                _walk(br.jaxpr if hasattr(br, "jaxpr") else br,
                      records, scope + "/cond", mult)
            continue
        if inner is not None or prim in _CALL_PRIMS:
            if inner is not None:
                name = eqn.params.get("name", prim)
                _walk(inner, records, f"{scope}/{name}", mult)
                continue
        flops, nbytes = _flops_bytes(eqn)
        # jax.named_scope / prof.scope names land in the equation's
        # source-info name stack, not in call-primitive params; join them
        # onto the structural call path so user annotations are visible
        # (reference traceMarker semantics, pyprof/nvtx/nvmarker.py).
        ns = getattr(getattr(eqn, "source_info", None), "name_stack", None)
        ns = str(ns) if ns is not None else ""
        full_scope = "/".join(p for p in (scope, ns) if p)
        records.append(OpRecord(
            index=len(records), op=prim, name=full_scope,
            in_shapes=[tuple(v.aval.shape) for v in eqn.invars
                       if hasattr(v, "aval")],
            in_dtypes=[str(v.aval.dtype) for v in eqn.invars
                       if hasattr(v, "aval") and hasattr(v.aval, "dtype")],
            out_shapes=[tuple(v.aval.shape) for v in eqn.outvars],
            out_dtypes=[str(v.aval.dtype) for v in eqn.outvars
                        if hasattr(v.aval, "dtype")],
            flops=flops, bytes=nbytes, count=mult))


def profile_function(fn: Callable, *args, xla_cost: bool = True,
                     **kwargs) -> Profile:
    """Trace ``fn(*args)`` and return a :class:`Profile`.

    The parse stage of pyprof (``pyprof/parse``) reads a profiler database;
    here the jaxpr IS the database.  With ``xla_cost=True`` the function is
    also lowered + compiled so XLA's own cost model is attached.
    """
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    records: List[OpRecord] = []
    _walk(jaxpr.jaxpr, records, "", 1)
    cost = None
    if xla_cost:
        try:
            compiled = jax.jit(fn).lower(*args, **kwargs).compile()
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else None
        except Exception:
            cost = None
    return Profile(records, cost)


# -- CLI -----------------------------------------------------------------------

def _load_target(spec: str):
    """Resolve ``module:attr`` to a Python object."""
    import importlib

    mod_name, _, attr = spec.partition(":")
    if not attr:
        raise SystemExit(f"--fn needs module:callable, got {spec!r}")
    mod = importlib.import_module(mod_name)
    obj = mod
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def _markers_table(path: str, top: int) -> str:
    """Render a dumped-markers file (``prof.capture.dump_markers``) as the
    reference's captured-op table (op name + arg shapes/dtypes)."""
    import json as _json

    lines = ["{:<28} {}".format("marker op", "args")]
    with open(path) as f:
        for i, line in enumerate(f):
            if i >= top:
                lines.append("...")
                break
            m = _json.loads(line)
            def fmt(d):
                if "shape" in d:
                    return f"{tuple(d['shape'])}:{d.get('dtype', '?')}"
                if "value" in d:
                    return repr(d["value"])
                return d.get("type", "?")
            args = [fmt(a) for a in m.get("args", [])]
            args += [f"{k}={fmt(v)}" for k, v in m.get("kwargs", {}).items()]
            lines.append("{:<28} {}".format(m.get("op", "?"), ", ".join(args)))
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m apex_tpu.prof.analysis`` — the runnable analysis stage
    (reference ``python -m apex.pyprof.prof net.dict``,
    ``apex/pyprof/prof/prof.py:171``): per-op FLOPs/bytes report for a
    target function, optionally joined with a measured trace dir and/or a
    dumped-markers file.

    The target is ``--fn module:callable``; by the graft-entry convention a
    zero-argument target is called to obtain ``(fn, example_args)``
    (``__graft_entry__:entry`` works out of the box), otherwise supply
    ``--shape``/``--dtype`` per positional argument.
    """
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.prof.analysis",
        description="Analytic per-op FLOPs/bytes report (+ optional "
                    "measured-trace join).")
    ap.add_argument("--fn", default="__graft_entry__:entry",
                    help="module:callable — either returns (fn, args) when "
                         "called with no arguments, or is profiled directly "
                         "with --shape/--dtype example inputs")
    ap.add_argument("--shape", action="append", default=[],
                    help="example-arg shape as comma-separated ints (repeat "
                         "per positional argument); e.g. --shape 8,128")
    ap.add_argument("--dtype", action="append", default=[],
                    help="dtype per --shape (default float32)")
    ap.add_argument("--trace", default=None,
                    help="trace logdir to join measured op times "
                         "(prof.parse stage output)")
    ap.add_argument("--markers", default=None,
                    help="dumped markers file (prof.capture.dump_markers)")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--no-xla-cost", action="store_true",
                    help="skip the compile-based XLA cost cross-check")
    args = ap.parse_args(argv)

    import numpy as np

    target = _load_target(args.fn)
    if args.shape:
        dtypes = list(args.dtype) + ["float32"] * (len(args.shape)
                                                   - len(args.dtype))
        ex = tuple(
            jnp.zeros(tuple(int(s) for s in sh.split(",") if s), dt)
            for sh, dt in zip(args.shape, dtypes))
        fn = target
    else:
        fn, ex = target()
        ex = tuple(jax.tree_util.tree_map(np.asarray, ex))

    prof = profile_function(fn, *ex, xla_cost=not args.no_xla_cost)
    print(prof.summary(top=args.top))
    if args.trace:
        from .parse import parse_trace, attach_measured
        print()
        print(attach_measured(prof, parse_trace(args.trace), top=args.top))
    if args.markers:
        print()
        print(_markers_table(args.markers, args.top))
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    raise SystemExit(main())
