"""Per-request serving analysis — TTFT/TPOT percentiles, request
waterfalls, and SLO goodput from a telemetry stream (ISSUE 20).

The offline half of the serving engine's request tracing: the engine
emits a ``done`` ``serving`` event per finished request (always, with
``ttft_s``/``tpot_s``/``total_s``/``queue_wait_s``) plus — for sampled
requests — a ``span`` tree (``request`` root, ``queue``/``prefill``/
``decode_step`` children) keyed by a deterministic trace id.  This
module reassembles both from a finished stream (or several per-host
streams) and answers the operator questions the live Prometheus gauges
cannot:

* latency percentiles over EVERY request of the run (the in-run
  histograms keep a bounded reservoir; dones are exact) — computed with
  the same :func:`~apex_tpu.telemetry.metrics.nearest_rank_percentiles`
  the reservoirs use, so the two agree within sampling error;
* goodput against a declarative SLO spec
  (:func:`apex_tpu.telemetry.slo.evaluate` — the SAME per-request
  predicate as the online :class:`~apex_tpu.telemetry.slo.SLOEngine`);
* the batch-size-vs-TPOT join: mean decode-step latency grouped by how
  many requests shared the batch — the continuous-batching cost curve;
* per-request waterfalls from the sampled span trees, exportable as a
  Chrome trace with ONE process lane per request (``--chrome``).

Usage::

    python -m apex_tpu.prof.requests serve.jsonl
    python -m apex_tpu.prof.requests serve.jsonl --slo 'ttft_p99<200ms,tpot_p99<30ms'
    python -m apex_tpu.prof.requests 'serve_host*.jsonl' --chrome req.trace.json

Multiple stream arguments (or a multi-host glob) merge onto the first
host's clock via :mod:`apex_tpu.prof.fleet` alignment; a rotated set
(``base.jsonl`` + ``base.jsonl.1`` …) reassembles automatically.  Like
the other ``prof`` CLIs this module is NOT imported by
``prof/__init__`` (runpy double-import hygiene).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Sequence

from ..telemetry.events import _iter_events, chrome_events
from ..telemetry.metrics import nearest_rank_percentiles
from ..telemetry.slo import evaluate as evaluate_slo

__all__ = ["load_request_events", "request_stats", "build_waterfalls",
           "analyze", "to_request_chrome_trace", "format_report", "main"]

_PCT_QS = (50.0, 90.0, 99.0)
#: report metric -> field name on the ``done`` serving event
_METRICS = (("ttft", "ttft_s"), ("tpot", "tpot_s"),
            ("e2e", "total_s"), ("queue_wait", "queue_wait_s"))


def load_request_events(streams: Sequence[str]) -> List[dict]:
    """Load one or more stream arguments into a single event list on a
    common clock.  One argument loads directly (rotated segments
    reassemble, stream time untouched).  Several arguments go through
    :func:`~apex_tpu.prof.fleet.load_fleet` +
    :func:`~apex_tpu.prof.fleet.align_clocks`: every host's events are
    shifted onto host 0's stream clock (anchor delta + residual window
    skew) and tagged with their ``host`` index, so cross-host request
    sets sort into one timeline."""
    streams = list(streams)
    if len(streams) == 1:
        return _iter_events(streams[0])
    from .fleet import align_clocks, load_fleet
    hosts = load_fleet(streams)
    corr = align_clocks(hosts)
    ref_anchor = hosts[0].anchor_unix or 0.0
    merged: List[dict] = []
    for s in hosts:
        off = ((s.anchor_unix or 0.0) - ref_anchor
               + float(corr.get(s.host, {}).get("offset_s", 0.0) or 0.0))
        for e in s.events:
            e = dict(e)
            e["t"] = round(float(e.get("t", 0.0)) + off, 6)
            e.setdefault("host", s.host)
            merged.append(e)
    merged.sort(key=lambda e: float(e.get("t", 0.0)))
    return merged


def _dones(events: Sequence[dict]) -> List[dict]:
    return [e for e in events
            if e.get("kind") == "serving" and e.get("phase") == "done"
            and e.get("total_s") is not None]


def request_stats(events: Sequence[dict]) -> Optional[Dict[str, Any]]:
    """Percentile summary over every finished request in ``events``
    (``None`` when the stream holds no serving ``done`` events) — the
    ``requests`` section :func:`apex_tpu.prof.timeline.analyze` embeds
    (timeline schema 1.2)."""
    dones = _dones(events)
    if not dones:
        return None
    out: Dict[str, Any] = {"n_requests": len(dones)}
    toks = [int(e.get("n_tokens", 0)) for e in dones]
    out["tokens_out"] = sum(toks)
    for name, field in _METRICS:
        vals = [float(e[field]) for e in dones
                if e.get(field) is not None]
        p50, p90, p99 = nearest_rank_percentiles(vals, _PCT_QS)
        out[name] = {
            "n": len(vals),
            "mean_ms": (round(1e3 * sum(vals) / len(vals), 3)
                        if vals else None),
            "p50_ms": round(1e3 * p50, 3) if p50 is not None else None,
            "p90_ms": round(1e3 * p90, 3) if p90 is not None else None,
            "p99_ms": round(1e3 * p99, 3) if p99 is not None else None,
        }
    # the continuous-batching cost curve: mean decode-step duration by
    # how many sequences shared the step (a decode event's ``dur`` IS
    # the per-token latency every member of that batch experienced)
    by_bs: Dict[int, List[float]] = {}
    for e in events:
        if e.get("kind") == "serving" and e.get("phase") == "decode":
            by_bs.setdefault(int(e.get("active", 0)), []).append(
                float(e.get("dur", 0.0)))
    out["batch_tpot"] = [
        {"batch_size": bs, "steps": len(durs),
         "mean_step_ms": round(1e3 * sum(durs) / len(durs), 3)}
        for bs, durs in sorted(by_bs.items()) if bs > 0]
    return out


def build_waterfalls(events: Sequence[dict],
                     limit: Optional[int] = None
                     ) -> List[Dict[str, Any]]:
    """Reassemble the sampled ``span`` events into per-request
    waterfalls: one entry per trace id, spans sorted by start time
    (``start = t - dur``; the emitter stamps ``t`` at span END).  Only
    sampled requests appear here — the percentile sections above cover
    every request regardless of sampling."""
    by_trace: Dict[str, List[dict]] = {}
    for e in events:
        if e.get("kind") == "span" and e.get("trace"):
            by_trace.setdefault(str(e["trace"]), []).append(e)
    out: List[Dict[str, Any]] = []
    for trace, spans in by_trace.items():
        rows = []
        for e in spans:
            dur = float(e.get("dur", 0.0))
            row = {"name": e.get("name"),
                   "span": e.get("span"), "parent": e.get("parent"),
                   "start_s": round(float(e.get("t", 0.0)) - dur, 6),
                   "dur_ms": round(1e3 * dur, 3)}
            for k in ("slot", "bucket", "batch_size", "prompt_len",
                      "n_tokens", "step", "host"):
                if k in e:
                    row[k] = e[k]
            rows.append(row)
        rows.sort(key=lambda r: (r["start_s"], -r["dur_ms"]))
        root = next((r for r in rows if r.get("parent") is None
                     and r["name"] == "request"), None)
        out.append({
            "trace": trace,
            "n_spans": len(rows),
            "start_s": rows[0]["start_s"] if rows else None,
            "e2e_ms": root["dur_ms"] if root else None,
            "decode_steps": sum(1 for r in rows
                                if r["name"] == "decode_step"),
            "spans": rows,
        })
    out.sort(key=lambda w: (w["start_s"] is None, w["start_s"]))
    return out[:limit] if limit is not None else out


def analyze(events: Sequence[dict],
            slo: Optional[str] = None) -> Dict[str, Any]:
    """Distill a loaded event list into the per-request report dict
    (``format_report`` / ``--json``).  ``slo`` adds a goodput section
    evaluated with the online engine's own predicate."""
    dones = _dones(events)
    run = next((e for e in events if e.get("kind") == "run"), {})
    out: Dict[str, Any] = {
        "n_events": len(events),
        "run_id": run.get("run_id"),
        "requests": request_stats(events),
        "waterfalls": build_waterfalls(events),
    }
    out["n_sampled"] = len(out["waterfalls"])
    if slo and dones:
        out["slo"] = evaluate_slo(slo, dones)
    # the engine's own closing summary, when the stream has one — the
    # bench gate compares our percentiles against its reservoir numbers
    summary = next((e for e in reversed(events)
                    if e.get("kind") == "summary"), None)
    if summary is not None and summary.get("slo") is not None:
        out["slo_online"] = summary["slo"]
    return out


def to_request_chrome_trace(events: Sequence[dict], out_path: str,
                            max_lanes: int = 64) -> int:
    """Export the sampled waterfalls as a Chrome ``trace_event`` file
    with ONE process lane per request (lane name = trace id) — open in
    Perfetto and each request reads as its own queue/prefill/decode
    waterfall.  Returns the number of non-metadata trace events; lanes
    beyond ``max_lanes`` are dropped (earliest requests win)."""
    falls = build_waterfalls(events)
    by_trace: Dict[str, List[dict]] = {}
    for e in events:
        if e.get("kind") == "span" and e.get("trace"):
            by_trace.setdefault(str(e["trace"]), []).append(e)
    out: List[dict] = []
    for lane, w in enumerate(falls[:max_lanes]):
        out.extend(chrome_events(by_trace[w["trace"]], pid=lane,
                                 host=f"req {w['trace']}"))
    n = sum(1 for e in out if e["ph"] != "M")
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)
    return n


def _fmt_row(name: str, d: Optional[Dict[str, Any]]) -> str:
    if not d or not d.get("n"):
        return f"  {name:<11} (no samples)"
    return (f"  {name:<11} mean {d['mean_ms']:>9.3f}  "
            f"p50 {d['p50_ms']:>9.3f}  p90 {d['p90_ms']:>9.3f}  "
            f"p99 {d['p99_ms']:>9.3f} ms  ({d['n']} requests)")


def format_report(a: Dict[str, Any]) -> str:
    """Human-readable report (the CLI's default output)."""
    lines: List[str] = []
    rid = f" (run {a['run_id']})" if a.get("run_id") else ""
    st = a.get("requests")
    if not st:
        return (f"no finished serving requests in the stream "
                f"({a.get('n_events', 0)} events){rid}")
    lines.append(f"serving requests — {st['n_requests']} finished, "
                 f"{st['tokens_out']} tokens out, "
                 f"{a.get('n_sampled', 0)} traced{rid}")
    for name, _field in _METRICS:
        lines.append(_fmt_row(name, st.get(name)))
    bt = st.get("batch_tpot") or []
    if bt:
        curve = "  ".join(f"bs{r['batch_size']}={r['mean_step_ms']:.3f}ms"
                          f"(x{r['steps']})" for r in bt)
        lines.append(f"decode step by batch size: {curve}")
    slo = a.get("slo")
    if slo:
        verdict = ("met" if slo["met"] else "MISSED"
                   ) if slo["met"] is not None else "n/a"
        lines.append(f"slo [{slo['spec']}]: goodput "
                     f"{slo['goodput_pct']}% of target "
                     f"{slo['target_pct']}% — {verdict}")
        for o in slo.get("objectives", []):
            ach = (f"{1e3 * o['achieved_s']:.3f} ms"
                   if o.get("achieved_s") is not None else "n/a")
            mark = "ok" if o["ok"] else "VIOLATED"
            lines.append(f"  {o['objective']:<24} achieved {ach:>12}  "
                         f"{mark}")
    for w in a.get("waterfalls", [])[:8]:
        lines.append(f"trace {w['trace']}: {w['n_spans']} spans, "
                     f"{w['decode_steps']} decode steps, "
                     f"e2e {w['e2e_ms']} ms")
        for r in w["spans"][:6]:
            extra = "".join(f" {k}={r[k]}" for k in
                            ("slot", "bucket", "batch_size",
                             "prompt_len", "n_tokens") if k in r)
            lines.append(f"    {r['name']:<12} +{r['start_s']:.6f}s  "
                         f"{r['dur_ms']:>9.3f} ms{extra}")
        if w["n_spans"] > 6:
            lines.append(f"    ... {w['n_spans'] - 6} more spans")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.prof.requests",
        description="Per-request serving analysis of an apex_tpu "
                    "telemetry stream: TTFT/TPOT percentiles, SLO "
                    "goodput, traced-request waterfalls.")
    p.add_argument("streams", nargs="+",
                   help="one or more .jsonl event streams (globs and "
                        "rotated sets expand; several hosts merge onto "
                        "host 0's clock)")
    p.add_argument("--slo", metavar="SPEC",
                   help="evaluate goodput against a spec, e.g. "
                        "'ttft_p99<200ms,tpot_p99<30ms'")
    p.add_argument("--json", action="store_true",
                   help="emit the analysis as JSON instead of the report")
    p.add_argument("--chrome", metavar="OUT",
                   help="export sampled requests as a Chrome trace_event "
                        "file, one process lane per request")
    p.add_argument("--lanes", type=int, default=64,
                   help="max request lanes in the Chrome export "
                        "(default 64)")
    args = p.parse_args(argv)
    try:
        events = load_request_events(args.streams)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if not events:
        print(f"no events in {args.streams}", file=sys.stderr)
        return 1
    a = analyze(events, slo=args.slo)
    if args.chrome:
        n = to_request_chrome_trace(events, args.chrome,
                                    max_lanes=args.lanes)
        print(f"wrote {n} chrome trace events to {args.chrome}",
              file=sys.stderr)
    try:
        if args.json:
            print(json.dumps(a, indent=1))
        else:
            print(format_report(a))
    except BrokenPipeError:       # `... | head` is a supported consumer
        try:
            sys.stdout.close()
        except Exception:
            pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
