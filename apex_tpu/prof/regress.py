"""Cross-run regression differ — ``python -m apex_tpu.prof.regress``.

Closes the observability loop (ISSUE 6): the timeline analyzer and the
bench write structured summaries; this tool diffs two of them —
baseline vs current — and **exits non-zero when a metric regressed past
its tolerance**, so CI can gate on "this commit made the run slower /
stallier / noisier" without a human reading JSON.

Inputs are any of:

* ``python -m apex_tpu.prof.timeline run.jsonl --json`` output
  (schema-versioned; a FUTURE schema major is rejected with a clear
  error rather than mis-compared — see
  :func:`apex_tpu.prof.timeline.check_schema_version`);
* ``BENCH_EXTRA.json`` / bench headline summaries (no schema field;
  their flattened numeric keys are matched by the same direction
  rules).

Direction is inferred from the metric name: time/stall/gap/retrace/
alert-ish keys are **lower-is-better**, throughput/MFU/speedup-ish keys
are **higher-is-better**, anything unclassifiable is reported as info
and never fails the diff.  The default tolerance is 10% relative,
overridable per metric (substring match) with ``--tol``; percentage-
point metrics (``*_pct``) get an extra 2-point absolute slack so a 0.0
-> 0.3% stall wobble is not a CI failure while 0 -> 1 new retraces
still is.

Exit codes: 0 no regressions, 1 regressions found, 2 usage/schema
error.

::

    python -m apex_tpu.prof.timeline base.jsonl --json > base.json
    python -m apex_tpu.prof.timeline cur.jsonl  --json > cur.json
    python -m apex_tpu.prof.regress base.json cur.json \\
        --tol steps_per_s=5 --tol p99_ms=25
    python -m apex_tpu.prof.regress BENCH_PREV.json BENCH_EXTRA.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .timeline import check_schema_version

__all__ = ["flatten_metrics", "diff_summaries", "main"]

#: default relative tolerance, percent
DEFAULT_TOL_PCT = 10.0
#: absolute slack (same unit as the metric) for percentage-point
#: metrics — noise floor for near-zero stall/gap percentages
PCT_POINT_SLACK = 2.0

# Name patterns -> direction.  HIGHER-better is checked first
# ("steps_per_s" is throughput); the rate pattern requires per_s/per_sec
# to end a word so "ms_per_step_o2" (a time) cannot match it.
import re as _re

_HIGHER_RE = _re.compile(
    r"per_s(ec)?(_|$|\.)|img_s|it_s(_|$)|tok_s|tflops|mfu|speedup|gb_s"
    r"|(^|_)bw(_|$)|coverage|img/s|goodput")
_LOWER_RE = _re.compile(
    r"_ms(_|$|\.)|(^|\.)ms_|(^|_)time|stall|gap|retrace|skips|alert"
    r"|overhead|wall|compile|(^|_)dur(_|$)|wait|spread|_s$|_s\."
    r"|burn_(short|long|rate)")
# keys that are identifiers/config, never compared even though numeric
_SKIP_FRAGMENTS = ("schema_version", "batch", "seq", "iters", "n_params",
                   "n_tensors", "n_leaves", "n_buckets", "image_size",
                   "samples", "n_events", "windows", "reservoir", "count",
                   "n_dense", "heads", "head_dim", "tolerance", "gate",
                   # run length is config, not performance: two streams
                   # of different step counts must not diff on elapsed
                   "elapsed", "steps_traced")


def flatten_metrics(obj: Any, prefix: str = "") -> Dict[str, float]:
    """Flatten nested dicts into dotted numeric leaves; lists, strings,
    bools, and None are skipped (trajectories and labels are not
    metrics)."""
    out: Dict[str, float] = {}
    if not isinstance(obj, dict):
        return out
    for k, v in obj.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(flatten_metrics(v, key))
        elif isinstance(v, bool) or v is None:
            continue
        elif isinstance(v, (int, float)):
            out[key] = float(v)
    return out


def _direction(key: str) -> Optional[str]:
    kl = key.lower()
    for frag in _SKIP_FRAGMENTS:
        if frag in kl:
            return None
    if _HIGHER_RE.search(kl):
        return "higher"
    if _LOWER_RE.search(kl):
        return "lower"
    return None


def _tol_for(key: str, tols: Dict[str, float], default: float) -> float:
    """Most specific (longest) substring override wins."""
    best: Tuple[int, float] = (-1, default)
    for frag, pct in tols.items():
        if frag in key and len(frag) > best[0]:
            best = (len(frag), pct)
    return best[1]


def diff_summaries(base: Dict[str, Any], cur: Dict[str, Any], *,
                   tolerances: Optional[Dict[str, float]] = None,
                   default_tol_pct: float = DEFAULT_TOL_PCT
                   ) -> Dict[str, Any]:
    """Compare two summary dicts; returns ``{"regressions": [...],
    "improvements": [...], "unchanged": n, "skipped": n}`` where each
    entry is ``{metric, base, cur, ratio, tol_pct, direction}``.

    Only metrics present in BOTH inputs are judged.  A lower-is-better
    metric regresses when ``cur > base * (1 + tol) + slack``; a
    higher-is-better one when ``cur < base * (1 - tol) - slack``
    (``slack`` is :data:`PCT_POINT_SLACK` for ``*pct*`` keys, else 0 —
    so integer counters like retraces/alerts fail on ANY increase from
    zero).

    The result dict is schema-versioned like ``timeline --json``
    (ISSUE 10 satellite): CI consumes ``--json`` output and annotates
    regressions machine-readably instead of parsing stderr, and
    :func:`~apex_tpu.prof.timeline.check_schema_version` protects it
    from a future tool's incompatible diff shape the same way."""
    from .timeline import SCHEMA_VERSION
    tolerances = tolerances or {}
    fb, fc = flatten_metrics(base), flatten_metrics(cur)
    regressions: List[dict] = []
    improvements: List[dict] = []
    unchanged = skipped = 0
    for key in sorted(set(fb) & set(fc)):
        direction = _direction(key)
        if direction is None:
            skipped += 1
            continue
        b, c = fb[key], fc[key]
        tol = _tol_for(key, tolerances, default_tol_pct) / 100.0
        slack = PCT_POINT_SLACK if "pct" in key.lower() else 0.0
        entry = {"metric": key, "base": b, "cur": c,
                 "ratio": (round(c / b, 4) if b else None),
                 "tol_pct": round(tol * 100.0, 2), "direction": direction}
        if direction == "lower":
            if c > b * (1.0 + tol) + slack:
                regressions.append(entry)
            elif c < b * (1.0 - tol) - slack:
                improvements.append(entry)
            else:
                unchanged += 1
        else:
            if c < b * (1.0 - tol) - slack:
                regressions.append(entry)
            elif c > b * (1.0 + tol) + slack:
                improvements.append(entry)
            else:
                unchanged += 1
    return {"schema_version": SCHEMA_VERSION,
            "regressions": regressions, "improvements": improvements,
            "unchanged": unchanged, "skipped": skipped}


def _load(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError(f"{path}: expected a JSON object summary")
    if isinstance(obj.get("parsed"), dict):
        # Driver-artifact wrapper (the checked-in BENCH_rNN.json files
        # wrap the bench headline under "parsed" next to run metadata):
        # unwrap so a round artifact diffs directly against a fresh
        # BENCH_SUMMARY.json headline (ISSUE 7 CI satellite).
        obj = obj["parsed"]
    check_schema_version(obj, where=path)
    return obj


def _fmt(entry: dict) -> str:
    arrow = {"lower": "^", "higher": "v"}[entry["direction"]]
    ratio = (f" ({entry['ratio']}x)" if entry["ratio"] is not None else "")
    return (f"  {arrow} {entry['metric']}: {entry['base']:g} -> "
            f"{entry['cur']:g}{ratio}  [tol {entry['tol_pct']}%]")


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.prof.regress",
        description="Diff two timeline/bench summaries; exit 1 on "
                    "regressions past per-metric tolerances.")
    p.add_argument("base", help="baseline summary JSON "
                               "(timeline --json output or BENCH_EXTRA)")
    p.add_argument("current", help="current summary JSON")
    p.add_argument("--tol", action="append", default=[],
                   metavar="METRIC=PCT",
                   help="per-metric tolerance override (substring match, "
                        "longest wins); repeatable")
    p.add_argument("--tol-default", type=float, default=DEFAULT_TOL_PCT,
                   help=f"default relative tolerance in percent "
                        f"(default {DEFAULT_TOL_PCT})")
    p.add_argument("--json", action="store_true",
                   help="emit the full diff as JSON")
    args = p.parse_args(argv)

    tols: Dict[str, float] = {}
    for spec in args.tol:
        name, _, pct = spec.partition("=")
        try:
            tols[name] = float(pct)
        except ValueError:
            print(f"error: --tol expects METRIC=PCT, got {spec!r}",
                  file=sys.stderr)
            return 2
    try:
        base, cur = _load(args.base), _load(args.current)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    diff = diff_summaries(base, cur, tolerances=tols,
                          default_tol_pct=args.tol_default)
    if args.json:
        print(json.dumps(diff, indent=1))
    else:
        n_reg = len(diff["regressions"])
        print(f"regress: {args.base} -> {args.current}: "
              f"{n_reg} regression(s), {len(diff['improvements'])} "
              f"improvement(s), {diff['unchanged']} within tolerance, "
              f"{diff['skipped']} unclassified")
        for e in diff["regressions"]:
            print(_fmt(e))
        if diff["improvements"]:
            print("improvements:")
            for e in diff["improvements"][:12]:
                print(_fmt(e))
    return 1 if diff["regressions"] else 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(main())
