"""Measured-trace parsing — the ``pyprof.parse`` stage.

The reference reads an nvprof CUPTI SQLite DB and emits one record per
measured kernel, which ``pyprof.prof`` then joins with captured op markers
(``apex/pyprof/parse/nvvp.py:14+``, ``prof/prof.py:39-56``).  The XLA
equivalent: :func:`apex_tpu.prof.capture.trace` writes a TensorBoard
profile directory containing a Chrome-trace JSON (``*.trace.json.gz``)
whose complete events carry ``hlo_op`` / ``hlo_module`` / ``run_id`` args
and a wall duration per executed HLO op.  This module:

* :func:`parse_trace` — read the newest run in a trace logdir into
  :class:`KernelRecord` rows (one per measured op execution) plus per-op
  aggregates and per-``run_id`` step segmentation (the kernel↔iteration
  association of the reference parse stage).
* :func:`attach_measured` — join measured per-op durations onto the static
  :class:`~apex_tpu.prof.analysis.OpRecord` rows by normalized op name, so
  a single report shows measured time next to analytic FLOPs/bytes.

The fprop↔bprop correlation of the reference (``findFpropKernel`` by seq
id) maps onto ``run_id`` + HLO op-name suffix matching here: backward ops
lowered from the same primitive share its base name (``dot_general.N``),
so :func:`TraceProfile.by_op` groups them under one key.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Dict, List, NamedTuple, Optional

__all__ = ["KernelRecord", "TraceProfile", "parse_trace", "attach_measured",
           "LOOP_FUSION_CATEGORY"]

# XLA's ``hlo_category`` string for elementwise loop fusions — the
# category the optimizer state sweep of a train step lands in.  Named
# here (next to the parser that surfaces categories) so consumers like
# ``bench._bert_mfu_bound`` match it by constant instead of a string
# literal that silently drifts if the category tables ever rename it.
LOOP_FUSION_CATEGORY = "loop fusion"


class KernelRecord(NamedTuple):
    """One measured HLO-op execution (the reference's per-kernel dict)."""
    name: str              # raw hlo op name, e.g. "dot_general.1"
    base_op: str           # normalized, e.g. "dot_general"
    hlo_module: str        # e.g. "jit_step_fn"
    duration_us: float
    start_us: float
    run_id: str            # one executable launch == one step
    device: str
    # Device-event format extras (TPU traces; zero/empty on CPU traces):
    category: str = ""     # XLA hlo_category, e.g. "convolution fusion"
    model_flops: float = 0.0
    bytes_accessed: float = 0.0
    # full HLO instruction text ("%x = bf16[128,56,56,64]{...} fusion(...)")
    # — carries operand/output shapes for shape-signature attribution
    long_name: str = ""


_WRAP_RE = re.compile(r"^(?:wrapped_|fusion_)?(.*?)(?:\.\d+)?$")


def _normalize(hlo_op: str) -> str:
    m = _WRAP_RE.match(hlo_op)
    base = m.group(1) if m else hlo_op
    return base.replace("-", "_")


def _newest_run_dir(logdir: str) -> str:
    runs = sorted(glob.glob(os.path.join(logdir, "plugins", "profile", "*")))
    if not runs:
        raise FileNotFoundError(
            f"no profile runs under {logdir!r} (expected "
            f"plugins/profile/<timestamp>/) — did capture.trace run?")
    return runs[-1]


class TraceProfile:
    """Parsed measured trace: records + aggregates + step segmentation."""

    def __init__(self, records: List[KernelRecord]):
        self.records = records

    def by_op(self) -> Dict[str, dict]:
        """Aggregate measured time per normalized op name."""
        out: Dict[str, dict] = {}
        for r in self.records:
            agg = out.setdefault(r.base_op,
                                 {"count": 0, "total_us": 0.0, "max_us": 0.0})
            agg["count"] += 1
            agg["total_us"] += r.duration_us
            agg["max_us"] = max(agg["max_us"], r.duration_us)
        for agg in out.values():
            agg["mean_us"] = agg["total_us"] / agg["count"]
        return out

    def by_category(self) -> Dict[str, dict]:
        """Aggregate per XLA ``hlo_category`` (TPU device-event traces):
        measured time, XLA-attributed model FLOPs and bytes, and the
        achieved TFLOP/s while that category was running.  Empty for
        CPU-style traces (which carry no category)."""
        out: Dict[str, dict] = {}
        for r in self.records:
            if not r.category:
                continue
            agg = out.setdefault(r.category, {
                "count": 0, "total_us": 0.0, "flops": 0.0, "bytes": 0.0})
            agg["count"] += 1
            agg["total_us"] += r.duration_us
            agg["flops"] += r.model_flops
            agg["bytes"] += r.bytes_accessed
        for agg in out.values():
            agg["tflops_per_sec"] = (agg["flops"] / agg["total_us"] / 1e6
                                     if agg["total_us"] else 0.0)
        return out

    def steps(self) -> Dict[str, float]:
        """Wall time per ``run_id`` (one executable launch = one step) —
        the kernel↔iteration association of the reference parse stage."""
        out: Dict[str, float] = {}
        for r in self.records:
            out[r.run_id] = out.get(r.run_id, 0.0) + r.duration_us
        return out

    @property
    def total_us(self) -> float:
        return sum(r.duration_us for r in self.records)

    def summary(self, top: int = 20) -> str:
        rows = sorted(self.by_op().items(), key=lambda kv: -kv[1]["total_us"])
        lines = ["{:<28} {:>7} {:>12} {:>12}".format(
            "op", "count", "total_us", "mean_us")]
        for name, agg in rows[:top]:
            lines.append("{:<28} {:>7} {:>12.1f} {:>12.2f}".format(
                name, agg["count"], agg["total_us"], agg["mean_us"]))
        cats = self.by_category()
        if cats:
            lines.append("")
            lines.append("{:<28} {:>7} {:>12} {:>12}".format(
                "hlo_category", "count", "total_us", "TFLOP/s"))
            for name, agg in sorted(cats.items(),
                                    key=lambda kv: -kv[1]["total_us"])[:top]:
                lines.append("{:<28} {:>7} {:>12.1f} {:>12.1f}".format(
                    name, agg["count"], agg["total_us"],
                    agg["tflops_per_sec"]))
        lines.append(f"TOTAL measured: {self.total_us:.1f} us over "
                     f"{len(self.steps())} step(s)")
        return "\n".join(lines)


def parse_trace(logdir: str, module_filter: Optional[str] = None
                ) -> TraceProfile:
    """Parse the newest profile run under ``logdir`` into a
    :class:`TraceProfile`.

    ``module_filter``: keep only ops whose ``hlo_module`` contains the
    substring (e.g. ``"step_fn"`` to drop unrelated eager ops).
    """
    run_dir = _newest_run_dir(logdir)
    traces = glob.glob(os.path.join(run_dir, "*.trace.json.gz"))
    if not traces:
        raise FileNotFoundError(f"no *.trace.json.gz in {run_dir!r}")
    records: List[KernelRecord] = []
    for path in traces:
        with gzip.open(path, "rt") as f:
            data = json.load(f)
        for e in data.get("traceEvents", []):
            if e.get("ph") != "X":
                continue
            args = e.get("args") or {}
            hlo_op = args.get("hlo_op")
            if hlo_op:
                # CPU/GPU-style trace: per-op events with hlo_op/hlo_module.
                module = args.get("hlo_module", "")
                if module_filter and module_filter not in module:
                    continue
                records.append(KernelRecord(
                    name=hlo_op,
                    base_op=_normalize(hlo_op),
                    hlo_module=module,
                    duration_us=float(e.get("dur", 0.0)),
                    start_us=float(e.get("ts", 0.0)),
                    run_id=str(args.get("run_id", "")),
                    device=str(args.get("device_ordinal", ""))))
            elif "hlo_category" in args:
                # TPU device-event format: the event NAME is the HLO
                # instruction ("convert_reduce_fusion.12"), args carry
                # hlo_category / model_flops / bytes_accessed (the CUPTI
                # kernel-record analog on real chips).  No run_id — step
                # segmentation is unavailable — and no hlo_module either,
                # so ``module_filter`` is ignored here rather than matched
                # against instruction names (which would silently drop
                # every event).
                name = str(e.get("name", ""))
                records.append(KernelRecord(
                    name=name,
                    base_op=_normalize(name),
                    hlo_module="",
                    duration_us=float(e.get("dur", 0.0)),
                    start_us=float(e.get("ts", 0.0)),
                    run_id="",
                    device=str(e.get("pid", "")),
                    category=str(args.get("hlo_category", "")),
                    model_flops=float(args.get("model_flops") or 0.0),
                    bytes_accessed=float(args.get("bytes_accessed") or 0.0),
                    long_name=str(args.get("long_name", ""))))
    records.sort(key=lambda r: r.start_us)
    return TraceProfile(records)


# -- join with the static analysis (the reference ``prof`` stage input) -------

_STATIC_ALIASES = {
    # measured base op -> static primitive names it may cover
    "reduce": ("reduce_sum", "reduce_max", "reduce_min", "reduce_prod"),
    "reduce_window": ("reduce_window_sum", "reduce_window_max"),
    "convolution": ("conv_general_dilated",),
    "dot": ("dot_general",),
}


def attach_measured(profile, trace: TraceProfile, top: int = 20) -> str:
    """Render the static analysis with measured time joined per op name —
    analytic FLOPs/bytes next to actual microseconds (the reference's
    final per-op report, ``pyprof/prof/output.py``)."""
    measured = trace.by_op()

    static_by_op: Dict[str, dict] = {}
    for r in profile.records:
        agg = static_by_op.setdefault(r.op, {"flops": 0.0, "bytes": 0.0})
        agg["flops"] += r.flops * r.count
        agg["bytes"] += r.bytes * r.count

    # Expand aliases onto static primitive names.  A measured op that may
    # cover several static primitives (e.g. HLO "reduce" vs reduce_sum and
    # reduce_max) has its time *apportioned* by each row's analytic-FLOPs
    # share (evenly when all shares are zero) so per-op times still sum to
    # the trace total instead of double-counting.
    joined: Dict[str, dict] = dict(measured)
    for meas_name, prims in _STATIC_ALIASES.items():
        if meas_name not in measured:
            continue
        present = [p for p in prims
                   if p in static_by_op and p not in joined]
        if not present:
            continue
        total_flops = sum(static_by_op[p]["flops"] for p in present)
        for p in present:
            share = (static_by_op[p]["flops"] / total_flops
                     if total_flops else 1.0 / len(present))
            m = dict(measured[meas_name])
            m["total_us"] = m.get("total_us", 0.0) * share
            joined[p] = m

    lines = ["{:<24} {:>13} {:>13} {:>11} {:>11}".format(
        "op", "flops", "bytes", "meas_us", "GFLOP/s")]
    order = sorted(static_by_op.items(),
                   key=lambda kv: -joined.get(kv[0], {}).get("total_us", 0.0))
    for op, agg in order[:top]:
        m = joined.get(op)
        if m:
            us = m["total_us"]
            rate = agg["flops"] / us / 1e3 if us else 0.0
            lines.append("{:<24} {:>13.3g} {:>13.3g} {:>11.1f} {:>11.1f}"
                         .format(op, agg["flops"], agg["bytes"], us, rate))
        else:
            lines.append("{:<24} {:>13.3g} {:>13.3g} {:>11} {:>11}"
                         .format(op, agg["flops"], agg["bytes"], "-", "-"))
    unmatched = sorted(set(measured) - set(static_by_op)
                       - set(_STATIC_ALIASES))
    if unmatched:
        lines.append("measured-only ops: " + ", ".join(unmatched[:10]))
    return "\n".join(lines)


# -- CLI -----------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    """``python -m apex_tpu.prof.parse <logdir>`` — parse a trace dir and
    print the measured per-op report (the reference's runnable parse stage,
    ``python -m apex.pyprof.parse net.sql`` → per-kernel dicts,
    ``apex/pyprof/parse/parse.py:25``; here the "DB" is the XLA trace
    directory written by :func:`apex_tpu.prof.capture.trace`)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.prof.parse",
        description="Parse an XLA profiler trace directory into a measured "
                    "per-op report.")
    ap.add_argument("logdir", help="trace logdir (from prof.capture.trace)")
    ap.add_argument("--module-filter", default=None,
                    help="keep only ops whose hlo_module contains this "
                         "substring (CPU/GPU-style traces)")
    ap.add_argument("--top", type=int, default=20,
                    help="rows per table (default 20)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON record per measured op execution "
                         "instead of the summary table (the net.dict analog)")
    args = ap.parse_args(argv)

    trace = parse_trace(args.logdir, module_filter=args.module_filter)
    if args.json:
        for r in trace.records:
            print(json.dumps(r._asdict()))
    else:
        print(trace.summary(top=args.top))
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    raise SystemExit(main())
