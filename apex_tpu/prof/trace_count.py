"""Trace-count assertions — the runtime complement to jaxlint's J004.

The static analyzer (``tools/jaxlint``) can only *guess* at retracing
hazards from the AST; the ground truth is the jit cache itself.  This
module turns that cache into a test assertion so tier-1 pins the
compile behavior of the hot paths: a training step should trace exactly
once, and every subsequent call with same-shaped inputs should reuse
the trace.  A silent retrace-per-step is the failure mode that shows up
as a 10x dispatch-floor regression in ``bench.py`` while every
numerical test stays green.

Usage (the shape ``tests/test_prof.py`` gates on)::

    step = jax.jit(step_fn)
    with assert_trace_count(step, 1):          # first call compiles...
        state, m = step(state, batch)
        for _ in range(4):
            state, m = step(state, batch)      # ...the rest must not

    with assert_trace_count(step, 0):          # steady state: no retrace
        state, m = step(state, batch)

Counting is by the jitted callable's tracing-cache size (one entry per
(shapes, dtypes, static args) signature), so it needs no profiler, no
TPU, and works under ``JAX_PLATFORMS=cpu``.
"""

from __future__ import annotations

import contextlib

__all__ = ["trace_count", "assert_trace_count"]


def trace_count(jitted) -> int:
    """Number of distinct traces the jitted callable has performed so
    far (its tracing-cache size).  Accepts anything ``jax.jit`` /
    ``pjit`` returned."""
    # PjitFunction exposes the tracing-cache size; anything without it
    # (a plain function, a partial over a jitted callable) cannot be
    # counted — fail loudly rather than report 0 forever.
    size = getattr(jitted, "_cache_size", None)
    if size is None:
        raise TypeError(
            f"{jitted!r} has no tracing cache — pass the object returned "
            f"by jax.jit itself (not a wrapper around it)")
    return size()


@contextlib.contextmanager
def assert_trace_count(jitted, expect: int, *, exact: bool = True):
    """Assert that exactly (or, with ``exact=False``, at most)
    ``expect`` NEW traces of ``jitted`` happen inside the block.

    ``assert_trace_count(step, 1)`` around a warmup-plus-N-steps loop
    pins "one compile, zero retraces"; ``assert_trace_count(step, 0)``
    around steady-state calls pins "no retrace ever".
    """
    before = trace_count(jitted)
    yield
    got = trace_count(jitted) - before
    name = getattr(jitted, "__name__", repr(jitted))
    if got > expect:
        raise AssertionError(
            f"{name} traced {got} time(s) in this block, expected "
            f"{'exactly' if exact else 'at most'} {expect} — a retrace "
            f"per call usually means a Python scalar or a dtype/shape "
            f"varies across calls (jaxlint J004)")
    if exact and got < expect:
        raise AssertionError(
            f"{name} traced {got} time(s) in this block, expected exactly "
            f"{expect} — fewer traces than expected (not invoked enough, "
            f"or a signature was already cached before the block)")
