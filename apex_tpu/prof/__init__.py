"""apex_tpu.prof — profiling toolkit (reference ``apex/pyprof``).

Three stages, mapped TPU-natively (SURVEY.md §2.9, §5):

1. capture  → :mod:`apex_tpu.prof.capture` (named scopes into HLO metadata,
   ``jax.profiler`` device traces, optional arg markers).
2. parse    → :mod:`apex_tpu.prof.parse` reads the *measured* trace the
   capture stage wrote (Chrome-trace JSON with per-HLO-op durations and
   run ids — the CUPTI-SQLite analog) into per-kernel records; the static
   jaxpr walk in :mod:`analysis` complements it with analytic costs.
3. prof     → :mod:`apex_tpu.prof.analysis` (per-op flops/bytes/intensity
   records, MXU-eligibility column, XLA cost-model cross-check) +
   :func:`apex_tpu.prof.parse.attach_measured` joining measured time onto
   the analytic records.

Plus the compile-behavior assertion :mod:`apex_tpu.prof.trace_count`
(``assert_trace_count``) — the runtime complement to the static
``tools/jaxlint`` J004 retracing rule: wrap it around a jitted step in a
test to pin "one compile, zero retraces" — and the run-telemetry
analyzer :mod:`apex_tpu.prof.timeline` (``python -m
apex_tpu.prof.timeline run.jsonl``), which distills the structured
event streams :mod:`apex_tpu.telemetry` records into step-time
percentiles, stall/gap attribution, the loss-scale trajectory, retrace
reports, watchdog alerts, and per-collective byte totals.

ISSUE 6 closes the attribution loop with two more runnable stages (like
``timeline``, deliberately NOT imported here — ``python -m`` would trip
runpy's double-import warning; import them explicitly):

* :mod:`apex_tpu.prof.roofline` — per-region FLOP/byte harvest at trace
  time (``jit(...).lower().cost_analysis()`` with a jaxpr-walk fallback)
  joined with measured step times into an MFU ledger: achieved FLOP/s,
  compute-vs-memory boundedness against measured peaks, and
  steady-vs-best-window gap attribution.
* :mod:`apex_tpu.prof.regress` — ``python -m apex_tpu.prof.regress
  base.json cur.json`` diffs two timeline/bench summaries with
  per-metric tolerances and exits non-zero on regressions (the CI gate).
"""

from .analysis import OpRecord, Profile, profile_function   # noqa: F401
from .capture import (init, annotate, scope, trace,          # noqa: F401
                      dump_markers, MARKERS)
from .ledger import loader_ledger                            # noqa: F401
from .parse import (KernelRecord, TraceProfile, parse_trace,  # noqa: F401
                    attach_measured)
# NOTE: .timeline (the offline stream analyzer) is deliberately NOT
# imported here — ``python -m apex_tpu.prof.timeline`` would otherwise
# trip runpy's double-import warning; import it explicitly.
from .trace_count import assert_trace_count, trace_count     # noqa: F401
