"""apex_tpu.prof — profiling toolkit (reference ``apex/pyprof``).

Three stages, mapped TPU-natively (SURVEY.md §2.9, §5):

1. capture  → :mod:`apex_tpu.prof.capture` (named scopes into HLO metadata,
   ``jax.profiler`` device traces, optional arg markers).
2. parse    → the jaxpr/compiled-HLO *is* the database; no SQLite.
3. prof     → :mod:`apex_tpu.prof.analysis` (per-op flops/bytes/intensity
   records, MXU-eligibility column, XLA cost-model cross-check).
"""

from .analysis import OpRecord, Profile, profile_function   # noqa: F401
from .capture import (init, annotate, scope, trace,          # noqa: F401
                      dump_markers, MARKERS)
