"""HBM memory ledger — peak device memory per compiled step, attributed
per :func:`apex_tpu.prof.capture.scope` region (ISSUE 10 tentpole,
piece 3).

The stack measures FLOPs (``prof.roofline``) and wire bytes
(``collective`` events) everywhere but has had zero visibility into
HBM — the resource that actually kills runs first at scale (an OOM is
instant; a 20% MFU gap is Tuesday).  This module is the missing column:

1. **compiled totals** (:func:`harvest_memory`) — XLA's own accounting
   from ``jit(fn).lower(*args).compile().memory_analysis()``:
   argument / output / temp / generated-code bytes (the numbers the
   compiler actually reserves), when the jax in use exposes the API;
2. **live-buffer jaxpr walk** — a conservative fallback (and ALWAYS the
   per-region attribution source, mirroring how
   :func:`apex_tpu.prof.roofline.harvest_costs` keeps the matmul split
   on the walk): replay the jaxpr tracking which buffers are live after
   each equation (an output is born at its equation, dies after its
   last use; jaxpr outputs never die), record the running total's peak
   and snapshot the live set AT the peak — each buffer attributed to
   the :func:`~apex_tpu.prof.capture.region_path` region that produced
   it.  Conservative: no donation/aliasing, no XLA rematerialization —
   an upper bound XLA usually beats;
3. **the join** — :func:`apex_tpu.prof.roofline.mfu_ledger` takes
   ``memory=`` and adds a peak-HBM column (totals + per-region peak
   attribution + top allocations) to the roofline ledger ``bench.py``
   records in ``BENCH_EXTRA.json``;
4. **live gauges + watchdog** — :func:`device_memory` reads the
   backend's per-device allocator stats where exposed
   (``Device.memory_stats()``: TPU yes, CPU no), published as
   ``hbm_bytes_in_use``/``hbm_bytes_limit`` gauges by the Prometheus
   exporter, and :func:`record_memory` emits the ``memory`` event the
   ``memory_headroom`` watchdog rule folds (headroom below threshold →
   debounced alert BEFORE the OOM, not a post-mortem).

Everything here is trace/compile-time or host-API work: nothing runs on
the device, nothing is donated, and the training step's own jit cache
is untouched.

CLI::

    python -m apex_tpu.prof.memory --fn mymod:make_step [--json]
"""

from __future__ import annotations

import json
import math
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax

from .capture import region_path

__all__ = ["MemoryHarvest", "harvest_memory", "live_buffer_walk",
           "stats_from_analysis", "device_memory",
           "update_device_memory_gauges", "record_memory", "main"]


@dataclass
class MemoryHarvest:
    """One computation's memory ledger (one call of ``fn(*args)``).

    ``peak_bytes`` is the headline: XLA's compiled accounting
    (``argument + output + temp + generated``) when
    ``memory_analysis()`` exists (``source="memory_analysis"``), else
    the jaxpr walk's conservative live-buffer peak (``source="jaxpr"``).
    ``walk_peak_bytes`` is ALWAYS the walk's number (the XLA
    cross-check; the walk has no donation/remat, so expect it >= the
    compiled peak).  ``by_region`` maps each region to the bytes of its
    buffers live AT the walk's peak moment; ``top_allocations`` are the
    largest of those buffers individually."""
    peak_bytes: int
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    generated_code_bytes: int
    source: str                  # "memory_analysis" | "jaxpr"
    walk_peak_bytes: int
    by_region: Dict[str, int] = field(default_factory=dict)
    top_allocations: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def peak_gb(self) -> float:
        return self.peak_bytes / 1e9


def _aval_bytes(aval) -> int:
    try:
        import jax.numpy as jnp
        return (math.prod(aval.shape) if aval.shape else 1) \
            * jnp.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def _inner_jaxpr(eqn):
    from .analysis import _inner_jaxpr as inner
    return inner(eqn)


def live_buffer_walk(closed_jaxpr, *, region_depth: int = 1,
                     top: int = 8) -> Dict[str, Any]:
    """Conservative live-buffer replay of a jaxpr.

    Walks the equations in program order keeping the set of live
    buffers (born at their producing equation, freed after their last
    use at this jaxpr level; jaxpr outputs and invars live to the end),
    and records the peak running total plus a snapshot of the live set
    at that moment.  Call-like equations (pjit/scan/cond/custom-vjp)
    recurse: the callee's own transient peak — its walk peak minus its
    input bytes, which the caller already holds live — is charged while
    the call runs.  Scan bodies execute once per step but reuse the
    same buffers, so one body recursion is the right charge.

    Returns ``{"peak_bytes", "argument_bytes", "output_bytes",
    "by_region", "top_allocations"}``; regions come from the equations'
    ``named_scope`` stacks via :func:`~apex_tpu.prof.capture.region_path`
    (forward and backward of one user scope land in one row).
    """
    jaxpr = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") \
        else closed_jaxpr

    def walk(j, scope):
        """Returns (peak_bytes, peak_snapshot) for jaxpr ``j`` with its
        invars+constvars live; snapshot is {var-ish: (bytes, region,
        shape, dtype)} of the live set at the peak."""
        live: Dict[Any, tuple] = {}
        for v in list(j.invars) + list(j.constvars):
            if hasattr(v, "aval"):
                live[v] = (_aval_bytes(v.aval), "<arguments>",
                           tuple(getattr(v.aval, "shape", ())),
                           str(getattr(v.aval, "dtype", "?")))
        # last use per var AT THIS LEVEL; outvars never die.  Literals
        # are unhashable non-buffers and are skipped everywhere (a real
        # train step's jaxpr returns some: constant-folded metrics).
        last_use: Dict[Any, int] = {}
        for i, eqn in enumerate(j.eqns):
            for v in eqn.invars:
                if hasattr(v, "aval") and not isinstance(v, jax.core.Literal):
                    last_use[v] = i
        # never free outputs NOR this jaxpr's own inputs: XLA keeps
        # (non-donated) arguments allocated for the whole execution, so
        # a conservative upper bound must hold them resident even after
        # their last in-program use (review finding — freeing them made
        # the fallback peak an UNDER-estimate on argument-heavy steps,
        # which would have silenced the memory_headroom pre-OOM rule).
        keep = set(live)
        keep.update(v for v in j.outvars
                    if hasattr(v, "aval")
                    and not isinstance(v, jax.core.Literal))
        total = sum(b for b, *_ in live.values())
        peak, snap = total, dict(live)
        for i, eqn in enumerate(j.eqns):
            ns = getattr(getattr(eqn, "source_info", None),
                         "name_stack", None)
            ns = str(ns) if ns is not None else ""
            region = region_path("/".join(p for p in (scope, ns) if p),
                                 depth=region_depth)
            inner = _inner_jaxpr(eqn)
            transient = 0
            if inner is not None:
                name = eqn.params.get("name", eqn.primitive.name)
                sub_peak, sub_snap = walk(inner, f"{scope}/{name}"
                                          if scope else str(name))
                # charge only the callee's INTERNAL temps while the
                # call runs: its inputs are the operands the caller
                # already holds live, and its outputs are born as this
                # equation's outvars below — counting either inside the
                # transient would double-book them (a bare relu is a
                # custom_jvp call; its output must not count twice).
                sub_args = sum(
                    _aval_bytes(v.aval)
                    for v in list(inner.invars) + list(inner.constvars)
                    if hasattr(v, "aval"))
                sub_outs = sum(
                    _aval_bytes(v.aval) for v in inner.outvars
                    if hasattr(v, "aval")
                    and not isinstance(v, jax.core.Literal))
                transient = max(0, sub_peak - sub_args - sub_outs)
            # outputs are born...
            born = []
            for v in eqn.outvars:
                if not hasattr(v, "aval"):
                    continue
                nbytes = _aval_bytes(v.aval)
                live[v] = (nbytes, region,
                           tuple(getattr(v.aval, "shape", ())),
                           str(getattr(v.aval, "dtype", "?")))
                born.append(v)
                total += nbytes
            if total + transient > peak:
                peak, snap = total + transient, dict(live)
                if transient:
                    snap[("transient", i)] = (transient, region, (),
                                              "<callee temps>")
            # ...then operands whose last use this was are freed
            for v in eqn.invars:
                if isinstance(v, jax.core.Literal):
                    continue
                if (last_use.get(v) == i and v in live and v not in keep):
                    total -= live.pop(v)[0]
        return peak, snap

    peak, snap = walk(jaxpr, "")
    by_region: Dict[str, int] = {}
    allocs: List[Dict[str, Any]] = []
    for (nbytes, region, shape, dtype) in snap.values():
        by_region[region] = by_region.get(region, 0) + nbytes
        allocs.append({"bytes": int(nbytes), "region": region,
                       "shape": list(shape), "dtype": dtype})
    allocs.sort(key=lambda a: -a["bytes"])
    arg_bytes = sum(_aval_bytes(v.aval)
                    for v in list(jaxpr.invars) + list(jaxpr.constvars)
                    if hasattr(v, "aval"))
    out_bytes = sum(_aval_bytes(v.aval) for v in jaxpr.outvars
                    if hasattr(v, "aval")
                    and not isinstance(v, jax.core.Literal))
    return {"peak_bytes": int(peak), "argument_bytes": int(arg_bytes),
            "output_bytes": int(out_bytes), "by_region": by_region,
            "top_allocations": allocs[:max(1, top)]}


def stats_from_analysis(ma) -> Optional[Dict[str, int]]:
    """``CompiledMemoryStats`` -> plain byte dict (None when the object
    carries nothing usable).  ``peak_bytes`` is the reservation XLA
    itself reports: arguments + outputs + temps + generated code, less
    input/output aliasing (donated buffers counted once)."""
    if ma is None:
        return None
    def g(name):
        try:
            return int(getattr(ma, name, 0) or 0)
        except Exception:
            return 0
    arg = g("argument_size_in_bytes")
    out = g("output_size_in_bytes")
    temp = g("temp_size_in_bytes")
    gen = g("generated_code_size_in_bytes")
    alias = g("alias_size_in_bytes")
    if not any((arg, out, temp, gen)):
        return None
    return {"argument_bytes": arg, "output_bytes": out,
            "temp_bytes": temp, "generated_code_bytes": gen,
            "alias_bytes": alias,
            "peak_bytes": max(0, arg + out + temp + gen - alias)}


def _xla_memory(fn, *args, **kwargs) -> Optional[Dict[str, int]]:
    """Compile ``fn`` on its OWN jit instance (the training step's
    cache is untouched) and read ``memory_analysis()``.  None on old
    jax (no API) or any compile failure — callers fall back to the
    walk.  Kept separate so tests can monkeypatch it."""
    try:
        compiled = jax.jit(fn).lower(*args, **kwargs).compile()
        return stats_from_analysis(compiled.memory_analysis())
    except Exception:
        return None


def harvest_memory(fn, *args, xla: bool = True, region_depth: int = 1,
                   top: int = 8, **kwargs) -> MemoryHarvest:
    """Harvest the memory ledger for ONE call of ``fn(*args)``.

    Totals come from XLA's ``memory_analysis()`` when ``xla=True`` and
    the API exists; the per-region attribution (and, as fallback, the
    totals) always comes from :func:`live_buffer_walk` — the same
    primary/fallback split as :func:`~apex_tpu.prof.roofline
    .harvest_costs`, and for the same reason: the attribution must not
    shift when jax versions change what they expose.  Pure trace /
    AOT-compile analysis — nothing executes on a device."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    w = live_buffer_walk(closed, region_depth=region_depth, top=top)
    xm = _xla_memory(fn, *args, **kwargs) if xla else None
    if xm is not None:
        return MemoryHarvest(
            peak_bytes=xm["peak_bytes"],
            argument_bytes=xm["argument_bytes"],
            output_bytes=xm["output_bytes"],
            temp_bytes=xm["temp_bytes"],
            generated_code_bytes=xm["generated_code_bytes"],
            source="memory_analysis",
            walk_peak_bytes=w["peak_bytes"],
            by_region=w["by_region"],
            top_allocations=w["top_allocations"])
    return MemoryHarvest(
        peak_bytes=w["peak_bytes"],
        argument_bytes=w["argument_bytes"],
        output_bytes=w["output_bytes"],
        temp_bytes=max(0, w["peak_bytes"] - w["argument_bytes"]
                       - w["output_bytes"]),
        generated_code_bytes=0,
        source="jaxpr",
        walk_peak_bytes=w["peak_bytes"],
        by_region=w["by_region"],
        top_allocations=w["top_allocations"])


# -- live device memory -------------------------------------------------------

def device_memory() -> List[Dict[str, Any]]:
    """Per-local-device allocator stats where the backend exposes them
    (``Device.memory_stats()`` — TPU/GPU yes, CPU typically None).
    Returns ``[{"id", "kind", "bytes_in_use", "bytes_limit", ...}]``,
    possibly empty.  A host API read — no device sync."""
    out: List[Dict[str, Any]] = []
    try:
        devices = jax.local_devices()
    except Exception:
        return out
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        out.append({
            "id": int(getattr(d, "id", len(out))),  # jaxlint: disable=J001 -- Device.memory_stats()/.id are host allocator-API reads (plain python ints), not device round-trips
            "kind": str(getattr(d, "device_kind", "?")),
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
        })
    return out


def update_device_memory_gauges(recorder) -> bool:
    """Publish summed local-device memory into the recorder's registry
    (``hbm_bytes_in_use`` / ``hbm_bytes_limit`` / ``hbm_headroom_pct``
    gauges the Prometheus exporter renders).  Returns True when the
    backend exposed anything."""
    devs = device_memory()
    if not devs:
        return False
    in_use = sum(d["bytes_in_use"] for d in devs)
    limit = sum(d["bytes_limit"] for d in devs)
    recorder.metrics.gauge("hbm_bytes_in_use").set(in_use)
    # allocator high-water mark: monotonic, never dips with a poll
    recorder.metrics.gauge("hbm_peak_bytes_in_use").set_max(
        sum(d["peak_bytes_in_use"] or d["bytes_in_use"] for d in devs))
    if limit:
        recorder.metrics.gauge("hbm_bytes_limit").set(limit)
        recorder.metrics.gauge("hbm_headroom_pct").set(
            100.0 * max(0.0, 1.0 - in_use / limit))
    return True


def record_memory(recorder, harvest_or_stats,
                  limit_bytes: Optional[int] = None,
                  **fields) -> Optional[dict]:
    """Emit one ``memory`` event (``phase="harvest"``) into the stream —
    the hook the ``memory_headroom`` watchdog rule folds and
    ``prof.fleet`` reads per host.

    ``harvest_or_stats`` is a :class:`MemoryHarvest` or a plain byte
    dict (:func:`stats_from_analysis` shape).  ``limit_bytes`` defaults
    to the SMALLEST per-device ``bytes_limit`` the backend exposes —
    an executable's peak is a per-device footprint, so the binding
    constraint is one chip's HBM, and comparing against the summed
    fleet limit would overstate headroom ~n_devices-fold and silence
    the pre-OOM rule (review finding).  With a limit the event carries
    ``headroom_pct``; the ``peak_hbm_bytes`` gauge is set either way.
    Returns the event fields (or None with no recorder)."""
    if recorder is None:
        return None
    if isinstance(harvest_or_stats, MemoryHarvest):
        h = harvest_or_stats
        stats = {"peak_bytes": h.peak_bytes,
                 "argument_bytes": h.argument_bytes,
                 "output_bytes": h.output_bytes,
                 "temp_bytes": h.temp_bytes,
                 "generated_code_bytes": h.generated_code_bytes,
                 "source": h.source}
    else:
        stats = dict(harvest_or_stats)
    if limit_bytes is None:
        limits = [d["bytes_limit"] for d in device_memory()
                  if d["bytes_limit"]]
        limit_bytes = min(limits) if limits else None
    ev = {"phase": "harvest", **stats, **fields}
    if limit_bytes:
        ev["bytes_limit"] = int(limit_bytes)
        ev["headroom_pct"] = round(
            100.0 * max(0.0, 1.0 - stats.get("peak_bytes", 0)
                        / limit_bytes), 2)
    # high-water mark across harvests (a smaller re-harvest — e.g. a
    # second pipeline's ledger — must not shrink the run's peak)
    recorder.metrics.gauge("peak_hbm_bytes").set_max(
        stats.get("peak_bytes", 0))
    recorder.event("memory", **ev)
    return ev


# -- CLI ----------------------------------------------------------------------

def format_harvest(h: MemoryHarvest) -> str:
    """Human-readable ledger (the CLI's default output)."""
    lines = [f"memory ledger ({h.source}): peak "
             f"{h.peak_bytes / 1e6:.3f} MB  (args "
             f"{h.argument_bytes / 1e6:.3f}, outputs "
             f"{h.output_bytes / 1e6:.3f}, temps "
             f"{h.temp_bytes / 1e6:.3f}, code "
             f"{h.generated_code_bytes / 1e6:.3f})"]
    if h.source != "jaxpr":
        lines.append(f"walk peak (conservative, no donation/remat): "
                     f"{h.walk_peak_bytes / 1e6:.3f} MB")
    lines.append("{:<30} {:>12}".format("region @ walk peak", "MB"))
    for name, b in sorted(h.by_region.items(), key=lambda kv: -kv[1]):
        lines.append("{:<30} {:>12.3f}".format(name[:30], b / 1e6))
    lines.append("top allocations at peak:")
    for a in h.top_allocations:
        lines.append(f"  {a['bytes'] / 1e6:10.3f} MB  {a['region']}  "
                     f"{a['dtype']}{a['shape']}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m apex_tpu.prof.memory`` — harvest one target's memory
    ledger (``--fn module:callable`` returning ``(fn, example_args)``,
    the ``prof.analysis`` convention)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.prof.memory",
        description="Peak-HBM ledger with per-region attribution.")
    ap.add_argument("--fn", default="__graft_entry__:entry",
                    help="module:callable returning (fn, example_args)")
    ap.add_argument("--region-depth", type=int, default=1)
    ap.add_argument("--top", type=int, default=8)
    ap.add_argument("--no-xla", action="store_true",
                    help="skip memory_analysis() (jaxpr walk only)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    from .analysis import _load_target

    fn, ex = _load_target(args.fn)()
    h = harvest_memory(fn, *ex, xla=not args.no_xla,
                       region_depth=args.region_depth, top=args.top)
    if args.json:
        from dataclasses import asdict
        print(json.dumps(asdict(h), indent=1))
    else:
        print(format_harvest(h))
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(main())
