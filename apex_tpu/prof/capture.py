"""Scope annotation + device-trace capture — the ``pyprof.nvtx`` stage.

The reference monkey-patches every torch function to push NVTX ranges with
op name/shapes (``pyprof/nvtx/nvmarker.py:67-213``).  Under jit that
technique is hostile to tracing; the TPU-native equivalents are:

* :func:`annotate` / :func:`scope` — ``jax.named_scope`` wrappers; the scope
  names flow into HLO metadata and show up in XLA profiler traces (the NVTX
  range analog, visible in Perfetto/TensorBoard).
* :func:`init` — reference API parity (``pyprof.nvtx.init()``): installs
  nothing globally (nothing to patch — tracing sees every op anyway) but
  flips a flag so :func:`annotate` records call markers with arg shapes
  into :data:`MARKERS`, mirroring the reference's traceMarker/argMarker
  dicts for tooling that consumed them.
* :func:`trace` — context manager around ``jax.profiler.trace`` (the
  ``nvprof -o net.sql`` analog; output is a TensorBoard/Perfetto trace
  directory instead of a CUPTI SQLite DB).
"""

from __future__ import annotations

import contextlib
import functools
import json
import re
from typing import Any, Callable, List

import jax

MARKERS: List[dict] = []
_enabled = False

# Transform wrappers jax folds AROUND user scope names in the jaxpr's
# name stack: a forward scope ``blockA`` reappears in the backward pass
# as ``jvp(blockA)`` / ``transpose(jvp(blockA))``, and call machinery
# contributes bare components like ``pjit``/``scan``.  Region
# attribution (``prof.roofline``) must see ONE region for fwd+bwd, so
# these are peeled/dropped by :func:`region_path`.
_TRANSFORM_WRAP_RE = re.compile(
    r"^(?:jit|pjit|jvp|vjp|transpose|vmap|pmap|remat|checkpoint|rematted"
    r"|custom_[a-z_]+|named)\((.*)\)$")
# Bare call-machinery components are dropped by EXACT match — a user
# region that merely starts with one of these names ('branch2a',
# 'body_net', 'scanner') must survive (review finding); only the
# 'custom_*' family is a genuine prefix.
_TRANSFORM_BARE = frozenset(
    ("jit", "pjit", "jvp", "vjp", "transpose", "vmap", "pmap", "scan",
     "while", "cond", "remat", "checkpoint", "rematted", "named", "body",
     "branch", "branches"))


def _peel(component: str) -> str:
    """Strip transform wrappers off one name-stack component:
    ``transpose(jvp(blockA))`` -> ``blockA``; a bare transform name
    (``pjit``, ``scan``) peels to the empty string."""
    prev = None
    while prev != component:
        prev = component
        m = _TRANSFORM_WRAP_RE.match(component)
        if m:
            component = m.group(1)
    if component in _TRANSFORM_BARE or component.startswith("custom_"):
        return ""
    # conv backward machinery: XLA emits dgrad/wgrad under
    # ``conv_general_dilated_transpose_lhs``/``..._rhs`` name-stack
    # components (a prefix family like ``custom_*``).  Dropping them
    # keeps a conv's dgrad/wgrad on the SAME ledger row as its forward
    # region instead of splitting off and diluting per-region MFU.
    if component.startswith("conv_general_dilated"):
        return ""
    return component


def region_path(scope: str, depth: int = 1) -> str:
    """Collapse a jaxpr scope / name-stack path to its leading ``depth``
    USER region components — the :func:`scope`/:func:`annotate` names,
    with jax's transform wrappers peeled so forward and backward ops of
    one region land in the same row (``transpose(jvp(blockA))/mm1`` and
    ``blockA/mm1`` both map to ``blockA`` at depth 1, ``blockA/mm1`` at
    depth 2).  Ops outside any user scope map to ``<unattributed>``."""
    parts = []
    for p in scope.split("/"):
        p = _peel(p.strip())
        if p:
            parts.append(p)
    if not parts:
        return "<unattributed>"
    return "/".join(parts[:max(1, depth)])


def init(enable_markers: bool = True) -> None:
    """Reference ``pyprof.nvtx.init()`` parity (nvmarker.py:206-213)."""
    global _enabled
    _enabled = enable_markers


def _arg_marker(fn_name: str, args, kwargs) -> dict:
    def describe(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return {"shape": tuple(int(s) for s in x.shape),
                    "dtype": str(x.dtype)}
        if isinstance(x, (int, float, bool, str)) or x is None:
            return {"value": x}
        return {"type": type(x).__name__}
    return {"op": fn_name,
            "args": [describe(a) for a in args],
            "kwargs": {k: describe(v) for k, v in kwargs.items()}}


@contextlib.contextmanager
def scope(name: str):
    """Named scope context; name lands in HLO metadata / profiler traces
    (and, after :func:`init`, as a ``marker`` event in an active
    telemetry stream — same contract as :func:`annotate`).  These names
    are the region keys :mod:`apex_tpu.prof.roofline` attributes
    harvested FLOPs/bytes to (see :func:`region_path`)."""
    if _enabled:
        from .. import telemetry as _telemetry
        rec = _telemetry.get_recorder()
        if rec is not None:
            rec.event("marker", op=name, args=[], kwargs={})
    with jax.named_scope(name):
        yield


def annotate(name: str = None) -> Callable:
    """Decorator: run the function under a named scope and (when
    :func:`init` was called) record an arg marker per trace."""
    def deco(fn):
        scope_name = name or getattr(fn, "__name__", "fn")

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            if _enabled:
                marker = _arg_marker(scope_name, args, kwargs)
                MARKERS.append(marker)
                # Telemetry (ISSUE 5): the same marker also lands in the
                # run's event stream, timestamped — the traceMarker dicts
                # become tail-able run events instead of a post-hoc dump.
                from .. import telemetry as _telemetry
                rec = _telemetry.get_recorder()
                if rec is not None:
                    rec.event("marker", **marker)
            with jax.named_scope(scope_name):
                return fn(*args, **kwargs)
        return wrapped
    return deco


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace (XLA profiler) to ``logdir`` — open with
    TensorBoard or Perfetto.  The ``emit_nvtx + nvprof`` analog."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def dump_markers(path: str) -> None:
    """Write collected markers as JSON lines (the ``net.dict`` analog the
    reference's ``parse`` stage emits for the ``prof`` stage)."""
    with open(path, "w") as f:
        for m in MARKERS:
            f.write(json.dumps(m) + "\n")
