"""HBM bytes ledger — measured fusion traffic vs model-intrinsic traffic.

VERDICT r4 #1: the ResNet-50 O2 step moves ~28 GB through conv fusions at
~93% of HBM peak, roughly 3x a back-of-envelope intrinsic estimate — being
bound by *the traffic XLA chose* is not being bound by *the model*.  This
module turns that envelope into a ledger:

* **intrinsic** (:func:`intrinsic_ledger`) — the traffic a perfectly
  fused program would move, computed from the jaxpr: every ``conv`` /
  ``dot_general`` reads its operands and writes its outputs at their
  actual dtypes (elementwise ops, casts, and reductions fuse into their
  producers/consumers for free in the ideal program — charging them too
  would double-count every activation), plus the optimizer-side traffic
  over the parameter leaves (grad read, master read+write, momentum
  read+write, compute-cast write — the cast *read* is a conv operand,
  already counted).  Grouped by ``named_scope``/flax module path, so the
  result is a per-layer table.
* **measured** (:func:`measured_ledger`) — per-fusion ``bytes_accessed``
  and duration from a real device trace
  (:func:`apex_tpu.prof.parse.parse_trace`), aggregated by hlo_category
  and listing the top fusions.
* **join** (:func:`bytes_ledger`) — ``measured / intrinsic`` per
  category-of-interest and in total: the number that says how much of the
  roofline story is the model and how much is XLA's schedule.

Reference anchor: the fused-kernel premise of apex — everything except
the math should be free (``csrc/multi_tensor_scale_kernel.cu:18-77``).
The TPU analog of "free" is "fused into the conv stream"; this ledger
measures how closely XLA approaches it.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from .analysis import profile_function

#: the MXU-eligible primitives: FLOPs charged to these are "matmul
#: FLOPs" everywhere downstream (this ledger's compute rows, and the
#: roofline engine's MFU numerator — ``prof.roofline`` imports this so
#: the two attributions can never disagree on what counts as math).
COMPUTE_OPS = ("conv_general_dilated", "dot_general")
_COMPUTE_OPS = COMPUTE_OPS

# Optimizer-side bytes per parameter ELEMENT for the O2 momentum-SGD /
# master-weights contract, beyond what conv/dot operands already count:
#   grad read (4) + master read (4) + master write (4)
#   + momentum read (4) + momentum write (4) + bf16 compute-cast write (2)
# The bf16 cast READ and the wgrad OUTPUT write are conv operands/outputs.
_OPT_BYTES_PER_PARAM_SGD = 22
#   adam: grad r(4) + master r/w(8) + m r/w(8) + v r/w(8) + cast w(2)
_OPT_BYTES_PER_PARAM_ADAM = 30


def _layer_of(scope: str) -> str:
    """Collapse a named_scope path to a readable layer key: the last two
    model-structure components (e.g. ``.../ResNet/layer3_2/conv2`` ->
    ``layer3_2/conv2``); transposed (backward) ops keep the same key, so
    fwd+bwd traffic lands in one row."""
    parts = [p for p in scope.split("/")
             if p and not p.startswith(("jit", "jvp", "transpose",
                                        "pjit", "scan", "while", "cond"))]
    if not parts:
        return "<top>"
    return "/".join(parts[-2:])


def intrinsic_ledger(fn, *args, n_params: Optional[int] = None,
                     optimizer: str = "sgd", prof=None) -> Dict[str, Any]:
    """Model-intrinsic HBM traffic of one call of ``fn(*args)``.

    Returns ``{"total_gb", "compute_gb", "optimizer_gb", "by_layer":
    [{layer, gb, flops_g, ops}...]}``; ``n_params`` (needed for the
    optimizer term) defaults to 0 when not supplied.  ``prof`` reuses an
    existing :func:`profile_function` result (the trace is expensive on
    a multi-thousand-equation train step — bytes_ledger shares one).
    """
    if prof is None:
        prof = profile_function(fn, *args, xla_cost=False)
    by_layer: Dict[str, Dict[str, float]] = {}
    compute_bytes = 0.0
    for r in prof.records:
        if r.op not in _COMPUTE_OPS:
            continue
        row = by_layer.setdefault(_layer_of(r.name),
                                  {"bytes": 0.0, "flops": 0.0, "ops": 0})
        row["bytes"] += r.bytes * r.count
        row["flops"] += r.flops * r.count
        row["ops"] += r.count
        compute_bytes += r.bytes * r.count
    per_param = (_OPT_BYTES_PER_PARAM_ADAM if optimizer == "adam"
                 else _OPT_BYTES_PER_PARAM_SGD)
    opt_bytes = float(n_params or 0) * per_param
    layers = [
        {"layer": k, "gb": round(v["bytes"] / 1e9, 4),
         "gflops": round(v["flops"] / 1e9, 1), "ops": v["ops"]}
        for k, v in sorted(by_layer.items(), key=lambda kv: -kv[1]["bytes"])]
    return {
        "total_gb": round((compute_bytes + opt_bytes) / 1e9, 3),
        "compute_gb": round(compute_bytes / 1e9, 3),
        "optimizer_gb": round(opt_bytes / 1e9, 3),
        "optimizer_model": f"{per_param} B/param ({optimizer})",
        "by_layer": layers,
    }


def _bridge_bytes(fn, *args, gap: int = 100) -> Dict[str, Any]:
    """Unavoidable fwd->bwd spill traffic: values produced more than
    ``gap`` equations before a consumer cannot stay resident in VMEM
    (~128 MB) across the intervening work, so they MUST be written to and
    re-read from HBM no matter how the program is fused — the saved
    activations of the backward pass.  Counted one write + one read per
    distant consumer, at the value's dtype, EXCLUDING values that are
    conv/dot operands (the compute ledger already charges those reads).

    The gap threshold is a documented approximation: in a fwd+bwd jaxpr
    the saved-residual distances are hundreds-to-thousands of equations,
    while fusable producer-consumer chains sit within a few.  Returns
    totals plus a per-spatial-stage breakdown (same keys as
    :func:`intrinsic_by_shape`).
    """
    jaxpr = jax.make_jaxpr(fn)(*args)
    counter = [0]
    produced: Dict[Any, int] = {}
    conv_operands = set()
    bridges: Dict[Any, Dict[str, Any]] = {}

    def walk(jx):
        for eqn in jx.eqns:
            prim = eqn.primitive.name
            inner = None
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                if key in eqn.params and eqn.params[key] is not None:
                    inner = eqn.params[key]
                    inner = getattr(inner, "jaxpr", inner)
                    break
            idx = counter[0]
            counter[0] += 1
            for v in eqn.invars:
                if not hasattr(v, "aval") or not hasattr(v.aval, "shape"):
                    continue
                if type(v).__name__ == "Literal":
                    continue
                if prim in _COMPUTE_OPS:
                    conv_operands.add(v)
                p = produced.get(v)
                if p is not None and idx - p > gap:
                    b = bridges.setdefault(v, {"reads": 0, "aval": v.aval})
                    b["reads"] += 1
            for v in eqn.outvars:
                produced[v] = idx
            if inner is not None:
                walk(inner)

    walk(jaxpr.jaxpr)
    total = 0.0
    by_stage: Dict[str, float] = {}
    for v, b in bridges.items():
        if v in conv_operands:
            continue          # already charged as a conv/dot operand read
        aval = b["aval"]
        nbytes = (math.prod(aval.shape) if aval.shape else 1) * \
            jnp.dtype(aval.dtype).itemsize
        t = nbytes * (1 + b["reads"])          # one write + distant reads
        total += t
        sig = "other"
        if len(aval.shape) == 4:
            sig = f"hw{aval.shape[1]}"
        by_stage[sig] = by_stage.get(sig, 0.0) + t
    return {"gb": round(total / 1e9, 3), "gap_eqns": gap,
            "by_stage": {k: round(vv / 1e9, 4)
                         for k, vv in by_stage.items()}}


def measured_ledger(tp, steps: int = 1) -> Dict[str, Any]:
    """Aggregate a parsed device trace into per-category and top-fusion
    bytes/time rows (per step, given ``steps`` traced)."""
    cats = {}
    for name, agg in sorted(tp.by_category().items(),
                            key=lambda kv: -kv[1]["total_us"]):
        cats[name] = {
            "us": round(agg["total_us"] / steps, 1),
            "gb": round(agg["bytes"] / steps / 1e9, 3),
            "gb_per_s": round(
                agg["bytes"] / (agg["total_us"] * 1e-6) / 1e9, 1)
            if agg["total_us"] else 0.0,
        }
    # top individual fusions by bytes (per step)
    per_op: Dict[str, Dict[str, float]] = {}
    for r in tp.records:
        agg = per_op.setdefault(r.name, {"us": 0.0, "bytes": 0.0,
                                         "count": 0,
                                         "category": r.category})
        agg["us"] += r.duration_us
        agg["bytes"] += r.bytes_accessed
        agg["count"] += 1
    top = [
        {"op": name, "category": a["category"],
         "us": round(a["us"] / steps, 1),
         "gb": round(a["bytes"] / steps / 1e9, 4),
         "gb_per_s": round(a["bytes"] / (a["us"] * 1e-6) / 1e9, 1)
         if a["us"] else 0.0}
        for name, a in sorted(per_op.items(),
                              key=lambda kv: -kv[1]["bytes"])[:10]]
    total_gb = sum(c["gb"] for c in cats.values())
    return {"total_gb": round(total_gb, 3), "by_category": cats,
            "top_fusions_by_bytes": top}


_SHAPE_RE = re.compile(r"(?:bf16|f32|f16|s32|u32|s8|u8)\[([\d,]+)\]")


def _spatial_sig(long_name: str) -> str:
    """Shape-signature group key for one HLO instruction: the spatial dim
    of the largest 4-D NHWC tensor mentioned in its text (conv fusions
    carry their activation shapes there), or ``other``.  Python source
    lines cannot attribute fusions to model layers (every residual block
    shares the same lines), and the executable renames fusions after the
    backend passes, so shape signatures — which survive both — are the
    honest join key at resolution-stage granularity."""
    best_elems, best_h = 0, None
    for dims in _SHAPE_RE.findall(long_name):
        parts = [int(x) for x in dims.split(",") if x]
        if len(parts) != 4:
            continue
        elems = math.prod(parts)
        if elems > best_elems:
            best_elems, best_h = elems, parts[1]
    return f"hw{best_h}" if best_h else "other"


def measured_by_shape(tp, steps: int = 1,
                      categories=("convolution fusion",)
                      ) -> Dict[str, Dict[str, float]]:
    """Per-spatial-stage measured bytes/time for the given categories."""
    rows: Dict[str, Dict[str, float]] = {}
    for r in tp.records:
        if categories and r.category not in categories:
            continue
        sig = _spatial_sig(r.long_name)
        agg = rows.setdefault(sig, {"us": 0.0, "bytes": 0.0, "count": 0})
        agg["us"] += r.duration_us
        agg["bytes"] += r.bytes_accessed
        agg["count"] += 1
    return {k: {"us": round(v["us"] / steps, 1),
                "gb": round(v["bytes"] / steps / 1e9, 4),
                "count": v["count"] // max(steps, 1)}
            for k, v in rows.items()}


def intrinsic_by_shape(fn, *args, prof=None) -> Dict[str, Dict[str, float]]:
    """Per-spatial-stage intrinsic conv/dot traffic, same grouping as
    :func:`measured_by_shape` (largest 4-D operand/output's H dim)."""
    if prof is None:
        prof = profile_function(fn, *args, xla_cost=False)
    rows: Dict[str, Dict[str, float]] = {}
    for r in prof.records:
        if r.op not in _COMPUTE_OPS:
            continue
        best_elems, best_h = 0, None
        for shp in list(r.in_shapes) + list(r.out_shapes):
            if len(shp) != 4:
                continue
            elems = math.prod(shp)
            if elems > best_elems:
                best_elems, best_h = elems, shp[1]
        sig = f"hw{best_h}" if best_h else "other"
        agg = rows.setdefault(sig, {"bytes": 0.0, "count": 0})
        agg["bytes"] += r.bytes * r.count
        agg["count"] += r.count
    return {k: {"gb": round(v["bytes"] / 1e9, 4), "count": v["count"]}
            for k, v in rows.items()}


def bytes_ledger(fn, args, tp, steps: int = 1,
                 n_params: Optional[int] = None,
                 optimizer: str = "sgd",
                 conv_categories=("convolution fusion",)) -> Dict[str, Any]:
    """The joined ledger: measured / intrinsic ratios, plus a
    per-resolution-stage measured-vs-intrinsic table joined through
    shape signatures.

    ``fn(*args)`` must be the SAME step the trace ``tp`` measured.
    """
    prof = profile_function(fn, *args, xla_cost=False)   # traced ONCE
    intr = intrinsic_ledger(fn, *args, n_params=n_params,
                            optimizer=optimizer, prof=prof)
    meas = measured_ledger(tp, steps=steps)
    bridge = _bridge_bytes(fn, *args)    # needs var identity: own jaxpr
    conv_meas = sum(meas["by_category"].get(c, {}).get("gb", 0.0)
                    for c in conv_categories)
    # v2 intrinsic: compute-boundary traffic + optimizer traffic + the
    # unavoidable fwd->bwd saved-tensor spills (see _bridge_bytes).
    intr_v2 = round(intr["total_gb"] + bridge["gb"], 3)
    out = {
        "intrinsic": intr,
        "bridge_saved_tensors": bridge,
        "intrinsic_v2_total_gb": intr_v2,
        "measured": meas,
        "ratio_total": (round(meas["total_gb"] / intr["total_gb"], 2)
                        if intr["total_gb"] else None),
        "ratio_total_vs_v2": (round(meas["total_gb"] / intr_v2, 2)
                              if intr_v2 else None),
        "ratio_conv_vs_intrinsic_compute": (
            round(conv_meas / intr["compute_gb"], 2)
            if intr["compute_gb"] else None),
    }
    # Per-resolution-stage join (shape signatures survive both the
    # backend's fusion renaming and python-line ambiguity; see
    # _spatial_sig).  Measured conv + loop-fusion bytes vs intrinsic
    # conv/dot + bridge bytes, per stage — elementwise loop fusions are
    # where the saved-tensor reads physically execute, so both sides of
    # the join must include them.
    meas_shapes = measured_by_shape(
        tp, steps=steps, categories=tuple(conv_categories) + (
            "loop fusion", "output fusion"))
    intr_shapes = intrinsic_by_shape(fn, *args, prof=prof)
    joined = []
    for sig, m in sorted(meas_shapes.items(), key=lambda kv: -kv[1]["gb"]):
        row = {"stage": sig, "measured_gb": m["gb"], "us": m["us"],
               "fusions": m["count"]}
        il = intr_shapes.get(sig, {}).get("gb", 0.0)
        ib = bridge["by_stage"].get(sig, 0.0)
        if il or ib:
            row["intrinsic_gb"] = round(il + ib, 4)
            row["ratio"] = (round(m["gb"] / (il + ib), 2)
                            if (il + ib) else None)
        joined.append(row)
    out["by_stage_joined"] = joined
    return out


def loader_ledger(stats: Dict[str, Any],
                  bytes_per_batch: Optional[float] = None) -> Dict[str, Any]:
    """Input-engine counters in ledger form (ISSUE 3): join a
    :meth:`apex_tpu.data.LoaderStats.snapshot` with derived utilization
    percentages so the steady-vs-best-window gap decomposes into
    attributed host-side time the same way :func:`bytes_ledger`
    attributes HBM traffic.

    * ``loader_stall_pct`` — consumer wait / wall: the fraction of the
      training wall clock the INPUT engine cost (the regression-gated
      number ``bench.py`` reports per example);
    * ``producer_stall_pct`` — worker back-pressure / wall: > 0 means
      the pipeline is producer-RICH (healthy — compute is the
      bottleneck);
    * ``stage_bw_gb_s`` — host->device staging dispatch bandwidth, when
      ``bytes_per_batch`` is known.
    """
    out = dict(stats)
    elapsed = float(stats.get("elapsed_s") or 0.0)
    if elapsed > 0:
        out["producer_stall_pct"] = round(
            100.0 * float(stats.get("producer_stall_s", 0.0)) / elapsed, 2)
        out["stage_pct"] = round(
            100.0 * float(stats.get("stage_s", 0.0)) / elapsed, 2)
    if bytes_per_batch and stats.get("stage_s"):
        # stage_s accrues for every STAGED batch — the stager runs up to
        # ``depth`` ahead of delivery and an abandoned stream staged
        # more than it delivered; dividing by the delivered count would
        # understate the dispatch bandwidth.
        staged = stats.get("staged", stats.get("batches", 0))
        out["stage_bw_gb_s"] = round(
            staged * bytes_per_batch
            / float(stats["stage_s"]) / 1e9, 2)
    return out
