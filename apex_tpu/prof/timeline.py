"""Offline timeline analyzer for telemetry streams — the ``prof`` stage
of the runtime pillar (ISSUE 5).

The reference's PyProf closes the loop with ``pyprof.prof`` reading the
parsed CUPTI DB into per-kernel reports; this module does the same for
the JSONL streams :class:`apex_tpu.telemetry.Recorder` emits::

    python -m apex_tpu.prof.timeline run.jsonl
    python -m apex_tpu.prof.timeline run.jsonl --chrome trace.json
    python -m apex_tpu.prof.timeline run.jsonl --json

Reported, from the stream alone (no re-run needed):

* **step-time percentiles** — per-step wall time from consecutive window
  dispatch starts (the time the host loop actually experienced,
  dispatch + everything between dispatches);
* **stall/gap attribution** — ``loader_stall_pct`` read from the SAME
  ``LoaderStats.as_dict()`` snapshot the examples print (agreement with
  ``bench.py``'s parsed number is by construction), plus the dispatch
  gap split into loader wait and other host time;
* **loss-scale trajectory** — per-step scale values with skip/growth
  markers (functional path: derived from the one-dispatch-behind metric
  fetches; imperative path: the optimizer/scaler skip events);
* **retraces** — tracing-cache growth events keyed by window shape
  signature: first compiles and known-benign same-signature
  re-specializations (the call-1 donation/sharding re-cache) are
  reported separately from TRUE retraces (never-seen signatures — the
  J004 bug class, ``prof.assert_trace_count``'s offline twin);
* **per-collective byte totals** — trace-time per-invocation bytes
  (one event per compile) multiplied out by the dispatched step count.

The analyzer is pure host-side JSON (no device, no jax import beyond
package init), so it runs anywhere the stream can be copied to.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["SCHEMA_VERSION", "load_events", "analyze", "format_report",
           "check_schema_version", "main"]

#: Version of the ANALYSIS dict this module emits (``analyze()`` /
#: ``--json``).  ``major.minor``: the major bumps only when an existing
#: field changes meaning or disappears; adding fields bumps the minor.
#: ``prof.regress`` diffs two analyses across commits, so it refuses
#: inputs whose major it does not understand (see
#: :func:`check_schema_version`) instead of silently comparing
#: incompatible numbers.
SCHEMA_VERSION = "1.2"      # 1.1: + memory section (ISSUE 10)
#                             1.2: + requests section (ISSUE 20)


def check_schema_version(obj: Dict[str, Any], where: str = "input") -> None:
    """Reject an analysis dict from a FUTURE schema major with a clear
    error (old majors and missing versions pass — forward tools must
    read old artifacts, old tools must not misread new ones)."""
    ver = obj.get("schema_version")
    if ver is None:
        return
    try:
        major = int(str(ver).split(".")[0])
    except (ValueError, AttributeError):
        raise ValueError(
            f"{where}: unparseable schema_version {ver!r} "
            f"(expected 'major.minor', e.g. {SCHEMA_VERSION!r})")
    supported = int(SCHEMA_VERSION.split(".")[0])
    if major > supported:
        raise ValueError(
            f"{where}: schema_version {ver} is a FUTURE major (this "
            f"analyzer understands <= {supported}.x) — regenerate the "
            f"summary with this repo's `python -m apex_tpu.prof.timeline "
            f"--json`, or upgrade apex_tpu to diff it")


def load_events(path: str) -> List[dict]:
    """Parse a JSONL telemetry stream; torn tail lines (a run killed
    mid-write) are skipped, not fatal.  ``path`` may be a glob, and a
    rotated set (``run.jsonl`` + ``run.jsonl.1`` … from
    ``telemetry.start(path, max_bytes=...)``) is re-assembled in
    segment order automatically
    (:func:`apex_tpu.telemetry.expand_stream_paths`)."""
    from ..telemetry.events import _iter_events
    return _iter_events(path)


def _percentiles(samples: Sequence[float],
                 qs=(50.0, 90.0, 99.0)) -> List[Optional[float]]:
    # same definition as the in-run Histogram reservoirs
    from ..telemetry.metrics import nearest_rank_percentiles
    return nearest_rank_percentiles(samples, qs)


def analyze(events: List[dict]) -> Dict[str, Any]:
    """Distill one stream into the attribution dict ``format_report``
    prints (and ``bench.py`` self-validates against)."""
    windows = [e for e in events if e.get("kind") == "window"]
    metrics_ev: Dict[int, dict] = {}
    for e in events:
        if e.get("kind") == "metrics":      # last fetch of a step wins
            metrics_ev[int(e.get("step", 0))] = e
    scale_ev = [e for e in events if e.get("kind") == "scale"]
    retrace_ev = [e for e in events if e.get("kind") == "retrace"]
    coll_ev = [e for e in events if e.get("kind") == "collective"]
    loader_ev = [e for e in events if e.get("kind") == "loader"]
    waits = [e for e in events if e.get("kind") == "loader_wait"]
    summary = next((e for e in events if e.get("kind") == "summary"), None)
    run_ev = next((e for e in events if e.get("kind") == "run"), None)

    alert_ev = [e for e in events if e.get("kind") == "alert"]

    out: Dict[str, Any] = {
        "schema_version": SCHEMA_VERSION,
        "meta": (run_ev or {}).get("meta", {}),
        "n_events": len(events),
    }

    # -- step timing --------------------------------------------------------
    steps = sum(int(w.get("n_valid", 0)) for w in windows)
    out["steps"] = steps
    out["windows"] = len(windows)
    if windows:
        starts = [float(w["t"]) - float(w.get("dur", 0.0)) for w in windows]
        # elapsed: first dispatch start -> last event that fences device
        # work (the final metric fetch), else the last dispatch return.
        t_end = max([float(w["t"]) for w in windows]
                    + [float(e["t"]) for e in metrics_ev.values()])
        elapsed = max(t_end - starts[0], 1e-9)
        per_step: List[float] = []
        for i in range(1, len(windows)):
            n = int(windows[i - 1].get("n_valid", 1)) or 1
            per_step += [(starts[i] - starts[i - 1]) / n] * n
        p50, p90, p99 = _percentiles(per_step)
        dur_total = sum(float(w.get("dur", 0.0)) for w in windows)
        gap_total = sum(float(w.get("gap", 0.0)) for w in windows)
        out["elapsed_s"] = round(elapsed, 4)
        out["steps_per_s"] = round(steps / elapsed, 2)
        out["step_time"] = {
            "mean_ms": (round(1e3 * sum(per_step) / len(per_step), 3)
                        if per_step else None),
            "p50_ms": round(1e3 * p50, 3) if p50 is not None else None,
            "p90_ms": round(1e3 * p90, 3) if p90 is not None else None,
            "p99_ms": round(1e3 * p99, 3) if p99 is not None else None,
            "samples": len(per_step),
        }
        # -- attribution ----------------------------------------------------
        wait_total = sum(float(e.get("dur", 0.0)) for e in waits)
        loader_stats = (loader_ev[-1].get("stats", {}) if loader_ev else {})
        out["attribution"] = {
            # the % of wall the host spent inside dispatch calls
            "dispatch_pct": round(100.0 * dur_total / elapsed, 2),
            # host time between dispatches (fetches, loader, glue)
            "dispatch_gap_pct": round(100.0 * gap_total / elapsed, 2),
            # consumer wait measured by the loader itself, as % of the
            # STREAM's elapsed window (the same seconds LoaderStats
            # counts; its own loader_stall_pct uses its own clock)
            "loader_wait_pct": round(100.0 * wait_total / elapsed, 2),
            "gap_minus_loader_pct": round(
                100.0 * max(0.0, gap_total - wait_total) / elapsed, 2),
            # the number the examples print and bench.py parses — read
            # from the SAME as_dict() snapshot, so they agree exactly
            "loader_stall_pct": float(
                loader_stats.get("loader_stall_pct", 0.0)),
        }
        out["loader"] = loader_stats or None

    # -- loss scale ---------------------------------------------------------
    trajectory: List[List[float]] = []
    for step in sorted(metrics_ev):
        e = metrics_ev[step]
        scales = e.get("loss_scale") or []
        for j, s in enumerate(scales):
            trajectory.append([step + j, float(s)])
    skips = sorted(int(e.get("step", -1)) for e in scale_ev
                   if e.get("event") == "skip")
    grows = sorted(int(e.get("step", -1)) for e in scale_ev
                   if e.get("event") == "grow")
    out["loss_scale"] = {
        "trajectory": trajectory,
        "skip_steps": skips,
        "grow_steps": grows,
        "final": trajectory[-1][1] if trajectory else None,
    }

    # -- retraces -----------------------------------------------------------
    # A cache-growth event is one of: the program's first compile, a
    # known-benign re-specialization (same shape signature — jit
    # re-caching on the donated state's returned sharding), or a TRUE
    # retrace (a never-seen signature — the J004 bug class).
    first_compiles = [e for e in retrace_ev if e.get("first")]
    respecs = [e for e in retrace_ev
               if not e.get("first") and not e.get("new_sig", True)]
    true_retraces = [e for e in retrace_ev
                     if not e.get("first") and e.get("new_sig", True)]
    out["retraces"] = {
        "compiles": len(first_compiles),
        "respecializations": len(respecs),
        "retraces": len(true_retraces),
        "by_signature": sorted({str(e.get("sig")) for e in true_retraces}),
        # host seconds spent inside dispatches that grew the jit cache
        # (each retrace event carries its dispatch's dur) — the compile
        # share of the steady-vs-best gap the roofline ledger reports.
        "compile_s": round(sum(float(e.get("dur", 0.0))
                               for e in retrace_ev), 4),
    }

    # -- memory ledger events (ISSUE 10) -------------------------------------
    mem_ev = [e for e in events if e.get("kind") == "memory"]
    if mem_ev:
        peaks = [float(e.get("peak_bytes", 0) or 0) for e in mem_ev]
        heads = [float(e["headroom_pct"]) for e in mem_ev
                 if e.get("headroom_pct") is not None]
        out["memory"] = {
            "events": len(mem_ev),
            "peak_hbm_gb": round(max(peaks) / 1e9, 6) if peaks else None,
            "min_headroom_pct": (round(min(heads), 2) if heads else None),
            "source": mem_ev[-1].get("source"),
        }

    # -- watchdog alerts ------------------------------------------------------
    by_rule: Dict[str, int] = {}
    for e in alert_ev:
        rule = str(e.get("rule", "?"))
        by_rule[rule] = by_rule.get(rule, 0) + 1
    out["alerts"] = {
        "total": len(alert_ev),
        "by_rule": by_rule,
        "steps": sorted({int(e["step"]) for e in alert_ev
                         if e.get("step") is not None})[:64],
    }

    # -- collectives --------------------------------------------------------
    # Events fire at TRACE time — once per reduce call per COMPILE.  The
    # hot and tail programs (and any re-specialization) of a pipeline
    # each re-record the same per-step collectives, so a group of
    # identical events divides by the number of observed compiles
    # (cache-growth events), ceil'd — two genuinely distinct reduce
    # calls of the same signature inside ONE step survive the division
    # instead of collapsing to one.  Without compile events the stream
    # came from a single trace, so every event counts.
    compiles_seen = max(1, len(retrace_ev))
    groups: Dict[tuple, List[dict]] = {}
    for e in coll_ev:
        key = (e.get("op"), json.dumps(e.get("axis")),
               int(e.get("bytes", 0)), int(e.get("n", 0)))
        groups.setdefault(key, []).append(e)
    colls = []
    for evs in groups.values():
        e = evs[0]
        mult = -(-len(evs) // compiles_seen)         # ceil
        b = int(e.get("bytes", 0)) * mult
        colls.append({
            "op": e.get("op"), "axis": e.get("axis"),
            "n_per_step": int(e.get("n", 0)) * mult,
            "bytes_per_step": b,
            "total_gb": round(b * steps / 1e9, 4),
            "dtype": e.get("dtype"),
        })
    colls.sort(key=lambda c: -c["bytes_per_step"])
    out["collectives"] = {
        "per_step_bytes": sum(c["bytes_per_step"] for c in colls),
        "total_gb": round(sum(c["bytes_per_step"] for c in colls)
                          * steps / 1e9, 4),
        "by_op": colls,
        # Per-mesh-axis attribution (ISSUE 12): the axis name(s) each
        # collective carries split the traffic per mesh dimension
        # (dp vs fsdp vs tp) instead of one undifferentiated pool — a
        # multi-axis psum is labeled with the joined axes (its bytes
        # cross every one of them as one HLO collective).
        "by_axis": _axis_totals(colls, steps),
    }

    # -- serving requests (ISSUE 20) ----------------------------------------
    # Present only when the stream came from the serving engine (has
    # `done` serving events): TTFT/TPOT/e2e/queue-wait percentiles over
    # EVERY finished request plus the batch-size-vs-TPOT join — the
    # schema-1.2 addition `prof.requests` computes in full detail.
    from .requests import request_stats
    req = request_stats(events)
    if req is not None:
        out["requests"] = req

    if summary is not None:
        out["summary"] = {k: v for k, v in summary.items()
                          if k not in ("t", "kind")}
    return out


def axis_label(axis) -> str:
    """Canonical label of a collective's mesh axis field: a bare name
    stays itself, a multi-axis tuple joins with '+' (one HLO collective
    crossing several axes)."""
    if isinstance(axis, (list, tuple)):
        return "+".join(str(a) for a in axis)
    return str(axis)


def _axis_totals(colls, steps: int) -> Dict[str, Dict[str, Any]]:
    """Aggregate per-collective rows into per-axis byte totals."""
    out: Dict[str, Dict[str, Any]] = {}
    for c in colls:
        d = out.setdefault(axis_label(c.get("axis")),
                           {"bytes_per_step": 0, "n_per_step": 0,
                            "ops": set()})
        d["bytes_per_step"] += c["bytes_per_step"]
        d["n_per_step"] += c["n_per_step"]
        d["ops"].add(c["op"])
    return {k: {"bytes_per_step": v["bytes_per_step"],
                "n_per_step": v["n_per_step"],
                "total_gb": round(v["bytes_per_step"] * steps / 1e9, 4),
                "ops": sorted(v["ops"])}
            for k, v in sorted(out.items())}


def _fmt_pct(v) -> str:
    return f"{v:6.2f}%" if v is not None else "   n/a"


def format_report(a: Dict[str, Any]) -> str:
    """Human-readable report (the CLI's default output)."""
    lines: List[str] = []
    meta = a.get("meta") or {}
    head = ", ".join(f"{k}={v}" for k, v in sorted(meta.items()))
    lines.append(f"telemetry timeline — {a.get('n_events', 0)} events"
                 + (f" ({head})" if head else ""))
    st = a.get("step_time")
    if st:
        lines.append(
            f"steps: {a['steps']} over {a['windows']} windows in "
            f"{a['elapsed_s']:.3f}s  ({a['steps_per_s']} steps/s)")
        lines.append(
            f"step time: mean {st['mean_ms']} ms  p50 {st['p50_ms']}  "
            f"p90 {st['p90_ms']}  p99 {st['p99_ms']} ms "
            f"({st['samples']} samples)")
    att = a.get("attribution")
    if att:
        lines.append("attribution (% of wall):")
        lines.append(f"  dispatch         {_fmt_pct(att['dispatch_pct'])}")
        lines.append(f"  dispatch gap     "
                     f"{_fmt_pct(att['dispatch_gap_pct'])}"
                     f"   (loader wait {_fmt_pct(att['loader_wait_pct'])},"
                     f" other {_fmt_pct(att['gap_minus_loader_pct'])})")
        lines.append(f"  loader stall     "
                     f"{_fmt_pct(att['loader_stall_pct'])}"
                     f"   (LoaderStats.as_dict, = the example's "
                     f"'loader: stall' line)")
    ls = a.get("loss_scale") or {}
    traj = ls.get("trajectory") or []
    if traj:
        distinct = []
        for step, s in traj:
            if not distinct or distinct[-1][1] != s:
                distinct.append((step, s))
        path = " -> ".join(f"{s:g}@{int(t)}" for t, s in distinct[:12])
        lines.append(f"loss scale: final {ls['final']:g}  ({path}"
                     + (" ..." if len(distinct) > 12 else "") + ")")
        lines.append(f"  skips at steps {ls['skip_steps'] or '[]'}  "
                     f"growth at {ls['grow_steps'] or '[]'}")
    rt = a.get("retraces") or {}
    lines.append(f"compiles: {rt.get('compiles', 0)}  "
                 f"re-specializations: {rt.get('respecializations', 0)}  "
                 f"retraces: {rt.get('retraces', 0)}"
                 + (f"  ({rt['compile_s']}s compiling)"
                    if rt.get("compile_s") else "")
                 + (f"  signatures: {rt['by_signature']}"
                    if rt.get("retraces") else ""))
    mem = a.get("memory") or {}
    if mem.get("peak_hbm_gb") is not None:
        head = (f", min headroom {mem['min_headroom_pct']}%"
                if mem.get("min_headroom_pct") is not None else "")
        lines.append(f"peak HBM: {mem['peak_hbm_gb']} GB "
                     f"[{mem.get('source')}]{head}")
    al = a.get("alerts") or {}
    if al.get("total"):
        rules = ", ".join(f"{k} x{v}"
                          for k, v in sorted(al["by_rule"].items()))
        lines.append(f"health: {al['total']} watchdog alert(s) ({rules})"
                     + (f" at steps {al['steps'][:8]}"
                        if al.get("steps") else ""))
    rq = a.get("requests") or {}
    if rq:
        t, tp = rq.get("ttft") or {}, rq.get("tpot") or {}
        lines.append(f"serving: {rq['n_requests']} requests, "
                     f"{rq['tokens_out']} tokens out  "
                     f"ttft p50/p99 {t.get('p50_ms')}/{t.get('p99_ms')} ms"
                     f"  tpot p50/p99 {tp.get('p50_ms')}/{tp.get('p99_ms')}"
                     f" ms  (waterfalls: python -m apex_tpu.prof.requests)")
    co = a.get("collectives") or {}
    if co.get("by_op"):
        lines.append(f"collectives: "
                     f"{co['per_step_bytes'] / 1e6:.3f} MB/step, "
                     f"{co['total_gb']} GB over the run")
        for c in co["by_op"][:8]:
            lines.append(f"  {c['op']:<14} axis={c['axis']} "
                         f"{c['bytes_per_step'] / 1e6:.3f} MB/step "
                         f"x{c['n_per_step']} ({c['dtype']}) "
                         f"total {c['total_gb']} GB")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.prof.timeline",
        description="Analyze an apex_tpu telemetry JSONL stream.")
    p.add_argument("stream", help="path to the run's .jsonl event stream "
                                  "(a glob or any member of a rotated "
                                  "set loads the whole set in order)")
    p.add_argument("--json", action="store_true",
                   help="emit the analysis as JSON instead of the report")
    p.add_argument("--chrome", metavar="OUT",
                   help="also export a Chrome trace_event file "
                        "(open in Perfetto / chrome://tracing)")
    args = p.parse_args(argv)
    events = load_events(args.stream)
    if not events:
        print(f"no events in {args.stream}", file=sys.stderr)
        return 1
    a = analyze(events)
    if args.chrome:
        from ..telemetry import to_chrome_trace
        n = to_chrome_trace(events, args.chrome)
        print(f"wrote {n} chrome trace events to {args.chrome}",
              file=sys.stderr)
    try:
        if args.json:
            print(json.dumps(a, indent=1))
        else:
            print(format_report(a))
    except BrokenPipeError:       # `... | head` is a supported consumer
        try:
            sys.stdout.close()
        except Exception:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
