"""Ring-flash attention (Pallas kernels inside sequence parallelism) vs
the jnp ring and the single-device oracle — fwd + grads, causal and full.

Runs the kernels in interpret mode on the CPU mesh (same code path as on
chip minus Mosaic lowering); the on-chip counterpart is the `tpu`-marked
test in test_pallas_tpu.py.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.ops.attention import dot_product_attention
from apex_tpu.parallel.ring_attention import _ring_flash

N = 4          # ring size
B, T, H, D = 1, 512, 2, 32      # global seq 512 -> 128 per shard


@pytest.fixture
def sp_mesh():
    return Mesh(np.array(jax.devices("cpu")[:N]), ("sp",))


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
                 for _ in range(3))


def _run_ring_flash(mesh, q, k, v, causal):
    """Drive the head-major core with interpret=True under shard_map.

    check_vma=False throughout: the interpret-mode pallas evaluator
    rejects rank-varying SMEM scalar operands (the dynamic ring offsets)
    under vma tracking — a tracker limitation whose error message says to
    use exactly this workaround.  Numerics are asserted vs the oracle.
    """
    def fn(q, k, v):
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        out = _ring_flash(qt, kt, vt, "sp", causal, D ** -0.5, 128, 128,
                          True)
        return out.transpose(0, 2, 1, 3)

    return jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False))(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_forward_matches_oracle(sp_mesh, causal):
    if not causal and jax.__version_info__ < (0, 5):
        pytest.skip("pre-0.5 SPMD partitioner rejects the non-causal "
                    "ring's PartitionId lowering (UNIMPLEMENTED)")
    q, k, v = _qkv()
    out = _run_ring_flash(sp_mesh, q, k, v, causal)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_grads_match_oracle(sp_mesh, causal):
    q, k, v = _qkv(1)

    def loss_ring(q, k, v):
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        out = _ring_flash(qt, kt, vt, "sp", causal, D ** -0.5, 128, 128,
                          True)
        # per-rank partial sums add up to the global sum through
        # shard_map's transpose, so grads match the dense loss exactly
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    def run(q, k, v):
        return jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)

    # check_vma=False: the interpret-mode pallas evaluator rejects
    # rank-varying SMEM scalar operands (the dynamic ring offsets) under
    # vma tracking — a tracker limitation the error message itself says to
    # work around this way.  Numerics are asserted against the dense
    # oracle below either way.
    g = jax.jit(shard_map(
        run, mesh=sp_mesh,
        in_specs=(P(None, "sp"),) * 3,
        out_specs=(P(None, "sp"),) * 3,
        check_vma=False))(q, k, v)

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(
            dot_product_attention(q, k, v, causal=causal)
            .astype(jnp.float32)))

    r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g, r):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
            err_msg=f"d{name} mismatch (causal={causal})")


def test_ring_flash_public_fallback_off_tpu(sp_mesh):
    """ring_flash_attention off-TPU (no interpret) silently runs the jnp
    ring path with the same numerics."""
    from apex_tpu.parallel import ring_flash_attention

    q, k, v = _qkv(2)

    def fn(q, k, v):
        return ring_flash_attention(q, k, v, "sp", causal=True)

    out = jax.jit(shard_map(
        fn, mesh=sp_mesh,
        in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp")))(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
