"""apex_tpu.RNN + reparameterization tests (reference test model:
tests/L0/run_amp/test_rnn.py exercises cells/stacks; weight-norm math vs
the v·g/‖v‖ definition, reference weight_norm.py:22-78)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.RNN import LSTM, GRU, ReLU, Tanh, mLSTM
from apex_tpu.reparameterization import (apply_weight_norm, reconstruct,
                                         remove_weight_norm)

T, B, F, H = 5, 3, 4, 8


@pytest.mark.parametrize("factory,n_states", [
    (LSTM, 2), (GRU, 1), (ReLU, 1), (Tanh, 1), (mLSTM, 2)])
def test_rnn_shapes_and_states(factory, n_states):
    model = factory(F, H, num_layers=2)
    x = jnp.asarray(np.random.RandomState(0).randn(T, B, F), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    (out, finals), = [model.apply(params, x)]
    assert out.shape == (T, B, H)
    assert len(finals) == 2            # per layer
    assert len(finals[0]) == n_states  # (h,) or (h, c)
    assert finals[0][0].shape == (B, H)


def test_rnn_batch_first_and_proj():
    model = LSTM(F, H, num_layers=1, batch_first=True, output_size=6)
    x = jnp.ones((B, T, F))
    params = model.init(jax.random.PRNGKey(0), x)
    out, _ = model.apply(params, x)
    assert out.shape == (B, T, 6)


def test_bidirectional_concat():
    model = GRU(F, H, num_layers=1, bidirectional=True)
    x = jnp.ones((T, B, F))
    params = model.init(jax.random.PRNGKey(0), x)
    out, (fin_f, fin_r) = model.apply(params, x)
    assert out.shape == (T, B, 2 * H)
    assert len(fin_f) == 1 and len(fin_r) == 1


def test_rnn_initial_state_threading():
    """Final state of one chunk feeds the next — the functional version of
    the reference's persistent hidden state (RNNBackend.py:309-347)."""
    model = Tanh(F, H, num_layers=1)
    x = jnp.asarray(np.random.RandomState(1).randn(2 * T, B, F), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    full, _ = model.apply(params, x)
    out1, fin1 = model.apply(params, x[:T])
    out2, _ = model.apply(params, x[T:], initial_states=fin1)
    np.testing.assert_allclose(np.asarray(full[T:]), np.asarray(out2),
                               atol=1e-5)


def test_rnn_collect_hidden():
    """collect_hidden=True returns every timestep's states per layer."""
    model = LSTM(F, H, num_layers=2)
    x = jnp.ones((T, B, F))
    params = model.init(jax.random.PRNGKey(0), x)
    out, per_step = model.apply(params, x, collect_hidden=True)
    assert len(per_step) == 2
    h_all, c_all = per_step[0]
    assert h_all.shape == (T, B, H) and c_all.shape == (T, B, H)
    # Last collected state equals the final state from the default call.
    _, finals = model.apply(params, x)
    np.testing.assert_allclose(np.asarray(h_all[-1]),
                               np.asarray(finals[0][0]), atol=1e-6)


def test_weight_norm_dim_recorded_in_marker():
    """Regression: reconstruct must use the dim recorded at apply time."""
    v = jnp.asarray(np.random.RandomState(0).randn(3, 5), jnp.float32)
    wn = apply_weight_norm({"l": {"kernel": v}}, dim=1)
    assert wn["l"]["kernel"]["g"].shape == (1, 5)
    rebuilt = reconstruct(wn)          # no dim argument — comes from marker
    np.testing.assert_allclose(np.asarray(rebuilt["l"]["kernel"]),
                               np.asarray(v), atol=1e-5)


def test_remove_weight_norm_respects_name_filter():
    params = {"a": {"kernel": jnp.ones((2, 3))},
              "b": {"kernel": jnp.ones((2, 3))}}
    wn = apply_weight_norm(params)
    partial = remove_weight_norm(wn, name="a")
    assert hasattr(partial["a"]["kernel"], "dtype")   # folded back to array
    assert isinstance(partial["b"]["kernel"], dict)   # still reparameterized


def test_rnn_grads_flow():
    model = LSTM(F, H, num_layers=1)
    x = jnp.ones((T, B, F))
    params = model.init(jax.random.PRNGKey(0), x)

    def loss(p):
        out, _ = model.apply(p, x)
        return jnp.sum(out ** 2)

    grads = jax.grad(loss)(params)
    gsum = sum(float(jnp.sum(jnp.abs(g)))
               for g in jax.tree_util.tree_leaves(grads))
    assert np.isfinite(gsum) and gsum > 0


def test_weight_norm_roundtrip():
    params = {"dense": {"kernel": jnp.asarray(
        np.random.RandomState(0).randn(4, 6), jnp.float32),
        "bias": jnp.zeros((6,))}}
    wn = apply_weight_norm(params)
    assert "g" in wn["dense"]["kernel"] and "v" in wn["dense"]["kernel"]
    # g has one magnitude per dim-0 slice
    assert wn["dense"]["kernel"]["g"].shape == (4, 1)
    rebuilt = reconstruct(wn)
    np.testing.assert_allclose(np.asarray(rebuilt["dense"]["kernel"]),
                               np.asarray(params["dense"]["kernel"]),
                               atol=1e-5)
    removed = remove_weight_norm(wn)
    np.testing.assert_allclose(np.asarray(removed["dense"]["kernel"]),
                               np.asarray(params["dense"]["kernel"]),
                               atol=1e-5)


def test_weight_norm_grad_decoupling():
    """Scaling g scales w; v's direction is what matters — the definitional
    property w = g·v/‖v‖."""
    v = jnp.asarray(np.random.RandomState(0).randn(3, 5), jnp.float32)
    params = {"layer": {"kernel": v}}
    wn = apply_weight_norm(params)
    wn2 = jax.tree_util.tree_map(lambda x: x, wn)
    wn2["layer"]["kernel"] = dict(wn["layer"]["kernel"])
    wn2["layer"]["kernel"]["v"] = wn["layer"]["kernel"]["v"] * 7.0
    r1 = reconstruct(wn)
    r2 = reconstruct(wn2)
    np.testing.assert_allclose(np.asarray(r1["layer"]["kernel"]),
                               np.asarray(r2["layer"]["kernel"]), atol=1e-4)


def test_weight_norm_inside_jit_and_grad():
    params = {"dense": {"kernel": jnp.ones((4, 2)), "bias": jnp.zeros((2,))}}
    wn = apply_weight_norm(params)
    x = jnp.ones((3, 4))

    @jax.jit
    def loss(p):
        w = reconstruct(p)
        return jnp.sum((x @ w["dense"]["kernel"] + w["dense"]["bias"]) ** 2)

    g = jax.grad(loss)(wn)
    assert g["dense"]["kernel"]["g"].shape == (4, 1)
    assert np.isfinite(float(jnp.sum(g["dense"]["kernel"]["v"])))
