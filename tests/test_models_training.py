"""Model zoo + fully-jitted train step tests (small shapes, CPU)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from apex_tpu import training
from apex_tpu.models import (ResNet18, ResNet50, bert_tiny, Generator,
                             Discriminator)
from apex_tpu.training import make_train_step, TrainState


def _xent(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


@pytest.mark.slow
def test_resnet_forward_shapes():
    model = ResNet18(num_classes=10)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


@pytest.mark.parametrize("opt_level", [
    pytest.param("O0", marks=pytest.mark.slow),   # O2 is the flagship
    "O2",                                         # config; O0/O3 ride the
    pytest.param("O3", marks=pytest.mark.slow)])  # full (slow) suite
def test_resnet_train_step_loss_decreases(opt_level):
    model = ResNet18(num_classes=10, dtype=jnp.bfloat16
                     if opt_level in ("O2", "O3") else jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 32, 32, 3),
                    jnp.float32)
    y = jnp.asarray(np.arange(8) % 10)
    variables = model.init(jax.random.PRNGKey(0), x)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(p, ms, batch):
        xb, yb = batch
        logits, updated = model.apply(
            {"params": p, "batch_stats": ms}, xb, train=True,
            mutable=["batch_stats"])
        return _xent(logits, yb), updated["batch_stats"]

    tx = training.sgd(lr=0.1, momentum=0.9)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level=opt_level,
                                       has_model_state=True)
    state = init_fn(params, batch_stats)
    step = jax.jit(step_fn)
    state, m0 = step(state, (x, y))
    for _ in range(8):
        state, m = step(state, (x, y))
    assert float(m["loss"]) < float(m0["loss"])


def test_train_step_dynamic_scale_overflow_masks_update():
    params = {"w": jnp.ones((4,), jnp.float32)}

    def loss_fn(p, batch):
        return jnp.sum(p["w"] * batch)

    tx = training.sgd(lr=1.0)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2",
                                       loss_scale="dynamic",
                                       keep_batchnorm_fp32=False)
    state = init_fn(params)
    step = jax.jit(step_fn)
    state, m = step(state, jnp.ones((4,)))
    assert not bool(m["overflow"])
    w_after = np.asarray(state.params["w"])
    # Inf in the batch -> inf grads -> masked update, halved scale.
    state, m = step(state, jnp.asarray([np.inf, 1, 1, 1], np.float32))
    assert bool(m["overflow"])
    np.testing.assert_array_equal(np.asarray(state.params["w"]), w_after)
    assert float(m["loss_scale"]) == 2.**15


def test_train_step_o2_params_stay_fp32_master():
    params = {"dense": {"kernel": jnp.ones((4, 4), jnp.float32)}}

    def loss_fn(p, batch):
        # O2: inside the step the compute copy is bf16.
        assert p["dense"]["kernel"].dtype == jnp.bfloat16
        return jnp.sum((batch @ p["dense"]["kernel"].astype(jnp.float32)) ** 2)

    tx = training.adam(lr=1e-2)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2",
                                       keep_batchnorm_fp32=False)
    state = init_fn(params)
    state, _ = jax.jit(step_fn)(state, jnp.ones((2, 4)))
    # Source of truth stays fp32 (master weights without duplicate storage).
    assert state.params["dense"]["kernel"].dtype == jnp.float32


def test_dp_train_step_on_mesh():
    """8-way DP: shard_map'ed train step with grad psum; replicas stay
    bitwise identical (the DDP contract)."""
    mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("data",))
    params = {"w": jnp.ones((3,), jnp.float32) * 0.5}

    def loss_fn(p, batch):
        x, y = batch
        pred = x @ jnp.broadcast_to(p["w"], (x.shape[-1],))
        return jnp.mean((pred - y) ** 2)

    tx = training.sgd(lr=0.1)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2",
                                       keep_batchnorm_fp32=False,
                                       axis_name="data")
    state = init_fn(params)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 3).astype(np.float32))
    y = jnp.asarray(rng.randn(64).astype(np.float32))

    sharded = shard_map(
        step_fn, mesh=mesh,
        in_specs=(P(), (P("data"), P("data"))),
        out_specs=(P(), P()),
    )
    new_state, metrics = jax.jit(sharded)(state, (x, y))
    assert np.isfinite(float(metrics["loss"]))

    # Oracle: single-device step on the full batch (grad of mean over all
    # shards == psum-mean of shard grads).
    init2, step2 = make_train_step(loss_fn, tx, opt_level="O2",
                                   keep_batchnorm_fp32=False)
    ref_state, _ = jax.jit(step2)(init2(params), (x, y))
    # Pre-0.5 jax (conftest's check_rep=False shard_map shim) inserts no
    # implicit psum, so grads reduce via the explicit collective — a
    # different bf16 summation order than the single-device oracle;
    # allow one bf16 ulp there, keep the tight gate on vma-aware jax.
    tol = ({"atol": 1e-6, "rtol": 1e-6} if jax.__version_info__ >= (0, 5)
           else {"atol": 4e-3, "rtol": 4e-3})
    np.testing.assert_allclose(np.asarray(new_state.params["w"]),
                               np.asarray(ref_state.params["w"]), **tol)


@pytest.mark.slow
def test_bert_tiny_forward_and_train():
    model = bert_tiny(dtype=jnp.bfloat16)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 1024, (2, 16)))
    variables = model.init(jax.random.PRNGKey(0), ids)
    logits = model.apply(variables, ids)
    assert logits.shape == (2, 2)

    def loss_fn(p, batch):
        ids_b, labels = batch
        return _xent(model.apply({"params": p}, ids_b), labels)

    tx = training.lamb(lr=1e-3)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2")
    state = init_fn(variables["params"])
    labels = jnp.asarray([0, 1])
    step = jax.jit(step_fn)
    state, m0 = step(state, (ids, labels))
    for _ in range(5):
        state, m = step(state, (ids, labels))
    assert float(m["loss"]) < float(m0["loss"])


@pytest.mark.slow
def test_dcgan_shapes():
    g = Generator(ngf=8, nc=3)
    d = Discriminator(ndf=8)
    z = jnp.ones((2, 16))
    gv = g.init(jax.random.PRNGKey(0), z)
    img = g.apply(gv, z, train=False)
    assert img.shape == (2, 64, 64, 3)
    dv = d.init(jax.random.PRNGKey(1), img)
    logit = d.apply(dv, img, train=False)
    assert logit.shape == (2, 1)
