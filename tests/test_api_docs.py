"""Drift gate for the generated API reference (VERDICT r4 missing #3):
docs/api/*.md must match the code's public symbols and docstrings."""

import pytest


@pytest.mark.slow          # imports every public module; ~10 s on CPU
def test_api_reference_matches_code():
    from tools.gen_api_docs import main
    assert main(check=True), (
        "docs/api drifted — regenerate with python tools/gen_api_docs.py")
