"""L1 cross-product convergence gate.

Re-design of the reference's main end-to-end correctness harness
(``tests/L1/common/run_test.sh:21-120`` sweeps {O0-O3} x {default, 1.0,
128.0, dynamic loss scale} x {keep_batchnorm_fp32 T/F} and
``compare.py:36-64`` asserts identical loss trajectories between installs).

The TPU translation of "install-vs-install bitwise equality":

* within one opt level, the loss trajectory must be BITWISE identical
  across loss scales {1.0, 128.0, dynamic} — power-of-two scaling and
  unscaling is exact in fp32/bf16, so any drift is a scaling-machinery bug;
* each opt level's trajectory must track the O0 fp32 oracle inside a
  precision-appropriate tolerance ladder (cf. the reference's fp16/fp64
  ladder in ``two_gpu_unit_test.py:40-46``);
* keep_batchnorm_fp32 True/False both converge at O2/O3;
* (on chip, ``-m tpu``) the Pallas kernel path and the jnp fallback path
  produce matching trajectories over a real training run.
"""

import os

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu import training
from apex_tpu.amp import autocast
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.training import make_train_step

STEPS = 6
_OPT_LEVELS = ("O0", "O1", "O2", "O3")
_SCALES = (1.0, 128.0, "dynamic")


class TinyModel(nn.Module):
    """conv + BatchNorm + FusedLayerNorm + dense: touches the keep-bn cast
    split, the norm kernels, and the MXU ops in a few thousand params."""
    dtype: object = jnp.float32

    @nn.compact
    def __call__(self, x, train=True):
        x = nn.Conv(8, (3, 3), dtype=self.dtype, param_dtype=jnp.float32,
                    name="conv0")(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         param_dtype=jnp.float32, name="bn0")(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(32, dtype=self.dtype, param_dtype=jnp.float32,
                     name="dense0")(x)
        x = FusedLayerNorm(normalized_shape=32, name="ln0")(x).astype(
            self.dtype)
        return nn.Dense(10, dtype=self.dtype, param_dtype=jnp.float32,
                        name="head")(x)


def _data():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 8, 8, 3), jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, (16,)))
    return x, y


def run_config(opt_level, loss_scale, keep_bn=True, steps=STEPS):
    """Train TinyModel ``steps`` steps; return the fp32 loss trajectory."""
    dtype = jnp.bfloat16 if opt_level in ("O2", "O3") else jnp.float32
    model = TinyModel(dtype=dtype)
    x, y = _data()
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(p, ms, batch):
        xb, yb = batch
        logits, upd = model.apply({"params": p, "batch_stats": ms}, xb,
                                  train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        loss = -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))
        return loss, upd["batch_stats"]

    tx = training.sgd(lr=0.05, momentum=0.9)
    if opt_level == "O1":
        autocast.init(enabled=True)
    try:
        init_fn, step_fn = make_train_step(
            loss_fn, tx, opt_level=opt_level, loss_scale=loss_scale,
            keep_batchnorm_fp32=keep_bn, has_model_state=True)
        state = init_fn(params, batch_stats)
        step = jax.jit(step_fn)
        losses = []
        for _ in range(steps):
            state, metrics = step(state, (x, y))
            losses.append(float(metrics["loss"]))
    finally:
        if opt_level == "O1":
            autocast.shutdown()
    return np.asarray(losses)


# Tolerance ladder vs the fp32 oracle (reference two_gpu_unit_test.py:40-46).
_TOL = {"O0": 0.0, "O1": 0.08, "O2": 0.08, "O3": 0.15}


@pytest.fixture(scope="module")
def oracle():
    return run_config("O0", 1.0)


@pytest.mark.parametrize("opt_level", [
    pytest.param("O0", marks=pytest.mark.slow),
    pytest.param("O1", marks=pytest.mark.slow),
    "O2",                                     # flagship on the fast gate
    pytest.param("O3", marks=pytest.mark.slow)])
def test_loss_scale_invariance(opt_level):
    """{1.0, 128.0, dynamic} trajectories are ulp-identical within one opt
    level (the install-parity analog of compare.py:36-64).  Power-of-two
    scale/unscale is exact arithmetic; the tolerance only absorbs XLA
    re-fusion between the scaled and fast-path programs (the scale==1.0
    config compiles without the scaling ops at all)."""
    base = run_config(opt_level, _SCALES[0])
    for scale in _SCALES[1:]:
        traj = run_config(opt_level, scale)
        np.testing.assert_allclose(
            traj, base, rtol=1e-5, atol=0,
            err_msg=f"{opt_level}: loss_scale={scale} diverged from 1.0")


@pytest.mark.parametrize("opt_level", ("O1", "O2", "O3"))
def test_trajectory_tracks_fp32_oracle(opt_level, oracle):
    traj = run_config(opt_level, "dynamic")
    assert traj.shape == oracle.shape
    np.testing.assert_allclose(
        traj, oracle, atol=_TOL[opt_level], rtol=0,
        err_msg=f"{opt_level} trajectory left the tolerance ladder")
    assert traj[-1] < traj[0], f"{opt_level} did not reduce the loss"


@pytest.mark.parametrize("opt_level", ("O2", "O3"))
@pytest.mark.parametrize("keep_bn", (True, False))
def test_keep_batchnorm_cross_product(opt_level, keep_bn, oracle):
    traj = run_config(opt_level, 128.0, keep_bn=keep_bn)
    np.testing.assert_allclose(
        traj, oracle, atol=2 * _TOL[opt_level] if not keep_bn
        else _TOL[opt_level], rtol=0)
    assert traj[-1] < traj[0]


def test_o0_matches_oracle_bitwise(oracle):
    np.testing.assert_array_equal(run_config("O0", 1.0), oracle)


@pytest.mark.tpu
def test_pallas_vs_fallback_trajectory_on_chip():
    """Fallback-vs-kernel over a training run (the L1 fused-vs-python gate,
    reference run_test.sh two-install sweep)."""
    with jax.default_device(jax.devices("tpu")[0]):
        kernel_traj = run_config("O2", "dynamic")
        os.environ["APEX_TPU_DISABLE_PALLAS"] = "1"
        try:
            fallback_traj = run_config("O2", "dynamic")
        finally:
            del os.environ["APEX_TPU_DISABLE_PALLAS"]
    np.testing.assert_allclose(kernel_traj, fallback_traj, atol=2e-2, rtol=0)


# -- distributed cross-product (reference tests/L1/cross_product_distributed) --

class TinySyncModel(nn.Module):
    """TinyModel with SyncBatchNorm so the distributed run computes the
    SAME function as the whole-batch single-process run."""
    dtype: object = jnp.float32
    axis_name: object = None

    @nn.compact
    def __call__(self, x, train=True):
        from apex_tpu.parallel import SyncBatchNorm
        x = nn.Conv(8, (3, 3), dtype=self.dtype, param_dtype=jnp.float32,
                    name="conv0")(x)
        x = SyncBatchNorm(axis_name=self.axis_name if train else None,
                          use_running_average=not train, name="bn0")(x)
        x = nn.relu(x).astype(self.dtype)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(32, dtype=self.dtype, param_dtype=jnp.float32,
                     name="dense0")(x)
        x = FusedLayerNorm(normalized_shape=32, name="ln0")(x).astype(
            self.dtype)
        return nn.Dense(10, dtype=self.dtype, param_dtype=jnp.float32,
                        name="head")(x)


def _run_sync(opt_level, loss_scale, axis_name, mesh=None, steps=STEPS):
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    dtype = jnp.bfloat16 if opt_level in ("O2", "O3") else jnp.float32
    model = TinySyncModel(dtype=dtype, axis_name=axis_name)
    init_model = TinySyncModel(dtype=dtype)          # no axis during init
    x, y = _data()
    variables = init_model.init(jax.random.PRNGKey(0), x[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(p, ms, batch):
        xb, yb = batch
        logits, upd = model.apply({"params": p, "batch_stats": ms}, xb,
                                  train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        loss = -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))
        return loss, upd["batch_stats"]

    tx = training.sgd(lr=0.05, momentum=0.9)
    init_fn, step_fn = make_train_step(
        loss_fn, tx, opt_level=opt_level, loss_scale=loss_scale,
        axis_name=axis_name, has_model_state=True)
    state = init_fn(params, batch_stats)
    if mesh is not None:
        step = jax.jit(shard_map(
            step_fn, mesh=mesh,
            in_specs=(P(), (P("data"), P("data"))), out_specs=(P(), P())))
    else:
        step = jax.jit(step_fn)
    losses = []
    for _ in range(steps):
        state, metrics = step(state, (x, y))
        losses.append(float(jnp.ravel(metrics["loss"])[0]))
    return np.asarray(losses)


@pytest.mark.parametrize("opt_level,loss_scale",
                         [pytest.param("O0", 1.0, marks=pytest.mark.slow),
                          pytest.param("O2", 128.0, marks=pytest.mark.slow),
                          ("O2", "dynamic")])   # flagship config on the
def test_distributed_cross_product_matches_single_process(opt_level,  # fast gate
                                                          loss_scale,
                                                          cpu_mesh):
    """8-way DP (shard_map + SyncBN + DDP grad averaging) must reproduce
    the whole-batch single-process trajectory — the TPU analog of the
    reference's 2-GPU cross-product gate, checked exactly rather than
    eyeballed."""
    single = _run_sync(opt_level, loss_scale, axis_name=None)
    dist = _run_sync(opt_level, loss_scale, axis_name="data", mesh=cpu_mesh)
    np.testing.assert_allclose(
        dist, single, rtol=2e-4 if opt_level == "O0" else 2e-3, atol=1e-6,
        err_msg=f"{opt_level}/{loss_scale}: DP trajectory diverged")
    assert dist[-1] < dist[0]


def test_fp16_mode_tracks_oracle(oracle):
    """cast_model_type=float16 (the reference's native half type) with
    dynamic scaling: the full fp16 master-weight + overflow machinery,
    selectable even though bf16 is the TPU default."""
    dtype = jnp.float16
    model = TinyModel(dtype=dtype)
    x, y = _data()
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(p, ms, batch):
        xb, yb = batch
        logits, upd = model.apply({"params": p, "batch_stats": ms}, xb,
                                  train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        loss = -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))
        return loss, upd["batch_stats"]

    tx = training.sgd(lr=0.05, momentum=0.9)
    init_fn, step_fn = make_train_step(
        loss_fn, tx, opt_level="O2", cast_model_type=jnp.float16,
        loss_scale="dynamic", has_model_state=True)
    state = init_fn(params, batch_stats)
    step = jax.jit(step_fn)
    losses = []
    for _ in range(STEPS):
        state, metrics = step(state, (x, y))
        losses.append(float(metrics["loss"]))
    traj = np.asarray(losses)
    np.testing.assert_allclose(traj, oracle, atol=_TOL["O2"], rtol=0)
    assert traj[-1] < traj[0]
