"""Unit tests for the bytes ledger (apex_tpu/prof/ledger.py, VERDICT r4
next #1): analytic intrinsic counting, bridge detection, and the shape
signature used for the measured join."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.prof.ledger import (_bridge_bytes, _spatial_sig,
                                  intrinsic_by_shape, intrinsic_ledger)


def test_intrinsic_counts_dot_operands_and_outputs():
    a = jnp.zeros((128, 256), jnp.bfloat16)
    b = jnp.zeros((256, 512), jnp.bfloat16)

    def f(a, b):
        return a @ b

    led = intrinsic_ledger(f, a, b)
    # operands + output at bf16: (128*256 + 256*512 + 128*512) * 2 bytes
    want = (128 * 256 + 256 * 512 + 128 * 512) * 2 / 1e9
    assert abs(led["compute_gb"] - round(want, 3)) < 1e-3
    assert led["optimizer_gb"] == 0.0


def test_intrinsic_optimizer_term():
    a = jnp.zeros((8, 8), jnp.bfloat16)
    led = intrinsic_ledger(lambda x: x @ x, a, n_params=1000,
                           optimizer="adam")
    assert led["optimizer_gb"] == round(1000 * 30 / 1e9, 3)


def test_bridge_detects_distant_consumer():
    # y is produced at eqn 0 and consumed ~200 elementwise eqns later:
    # it must spill.  The chain itself is producer-consumer fusable and
    # must NOT be counted.
    def f(x):
        y = jnp.sin(x)
        z = y
        for _ in range(200):
            z = z + 1.0
        return z + y          # distant read of y

    x = jnp.zeros((256, 256), jnp.float32)
    b = _bridge_bytes(f, x, gap=100)
    want_gb = 256 * 256 * 4 * 2 / 1e9          # one write + one read
    assert abs(b["gb"] - round(want_gb, 3)) < 2e-3, b


def test_bridge_excludes_conv_operands():
    # the distant consumer is a dot_general: its read is charged by the
    # compute ledger, so the bridge must NOT double-count it.
    def f(x):
        y = jnp.sin(x)
        z = y
        for _ in range(200):
            z = z + 1.0
        return z @ y

    x = jnp.zeros((128, 128), jnp.float32)
    b = _bridge_bytes(f, x, gap=100)
    assert b["gb"] == 0.0, b


def test_spatial_sig_picks_largest_nhwc():
    # 128*56*56*64 (25.7M elems) > 128*112*112*3 (4.8M): the larger
    # tensor's spatial dim wins, kernels (7,7,3,64) never do.
    ln = ("%f = (f32[64]{0}, bf16[128,56,56,64]{...}) fusion("
          "bf16[128,112,112,3]{...} %p0, bf16[7,7,3,64]{...} %p1)")
    assert _spatial_sig(ln) == "hw56"
    assert _spatial_sig("%a = f32[8]{0} add(...)") == "other"


def test_intrinsic_by_shape_groups_convs():
    def f(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    x = jnp.zeros((2, 16, 16, 8), jnp.bfloat16)
    w = jnp.zeros((3, 3, 8, 8), jnp.bfloat16)
    rows = intrinsic_by_shape(f, x, w)
    assert "hw16" in rows and rows["hw16"]["count"] == 1
