"""Distributed layer tests on a simulated 8-device CPU mesh.

Covers the DDP contract (reference ``tests/distributed/DDP/
ddp_race_condition_test.py`` semantics — exact grad sums across replicas),
SyncBatchNorm vs. whole-batch BatchNorm (reference ``tests/distributed/
synced_batchnorm`` suite incl. group tests), and LARC.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

import flax.linen as nn

from apex_tpu.parallel import (DistributedDataParallel, Reducer, SyncBatchNorm,
                               LARC, broadcast_params, reduce_gradients,
                               create_syncbn_process_group,
                               convert_syncbn_model, welford_parallel,
                               adopt_batchnorm_stats,
                               larc_gradients)
from apex_tpu.optimizers import FusedSGD

NDEV = 8

# Pre-vma jax (< 0.5, conftest shims shard_map from the experimental
# home with check_rep=False): shard_map autodiff inserts no implicit
# psum and group collectives lower differently, so the tests asserting
# those newer-jax contracts are version-gated.
_pre_vma_jax = pytest.mark.skipif(
    jax.__version_info__ < (0, 5),
    reason="asserts jax>=0.5 shard_map vma/lowering semantics")


def _mesh():
    return Mesh(np.array(jax.devices("cpu")[:NDEV]), ("data",))


def _shmap(fn, mesh, in_specs, out_specs):
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


# -- DDP gradient reduction ---------------------------------------------------

def test_reduce_gradients_mean():
    mesh = _mesh()
    grads = jnp.arange(NDEV * 4, dtype=jnp.float32).reshape(NDEV, 4)

    f = _shmap(lambda g: reduce_gradients({"w": g}, "data")["w"],
               mesh, (P("data"),), P("data"))
    out = f(grads)
    expected = np.broadcast_to(np.asarray(grads).mean(0), (NDEV, 4))
    np.testing.assert_allclose(np.asarray(out).reshape(NDEV, 4), expected,
                               rtol=1e-6)


def test_reduce_gradients_check_vma_false_still_reduces():
    """Regression: under shard_map(check_vma=False) every aval has an empty
    vma set — that must NOT be mistaken for 'already psummed' (there the
    implicit-broadcast transpose does not insert the psum either)."""
    mesh = _mesh()
    grads = jnp.arange(NDEV, dtype=jnp.float32)
    f = shard_map(lambda g: reduce_gradients({"w": g}, "data")["w"],
                  mesh=mesh, in_specs=P("data"), out_specs=P("data"),
                  check_vma=False)
    out = np.asarray(f(grads))
    np.testing.assert_allclose(out, np.full(NDEV, np.asarray(grads).mean()),
                               rtol=1e-6)


@_pre_vma_jax
def test_reduce_gradients_implicit_psum_with_subgroups_divides_full_axis():
    """Regression: a grad already full-axis-psummed by shard_map autodiff
    must be divided by the FULL axis size even when axis_index_groups names
    subgroups (the implicit psum ignores group structure)."""
    mesh = _mesh()
    groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
    x = jnp.asarray(np.random.RandomState(0).randn(NDEV * 2, 3), jnp.float32)
    w = jnp.ones((3,), jnp.float32)

    def step(w_rep, xs):
        def loss(wl):
            return jnp.mean((xs @ wl) ** 2)
        g = jax.grad(loss)(w_rep)     # implicit full-axis psum (replicated w)
        return reduce_gradients({"w": g}, "data",
                                axis_index_groups=groups)["w"]

    f = shard_map(step, mesh=mesh, in_specs=(P(), P("data")), out_specs=P())
    got = np.asarray(jax.jit(f)(w, x))
    # Oracle: average over ALL replicas of the per-shard grad.
    want = np.asarray(jax.grad(
        lambda wl: jnp.mean(jnp.stack([jnp.mean((xs @ wl) ** 2)
                                       for xs in jnp.split(x, NDEV)])))(w))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_reduce_gradients_sum_when_average_off():
    mesh = _mesh()
    grads = jnp.ones((NDEV, 4), jnp.float32)
    f = _shmap(lambda g: reduce_gradients({"w": g}, "data",
                                          gradient_average=False)["w"],
               mesh, (P("data"),), P("data"))
    np.testing.assert_allclose(np.asarray(f(grads)), NDEV)


def test_predivide_factor_equivalent_result():
    """Predivide changes the order of ops, not the result (fp32)."""
    mesh = _mesh()
    rng = np.random.RandomState(0)
    grads = jnp.asarray(rng.randn(NDEV, 16).astype(np.float32))

    def run(predivide):
        f = _shmap(lambda g: reduce_gradients(
            {"w": g}, "data", gradient_predivide_factor=predivide)["w"],
            mesh, (P("data"),), P("data"))
        return np.asarray(f(grads))

    np.testing.assert_allclose(run(1.0), run(8.0), rtol=1e-5, atol=1e-6)


def test_allreduce_always_fp32_preserves_dtype_and_accuracy():
    mesh = _mesh()
    rng = np.random.RandomState(1)
    base = rng.randn(NDEV, 32).astype(np.float32)
    grads = jnp.asarray(base, jnp.bfloat16)
    ddp = DistributedDataParallel(axis_name="data", allreduce_always_fp32=True)
    f = _shmap(lambda g: ddp.reduce_gradients({"w": g})["w"],
               mesh, (P("data"),), P("data"))
    out = f(grads)
    assert out.dtype == jnp.bfloat16
    expected = np.asarray(jnp.asarray(base, jnp.bfloat16), np.float32).mean(0)
    np.testing.assert_allclose(np.asarray(out, np.float32)[0], expected,
                               atol=2e-2, rtol=2e-2)


def test_no_sync_disables_reduction():
    mesh = _mesh()
    ddp = DistributedDataParallel(axis_name="data")
    grads = jnp.arange(NDEV, dtype=jnp.float32).reshape(NDEV, 1)
    with ddp.no_sync():
        f = _shmap(lambda g: ddp.reduce_gradients({"w": g})["w"],
                   mesh, (P("data"),), P("data"))
        out = f(grads)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(grads))


def test_broadcast_params_from_rank0():
    mesh = _mesh()
    params = jnp.arange(NDEV * 3, dtype=jnp.float32).reshape(NDEV, 3)
    f = _shmap(lambda p: broadcast_params({"w": p}, "data")["w"],
               mesh, (P("data"),), P("data"))
    out = np.asarray(f(params)).reshape(NDEV, 3)
    for r in range(NDEV):
        np.testing.assert_array_equal(out[r], np.asarray(params)[0])


def test_subgroup_allreduce():
    """Round-robin communicators → axis_index_groups (reference process
    groups)."""
    mesh = _mesh()
    groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
    grads = jnp.arange(NDEV, dtype=jnp.float32).reshape(NDEV, 1)
    f = _shmap(lambda g: reduce_gradients({"w": g}, "data",
                                          axis_index_groups=groups)["w"],
               mesh, (P("data"),), P("data"))
    out = np.asarray(f(grads)).ravel()
    np.testing.assert_allclose(out[:4], np.mean([0, 1, 2, 3]))
    np.testing.assert_allclose(out[4:], np.mean([4, 5, 6, 7]))


def test_ddp_determinism_race_analog():
    """The ddp_race_condition_test analog: exact, reproducible grad sums
    every iteration (SPMD has no stream races by construction — assert it)."""
    mesh = _mesh()

    def step(g):
        return reduce_gradients({"w": g * 2.0}, "data")["w"]

    f = jax.jit(_shmap(step, mesh, (P("data"),), P("data")))
    g = jnp.arange(NDEV * 8, dtype=jnp.float32).reshape(NDEV, 8)
    first = np.asarray(f(g))
    for _ in range(5):
        np.testing.assert_array_equal(np.asarray(f(g)), first)


# -- Reducer ------------------------------------------------------------------

def test_reducer_manual_allreduce():
    mesh = _mesh()
    r = Reducer(axis_name="data")
    vals = jnp.arange(NDEV, dtype=jnp.float32).reshape(NDEV, 1)
    f = _shmap(lambda v: r.reduce({"p": v})["p"], mesh, (P("data"),), P("data"))
    np.testing.assert_allclose(np.asarray(f(vals)),
                               np.asarray(vals).mean())


# -- SyncBatchNorm ------------------------------------------------------------

def _bn_reference(x, eps=1e-5):
    """Whole-batch BN oracle (torch-free, fp64 accumulation)."""
    xf = np.asarray(x, np.float64)
    axes = tuple(a for a in range(xf.ndim) if a != xf.ndim - 1)
    mean = xf.mean(axis=axes)
    var = xf.var(axis=axes)
    return ((xf - mean) / np.sqrt(var + eps)).astype(np.float32), mean, var


def test_syncbn_matches_whole_batch_bn():
    """Stats synced over 8 shards == BN over the concatenated batch
    (reference two_gpu_unit_test.py)."""
    mesh = _mesh()
    rng = np.random.RandomState(2)
    x = rng.randn(NDEV * 4, 6, 6, 5).astype(np.float32) * 3 + 1
    bn = SyncBatchNorm(axis_name="data", affine=False,
                       track_running_stats=False)
    params = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[:4]))

    def fwd(xs):
        return bn.apply(params, xs)

    f = _shmap(fwd, mesh, (P("data"),), P("data"))
    out = np.asarray(f(jnp.asarray(x)))
    expected, _, _ = _bn_reference(x)
    np.testing.assert_allclose(out, expected, atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_syncbn_running_stats_and_eval():
    mesh = _mesh()
    rng = np.random.RandomState(3)
    x = rng.randn(NDEV * 2, 4, 4, 3).astype(np.float32) * 2 + 5
    bn = SyncBatchNorm(axis_name="data", momentum=1.0)  # running = batch stat
    params = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))

    def fwd(xs):
        return bn.apply(params, xs, mutable=["batch_stats"])

    f = _shmap(fwd, mesh, (P("data"),), (P("data"), P()))
    _, updates = f(jnp.asarray(x))
    _, mean, var = _bn_reference(x)
    n = x.size // x.shape[-1]
    np.testing.assert_allclose(
        np.asarray(updates["batch_stats"]["running_mean"]), mean,
        atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(updates["batch_stats"]["running_var"]),
        var * n / (n - 1), atol=1e-4, rtol=1e-4)
    # Eval path uses the stored stats, no axis needed.
    out_eval = bn.apply(
        {"params": params["params"],
         "batch_stats": updates["batch_stats"]},
        jnp.asarray(x), use_running_average=True)
    assert np.isfinite(np.asarray(out_eval)).all()


def test_syncbn_groups():
    """group_size sub-groups normalize independently (reference
    test_groups.py)."""
    mesh = _mesh()
    groups = create_syncbn_process_group(4, world_size=NDEV)
    assert groups == [[0, 1, 2, 3], [4, 5, 6, 7]]
    rng = np.random.RandomState(4)
    # Make the two halves statistically different.
    x = np.concatenate([
        rng.randn(NDEV // 2 * 2, 3, 3, 2).astype(np.float32),
        rng.randn(NDEV // 2 * 2, 3, 3, 2).astype(np.float32) * 10 + 7])
    bn = SyncBatchNorm(axis_name="data", affine=False,
                       track_running_stats=False, process_group=groups)
    params = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))
    f = _shmap(lambda xs: bn.apply(params, xs), mesh, (P("data"),), P("data"))
    out = np.asarray(f(jnp.asarray(x)))
    half = x.shape[0] // 2
    exp0, _, _ = _bn_reference(x[:half])
    exp1, _, _ = _bn_reference(x[half:])
    np.testing.assert_allclose(out[:half], exp0, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(out[half:], exp1, atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_syncbn_backward_matches_whole_batch():
    """Autodiff through psum == reference's hand-written backward
    (mean_dy/mean_dy_xmu allreduce)."""
    mesh = _mesh()
    rng = np.random.RandomState(5)
    x = rng.randn(NDEV * 2, 4).astype(np.float32) * 2 + 1
    bn = SyncBatchNorm(axis_name="data", affine=False,
                       track_running_stats=False)
    params = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))

    def sharded_loss(xs):
        def inner(xs_):
            out = bn.apply(params, xs_)
            # psum so every shard sees the same scalar; grad is still local.
            return jax.lax.psum(jnp.sum(jnp.sin(out)), "data")
        return _shmap(inner, mesh, (P("data"),), P())(xs)

    gx = np.asarray(jax.grad(lambda xs: sharded_loss(xs))(jnp.asarray(x)))

    bn_full = SyncBatchNorm(axis_name=None, affine=False,
                            track_running_stats=False)
    params_full = bn_full.init(jax.random.PRNGKey(0), jnp.asarray(x))
    gx_full = np.asarray(jax.grad(
        lambda xs: jnp.sum(jnp.sin(bn_full.apply(params_full, xs))))(
            jnp.asarray(x)))
    np.testing.assert_allclose(gx, gx_full, atol=1e-4, rtol=1e-4)


def test_welford_parallel_combine():
    rng = np.random.RandomState(6)
    chunks = [rng.randn(n, 3).astype(np.float32) for n in (5, 9, 2)]
    means = jnp.stack([jnp.mean(jnp.asarray(c), 0) for c in chunks])
    variances = jnp.stack([jnp.var(jnp.asarray(c), 0) for c in chunks])
    counts = jnp.asarray([[c.shape[0]] * 3 for c in chunks], jnp.float32)
    mean, var = welford_parallel(means, variances, counts)
    full = np.concatenate(chunks)
    np.testing.assert_allclose(np.asarray(mean), full.mean(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(var), full.var(0), rtol=1e-4)


def test_adopt_batchnorm_stats_renames_recursively():
    """Plain-BN init stats adopt SyncBatchNorm's reference names at any
    nesting depth; non-stat leaves and dicts pass through untouched."""
    stats = {"bn_init": {"mean": 1, "var": 2},
             "block": {"bn1": {"mean": 3, "var": 4},
                       "other": {"scale": 7}}}
    out = adopt_batchnorm_stats(stats)
    assert out == {"bn_init": {"running_mean": 1, "running_var": 2},
                   "block": {"bn1": {"running_mean": 3, "running_var": 4},
                             "other": {"scale": 7}}}
    # already-adopted stats are a fixed point
    assert adopt_batchnorm_stats(out) == out


def test_convert_syncbn_model():
    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Dense(4)(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            return x

    class Outer(nn.Module):
        inner: nn.Module = None

        @nn.compact
        def __call__(self, x):
            return self.inner(x)

    net = Outer(inner=Net())
    converted = convert_syncbn_model(net, axis_name="data")
    # The BatchNorm inside a @nn.compact body can't be seen statically;
    # converting a module *instance* tree works on dataclass fields.
    assert isinstance(converted, Outer)

    # Direct conversion of a BatchNorm instance:
    bn = nn.BatchNorm(use_running_average=False, epsilon=1e-3, momentum=0.9)
    sbn = convert_syncbn_model(bn, axis_name="data")
    assert isinstance(sbn, SyncBatchNorm)
    assert sbn.eps == 1e-3
    np.testing.assert_allclose(sbn.momentum, 0.1)
    assert sbn.axis_name == "data"


# -- LARC ---------------------------------------------------------------------

def test_larc_gradients_clip_mode():
    params = {"w": jnp.full((4,), 2.0)}
    grads = {"w": jnp.full((4,), 1.0)}
    out = larc_gradients(grads, params, lr=1.0, trust_coefficient=0.02,
                         clip=True, weight_decay=0.0)
    p_norm, g_norm = 4.0, 2.0
    adaptive = 0.02 * p_norm / g_norm  # = 0.04 -> min(0.04/1.0, 1) = 0.04
    np.testing.assert_allclose(np.asarray(out["w"]), 0.04, rtol=1e-6)


def test_larc_wrapper_steps():
    params = {"w": jnp.full((4,), 2.0)}
    opt = LARC(FusedSGD(params, lr=1.0, weight_decay=0.1))
    grads = {"w": jnp.full((4,), 1.0)}
    opt.step(grads=grads)
    # grad rewrite: (g + wd*p) * min(tc*|p|/(|g|+wd*|p|+eps)/lr, 1)
    gf = 1.0 + 0.1 * 2.0
    adaptive = 0.02 * 4.0 / (2.0 + 0.1 * 4.0 + 1e-8)
    expected = 2.0 - min(adaptive, 1.0) * gf
    np.testing.assert_allclose(np.asarray(opt.optim.params["w"]), expected,
                               rtol=1e-5)
    # wd restored after step
    assert opt.optim.defaults["weight_decay"] == 0.1


# -- grouped psum lowering (VERDICT r2 #8: scalable subgroup collectives) ----

def test_group_psum_butterfly_matches_expected():
    """Power-of-two groups take the ppermute butterfly path and sum exactly
    within each group."""
    from apex_tpu.parallel.distributed import group_psum
    mesh = _mesh()
    groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
    x = jnp.arange(NDEV, dtype=jnp.float32) + 1.0       # 1..8
    f = _shmap(lambda v: group_psum(v, "data", groups), mesh,
               P("data"), P("data"))
    out = np.asarray(jax.jit(f)(x))
    np.testing.assert_array_equal(out[:4], np.full(4, 10.0))   # 1+2+3+4
    np.testing.assert_array_equal(out[4:], np.full(4, 26.0))   # 5+6+7+8


@_pre_vma_jax
def test_group_psum_butterfly_no_full_world_gather():
    """The lowered HLO for power-of-two groups must contain collective
    permutes, not a full-world all-gather (pod-scalability contract)."""
    from apex_tpu.parallel.distributed import group_psum
    mesh = _mesh()
    groups = [[0, 1], [2, 3], [4, 5], [6, 7]]
    f = jax.jit(_shmap(lambda v: group_psum(v, "data", groups), mesh,
                       P("data"), P("data")))
    hlo = f.lower(jnp.zeros((NDEV, 16), jnp.float32)).as_text()
    assert ("collective_permute" in hlo) or ("collective-permute" in hlo)
    assert "all_gather" not in hlo and "all-gather" not in hlo


def test_group_psum_irregular_groups_fallback():
    """Unequal group sizes fall back to the gather+mask lowering and still
    produce correct per-group sums."""
    from apex_tpu.parallel.distributed import group_psum
    mesh = _mesh()
    groups = [[0, 1, 2], [3, 4, 5], [6, 7]]
    x = jnp.arange(NDEV, dtype=jnp.float32) + 1.0
    f = _shmap(lambda v: group_psum(v, "data", groups), mesh,
               P("data"), P("data"))
    out = np.asarray(jax.jit(f)(x))
    np.testing.assert_array_equal(out[:3], np.full(3, 6.0))
    np.testing.assert_array_equal(out[3:6], np.full(3, 15.0))
    np.testing.assert_array_equal(out[6:], np.full(2, 15.0))
