"""tools/jaxlint self-tests + the repo-wide clean-lint tier-1 gate.

Two layers, mirroring the linter's contract (docs/jaxlint.md):

1. fixture self-tests — for every rule J001-J016 a known-bad snippet
   must flag and the same snippet with an inline waiver (or the real
   fix) must pass, so a rule that silently stops firing breaks CI
   before it stops protecting the codebase;
2. the repo gate — ``lint_paths(apex_tpu examples tools bench.py)``
   must return zero findings forever: introducing an unwaived host
   sync / retrace hazard / fp32 leak fails tier-1, the same way the
   reference relied on pjit's trace-time machinery (SNIPPETS.md [1]).

Pure AST analysis: no accelerator, runs under ``JAX_PLATFORMS=cpu``
with the standard conftest skip logic (not a ``tpu``-marked test).
"""

import os
import subprocess
import sys
import textwrap

import pytest

from tools.jaxlint import lint_paths, lint_source
from tools.jaxlint.cli import main as jaxlint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_TARGETS = [os.path.join(REPO, p)
                for p in ("apex_tpu", "examples", "tools")] \
    + [os.path.join(REPO, "bench.py")]


def _codes(src, path="apex_tpu/fixture.py", driver=None):
    """Rule codes flagged for a snippet (library context by default)."""
    return sorted({f.rule for f in
                   lint_source(textwrap.dedent(src), path, driver=driver)})


# -- J001: host sync in device code -------------------------------------------

def test_j001_flags_host_sync_in_library_code():
    bad = """
    import jax

    def probe(flag):
        return float(jax.device_get(flag))
    """
    assert _codes(bad) == ["J001"]


def test_j001_waiver_with_reason_passes():
    waived = """
    import jax

    def probe(flag):
        return float(jax.device_get(flag))  # jaxlint: disable=J001 -- test fixture
    """
    assert _codes(waived) == []


def test_j001_driver_flags_only_loop_syncs():
    src = """
    import jax
    import jax.numpy as jnp

    for i in range(10):
        x = jnp.ones(3)
        print(float(jax.device_get(x)))
    done = float(jax.device_get(jnp.ones(3)))
    """
    findings = lint_source(textwrap.dedent(src), "examples/demo.py")
    assert [f.rule for f in findings] == ["J001"]
    assert "inside a loop" in findings[0].message


def test_j001_metadata_reads_are_not_syncs():
    ok = """
    import jax.numpy as jnp

    def widths(x):
        y = jnp.ones(3)
        return int(y.shape[0]), int(jnp.size(y))
    """
    assert _codes(ok) == []


def test_j001_loop_target_from_array_iterable_flags():
    """ISSUE-2 extension: iterating a jax array binds device values to
    the loop target, so float()/.item()/np.asarray() on it inside the
    for body is a per-iteration sync (the old tracking only followed
    Assign bindings and missed exactly this)."""
    bad = """
    import jax.numpy as jnp

    losses = jnp.ones(8)
    for l in losses:
        print(float(l))
    """
    findings = lint_source(textwrap.dedent(bad), "examples/demo.py")
    assert [f.rule for f in findings] == ["J001"]
    waived = bad.replace(
        "print(float(l))",
        "print(float(l))  # jaxlint: disable=J001 -- fixture")
    assert _codes(waived, "examples/demo.py") == []


def test_j001_zip_and_while_body_syncs_flag():
    bad_zip = """
    import jax.numpy as jnp
    import numpy as np

    xs = jnp.ones((4, 2))
    ys = jnp.ones(4)
    for x, y in zip(xs, ys):
        np.asarray(x)
    """
    assert _codes(bad_zip, "examples/demo.py") == ["J001"]
    bad_while = """
    import jax.numpy as jnp

    x = jnp.ones(3)
    while True:
        v = x.item()
        break
    """
    # a while-body sync still flags — since ISSUE 11 as the more
    # specific serving-loop rule J012 (reported INSTEAD of J001)
    assert _codes(bad_while, "examples/demo.py") == ["J012"]


def test_j001_scalar_loop_counters_stay_host_values():
    """enumerate over a jax array: the VALUE target is arrayish, the
    counter stays a Python int — float(i) must not flag."""
    src = """
    import jax.numpy as jnp

    losses = jnp.ones(8)
    for i, l in enumerate(losses):
        print(float(i))
    """
    assert _codes(src, "examples/demo.py") == []
    flagged = src.replace("float(i)", "float(l)")
    assert _codes(flagged, "examples/demo.py") == ["J001"]


# -- J002: jit of non-array Python args ---------------------------------------

_J002_BAD = """
import jax

def step(x, training: bool):
    return x

run = jax.jit(step)
"""


def test_j002_flags_unmarked_python_arg():
    assert _codes(_J002_BAD) == ["J002"]


def test_j002_static_argnums_passes():
    assert _codes(_J002_BAD.replace(
        "jax.jit(step)", "jax.jit(step, static_argnums=(1,))")) == []


def test_j002_static_argnames_and_waiver_pass():
    assert _codes(_J002_BAD.replace(
        "jax.jit(step)",
        "jax.jit(step, static_argnames=('training',))")) == []
    assert _codes(_J002_BAD.replace(
        "run = jax.jit(step)",
        "run = jax.jit(step)  # jaxlint: disable=J002 -- fixture")) == []


def test_j002_flags_str_default():
    bad = """
    import jax

    def step(x, mode="train"):
        return x

    run = jax.jit(step)
    """
    assert _codes(bad) == ["J002"]


# -- J003: fp32 leak in bf16 paths --------------------------------------------

_J003_BAD = """
import jax.numpy as jnp

def forward(x, w):
    assert str(w.dtype) == "bfloat16"
    h = x @ w
    wide = h.astype(jnp.float32)
    return wide + 1
"""


def test_j003_flags_uncompensated_fp32_cast():
    assert _codes(_J003_BAD) == ["J003"]


def test_j003_compensating_downcast_passes():
    fixed = _J003_BAD.replace("return wide + 1",
                              "return (wide + 1).astype(x.dtype)")
    assert _codes(fixed) == []


def test_j003_fp32_loss_sink_is_exempt():
    ok = """
    import jax.numpy as jnp

    def loss(x):
        h = x.astype(jnp.bfloat16)
        return jnp.mean(h.astype(jnp.float32))
    """
    # reductions/losses belong in fp32 under amp (the O1 fp32 list)
    assert "J003" not in _codes(ok)


def test_j003_flags_literal_promotion():
    bad = """
    import jax.numpy as jnp

    def scale(x):
        h = x.astype(jnp.bfloat16)
        return h * jnp.float32(2.0)
    """
    assert "J003" in _codes(bad)


# -- J004: retracing hazards --------------------------------------------------

_J004_BAD = """
import jax
import jax.numpy as jnp

step = jax.jit(lambda x, s: x * s)
x = jnp.ones(3)
for i in range(10):
    x = step(x, i)
"""


def test_j004_flags_loop_scalar_into_jit():
    assert _codes(_J004_BAD, "examples/demo.py") == ["J004"]


def test_j004_traced_array_passes():
    fixed = _J004_BAD.replace("step(x, i)", "step(x, jnp.asarray(i))")
    assert _codes(fixed, "examples/demo.py") == []


def test_j004_flags_loop_scalar_as_keyword_arg():
    # keyword args retrace exactly like positional ones (review finding)
    bad = _J004_BAD.replace("lambda x, s: x * s", "lambda x, s=1: x * s") \
                   .replace("step(x, i)", "step(x, s=i)")
    assert _codes(bad, "examples/demo.py") == ["J004"]


def test_j004_flags_jit_inside_loop():
    bad = """
    import jax

    def rebuild(fns, x):
        outs = []
        for fn in fns:
            outs.append(jax.jit(fn)(x))
        return outs
    """
    assert "J004" in _codes(bad)


# -- J005: use-after-donate ---------------------------------------------------

_J005_BAD = """
import jax

step = jax.jit(lambda s, b: s, donate_argnums=(0,))

def run(state, batch):
    out = step(state, batch)
    return state
"""


def test_j005_flags_read_after_donate():
    assert _codes(_J005_BAD) == ["J005"]


def test_j005_rebinding_passes():
    fixed = _J005_BAD.replace("out = step(state, batch)",
                              "state = step(state, batch)") \
                     .replace("return state", "return state  # rebound")
    assert _codes(fixed) == []


def test_j005_flags_same_line_read_in_rebind():
    # `state = f(state)` after donating state: the RHS Load evaluates
    # before the Store even though the Store tokenizes first (review)
    bad = """
    import jax
    import jax.numpy as jnp

    step = jax.jit(lambda s, b: s, donate_argnums=(0,))

    def run(state, extra, batch):
        out = step(state, batch)
        state = jnp.concatenate([state, extra])
        return out, state
    """
    assert "J005" in _codes(bad)


def test_j005_flags_loop_without_rebind():
    bad = """
    import jax

    step = jax.jit(lambda s, b: s, donate_argnums=(0,))

    def run(state, batches):
        for b in batches:
            out = step(state, b)
        return out
    """
    assert "J005" in _codes(bad)


# -- J006: Python control flow on traced values -------------------------------

_J006_BAD = """
import jax
import jax.numpy as jnp

@jax.jit
def clamp(x):
    if jnp.any(x > 0):
        return x
    return -x
"""


def test_j006_flags_branch_on_traced():
    assert _codes(_J006_BAD) == ["J006"]


def test_j006_unjitted_branch_passes():
    # same body outside jit: Python branching on a concrete array is fine
    assert _codes(_J006_BAD.replace("@jax.jit\n", "")) == []


def test_j006_where_passes():
    fixed = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def clamp(x):
        return jnp.where(jnp.any(x > 0), x, -x)
    """
    assert _codes(fixed) == []


# -- J007: per-step host staging in training loops ----------------------------

_J007_BAD = """
import jax
import numpy as np

for batch in loader:
    x = jax.device_put(batch)
    state = step(state, x)
"""


def test_j007_flags_per_step_device_put_on_batch():
    assert _codes(_J007_BAD, "examples/demo.py") == ["J007"]


def test_j007_flags_per_step_asarray_in_driver():
    bad = """
    import numpy as np

    for images, labels in stream:
        x = np.asarray(images, np.float32)
        state = step(state, x, labels)
    """
    assert _codes(bad, "examples/demo.py") == ["J007"]


def test_j007_asarray_in_library_loop_passes():
    # the asarray half is scoped to DRIVER files: library code
    # legitimately asarray's in serialization / metadata loops
    src = """
    import numpy as np

    def save_all(leaves):
        return [np.asarray(l) for l in leaves]

    def stage(batches):
        out = []
        for b in batches:
            out.append(np.asarray(b))
        return out
    """
    assert _codes(src, "apex_tpu/fixture.py") == []


def test_j007_device_put_flags_in_library_loops_too():
    # device_put is flagged regardless of driver/library: re-staging
    # per step is the same stall wherever it lives
    src = """
    import jax

    def feed(batches):
        for b in batches:
            yield jax.device_put(b)
    """
    assert _codes(src, "apex_tpu/fixture.py") == ["J007"]


def test_j007_waiver_and_loader_staging_pass():
    waived = _J007_BAD.replace(
        "x = jax.device_put(batch)",
        "x = jax.device_put(batch)  # jaxlint: disable=J007 -- fixture")
    assert _codes(waived, "examples/demo.py") == []
    # the FIX: stage once via the loader, iterate device batches
    fixed = """
    import jax
    from apex_tpu.data import PrefetchLoader

    for batch in PrefetchLoader(stream, depth=2, workers=4):
        state = step(state, batch)
    """
    assert _codes(fixed, "examples/demo.py") == []


def test_j007_outside_loop_passes():
    # one-time staging before the loop is the sanctioned pattern
    src = """
    import jax

    window = jax.device_put(host_window)
    for _ in range(10):
        state = step(state, window)
    """
    assert _codes(src, "examples/demo.py") == []


# -- J008: per-leaf host syncs in tree_leaves loops ---------------------------

_J008_BAD = """
import jax
import jax.numpy as jnp

def grad_norms(grads):
    out = []
    for g in jax.tree_util.tree_leaves(grads):
        leaf_norm = jnp.sqrt(jnp.sum(g * g))
        out.append(float(leaf_norm))
    return out
"""


def test_j008_flags_per_leaf_sync_and_not_j001():
    """The ISSUE-4 fixture: float(leaf_norm) inside a loop over
    tree_leaves is the O(leaves)-round-trips sweep — reported as the
    specific J008, not a garden-variety J001."""
    assert _codes(_J008_BAD) == ["J008"]


def test_j008_waiver_with_reason_passes():
    waived = _J008_BAD.replace(
        "out.append(float(leaf_norm))",
        "out.append(float(leaf_norm))  # jaxlint: disable=J008 -- fixture")
    assert _codes(waived) == []


def test_j008_device_side_reduction_is_the_fix():
    fixed = """
    import jax
    import jax.numpy as jnp

    def grad_norms(grads):
        leaves = jax.tree_util.tree_leaves(grads)
        return jnp.stack([jnp.sqrt(jnp.sum(g * g)) for g in leaves])
    """
    assert _codes(fixed) == []


def test_j008_tree_flatten_binding_and_driver_context():
    bad = """
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    for l in leaves:
        print(np.asarray(l))
    """
    assert _codes(bad, "examples/demo.py") == ["J008"]
    bad_sub = bad.replace(
        "leaves, treedef = jax.tree_util.tree_flatten(tree)",
        "leaves = jax.tree_util.tree_flatten(tree)[0]")
    assert _codes(bad_sub, "examples/demo.py") == ["J008"]


def test_j008_zip_over_leaf_lists_flags():
    bad = """
    import jax

    def drain(a, b):
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)):
            jax.device_get(x + y)
    """
    assert _codes(bad) == ["J008"]


def test_j008_leafless_loop_still_plain_j001():
    # an ordinary array loop stays J001 — J008 is only the tree sweep
    src = """
    import jax.numpy as jnp

    losses = jnp.ones(8)
    for l in losses:
        print(float(l))
    """
    assert _codes(src, "examples/demo.py") == ["J001"]


def test_j008_host_boundary_funcs_stay_exempt():
    # serialization materializes per leaf by contract, like J001
    src = """
    import jax
    import numpy as np

    class Opt:
        def state_dict(self):
            out = []
            for l in jax.tree_util.tree_leaves(self.state):
                out.append(np.asarray(l))
            return out
    """
    assert _codes(src) == []


# -- J009: async-dispatch timing lies -----------------------------------------

_J009_BAD = """
import time
import jax

step = jax.jit(lambda s, b: s + b)

def bench(state, batches):
    t0 = time.perf_counter()
    for b in batches:
        state = step(state, b)
    dt = time.perf_counter() - t0
    return dt
"""


def test_j009_flags_unfenced_timing_of_jitted_call():
    """The ISSUE-5 fixture: perf_counter around a jitted loop with no
    sync in the span times ENQUEUE, not compute (the 6x-chip-peak bench
    round-1 failure mode)."""
    assert _codes(_J009_BAD, "examples/demo.py") == ["J009"]


def test_j009_waiver_with_reason_passes():
    waived = _J009_BAD.replace(
        "    dt = time.perf_counter() - t0",
        "    dt = time.perf_counter() - t0  "
        "# jaxlint: disable=J009 -- fixture")
    assert _codes(waived, "examples/demo.py") == []


def test_j009_block_until_ready_fence_passes():
    fixed = _J009_BAD.replace(
        "    dt = time.perf_counter() - t0",
        "    jax.block_until_ready(state)\n"
        "    dt = time.perf_counter() - t0")
    assert _codes(fixed, "examples/demo.py") == []


def test_j009_value_fetch_fence_passes():
    fixed = _J009_BAD.replace(
        "    dt = time.perf_counter() - t0",
        "    _ = float(state[0])\n"
        "    dt = time.perf_counter() - t0")
    assert _codes(fixed, "examples/demo.py") == []


def test_j009_local_sync_helper_counts_as_fence():
    """A call to a module-local helper that syncs internally (bench.py's
    ``_force`` pattern) fences the timing — one-level interprocedural."""
    fixed = """
    import time
    import jax
    import jax.numpy as jnp

    step = jax.jit(lambda s, b: s + b)

    def _force(x):
        return float(jnp.ravel(x)[0])

    def bench(state, batches):
        t0 = time.perf_counter()
        for b in batches:
            state = step(state, b)
        _force(state)
        dt = time.perf_counter() - t0
        return dt
    """
    assert _codes(fixed, "examples/demo.py") == []


def test_j009_needs_a_jitted_call_between_clocks():
    # plain host timing, and a jitted call outside the clock pair, pass
    src = """
    import time
    import jax

    step = jax.jit(lambda s: s)

    def setup(state):
        state = step(state)          # before the first clock read
        t0 = time.perf_counter()
        host_work()
        dt = time.perf_counter() - t0
        return state, dt
    """
    assert _codes(src, "examples/demo.py") == []


# -- J010: cost harvesting inside step loops ----------------------------------

def test_j010_flags_cost_analysis_in_loop():
    """The ISSUE-6 fixture: harvesting XLA costs per loop iteration
    re-traces (and recompiles) every call — harvest once before."""
    bad = """
    import jax

    step = jax.jit(lambda s, b: s + b)

    def sweep(batches):
        for b in batches:
            cost = step.lower(0.0, b).compile().cost_analysis()
            use(cost)
    """
    # .lower / .compile / .cost_analysis all sit on the same chain;
    # codes dedup to one J010 (jax.jit itself is hoisted, so no J004)
    assert _codes(bad) == ["J010"]


def test_j010_flags_lower_of_jitted_name_in_loop():
    bad = """
    import jax

    step = jax.jit(lambda s, b: s + b)

    def probe(batches):
        for b in batches:
            hlo = step.lower(0.0, b)
    """
    assert _codes(bad) == ["J010"]


def test_j010_waiver_with_reason_passes():
    waived = """
    import jax

    step = jax.jit(lambda s, b: s + b)

    def sweep(shapes):
        for b in shapes:
            # jaxlint: disable=J010 -- fixture: deliberate per-shape harvest
            cost = step.lower(0.0, b).compile().cost_analysis()
    """
    assert _codes(waived) == []


def test_j010_harvest_before_loop_passes():
    ok = """
    import jax

    def sweep(fn, b, batches):
        cost = jax.jit(fn).lower(b).compile().cost_analysis()
        for bb in batches:
            use(cost, bb)
    """
    assert _codes(ok) == []


def test_j010_string_lower_and_re_compile_pass():
    """`.lower()` on a string and `re.compile` are not jitted
    computations — the receiver must be demonstrably jitted."""
    ok = """
    import re

    def scan(names):
        for n in names:
            m = re.compile("x").match(n.lower())
    """
    assert _codes(ok) == []


# -- J011: unfused BN/GN + ReLU chains in model bodies (advisory) -------------

def test_j011_nested_bn_relu_flags():
    bad = """
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.relu(nn.BatchNorm(use_running_average=False)(x))
    """
    assert _codes(bad) == ["J011"]


def test_j011_consecutive_statements_flag():
    bad = """
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            y = nn.GroupNorm(num_groups=8)(x)
            y = nn.relu(y)
            return y
    """
    assert _codes(bad) == ["J011"]


def test_j011_partial_and_lambda_norm_aliases_flag():
    """The factory idiom model bodies actually use (dcgan's lambda,
    resnet's functools.partial) must not hide the chain."""
    bad = """
    import functools
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            norm = functools.partial(nn.BatchNorm,
                                     use_running_average=not train)
            x = nn.relu(norm(name="bn0")(x))
            lnorm = lambda name: nn.BatchNorm(use_running_average=not train,
                                              name=name)
            x = nn.relu(lnorm("bn1")(x))
            return x
    """
    assert _codes(bad) == ["J011"]


def test_j011_else_branch_chain_flags():
    """The scan covers every statement list, not just .body — an
    else-arm bn->relu chain is the same two sweeps (review regression
    pin)."""
    bad = """
    import flax.linen as nn

    class Net(nn.Module):
        fused: bool = False

        @nn.compact
        def __call__(self, x):
            if self.fused:
                x = x
            else:
                y = nn.BatchNorm(use_running_average=False)(x)
                y = nn.relu(y)
            return y
    """
    assert _codes(bad) == ["J011"]


def test_j011_negatives_pass():
    """leaky_relu has no fused epilogue; an intervening statement breaks
    the chain; non-__call__ bodies are out of scope."""
    ok = """
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.leaky_relu(nn.BatchNorm(use_running_average=False)(x),
                              0.2)
            y = nn.BatchNorm(use_running_average=False, name="bn2")(x)
            y = y + x
            y = nn.relu(y)
            return y

    def helper(x):
        return nn.relu(nn.BatchNorm(use_running_average=False)(x))
    """
    assert _codes(ok) == []


def test_j011_waiver_with_reason_passes():
    waived = """
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.relu(nn.BatchNorm(use_running_average=False)(x))  # jaxlint: disable=J011 -- fixture: tiny maps below the fusion crossover
    """
    assert _codes(waived) == []


def test_j011_is_advisory_and_cli_exits_zero(tmp_path):
    """Advisory contract: the finding renders as [advisory] and an
    advisory-only file does NOT fail the CLI; mixing in an error-class
    finding still does."""
    from tools.jaxlint.linter import Finding
    src = textwrap.dedent("""
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.relu(nn.BatchNorm(use_running_average=False)(x))
    """)
    findings = lint_source(src, "apex_tpu/fixture.py")
    assert [f.rule for f in findings] == ["J011"]
    assert findings[0].advisory and "[advisory]" in findings[0].render()
    assert not Finding("p", 1, 0, "J001", "m").advisory

    adv = tmp_path / "advisory_only.py"
    adv.write_text(src)
    assert jaxlint_main([str(adv)]) == 0

    mixed = tmp_path / "mixed.py"
    mixed.write_text(src + textwrap.dedent("""
    import jax

    def probe(flag):
        return float(jax.device_get(flag))
    """))
    assert jaxlint_main([str(mixed)]) == 1


# -- J000: waiver hygiene -----------------------------------------------------

def test_j000_waiver_without_reason_flags_and_waives_nothing():
    bad = """
    import jax

    def probe(flag):
        return float(jax.device_get(flag))  # jaxlint: disable=J001
    """
    assert _codes(bad) == ["J000", "J001"]


def test_j000_unknown_rule_code_flags():
    assert "J000" in _codes("x = 1  # jaxlint: disable=J999 -- nope\n")


def test_waiver_covers_following_line():
    # multi-line statements can't carry a trailing comment on line 1
    src = """
    import jax

    def probe(a, b):
        # jaxlint: disable=J001 -- fixture: stacked transfer
        return float(jax.device_get(
            a + b))
    """
    assert _codes(src) == []


def test_file_level_waiver():
    src = """
    # jaxlint: disable-file=J001 -- fixture: host-side module by design
    import jax

    def probe(flag):
        return float(jax.device_get(flag))
    """
    assert _codes(src) == []


def test_trailing_waiver_does_not_bleed_to_next_line():
    # a trailing waiver is scoped to its own line: an unrelated
    # violation added directly below must still flag (review finding)
    src = """
    import jax

    def probe(a, b):
        x = float(jax.device_get(a))  # jaxlint: disable=J001 -- sanctioned
        y = float(jax.device_get(b))
        return x + y
    """
    findings = lint_source(textwrap.dedent(src), "apex_tpu/fixture.py")
    assert [f.rule for f in findings] == ["J001"]
    assert findings[0].line == 6          # the unwaived second sync


def test_j001_flags_sync_on_jitted_step_outputs():
    # tuple-unpacked results of a jitted callable are device arrays:
    # the per-step float(metrics[...]) sync must flag (review finding —
    # the exact bug class this PR scrubbed from examples/lm)
    src = """
    import jax

    step = jax.jit(lambda s, b: (s, {"loss": s}))

    def train(state, batches):
        for b in batches:
            state, metrics = step(state, b)
            print(float(metrics["loss"]))
        return state
    """
    assert "J001" in _codes(src, "examples/demo.py")


def test_j001_metadata_mixed_with_compute_still_flags():
    # .shape appearing INSIDE a device computation is not an exemption
    # (review finding: float(jnp.sum(y) / y.shape[0]) is a real sync)
    bad = """
    import jax.numpy as jnp

    def mean_of(y):
        return float(jnp.sum(y) / y.shape[0])
    """
    assert _codes(bad) == ["J001"]
    ok = """
    import jax.numpy as jnp

    def rows_times_cols(y):
        return int(y.shape[0] * y.shape[1])
    """
    assert _codes(ok) == []


def test_j001_post_fetch_host_values_are_free():
    # the fetch is the one finding; consuming the fetched host value
    # afterwards is plain host arithmetic (review finding)
    src = """
    import jax
    import jax.numpy as jnp

    def drain(flags):
        vals = jax.device_get(jnp.stack(flags))  # jaxlint: disable=J001 -- the one batched transfer
        if bool(vals.any()):
            return [bool(v) for v in vals]
        return []
    """
    assert _codes(src) == []


def test_j005_fires_at_module_scope():
    # drivers donate-and-read at the top level (review finding: the
    # fn-only read-later lookup made J005 a no-op there)
    src = """
    import jax

    step = jax.jit(lambda s, b: s, donate_argnums=(0,))
    state = init()
    out = step(state, batch)
    print(state)
    """
    assert "J005" in _codes(src, "examples/demo.py")


def test_lambda_argument_is_not_arrayish():
    # feeding arrays to a timing harness via a lambda must not mark the
    # harness's host-float result arrayish (tools/attention_sweep idiom)
    src = """
    import jax.numpy as jnp

    def sweep(timer):
        q = jnp.ones(8)
        t = timer(lambda: q * 2) * 1e3
        best = bool(t < 5.0)
        return best
    """
    assert _codes(src) == []


def test_waivers_in_docstrings_are_ignored():
    src = '''
    def doc():
        """Example: x  # jaxlint: disable=J001"""
        return 1
    '''
    assert _codes(src) == []


# -- CLI contract -------------------------------------------------------------

def test_cli_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert jaxlint_main([str(clean)]) == 0
    assert "clean" in capsys.readouterr().out

    dirty = tmp_path / "dirty.py"
    dirty.write_text("import jax\n\n"
                     "def probe(f):\n"
                     "    return float(jax.device_get(f))\n")
    assert jaxlint_main([str(dirty)]) == 1
    out = capsys.readouterr().out
    assert "J001" in out and "finding" in out

    assert jaxlint_main([]) == 2                       # no paths
    assert jaxlint_main([str(tmp_path / "nope.txt")]) == 2
    assert jaxlint_main(["--list-rules"]) == 0
    assert "J004" in capsys.readouterr().out


@pytest.mark.slow
def test_cli_module_entry_point(tmp_path):
    """``python -m tools.jaxlint`` — the exact invocation CI documents."""
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import jax\n\n"
                     "def probe(f):\n"
                     "    return float(jax.device_get(f))\n")
    r = subprocess.run([sys.executable, "-m", "tools.jaxlint", str(dirty)],
                       cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 1 and "J001" in r.stdout
    r = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", "--list-rules"],
        cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 0


# -- the tier-1 gate ----------------------------------------------------------

def test_repo_is_lint_clean():
    """THE gate: every finding in the package, the examples, the tools,
    and the bench is either fixed or carries a documented waiver.  A new
    unwaived host sync / retrace hazard / fp32 leak fails tier-1 here."""
    findings = lint_paths(LINT_TARGETS)
    assert not findings, (
        f"{len(findings)} jaxlint finding(s) — fix them or waive with "
        f"'# jaxlint: disable=<rule> -- <reason>':\n"
        + "\n".join(f.render() for f in findings))


def test_repo_gate_actually_sees_the_package():
    """Guard the gate itself: the walk must visit the real modules (an
    empty file list would make the gate pass vacuously)."""
    import glob
    n_pkg = len(glob.glob(os.path.join(REPO, "apex_tpu", "**", "*.py"),
                          recursive=True))
    assert n_pkg > 30        # the package has ~40 modules


# -- J012: per-request host syncs in serving contexts (ISSUE 11) --------------

def test_j012_sync_in_while_serving_loop():
    bad = """
    import jax

    def drain(queue, engine):
        while queue:
            out = engine.decode()
            jax.block_until_ready(out)
    """
    assert _codes(bad) == ["J012"]


def test_j012_sync_in_request_handler_function():
    bad = """
    import jax

    def handle_request(engine, prompt):
        logits = engine.prefill(prompt)
        return jax.device_get(logits)
    """
    assert _codes(bad) == ["J012"]
    # handler-segment matching: 'serve'/'request'/'handler' names too
    also = bad.replace("handle_request", "serve_one")
    assert _codes(also) == ["J012"]


def test_j012_replaces_j001_not_added_to_it():
    """J012 is the MORE SPECIFIC rule: in a serving context the sync is
    reported once as J012, never doubled with J001; outside those
    contexts a loop sync stays plain J001."""
    serving = """
    import jax

    def pump(engine):
        while True:
            x = engine.step()
            v = float(jax.device_get(x))
    """
    assert _codes(serving) == ["J012"]
    plain = """
    import jax

    def sweep(items):
        for it in items:
            jax.device_get(it)
    """
    assert _codes(plain) == ["J001"]


def test_j012_waived_response_boundary():
    ok = """
    import numpy as np

    def handle_request(engine, prompt):
        tok = engine.decode(prompt)
        return np.asarray(tok)  # jaxlint: disable=J001,J012 -- the response boundary: sampled tokens must reach the caller
    """
    assert _codes(ok) == []


def test_j012_driver_top_level_handler_not_flagged():
    """Driver scripts keep the in-loop gate: a handler-named function
    syncing once at top level is the legitimate end-of-run read."""
    src = """
    import jax

    def handle_request(engine, p):
        return jax.device_get(engine.run(p))
    """
    assert _codes(src, path="examples/serve.py") == []
    # ...but a while-loop sync in a driver is still per-request
    loop = """
    import jax

    def main(engine, reqs):
        while reqs:
            jax.device_get(engine.step())
    """
    assert _codes(loop, path="examples/serve.py") == ["J012"]


def test_j012_interior_on_segment_stays_j001():
    """`on` marks a handler only as a PREFIX (`on_request`): an interior
    `_on_` (train_on_batch) must stay J001 so existing J001 waivers keep
    covering it."""
    src = """
    import jax

    def train_on_batch(step, state, b):
        state, m = step(state, b)
        return float(jax.device_get(m))
    """
    assert _codes(src) == ["J001"]
    prefixed = src.replace("train_on_batch", "on_request")
    assert _codes(prefixed) == ["J012"]


# -- J013: unsharded parameter staging in multi-device entry points (ISSUE 12)-

def test_j013_flags_bare_device_put_in_mesh_function():
    bad = """
    import jax
    from jax.sharding import Mesh

    def launch(params, batch):
        mesh = Mesh(jax.devices(), ("data",))
        params = jax.device_put(params)
        return mesh
    """
    assert _codes(bad) == ["J013"]


def test_j013_flags_jnp_asarray_of_params_in_mesh_function():
    bad = """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    def setup(weights):
        w = jnp.asarray(weights)
        return NamedSharding
    """
    assert _codes(bad) == ["J013"]


def test_j013_explicit_sharding_passes():
    ok = """
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    def launch(params):
        mesh = Mesh(jax.devices(), ("data",))
        sh = NamedSharding(mesh, P())
        params = jax.device_put(params, sh)
        other = jax.device_put(params, device=sh)
        return params, other
    """
    assert _codes(ok) == []


def test_j013_only_fires_in_multi_device_functions():
    """A bare device_put in single-device code is normal staging — the
    rule needs the mesh marker (Mesh/MeshPlan/shard_map/NamedSharding)
    in the same function."""
    ok = """
    import jax

    def stage(params):
        return jax.device_put(params)
    """
    assert _codes(ok) == []


def test_j013_only_parameter_sized_names_flag():
    """A scalar/batch staged without a sharding is noise, not a finding
    — the name heuristic keeps the rule to parameter-sized arrays."""
    ok = """
    import jax
    from jax.sharding import Mesh

    def launch(flag):
        mesh = Mesh(jax.devices(), ("data",))
        f = jax.device_put(flag)
        return mesh
    """
    assert _codes(ok) == []


def test_j013_is_advisory_and_waivable():
    from tools.jaxlint.linter import Finding

    assert Finding("p", 1, 0, "J013", "m").advisory
    waived = """
    import jax
    from jax.sharding import Mesh

    def launch(params):
        mesh = Mesh(jax.devices(), ("data",))
        params = jax.device_put(params)  # jaxlint: disable=J013 -- single-host tool, placement irrelevant
        return mesh
    """
    assert _codes(waived) == []


# -- J014: per-step recalibration at quantized-matmul call sites (ISSUE 13) ---

def test_j014_flags_inline_absmax_scale():
    bad = """
    import jax.numpy as jnp
    from apex_tpu import quant

    def step_fn(state, batch):
        x = batch["x"]
        return quant.quantized_matmul(
            x, state["w"], x_scale=jnp.max(jnp.abs(x)) / 127.0)
    """
    assert _codes(bad) == ["J014"]


def test_j014_flags_local_assigned_absmax_and_method_form():
    bad = """
    import jax.numpy as jnp
    from apex_tpu import quant

    def step_fn(state, batch):
        x = batch["x"]
        s = jnp.abs(x).max() / 127.0
        return quant.quantized_matmul(x, state["w"], x_scale=s)
    """
    assert _codes(bad) == ["J014"]


def test_j014_frozen_scale_and_w_scale_pass():
    ok = """
    import jax.numpy as jnp
    from apex_tpu import quant

    def step_fn(state, batch, calib):
        x = batch["x"]
        frozen = calib.scales["mlp_up"]
        a = quant.quantized_matmul(x, state["w"], x_scale=frozen)
        # w_scale from the CURRENT weights is the correct recipe —
        # weights are exact at trace time (never J014)
        b = quant.quantized_matmul(
            x, state["w"], x_scale=frozen,
            w_scale=jnp.max(jnp.abs(state["w"]), axis=0) / 127.0)
        return a + b
    """
    assert _codes(ok) == []


def test_j014_only_fires_on_quant_call_sites():
    ok = """
    import jax.numpy as jnp

    def step_fn(x, w):
        # an absmax that is NOT a quantized-matmul scale arg is fine
        norm = jnp.max(jnp.abs(x))
        return some_op(x, scale=jnp.max(jnp.abs(x)))
    """
    assert _codes(ok) == []


def test_j014_nested_helper_names_do_not_leak():
    """A nested helper's local fresh-absmax name must not mark the
    ENCLOSING function's same-named frozen constant as fresh (review:
    ast.walk descended into nested defs); the helper's own call still
    flags in its own scope."""
    ok = """
    import jax.numpy as jnp
    from apex_tpu import quant

    def outer(state, batch, calib):
        def helper(x):
            s = jnp.abs(x).max() / 127.0
            return s
        s = calib.scales["mlp_up"]       # frozen — shares the name only
        return quant.quantized_matmul(batch["x"], state["w"], x_scale=s)
    """
    assert _codes(ok) == []
    bad = """
    import jax.numpy as jnp
    from apex_tpu import quant

    def outer(state, batch, calib):
        def helper(x):
            s = jnp.abs(x).max() / 127.0
            return quant.quantized_matmul(x, state["w"], x_scale=s)
        frozen = calib.scales["mlp_up"]
        a = quant.quantized_matmul(batch["x"], state["w"], x_scale=frozen)
        return a + helper(batch["x"])
    """
    assert _codes(bad) == ["J014"]       # the helper's OWN site, once


def test_j014_resolution_is_binding_order_aware():
    """The LAST assignment before the call site decides freshness
    (review): a name rebound from a fresh absmax to a frozen constant
    resolves frozen — and the reverse order still flags."""
    ok = """
    import jax.numpy as jnp
    from apex_tpu import quant

    def step_fn(state, batch, calib):
        x = batch["x"]
        s = jnp.max(jnp.abs(x)) / 127.0       # used for clipping only
        clipped = jnp.clip(x, -s * 127.0, s * 127.0)
        s = calib.scales["mlp_up"]            # rebound to the constant
        return quant.quantized_matmul(clipped, state["w"], x_scale=s)
    """
    assert _codes(ok) == []
    bad = """
    import jax.numpy as jnp
    from apex_tpu import quant

    def step_fn(state, batch, calib):
        x = batch["x"]
        s = calib.scales["mlp_up"]
        s = jnp.max(jnp.abs(x)) / 127.0       # rebound to FRESH
        return quant.quantized_matmul(x, state["w"], x_scale=s)
    """
    assert _codes(bad) == ["J014"]


def test_j014_is_advisory_and_waivable():
    from tools.jaxlint.linter import Finding

    assert Finding("p", 1, 0, "J014", "m").advisory
    waived = """
    import jax.numpy as jnp
    from apex_tpu import quant

    def step_fn(state, batch):
        x = batch["x"]
        return quant.quantized_matmul(x, state["w"], x_scale=jnp.max(jnp.abs(x)) / 127.0)  # jaxlint: disable=J014 -- sanctioned dynamic-range probe for the calibration sweep
    """
    assert _codes(waived) == []


# -- J015: literal block-size overrides at kernel call sites (ISSUE 14) -------

def test_j015_flags_literal_block_overrides():
    bad = """
    from apex_tpu.ops.flash_attention import flash_attention

    def step_fn(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=512,
                               block_k=512)
    """
    assert _codes(bad) == ["J015"]


def test_j015_flags_every_tuned_kernel_kwarg():
    bad = """
    from apex_tpu import normalization, quant
    from apex_tpu.normalization.fused_bn_act import bn_relu_residual

    def step_fn(x, w, mean, invstd, calib):
        a = normalization.fused_layer_norm(x, (768,), row_block=64)
        b = bn_relu_residual(x, mean, invstd, row_block=32)
        c = quant.quantized_matmul(x, w, x_scale=calib.s, block_m=128,
                                   block_n=256)
        return a, b, c
    """
    findings = lint_source(textwrap.dedent(bad), "apex_tpu/fixture.py")
    # one finding per call site (dedup is line-scoped, like waivers —
    # block_m/block_n on one call collapse into a single report)
    assert [f.rule for f in findings] == ["J015"] * 3


def test_j015_variables_and_tuned_dispatch_pass():
    ok = """
    from apex_tpu.ops.flash_attention import flash_attention

    def sweep(q, k, v, blk, cfg):
        # a measured variable / config-derived block is the sanctioned
        # escape hatch; defaults dispatch through the tune cache
        a = flash_attention(q, k, v, causal=True, block_q=blk,
                            block_k=cfg["block_k"])
        b = flash_attention(q, k, v, causal=True)
        return a, b
    """
    assert _codes(ok) == []


def test_j015_only_fires_on_tunable_kernels():
    ok = """
    def step_fn(q, k, v):
        # block-ish kwargs on arbitrary functions are not findings
        return my_custom_op(q, k, v, block_q=512, row_block=64)
    """
    assert _codes(ok) == []


def test_j015_is_advisory_and_waivable():
    from tools.jaxlint.linter import Finding

    assert Finding("p", 1, 0, "J015", "m").advisory
    waived = """
    from apex_tpu.ops.flash_attention import flash_attention

    def reference_probe(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=1024, block_k=1024)  # jaxlint: disable=J015 -- documented reference path: pins the r4 sweep winner as the A/B baseline
    """
    assert _codes(waived) == []


# -- J016: NCHW convolution layouts (ISSUE 18) --------------------------------

def test_j016_flags_missing_dimension_numbers():
    bad = """
    import jax

    def model(x, w):
        # lax's DEFAULT dimension_numbers IS ('NCHW','OIHW','NCHW')
        return jax.lax.conv_general_dilated(x, w, (1, 1), "SAME")
    """
    assert _codes(bad) == ["J016"]


def test_j016_flags_nchw_literal_and_lax_conv():
    bad = """
    import jax
    from jax import lax

    def model(x, w):
        a = lax.conv_general_dilated(
            x, w, (1, 1), "SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        b = jax.lax.conv(x, w, (1, 1), "SAME")
        c = lax.conv_with_general_padding(x, w, (1, 1), [(0, 0), (0, 0)],
                                          None, None)
        return a, b, c
    """
    findings = lint_source(textwrap.dedent(bad), "apex_tpu/fixture.py")
    assert [f.rule for f in findings] == ["J016"] * 3


def test_j016_nhwc_and_non_lax_conv_pass():
    ok = """
    import jax
    from jax import lax

    def model(self, x, w, dn):
        # explicit NHWC is the sanctioned spelling
        a = lax.conv_general_dilated(
            x, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # a variable / ConvDimensionNumbers spec is not inspected
        b = jax.lax.conv_general_dilated(x, w, (1, 1), "SAME",
                                         dimension_numbers=dn)
        # the bare leaf 'conv' (module factories, self.conv) never fires
        c = self.conv(x)
        return a, b, c
    """
    assert _codes(ok) == []


def test_j016_is_advisory_and_waivable():
    from tools.jaxlint.linter import Finding

    assert Finding("p", 1, 0, "J016", "m").advisory
    waived = """
    import jax

    def nchw_ab_probe(x, w):
        return jax.lax.conv(x, w, (1, 1), "SAME")  # jaxlint: disable=J016 -- deliberate NCHW side of the layout A/B benchmark
    """
    assert _codes(waived) == []
