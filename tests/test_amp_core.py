"""amp core tests: Properties state machine, policy casting, loss scaler,
checkpoint round-trip.  Mirrors reference ``tests/L0/run_amp`` semantics
(test_basic_casts, test_checkpointing state parts).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu
from apex_tpu import amp
from apex_tpu.amp import LossScaler
from apex_tpu.amp.properties import AmpOptionError, opt_levels


# -- Properties / opt levels --------------------------------------------------

def test_opt_level_presets():
    o2 = opt_levels["O2"]()
    assert o2.cast_model_type == jnp.bfloat16
    assert o2.master_weights is True
    assert o2.keep_batchnorm_fp32 is True
    assert not o2.patch_functions
    o1 = opt_levels["O1"]()
    assert o1.patch_functions
    assert o1.cast_model_type is None
    o0 = opt_levels["O0"]()
    assert o0.cast_model_type == jnp.float32


def test_properties_rejects_unknown_option():
    p = opt_levels["O2"]()
    with pytest.raises(AmpOptionError):
        p.bogus_option = 3


def test_properties_rejects_inconsistent_combos():
    p = opt_levels["O1"]()
    with pytest.raises(AmpOptionError):
        p.cast_model_type = jnp.bfloat16  # O1 + whole-model cast
    p2 = opt_levels["O2"]()
    with pytest.raises(AmpOptionError):
        p2.patch_functions = True  # O2 + patching
    with pytest.raises(AmpOptionError):
        p2.keep_batchnorm_fp32 = "maybe"
    with pytest.raises(AmpOptionError):
        p2.loss_scale = -1.0


def test_initialize_rejects_bad_opt_level():
    # O4 became a real level in ISSUE 13; O5 is the next unknown one
    with pytest.raises(AmpOptionError):
        amp.initialize(opt_level="O5")
    with pytest.raises(AmpOptionError):
        amp.initialize(opt_level="02")  # zero-two, the classic typo


# -- policy casting -----------------------------------------------------------

def _params():
    return {
        "conv1": {"kernel": jnp.ones((3, 3, 4, 8), jnp.float32)},
        "bn1": {"scale": jnp.ones((8,), jnp.float32),
                "bias": jnp.zeros((8,), jnp.float32)},
        "dense": {"kernel": jnp.ones((8, 2), jnp.float32),
                  "bias": jnp.zeros((2,), jnp.float32)},
    }


def test_convert_params_keep_bn_fp32():
    cast = amp.convert_params(_params(), jnp.bfloat16, keep_norm_fp32=True)
    assert cast["conv1"]["kernel"].dtype == jnp.bfloat16
    assert cast["dense"]["kernel"].dtype == jnp.bfloat16
    assert cast["bn1"]["scale"].dtype == jnp.float32
    assert cast["bn1"]["bias"].dtype == jnp.float32


def test_convert_params_no_keep():
    cast = amp.convert_params(_params(), jnp.bfloat16, keep_norm_fp32=False)
    assert cast["bn1"]["scale"].dtype == jnp.bfloat16


def test_to_type_skips_integers():
    tree = {"x": jnp.ones((2,), jnp.float32), "idx": jnp.arange(3)}
    out = amp.to_type(jnp.bfloat16, tree)
    assert out["x"].dtype == jnp.bfloat16
    assert out["idx"].dtype == jnp.int32


def test_wrap_forward_casts_inputs_and_outputs():
    seen = {}

    def apply_fn(x):
        seen["dtype"] = x.dtype
        return x * 2

    f = amp.wrap_forward(apply_fn, cast_input_type=jnp.bfloat16)
    out = f(jnp.ones((4,), jnp.float32))
    assert seen["dtype"] == jnp.bfloat16
    assert out.dtype == jnp.float32


# -- loss scaler --------------------------------------------------------------

def test_static_scaler_noop():
    s = LossScaler(1.0)
    assert s.scale_loss(jnp.float32(3.0)) == 3.0
    grads, _ = s.unscale([jnp.ones((4,))])
    np.testing.assert_allclose(np.asarray(grads[0]), 1.0)


def test_static_scaler_scales():
    s = LossScaler(128.0)
    assert float(s.scale_loss(jnp.float32(2.0))) == 256.0
    grads, _ = s.unscale([jnp.full((4,), 128.0)])
    np.testing.assert_allclose(np.asarray(grads[0]), 1.0)


def test_dynamic_scaler_backoff_and_growth():
    s = LossScaler("dynamic", init_scale=2.**4, scale_window=3)
    assert s.loss_scale() == 16.0
    # Overflow -> halve.
    _, _ = s.unscale([jnp.asarray([np.inf], np.float32)])
    skip = s.update_scale_sync()
    assert skip
    assert s.loss_scale() == 8.0
    # 3 clean steps -> double.
    for _ in range(3):
        _, _ = s.unscale([jnp.ones((2,))])
        assert not s.update_scale_sync()
    assert s.loss_scale() == 16.0


def test_dynamic_scaler_respects_bounds():
    s = LossScaler("dynamic", init_scale=4.0, scale_window=1,
                   min_loss_scale=2.0, max_loss_scale=8.0)
    _, _ = s.unscale([jnp.asarray([np.nan], np.float32)])
    s.update_scale_sync()
    assert s.loss_scale() == 2.0
    _, _ = s.unscale([jnp.asarray([np.nan], np.float32)])
    s.update_scale_sync()
    assert s.loss_scale() == 2.0  # clamped at min
    for _ in range(3):
        _, _ = s.unscale([jnp.ones((2,))])
        s.update_scale_sync()
    assert s.loss_scale() == 8.0  # clamped at max


def test_scaler_functional_jit():
    s = LossScaler("dynamic", init_scale=8.0, scale_window=100)

    @jax.jit
    def step(state, grads):
        out, state = s.unscale(grads, state)
        state = s.update_scale(state)
        return out, state

    state = s.init()
    out, state = step(state, [jnp.full((4,), 8.0)])
    np.testing.assert_allclose(np.asarray(out[0]), 1.0)
    assert float(state.loss_scale) == 8.0
    out, state = step(state, [jnp.asarray([np.inf, 1.0, 1.0, 1.0], np.float32)])
    assert float(state.loss_scale) == 4.0


def test_unscale_with_stashed():
    s = LossScaler(4.0)
    out, _ = s.unscale_with_stashed([jnp.full((3,), 8.0)],
                                    [jnp.full((3,), 1.0)])
    np.testing.assert_allclose(np.asarray(out[0]), 3.0)  # 8/4 + 1


# -- amp state_dict round trip ------------------------------------------------

def test_amp_state_dict_roundtrip():
    amp.initialize(opt_level="O2", loss_scale="dynamic", num_losses=2,
                   verbosity=0)
    sd = amp.state_dict()
    assert set(sd) == {"loss_scaler0", "loss_scaler1"}
    assert sd["loss_scaler0"]["loss_scale"] == 2.**16
    # Simulate an overflow on scaler 0, then restore.
    from apex_tpu.amp._amp_state import _amp_state
    _amp_state.loss_scalers[0].unscale([jnp.asarray([np.inf], np.float32)])
    _amp_state.loss_scalers[0].update_scale_sync()
    assert amp.state_dict()["loss_scaler0"]["loss_scale"] == 2.**15
    amp.load_state_dict(sd)
    assert amp.state_dict()["loss_scaler0"]["loss_scale"] == 2.**16


def test_initialize_casts_model_o2():
    params, = amp.initialize([_params()], opt_level="O2", verbosity=0),
    params = params[0]
    assert params["conv1"]["kernel"].dtype == jnp.bfloat16
    assert params["bn1"]["scale"].dtype == jnp.float32


def test_initialize_o0_stays_fp32():
    params = amp.initialize(_params(), opt_level="O0", verbosity=0)
    assert params["conv1"]["kernel"].dtype == jnp.float32


def test_cast_cache_is_identity_checked():
    """Regression: the weight-cast cache is keyed by id(x); ids are reused
    after gc, so a hit must verify the stored source IS the argument —
    otherwise a later array at a recycled address receives a stale cast of
    a different tensor (observed as shape corruption in the DCGAN
    multi-model O1 loop)."""
    from apex_tpu.amp import autocast
    autocast.clear_cast_cache()
    x = jnp.ones((3,), jnp.float32)
    out_x = autocast.cached_cast(jnp.bfloat16, x)
    assert out_x.dtype == jnp.bfloat16
    key = (id(x), "bfloat16")
    assert autocast._cast_cache[key][0] is x   # source pinned

    # Simulate id reuse: plant a stale entry under y's id pointing at x.
    y = jnp.full((5,), 2.0, jnp.float32)
    autocast._cast_cache[(id(y), "bfloat16")] = (x, out_x)
    out_y = autocast.cached_cast(jnp.bfloat16, y)
    assert out_y.shape == (5,)                 # not the stale (3,) cast
    np.testing.assert_allclose(np.asarray(out_y, np.float32), 2.0)
    autocast.clear_cast_cache()


# -- initialize validation surface (reference _initialize.py:60-126) ---------

def test_initialize_rejects_half_params():
    """check_params_fp32 analog: reduced-precision incoming params error."""
    from apex_tpu.optimizers import FusedSGD
    params = {"w": jnp.ones((3,), jnp.bfloat16)}
    opt = FusedSGD(params, lr=0.1)
    with pytest.raises(RuntimeError, match="expected float32"):
        amp.initialize(params, opt, opt_level="O2", verbosity=0)


def test_initialize_allows_half_params_at_o3():
    params = {"w": jnp.ones((3,), jnp.bfloat16)}
    out = amp.initialize(params, opt_level="O3", verbosity=0)
    assert out["w"].dtype == jnp.bfloat16


def test_initialize_rejects_wrapped_optimizer():
    """check_optimizers analog: FP16_Optimizer must not be passed in."""
    from apex_tpu.optimizers import FP16_Optimizer, FusedSGD
    params = {"w": jnp.ones((3,), jnp.float32)}
    wrapped = FP16_Optimizer(FusedSGD(params, lr=0.1))
    with pytest.raises(RuntimeError, match="must be bare"):
        amp.initialize(params, wrapped, opt_level="O2", verbosity=0)


def test_initialize_rejects_ddp_wrapped_model():
    from apex_tpu.parallel import DistributedDataParallel

    class _Apply:
        def __call__(self, params, x):
            return x
    ddp = DistributedDataParallel.__new__(DistributedDataParallel)
    with pytest.raises(RuntimeError, match="AFTER"):
        amp.initialize(ddp, opt_level="O2", verbosity=0)


# -- O1 cast-list breadth + banned functions ---------------------------------

def test_o1_broadened_fp32_list():
    from apex_tpu.amp import autocast
    import jax.nn as jnn
    autocast.init(enabled=True)
    try:
        x = jnp.ones((4,), jnp.bfloat16)
        assert jnn.gelu(x).dtype == jnp.float32
        assert jnn.sigmoid(x).dtype == jnp.float32
        assert jnp.linalg.norm(x).dtype == jnp.float32
        assert jnp.arccos(x * 0).dtype == jnp.float32
    finally:
        autocast.shutdown()


def test_banned_bce_raises_under_fp16_runs_under_bf16():
    from apex_tpu.amp import autocast
    from apex_tpu.ops import losses
    probs = jnp.asarray([0.3, 0.7], jnp.float32)
    targets = jnp.asarray([0.0, 1.0])

    autocast.init(enabled=True, half_dtype=jnp.float16)
    try:
        with pytest.raises(NotImplementedError, match="float range"):
            losses.binary_cross_entropy(probs, targets)
    finally:
        autocast.shutdown()

    autocast.init(enabled=True)   # bf16 default: runs in fp32 instead
    try:
        out = losses.binary_cross_entropy(probs, targets)
        assert out.dtype == jnp.float32
        ref = -np.mean([np.log(0.7), np.log(0.7)])
        np.testing.assert_allclose(float(out), ref, rtol=1e-5)
    finally:
        autocast.shutdown()


def test_initialize_disabled_restores_patches():
    """enabled=False tears the autocast patches down (weak-#7 wiring)."""
    from apex_tpu.amp import autocast
    import jax.numpy as jnp_mod
    autocast.init(enabled=True)
    assert hasattr(jnp_mod.matmul, "__amp_original__")
    amp.initialize(enabled=False, verbosity=0)
    assert not hasattr(jnp_mod.matmul, "__amp_original__")
    assert not autocast._patched


def test_initialize_disabled_passes_lists_through():
    """enabled=False must return list inputs untouched — not collapse them
    to their first element (reference _initialize.py:42-56)."""
    from apex_tpu import amp, optimizers

    m1 = {"w": jnp.ones((2, 2))}
    m2 = {"w": jnp.zeros((3,))}
    o1 = optimizers.FusedSGD(m1, lr=0.1)
    o2 = optimizers.FusedSGD(m2, lr=0.1)
    models, opts = amp.initialize([m1, m2], [o1, o2], enabled=False,
                                  verbosity=0)
    assert isinstance(models, list) and len(models) == 2
    assert models[0] is m1 and models[1] is m2
    assert isinstance(opts, list) and opts == [o1, o2]

    # Single objects also pass through unchanged.
    m, o = amp.initialize(m1, o1, enabled=False, verbosity=0)
    assert m is m1 and o is o1


def test_grouped_amp_wire_rejects_lookalike_model_list():
    """A model pytree that is a top-level 2-list must not be mis-wired as a
    per-group cast list for a 2-group optimizer with different structure."""
    from apex_tpu import amp, optimizers

    groups = [{"params": {"a": jnp.ones((2,))}, "lr": 0.1},
              {"params": {"b": jnp.ones((3,)), "c": jnp.ones((4,))},
               "lr": 0.01}]
    opt = optimizers.FusedSGD(groups, lr=0.1)
    # model is a list of 2 pytrees whose structures do NOT match the groups
    lookalike = [{"x": jnp.ones((5,))}, {"y": jnp.ones((6,))}]
    _, opt = amp.initialize(lookalike, opt, opt_level="O2", verbosity=0)
    # groups keep their own (cast) structure, not the lookalike's
    assert set(opt.param_groups[0]["params"].keys()) == {"a"}
    assert set(opt.param_groups[1]["params"].keys()) == {"b", "c"}
