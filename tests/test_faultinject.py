"""Kill-and-resume bit-parity (ISSUE 9 acceptance): a training
subprocess killed with SIGTERM at a window boundary (graceful drain) or
SIGKILL mid-run (hard crash), then resumed from its newest valid
checkpoint, must finish with parameters BITWISE identical to an
uninterrupted run — scaler state, step counter, and batch stream all
round-trip.  The kill step is drawn from a seeded RNG (``randomized
steps``, reproducible in CI)."""

import os
import signal

import numpy as np
import pytest

from tests import faultinject

STEPS = 12
SPC = 2
SAVE_EVERY = 2
_KILL_RNG = np.random.RandomState(20260804)


def _final_arrays(path):
    assert os.path.exists(path), f"child never wrote {path}"
    with np.load(path, allow_pickle=False) as data:
        return {k: data[k] for k in data.files}


@pytest.fixture(scope="module")
def uninterrupted(tmp_path_factory):
    """One continuous run to STEPS — the parity oracle both kill modes
    compare against (module-scoped: ~one subprocess, reused)."""
    root = tmp_path_factory.mktemp("uninterrupted")
    out = str(root / "final.npz")
    rc, log = faultinject.run_child(
        dir=str(root / "ck"), out=out, steps=STEPS, spc=SPC,
        save_every=SAVE_EVERY)
    assert rc == 0 and f"FINAL {STEPS}" in log, log
    return _final_arrays(out)


def _assert_parity(oracle, resumed_out, log):
    got = _final_arrays(resumed_out)
    assert sorted(got) == sorted(oracle)
    for k in oracle:
        np.testing.assert_array_equal(
            got[k], oracle[k],
            err_msg=f"leaf {k!r} diverged after kill-and-resume\n{log}")


def test_sigterm_drain_then_resume_is_bit_identical(tmp_path,
                                                    uninterrupted):
    """SIGTERM → the drain finishes the in-flight window, writes a
    final checkpoint, and exits 0; the resumed run must land exactly on
    the uninterrupted trajectory."""
    ck = str(tmp_path / "ck")
    # a window boundary strictly inside the run (randomized, seeded)
    kill_at = SPC * int(_KILL_RNG.randint(1, STEPS // SPC - 1))
    rc, log = faultinject.run_and_kill(
        signal.SIGTERM, kill_at, dir=ck, steps=STEPS, spc=SPC,
        save_every=SAVE_EVERY, step_delay=0.05)
    assert rc == 0, f"drain exit should be clean:\n{log}"
    assert "DRAINED" in log, log
    out = str(tmp_path / "final.npz")
    rc2, log2 = faultinject.run_child(
        dir=ck, out=out, steps=STEPS, spc=SPC, save_every=SAVE_EVERY,
        resume=True)
    assert rc2 == 0 and "RESUMED" in log2 and f"FINAL {STEPS}" in log2, log2
    _assert_parity(uninterrupted, out, log + log2)


def test_sigkill_midrun_then_resume_is_bit_identical(tmp_path,
                                                     uninterrupted):
    """SIGKILL cannot be caught: the child dies wherever it is —
    possibly mid-checkpoint-write, leaving ``.tmp`` debris — and the
    resume must fall back to the newest VALID checkpoint and still
    reproduce the uninterrupted trajectory bitwise."""
    ck = str(tmp_path / "ck")
    # at least two save cadences in: the async write of an EARLIER step
    # has provably landed, so the kill can at worst corrupt the newest
    # in-flight write — the fallback path under test (killing before
    # any save just exercises a fresh start, which the drain test's
    # window already covers)
    kill_at = SPC * int(_KILL_RNG.randint(2, STEPS // SPC - 1))
    rc, log = faultinject.run_and_kill(
        signal.SIGKILL, kill_at, dir=ck, steps=STEPS, spc=SPC,
        save_every=SAVE_EVERY, step_delay=0.05)
    assert rc != 0, f"SIGKILL must not exit cleanly:\n{log}"
    out = str(tmp_path / "final.npz")
    rc2, log2 = faultinject.run_child(
        dir=ck, out=out, steps=STEPS, spc=SPC, save_every=SAVE_EVERY,
        resume=True)
    assert rc2 == 0 and "RESUMED" in log2 and f"FINAL {STEPS}" in log2, log2
    _assert_parity(uninterrupted, out, log + log2)


def test_sync_write_mode_matches_async(tmp_path, uninterrupted):
    """The synchronous writer (the bench's stall baseline) must be a
    pure performance variant: same files, same resumed trajectory."""
    ck = str(tmp_path / "ck")
    rc, log = faultinject.run_and_kill(
        signal.SIGTERM, SPC * 2, dir=ck, steps=STEPS, spc=SPC,
        save_every=SAVE_EVERY, step_delay=0.05, sync_writes=True)
    assert rc == 0 and "DRAINED" in log, log
    out = str(tmp_path / "final.npz")
    rc2, log2 = faultinject.run_child(
        dir=ck, out=out, steps=STEPS, spc=SPC, save_every=SAVE_EVERY,
        resume=True, sync_writes=True)
    assert rc2 == 0 and f"FINAL {STEPS}" in log2, log2
    _assert_parity(uninterrupted, out, log + log2)
