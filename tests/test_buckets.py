"""Flat-bucket parameter engine (ISSUE 4): pack/unpack round trips,
bucketed overflow flags, exact leafwise-vs-bucketed optimizer parity
(including loss-scale skip steps), trace-count pins, and the bucketed
distributed paths (DDP reduce, ZeRO-1) on the virtual CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import amp, training
from apex_tpu.multi_tensor import (BucketStore, Packed, multi_tensor_axpby,
                                   multi_tensor_l2norm, multi_tensor_scale,
                                   tree_finite)
from apex_tpu.optimizers import FusedAdam, FusedLAMB, functional as F
from apex_tpu.prof import assert_trace_count


def _rand_tree(seed, shapes=((7,), (3, 5), (64,), (1,)), dtype=np.float32):
    rng = np.random.RandomState(seed)
    return {f"p{i}": jnp.asarray(rng.randn(*s).astype(dtype))
            for i, s in enumerate(shapes)}


# -- pack / unpack round trips ------------------------------------------------

MIXED_TREE = {
    "a": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
    "nest": {
        "bf": jnp.arange(7, dtype=jnp.float32).astype(jnp.bfloat16),
        "scalar": jnp.float32(3.5),
        "ints": jnp.arange(5, dtype=jnp.int32),
        "flag": jnp.asarray(True),
    },
    "list": [jnp.ones((2, 2), jnp.float32), jnp.zeros((3,), jnp.bfloat16)],
}


def test_roundtrip_preserves_dtypes_shapes_values_exactly():
    store = BucketStore(MIXED_TREE)
    packed = store.pack(MIXED_TREE)
    back = store.unpack(packed)
    for orig, got in zip(jax.tree_util.tree_leaves(MIXED_TREE),
                         jax.tree_util.tree_leaves(back)):
        assert jnp.shape(orig) == jnp.shape(got)
        assert jnp.asarray(orig).dtype == jnp.asarray(got).dtype
        np.testing.assert_array_equal(
            np.asarray(orig, np.float32), np.asarray(got, np.float32))


def test_buckets_are_keyed_per_dtype():
    store = BucketStore(MIXED_TREE)
    assert store.n_buckets == 2            # fp32 + bf16
    assert {d.name for d in store.dtypes} == {"float32", "bfloat16"}
    # non-float leaves (ints, bool) pass through in .rest
    packed = store.pack(MIXED_TREE)
    assert len(packed.rest) == 2


def test_scalar_and_empty_trees():
    s = BucketStore({"x": jnp.float32(2.0)})
    p = s.pack({"x": jnp.float32(2.0)})
    assert p.data[0].shape == (1,)
    assert float(s.unpack(p)["x"]) == 2.0

    empty = BucketStore({})
    assert empty.n_buckets == 0
    assert bool(tree_finite({}, store=empty))

    nofloat = BucketStore({"i": jnp.arange(3)})
    packed = nofloat.pack({"i": jnp.arange(3)})
    assert packed.data == () and len(packed.rest) == 1
    np.testing.assert_array_equal(
        np.asarray(nofloat.unpack(packed)["i"]), np.arange(3))


def test_pack_rejects_structure_and_dtype_mismatch():
    store = BucketStore({"a": jnp.ones((3,), jnp.float32)})
    with pytest.raises(ValueError, match="structure"):
        store.pack({"b": jnp.ones((3,), jnp.float32)})
    with pytest.raises(ValueError, match="dtype"):
        store.pack({"a": jnp.ones((3,), jnp.bfloat16)})
    # explicit casts are fine
    out = store.pack({"a": jnp.ones((3,), jnp.bfloat16)}, cast=True)
    assert out.data[0].dtype == jnp.float32
    out = store.pack({"a": jnp.ones((3,), jnp.float32)}, dtype=jnp.bfloat16)
    assert out.data[0].dtype == jnp.bfloat16


def test_view_returns_each_leaf():
    tree = _rand_tree(0)
    store = BucketStore(tree)
    packed = store.pack(tree)
    for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
        np.testing.assert_array_equal(np.asarray(store.view(packed, i)),
                                      np.asarray(leaf))


def test_pack_unpack_jit_safe_and_donation_friendly():
    tree = _rand_tree(1)
    store = BucketStore(tree)

    @jax.jit
    def roundtrip(t):
        return store.unpack(store.pack(t))

    back = roundtrip(tree)
    for orig, got in zip(jax.tree_util.tree_leaves(tree),
                         jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(orig), np.asarray(got))

    # a Packed is a pytree: donation across a jit boundary works
    packed = store.pack(tree)
    doubled = jax.jit(
        lambda p: Packed(tuple(b * 2 for b in p.data), p.rest),
        donate_argnums=(0,))(packed)
    np.testing.assert_allclose(np.asarray(doubled.data[0]),
                               2 * np.asarray(store.pack(tree).data[0]))


def test_decay_mask_splits_buckets():
    tree = {"w": jnp.ones((4,)), "b": jnp.ones((2,))}
    mask = {"w": True, "b": False}
    store = BucketStore(tree, decay_mask=mask)
    assert store.n_buckets == 2
    assert set(store.decay_flags) == {True, False}
    back = store.unpack(store.pack(tree))
    np.testing.assert_array_equal(np.asarray(back["b"]), np.ones(2))


def test_per_leaf_segment_norms_match_leafwise():
    tree = _rand_tree(2)
    store = BucketStore(tree)
    packed = store.pack(tree)
    seg = store.per_leaf_sq_sums(packed.data)
    flat = [float(x) for s in seg for x in np.asarray(s)]
    expect = [float(jnp.sum(jnp.square(l)))
              for l in jax.tree_util.tree_leaves(tree)]
    np.testing.assert_allclose(sorted(flat), sorted(expect), rtol=1e-5)


# -- overflow flags through buckets -------------------------------------------

@pytest.mark.parametrize("bad", [np.inf, -np.inf, np.nan])
@pytest.mark.parametrize("where", ["first", "last"])
def test_nan_inf_propagate_through_bucketed_flags(bad, where):
    x = np.ones((37,), np.float32)
    x[0 if where == "first" else -1] = bad
    tree = {"ok": jnp.ones((5,), jnp.float32), "bad": jnp.asarray(x),
            "bf": jnp.ones((3,), jnp.bfloat16)}
    store = BucketStore(tree)
    assert not bool(tree_finite(tree, store=store))
    _, overflow = multi_tensor_scale(tree, 1.0, store=store)
    assert bool(overflow)
    _, overflow = multi_tensor_axpby(
        tree, jax.tree_util.tree_map(jnp.zeros_like, tree), 1.0, 1.0,
        store=store)
    assert bool(overflow)


def test_bucketed_ops_match_leafwise():
    tree = _rand_tree(3)
    store = BucketStore(tree)
    out_l, ov_l = multi_tensor_scale(tree, 0.25)
    out_b, ov_b = multi_tensor_scale(tree, 0.25, store=store)
    for a, b in zip(jax.tree_util.tree_leaves(out_l),
                    jax.tree_util.tree_leaves(out_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert bool(ov_l) == bool(ov_b)

    gl, pl = multi_tensor_l2norm(tree, per_tensor=True)
    gb, pb = multi_tensor_l2norm(tree, per_tensor=True, store=store)
    np.testing.assert_allclose(float(gl), float(gb), rtol=1e-6)
    for a, b in zip(pl, pb):
        np.testing.assert_allclose(float(a), float(b), rtol=1e-5)


def test_packed_input_stays_packed():
    tree = _rand_tree(4)
    store = BucketStore(tree)
    packed = store.pack(tree)
    out, overflow = multi_tensor_scale(packed, 2.0, store=store)
    assert isinstance(out, Packed)
    assert not bool(overflow)
    np.testing.assert_allclose(np.asarray(out.data[0]),
                               2 * np.asarray(packed.data[0]))


# -- optimizer parity: leafwise vs bucketed -----------------------------------

def test_adam_bitwise_parity_100_steps_with_skips_fp32():
    """fp32 bucketed Adam must be BIT-identical to leafwise over 100
    steps, including loss-scale skip steps (apply_mask=False) and a
    non-unit grad_scale — the elementwise math runs in the same order
    per element."""
    params = _rand_tree(5)
    store = BucketStore(params)
    st_l, st_b = F.adam_init(params), F.adam_init(params, store=store)
    p_l = p_b = params
    rng = np.random.RandomState(6)
    for i in range(100):
        g = {k: jnp.asarray(rng.randn(*v.shape).astype(np.float32))
             for k, v in params.items()}
        mask = jnp.asarray(i % 9 != 0)        # periodic skip steps
        kw = dict(lr=1e-2, weight_decay=0.01, grad_scale=jnp.float32(4.0),
                  apply_mask=mask)
        p_l, st_l = F.adam_update(g, st_l, p_l, **kw)
        p_b, st_b = F.adam_update(g, st_b, p_b, store=store, **kw)
    for k in params:
        np.testing.assert_array_equal(np.asarray(p_l[k]), np.asarray(p_b[k]))
    assert int(st_l.step) == int(st_b.step)
    # moments identical too (unpacked view)
    m_b = store.unpack(st_b.exp_avg._replace(rest=()))
    for k in params:
        np.testing.assert_array_equal(np.asarray(st_l.exp_avg[k]),
                                      np.asarray(m_b[k]))


def test_lamb_parity_100_steps():
    params = _rand_tree(7)
    store = BucketStore(params)
    st_l, st_b = F.lamb_init(params), F.lamb_init(params, store=store)
    p_l = p_b = params
    rng = np.random.RandomState(8)
    for i in range(100):
        g = {k: jnp.asarray(rng.randn(*v.shape).astype(np.float32))
             for k, v in params.items()}
        mask = jnp.asarray(i % 11 != 0)
        kw = dict(lr=1e-2, weight_decay=0.01, apply_mask=mask)
        p_l, st_l = F.lamb_update(g, st_l, p_l, **kw)
        p_b, st_b = F.lamb_update(g, st_b, p_b, store=store, **kw)
    for k in params:
        np.testing.assert_allclose(np.asarray(p_l[k]), np.asarray(p_b[k]),
                                   rtol=5e-5, atol=5e-6, err_msg=k)


def test_sgd_and_novograd_parity():
    params = _rand_tree(9)
    store = BucketStore(params)
    rng = np.random.RandomState(10)
    grads = [{k: jnp.asarray(rng.randn(*v.shape).astype(np.float32))
              for k, v in params.items()} for _ in range(10)]

    st_l = F.sgd_init(params, 0.9)
    st_b = F.sgd_init(params, 0.9, store=store)
    p_l = p_b = params
    for g in grads:
        kw = dict(lr=0.1, momentum=0.9, nesterov=True, weight_decay=1e-2)
        p_l, st_l = F.sgd_update(g, st_l, p_l, **kw)
        p_b, st_b = F.sgd_update(g, st_b, p_b, store=store, **kw)
    for k in params:
        np.testing.assert_array_equal(np.asarray(p_l[k]), np.asarray(p_b[k]))

    st_l = F.novograd_init(params)
    st_b = F.novograd_init(params, store=store)
    p_l = p_b = params
    for g in grads:
        kw = dict(lr=1e-2, weight_decay=0.01)
        p_l, st_l = F.novograd_update(g, st_l, p_l, **kw)
        p_b, st_b = F.novograd_update(g, st_b, p_b, store=store, **kw)
    for k in params:
        np.testing.assert_allclose(np.asarray(p_l[k]), np.asarray(p_b[k]),
                                   rtol=5e-5, atol=5e-6, err_msg=k)


def test_bucketed_adam_bf16_params_allclose():
    params = {k: jnp.asarray(v, jnp.bfloat16)
              for k, v in _rand_tree(11).items()}
    store = BucketStore(params)
    st_l, st_b = F.adam_init(params), F.adam_init(params, store=store)
    p_l = p_b = params
    rng = np.random.RandomState(12)
    for _ in range(10):
        g = {k: jnp.asarray(rng.randn(*v.shape), jnp.bfloat16)
             for k, v in params.items()}
        p_l, st_l = F.adam_update(g, st_l, p_l, lr=1e-2)
        p_b, st_b = F.adam_update(g, st_b, p_b, lr=1e-2, store=store)
    for k in params:
        np.testing.assert_allclose(np.asarray(p_l[k], np.float32),
                                   np.asarray(p_b[k], np.float32),
                                   rtol=2e-2, atol=1e-3)


# -- FusedOptimizer bucketed (imperative surface) -----------------------------

def test_fused_adam_bucketed_matches_leafwise():
    params = _rand_tree(13)
    o_l = FusedAdam(params, lr=1e-2, weight_decay=0.1)
    o_b = FusedAdam(params, lr=1e-2, weight_decay=0.1, bucketed=True)
    rng = np.random.RandomState(14)
    for _ in range(5):
        g = {k: jnp.asarray(rng.randn(*v.shape).astype(np.float32))
             for k, v in params.items()}
        o_l.step(grads=g)
        o_b.step(grads=g)
    for k in params:
        np.testing.assert_array_equal(np.asarray(o_l.params[k]),
                                      np.asarray(o_b.params[k]))


def test_fused_lamb_bucketed_matches_leafwise():
    params = _rand_tree(15)
    o_l = FusedLAMB(params, lr=1e-2)
    o_b = FusedLAMB(params, lr=1e-2, bucketed=True)
    rng = np.random.RandomState(16)
    for _ in range(5):
        g = {k: jnp.asarray(rng.randn(*v.shape).astype(np.float32))
             for k, v in params.items()}
        o_l.step(grads=g)
        o_b.step(grads=g)
    for k in params:
        np.testing.assert_allclose(np.asarray(o_l.params[k]),
                                   np.asarray(o_b.params[k]),
                                   rtol=5e-5, atol=5e-6)


def test_fused_adam_bucketed_amp_o2_with_overflow_skip():
    """The full amp handshake on buckets: bf16 model copy, fp32 masters
    AS buckets, packed master grads, dynamic scaler halving on an
    injected inf, step-skip parity with the leafwise path."""
    def run(bucketed):
        params = _rand_tree(17)
        opt = FusedAdam(params, lr=1e-2, weight_decay=0.01,
                        bucketed=bucketed)
        params, opt = amp.initialize(params, opt, opt_level="O2",
                                     verbosity=0, loss_scale="dynamic")
        rng = np.random.RandomState(18)
        for i in range(6):
            g = {k: jnp.asarray(rng.randn(*np.shape(v)).astype(np.float32),
                                jnp.bfloat16)
                 for k, v in params.items()}
            if i == 2:
                g["p0"] = g["p0"].at[0].set(jnp.inf)
            with amp.scale_loss(jnp.float32(1.0), opt):
                opt.backward(g)
            opt.step()
        return (jax.device_get(opt.master_params),
                float(opt.loss_scaler.loss_scale()))

    m_l, s_l = run(False)
    m_b, s_b = run(True)
    assert s_l == s_b                       # same skip/halve trajectory
    for k in m_l:
        np.testing.assert_allclose(m_l[k], m_b[k], rtol=1e-5, atol=1e-6)


def test_fused_adam_bucketed_grad_accumulation():
    """Review regression: two backward passes between steps — the second
    stashes a bucket-resident (Packed) master grad, and the fused axpby
    accumulation must run on buckets (mixing a Packed stash with a
    pytree of new grads used to crash in tree_map)."""
    def run(bucketed, split):
        params = _rand_tree(28)
        opt = FusedAdam(params, lr=1e-2, bucketed=bucketed)
        params, opt = amp.initialize(params, opt, opt_level="O2",
                                     verbosity=0, loss_scale=4.0)
        rng = np.random.RandomState(29)
        for _ in range(3):
            g = {k: jnp.asarray((rng.randn(*np.shape(v)) * 4.0)
                                .astype(np.float32), jnp.bfloat16)
                 for k, v in params.items()}
            if split:
                half = {k: (v / 2).astype(v.dtype) for k, v in g.items()}
                for _ in range(2):          # two backwards, one step
                    with amp.scale_loss(jnp.float32(1.0), opt):
                        opt.backward(half)
            else:
                with amp.scale_loss(jnp.float32(1.0), opt):
                    opt.backward(g)
            opt.step()
        return jax.device_get(opt.master_params)

    m_one = run(True, split=False)
    m_acc = run(True, split=True)            # used to raise ValueError
    m_ref = run(False, split=True)
    for k in m_one:
        np.testing.assert_allclose(m_acc[k], m_ref[k], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(m_acc[k], m_one[k], rtol=1e-4, atol=1e-5)


def test_fused_adam_bucketed_o3_mixed_dtype_store_rebuild():
    """Review regression: O3 (no masters) casts the params AFTER the
    state was built — the Packed state must be re-segmented on the cast
    model params (bf16 + keep-norm-fp32 leaves -> two buckets) or the
    first step broadcasts mismatched bucket shapes."""
    params = {"dense": {"kernel": jnp.ones((4, 5)), "bias": jnp.ones((5,))},
              "bn": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))}}
    opt = FusedAdam(jax.tree_util.tree_map(jnp.asarray, params),
                    lr=1e-2, bucketed=True)
    model, opt = amp.initialize(params, opt, opt_level="O3",
                                keep_batchnorm_fp32=True, verbosity=0,
                                loss_scale=1.0)
    assert model["dense"]["kernel"].dtype == jnp.bfloat16
    assert model["bn"]["scale"].dtype == jnp.float32
    g = jax.tree_util.tree_map(
        lambda p: jnp.full(jnp.shape(p), 0.1, p.dtype), opt.params)
    opt.step(grads=g)                        # used to fail to broadcast
    assert not np.allclose(
        np.asarray(opt.params["dense"]["kernel"], np.float32), 1.0)


def test_fused_adam_bucketed_state_dict_roundtrip():
    params = _rand_tree(19)
    opt = FusedAdam(params, lr=1e-2, bucketed=True)
    g = {k: jnp.ones_like(v) for k, v in params.items()}
    opt.step(grads=g)
    sd = opt.state_dict()
    opt2 = FusedAdam(jax.tree_util.tree_map(
        jnp.asarray, jax.device_get(opt.params)), lr=1e-2, bucketed=True)
    opt2.load_state_dict(sd)
    opt.step(grads=g)
    opt2.step(grads=g)
    for k in params:
        np.testing.assert_array_equal(np.asarray(opt.params[k]),
                                      np.asarray(opt2.params[k]))


def test_fused_adam_bucketed_param_groups():
    decay = _rand_tree(20, shapes=((4, 3), (5,)))
    no_decay = _rand_tree(21, shapes=((3,),))
    groups = [{"params": decay, "lr": 1e-2, "weight_decay": 0.1},
              {"params": no_decay, "lr": 5e-3, "weight_decay": 0.0}]
    o_b = FusedAdam([dict(g) for g in groups], lr=9.0, bucketed=True)
    o_l = FusedAdam([dict(g) for g in groups], lr=9.0)
    grads = [{k: jnp.full_like(v, 0.1) for k, v in decay.items()},
             {k: jnp.full_like(v, -0.2) for k, v in no_decay.items()}]
    for _ in range(3):
        o_b.step(grads=grads)
        o_l.step(grads=grads)
    for got, want in zip(jax.tree_util.tree_leaves(o_b.params),
                         jax.tree_util.tree_leaves(o_l.params)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# -- functional train step + runtime carry ------------------------------------

def _quadratic_loss(p, batch):
    x, y = batch
    return jnp.mean((x @ p["w"] - y) ** 2)


def test_make_train_step_bucketed_bitwise_and_trace_count():
    rng = np.random.RandomState(22)
    w = {"w": jnp.asarray(rng.randn(5, 3).astype(np.float32))}
    x = jnp.asarray(rng.randn(16, 5), jnp.float32)
    y = jnp.asarray(rng.randn(16, 3), jnp.float32)

    def run(tx):
        init_fn, step_fn = training.make_train_step(
            _quadratic_loss, tx, opt_level="O2", loss_scale="dynamic")
        state = init_fn({k: jnp.asarray(v) for k, v in w.items()})
        step = jax.jit(step_fn)
        # CI satellite: pin the trace count of the bucketed adam step —
        # one compile, zero retraces across steps.
        with assert_trace_count(step, 1):
            for _ in range(5):
                state, m = step(state, (x, y))
        with assert_trace_count(step, 0):
            state, m = step(state, (x, y))
        return jax.device_get(state.params)

    p_l = run(training.adam(1e-2))
    p_b = run(training.adam(1e-2, bucketed=True))
    np.testing.assert_array_equal(p_l["w"], p_b["w"])


def test_bucketed_opt_state_is_a_small_scan_carry():
    """The StepPipeline integration: a bucketed TrainState carries
    O(buckets) moment leaves (here 2) instead of two per param leaf."""
    params = _rand_tree(23, shapes=((4,), (3, 2), (5,), (6,), (2, 2)))
    tx_l, tx_b = training.adam(1e-2), training.adam(1e-2, bucketed=True)
    n_l = len(jax.tree_util.tree_leaves(tx_l.init(params)))
    n_b = len(jax.tree_util.tree_leaves(tx_b.init(params)))
    assert n_l == 2 * len(params) + 1        # two moments per leaf + step
    assert n_b == 3                          # two moment buckets + step


def test_chain_steps_with_bucketed_state():
    """K-step device loop (lax.scan) over a bucketed TrainState."""
    rng = np.random.RandomState(24)
    w = {"w": jnp.asarray(rng.randn(5, 3).astype(np.float32))}
    x = jnp.asarray(rng.randn(4, 8, 5), jnp.float32)
    y = jnp.asarray(rng.randn(4, 8, 3), jnp.float32)
    init_fn, step_fn = training.make_train_step(
        _quadratic_loss, training.adam(1e-2, bucketed=True),
        opt_level="O2")
    state = init_fn(w)
    chained = jax.jit(training.chain_steps(step_fn))
    state, metrics = chained(state, (x, y))
    assert metrics["loss"].shape == (4,)
    assert np.all(np.isfinite(np.asarray(metrics["loss"])))


# -- distributed bucketed paths (virtual CPU mesh) ----------------------------

N = 4


@pytest.fixture
def dp_mesh():
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices("cpu")[:N]), ("data",))


def test_reduce_gradients_bucketed_matches_leafwise(dp_mesh):
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.distributed import reduce_gradients
    shard_map = jax.shard_map

    rng = np.random.RandomState(25)
    grads = {"a": jnp.asarray(rng.randn(N, 3, 4), jnp.float32),
             "b": jnp.asarray(rng.randn(N, 5), jnp.bfloat16)}
    # template = the SHARD-shaped view the mapped function actually sees
    store = BucketStore(
        jax.tree_util.tree_map(lambda g: g[:1], grads))

    def leafwise(g):
        return reduce_gradients(g, "data", allreduce_always_fp32=True)

    def bucketed(g):
        return reduce_gradients(g, "data", allreduce_always_fp32=True,
                                bucket_store=store)

    spec = {"a": P("data"), "b": P("data")}
    out_spec = {"a": P(), "b": P()}
    run_l = jax.jit(shard_map(leafwise, mesh=dp_mesh, in_specs=(spec,),
                              out_specs=out_spec, check_vma=False))
    run_b = jax.jit(shard_map(bucketed, mesh=dp_mesh, in_specs=(spec,),
                              out_specs=out_spec, check_vma=False))
    o_l, o_b = run_l(grads), run_b(grads)
    for k in grads:
        assert o_b[k].dtype == grads[k].dtype           # dtype preserved
        np.testing.assert_allclose(np.asarray(o_l[k], np.float32),
                                   np.asarray(o_b[k], np.float32),
                                   rtol=1e-6, atol=1e-7)


def test_zero1_bucketed_matches_plain_dp(dp_mesh):
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.zero import zero1, zero1_partition_spec
    from apex_tpu.training import TrainState, make_train_step
    shard_map = jax.shard_map

    rng = np.random.RandomState(26)
    params = {"w": jnp.asarray(rng.randn(5, 7) * 0.3, jnp.float32),
              "b": jnp.zeros((7,), jnp.float32)}
    x = jnp.asarray(rng.randn(8 * N, 5), jnp.float32)
    y = jnp.asarray(rng.randn(8 * N, 7) * 0.1, jnp.float32)

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

    def run(tx, reduce_grads, sharded):
        init_fn, step_fn = make_train_step(
            loss_fn, tx, opt_level="O2", axis_name=("data",),
            reduce_grads=reduce_grads)
        state = init_fn({k: jnp.asarray(v) for k, v in params.items()})
        opt_spec = (zero1_partition_spec(state.opt_state, "data")
                    if sharded else P())
        ss = TrainState(params=P(), opt_state=opt_spec, scaler=P(),
                        model_state=P())

        def wrapped(s, b):
            ns, m = step_fn(s, b)
            return ns, jax.tree_util.tree_map(
                lambda v: training._pmean_varying(v, ("data",)), m)

        step = jax.jit(shard_map(
            wrapped, mesh=dp_mesh,
            in_specs=(ss, (P("data"), P("data"))), out_specs=(ss, P())))
        for _ in range(5):
            state, _ = step(state, (x, y))
        return jax.device_get(state.params)

    p_dp = run(training.adam(1e-2), True, False)
    p_z = run(zero1(training.adam(1e-2), "data", num_shards=N,
                    bucketed=True), False, True)
    for k in params:
        np.testing.assert_allclose(p_dp[k], p_z[k], rtol=1e-5, atol=1e-7)


def test_zero1_bucketed_allows_mixed_dtypes(dp_mesh):
    """The per-dtype flat buckets lift the uniform-dtype restriction the
    single-buffer path enforces."""
    from apex_tpu.parallel.zero import zero1

    params = {"w": jnp.zeros((5,), jnp.float32),
              "b": jnp.zeros((3,), jnp.bfloat16)}
    with pytest.raises(ValueError, match="uniform parameter dtype"):
        zero1(training.adam(1e-2), "data", num_shards=N).init(params)
    state = zero1(training.adam(1e-2), "data", num_shards=N,
                  bucketed=True).init(params)
    # one inner state per dtype bucket, flat chunks padded to N
    assert len(state.inner) == 2
    for inner in state.inner:
        assert inner.exp_avg.shape[0] % N == 0


def test_loss_scaler_bucketed_unscale_matches_leafwise():
    from apex_tpu.amp.loss_scaler import LossScaler

    grads = {k: jnp.asarray(v, jnp.bfloat16)
             for k, v in _rand_tree(27).items()}
    store = BucketStore(grads)
    scaler = LossScaler("dynamic")
    out_l, st_l = scaler.unscale(grads, scaler.init())
    out_b, st_b = scaler.unscale(grads, scaler.init(), store=store)
    for k in grads:
        assert out_b[k].dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(out_l[k]),
                                      np.asarray(out_b[k]))
    assert bool(st_l.overflow) == bool(st_b.overflow)

    bad = dict(grads, p0=grads["p0"].at[0].set(jnp.inf))
    _, st = scaler.unscale(bad, scaler.init(), store=store)
    assert bool(st.overflow)


# -- chunked buckets + interleaved collectives (ISSUE 7) ----------------------

def test_chunked_store_roundtrip_and_caps():
    """max_bucket_elems splits (dtype, decay) groups into leaf-order
    chunks: pack/unpack stays the bitwise identity, no chunk exceeds the
    cap unless a single oversized leaf owns it alone."""
    tree = {f"l{i}": jnp.asarray(np.random.RandomState(i).randn(7, 3),
                                 jnp.float32) for i in range(6)}
    tree["big"] = jnp.asarray(np.random.RandomState(9).randn(40, 3),
                              jnp.float32)          # 120 > cap: alone
    cap = 50
    store = BucketStore(tree, max_bucket_elems=cap)
    flat = BucketStore(tree)
    assert flat.n_buckets == 1
    assert store.n_buckets > flat.n_buckets
    for b in store.buckets:
        assert b.size <= cap or len(b.leaf_ids) == 1
    packed = store.pack(tree)
    out = store.unpack(packed)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(out[k]),
                                      np.asarray(tree[k]))
    # leaf order preserved within the dtype group across chunks
    seen = [i for b in store.buckets for i in b.leaf_ids]
    assert seen == sorted(seen)


def test_chunked_store_rejects_bad_cap():
    with pytest.raises(ValueError, match="max_bucket_elems"):
        BucketStore({"a": jnp.zeros((3,))}, max_bucket_elems=0)


def test_reverse_topological_order():
    """Backward finalizes grads deepest-layer-first (highest flat leaf
    ids first), so the issue order is descending min-leaf-id: the first
    bucket psum'd is the one whose grads close earliest."""
    tree = {f"l{i:02d}": jnp.zeros((10,), jnp.float32) for i in range(8)}
    store = BucketStore(tree, max_bucket_elems=25)   # chunks of <=2 leaves
    order = store.reverse_topological_order()
    assert sorted(order) == list(range(store.n_buckets))
    mins = [min(store.buckets[bi].leaf_ids) for bi in order]
    assert mins == sorted(mins, reverse=True)
    # a flat store degenerates to the single-bucket order
    assert BucketStore(tree).reverse_topological_order() == (0,)


def test_reduce_gradients_chunked_matches_leafwise(dp_mesh):
    """The interleaved per-chunk psum path (reverse-topological issue
    order) must be bitwise-identical to the leafwise reduction — the
    overlap is scheduling, never numerics."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.distributed import reduce_gradients
    shard_map = jax.shard_map

    rng = np.random.RandomState(31)
    grads = {f"p{i}": jnp.asarray(rng.randn(N, 6, 5), jnp.float32)
             for i in range(5)}
    store = BucketStore(jax.tree_util.tree_map(lambda g: g[:1], grads),
                        max_bucket_elems=61)        # ~2 leaves per chunk
    assert store.n_buckets >= 3                      # really interleaved

    def leafwise(g):
        return reduce_gradients(g, "data")

    def chunked(g):
        return reduce_gradients(g, "data", bucket_store=store)

    spec = {k: P("data") for k in grads}
    out_spec = {k: P() for k in grads}
    run_l = jax.jit(shard_map(leafwise, mesh=dp_mesh, in_specs=(spec,),
                              out_specs=out_spec, check_vma=False))
    run_c = jax.jit(shard_map(chunked, mesh=dp_mesh, in_specs=(spec,),
                              out_specs=out_spec, check_vma=False))
    o_l, o_c = run_l(grads), run_c(grads)
    for k in grads:
        np.testing.assert_array_equal(np.asarray(o_l[k]),
                                      np.asarray(o_c[k]))
