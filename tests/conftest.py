"""Test configuration: run everything on a simulated 8-device CPU platform.

SURVEY.md §4: the reference can only test distributed behavior on real
multi-GPU nodes; the TPU build does better by unit-testing DP/SyncBN
semantics on a virtual CPU mesh.  The XLA flag must be set before jax
initializes its backends, hence the top-of-conftest placement.

Note: the axon TPU plugin (if present) keeps "tpu" as the default backend
even with JAX_PLATFORMS=cpu, so we pin the default *device* to cpu:0 and
build test meshes from ``jax.devices("cpu")`` (see ``cpu_mesh``).

Two modes:
* default — the full suite on the virtual CPU mesh (the CI gate);
* ``APEX_TPU_TESTS=1`` — a *kernel-validation* mode that leaves the
  default device on the real TPU and runs ONLY the ``tpu``-marked tests;
  everything else is skipped because the CPU-mesh pinning is global and
  mixed-device runs produce spurious failures.  It complements, not
  replaces, a default-mode run.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# APEX_TPU_TESTS=1 leaves the default device on the real TPU so the
# ``tpu``-marked kernel tests (test_pallas_tpu.py) exercise the Mosaic
# kernels on chip; everything else still builds its meshes from CPU devices.
_ON_CHIP = bool(os.environ.get("APEX_TPU_TESTS"))

import jax  # noqa: E402

if not hasattr(jax, "shard_map"):
    # Older jax (< 0.6) keeps shard_map under experimental and has no
    # top-level re-export; publish one so the suite's
    # ``from jax import shard_map`` imports resolve.  Mirrors
    # apex_tpu.parallel.distributed.import_shard_map — inlined rather
    # than imported because apex_tpu must not be imported before the
    # default-device pin below (import-time dispatch would precede it).
    import functools

    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def _compat_shard_map(f=None, **kw):
        kw.pop("check_vma", None)   # new-jax spelling; rep checking off
        kw["check_rep"] = False
        if f is None:               # decorator form: @shard_map(mesh=...)
            return functools.partial(_compat_shard_map, **kw)
        return _legacy_shard_map(f, **kw)

    jax.shard_map = _compat_shard_map

jax.config.update("jax_default_matmul_precision", "highest")
if not _ON_CHIP:
    jax.config.update("jax_default_device", jax.devices("cpu")[0])

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    skip = pytest.mark.skip(
        reason="TPU kernel test: set APEX_TPU_TESTS=1 on a TPU host")
    skip_cpu = pytest.mark.skip(
        reason="CPU-mesh test: run without APEX_TPU_TESTS (on-chip mode "
               "keeps the TPU default device, which breaks tests built "
               "around the virtual CPU mesh)")
    run_on_chip = _ON_CHIP and jax.default_backend() == "tpu"
    for item in items:
        if "tpu" in item.keywords and not run_on_chip:
            item.add_marker(skip)
        elif "tpu" not in item.keywords and run_on_chip:
            item.add_marker(skip_cpu)


@pytest.fixture(scope="session", autouse=True)
def _isolated_tune_cache(tmp_path_factory):
    """Hermetic suite vs the kernel autotuner (ISSUE 14): registered
    kernels consult the per-device tune config cache at dispatch time,
    whose default location is ``~/.cache/apex_tpu`` — a developer who
    ran ``python -m apex_tpu.tune`` locally would otherwise have every
    interpret-mode kernel test silently dispatch THEIR cached blocks
    instead of the shipped defaults.  Point the env override at an
    empty per-session tmpdir (an explicit APEX_TPU_TUNE_CACHE — e.g. an
    on-chip validation run exercising a real cache — still wins)."""
    if not os.environ.get("APEX_TPU_TUNE_CACHE"):
        os.environ["APEX_TPU_TUNE_CACHE"] = str(
            tmp_path_factory.mktemp("tune_cache") / "tune_configs.json")
    yield


@pytest.fixture
def cpu_mesh():
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices("cpu")[:8]), ("data",))
