"""Test configuration: run everything on a simulated 8-device CPU platform.

SURVEY.md §4: the reference can only test distributed behavior on real
multi-GPU nodes; the TPU build does better by unit-testing DP/SyncBN
semantics on a virtual CPU mesh.  These env vars must be set before jax
initializes its backends, hence the top-of-conftest placement.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_default_matmul_precision", "highest")
