"""Slow suite wrapper for the sharded trajectory gates (VERDICT r4 next
#7): dp × tp and ZeRO-1 vs the same program on a 1-device mesh, at
reduced depth for CI (the driver artifact runs 120 steps via
``tools/convergence_sharded.py``)."""

import pytest

pytestmark = pytest.mark.slow


def test_sharded_trajectories_track_single():
    from tools.convergence_sharded import run_gates
    # 100 steps: the dp x tp toy transformer needs ~80+ steps before the
    # "learned" criterion (tail < 0.6 * head) turns green (the 120-step
    # driver artifact reaches tail ~0.02; at 60 steps it is still ~2.1).
    art = run_gates(steps=100, log_every=0)
    for topo, v in art["verdicts"].items():
        assert v["o0"]["ok"], (topo, v["o0"])
        assert v["o2"]["ok"], (topo, v["o2"])
        assert v["o0"]["head_max_rel"] < 2e-3
    assert art["ok"]
