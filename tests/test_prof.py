"""prof package tests: analytic FLOP counts against hand-computed values,
scan multiplicity, capture markers, summary output."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import prof
from apex_tpu.prof import profile_function


def test_matmul_flops_exact():
    a = jnp.ones((64, 32))
    b = jnp.ones((32, 128))
    p = profile_function(lambda x, y: x @ y, a, b, xla_cost=False)
    dots = [r for r in p.records if r.op == "dot_general"]
    assert len(dots) == 1
    assert dots[0].flops == 2 * 64 * 32 * 128
    # bytes: read a + read b + write out, fp32
    assert dots[0].bytes == 4 * (64 * 32 + 32 * 128 + 64 * 128)
    assert dots[0].intensity > 1


def test_conv_flops():
    x = jnp.ones((2, 8, 8, 3))
    k = jnp.ones((3, 3, 3, 16))
    f = lambda a, b: jax.lax.conv_general_dilated(
        a, b, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    p = profile_function(f, x, k, xla_cost=False)
    convs = [r for r in p.records if r.op == "conv_general_dilated"]
    assert len(convs) == 1
    out_elems = 2 * 8 * 8 * 16
    assert convs[0].flops == 2 * out_elems * 3 * 3 * 3


def test_elementwise_and_reduction():
    x = jnp.ones((100,))
    p = profile_function(lambda a: jnp.sum(jnp.exp(a) + a), x,
                        xla_cost=False)
    ops = {r.op: r for r in p.records}
    assert ops["exp"].flops == 100
    assert ops["add"].flops == 100
    assert ops["reduce_sum"].flops == 100


def test_scan_multiplicity():
    x = jnp.ones((4, 8))

    def f(a):
        def body(c, _):
            return c @ jnp.ones((8, 8)), None
        out, _ = jax.lax.scan(body, a, None, length=10)
        return out

    p = profile_function(f, x, xla_cost=False)
    dots = [r for r in p.records if r.op == "dot_general"]
    assert dots and dots[0].count == 10
    assert p.total_flops >= 10 * 2 * 4 * 8 * 8


def test_profile_through_jit_and_grad():
    def loss(w, x):
        return jnp.sum(jnp.tanh(x @ w) ** 2)

    w = jnp.ones((16, 4))
    x = jnp.ones((8, 16))
    p = profile_function(jax.grad(loss), w, x, xla_cost=False)
    # forward + transpose matmuls present
    assert sum(1 for r in p.records if r.op == "dot_general") >= 2
    assert p.total_flops > 0


def test_summary_and_by_op():
    a = jnp.ones((32, 32))
    p = profile_function(lambda x: jnp.sum(x @ x), a, xla_cost=False)
    s = p.summary()
    assert "dot_general" in s and "TOTAL" in s and "MXU" in s
    assert p.by_op()["dot_general"] == 2 * 32 ** 3


def test_xla_cost_analysis_attached():
    a = jnp.ones((64, 64))
    p = profile_function(lambda x: x @ x, a, xla_cost=True)
    if p.xla_cost:  # backend-dependent; when present, sanity-check
        flops = p.xla_cost.get("flops")
        if flops:
            assert flops > 0


def test_capture_markers_and_scope():
    prof.MARKERS.clear()
    prof.init()

    @prof.annotate("my_matmul")
    def f(a):
        return a @ a

    out = jax.jit(f)(jnp.ones((8, 8)))
    assert out.shape == (8, 8)
    assert prof.MARKERS and prof.MARKERS[0]["op"] == "my_matmul"
    assert prof.MARKERS[0]["args"][0]["shape"] == (8, 8)

    with prof.scope("outer"):
        _ = jnp.ones((2,)) + 1


def test_dump_markers(tmp_path):
    prof.MARKERS.clear()
    prof.init()

    @prof.annotate()
    def g(a, flag=True):
        return a * 2

    g(jnp.ones((3,)), flag=False)
    path = tmp_path / "markers.jsonl"
    prof.dump_markers(str(path))
    import json
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert lines[0]["op"] == "g"
    assert lines[0]["kwargs"]["flag"]["value"] is False


def test_capture_scope_annotate_nesting():
    """ISSUE 5 satellite: nested annotate/scope/annotate must (a) nest
    the named scopes into HLO metadata (the NVTX-range analog the
    profiler trace shows) and (b) record one marker per annotated call
    in call order."""
    prof.MARKERS.clear()
    prof.init()
    try:
        @prof.annotate("inner_op")
        def inner(a):
            return a * 2

        @prof.annotate("outer_op")
        def outer(a):
            with prof.scope("mid"):
                return inner(a) + 1

        hlo = jax.jit(outer).lower(jnp.ones((4,))).compile().as_text()
        assert "outer_op/mid/inner_op" in hlo, \
            "named scopes must nest into HLO op metadata"
        assert [m["op"] for m in prof.MARKERS] == ["outer_op", "inner_op"]
        assert prof.MARKERS[0]["args"][0]["shape"] == (4,)
    finally:
        prof.init(enable_markers=False)


def test_dump_markers_roundtrip():
    """The dumped JSONL parses back into exactly the MARKERS content
    (tuples arrive as lists — the JSON-normalized forms must match)."""
    import json
    import tempfile

    prof.MARKERS.clear()
    prof.init()
    try:
        @prof.annotate("round")
        def f(a, mode="x"):
            return a

        f(jnp.ones((2, 3)), mode="y")
        f(7, mode=None)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "markers.jsonl")
            prof.dump_markers(path)
            with open(path) as fh:
                back = [json.loads(line) for line in fh]
        want = [json.loads(json.dumps(m)) for m in prof.MARKERS]
        assert back == want
        assert back[0]["op"] == "round"
        assert back[0]["kwargs"]["mode"]["value"] == "y"
        assert back[1]["args"][0]["value"] == 7
    finally:
        prof.init(enable_markers=False)


def test_annotate_emits_marker_into_telemetry_stream(tmp_path):
    """ISSUE 5: with a telemetry recorder active, each annotate call
    also lands a timestamped ``marker`` event in the run's stream (the
    traceMarker dicts become tail-able run events)."""
    import json

    from apex_tpu import telemetry

    prof.MARKERS.clear()
    prof.init()
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.start(path)
    try:
        @prof.annotate("tele_op")
        def f(a):
            return a + 1

        f(jnp.ones((2,)))
    finally:
        rec.close()
        prof.init(enable_markers=False)
    with open(path) as fh:
        events = [json.loads(line) for line in fh]
    markers = [e for e in events if e["kind"] == "marker"]
    assert len(markers) == 1
    assert markers[0]["op"] == "tele_op"
    assert markers[0]["args"][0]["shape"] == [2]
    assert markers[0]["t"] >= 0
    # and the in-memory MARKERS list still got its copy (dump_markers
    # and the stream describe the same call)
    assert prof.MARKERS[0]["op"] == "tele_op"


# -- measured-trace parse stage (VERDICT r2 #6) -------------------------------

@pytest.mark.slow
def test_parse_trace_roundtrip(tmp_path):
    """Capture a REAL device trace, parse it back, and join measured
    durations onto the static analysis (reference pyprof parse stage,
    ``parse/nvvp.py`` + ``prof/prof.py:39-56``)."""
    from apex_tpu import prof as P

    @jax.jit
    def f(x):
        return jnp.sum(jnp.tanh(x @ x))

    x = jnp.ones((256, 256), jnp.float32)
    f(x).block_until_ready()              # compile outside the trace window
    with P.trace(str(tmp_path)):
        for _ in range(3):
            r = f(x)
        r.block_until_ready()

    tp = P.parse_trace(str(tmp_path))
    assert tp.records, "no measured kernel records parsed"
    by_op = tp.by_op()
    assert any(k.startswith("dot") for k in by_op), by_op.keys()
    dot_key = next(k for k in by_op if k.startswith("dot"))
    assert by_op[dot_key]["count"] >= 3          # one per traced iteration
    assert by_op[dot_key]["total_us"] > 0
    # step segmentation: one run_id per executed iteration
    assert len(tp.steps()) >= 3
    assert tp.summary()

    static = P.profile_function(f, x, xla_cost=False)
    report = P.attach_measured(static, tp)
    # the joined report shows measured microseconds on the matmul row
    dot_line = next(l for l in report.splitlines()
                    if l.startswith("dot_general"))
    assert "-" not in dot_line.split()[3], report


def test_parse_trace_missing_dir_raises(tmp_path):
    from apex_tpu import prof as P
    with pytest.raises(FileNotFoundError):
        P.parse_trace(str(tmp_path / "nope"))


def test_parse_trace_tpu_device_event_format(tmp_path):
    """TPU traces carry hlo_category/model_flops device events (no hlo_op
    arg); the parse stage must ingest them (discovered live on the axon
    v5e trace — reference kernel-record parity for real chips)."""
    import json
    import gzip

    from apex_tpu import prof as P

    run = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    run.mkdir(parents=True)
    events = [
        {"ph": "X", "pid": 3, "tid": 3, "ts": 10.0, "dur": 100.0,
         "name": "convert_reduce_fusion.7",
         "args": {"hlo_category": "convolution fusion",
                  "model_flops": "2000000", "bytes_accessed": "4096"}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 120.0, "dur": 50.0,
         "name": "multiply_subtract_fusion.2",
         "args": {"hlo_category": "loop fusion",
                  "model_flops": "1000", "bytes_accessed": "2048"}},
        {"ph": "M", "name": "process_name"},          # metadata: ignored
        {"ph": "X", "ts": 1.0, "dur": 1.0, "name": "no_args_event"},
    ]
    with gzip.open(run / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)

    tp = P.parse_trace(str(tmp_path))
    assert len(tp.records) == 2
    by_op = tp.by_op()
    assert by_op["convert_reduce_fusion"]["total_us"] == 100.0
    cats = tp.by_category()
    assert cats["convolution fusion"]["count"] == 1
    assert abs(cats["convolution fusion"]["tflops_per_sec"] - 0.02) < 1e-9
    assert "hlo_category" in tp.summary()


# -- CLI entry points (VERDICT r2 next #7) ------------------------------------

def _make_synthetic_trace(tmp_path):
    import gzip
    import json

    run = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    run.mkdir(parents=True)
    events = [
        {"ph": "X", "pid": 3, "tid": 3, "ts": 10.0, "dur": 100.0,
         "name": "fusion.7",
         "args": {"hlo_category": "convolution fusion",
                  "model_flops": "2000000", "bytes_accessed": "4096"}},
    ]
    with gzip.open(run / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)


@pytest.mark.slow
def test_parse_cli_subprocess(tmp_path):
    """``python -m apex_tpu.prof.parse <logdir>`` is a runnable tool
    (reference ``python -m apex.pyprof.parse net.sql``, parse/parse.py:25)."""
    import subprocess
    import sys

    _make_synthetic_trace(tmp_path)
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.prof.parse", str(tmp_path)],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "fusion" in out.stdout and "TOTAL measured" in out.stdout

    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.prof.parse", str(tmp_path),
         "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    import json
    rec = json.loads(out.stdout.splitlines()[0])
    assert rec["base_op"] == "fusion" and rec["duration_us"] == 100.0


@pytest.mark.slow
def test_analysis_cli_subprocess(tmp_path):
    """``python -m apex_tpu.prof.analysis --fn ... --shape ...`` emits the
    tabular flops/bytes report (reference ``python -m apex.pyprof.prof``,
    prof/prof.py:171), joined with a trace dir and a markers file."""
    import json
    import subprocess
    import sys

    _make_synthetic_trace(tmp_path)
    markers = tmp_path / "markers.jsonl"
    markers.write_text(json.dumps(
        {"op": "dense", "args": [{"shape": [8, 16], "dtype": "float32"}],
         "kwargs": {"causal": {"value": True}}}) + "\n")
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.prof.analysis",
         "--fn", "jax.numpy:tanh", "--shape", "8,128",
         "--no-xla-cost", "--trace", str(tmp_path),
         "--markers", str(markers)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    assert "tanh" in out.stdout          # static table has the op
    assert "TOTAL" in out.stdout
    assert "marker op" in out.stdout and "dense" in out.stdout
    assert "causal=True" in out.stdout


# -- trace-count assertions (runtime complement to jaxlint J004) --------------

def test_assert_trace_count_basic():
    f = jax.jit(lambda x: x * 2)
    with prof.assert_trace_count(f, 1):          # first call compiles
        for _ in range(3):
            f(jnp.ones(3))
    with prof.assert_trace_count(f, 0):          # steady state
        f(jnp.ones(3))
    assert prof.trace_count(f) == 1


def test_assert_trace_count_catches_retrace():
    f = jax.jit(lambda x: x + 1)
    f(jnp.ones(3))
    with pytest.raises(AssertionError, match="J004"):
        with prof.assert_trace_count(f, 0):
            f(jnp.ones(5))                       # new shape: retrace
    g = jax.jit(lambda x, n: x * n, static_argnums=(1,))
    with pytest.raises(AssertionError, match="J004"):
        with prof.assert_trace_count(g, 1):
            for i in range(3):
                g(jnp.ones(3), i)                # static arg varies: retrace


def test_assert_trace_count_exact_catches_missing_compile():
    f = jax.jit(lambda x: x - 1)
    with pytest.raises(AssertionError, match="not invoked"):
        with prof.assert_trace_count(f, 1):
            pass                                 # never called
    with prof.assert_trace_count(f, 1, exact=False):
        pass                                     # at-most mode: ok


def test_trace_count_rejects_plain_function():
    with pytest.raises(TypeError, match="tracing cache"):
        prof.trace_count(lambda x: x)


def test_amp_o2_step_compiles_once_never_retraces():
    """The headline contract: a representative amp O2 train step traces
    exactly once, then every same-shaped step reuses the trace.  This is
    the runtime ground truth behind jaxlint J004 — a Python scalar or a
    weak-type literal sneaking into the carried state would retrace
    every step and fail here before it shows up as a 10x dispatch-floor
    regression in bench.py."""
    from apex_tpu import training
    from apex_tpu.training import make_train_step

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(6, 4) * 0.3, jnp.float32),
              "b": jnp.zeros((4,), jnp.float32)}
    x = jnp.asarray(rng.randn(16, 6), jnp.float32)
    y = jnp.asarray(rng.randn(16, 4) * 0.1, jnp.float32)

    def loss_fn(p, batch):
        xb, yb = batch
        out = xb @ p["w"].astype(xb.dtype) + p["b"].astype(xb.dtype)
        return jnp.mean((out.astype(jnp.float32) - yb) ** 2)

    init_fn, step_fn = make_train_step(loss_fn, training.adam(1e-2),
                                       opt_level="O2", loss_scale="dynamic")
    state = init_fn(params)
    step = jax.jit(step_fn)
    with prof.assert_trace_count(step, 1):       # one compile...
        for _ in range(5):
            state, metrics = step(state, (x, y))
    with prof.assert_trace_count(step, 0):       # ...zero retraces after
        state, metrics = step(state, (x, y))
    assert np.isfinite(float(metrics["loss"]))
