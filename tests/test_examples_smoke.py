"""Smoke-run the example entry points (VERDICT r1 weak-#8: the flagship
"examples run unmodified" claim was never CI-verified).

Each example runs in-process via runpy with a tiny synthetic config
(`--synthetic --prof N`-style), mirroring how the reference's L1 harness
drives ``examples/imagenet/main_amp.py``.  The conftest pins the default
device to CPU, so these are fast correctness runs, not benchmarks.
"""

import os
import runpy
import sys

import numpy as np
import pytest

# The example entry points are exercised on-chip by bench.py every round;
# off the fast gate they cost ~5 min of CPU compiles.
pytestmark = pytest.mark.slow

import jax

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


def _run_example(monkeypatch, rel_path, argv):
    path = os.path.join(_ROOT, rel_path)
    monkeypatch.setattr(sys, "argv", [path] + argv)
    monkeypatch.syspath_prepend(_ROOT)
    from apex_tpu.amp import autocast
    try:
        runpy.run_path(path, run_name="__main__")
    finally:
        autocast.shutdown()   # examples may enable O1 globally


@pytest.mark.parametrize("opt_level", ["O0", "O2"])
def test_imagenet_example(monkeypatch, opt_level, capsys):
    _run_example(monkeypatch, "examples/imagenet/main_amp.py", [
        "--synthetic", "--prof", "3", "-b", "8", "--image-size", "32",
        "-a", "resnet18", "--epochs", "1", "--steps-per-epoch", "3",
        "--opt-level", opt_level])
    out = capsys.readouterr().out
    assert "opt_level = " + opt_level in out


def test_imagenet_example_real_data_worker_pool(monkeypatch, tmp_path,
                                                capsys):
    """The real-data input path end to end (ISSUE 3): directory source
    (decode=False descriptors) -> 2-worker window assembly with the
    fused crop/flip/normalize augment -> async device staging -> train
    loop, plus the parseable loader-stall attribution line."""
    import re

    import numpy as np

    rng = np.random.RandomState(0)
    for cls in ("a", "b"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(10):
            np.save(d / f"s{i}.npy",
                    rng.randint(0, 256, (64, 64, 3)).astype(np.uint8))
    _run_example(monkeypatch, "examples/imagenet/main_amp.py", [
        str(tmp_path), "--prof", "2", "-b", "8", "--image-size", "32",
        "-a", "resnet18", "--epochs", "1", "--opt-level", "O2",
        "--workers", "2", "--augment"])
    out = capsys.readouterr().out
    m = re.search(r"loader: stall ([\d.]+)%", out)   # bench._LOADER_RE
    assert m, f"no loader attribution line in:\n{out[-2000:]}"
    assert 0.0 <= float(m.group(1)) <= 100.0


def test_imagenet_example_telemetry_stream(monkeypatch, tmp_path, capsys):
    """ISSUE 5 acceptance shape: the imagenet CPU smoke run emits a
    telemetry stream; ``apex_tpu.prof.timeline`` analyzes it and its
    stall attribution agrees with the 'loader: stall' line the example
    printed (bench.py gates the same agreement every round)."""
    import json
    import re

    tel = str(tmp_path / "run.jsonl")
    _run_example(monkeypatch, "examples/imagenet/main_amp.py", [
        "--synthetic", "--prof", "4", "-b", "8", "--image-size", "32",
        "-a", "resnet18", "--epochs", "1", "--steps-per-epoch", "4",
        "--opt-level", "O2", "--loss-scale", "dynamic",
        "--steps-per-call", "2", "--telemetry", tel])
    out = capsys.readouterr().out
    m = re.search(r"loader: stall ([\d.]+)%", out)
    assert m, f"no loader line in:\n{out[-2000:]}"
    assert "telemetry:" in out
    # ISSUE 6: the watchdog is on by default under --telemetry and a
    # healthy smoke run prints the ok health line at exit
    assert "health: ok (0 alerts)" in out

    from apex_tpu.prof import timeline
    events = timeline.load_events(tel)
    a = timeline.analyze(events)
    assert a["steps"] == 4 and a["windows"] == 2
    # stall attribution agrees with the printed number (same snapshot;
    # the synthetic pool never waits on input, so both are 0.0)
    assert abs(a["attribution"]["loader_stall_pct"]
               - float(m.group(1))) <= 2.0
    # the stream is valid JSONL with a summary and a run header
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "run" and kinds[-1] == "summary"
    assert "window" in kinds and "metrics" in kinds
    # chrome export round-trips
    chrome = str(tmp_path / "trace.json")
    from apex_tpu import telemetry
    assert telemetry.to_chrome_trace(events, chrome) > 0
    json.load(open(chrome))


def test_imagenet_example_sync_bn(monkeypatch, capsys):
    _run_example(monkeypatch, "examples/imagenet/main_amp.py", [
        "--synthetic", "--prof", "2", "-b", "8", "--image-size", "32",
        "-a", "resnet18", "--epochs", "1", "--steps-per-epoch", "2",
        "--opt-level", "O2", "--sync_bn"])


def test_dcgan_example_multi_loss(monkeypatch):
    """The multi-model / multi-loss O1 path (3 loss scalers), default
    (step-pipelined) mode: the whole GAN iteration — both D backwards,
    the G phase, and all three scaler machines — runs through
    runtime.StepPipeline."""
    _run_example(monkeypatch, "examples/dcgan/main_amp.py", [
        "--batchSize", "8", "--ngf", "8", "--ndf", "8",
        "--iters-per-epoch", "2", "--niter", "1", "--steps-per-call", "2"])


def test_dcgan_example_multi_loss_imperative(monkeypatch):
    """The reference-parity imperative surface (amp.initialize with
    num_losses=3, scale_loss loss_id=0/1/2, FusedAdam.step — reference
    dcgan/main_amp.py:214-253)."""
    _run_example(monkeypatch, "examples/dcgan/main_amp.py", [
        "--batchSize", "8", "--ngf", "8", "--ndf", "8",
        "--iters-per-epoch", "2", "--niter", "1", "--imperative"])


def test_imagenet_example_steps_per_call(monkeypatch, capsys):
    """The K-step device loop through the example CLI (--prof rounds up
    to whole calls; the ragged-tail path is covered by
    tests/test_runtime.py on the stage_windows protocol)."""
    _run_example(monkeypatch, "examples/imagenet/main_amp.py", [
        "--synthetic", "--prof", "5", "-b", "8", "--image-size", "32",
        "-a", "resnet18", "--epochs", "1", "--steps-per-epoch", "6",
        "--opt-level", "O2", "--steps-per-call", "2", "--print-freq", "2"])
    out = capsys.readouterr().out
    assert "done" in out


def test_distributed_example(monkeypatch):
    """SPMD DDP example over a 4-device CPU mesh."""
    cpus = jax.devices("cpu")[:4]
    orig_devices = jax.devices
    monkeypatch.setattr(
        jax, "devices",
        lambda *a, **kw: orig_devices(*a, **kw) if a or kw else cpus)
    _run_example(monkeypatch,
                 "examples/simple/distributed/distributed_data_parallel.py",
                 [])


@pytest.mark.parametrize("name", ["lenet", "user_annotation",
                                  "custom_func_module", "end_to_end",
                                  "jit_function", "apex_ops"])
def test_prof_examples(monkeypatch, name, tmp_path):
    """The pyprof-examples analog (reference apex/pyprof/examples/)."""
    argv = [str(tmp_path / "trace")] if name == "end_to_end" else []
    _run_example(monkeypatch, f"examples/prof/{name}.py", argv)


@pytest.mark.parametrize("name,argv", [
    ("imagenet", ["-m", "resnet18", "-b", "4", "--image-size", "32"]),
    ("operators", []),
])
def test_prof_examples_with_args(monkeypatch, name, argv, tmp_path):
    """Round-4 recipes: imagenet-scale profiling CLI (reference
    pyprof/examples/imagenet/) and the operator sweep + start/stop window
    (operators.py + simple.py)."""
    if name == "operators":
        argv = [str(tmp_path / "trace")]
    _run_example(monkeypatch, f"examples/prof/{name}.py", argv)


def test_lm_example(monkeypatch, capsys):
    """GPT causal-LM example (flash attention path, fully-jitted step)."""
    _run_example(monkeypatch, "examples/lm/main_amp.py", [
        "--synthetic", "--steps", "2", "-b", "2", "--seq-len", "33",
        "--hidden", "32", "--layers", "1", "--heads", "2",
        "--vocab", "128", "--opt-level", "O2"])
    out = capsys.readouterr().out
    assert "opt_level = O2" in out


def test_lm_example_sequence_parallel(monkeypatch):
    """GPT over a 2-way sp mesh with ring attention."""
    _run_example(monkeypatch, "examples/lm/main_amp.py", [
        "--synthetic", "--steps", "2", "-b", "2", "--seq-len", "33",
        "--hidden", "32", "--layers", "1", "--heads", "2",
        "--vocab", "128", "--sp", "2", "--attention", "ring"])


def test_lm_example_fused_loss_parity(monkeypatch, capsys):
    """ISSUE 7 satellite: the contrib fused softmax-xentropy (the lm
    example's default) must produce the SAME loss trajectory as the
    --no-fused-loss log_softmax reference composition — its vocab-sized
    logits are the kernel's textbook case, and a trajectory match over
    real update steps pins forward AND backward parity."""
    import re

    argv = ["--synthetic", "--steps", "2", "-b", "2", "--seq-len", "33",
            "--hidden", "32", "--layers", "1", "--heads", "2",
            "--vocab", "128", "--opt-level", "O2", "--smoothing", "0.1"]
    _run_example(monkeypatch, "examples/lm/main_amp.py", argv)
    fused = [float(v) for v in
             re.findall(r"loss ([\d.]+)", capsys.readouterr().out)]
    _run_example(monkeypatch, "examples/lm/main_amp.py",
                 argv + ["--no-fused-loss"])
    ref = [float(v) for v in
           re.findall(r"loss ([\d.]+)", capsys.readouterr().out)]
    assert fused and len(fused) == len(ref)
    np.testing.assert_allclose(fused, ref, atol=2e-3)


def test_imagenet_example_unfused_flags(monkeypatch, capsys):
    """--no-fused-bn/--no-fused-loss/--no-aot-warmup keep the plain
    nn.BatchNorm + log_softmax + cold-compile surface alive."""
    _run_example(monkeypatch, "examples/imagenet/main_amp.py", [
        "--synthetic", "--prof", "2", "-b", "8", "--image-size", "32",
        "-a", "resnet18", "--epochs", "1", "--steps-per-epoch", "2",
        "--opt-level", "O2", "--no-fused-bn", "--no-fused-loss",
        "--no-aot-warmup"])
    out = capsys.readouterr().out
    assert "done" in out


@pytest.mark.parametrize("zero", [2, 3])
def test_mesh_example(monkeypatch, capsys, zero):
    """The mesh-frontend flagship: plan declaration, ZeRO sharding,
    AOT-warmed pipeline, state-bytes ledger (ISSUE 12)."""
    cpus = jax.devices("cpu")[:4]
    orig_devices = jax.devices
    monkeypatch.setattr(
        jax, "devices",
        lambda *a, **kw: orig_devices(*a, **kw) if a or kw else cpus)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    _run_example(monkeypatch, "examples/simple/mesh/fsdp_train.py",
                 ["--zero", str(zero), "--steps", "8",
                  "--steps-per-call", "4", "--fsdp", "4", "--batch", "4"])
    out = capsys.readouterr().out
    assert "done" in out
    assert "ratio" in out
    if zero == 3:
        assert "0.25" in out          # params+state divided 4 ways
