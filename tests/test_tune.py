"""apex_tpu.tune — autotuner registry/harness/cache lifecycle (ISSUE 14).

CPU-runnable by design: the tuner's measurement runs the REAL Pallas
kernels in interpret mode (the fused_bn_act/xentropy tier-parity
pattern), the cache lifecycle is pure host JSON, and dispatch consults
are trace-time dict lookups.  Covered here:

* config roundtrip + process-restart survival (reload from disk only);
* stale-entry invalidation when a kernel bumps its registered version;
* corrupt/partial cache files fall back to defaults loudly-ONCE;
* deterministic tuner runs on CPU (interpret mode, seeded candidate
  order, injected deterministic timer);
* ledger-driven candidate prioritization (memory- vs compute-bound
  verdicts reorder the search);
* every registered kernel dispatches through the cache with outputs
  bitwise-identical to its default config (tolerance for flash
  attention's reordered online softmax — its oracle contract);
* tune telemetry events + the tuned_kernel_pct gauge;
* the python -m apex_tpu.tune CLI (tune one kernel / show table /
  refuses to measure off-TPU without --interpret).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.telemetry as telemetry
from apex_tpu.tune import dispatch, measure, registry, space, store
from apex_tpu.tune.__main__ import main as tune_main

registry.load_builtin()


@pytest.fixture
def tune_cache(tmp_path, monkeypatch):
    """Isolated config cache: fresh file path, cleared memo/stats, the
    env override pointing dispatch at it."""
    path = str(tmp_path / "tune_configs.json")
    monkeypatch.setenv("APEX_TPU_TUNE_CACHE", path)
    store._STATE["memo_path"] = None
    store._STATE["memo"] = None
    store._STATE["warned"] = set()
    dispatch.reset_stats()
    yield path
    store._STATE["memo_path"] = None
    store._STATE["memo"] = None
    dispatch.reset_stats()


def _fresh_reload(path):
    """Simulate a process restart: drop every in-memory trace and read
    the persisted file back."""
    store._STATE["memo_path"] = None
    store._STATE["memo"] = None
    return store.load(path, reload=True)


# -- space helpers (the hoisted VMEM math) ------------------------------------

def test_space_is_the_one_home_for_vmem_math():
    import importlib
    fba = importlib.import_module("apex_tpu.normalization.fused_bn_act")
    # (the package __init__ re-exports the FUNCTION under this name)
    fln = importlib.import_module(
        "apex_tpu.normalization.fused_layer_norm")

    # the kernel aliases ARE the shared constants
    assert fln._VMEM_BUDGET_BYTES == space.VMEM_BUDGET_BYTES
    assert fln._SUBLANE_ROWS == space.SUBLANE_ROWS
    # and both kernels' row pickers delegate to the same function
    for n1, n2, bpe in ((32768, 768, 22), (32768, 4096, 22),
                       (4, 768, 22), (32768, 16384, 28)):
        assert fln._pick_rows(n1, n2, bpe) == space.pick_rows(n1, n2, bpe)
        assert fba._pick_rows(n1, n2, bpe) == space.pick_rows(n1, n2, bpe)
    # width gate equivalence (fused_layer_norm's bwd footprint)
    assert fln._kernel_max_width(4) == space.max_width(3 * 4 + 16)
    assert fba._kernel_fits(1024, 2) == space.floor_block_fits(1024, 14)


def test_space_row_block_candidates_dedupe_clamped_blocks():
    # at width 4096 fp32-bwd footprint the budget admits ~100 rows, so
    # 128/256/512/1024 all clamp to the same effective block — only one
    # survives alongside the genuinely distinct small blocks
    cands = space.row_block_candidates(32768, 4096, 28)
    assert sorted(set(cands)) == sorted(cands)
    effs = {space.pick_rows(32768, 4096, 28, row_block=b) for b in cands}
    assert len(effs) == len(cands)


def test_pow2_bucket():
    assert [space.pow2_bucket(n) for n in (1, 2, 3, 64, 65, 1024)] \
        == [1, 2, 4, 64, 128, 1024]


# -- cache lifecycle ----------------------------------------------------------

def test_config_roundtrip_survives_restart(tune_cache):
    key = store.put("fused_layer_norm", 1, "r64_w128_i4",
                    {"row_block": 32}, meta={"best_ms": 0.5},
                    path=tune_cache)
    assert key == "cpu|fused_layer_norm|v1|r64_w128_i4"
    assert store.lookup("fused_layer_norm", 1, "r64_w128_i4",
                        path=tune_cache) == {"row_block": 32}
    # "restart": only the persisted file survives
    _fresh_reload(tune_cache)
    assert store.lookup("fused_layer_norm", 1, "r64_w128_i4",
                        path=tune_cache) == {"row_block": 32}
    ents = store.entries(tune_cache)
    assert len(ents) == 1 and ents[0]["meta"]["best_ms"] == 0.5


def test_version_bump_invalidates_stale_entries(tune_cache):
    store.put("fused_layer_norm", 1, "r64_w128_i4", {"row_block": 32},
              path=tune_cache)
    # the bumped kernel never sees the v1 entry
    assert store.lookup("fused_layer_norm", 2, "r64_w128_i4",
                        path=tune_cache) is None
    # and the garbage collector drops it from disk
    assert store.prune_stale({"fused_layer_norm": 2},
                             path=tune_cache) == 1
    _fresh_reload(tune_cache)
    assert store.lookup("fused_layer_norm", 1, "r64_w128_i4",
                        path=tune_cache) is None
    assert store.entries(tune_cache) == []


def test_corrupt_cache_falls_back_loudly_once(tune_cache, capsys):
    with open(tune_cache, "w") as f:
        f.write('{"schema": 1, "entries": {TRUNCATED')
    assert store.lookup("fused_layer_norm", 1, "b", path=tune_cache) is None
    assert store.lookup("bn_relu_residual", 1, "b", path=tune_cache) is None
    err = capsys.readouterr().err
    # loudly: the fallback is announced; once: a single line for both
    assert err.count("falling back to built-in default configs") == 1
    assert "corrupt" in err
    # a later put repairs the file
    store.put("xentropy", 1, "r32_h128", {"row_block": 64},
              path=tune_cache)
    _fresh_reload(tune_cache)
    assert store.lookup("xentropy", 1, "r32_h128",
                        path=tune_cache) == {"row_block": 64}


def test_partial_entries_are_skipped_not_fatal(tune_cache, capsys):
    with open(tune_cache, "w") as f:
        json.dump({"schema": 1, "entries": {
            "cpu|xentropy|v1|r32_h128": {"kernel": "xentropy"},  # no config
            "cpu|fused_layer_norm|v1|b": {
                "kernel": "fused_layer_norm", "version": 1, "bucket": "b",
                "device_kind": "cpu", "config": {"row_block": 16}},
        }}, f)
    assert store.lookup("xentropy", 1, "r32_h128", path=tune_cache) is None
    assert store.lookup("fused_layer_norm", 1, "b",
                        path=tune_cache) == {"row_block": 16}
    assert "partial" in capsys.readouterr().err


def test_future_schema_is_not_misread(tune_cache, capsys):
    with open(tune_cache, "w") as f:
        json.dump({"schema": 99, "entries": {
            "cpu|xentropy|v1|b": {"config": {"row_block": 8}}}}, f)
    assert store.lookup("xentropy", 1, "b", path=tune_cache) is None
    assert "newer" in capsys.readouterr().err


# -- deterministic tuner runs on CPU ------------------------------------------

def _fake_timer(model):
    """Deterministic injected timer: seconds from a pure function of
    the config (no device clock involved)."""
    def timer(cfg, run):
        run()
        return model(cfg)
    return timer


def test_tuner_is_deterministic_on_cpu(tune_cache):
    # n1=1024 keeps every row-block candidate a DISTINCT effective
    # block (at tiny n1 the effective-dedupe collapses the big blocks
    # onto the default — covered separately below)
    shape = {"n1": 1024, "n2": 128, "dtype": "float32"}
    model = lambda cfg: 1e-3 * (1 + abs(cfg["row_block"] - 64))

    runs = []
    for _ in range(2):
        _fresh_reload(tune_cache)
        res = measure.tune_kernel("fused_layer_norm", shape, seed=7,
                                  interpret=True,
                                  measure=_fake_timer(model),
                                  path=tune_cache)
        runs.append(res)
    a, b = runs
    # same winner, same candidate visit order, same measurements
    assert a.config == b.config == {"row_block": 64}
    assert a.order == b.order
    assert a.best_ms == b.best_ms
    assert a.source == "interpret"
    # a different seed may reorder, but the min is order-independent
    c = measure.tune_kernel("fused_layer_norm", shape, seed=8,
                            interpret=True, measure=_fake_timer(model),
                            path=tune_cache)
    assert c.config == {"row_block": 64}


def test_tuner_refuses_to_measure_off_tpu_without_interpret():
    if jax.default_backend() == "tpu":
        pytest.skip("on-chip run: the refusal is the CPU contract")
    with pytest.raises(RuntimeError, match="only runs on TPU"):
        measure.tune_kernel("fused_layer_norm",
                            {"n1": 8, "n2": 128}, store_result=False)


def test_tuned_never_slower_than_default_by_construction(tune_cache):
    # the default config is always a candidate, so best <= default even
    # under an adversarial timer that makes everything else slower
    model = lambda cfg: 1e-3 * (100.0 if cfg["row_block"] != 256 else 1.0)
    res = measure.tune_kernel("fused_layer_norm",
                              {"n1": 64, "n2": 128}, interpret=True,
                              measure=_fake_timer(model), path=tune_cache)
    assert res.config == res.default_config == {"row_block": 256}
    assert res.tuned_over_default == 1.0


def test_oracle_rejects_wrong_outputs(tune_cache):
    from apex_tpu.tune.registry import KernelSpec, TuneCase

    def build(shape, interpret):
        def run(cfg):
            # a "kernel" whose non-default config computes WRONG values
            base = jnp.arange(8, dtype=jnp.float32)
            return base * (1.0 if cfg["blk"] == 1 else 1.5)
        return TuneCase(run=run)

    spec = KernelSpec(
        name="_test_wrong", version=1, params=("blk",), kind="memory",
        exact=True, defaults=lambda s: {"blk": 1},
        candidates=lambda s, b: [{"blk": 2}, {"blk": 3}],
        constraint=lambda s, c: True, build=build,
        bucket=lambda s: "b", small_shape={}, example_shape={})
    model = lambda cfg: 1e-6 * cfg["blk"]   # wrong configs look faster
    res = measure.tune_kernel(spec, {}, interpret=True,
                              measure=_fake_timer(model), path=tune_cache)
    assert res.rejected_oracle == 2
    assert res.config == {"blk": 1}         # the wrong ones cannot win


def test_constraint_rejects_before_timing(tune_cache):
    from apex_tpu.tune.registry import KernelSpec, TuneCase

    timed = []

    def build(shape, interpret):
        def run(cfg):
            return jnp.zeros(4)
        return TuneCase(run=run)

    spec = KernelSpec(
        name="_test_constraint", version=1, params=("blk",),
        kind="memory", exact=True, defaults=lambda s: {"blk": 8},
        candidates=lambda s, b: [{"blk": 16}, {"blk": 4096}],
        constraint=lambda s, c: c["blk"] <= 64, build=build,
        bucket=lambda s: "b", small_shape={}, example_shape={})

    def timer(cfg, run):
        timed.append(dict(cfg))
        return 1e-3
    res = measure.tune_kernel(spec, {}, interpret=True, measure=timer,
                              path=tune_cache)
    assert res.rejected_constraint == 1
    assert {"blk": 4096} not in timed       # never timed, never compiled


def test_bound_from_ledger_reorders_candidates():
    spec = registry.get_spec("flash_attention")
    ledger_mem = {"regions": [
        {"region": "encoder/attention", "bound": "memory",
         "modeled_ms": 10.0},
        {"region": "mlp", "bound": "compute", "modeled_ms": 50.0}]}
    ledger_cmp = {"regions": [
        {"region": "encoder/attention", "bound": "compute",
         "modeled_ms": 10.0}]}
    assert measure.bound_from_ledger(ledger_mem, spec) == "memory"
    assert measure.bound_from_ledger(ledger_cmp, spec) == "compute"
    # no attention-ish region -> None (the spec's own kind decides)
    assert measure.bound_from_ledger({"regions": [
        {"region": "optimizer", "bound": "memory"}]}, spec) is None

    shape = dict(spec.small_shape)
    mem = spec.candidates(shape, "memory")
    mem.sort(key=lambda c: spec.priority(shape, c, "memory"))
    cmp_ = spec.candidates(shape, "compute")
    cmp_.sort(key=lambda c: spec.priority(shape, c, "compute"))
    area = lambda c: c["block_q"] * c["block_k"]
    assert area(mem[0]) == min(area(c) for c in mem)
    assert area(cmp_[0]) == max(area(c) for c in cmp_)


# -- dispatch integration: every registered kernel consults the cache ---------

def test_layer_norm_dispatch_is_bitwise_with_tuned_config(tune_cache):
    from apex_tpu.normalization.fused_layer_norm import (TUNE_VERSION,
                                                         fused_layer_norm,
                                                         tune_bucket)
    x = jnp.linspace(-2, 2, 64 * 128, dtype=jnp.float32).reshape(64, 128)
    w = jnp.linspace(0.5, 1.5, 128, dtype=jnp.float32)
    b = jnp.linspace(-0.1, 0.1, 128, dtype=jnp.float32)
    base = fused_layer_norm(x, (128,), w, b, interpret=True)
    assert dispatch.dispatch_stats()["by_kernel"][
        "fused_layer_norm"]["misses"] >= 1

    store.put("fused_layer_norm", TUNE_VERSION, tune_bucket(64, 128, 4),
              {"row_block": 16}, path=tune_cache)
    tuned = fused_layer_norm(x, (128,), w, b, interpret=True)
    stats = dispatch.dispatch_stats()["by_kernel"]["fused_layer_norm"]
    assert stats["hits"] >= 1 and stats["tuned"]
    np.testing.assert_array_equal(np.asarray(base), np.asarray(tuned))


def test_bn_relu_dispatch_is_bitwise_with_tuned_config(tune_cache):
    from apex_tpu.normalization.fused_bn_act import (TUNE_VERSION,
                                                     bn_relu_residual,
                                                     tune_bucket)
    x = jnp.linspace(-3, 3, 64 * 128, dtype=jnp.float32).reshape(64, 128)
    z = jnp.flip(x, axis=0)
    mean = jnp.linspace(-0.2, 0.2, 128)
    invstd = jnp.linspace(0.8, 1.2, 128)
    base = bn_relu_residual(x, mean, invstd, z=z, interpret=True)
    store.put("bn_relu_residual", TUNE_VERSION,
              tune_bucket(64, 128, 4, True), {"row_block": 8},
              path=tune_cache)
    tuned = bn_relu_residual(x, mean, invstd, z=z, interpret=True)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(tuned))
    assert dispatch.dispatch_stats()["by_kernel"][
        "bn_relu_residual"]["tuned"]


def test_quantized_matmul_dispatch_is_bitwise_with_tuned_config(tune_cache):
    from apex_tpu.quant.kernels import (TUNE_VERSION, quantized_matmul,
                                        tune_bucket)
    x = jnp.linspace(-1, 1, 64 * 128, dtype=jnp.float32).reshape(64, 128)
    w = jnp.linspace(-0.5, 0.5, 128 * 128,
                     dtype=jnp.float32).reshape(128, 128)
    base = quantized_matmul(x, w, x_scale=0.01, interpret=True)
    store.put("quantized_matmul", TUNE_VERSION,
              tune_bucket(64, 128, 128, 4),
              {"block_m": 8, "block_n": 128}, path=tune_cache)
    tuned = quantized_matmul(x, w, x_scale=0.01, interpret=True)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(tuned))
    assert dispatch.dispatch_stats()["by_kernel"][
        "quantized_matmul"]["tuned"]


def test_flash_dispatch_consults_and_matches_default(tune_cache):
    from apex_tpu.ops.flash_attention import (TUNE_VERSION,
                                              flash_attention,
                                              tune_bucket)
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 256, 2, 64), jnp.float32)
    k = jnp.asarray(rs.randn(1, 256, 2, 64), jnp.float32)
    v = jnp.asarray(rs.randn(1, 256, 2, 64), jnp.float32)
    base = flash_attention(q, k, v, causal=True, interpret=True)
    store.put("flash_attention", TUNE_VERSION,
              tune_bucket(256, 256, 64, True, False, False),
              {"block_q": 128, "block_k": 128}, path=tune_cache)
    tuned = flash_attention(q, k, v, causal=True, interpret=True)
    stats = dispatch.dispatch_stats()["by_kernel"]["flash_attention"]
    assert stats["hits"] >= 1
    # flash's oracle contract: tolerance, not bitwise (online softmax
    # reorders with the KV block)
    np.testing.assert_allclose(np.asarray(base), np.asarray(tuned),
                               rtol=2e-5, atol=2e-6)


def test_explicit_blocks_and_bad_entries_bypass_the_cache(tune_cache):
    from apex_tpu.normalization.fused_layer_norm import (TUNE_VERSION,
                                                         fused_layer_norm,
                                                         tune_bucket)
    x = jnp.ones((64, 128), jnp.float32)
    # unknown keys / non-int values are rejected as a miss, not passed
    # through to pallas_call
    store.put("fused_layer_norm", TUNE_VERSION, tune_bucket(64, 128, 4),
              {"row_block": 16, "exotic_knob": 3}, path=tune_cache)
    fused_layer_norm(x, (128,), interpret=True)
    assert not dispatch.dispatch_stats()["by_kernel"][
        "fused_layer_norm"]["tuned"]
    dispatch.reset_stats()
    # an explicit row_block never consults at all
    fused_layer_norm(x, (128,), row_block=32, interpret=True)
    assert "fused_layer_norm" not in dispatch.dispatch_stats()["by_kernel"]


def test_partial_config_entry_is_a_miss_not_a_crash(tune_cache):
    """A half-written entry (only block_q) must fall back to defaults —
    the kernels index the config unconditionally, so the params filter
    rejects MISSING keys too (review finding: KeyError at dispatch)."""
    from apex_tpu.ops.flash_attention import (TUNE_VERSION,
                                              flash_attention,
                                              tune_bucket)
    store.put("flash_attention", TUNE_VERSION,
              tune_bucket(256, 256, 64, True, False, False),
              {"block_q": 128}, path=tune_cache)
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 256, 2, 64), jnp.float32)
    out = flash_attention(q, q, q, causal=True, interpret=True)
    assert out.shape == (1, 256, 2, 64)
    assert not dispatch.dispatch_stats()["by_kernel"][
        "flash_attention"]["tuned"]


def test_hostile_row_block_is_rounded_legal(tune_cache):
    """An out-of-band cache value (hand-edited 100, hostile 3) must
    reach pallas_call as a legal sublane-multiple block (review
    finding: pick_rows only rounded the budget cap, not the knob)."""
    assert space.pick_rows(4096, 1024, 12, row_block=100) == 96
    assert space.pick_rows(4096, 1024, 12, row_block=3) == 8
    from apex_tpu.normalization.fused_layer_norm import (TUNE_VERSION,
                                                         fused_layer_norm,
                                                         tune_bucket)
    store.put("fused_layer_norm", TUNE_VERSION, tune_bucket(64, 128, 4),
              {"row_block": 100}, path=tune_cache)
    x = jnp.linspace(-2, 2, 64 * 128, dtype=jnp.float32).reshape(64, 128)
    tuned = fused_layer_norm(x, (128,), interpret=True)
    assert dispatch.dispatch_stats()["by_kernel"][
        "fused_layer_norm"]["tuned"]
    dispatch.reset_stats()
    base = fused_layer_norm(x, (128,), row_block=256, interpret=True)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(tuned))


def test_bool_config_values_are_rejected(tune_cache):
    """JSON `true` is an int subclass — it must not pass the positive-
    int gate and reach _pick_block as 1 (review finding)."""
    from apex_tpu.quant.kernels import TUNE_VERSION, tune_bucket
    store.put("quantized_matmul", TUNE_VERSION, tune_bucket(64, 128, 128, 4),
              {"block_m": True, "block_n": 256}, path=tune_cache)
    assert dispatch.kernel_config(
        "quantized_matmul", TUNE_VERSION, tune_bucket(64, 128, 128, 4),
        params=("block_m", "block_n")) is None


def test_effective_dedupe_never_times_the_default_twice(tune_cache):
    """At n1=64 every row_block >= 64 clamps onto the same effective
    block as the default — only ONE of them may be measured (review
    finding: a clamped twin of the default could be persisted as a
    noise 'win')."""
    spec = registry.get_spec("fused_layer_norm")
    shape = {"n1": 64, "n2": 128, "dtype": "float32"}
    model = lambda cfg: 1e-3
    res = measure.tune_kernel(spec, shape, interpret=True,
                              measure=_fake_timer(model), path=tune_cache)
    keys = [repr(spec.effective(shape, c)) for c in res.order]
    assert len(keys) == len(set(keys))
    # the default's effective block appears exactly once (the default)
    assert keys.count(repr(spec.effective(shape,
                                          res.default_config))) == 1


def test_max_candidates_counts_as_truncated_not_constraint(tune_cache):
    model = lambda cfg: 1e-3 * cfg["row_block"]
    res = measure.tune_kernel("fused_layer_norm",
                              {"n1": 64, "n2": 128}, interpret=True,
                              max_candidates=2,
                              measure=_fake_timer(model), path=tune_cache)
    assert res.truncated > 0
    assert res.rejected_constraint == 0


def test_xentropy_tuned_rows_helper(tune_cache):
    from apex_tpu.contrib import xentropy as xe
    assert xe._tuned_rows(32, 128) is None
    store.put("xentropy", xe.TUNE_VERSION, xe.tune_bucket(32, 128),
              {"row_block": 64}, path=tune_cache)
    assert xe._tuned_rows(32, 128) == 64
    # the budget clamp still binds a hostile value
    assert xe._row_block(32, 128, 4096) <= 512


# -- telemetry ----------------------------------------------------------------

def test_tune_events_and_tuned_kernel_pct_gauge(tune_cache, tmp_path):
    stream = tmp_path / "tune_stream.jsonl"
    rec = telemetry.start(str(stream))
    try:
        model = lambda cfg: 1e-3 * cfg["row_block"]
        measure.tune_kernel("fused_layer_norm", {"n1": 64, "n2": 128},
                            interpret=True, measure=_fake_timer(model),
                            path=tune_cache)
        from apex_tpu.normalization.fused_layer_norm import \
            fused_layer_norm
        fused_layer_norm(jnp.ones((64, 128), jnp.float32), (128,),
                         interpret=True)
        gauge = rec.metrics.gauge("tuned_kernel_pct").value
        assert gauge == 100.0
    finally:
        rec.close()
    kinds = {}
    with open(stream) as f:
        events = [json.loads(line) for line in f]
    tune_events = [e for e in events if e["kind"] == "tune"]
    phases = {e["phase"] for e in tune_events}
    assert {"result", "dispatch"} <= phases
    result = next(e for e in tune_events if e["phase"] == "result")
    assert result["kernel"] == "fused_layer_norm"
    assert result["best_ms"] <= result["default_ms"]
    assert result["stored"] is True
    hit = next(e for e in tune_events if e["phase"] == "dispatch")
    assert hit["hit"] is True and hit["config"]


# -- CLI ----------------------------------------------------------------------

def test_cli_tune_show_and_offline_refusal(tune_cache, capsys):
    rc = tune_main(["kernel", "fused_layer_norm", "--interpret",
                    "--cache", tune_cache, "--iters", "1", "--reps", "1",
                    "--shape", "n1=64,n2=128"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "persisted to" in out and "tuned" in out

    rc = tune_main(["show", "--cache", tune_cache])
    out = capsys.readouterr().out
    assert rc == 0 and "fused_layer_norm" in out and "r64_w128_i4" in out

    rc = tune_main(["show", "--cache", tune_cache, "--json"])
    rows = json.loads(capsys.readouterr().out)
    assert rows and rows[0]["kernel"] == "fused_layer_norm"

    if jax.default_backend() != "tpu":
        rc = tune_main(["kernel", "fused_layer_norm", "--cache",
                        tune_cache])
        assert rc == 2
        assert "only runs on TPU" in capsys.readouterr().err


def test_cli_ledger_rejects_shape(tune_cache, tmp_path, capsys):
    ledger = tmp_path / "ledger.json"
    ledger.write_text(json.dumps({"regions": []}))
    rc = tune_main(["ledger", str(ledger), "--interpret",
                    "--cache", tune_cache, "--shape", "rows=64"])
    assert rc == 2
    assert "--shape applies to `kernel NAME`" in capsys.readouterr().err


def test_cli_prune_drops_stale_versions(tune_cache, capsys):
    from apex_tpu.normalization.fused_layer_norm import TUNE_VERSION
    store.put("fused_layer_norm", TUNE_VERSION + 1, "b1",
              {"row_block": 16}, path=tune_cache)      # stale (future)
    store.put("fused_layer_norm", TUNE_VERSION, "b2",
              {"row_block": 16}, path=tune_cache)      # current
    rc = tune_main(["prune", "--cache", tune_cache])
    assert rc == 0
    assert "pruned 1" in capsys.readouterr().out
    _fresh_reload(tune_cache)
    assert [e["bucket"] for e in store.entries(tune_cache)] == ["b2"]


def test_cli_ledger_driven(tune_cache, tmp_path, capsys):
    ledger = tmp_path / "ledger.json"
    ledger.write_text(json.dumps({"regions": [
        {"region": "attention", "bound": "compute", "modeled_ms": 5.0},
        {"region": "layer_norm", "bound": "memory", "modeled_ms": 2.0}]}))
    # tune only the two cheapest kernels through the ledger path to keep
    # the CPU run fast: restrict via monkeypatched registry listing
    specs = [registry.get_spec("fused_layer_norm"),
             registry.get_spec("xentropy")]
    results = measure.tune_from_ledger(
        json.loads(ledger.read_text()), specs=specs, interpret=True,
        iters=1, reps=1, path=tune_cache)
    assert {r.kernel for r in results} == {"fused_layer_norm", "xentropy"}
    ln = next(r for r in results if r.kernel == "fused_layer_norm")
    assert ln.bound == "memory"          # the ledger verdict, not kind
    assert len(store.entries(tune_cache)) == 2
