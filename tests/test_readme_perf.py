"""Fast-gate drift test: the README perf table must match BENCH_EXTRA.json.

VERDICT r3 and r4 both caught hand-edited README numbers drifting from the
shipped bench artifact; the table is now generated
(``tools/gen_readme_perf.py``) and this test fails whenever the committed
README block and the committed artifact disagree.
"""

import json
import os
import subprocess
import sys

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


def test_readme_perf_table_matches_artifact():
    from tools.gen_readme_perf import update
    assert update(check=True), (
        "README perf table drifted from BENCH_EXTRA.json — regenerate with "
        "python tools/gen_readme_perf.py")


def test_generator_cli_check_mode():
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "gen_readme_perf.py"),
         "--check"], capture_output=True, text=True, cwd=ROOT)
    assert r.returncode == 0, r.stderr


def test_render_tolerates_missing_fields():
    """A partial artifact (CPU smoke, early round) must render, not crash."""
    from tools.gen_readme_perf import render
    out = render({"resnet50": {}, "examples": {}})
    assert "| Metric | Value |" in out
