"""contrib tests — label-smoothing xentropy vs pure-jnp references (reference
contrib/test/test_label_smoothing.py:10-28 pattern: fused vs two torch
references, fwd+bwd) and GroupBN NHWC semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from apex_tpu.contrib.xentropy import (SoftmaxCrossEntropyLoss,
                                       softmax_cross_entropy_loss)
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC


def _raw_reference(x, target, padding_idx, smoothing):
    """reference label_smoothing_raw (test_label_smoothing.py:10-18)."""
    logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, target[:, None], axis=-1)[:, 0]
    smooth = -jnp.mean(logp, axis=-1)
    loss = (1.0 - smoothing) * nll + smoothing * smooth
    return jnp.where(target == padding_idx, 0.0, loss)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_xentropy_forward_matches_reference(smoothing, dtype):
    rng = np.random.RandomState(0)
    n, h = 64, 512
    x = jnp.asarray(rng.randn(n, h), dtype)
    labels = jnp.asarray(rng.randint(0, h, n))
    labels = labels.at[::6].set(0)   # padding hits (reference: 1/6 padded)
    got = softmax_cross_entropy_loss(x, labels, smoothing, padding_idx=0)
    want = _raw_reference(x, labels, 0, smoothing)
    tol = 1e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=tol)
    # padded rows exactly zero
    np.testing.assert_array_equal(np.asarray(got[::6]), 0.0)


def test_xentropy_backward_matches_autodiff_reference():
    rng = np.random.RandomState(1)
    n, h = 32, 128
    x = jnp.asarray(rng.randn(n, h), jnp.float32)
    labels = jnp.asarray(rng.randint(1, h, n)).at[::5].set(0)

    def fused(xx):
        return jnp.sum(softmax_cross_entropy_loss(xx, labels, 0.1, 0))

    def ref(xx):
        return jnp.sum(_raw_reference(xx, labels, 0, 0.1))

    g_fused = jax.grad(fused)(x)
    g_ref = jax.grad(ref)(x)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               atol=1e-5)
    # padded rows give zero gradient
    np.testing.assert_array_equal(np.asarray(g_fused[::5]), 0.0)


def test_xentropy_apply_interface_and_jit():
    x = jnp.ones((8, 16))
    labels = jnp.asarray(np.arange(8) % 16)
    out = jax.jit(lambda a, b: SoftmaxCrossEntropyLoss.apply(a, b, 0.1, -1))(
        x, labels)
    assert out.shape == (8,)
    np.testing.assert_allclose(np.asarray(out), np.log(16), atol=1e-5)


def test_groupbn_local_when_group_1():
    model = BatchNorm2d_NHWC(num_features=4)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 5, 5, 4), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    y, _ = model.apply(variables, x, mutable=["batch_stats"])
    yf = np.asarray(y).reshape(-1, 4)
    np.testing.assert_allclose(yf.mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(yf.std(0), 1.0, atol=1e-2)


def test_groupbn_fuse_relu_and_z_add():
    model = BatchNorm2d_NHWC(num_features=4, fuse_relu=True)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 5, 5, 4), jnp.float32)
    z = jnp.asarray(np.random.RandomState(1).randn(2, 5, 5, 4), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, z)
    y, _ = model.apply(variables, x, z, mutable=["batch_stats"])
    assert float(jnp.min(y)) >= 0.0   # relu applied after the z add


def test_groupbn_validation_errors():
    x = jnp.ones((2, 4, 4, 4))
    with pytest.raises(ValueError, match="axis_name"):
        BatchNorm2d_NHWC(num_features=4, bn_group=4, world_size=8).init(
            jax.random.PRNGKey(0), x)
    with pytest.raises(ValueError, match="divisible"):
        BatchNorm2d_NHWC(num_features=4, bn_group=4, world_size=6,
                         axis_name="data").init(jax.random.PRNGKey(0), x)


# -- tier parity (ISSUE 7 satellite): the REAL pallas kernels, interpret
# mode on CPU, vs the _fwd_ref/_bwd_ref oracles -------------------------------

from apex_tpu.contrib.xentropy import (_bwd_pallas, _bwd_ref, _fwd_pallas,
                                       _fwd_ref)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xentropy_pallas_interpret_forward_parity(smoothing):
    rng = np.random.RandomState(2)
    n, h = 48, 256
    x = jnp.asarray(rng.randn(n, h), jnp.float32)
    labels = jnp.asarray(rng.randint(0, h, n), jnp.int32)
    loss_k, mlse_k = _fwd_pallas(x, labels, smoothing, interpret=True)
    loss_r, mlse_r = _fwd_ref(x, labels, smoothing)
    np.testing.assert_allclose(np.asarray(loss_k), np.asarray(loss_r),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(mlse_k), np.asarray(mlse_r),
                               atol=1e-5)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xentropy_pallas_interpret_backward_parity(smoothing):
    """Kernel-vs-reference grad parity including the padding corner: the
    custom VJP masks padded rows' incoming grads BEFORE the kernel, so
    the kernel itself is exercised with exactly that masked input."""
    rng = np.random.RandomState(3)
    n, h = 40, 128
    padding_idx = 0
    x = jnp.asarray(rng.randn(n, h), jnp.float32)
    labels = jnp.asarray(rng.randint(1, h, n), jnp.int32)
    labels = labels.at[::5].set(padding_idx)         # padded rows
    _, mlse = _fwd_ref(x, labels, smoothing)
    g = jnp.asarray(rng.rand(n), jnp.float32)
    g = jnp.where(labels == padding_idx, 0.0, g)     # the vjp's mask
    dx_k = _bwd_pallas(g, x, mlse, labels, smoothing, interpret=True)
    dx_r = _bwd_ref(g, x, mlse, labels, smoothing)
    np.testing.assert_allclose(np.asarray(dx_k), np.asarray(dx_r),
                               atol=1e-5)
    # padded rows: exactly zero through the kernel too
    np.testing.assert_array_equal(np.asarray(dx_k[::5]), 0.0)


def test_groupbn_z_add_relu_matches_oracle():
    """Quantitative oracle for the fused bn(+z)+relu epilogue through
    the groupbn module (not just sign checks): batch moments computed
    independently, the whole chain in fp64-free numpy."""
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(3, 6, 6, 5), jnp.float32)
    z = jnp.asarray(rng.randn(3, 6, 6, 5), jnp.float32)
    model = BatchNorm2d_NHWC(num_features=5, fuse_relu=True)
    variables = model.init(jax.random.PRNGKey(0), x, z)
    y, _ = model.apply(variables, x, z, mutable=["batch_stats"])
    xf = np.asarray(x).reshape(-1, 5)
    mean, var = xf.mean(0), xf.var(0)
    want = np.maximum(
        (np.asarray(x) - mean) / np.sqrt(var + 1e-5) + np.asarray(z), 0.0)
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-4)


def test_groupbn_epilogue_pallas_interpret_parity():
    """The groupbn elementwise tail IS normalization.bn_relu_residual;
    tier parity of that kernel (interpret mode) against its reference,
    z-residual corner included, through fwd and grads."""
    from apex_tpu.normalization.fused_bn_act import (bn_act_epilogue_ref,
                                                     bn_relu_residual)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(2, 4, 4, 8), jnp.float32)
    z = jnp.asarray(rng.randn(2, 4, 4, 8), jnp.float32)
    mean = jnp.asarray(rng.randn(8), jnp.float32)
    invstd = jnp.asarray(np.abs(rng.randn(8)) + 0.3, jnp.float32)
    w = jnp.asarray(rng.randn(8), jnp.float32)
    b = jnp.asarray(rng.randn(8), jnp.float32)

    for zz in (z, None):
        got = bn_relu_residual(x, mean, invstd, w, b, z=zz, relu=True,
                               interpret=True)
        want = bn_act_epilogue_ref(x, mean, invstd, w, b, z=zz, relu=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)

    def loss(interp, *operands):
        return jnp.sum(bn_relu_residual(*operands, z=z, relu=True,
                                        interpret=interp) ** 2)

    g_k = jax.grad(lambda *o: loss(True, *o), argnums=(0, 1, 2, 3, 4))(
        x, mean, invstd, w, b)
    g_r = jax.grad(lambda *o: loss(False, *o), argnums=(0, 1, 2, 3, 4))(
        x, mean, invstd, w, b)
    for a, r in zip(g_k, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=1e-4, rtol=1e-4)


def test_groupbn_bn_group_sync_on_mesh():
    """bn_group=4 on an 8-replica mesh: stats shared within each half."""
    mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("data",))
    model = BatchNorm2d_NHWC(num_features=3, bn_group=4, axis_name="data",
                             world_size=8)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8 * 2, 4, 4, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x[:2])

    def fwd(xs):
        y, _ = model.apply(variables, xs, mutable=["batch_stats"])
        return y

    y = jax.jit(shard_map(fwd, mesh=mesh, in_specs=P("data"),
                          out_specs=P("data")))(x)
    # Oracle: normalize each half-batch (ranks 0-3 see x[:8], ranks 4-7 x[8:])
    for lo, hi in ((0, 8), (8, 16)):
        seg = np.asarray(x[lo:hi]).reshape(-1, 3)
        mean, var = seg.mean(0), seg.var(0)
        want = (np.asarray(x[lo:hi]) - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(y[lo:hi]), want, atol=1e-4)
