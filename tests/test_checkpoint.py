"""Checkpoint/resume tests — the reference's bitwise-resume gate
(tests/L0/run_amp/test_checkpointing.py:28-300): save mid-training, restore,
continue, and require IDENTICAL trajectories — plus the v2 elastic
engine (ISSUE 9): async sharded CheckpointManager, manifest validation
with newest-valid fallback, retention, per-host shard merge, device
placement onto committed shardings, and zero1 flat-bucket resharding
across shard counts."""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import checkpoint as ckpt
from apex_tpu import training
from apex_tpu.checkpoint import (CheckpointError, CheckpointManager,
                                 latest_checkpoint, load_checkpoint,
                                 load_checkpoint_dir, save_checkpoint)
from apex_tpu.training import make_train_step


def _setup():
    params = {"dense": {"kernel": jnp.ones((6, 4), jnp.float32) * 0.3,
                        "bias": jnp.zeros((4,), jnp.float32)}}

    def loss_fn(p, batch):
        x, y = batch
        out = x @ p["dense"]["kernel"].astype(x.dtype) + p["dense"]["bias"]
        return jnp.mean((out.astype(jnp.float32) - y) ** 2)

    tx = training.adam(lr=1e-2)
    return params, loss_fn, tx


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    return [(jnp.asarray(rng.randn(8, 6), jnp.float32),
             jnp.asarray(rng.randn(8, 4), jnp.float32)) for _ in range(n)]


def test_bitwise_resume(tmp_path):
    params, loss_fn, tx = _setup()
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2",
                                       loss_scale="dynamic",
                                       keep_batchnorm_fp32=False)
    step = jax.jit(step_fn)
    batches = _batches(10)

    # Continuous run.
    state = init_fn(params)
    for b in batches:
        state, _ = step(state, b)
    final_cont = jax.device_get(state.params)

    # Interrupted run: 5 steps, checkpoint, restore, 5 more.
    state = init_fn(params)
    for b in batches[:5]:
        state, _ = step(state, b)
    ck = str(tmp_path / "ckpt.npz")
    save_checkpoint(ck, state, step=5)
    template = init_fn(params)
    restored, _, extra = load_checkpoint(ck, template)
    assert int(extra["step"]) == 5
    for b in batches[5:]:
        restored, _ = step(restored, b)
    final_resumed = jax.device_get(restored.params)

    for a, b in zip(jax.tree_util.tree_leaves(final_cont),
                    jax.tree_util.tree_leaves(final_resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16_leaves_roundtrip(tmp_path):
    """Regression: bf16 arrays survive npz (stored as uint16 bits)."""
    state = {"w": jnp.asarray([[1.5, -2.0]], jnp.bfloat16),
             "b": jnp.zeros((2,), jnp.float32)}
    ck = str(tmp_path / "bf16.npz")
    save_checkpoint(ck, state)
    restored, _, _ = load_checkpoint(ck, state)
    assert restored["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored["w"], np.float32),
        np.asarray(state["w"], np.float32))


def test_o3_checkpoint_recoverable(tmp_path):
    """O3 (bf16 storage) runs must restore from their own checkpoints."""
    params, loss_fn, tx = _setup()
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O3",
                                       keep_batchnorm_fp32=False)
    state = init_fn(params)
    state, _ = jax.jit(step_fn)(state, _batches(1)[0])
    ck = str(tmp_path / "o3.npz")
    save_checkpoint(ck, state)
    restored, _, _ = load_checkpoint(ck, init_fn(params))
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))


def test_scaler_state_roundtrips(tmp_path):
    """Loss-scale decrease must survive a checkpoint (reference
    test_checkpointing 'restore after scale drop')."""
    params, loss_fn, tx = _setup()
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2",
                                       loss_scale="dynamic",
                                       keep_batchnorm_fp32=False)
    step = jax.jit(step_fn)
    state = init_fn(params)
    bad = (jnp.full((8, 6), jnp.inf, jnp.float32),
           jnp.zeros((8, 4), jnp.float32))
    state, m = step(state, bad)
    assert float(m["loss_scale"]) == 2.0 ** 15
    ck = str(tmp_path / "scaler.npz")
    save_checkpoint(ck, state)
    restored, _, _ = load_checkpoint(ck, init_fn(params))
    assert float(restored.scaler.loss_scale) == 2.0 ** 15
    assert int(restored.scaler.unskipped) == int(state.scaler.unskipped)


def test_dtype_mismatch_rejected(tmp_path):
    """Restoring with a different opt_level (different storage dtypes) must
    fail loudly, mirroring the same-opt-level rule."""
    params, loss_fn, tx = _setup()
    init2, _ = make_train_step(loss_fn, tx, opt_level="O2",
                               keep_batchnorm_fp32=False)
    init3, _ = make_train_step(loss_fn, tx, opt_level="O3",
                               keep_batchnorm_fp32=False)
    ck = str(tmp_path / "o2.npz")
    save_checkpoint(ck, init2(params))          # fp32 masters
    with pytest.raises(ValueError, match="opt_level"):
        load_checkpoint(ck, init3(params))      # bf16 storage template


def test_missing_leaf_rejected(tmp_path):
    params, loss_fn, tx = _setup()
    init_fn, _ = make_train_step(loss_fn, tx, opt_level="O0")
    ck = str(tmp_path / "x.npz")
    save_checkpoint(ck, {"only": jnp.ones((2,))})
    with pytest.raises(KeyError):
        load_checkpoint(ck, init_fn(params))


def test_amp_state_dict_roundtrip(tmp_path):
    from apex_tpu import amp
    from apex_tpu.optimizers import FusedSGD
    params = {"w": jnp.ones((3,), jnp.float32)}
    opt = FusedSGD(params, lr=0.1)
    params, opt = amp.initialize(params, opt, opt_level="O2",
                                 loss_scale="dynamic", verbosity=0)
    sd = amp.state_dict()
    ck = str(tmp_path / "amp.npz")
    save_checkpoint(ck, {"dummy": jnp.zeros(())}, amp_state=sd)
    _, amp_sd, _ = load_checkpoint(ck, {"dummy": jnp.zeros(())})
    assert any("loss_scale" in k for k in amp_sd)
    amp.load_state_dict({k: v for k, v in sd.items()})
    amp.shutdown()


# -- satellite fixes: extras round-trip + device placement --------------------

def test_extras_roundtrip_python_types(tmp_path):
    """ISSUE 9 satellite: str/bool/None/dict extras used to crash
    (``np.asarray(None)`` is an object array) or munge (np scalar types
    on reload); now they round-trip with python types intact while
    numeric scalars keep the historical array path."""
    ck = str(tmp_path / "x.npz")
    save_checkpoint(ck, {"w": jnp.zeros(())}, step=7, lr=0.1,
                    run_name="imagenet-a", resumed=True, note=None,
                    sched={"warmup": 5, "decay": "cosine"})
    _, _, extra = load_checkpoint(ck, {"w": jnp.zeros(())})
    assert int(extra["step"]) == 7
    assert float(extra["lr"]) == pytest.approx(0.1)
    assert extra["run_name"] == "imagenet-a" and isinstance(
        extra["run_name"], str)
    assert extra["resumed"] is True
    assert extra["note"] is None
    assert extra["sched"] == {"warmup": 5, "decay": "cosine"}


def test_extras_reject_unserializable():
    with pytest.raises(TypeError, match="not serializable|object dtype"):
        save_checkpoint("/dev/null", {"w": jnp.zeros(())},
                        bad=object())


def test_load_places_leaves_on_template_sharding(tmp_path):
    """ISSUE 9 satellite regression: restored leaves used to land as
    host numpy regardless of the template's sharding — resuming on a
    mesh silently un-sharded the state.  Committed template shardings
    must be honored leaf-by-leaf."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices("cpu")[:4]), ("data",))
    sh = NamedSharding(mesh, P("data"))
    template = {"w": jax.device_put(jnp.arange(16.0), sh),
                "s": jnp.float32(3.0)}          # uncommitted scalar
    ck = str(tmp_path / "sharded.npz")
    save_checkpoint(ck, template)
    restored, _, _ = load_checkpoint(ck, template)
    assert restored["w"].sharding == sh
    assert restored["w"].committed
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(16.0))


# -- v2: CheckpointManager ----------------------------------------------------

def _state():
    return {"w": jnp.asarray(np.arange(24.0, dtype=np.float32)),
            "b": jnp.ones((3,), jnp.bfloat16),
            "n": jnp.asarray(5, jnp.int32)}


def test_manager_async_save_restore_roundtrip(tmp_path):
    state = _state()
    with CheckpointManager(str(tmp_path), every_steps=4) as mgr:
        assert not mgr.maybe_save(0, state)        # cadence anchors at 0
        assert not mgr.maybe_save(2, state)        # under the cadence
        assert mgr.maybe_save(4, state, loader_state={"cursor": 4},
                              note="mid")
        assert not mgr.maybe_save(6, state)
        mgr.wait()
        restored = mgr.restore(like=state)
    assert restored.step == 4
    assert restored.loader_state == {"cursor": 4}
    assert restored.extra["note"] == "mid"
    assert restored.run_id
    for k in state:
        np.testing.assert_array_equal(
            np.asarray(restored.state[k], np.float32),
            np.asarray(state[k], np.float32))
        assert restored.state[k].dtype == state[k].dtype


def test_manager_sync_mode_and_retention(tmp_path):
    state = _state()
    with CheckpointManager(str(tmp_path), keep=2,
                           async_write=False) as mgr:
        for step in (1, 2, 3, 4):
            mgr.save(step, state)
    names = sorted(os.path.basename(p) for p in
                   glob.glob(str(tmp_path / "step_*")))
    assert names == ["step_00000003", "step_00000004"]


def test_corrupt_newest_falls_back_to_previous_valid(tmp_path):
    """ISSUE 9 acceptance: corrupted/truncated shard files and
    mid-write crashes (.tmp left behind) fail cleanly to the newest
    VALID checkpoint."""
    state = _state()
    with CheckpointManager(str(tmp_path), async_write=False) as mgr:
        mgr.save(5, state)
        mgr.save(10, state)
    # truncate the newest shard (torn write)
    newest = latest_checkpoint(str(tmp_path))
    assert newest.endswith("step_00000010")
    shard = glob.glob(os.path.join(newest, "shard_*.npz"))[0]
    with open(shard, "r+b") as f:
        f.truncate(16)
    # plus .tmp debris as a mid-write crash would leave
    with open(shard + ".tmp", "wb") as f:
        f.write(b"partial")
    restored = load_checkpoint_dir(str(tmp_path), state)
    assert restored.step == 5


def test_missing_manifest_part_is_invalid(tmp_path):
    state = _state()
    m0 = CheckpointManager(str(tmp_path), procs=(0, 2))
    m1 = CheckpointManager(str(tmp_path), procs=(1, 2))
    m0.save(3, state, block=True)
    m1.save(3, state, block=True)
    m0.save(6, state, block=True)       # host 1's part never lands
    assert latest_checkpoint(str(tmp_path)).endswith("step_00000003")
    restored = load_checkpoint_dir(str(tmp_path), state)
    assert restored.step == 3
    m0.close(), m1.close()


def test_per_host_sharded_layout_merges(tmp_path):
    """Each host writes only the leaves it owns; the reader needs every
    part and reassembles the full tree."""
    state = _state()
    m0 = CheckpointManager(str(tmp_path), procs=(0, 2), run_id="r1")
    m1 = CheckpointManager(str(tmp_path), procs=(1, 2), run_id="r1")
    m0.save(7, state, block=True, tag="host0-extra")
    m1.save(7, state, block=True)
    step_dir = latest_checkpoint(str(tmp_path))
    shards = sorted(glob.glob(os.path.join(step_dir, "shard_*.npz")))
    assert len(shards) == 2
    # ownership is a real split: neither shard holds the whole tree
    with np.load(shards[0]) as a, np.load(shards[1]) as b:
        keys_a = [k for k in a.files if not k.startswith("__")]
        keys_b = [k for k in b.files if not k.startswith("__")]
    assert keys_a and keys_b and not set(keys_a) & set(keys_b)
    restored = load_checkpoint_dir(str(tmp_path), state)
    assert restored.extra["tag"] == "host0-extra"
    assert restored.run_id == "r1"
    for k in state:
        np.testing.assert_array_equal(
            np.asarray(restored.state[k], np.float32),
            np.asarray(state[k], np.float32))
    m0.close(), m1.close()


def test_manifest_checksum_catches_bit_corruption(tmp_path):
    state = _state()
    with CheckpointManager(str(tmp_path), async_write=False) as mgr:
        mgr.save(1, state)
    shard = glob.glob(str(tmp_path / "step_*" / "shard_*.npz"))[0]
    data = bytearray(open(shard, "rb").read())
    data[len(data) // 2] ^= 0xFF                    # flip one byte
    open(shard, "wb").write(bytes(data))
    assert latest_checkpoint(str(tmp_path)) is None
    with pytest.raises(CheckpointError, match="no valid checkpoint"):
        load_checkpoint_dir(str(tmp_path), state)


def test_writer_error_surfaces_on_caller(tmp_path):
    state = _state()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(1, state)
    mgr.wait()
    # break the directory out from under the writer
    import shutil
    shutil.rmtree(str(tmp_path / "ck"))
    open(str(tmp_path / "ck"), "w").close()     # a FILE where a dir was
    mgr.save(2, state)
    with pytest.raises(CheckpointError, match="writer failed"):
        mgr.wait()


def test_manager_emits_checkpoint_telemetry(tmp_path):
    from apex_tpu import telemetry

    state = _state()
    rec = telemetry.start(str(tmp_path / "run.jsonl"))
    try:
        with CheckpointManager(str(tmp_path / "ck")) as mgr:
            mgr.save(3, state, block=True)
            mgr.restore(like=state)
    finally:
        rec.close()
        telemetry.set_recorder(None)
    events = [json.loads(line) for line in
              open(str(tmp_path / "run.jsonl")) if line.strip()]
    phases = [e.get("phase") for e in events
              if e.get("kind") == "checkpoint"]
    for want in ("snapshot", "serialize", "commit", "restore"):
        assert want in phases, phases
    # the manager adopted the active recorder's run id
    assert mgr.run_id == rec.run_id


# -- elastic resharding (zero1 bucketed) --------------------------------------

def test_zero1_bucketed_restores_at_different_shard_count(tmp_path):
    """ISSUE 9 acceptance: a zero1 ``bucketed=True`` checkpoint saved at
    shard count N restores at M != N on the CPU mesh — the manifest's
    bucket layout lets the loader re-slice each padded flat bucket to
    its true size and re-pad for the new world; training continues."""
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.multi_tensor.buckets import (BucketStore,
                                               padded_shard_len)
    from apex_tpu.parallel.zero import zero1, zero1_partition_spec
    from apex_tpu.training import TrainState

    shard_map = jax.shard_map
    N, M = 4, 2
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(5, 7) * 0.3, jnp.float32),
              "b": jnp.zeros((3,), jnp.float32)}   # 38 elems

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + jnp.pad(p["b"], (0, 4)) - yb) ** 2)

    def make(n_shards, n_dev):
        mesh = Mesh(np.array(jax.devices("cpu")[:n_dev]), ("data",))
        tx = zero1(training.adam(1e-2), "data", num_shards=n_shards,
                   bucketed=True)
        init_fn, step_fn = make_train_step(
            loss_fn, tx, opt_level="O2", axis_name=("data",),
            reduce_grads=False)
        state = init_fn({k: jnp.asarray(v) for k, v in params.items()})
        spec = TrainState(params=P(),
                          opt_state=zero1_partition_spec(
                              state.opt_state, "data"),
                          scaler=P(), model_state=P())

        def wrapped(s, b):
            ns, m = step_fn(s, b)
            return ns, jax.tree_util.tree_map(
                lambda v: training._pmean_varying(v, ("data",)), m)

        step = jax.jit(shard_map(
            wrapped, mesh=mesh,
            in_specs=(spec, (P("data"), P("data"))),
            out_specs=(spec, P())))
        return state, step

    def batch(n_dev, seed):
        r = np.random.RandomState(seed)
        return (jnp.asarray(r.randn(4 * n_dev, 5), jnp.float32),
                jnp.asarray(r.randn(4 * n_dev, 7) * 0.1, jnp.float32))

    # train at N, checkpoint with the bucket layout
    state_n, step_n = make(N, N)
    for s in range(3):
        state_n, _ = step_n(state_n, batch(N, s))
    store = BucketStore(jax.tree_util.tree_map(
        lambda l: jnp.asarray(l, jnp.float32), params))
    with CheckpointManager(str(tmp_path)) as mgr:
        mgr.save(3, state_n, block=True,
                 bucket_layout=ckpt.bucket_layout(store, N))

    # restore into the M-shard template: padded lengths differ
    state_m, step_m = make(M, M)
    old_len = padded_shard_len(38, N)
    new_len = padded_shard_len(38, M)
    assert old_len != new_len                     # 40 vs 38
    restored = load_checkpoint_dir(str(tmp_path), state_m)
    assert restored.step == 3
    # params are replicated — bitwise across worlds
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(restored.state.params[k]),
            np.asarray(state_n.params[k]))
    # moments: the TRUE (unpadded) prefix survives the reshard exactly
    n_inner = jax.tree_util.tree_leaves(state_n.opt_state)
    m_inner = jax.tree_util.tree_leaves(restored.state.opt_state)
    assert len(n_inner) == len(m_inner)
    for a, b in zip(n_inner, m_inner):
        a, b = np.asarray(a), np.asarray(b)
        if a.ndim == 1 and a.shape != b.shape:
            assert a.shape == (old_len,) and b.shape == (new_len,)
            np.testing.assert_array_equal(a[:38], b[:38])
        else:
            np.testing.assert_array_equal(a, b)
    # and the resumed world actually trains
    state2 = restored.state
    for s in range(2):
        state2, metrics = step_m(state2, batch(M, 10 + s))
    assert np.isfinite(float(jnp.ravel(metrics["loss"])[0]))
    assert not np.array_equal(np.asarray(state2.params["w"]),
                              np.asarray(restored.state.params["w"]))


def test_mesh_fsdp_checkpoint_reshards_across_mesh_sizes(tmp_path):
    """ISSUE 12 satellite: the elastic N->M reshard covers MESH-sharded
    (FSDP-axis) checkpoints, not just zero1 flat buckets — a ZeRO-3
    checkpoint saved on a 4-way mesh restores onto a 2-way mesh (padded
    flat lengths differ: 40 vs 38) BITWISE equal to an exact host-side
    repack of the same state, and training continues identically."""
    from apex_tpu.multi_tensor.buckets import padded_shard_len
    from apex_tpu.parallel import mesh as M

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(5, 7) * 0.3, jnp.float32),
              "b": jnp.zeros((3,), jnp.float32)}   # 38 elems

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + jnp.pad(p["b"], (0, 4)) - yb) ** 2)

    def make(fsdp):
        plan = M.MeshPlan(dp=1, fsdp=fsdp,
                          devices=jax.devices("cpu")[:fsdp])
        ms = M.make_mesh_train_step(loss_fn, training.adam(1e-2), plan,
                                    zero=3, opt_level="O2")
        state = ms.init(params)
        return plan, ms, state, ms.jit_step(state, donate=False)

    def batch(plan, seed):
        r = np.random.RandomState(seed)
        return plan.device_put_batch(
            (jnp.asarray(r.randn(4 * plan.fsdp, 5), jnp.float32),
             jnp.asarray(r.randn(4 * plan.fsdp, 7) * 0.1, jnp.float32)))

    # train on the 4-way mesh, checkpoint with the bucket layout
    plan4, ms4, state4, step4 = make(4)
    for s in range(3):
        state4, _ = step4(state4, batch(plan4, s))
    with CheckpointManager(str(tmp_path)) as mgr:
        mgr.save(3, state4, block=True,
                 bucket_layout=plan4.bucket_layout(ms4.store()))

    # restore onto the 2-way mesh: every padded flat bucket re-slices
    plan2, ms2, state2_tpl, step2 = make(2)
    old_len, new_len = padded_shard_len(38, 4), padded_shard_len(38, 2)
    assert (old_len, new_len) == (40, 38)
    restored = load_checkpoint_dir(str(tmp_path), state2_tpl)
    assert restored.step == 3

    # oracle: the exact host-side repack of the 4-way state
    def repack(leaf, tpl):
        a = np.asarray(jax.device_get(leaf))
        if a.ndim == 1 and a.shape != tuple(tpl.shape):
            a = a[:38]
            a = np.concatenate(
                [a, np.zeros((tpl.shape[0] - a.shape[0],), a.dtype)])
        return a

    direct = jax.tree_util.tree_map(repack, state4, state2_tpl)
    for got, want, tpl in zip(
            jax.tree_util.tree_leaves(restored.state),
            jax.tree_util.tree_leaves(direct),
            jax.tree_util.tree_leaves(state2_tpl)):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(got)), np.asarray(want))
        # and each leaf landed back SHARDED on the 2-way mesh
        assert got.sharding == tpl.sharding

    # training continues on the resharded state — and is bitwise equal
    # to continuing from the direct repack (reshard-on-read injects
    # nothing)
    state_r = restored.state
    state_d = jax.tree_util.tree_map(
        lambda a, tpl: jax.device_put(a, tpl.sharding), direct, state2_tpl)
    for s in range(2):
        b = batch(plan2, 10 + s)
        state_r, m_r = step2(state_r, b)
        state_d, m_d = step2(state_d, b)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(m_r["loss"])),
            np.asarray(jax.device_get(m_d["loss"])))
    for a, b in zip(jax.tree_util.tree_leaves(state_r.params),
                    jax.tree_util.tree_leaves(state_d.params)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(jax.device_get(b)))
    assert np.isfinite(float(np.ravel(jax.device_get(m_r["loss"]))[0]))
