"""Checkpoint/resume tests — the reference's bitwise-resume gate
(tests/L0/run_amp/test_checkpointing.py:28-300): save mid-training, restore,
continue, and require IDENTICAL trajectories."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import training
from apex_tpu.checkpoint import load_checkpoint, save_checkpoint
from apex_tpu.training import make_train_step


def _setup():
    params = {"dense": {"kernel": jnp.ones((6, 4), jnp.float32) * 0.3,
                        "bias": jnp.zeros((4,), jnp.float32)}}

    def loss_fn(p, batch):
        x, y = batch
        out = x @ p["dense"]["kernel"].astype(x.dtype) + p["dense"]["bias"]
        return jnp.mean((out.astype(jnp.float32) - y) ** 2)

    tx = training.adam(lr=1e-2)
    return params, loss_fn, tx


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    return [(jnp.asarray(rng.randn(8, 6), jnp.float32),
             jnp.asarray(rng.randn(8, 4), jnp.float32)) for _ in range(n)]


def test_bitwise_resume(tmp_path):
    params, loss_fn, tx = _setup()
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2",
                                       loss_scale="dynamic",
                                       keep_batchnorm_fp32=False)
    step = jax.jit(step_fn)
    batches = _batches(10)

    # Continuous run.
    state = init_fn(params)
    for b in batches:
        state, _ = step(state, b)
    final_cont = jax.device_get(state.params)

    # Interrupted run: 5 steps, checkpoint, restore, 5 more.
    state = init_fn(params)
    for b in batches[:5]:
        state, _ = step(state, b)
    ck = str(tmp_path / "ckpt.npz")
    save_checkpoint(ck, state, step=5)
    template = init_fn(params)
    restored, _, extra = load_checkpoint(ck, template)
    assert int(extra["step"]) == 5
    for b in batches[5:]:
        restored, _ = step(restored, b)
    final_resumed = jax.device_get(restored.params)

    for a, b in zip(jax.tree_util.tree_leaves(final_cont),
                    jax.tree_util.tree_leaves(final_resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16_leaves_roundtrip(tmp_path):
    """Regression: bf16 arrays survive npz (stored as uint16 bits)."""
    state = {"w": jnp.asarray([[1.5, -2.0]], jnp.bfloat16),
             "b": jnp.zeros((2,), jnp.float32)}
    ck = str(tmp_path / "bf16.npz")
    save_checkpoint(ck, state)
    restored, _, _ = load_checkpoint(ck, state)
    assert restored["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored["w"], np.float32),
        np.asarray(state["w"], np.float32))


def test_o3_checkpoint_recoverable(tmp_path):
    """O3 (bf16 storage) runs must restore from their own checkpoints."""
    params, loss_fn, tx = _setup()
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O3",
                                       keep_batchnorm_fp32=False)
    state = init_fn(params)
    state, _ = jax.jit(step_fn)(state, _batches(1)[0])
    ck = str(tmp_path / "o3.npz")
    save_checkpoint(ck, state)
    restored, _, _ = load_checkpoint(ck, init_fn(params))
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))


def test_scaler_state_roundtrips(tmp_path):
    """Loss-scale decrease must survive a checkpoint (reference
    test_checkpointing 'restore after scale drop')."""
    params, loss_fn, tx = _setup()
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2",
                                       loss_scale="dynamic",
                                       keep_batchnorm_fp32=False)
    step = jax.jit(step_fn)
    state = init_fn(params)
    bad = (jnp.full((8, 6), jnp.inf, jnp.float32),
           jnp.zeros((8, 4), jnp.float32))
    state, m = step(state, bad)
    assert float(m["loss_scale"]) == 2.0 ** 15
    ck = str(tmp_path / "scaler.npz")
    save_checkpoint(ck, state)
    restored, _, _ = load_checkpoint(ck, init_fn(params))
    assert float(restored.scaler.loss_scale) == 2.0 ** 15
    assert int(restored.scaler.unskipped) == int(state.scaler.unskipped)


def test_dtype_mismatch_rejected(tmp_path):
    """Restoring with a different opt_level (different storage dtypes) must
    fail loudly, mirroring the same-opt-level rule."""
    params, loss_fn, tx = _setup()
    init2, _ = make_train_step(loss_fn, tx, opt_level="O2",
                               keep_batchnorm_fp32=False)
    init3, _ = make_train_step(loss_fn, tx, opt_level="O3",
                               keep_batchnorm_fp32=False)
    ck = str(tmp_path / "o2.npz")
    save_checkpoint(ck, init2(params))          # fp32 masters
    with pytest.raises(ValueError, match="opt_level"):
        load_checkpoint(ck, init3(params))      # bf16 storage template


def test_missing_leaf_rejected(tmp_path):
    params, loss_fn, tx = _setup()
    init_fn, _ = make_train_step(loss_fn, tx, opt_level="O0")
    ck = str(tmp_path / "x.npz")
    save_checkpoint(ck, {"only": jnp.ones((2,))})
    with pytest.raises(KeyError):
        load_checkpoint(ck, init_fn(params))


def test_amp_state_dict_roundtrip(tmp_path):
    from apex_tpu import amp
    from apex_tpu.optimizers import FusedSGD
    params = {"w": jnp.ones((3,), jnp.float32)}
    opt = FusedSGD(params, lr=0.1)
    params, opt = amp.initialize(params, opt, opt_level="O2",
                                 loss_scale="dynamic", verbosity=0)
    sd = amp.state_dict()
    ck = str(tmp_path / "amp.npz")
    save_checkpoint(ck, {"dummy": jnp.zeros(())}, amp_state=sd)
    _, amp_sd, _ = load_checkpoint(ck, {"dummy": jnp.zeros(())})
    assert any("loss_scale" in k for k in amp_sd)
    amp.load_state_dict({k: v for k, v in sd.items()})
    amp.shutdown()
