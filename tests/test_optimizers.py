"""Fused optimizer correctness vs. reference implementations.

Mirrors reference ``tests/L0/run_optimizers/test_adam.py`` (FusedAdam vs
torch.optim within abs/rel tolerance over random steps, including reduced
precision and grad_scale) and ``test_fused_sgd.py`` skip-step semantics.
torch (CPU) provides the oracle for Adam/AdamW/SGD; LAMB/NovoGrad are checked
against straightforward numpy references of the published algorithms.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.optimizers import (FusedAdam, FusedSGD, FusedLAMB,
                                 FusedNovoGrad, functional as F)


def _rand_tree(seed, shapes=((7,), (3, 5), (64,))):
    rng = np.random.RandomState(seed)
    return {f"p{i}": jnp.asarray(rng.randn(*s).astype(np.float32))
            for i, s in enumerate(shapes)}


def _to_torch(tree):
    return [torch.nn.Parameter(torch.tensor(np.asarray(v))) for v in tree.values()]


def _assert_close(tree, tparams, atol=1e-5, rtol=1e-5):
    for (k, v), t in zip(tree.items(), tparams):
        np.testing.assert_allclose(np.asarray(v), t.detach().numpy(),
                                   atol=atol, rtol=rtol, err_msg=k)


STEPS = 5


def _run_pair(opt, topt, params, seed=0):
    rng = np.random.RandomState(seed)
    tparams = list(topt.param_groups[0]["params"])
    for _ in range(STEPS):
        grads = {k: jnp.asarray(rng.randn(*v.shape).astype(np.float32))
                 for k, v in params.items()}
        for t, (k, g) in zip(tparams, grads.items()):
            t.grad = torch.tensor(np.asarray(g))
        opt.step(grads=grads)
        topt.step()
    return opt.params, tparams


def test_fused_adam_matches_torch_adamw():
    params = _rand_tree(1)
    opt = FusedAdam(params, lr=1e-2, weight_decay=0.1, adam_w_mode=True)
    topt = torch.optim.AdamW(_to_torch(params), lr=1e-2, weight_decay=0.1,
                             eps=1e-8)
    p, tp = _run_pair(opt, topt, params)
    _assert_close(p, tp)


def test_fused_adam_l2_mode_matches_torch_adam():
    params = _rand_tree(2)
    opt = FusedAdam(params, lr=1e-2, weight_decay=0.1, adam_w_mode=False)
    topt = torch.optim.Adam(_to_torch(params), lr=1e-2, weight_decay=0.1,
                            eps=1e-8)
    p, tp = _run_pair(opt, topt, params)
    _assert_close(p, tp)


@pytest.mark.parametrize("momentum,nesterov,wd", [
    (0.0, False, 0.0), (0.9, False, 0.0), (0.9, True, 0.0), (0.9, False, 1e-2)])
def test_fused_sgd_matches_torch(momentum, nesterov, wd):
    params = _rand_tree(3)
    opt = FusedSGD(params, lr=0.1, momentum=momentum, nesterov=nesterov,
                   weight_decay=wd)
    topt = torch.optim.SGD(_to_torch(params), lr=0.1, momentum=momentum,
                           nesterov=nesterov, weight_decay=wd)
    p, tp = _run_pair(opt, topt, params)
    _assert_close(p, tp)


def _numpy_lamb_reference(params, grads_seq, lr, b1, b2, eps, wd, max_norm):
    """Direct transcription of the LAMB algorithm (stage1 global clip +
    stage2 trust ratio), independent of the implementation under test."""
    m = {k: np.zeros_like(v) for k, v in params.items()}
    v = {k: np.zeros_like(p) for k, p in params.items()}
    p = {k: np.array(x) for k, x in params.items()}
    step = 0
    for grads in grads_seq:
        step += 1
        gnorm = np.sqrt(sum(float(np.sum(g ** 2)) for g in grads.values()))
        clip = gnorm / max_norm if gnorm > max_norm else 1.0
        bc1 = 1 - b1 ** step
        bc2 = 1 - b2 ** step
        for k in p:
            g = grads[k] / clip
            m[k] = b1 * m[k] + (1 - b1) * g
            v[k] = b2 * v[k] + (1 - b2) * g * g
            upd = (m[k] / bc1) / (np.sqrt(v[k] / bc2) + eps) + wd * p[k]
            pn = np.sqrt(np.sum(p[k] ** 2))
            un = np.sqrt(np.sum(upd ** 2))
            ratio = pn / un if (pn > 0 and un > 0) else 1.0
            p[k] = p[k] - lr * ratio * upd
    return p


def test_fused_lamb_matches_numpy_reference():
    params = _rand_tree(4)
    rng = np.random.RandomState(10)
    grads_seq = [{k: rng.randn(*v.shape).astype(np.float32)
                  for k, v in params.items()} for _ in range(STEPS)]
    opt = FusedLAMB(params, lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    for grads in grads_seq:
        opt.step(grads={k: jnp.asarray(g) for k, g in grads.items()})
    expected = _numpy_lamb_reference(
        {k: np.asarray(v) for k, v in params.items()}, grads_seq,
        lr=1e-2, b1=0.9, b2=0.999, eps=1e-6, wd=0.01, max_norm=1.0)
    for k in params:
        np.testing.assert_allclose(np.asarray(opt.params[k]), expected[k],
                                   atol=1e-5, rtol=1e-5, err_msg=k)


def _numpy_novograd_reference(params, grads_seq, lr, b1, b2, eps, wd):
    m = {k: np.zeros_like(v) for k, v in params.items()}
    vnorm = {k: 0.0 for k in params}
    p = {k: np.array(x) for k, x in params.items()}
    first = True
    for grads in grads_seq:
        for k in p:
            g = grads[k]
            gn = np.sqrt(np.sum(g * g))
            vnorm[k] = gn if first else b2 * vnorm[k] + (1 - b2) * gn
            sg = g / (vnorm[k] + eps)
            m[k] = b1 * m[k] + (1 - b1) * sg
            upd = m[k] + wd * p[k]
            p[k] = p[k] - lr * upd
        first = False
    return p


def test_fused_novograd_matches_numpy_reference():
    params = _rand_tree(5)
    rng = np.random.RandomState(11)
    grads_seq = [{k: rng.randn(*v.shape).astype(np.float32)
                  for k, v in params.items()} for _ in range(STEPS)]
    opt = FusedNovoGrad(params, lr=1e-2, weight_decay=0.01,
                        grad_averaging=True, bias_correction=False)
    for grads in grads_seq:
        opt.step(grads={k: jnp.asarray(g) for k, g in grads.items()})
    expected = _numpy_novograd_reference(
        {k: np.asarray(v) for k, v in params.items()}, grads_seq,
        lr=1e-2, b1=0.95, b2=0.98, eps=1e-8, wd=0.01)
    for k in params:
        np.testing.assert_allclose(np.asarray(opt.params[k]), expected[k],
                                   atol=1e-5, rtol=1e-5, err_msg=k)


# -- functional / apply_mask (step skipping as a select) ----------------------

def test_adam_apply_mask_skips_update():
    params = _rand_tree(6)
    state = F.adam_init(params)
    grads = {k: jnp.ones_like(v) for k, v in params.items()}
    new_p, new_s = F.adam_update(grads, state, params, lr=0.1,
                                 apply_mask=jnp.asarray(False))
    for k in params:
        np.testing.assert_array_equal(np.asarray(new_p[k]),
                                      np.asarray(params[k]))
    assert int(new_s.step) == 0
    new_p2, new_s2 = F.adam_update(grads, new_s, params, lr=0.1,
                                   apply_mask=jnp.asarray(True))
    assert int(new_s2.step) == 1
    assert not np.allclose(np.asarray(new_p2["p0"]), np.asarray(params["p0"]))


def test_lr_change_does_not_recompile():
    params = _rand_tree(7)
    opt = FusedAdam(params, lr=1e-3)
    grads = {k: jnp.ones_like(v) for k, v in params.items()}
    opt.step(grads=grads)
    before = opt._jit_update._cache_size()
    opt.lr = 5e-4
    opt.step(grads=grads)
    assert opt._jit_update._cache_size() == before


def test_optimizer_state_dict_roundtrip():
    params = _rand_tree(8)
    opt = FusedAdam(params, lr=1e-2)
    grads = {k: jnp.ones_like(v) for k, v in params.items()}
    opt.step(grads=grads)
    sd = opt.state_dict()

    # A checkpoint restores model params AND optimizer state.
    opt2 = FusedAdam(jax.tree_util.tree_map(jnp.asarray,
                                            jax.device_get(opt.params)),
                     lr=1e-2)
    opt2.load_state_dict(sd)
    opt.step(grads=grads)
    opt2.step(grads=grads)
    for k in params:
        np.testing.assert_array_equal(np.asarray(opt.params[k]),
                                      np.asarray(opt2.params[k]))


# -- parameter groups (reference fused_adam.py:75-134 iterates param_groups
# with per-group lr/wd; LARC.py:71-97 absorbs per-group weight decay) --------

def test_param_groups_match_separate_optimizers():
    """Two groups with different lr/wd must step exactly like two separate
    single-group optimizers over the same subtrees."""
    decay = _rand_tree(11, shapes=((4, 3), (5,)))
    no_decay = _rand_tree(12, shapes=((3,), (2, 2)))
    grouped = FusedAdam([
        {"params": decay, "lr": 1e-2, "weight_decay": 0.1},
        {"params": no_decay, "lr": 5e-3, "weight_decay": 0.0},
    ], lr=999.0, weight_decay=999.0)   # defaults must be overridden

    ref_a = FusedAdam(decay, lr=1e-2, weight_decay=0.1)
    ref_b = FusedAdam(no_decay, lr=5e-3, weight_decay=0.0)

    for step in range(3):
        g_decay = {k: jnp.full_like(v, 0.1 * (step + 1))
                   for k, v in decay.items()}
        g_nodecay = {k: jnp.full_like(v, -0.2) for k, v in no_decay.items()}
        grouped.step(grads=[g_decay, g_nodecay])
        ref_a.step(grads=g_decay)
        ref_b.step(grads=g_nodecay)

    got_a, got_b = grouped.params
    for k in decay:
        np.testing.assert_array_equal(np.asarray(got_a[k]),
                                      np.asarray(ref_a.params[k]))
    for k in no_decay:
        np.testing.assert_array_equal(np.asarray(got_b[k]),
                                      np.asarray(ref_b.params[k]))


def test_param_groups_bert_no_decay_recipe():
    """The BERT recipe: no weight decay on bias/LayerNorm params."""
    params = {
        "dense": {"kernel": jnp.ones((4, 4)), "bias": jnp.ones((4,))},
        "ln": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
    }
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def is_no_decay(path):
        names = [getattr(p, "key", "") for p in path]
        return "bias" in names or "ln" in names

    decay = {"dense_kernel": params["dense"]["kernel"]}
    no_decay = {"dense_bias": params["dense"]["bias"],
                "ln_scale": params["ln"]["scale"],
                "ln_bias": params["ln"]["bias"]}
    assert sum(1 for p, _ in flat if is_no_decay(p)) == len(no_decay)

    opt = FusedAdam([
        {"params": decay, "weight_decay": 0.01},
        {"params": no_decay, "weight_decay": 0.0},
    ], lr=1e-3)
    grads = [{k: jnp.zeros_like(v) for k, v in decay.items()},
             {k: jnp.zeros_like(v) for k, v in no_decay.items()}]
    opt.step(grads=grads)
    new_decay, new_no_decay = opt.params
    # zero grads: only wd moves params -> decay group shrinks, no-decay frozen
    assert np.all(np.asarray(new_decay["dense_kernel"]) < 1.0)
    np.testing.assert_array_equal(np.asarray(new_no_decay["ln_scale"]),
                                  np.ones((4,)))


def test_add_param_group():
    base = _rand_tree(13, shapes=((3,),))
    extra = _rand_tree(14, shapes=((2, 2),))
    opt = FusedAdam(base, lr=1e-2)
    opt.add_param_group({"params": extra, "lr": 1e-3})
    assert len(opt.param_groups) == 2
    grads = [{k: jnp.ones_like(v) for k, v in base.items()},
             {k: jnp.ones_like(v) for k, v in extra.items()}]
    opt.step(grads=grads)
    p0, p1 = opt.params
    assert not np.allclose(np.asarray(p0["p0"]), np.asarray(base["p0"]))
    assert not np.allclose(np.asarray(p1["p0"]), np.asarray(extra["p0"]))


def test_larc_per_group_weight_decay():
    from apex_tpu.parallel import LARC
    decay = _rand_tree(15, shapes=((4,),))
    no_decay = _rand_tree(16, shapes=((4,),))
    opt = LARC(FusedSGD([
        {"params": decay, "weight_decay": 0.1},
        {"params": no_decay, "weight_decay": 0.0},
    ], lr=1e-2, momentum=0.0))
    grads = [{k: jnp.full_like(v, 0.01) for k, v in decay.items()},
             {k: jnp.full_like(v, 0.01) for k, v in no_decay.items()}]
    before = jax.device_get(opt.optim.params)
    opt.step(grads=grads)
    after = jax.device_get(opt.optim.params)
    # wd absorbed into LARC grads, restored afterwards on the group
    assert opt.optim.param_groups[0]["weight_decay"] == 0.1
    assert opt.optim.param_groups[1]["weight_decay"] == 0.0
    assert not np.allclose(after[0]["p0"], before[0]["p0"])


def test_larc_with_amp_masters_single_group():
    """Regression: LARC.step with an O2-wired (master-weights) optimizer
    built from a plain params pytree must use the canonical group list."""
    from apex_tpu import amp
    from apex_tpu.parallel import LARC
    params = {"w": jnp.ones((4,), jnp.float32)}
    opt = FusedSGD(params, lr=0.1, weight_decay=0.01)
    params, opt = amp.initialize(params, opt, opt_level="O2", verbosity=0,
                                 loss_scale=1.0)
    larc = LARC(opt)
    grads = {"w": jnp.full((4,), 0.1, jnp.bfloat16)}
    larc.step(grads=grads)
    assert opt.param_groups[0]["weight_decay"] == 0.01   # restored
    assert not np.allclose(np.asarray(opt.master_params["w"]), 1.0)


def test_grouped_optimizer_amp_initialize_o2():
    """Regression: amp.initialize with a grouped optimizer must cast each
    group's own params (the i-th model pytree does not match the group
    structure)."""
    from apex_tpu import amp
    decay = {"kernel": jnp.ones((4, 4))}
    no_decay = {"bias": jnp.ones((4,))}
    opt = FusedAdam([{"params": decay, "weight_decay": 0.01},
                     {"params": no_decay, "weight_decay": 0.0}], lr=1e-3)
    _, opt = amp.initialize([decay, no_decay], opt, opt_level="O2",
                            loss_scale=1.0, verbosity=0)
    assert opt.params[0]["kernel"].dtype == jnp.bfloat16
    assert opt.master_params[0]["kernel"].dtype == jnp.float32
    grads = [{"kernel": jnp.full((4, 4), 0.1, jnp.bfloat16)},
             {"bias": jnp.full((4,), 0.1, jnp.bfloat16)}]
    with amp.scale_loss(jnp.float32(1.0), opt):
        opt.backward(grads)
    opt.step()
    assert not np.allclose(np.asarray(opt.master_params[0]["kernel"]), 1.0)
