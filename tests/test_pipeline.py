"""SPMD pipeline parallelism vs the sequential oracle (fwd + grads).

Beyond-parity (reference is DP-only): the collective-permute pipeline of
``apex_tpu/parallel/pipeline.py`` on a 4-stage virtual CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel.pipeline import spmd_pipeline, stack_stage_params

S = 4          # stages
M = 4          # microbatches
D = 16

# Pre-vma jax (< 0.5; conftest shims shard_map with check_rep=False)
# inserts no implicit psum when differentiating w.r.t. replicated params
# under shard_map, so grad-vs-sequential-oracle comparisons only hold on
# vma-aware jax.
_pre_vma_jax = pytest.mark.skipif(
    jax.__version_info__ < (0, 5),
    reason="asserts jax>=0.5 shard_map autodiff (implicit psum) semantics")


@pytest.fixture
def pp_mesh():
    return Mesh(np.array(jax.devices("cpu")[:S]), ("pp",))


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _params():
    rng = np.random.RandomState(0)
    return [{"w": jnp.asarray(rng.randn(D, D) * 0.3, jnp.float32),
             "b": jnp.asarray(rng.randn(D) * 0.1, jnp.float32)}
            for _ in range(S)]


def _sequential(per_stage, x):
    for p in per_stage:
        x = _stage_fn(p, x)
    return x


def test_pipeline_forward_matches_sequential(pp_mesh):
    per_stage = _params()
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(np.random.RandomState(1).randn(8, D), jnp.float32)

    y = jax.jit(shard_map(
        lambda sp, x: spmd_pipeline(_stage_fn, sp, x, axis_name="pp",
                                    num_microbatches=M),
        mesh=pp_mesh, in_specs=(P("pp"), P()), out_specs=P()))(stacked, x)
    ref = _sequential(per_stage, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


@_pre_vma_jax
def test_pipeline_grads_match_sequential(pp_mesh):
    per_stage = _params()
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(np.random.RandomState(2).randn(8, D), jnp.float32)

    def loss_pipe(sp, x):
        y = spmd_pipeline(_stage_fn, sp, x, axis_name="pp",
                          num_microbatches=M)
        return jnp.mean(y ** 2)

    def run(sp, x):
        return jax.grad(loss_pipe, argnums=(0, 1))(sp, x)

    gs, gx = jax.jit(shard_map(
        run, mesh=pp_mesh, in_specs=(P("pp"), P()),
        out_specs=(P("pp"), P())))(stacked, x)

    def loss_seq(per_stage, x):
        return jnp.mean(_sequential(per_stage, x) ** 2)

    rs, rx = jax.grad(loss_seq, argnums=(0, 1))(per_stage, x)
    rs_stacked = stack_stage_params(rs)
    for a, b in zip(jax.tree_util.tree_leaves(gs),
                    jax.tree_util.tree_leaves(rs_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_rejects_indivisible_microbatches(pp_mesh):
    stacked = stack_stage_params(_params())
    x = jnp.ones((6, D), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(shard_map(
            lambda sp, x: spmd_pipeline(_stage_fn, sp, x, axis_name="pp",
                                        num_microbatches=4),
            mesh=pp_mesh, in_specs=(P("pp"), P()), out_specs=P()))(stacked, x)


def test_pipeline_microbatch_count_invariance(pp_mesh):
    """M=2 and M=8 produce identical results (schedule-independence)."""
    per_stage = _params()
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(np.random.RandomState(3).randn(8, D), jnp.float32)

    def run(m):
        return jax.jit(shard_map(
            lambda sp, x: spmd_pipeline(_stage_fn, sp, x, axis_name="pp",
                                        num_microbatches=m),
            mesh=pp_mesh, in_specs=(P("pp"), P()), out_specs=P()))(stacked, x)

    np.testing.assert_allclose(np.asarray(run(2)), np.asarray(run(8)),
                               atol=1e-6, rtol=1e-6)


# -- interleaved (circular) schedule ------------------------------------------

from apex_tpu.parallel.pipeline import (spmd_pipeline_interleaved,
                                        stack_interleaved_stage_params)

V = 2          # chunks per rank -> S * V virtual stages


def _params_n(n, seed=0):
    rng = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rng.randn(D, D) * 0.3, jnp.float32),
             "b": jnp.asarray(rng.randn(D) * 0.1, jnp.float32)}
            for _ in range(n)]


@pytest.mark.parametrize("m", [S, 2 * S])
def test_interleaved_forward_matches_sequential(pp_mesh, m):
    per_stage = _params_n(S * V)
    stacked = stack_interleaved_stage_params(per_stage, S)   # [V, S, ...]
    x = jnp.asarray(np.random.RandomState(1).randn(2 * m, D), jnp.float32)

    y = jax.jit(shard_map(
        lambda sp, x: spmd_pipeline_interleaved(
            _stage_fn, sp, x, axis_name="pp", num_microbatches=m),
        mesh=pp_mesh, in_specs=(P(None, "pp"), P()), out_specs=P()))(
            stacked, x)
    ref = _sequential(per_stage, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_interleaved_grads_match_sequential(pp_mesh):
    per_stage = _params_n(S * V, seed=3)
    stacked = stack_interleaved_stage_params(per_stage, S)
    x = jnp.asarray(np.random.RandomState(2).randn(8, D), jnp.float32)
    y_tgt = jnp.asarray(np.random.RandomState(4).randn(8, D), jnp.float32)

    def loss_pipe(sp, x):
        f = shard_map(
            lambda sp, x: spmd_pipeline_interleaved(
                _stage_fn, sp, x, axis_name="pp", num_microbatches=M),
            mesh=pp_mesh, in_specs=(P(None, "pp"), P()), out_specs=P())
        return jnp.mean((f(sp, x) - y_tgt) ** 2)

    def loss_seq(per, x):
        return jnp.mean((_sequential(per, x) - y_tgt) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked, x)
    g_seq = jax.grad(loss_seq)(per_stage, x)
    g_seq_stacked = stack_interleaved_stage_params(g_seq, S)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_seq_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def _scan_lengths(jaxpr):
    """All `scan` lengths found recursively in a (closed) jaxpr."""
    found = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            found.append(int(eqn.params["length"]))
        for p in eqn.params.values():
            if hasattr(p, "eqns"):                 # raw Jaxpr (shard_map)
                found.extend(_scan_lengths(p))
            elif hasattr(p, "jaxpr"):              # ClosedJaxpr (pjit, scan)
                found.extend(_scan_lengths(p.jaxpr))
    return found


def test_interleaved_tick_economics(pp_mesh):
    """The schedule property the variant exists for: m*v + p - 1 ticks of
    1/v-stage work vs GPipe's m + p - 1 ticks of full-stage work — the
    interleaved bubble is (p-1)/v full-stage units, a v-fold reduction.
    The tick counts are read from the TRACED programs' scan lengths, so a
    schedule regression (e.g. dropped drain ticks) fails here."""
    p, v, m = S, V, 2 * S
    x = jnp.zeros((2 * m, D), jnp.float32)

    stacked_i = stack_interleaved_stage_params(_params_n(p * v), p)
    jx_i = jax.make_jaxpr(shard_map(
        lambda sp, x: spmd_pipeline_interleaved(
            _stage_fn, sp, x, axis_name="pp", num_microbatches=m),
        mesh=pp_mesh, in_specs=(P(None, "pp"), P()), out_specs=P()))(
            stacked_i, x)
    stacked_g = stack_stage_params(_params_n(p))
    jx_g = jax.make_jaxpr(shard_map(
        lambda sp, x: spmd_pipeline(
            _stage_fn, sp, x, axis_name="pp", num_microbatches=m),
        mesh=pp_mesh, in_specs=(P("pp"), P()), out_specs=P()))(stacked_g, x)

    inter_ticks = m * v + p - 1
    gpipe_ticks = m + p - 1
    assert inter_ticks in _scan_lengths(jx_i.jaxpr)
    assert gpipe_ticks in _scan_lengths(jx_g.jaxpr)
    # wall in virtual-stage units (one gpipe tick = v virtual stages):
    # bubble interleaved (p-1), gpipe (p-1)*v
    assert inter_ticks - m * v == p - 1
    assert gpipe_ticks * v - m * v == (p - 1) * v


def test_interleaved_rejects_partial_groups(pp_mesh):
    per_stage = _params_n(S * V)
    stacked = stack_interleaved_stage_params(per_stage, S)
    x = jnp.asarray(np.random.RandomState(1).randn(6, D), jnp.float32)
    with pytest.raises(ValueError, match="multiple of the"):
        jax.jit(shard_map(
            lambda sp, x: spmd_pipeline_interleaved(
                _stage_fn, sp, x, axis_name="pp", num_microbatches=6),
            mesh=pp_mesh, in_specs=(P(None, "pp"), P()), out_specs=P()))(
                stacked, x)


def test_stack_interleaved_layout():
    per_stage = _params_n(S * V)
    stacked = stack_interleaved_stage_params(per_stage, S)
    w = jax.tree_util.tree_leaves(stacked)[0]
    assert w.shape[:2] == (V, S)
    # virtual stage s = c*p + r lives at [c, r]
    np.testing.assert_array_equal(
        np.asarray(stacked["b"][1, 2]), np.asarray(per_stage[1 * S + 2]["b"]))
