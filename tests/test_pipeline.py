"""SPMD pipeline parallelism vs the sequential oracle (fwd + grads).

Beyond-parity (reference is DP-only): the collective-permute pipeline of
``apex_tpu/parallel/pipeline.py`` on a 4-stage virtual CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel.pipeline import spmd_pipeline, stack_stage_params

S = 4          # stages
M = 4          # microbatches
D = 16


@pytest.fixture
def pp_mesh():
    return Mesh(np.array(jax.devices("cpu")[:S]), ("pp",))


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _params():
    rng = np.random.RandomState(0)
    return [{"w": jnp.asarray(rng.randn(D, D) * 0.3, jnp.float32),
             "b": jnp.asarray(rng.randn(D) * 0.1, jnp.float32)}
            for _ in range(S)]


def _sequential(per_stage, x):
    for p in per_stage:
        x = _stage_fn(p, x)
    return x


def test_pipeline_forward_matches_sequential(pp_mesh):
    per_stage = _params()
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(np.random.RandomState(1).randn(8, D), jnp.float32)

    y = jax.jit(shard_map(
        lambda sp, x: spmd_pipeline(_stage_fn, sp, x, axis_name="pp",
                                    num_microbatches=M),
        mesh=pp_mesh, in_specs=(P("pp"), P()), out_specs=P()))(stacked, x)
    ref = _sequential(per_stage, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_pipeline_grads_match_sequential(pp_mesh):
    per_stage = _params()
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(np.random.RandomState(2).randn(8, D), jnp.float32)

    def loss_pipe(sp, x):
        y = spmd_pipeline(_stage_fn, sp, x, axis_name="pp",
                          num_microbatches=M)
        return jnp.mean(y ** 2)

    def run(sp, x):
        return jax.grad(loss_pipe, argnums=(0, 1))(sp, x)

    gs, gx = jax.jit(shard_map(
        run, mesh=pp_mesh, in_specs=(P("pp"), P()),
        out_specs=(P("pp"), P())))(stacked, x)

    def loss_seq(per_stage, x):
        return jnp.mean(_sequential(per_stage, x) ** 2)

    rs, rx = jax.grad(loss_seq, argnums=(0, 1))(per_stage, x)
    rs_stacked = stack_stage_params(rs)
    for a, b in zip(jax.tree_util.tree_leaves(gs),
                    jax.tree_util.tree_leaves(rs_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_rejects_indivisible_microbatches(pp_mesh):
    stacked = stack_stage_params(_params())
    x = jnp.ones((6, D), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(shard_map(
            lambda sp, x: spmd_pipeline(_stage_fn, sp, x, axis_name="pp",
                                        num_microbatches=4),
            mesh=pp_mesh, in_specs=(P("pp"), P()), out_specs=P()))(stacked, x)


def test_pipeline_microbatch_count_invariance(pp_mesh):
    """M=2 and M=8 produce identical results (schedule-independence)."""
    per_stage = _params()
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(np.random.RandomState(3).randn(8, D), jnp.float32)

    def run(m):
        return jax.jit(shard_map(
            lambda sp, x: spmd_pipeline(_stage_fn, sp, x, axis_name="pp",
                                        num_microbatches=m),
            mesh=pp_mesh, in_specs=(P("pp"), P()), out_specs=P()))(stacked, x)

    np.testing.assert_allclose(np.asarray(run(2)), np.asarray(run(8)),
                               atol=1e-6, rtol=1e-6)
