"""Request-level tracing + SLO engine (ISSUE 20 tentpole).

The acceptance pins:

* **deterministic sampling** — counter-based every-Nth, no wall-clock
  or RNG entropy; ``sample_n=0`` never samples; the env wiring parses
  garbage as "off";
* **strict no-op** — no recorder (or an unsampled request) emits
  nothing and allocates nothing observable;
* **span-tree integrity under the threaded server** — every sampled
  request in a ``serve_forever`` load yields exactly one root
  ``request`` span, with queue/prefill/decode_step children all
  parented to it, through rotation included;
* **SLO semantics** — spec parsing, goodput evaluation, the online
  fold's burn-rate gauges, and the ``slo_burn`` / ``slo_exhausted``
  watchdog rules firing and recovering on a synthetic stream.
"""

import json
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import serving, telemetry
from apex_tpu.models import gpt_tiny
from apex_tpu.telemetry import slo as slo_mod
from apex_tpu.telemetry import tracing
from apex_tpu.telemetry.events import expand_stream_paths

VOCAB = 256


@pytest.fixture(autouse=True)
def _clean_recorder():
    telemetry.set_recorder(None)
    yield
    telemetry.set_recorder(None)


@pytest.fixture(scope="module")
def model_and_params():
    m = gpt_tiny(max_len=64, vocab_size=VOCAB, hidden_size=64,
                 num_layers=2, num_heads=2, mlp_dim=128)
    probe = jnp.asarray(np.random.RandomState(0).randint(1, VOCAB, (1, 8)))
    params = m.init(jax.random.PRNGKey(1), probe)["params"]
    return m, params


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(1, VOCAB, (n,)).astype(
        np.int32)


def _spans(path_or_events):
    if isinstance(path_or_events, str):
        events = []
        for p in expand_stream_paths(path_or_events):
            with open(p) as f:
                events += [json.loads(l) for l in f if l.strip()]
    else:
        events = path_or_events
    return [e for e in events if e.get("kind") == "span"]


def _check_trees(spans):
    """Every trace: one parentless ``request`` root, all other spans
    parented to it.  Returns the trace->spans map."""
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)
    for trace, ss in by_trace.items():
        roots = [s for s in ss if "parent" not in s]
        assert len(roots) == 1, f"{trace}: {len(roots)} roots"
        assert roots[0]["name"] == "request"
        rid = roots[0]["span"]
        for s in ss:
            if s is not roots[0]:
                assert s["parent"] == rid, \
                    f"{trace}: {s['name']} parented to {s['parent']}"
        names = {s["name"] for s in ss}
        assert {"queue", "prefill", "decode_step"} <= names, names
    return by_trace


# -- sampling + id determinism ------------------------------------------------

def test_sampler_every_nth_deterministic(tmp_path):
    rec = telemetry.Recorder(str(tmp_path / "t.jsonl"))
    tr = tracing.Tracer(rec, sample_n=3)
    got = [tr.sample() for _ in range(9)]
    assert got == ["t0-000000", None, None,
                   "t0-000001", None, None,
                   "t0-000002", None, None]
    assert tr.next_span_id() == "s000000"
    assert tr.next_span_id() == "s000001"
    rec.close()


def test_sampler_off_and_env_parse(tmp_path, monkeypatch):
    rec = telemetry.Recorder(str(tmp_path / "t.jsonl"))
    tr = tracing.Tracer(rec, sample_n=0)
    assert all(tr.sample() is None for _ in range(16))
    rec.close()
    monkeypatch.delenv("APEX_TPU_TRACE_SAMPLE", raising=False)
    assert tracing.sample_n_from_env() == 0
    monkeypatch.setenv("APEX_TPU_TRACE_SAMPLE", "4")
    assert tracing.sample_n_from_env() == 4
    monkeypatch.setenv("APEX_TPU_TRACE_SAMPLE", "banana")
    assert tracing.sample_n_from_env() == 0
    monkeypatch.setenv("APEX_TPU_TRACE_SAMPLE", "-2")
    assert tracing.sample_n_from_env() == 0


def test_unsampled_and_closed_recorder_are_noops(tmp_path):
    path = str(tmp_path / "t.jsonl")
    rec = telemetry.Recorder(path)
    tr = tracing.Tracer(rec, sample_n=1)
    # trace=None (the unsampled request): emit and span are no-ops
    assert tr.emit("prefill", None, dur=0.1) is None
    with tr.span("prefill", None) as sid:
        assert sid is None
    rec.close()
    # a closed recorder swallows emits instead of raising
    assert tr.emit("prefill", "t0-000000", dur=0.1) is None
    assert _spans(path) == []


def test_start_without_sampling_emits_no_spans(tmp_path, model_and_params):
    """trace_sample_n=0 (the default with the env unset): a full
    engine load writes ZERO span events — the strict no-op contract
    the bench gates bitwise."""
    m, params = model_and_params
    path = str(tmp_path / "dark.jsonl")
    rec = telemetry.start(path, trace_sample_n=0)
    assert rec.tracer is None
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=2, telemetry=rec)
    eng.warmup()
    eng.generate([_prompt(4), _prompt(6, 1)], max_new_tokens=3)
    eng.close()
    rec.close()
    assert _spans(path) == []


# -- engine span trees --------------------------------------------------------

def test_threaded_serving_span_tree_integrity(tmp_path, model_and_params):
    """The tentpole's integration pin: under the background
    ``serve_forever`` scheduler with concurrent submitters, every
    sampled request still reassembles into a single well-formed span
    tree, and the done events carry TTFT/TPOT."""
    m, params = model_and_params
    path = str(tmp_path / "serve.jsonl")
    rec = telemetry.start(path, trace_sample_n=1)
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=2, telemetry=rec)
    eng.warmup()
    eng.start()                          # background serve thread
    comps = []
    lock = threading.Lock()

    def submit(seed):
        c = eng.submit(_prompt(4 + seed % 5, seed), 3)
        with lock:
            comps.append(c)

    threads = [threading.Thread(target=submit, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    results = [c.result(timeout=60) for c in comps]
    eng.close()
    rec.close()
    assert all(r.ok for r in results)
    events = [json.loads(l) for l in open(path) if l.strip()]
    by_trace = _check_trees(_spans(events))
    assert len(by_trace) == 6                    # sample_n=1: all traced
    dones = [e for e in events if e.get("kind") == "serving"
             and e.get("phase") == "done"]
    assert len(dones) == 6
    for d in dones:
        assert d.get("ttft_s") is not None and d["ttft_s"] > 0
        assert d.get("trace") in by_trace
        # TTFT is part of e2e; TPOT spreads the decode tail
        assert d["ttft_s"] <= d["total_s"] + 1e-9
        if d.get("tpot_s") is not None:
            assert d["tpot_s"] >= 0
    # results expose the same numbers to the caller
    for r in results:
        assert r.timings.get("ttft_s") is not None


def test_span_trees_survive_rotation(tmp_path, model_and_params):
    """Spans split across rotated segments reassemble into intact
    trees via expand_stream_paths — the same reassembly prof.requests
    uses."""
    m, params = model_and_params
    path = str(tmp_path / "rot.jsonl")
    rec = telemetry.start(path, trace_sample_n=1, max_bytes=4096)
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=2, telemetry=rec)
    eng.warmup()
    eng.generate([_prompt(4 + i % 4, i) for i in range(6)],
                 max_new_tokens=4)
    eng.close()
    rec.close()
    assert len(expand_stream_paths(path)) > 1, "load too small to rotate"
    by_trace = _check_trees(_spans(path))
    assert len(by_trace) == 6


def test_traced_tokens_bitwise_vs_untraced(tmp_path, model_and_params):
    """Tracing must observe, never steer: the traced engine's greedy
    tokens equal the untraced engine's bitwise."""
    m, params = model_and_params
    prompts = [_prompt(5), _prompt(7, 1), _prompt(4, 2)]

    def run(rec):
        eng = serving.ServingEngine(m, params, buckets=(16,),
                                    page_size=4, max_seqs=2,
                                    telemetry=rec)
        try:
            eng.warmup()
            return [np.asarray(r.tokens) for r in
                    eng.generate(prompts, max_new_tokens=4)]
        finally:
            eng.close()

    plain = run(None)
    rec = telemetry.start(str(tmp_path / "on.jsonl"), trace_sample_n=1,
                          slo="ttft_p99<60s")
    traced = run(rec)
    rec.close()
    for a, b in zip(plain, traced):
        assert np.array_equal(a, b)


# -- SLO spec + evaluation ----------------------------------------------------

def test_parse_slo_specs():
    spec = slo_mod.parse_slo("ttft_p99<200ms, tpot_p95<=30ms")
    assert spec.target_pct == 99.0               # max qualifier wins
    assert [o.metric for o in spec.objectives] == ["ttft", "tpot"]
    assert spec.objectives[0].threshold_s == pytest.approx(0.2)
    assert spec.objectives[1].threshold_s == pytest.approx(0.03)
    # bare metric defaults, units, seconds
    spec2 = slo_mod.parse_slo("e2e<1.5s")
    assert spec2.objectives[0].threshold_s == pytest.approx(1.5)
    for bad in ("", "ttft<", "nope_p99<1ms", "ttft_p200<1ms",
                "ttft>1ms"):
        with pytest.raises(ValueError):
            slo_mod.parse_slo(bad)


def test_evaluate_goodput():
    reqs = ([{"ttft_s": 0.01, "total_s": 0.05}] * 9
            + [{"ttft_s": 0.50, "total_s": 0.9}])
    out = slo_mod.evaluate("ttft_p90<100ms", reqs)
    assert out["n_requests"] == 10 and out["good"] == 9
    assert out["goodput_pct"] == pytest.approx(90.0)
    assert out["met"] is True                    # target p90 -> 90%
    [obj] = out["objectives"]
    assert obj["ok"] is True                     # p90 of ttft <= 100ms
    out2 = slo_mod.evaluate("ttft_p99<100ms", reqs)
    assert out2["met"] is False                  # 90% < 99% target


def test_slo_fold_burn_fire_and_recover(tmp_path):
    """Synthetic stream on a deterministic clock: a burst of bad
    requests trips slo_burn (warning) and slo_exhausted (critical);
    a long good stretch brings the windowed burn back under 1x."""
    path = str(tmp_path / "slo.jsonl")
    rec = telemetry.Recorder(path)
    from apex_tpu.telemetry import watchdog as wdog
    wdog.attach(rec)
    eng = slo_mod.attach(rec, "ttft_p99<100ms",
                         short_window_s=10.0, long_window_s=50.0,
                         eval_every=1, min_requests=8)

    def done(t, ttft):
        # events enter through Recorder.event like the engine's own,
        # with a pinned stream clock for determinism
        rec.event("serving", phase="done", t=t, ttft_s=ttft,
                  total_s=ttft + 0.01, n_tokens=4)

    for i in range(10):                          # all out of SLO
        done(float(i), 0.5)
    assert eng.last is not None
    assert eng.last["burn_short"] > 1.0 and eng.last["burn_long"] > 1.0
    assert eng.last["exhausted"] is True
    rules = {a["rule"] for a in rec.watchdog.alerts}
    assert "slo_burn" in rules and "slo_exhausted" in rules
    # recovery: the windows slide past the bad burst
    for i in range(10, 80):
        done(float(i), 0.005)
    assert eng.last["burn_short"] == 0.0
    assert eng.last["goodput_pct"] == 100.0
    snap = rec.metrics.snapshot()["gauges"]
    assert snap["slo_goodput_pct"] == 100.0
    assert snap["slo_burn_rate_short"] == 0.0
    rec.close()
    # the stream carries the slo evaluations and the summary the exit
    # line reads
    events = [json.loads(l) for l in open(path) if l.strip()]
    assert any(e["kind"] == "slo" for e in events)
    summary = next(e for e in events if e["kind"] == "summary")
    assert summary["slo"]["goodput_pct"] == 100.0
    assert "goodput" in eng.format_line()


def test_engine_slo_end_to_end(tmp_path, model_and_params):
    """A real engine load under an impossible SLO: every request is
    bad, the stream carries slo events, and the watchdog pages."""
    m, params = model_and_params
    path = str(tmp_path / "impossible.jsonl")
    rec = telemetry.start(path, watchdog=True)
    slo_mod.attach(rec, "ttft_p99<1us", eval_every=1, min_requests=4)
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=2, telemetry=rec)
    eng.warmup()
    eng.generate([_prompt(4 + i, i) for i in range(5)],
                 max_new_tokens=3)
    eng.close()
    rec.close()
    events = [json.loads(l) for l in open(path) if l.strip()]
    slos = [e for e in events if e["kind"] == "slo"]
    assert slos and slos[-1]["goodput_pct"] == 0.0
    assert any(e["kind"] == "alert" and e["rule"] == "slo_exhausted"
               for e in events)
