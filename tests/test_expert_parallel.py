"""Expert-parallel MoE vs the dense oracle (fwd + grads + drop behavior).

Beyond-parity (reference is DP-only): switch-style top-1 MoE with
all_to_all dispatch over a 4-rank virtual CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel.expert_parallel import moe_layer
from apex_tpu.parallel.pipeline import stack_stage_params


# Pre-vma jax (< 0.5; conftest shims shard_map with check_rep=False)
# inserts no implicit psum when differentiating w.r.t. replicated params
# under shard_map, so grad-vs-sequential-oracle comparisons only hold on
# vma-aware jax.
_pre_vma_jax = pytest.mark.skipif(
    jax.__version_info__ < (0, 5),
    reason="asserts jax>=0.5 shard_map autodiff (implicit psum) semantics")

E = 4          # experts == ep ranks
D = 8
T = 16         # tokens per rank


@pytest.fixture
def ep_mesh():
    return Mesh(np.array(jax.devices("cpu")[:E]), ("ep",))


def _expert_fn(p, h):
    return jnp.tanh(h @ p["w"]) @ p["v"]


def _params():
    rng = np.random.RandomState(0)
    router = jnp.asarray(rng.randn(D, E) * 0.5, jnp.float32)
    experts = [{"w": jnp.asarray(rng.randn(D, 2 * D) * 0.3, jnp.float32),
                "v": jnp.asarray(rng.randn(2 * D, D) * 0.3, jnp.float32)}
               for _ in range(E)]
    return router, experts


def _oracle(router, experts, x):
    """Dense per-token computation: every token through its argmax expert,
    scaled by its gate (no capacity drops)."""
    logits = x @ router
    probs = jax.nn.softmax(logits, axis=-1)
    assign = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    all_out = jnp.stack([_expert_fn(p, x) for p in experts])   # [E, T, D]
    y = all_out[assign, jnp.arange(x.shape[0])]
    return y * gate[:, None]


def _run_moe(mesh, router, experts_stacked, x, capacity_factor):
    def fn(router, ep, x):
        ep = jax.tree_util.tree_map(lambda p: jnp.squeeze(p, 0), ep)
        y, aux = moe_layer(x, router, _expert_fn, ep, axis_name="ep",
                           capacity_factor=capacity_factor)
        # load_balance_loss is already global (pmean'd inside moe_layer);
        # dropped_fraction is per-rank — average it to a global diagnostic.
        # pmean of the replicated loss is the identity, so one map is fine.
        aux = jax.tree_util.tree_map(
            lambda v: jax.lax.pmean(v, "ep"), aux)
        return y, aux

    return jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(P(), P("ep"), P("ep")),
        out_specs=(P("ep"), P())))(router, experts_stacked, x)


def test_moe_matches_dense_oracle_when_capacity_suffices(ep_mesh):
    router, experts = _params()
    stacked = stack_stage_params(experts)
    x = jnp.asarray(np.random.RandomState(1).randn(E * T, D), jnp.float32)

    # capacity_factor=E => capacity==tokens_per_rank: nothing can drop.
    y, aux = _run_moe(ep_mesh, router, stacked, x, capacity_factor=E)
    assert float(aux.dropped_fraction) == 0.0
    ref = _oracle(router, experts, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_moe_drops_overflow_tokens(ep_mesh):
    router, experts = _params()
    stacked = stack_stage_params(experts)
    # All tokens identical -> all route to ONE expert -> heavy overflow at
    # capacity_factor 1 (capacity = T/E).
    x = jnp.ones((E * T, D), jnp.float32)
    y, aux = _run_moe(ep_mesh, router, stacked, x, capacity_factor=1.0)
    assert float(aux.dropped_fraction) > 0.5
    # dropped tokens contribute exactly zero
    kept_rows = np.abs(np.asarray(y)).sum(axis=1) > 0
    assert kept_rows.sum() == round((1 - float(aux.dropped_fraction))
                                    * E * T)


@_pre_vma_jax
def test_moe_gradients_flow_to_experts_and_router(ep_mesh):
    router, experts = _params()
    stacked = stack_stage_params(experts)
    x = jnp.asarray(np.random.RandomState(2).randn(E * T, D), jnp.float32)

    def loss(router, ep, x):
        ep_local = jax.tree_util.tree_map(lambda p: jnp.squeeze(p, 0), ep)
        y, aux = moe_layer(x, router, _expert_fn, ep_local, axis_name="ep",
                           capacity_factor=float(E))
        # Per-rank losses SUM across ranks through shard_map's transpose,
        # so divide by the rank count to match the dense global mean.
        return jnp.mean(y ** 2) / E

    def run(router, ep, x):
        return jax.grad(loss, argnums=(0, 1))(router, ep, x)

    g_router, g_experts = jax.jit(shard_map(
        run, mesh=ep_mesh,
        in_specs=(P(), P("ep"), P("ep")),
        out_specs=(P(), P("ep"))))(router, stacked, x)

    def loss_dense(router, experts, x):
        return jnp.mean(_oracle(router, experts, x) ** 2)

    r_router, r_experts = jax.grad(loss_dense, argnums=(0, 1))(
        router, experts, x)
    np.testing.assert_allclose(np.asarray(g_router), np.asarray(r_router),
                               atol=1e-4, rtol=1e-4)
    assert float(jnp.linalg.norm(g_router)) > 0
    r_stacked = stack_stage_params(r_experts)
    for a, b in zip(jax.tree_util.tree_leaves(g_experts),
                    jax.tree_util.tree_leaves(r_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_moe_load_balance_loss_uses_global_means(ep_mesh):
    """Switch aux loss must be E * sum_e f_e * P_e over the GLOBAL batch
    (ADVICE r2): with routing skew across ranks, mean-of-local-products
    differs from the correct product-of-global-means."""
    router, experts = _params()
    stacked = stack_stage_params(experts)
    x = jnp.asarray(np.random.RandomState(7).randn(E * T, D) * 3, jnp.float32)

    _, aux = _run_moe(ep_mesh, router, stacked, x, capacity_factor=E)

    # Oracle on the full (unsharded) batch.
    probs = jax.nn.softmax(x @ router, axis=-1)
    f_g = jnp.mean(jax.nn.one_hot(jnp.argmax(probs, -1), E), axis=0)
    p_g = jnp.mean(probs, axis=0)
    want = float(E * jnp.sum(f_g * p_g))
    got = float(aux.load_balance_loss)
    assert abs(got - want) < 1e-5, (got, want)

    # And the skew is real in this fixture: the per-rank means differ,
    # so a mean-of-local-losses would NOT equal the global formula.
    xs = x.reshape(E, T, D)
    local = []
    for r in range(E):
        pr = jax.nn.softmax(xs[r] @ router, axis=-1)
        fr = jnp.mean(jax.nn.one_hot(jnp.argmax(pr, -1), E), axis=0)
        local.append(float(E * jnp.sum(fr * jnp.mean(pr, axis=0))))
    assert abs(np.mean(local) - want) > 1e-4, (np.mean(local), want)
