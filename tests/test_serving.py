"""Serving engine (ISSUE 11): paged KV cache, AOT-bucketed
prefill/decode, continuous batching, weight hot-swap, and per-request
telemetry.

The acceptance pins: every request's greedy output is BITWISE the
repeated-full-forward sequence regardless of how the scheduler batched
it (continuous batching is an optimization, never a numerics change);
warmed buckets serve with ZERO jit traces; an un-warmed bucket is a
clean lookup miss served by the jit path; a mid-load hot-swap fails no
request and post-swap outputs match the new checkpoint's; the manifest
watcher never adopts corrupt/in-flight checkpoints (the test_checkpoint
debris fixtures, pointed at the watcher).
"""

import glob
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import serving, telemetry
from apex_tpu.checkpoint import CheckpointManager, latest_checkpoint
from apex_tpu.models import gpt_tiny
from apex_tpu.prof import assert_trace_count
from apex_tpu.serving.kv_cache import (PageAllocator, gather_views,
                                       make_pool, scatter_prefill,
                                       scatter_token)

VOCAB = 256


@pytest.fixture(autouse=True)
def _clean_recorder():
    telemetry.set_recorder(None)
    yield
    telemetry.set_recorder(None)


@pytest.fixture(scope="module")
def model_and_params():
    m = gpt_tiny(max_len=64, vocab_size=VOCAB, hidden_size=64,
                 num_layers=2, num_heads=2, mlp_dim=128)
    probe = jnp.asarray(np.random.RandomState(0).randint(1, VOCAB, (1, 8)))
    params = m.init(jax.random.PRNGKey(1), probe)["params"]
    return m, params


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(1, VOCAB, (n,)).astype(
        np.int32)


def _full_forward_greedy(m, params, prompt, n_new):
    """The oracle: repeated full forward passes, argmax each step."""
    ids = jnp.asarray(prompt)[None]
    for _ in range(n_new):
        logits = m.apply({"params": params}, ids)[:, -1]
        ids = jnp.concatenate(
            [ids, jnp.argmax(logits, -1)[:, None].astype(ids.dtype)],
            axis=1)
    return np.asarray(ids[0, len(prompt):])


# -- paged KV cache substrate -------------------------------------------------

def test_page_allocator_accounting():
    al = PageAllocator(9)                # 8 allocatable + trash page 0
    assert al.total_pages == 8 and al.free_pages == 8
    a = al.alloc(3)
    b = al.alloc(5)
    assert al.free_pages == 0 and al.alloc(1) is None   # all-or-nothing
    assert al.occupancy_pct == 100.0
    assert 0 not in a + b                # trash page never allocated
    al.free(a)
    assert al.free_pages == 3 and al.occupancy_pct == pytest.approx(62.5)
    with pytest.raises(ValueError, match="double free"):
        al.free(b + b[:1])
    with pytest.raises(ValueError, match="trash"):
        al.free([0])


def test_pool_gather_scatter_roundtrip(model_and_params):
    """scatter_prefill -> gather_views must reproduce the dense cache
    exactly, through an arbitrary page permutation."""
    m, _ = model_and_params
    page = 4
    pool_k, pool_v = make_pool(m, n_pages=9, page_size=page)
    bucket = 16
    rng = np.random.RandomState(3)
    dense = jnp.asarray(rng.randn(m.num_layers, bucket, 2,
                                  pool_k.shape[-1]), pool_k.dtype)
    pages = jnp.asarray([5, 2, 7, 1], jnp.int32)      # permuted pages
    pool_k = scatter_prefill(pool_k, pages, dense)
    tables = np.zeros((2, bucket // page), np.int32)
    tables[1] = np.asarray(pages)                     # slot 1 owns them
    views = gather_views(pool_k, pool_v, jnp.asarray(tables))
    for i in range(m.num_layers):
        np.testing.assert_array_equal(np.asarray(views[i][0][1]),
                                      np.asarray(dense[i]))
        assert not np.any(np.asarray(views[i][0][0]))  # slot 0: trash
    # single-token scatter lands at (page, offset)
    tok = jnp.ones((m.num_layers, 2, 2, pool_k.shape[-1]), pool_k.dtype)
    pool_k = scatter_token(pool_k, jnp.asarray([5, 0]),
                           jnp.asarray([3, 0]), tok)
    np.testing.assert_array_equal(
        np.asarray(pool_k[:, 5, 3]), np.ones_like(np.asarray(pool_k[:, 5, 3])))


# -- engine: continuous batching parity ---------------------------------------

def test_engine_matches_full_forward_greedy(model_and_params):
    """Mixed prompt lengths across two buckets, more requests than
    slots: every request's tokens are bitwise the full-forward greedy
    sequence, pages drain to zero, and no AOT lookup ever missed."""
    m, params = model_and_params
    eng = serving.ServingEngine(m, params, buckets=(16, 32), page_size=4,
                                max_seqs=2)
    eng.warmup()
    prompts = [_prompt(n, seed=n) for n in (3, 7, 12, 5, 9)]
    results = eng.generate(prompts, max_new_tokens=5)
    for p, r in zip(prompts, results):
        assert r.ok
        np.testing.assert_array_equal(
            _full_forward_greedy(m, params, p, 5), r.tokens)
    assert eng.stats["completed"] == 5
    assert eng.stats["aot_misses"] == 0
    assert eng.pages.occupancy_pct == 0.0
    assert {r.bucket for r in results} == {16, 32}   # both buckets hit
    eng.close()


def test_engine_zero_traces_after_warmup(model_and_params):
    """The steady-state contract: after warmup, serving dispatches go
    straight to the AOT executables — ZERO traces on the jit callables
    (pinned), zero lookup misses."""
    m, params = model_and_params
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=2)
    eng.warmup()
    pins = [assert_trace_count(fn, 0) for fn in eng._jit.values()]
    for pin in pins:
        pin.__enter__()
    try:
        eng.generate([_prompt(4), _prompt(6, 1)], max_new_tokens=4)
    finally:
        for pin in pins:
            pin.__exit__(None, None, None)
    assert eng.stats["aot_misses"] == 0
    eng.close()


def test_unwarmed_bucket_is_clean_lookup_miss(model_and_params):
    """ISSUE 11 satellite: a bucket never warmed keys to a MISS in the
    AOT table (the static bucket param keeps keys distinct) and the jit
    fallback serves it with identical numerics."""
    m, params = model_and_params
    eng = serving.ServingEngine(m, params, buckets=(16, 32), page_size=4,
                                max_seqs=2)
    eng.warmup(buckets=(16,))            # bucket 32 never warmed
    p_small, p_big = _prompt(4), _prompt(20, 1)
    r_small, r_big = eng.generate([p_small, p_big], max_new_tokens=4)
    assert r_small.bucket == 16 and r_big.bucket == 32
    assert eng.stats["aot_misses"] > 0   # the miss was counted...
    np.testing.assert_array_equal(      # ...and served correctly
        _full_forward_greedy(m, params, p_big, 4), r_big.tokens)
    np.testing.assert_array_equal(
        _full_forward_greedy(m, params, p_small, 4), r_small.tokens)
    eng.close()


def test_admission_waits_for_free_pages(model_and_params):
    """More concurrent demand than pages: requests queue until an
    eviction frees pages — nothing is dropped, everything completes."""
    m, params = model_and_params
    # pool sized for ONE bucket-16 sequence (4 pages + trash)
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=2, n_pages=5)
    eng.warmup()
    prompts = [_prompt(4, s) for s in range(3)]
    results = eng.generate(prompts, max_new_tokens=3)
    assert all(r.ok for r in results)
    for p, r in zip(prompts, results):
        np.testing.assert_array_equal(
            _full_forward_greedy(m, params, p, 3), r.tokens)
    eng.close()


def test_oversized_request_rejected_not_truncated(model_and_params):
    m, params = model_and_params
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=1)
    eng.warmup()
    r = eng.generate([_prompt(14)], max_new_tokens=8)[0]
    assert not r.ok and "fits no bucket" in r.error
    assert eng.stats["rejected"] == 1
    assert eng.pages.occupancy_pct == 0.0
    eng.close()


def test_stop_token_finishes_early(model_and_params):
    m, params = model_and_params
    eng = serving.ServingEngine(m, params, buckets=(32,), page_size=4,
                                max_seqs=1)
    eng.warmup()
    p = _prompt(5, 7)
    free_run = eng.generate([p], max_new_tokens=10)[0]
    toks = free_run.tokens.tolist()
    # stop on the first token whose FIRST occurrence is past index 0
    i, stop = next((i, t) for i, t in enumerate(toks)
                   if i >= 1 and t not in toks[:i])
    stopped = eng.generate([p], max_new_tokens=10, stop_token=stop)[0]
    assert stopped.tokens.tolist() == toks[:i + 1]
    eng.close()


def test_submit_backpressure_and_threaded_serving(model_and_params):
    m, params = model_and_params
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=1, max_queue=2)
    eng.warmup()
    c1 = eng.submit(_prompt(3), 2)
    c2 = eng.submit(_prompt(3, 1), 2)
    with pytest.raises(RuntimeError, match="queue full"):
        eng.submit(_prompt(3, 2), 2, block=False)
    eng.start()                          # serve thread drains the queue
    assert c1.result(timeout=60).ok and c2.result(timeout=60).ok
    eng.close()


# -- weight hot-swap ----------------------------------------------------------

def _save_params(directory, params, step):
    mgr = CheckpointManager(directory, keep=3, procs=(0, 1),
                            async_write=False)
    mgr.save(step, params)
    mgr.close()


def test_hotswap_mid_load_no_failed_requests(model_and_params, tmp_path):
    """The zero-downtime contract: a checkpoint published mid-load is
    adopted between steps; every in-flight request completes; requests
    served AFTER the swap match the new checkpoint's single-request
    output bitwise."""
    m, params = model_and_params
    params2 = jax.tree_util.tree_map(lambda x: x * 1.01, params)
    d = str(tmp_path / "ckpt")
    eng = serving.ServingEngine(m, params, buckets=(32,), page_size=4,
                                max_seqs=2, watch_dir=d, poll_every_s=60)
    eng.warmup()
    # in-flight request, half served under the old weights
    comp = eng.submit(_prompt(5), 8)
    for _ in range(4):
        eng.step()
    _save_params(d, params2, step=11)
    assert eng.watcher.poll_once()       # stage synchronously (no sleep)
    eng.run_until_idle()
    assert comp.result(timeout=0).ok     # in-flight request completed
    assert eng.stats["hotswaps"] == 1

    post = eng.generate([_prompt(6, 9)], max_new_tokens=5)[0]
    assert post.ok and eng.stats["aot_misses"] == 0
    np.testing.assert_array_equal(       # bitwise vs the new checkpoint
        _full_forward_greedy(m, params2, _prompt(6, 9), 5), post.tokens)
    eng.close()


def test_watcher_ignores_corrupt_and_inflight_manifests(model_and_params,
                                                        tmp_path):
    """ISSUE 11 satellite (the test_checkpoint debris fixtures, pointed
    at the watcher): a truncated shard + .tmp debris, a bit-flipped
    shard, and a missing manifest part must all be invisible — the
    watcher stays on the newest VALID step and adopts a later valid one
    when it commits."""
    m, params = model_and_params
    d = str(tmp_path / "ckpt")
    w = serving.WeightWatcher(d, like=params, poll_every_s=60)
    assert not w.poll_once()             # empty directory: nothing
    _save_params(d, params, step=5)
    assert w.poll_once() and w.adopted_step == 5
    assert w.take()[0] == 5 and w.take() is None    # at most once

    # newest = torn write: truncated shard + .tmp debris
    params2 = jax.tree_util.tree_map(lambda x: x * 2.0, params)
    _save_params(d, params2, step=10)
    newest = latest_checkpoint(d)
    shard = glob.glob(os.path.join(newest, "shard_*.npz"))[0]
    with open(shard, "r+b") as f:
        f.truncate(16)
    with open(shard + ".tmp", "wb") as f:
        f.write(b"partial")
    assert not w.poll_once() and w.adopted_step == 5

    # newest = bit corruption (checksum catches it)
    _save_params(d, params2, step=15)
    shard = glob.glob(os.path.join(
        d, "step_00000015", "shard_*.npz"))[0]
    data = bytearray(open(shard, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(shard, "wb").write(bytes(data))
    assert not w.poll_once() and w.adopted_step == 5

    # newest = in-flight multi-host save (one manifest part missing)
    m0 = CheckpointManager(d, procs=(0, 2), async_write=False)
    m0.save(20, params2)
    m0.close()
    assert not w.poll_once() and w.adopted_step == 5

    # a later VALID checkpoint is adopted over all the debris
    _save_params(d, params2, step=25)
    assert w.poll_once() and w.take()[0] == 25
    w.close()


# -- telemetry ----------------------------------------------------------------

def test_serving_events_and_gauges_in_stream(model_and_params, tmp_path):
    m, params = model_and_params
    path = str(tmp_path / "serve.jsonl")
    rec = telemetry.start(path, watchdog=True, example="serving-test")
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=2)
    eng.warmup()
    eng.generate([_prompt(4), _prompt(6, 1)], max_new_tokens=3)
    snap = rec.metrics.snapshot()
    eng.close()
    rec.close()
    events = [json.loads(l) for l in open(path) if l.strip()]
    phases = [e.get("phase") for e in events if e["kind"] == "serving"]
    for want in ("submit", "admit", "decode", "done"):
        assert want in phases, f"missing serving phase {want}"
    admit = next(e for e in events
                 if e["kind"] == "serving" and e["phase"] == "admit")
    assert "queue_wait" in admit and "prefill_dur" in admit
    done = next(e for e in events
                if e["kind"] == "serving" and e["phase"] == "done")
    assert done["n_tokens"] == 3 and "decode_s" in done
    gauges = snap["gauges"]
    for g in ("serving_queue_depth", "serving_active_seqs",
              "serving_kv_page_occupancy_pct"):
        assert g in gauges, f"missing gauge {g}"
    hists = snap["histograms"]
    for h in ("serving_queue_wait_s", "serving_prefill_s",
              "serving_decode_step_s"):
        assert h in hists and hists[h]["count"] > 0
    # the clean run raised no serving alerts
    assert not any(e["kind"] == "alert" for e in events)


def test_tokens_per_s_gauge_decays_when_idle(model_and_params, tmp_path):
    """Regression (ISSUE 20 satellite): serving_tokens_per_s froze at
    its last computed rate across idle gaps — a drained server scraped
    as if it were still serving at full tilt.  With no decode landing
    inside the idle horizon the next scheduler pass must ZERO the
    gauge (and re-anchor cleanly when load returns)."""
    m, params = model_and_params
    rec = telemetry.start(str(tmp_path / "rate.jsonl"))
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=2)
    eng.warmup()
    eng.generate([_prompt(4), _prompt(6, 1)], max_new_tokens=3)
    g = rec.metrics.gauge("serving_tokens_per_s")
    assert g.value is not None and g.value > 0   # live rate under load
    eng.rate_idle_s = 0.0                        # horizon: immediate
    eng.step()                                   # idle scheduler pass
    assert g.value == 0.0
    # load returns: the rate re-anchors and goes live again
    eng.rate_idle_s = 5.0
    eng.generate([_prompt(5, 2)], max_new_tokens=3)
    assert g.value > 0
    eng.close()
    rec.close()


def test_serving_queue_stall_alert_end_to_end(model_and_params, tmp_path):
    """A request that waits past the threshold in the queue trips the
    serving_queue_stall rule when it is finally admitted."""
    m, params = model_and_params
    from apex_tpu.telemetry import watchdog as wdog
    path = str(tmp_path / "stall.jsonl")
    rec = telemetry.Recorder(path)
    wdog.attach(rec, serving_stall_s=0.0)
    telemetry.set_recorder(rec)
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=1)
    eng.warmup()
    eng.generate([_prompt(4), _prompt(5, 1)], max_new_tokens=2)
    eng.close()
    rec.close()
    events = [json.loads(l) for l in open(path) if l.strip()]
    alerts = [e for e in events if e["kind"] == "alert"]
    assert any(a["rule"] == "serving_queue_stall" for a in alerts)


# -- example smoke ------------------------------------------------------------

@pytest.mark.slow
def test_serve_lm_example_smoke():
    """The deployment driver runs end to end and prints the served
    line (subprocess: the example owns its own recorder/engine)."""
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "serving",
                                      "serve_lm.py"),
         "--requests", "3", "--max-new", "3", "--buckets", "32",
         "--page-size", "8"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "served 3/3 requests" in r.stdout
    assert "aot_misses 0" in r.stdout


def test_close_resolves_inflight_and_queued_requests(model_and_params):
    """close() must fail BOTH never-admitted and admitted-but-unfinished
    requests (no caller blocks forever) and return their pages to the
    pool; submit after close raises (review findings)."""
    m, params = model_and_params
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=1)
    eng.warmup()
    inflight = eng.submit(_prompt(3), 8)
    eng.step()                           # admitted, far from finished
    queued = eng.submit(_prompt(4, 1), 8)
    eng.close()
    assert not inflight.result(timeout=5).ok
    assert not queued.result(timeout=5).ok
    assert eng.pages.occupancy_pct == 0.0
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit(_prompt(3), 2)


def test_run_until_idle_refuses_beside_serve_thread(model_and_params):
    m, params = model_and_params
    eng = serving.ServingEngine(m, params, buckets=(16,), page_size=4,
                                max_seqs=1)
    eng.warmup()
    eng.start()
    with pytest.raises(RuntimeError, match="serve thread"):
        eng.run_until_idle()
    # generate() beside the serve thread submits + waits instead
    r = eng.generate([_prompt(4)], max_new_tokens=2)[0]
    assert r.ok
    eng.close()
