"""Loader resume determinism (ISSUE 9): ``state_dict()`` → ``resume()``
must replay the identical batch/augment stream — across multi-epoch
reshuffles, host shards, ordered vs completion-order delivery, and the
no-native (tier-2) install."""

import os

import numpy as np
import pytest

from apex_tpu.data import (BatchFiles, DirectoryImagenet, PrefetchLoader,
                           augment_images, directory_imagenet, load_batch)


@pytest.fixture(params=["native-default", "no-native"])
def native_tier(request, monkeypatch):
    if request.param == "no-native":
        monkeypatch.setenv("APEX_TPU_DISABLE_NATIVE", "1")
    return request.param


def _npy_tree(tmp_path, per_class=6, classes=2, size=16):
    rng = np.random.RandomState(7)
    for c in range(classes):
        d = tmp_path / f"class{c}"
        d.mkdir()
        for i in range(per_class):
            np.save(d / f"s{i}.npy",
                    rng.randint(0, 256, (size, size, 3)).astype(np.uint8))
    return str(tmp_path)


def _batch_key(batch):
    """Order-independent identity of one decoded batch."""
    imgs, labels = batch
    return (np.asarray(imgs).tobytes(), np.asarray(labels).tobytes())


@pytest.mark.parametrize("host_shard", [None, (0, 2), (1, 2)])
def test_stream_resume_replays_identical_tail(tmp_path, host_shard):
    """Mid-run (and mid-epoch) resume: a fresh stream resumed from the
    saved state yields exactly the batches the uninterrupted stream
    would have yielded next — across epoch boundaries (per-epoch
    reshuffle re-derives from seed + epoch)."""
    root = _npy_tree(tmp_path, per_class=10)   # 20 samples
    kw = dict(batch_size=4, image_size=16, epochs=3, seed=5,
              host_shard=host_shard)
    full = list(directory_imagenet(root, **kw))
    assert len(full) >= 4
    cut = len(full) // 2 + 1          # inside epoch 1 of 3
    consumed = directory_imagenet(root, **kw)
    for _ in range(cut):
        next(consumed)
    sd = consumed.state_dict()
    assert sd["cursor"] == cut
    resumed = directory_imagenet(root, **kw).resume(sd)
    tail = list(resumed)
    assert len(tail) == len(full) - cut
    for (a, la), (b, lb) in zip(full[cut:], tail):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(la, lb)


def test_skip_equals_consume_and_seq_is_stable(tmp_path):
    """skip(n) is pure index math but must land on the same batch AND
    the same ``BatchFiles.seq`` as consuming n batches — seq feeds the
    augment seed, so an off-by-one would silently change crops."""
    root = _npy_tree(tmp_path)
    kw = dict(batch_size=4, image_size=16, epochs=2, decode=False)
    a = directory_imagenet(root, **kw)
    for _ in range(3):
        next(a)
    b = directory_imagenet(root, **kw).skip(3)
    ta, tb = next(a), next(b)
    assert isinstance(ta, BatchFiles)
    assert ta.paths == tb.paths and ta.seq == tb.seq == 3
    np.testing.assert_array_equal(ta.labels, tb.labels)


def test_resume_rejects_mismatched_schedule(tmp_path):
    root = _npy_tree(tmp_path)
    sd = directory_imagenet(root, batch_size=4, image_size=16,
                            seed=5).state_dict()
    other = directory_imagenet(root, batch_size=4, image_size=16, seed=6)
    with pytest.raises(ValueError, match="resume mismatch"):
        other.resume(sd)


def _augment_transform(image_size):
    """The imagenet example's deterministic augment recipe: the rng is
    seeded from the batch's content + global seq, so identical
    descriptors draw identical crops/flips on ANY worker, native or
    fallback tier."""
    import zlib

    def assemble(task):
        imgs, labels = load_batch(task)
        rng = np.random.RandomState(
            (zlib.crc32("|".join(task.paths).encode())
             ^ (task.seq * 2654435761)) & 0x7FFFFFFF)
        return augment_images(imgs, image_size - 4, rng), labels
    return assemble


@pytest.mark.parametrize("workers", [1, 3])
def test_prefetch_resume_ordered_replays_identical(tmp_path, native_tier,
                                                   workers):
    """The full kill-and-resume input path, ordered delivery: consume
    half through a PrefetchLoader (decode + augment in the worker
    pool), capture ``loader.state_dict()``, rebuild stream + loader
    from it, and require the remaining AUGMENTED stream bit-identical
    to the uninterrupted one."""
    root = _npy_tree(tmp_path)
    kw = dict(batch_size=4, image_size=16, epochs=2, seed=3, decode=False)
    assemble = _augment_transform(16)

    def loader_for(stream):
        return PrefetchLoader(stream, depth=2, workers=workers,
                              transform=assemble, ordered=True)

    with loader_for(directory_imagenet(root, **kw)) as full_loader:
        full = list(full_loader)
    cut = len(full) // 2 + 1
    loader = loader_for(directory_imagenet(root, **kw))
    it = iter(loader)
    for _ in range(cut):
        next(it)
    sd = loader.state_dict()
    loader.close()
    assert sd["delivered"] == cut
    # the source ran AHEAD of delivery (prefetch): the saved source
    # state must be rewound to the delivery boundary, not the source
    # cursor
    assert sd["source"]["cursor"] == cut
    resumed_stream = directory_imagenet(root, **kw).resume(sd["source"])
    with loader_for(resumed_stream) as resumed_loader:
        tail = list(resumed_loader)
    assert len(tail) == len(full) - cut
    for (a, la), (b, lb) in zip(full[cut:], tail):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(la, lb)


def test_prefetch_resume_completion_order_delivers_exact_set(tmp_path,
                                                             native_tier):
    """Completion-order delivery trades sequence stability for latency:
    a resumed loader still delivers EXACTLY the source batches from the
    cursor on — as a set — and each batch's augment draws stay
    bit-identical (seeded by content + seq, not by arrival order)."""
    root = _npy_tree(tmp_path)
    kw = dict(batch_size=4, image_size=16, epochs=2, seed=3, decode=False)
    assemble = _augment_transform(16)
    with PrefetchLoader(directory_imagenet(root, **kw), depth=2,
                        workers=3, transform=assemble,
                        ordered=True) as ordered_loader:
        full = list(ordered_loader)
    cut = len(full) // 2
    resumed_stream = directory_imagenet(root, **kw).skip(cut)
    with PrefetchLoader(resumed_stream, depth=2, workers=3,
                        transform=assemble, ordered=False) as loader:
        tail = list(loader)
    assert len(tail) == len(full) - cut
    want = sorted(_batch_key(b) for b in full[cut:])
    got = sorted(_batch_key(b) for b in tail)
    assert want == got


def test_prefetch_state_dict_rejects_completion_order(tmp_path):
    """Review fix: under ordered=False the delivered batches are not a
    prefix of the source order, so no integer cursor can rewind to the
    delivery boundary — state_dict must refuse rather than silently
    skip in-flight batches on resume."""
    root = _npy_tree(tmp_path)
    loader = PrefetchLoader(
        directory_imagenet(root, batch_size=4, image_size=16,
                           decode=False),
        workers=2, transform=load_batch, ordered=False)
    with loader:
        it = iter(loader)
        next(it)
        with pytest.raises(ValueError, match="ordered"):
            loader.state_dict()


def test_stream_survives_host_shard_cursor_math(tmp_path):
    """Sharded resume: each host resumes its OWN cursor over the shared
    shuffle; the interleaving of resumed shard streams reproduces the
    unsharded tail (the property the multichip resume leans on)."""
    root = _npy_tree(tmp_path, per_class=8)   # 16 samples, batch 2 -> 8
    kw = dict(batch_size=2, image_size=16, seed=3, epochs=2)
    full = list(directory_imagenet(root, **kw))
    cut_per_host = 2
    shards = []
    for i in range(2):
        s = directory_imagenet(root, host_shard=(i, 2), **kw)
        s.skip(cut_per_host)
        shards.append(list(s))
    interleaved = [b for pair in zip(*shards) for b in pair]
    for (a, la), (b, lb) in zip(full[2 * cut_per_host:], interleaved):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(la, lb)
