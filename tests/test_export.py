"""Live metrics export (ISSUE 10): Prometheus rendering, the atomic
textfile, the http endpoint, env-var startup, and stream rotation.

The contracts tier-1 pins here:

* **disabled-path bitwise identity** — a training loop run with the
  exporter attached produces BITWISE-identical parameters to the
  uninstrumented run (the exporter rides the recorder's event threads;
  no recorder, no exporter, no difference);
* **scrape under load** — concurrent scrapes against a loop that is
  actively emitting events return complete, parseable exposition text
  carrying the loop's own instruments;
* **atomic textfile** — the scrape file is replaced via rename, so a
  reader never observes a torn render;
* **env-var startup** — ``APEX_TPU_TELEMETRY`` / ``APEX_TPU_WATCHDOG``
  / ``APEX_TPU_METRICS_*`` configure :func:`telemetry.start` and
  :func:`telemetry.start_from_env` without flags (ISSUE 10 satellite);
* **rotation** — ``max_bytes`` seals segments with a ``rotate`` event
  + atomic rename, every segment is self-describing, and the analyzers
  re-assemble the set.
"""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import runtime, telemetry, training
from apex_tpu.prof import timeline
from apex_tpu.telemetry import export as tel_export
from apex_tpu.training import make_train_step


@pytest.fixture(autouse=True)
def _clean_recorder():
    telemetry.set_recorder(None)
    yield
    telemetry.set_recorder(None)


def _loss_fn(p, batch):
    x, y = batch
    return jnp.mean((x @ p["w"] - y) ** 2)


def _run_loop(k=4, n=8, dim=32):
    rs = np.random.RandomState(0)
    batches = [(rs.randn(4, dim).astype(np.float32),
                rs.randn(4, dim).astype(np.float32)) for _ in range(n)]
    init_fn, step_fn = make_train_step(_loss_fn, training.sgd(lr=0.01))
    pipe = runtime.StepPipeline(step_fn, k)
    state = init_fn({"w": jnp.asarray(rs.randn(dim, dim)
                                      .astype(np.float32) / 11.0)})
    state, reader = pipe.run(
        state, runtime.window_batches(iter(batches), k))
    reader.last()
    # deep-copy the fetched leaves: on CPU device_get can hand back
    # zero-copy views into device buffers, and a LATER loop's buffer
    # reuse would corrupt the first snapshot (flaky bitwise compare)
    return jax.tree_util.tree_map(
        lambda x: np.array(x, copy=True),
        jax.device_get(state.params))  # jaxlint: disable=J001 -- end-of-test host materialization


# -- rendering ----------------------------------------------------------------

def test_render_families(tmp_path):
    rec = telemetry.start(str(tmp_path / "r.jsonl"), watchdog=True,
                          example="t")
    rec.metrics.counter("steps_dispatched").inc(7)
    rec.metrics.gauge("steps_per_s").set(123.5)
    for v in (0.1, 0.2, 0.3):
        rec.metrics.histogram("window_dispatch_s").observe(v)
    text = tel_export.render(rec)
    assert "# TYPE apex_tpu_steps_dispatched_total counter" in text
    assert "apex_tpu_steps_dispatched_total 7" in text
    assert "apex_tpu_steps_per_s 123.5" in text
    assert 'apex_tpu_window_dispatch_s{quantile="0.5"} 0.2' in text
    assert "apex_tpu_window_dispatch_s_count 3" in text
    assert "apex_tpu_watchdog_ok 1" in text
    assert f'run_id="{rec.run_id}"' in text
    assert 'process_index="0"' in text
    rec.close()


def test_render_cumulative_histogram(tmp_path):
    """ISSUE 20 satellite: ``*_s`` histograms expose a TRUE cumulative
    ``_bucket{le=...}`` family with fixed bounds (rate()-able by
    external alerting) alongside the summary-style quantile gauges —
    counts cumulative in ``le``, a ``+Inf`` terminal equal to ``_count``,
    and sum/count consistent between the two families."""
    rec = telemetry.start(str(tmp_path / "r.jsonl"))
    h = rec.metrics.histogram("serving_ttft_s")
    for v in (0.004, 0.02, 0.02, 3.0):
        h.observe(v)
    rec.metrics.histogram("unbounded_things").observe(1.0)
    text = tel_export.render(rec)
    assert "# TYPE apex_tpu_serving_ttft_s_hist histogram" in text
    assert 'apex_tpu_serving_ttft_s_hist_bucket{le="0.005"} 1' in text
    assert 'apex_tpu_serving_ttft_s_hist_bucket{le="0.025"} 3' in text
    assert 'apex_tpu_serving_ttft_s_hist_bucket{le="2.5"} 3' in text
    assert 'apex_tpu_serving_ttft_s_hist_bucket{le="5"} 4' in text
    assert 'apex_tpu_serving_ttft_s_hist_bucket{le="+Inf"} 4' in text
    assert "apex_tpu_serving_ttft_s_hist_count 4" in text
    # the summary family is still present under the original name
    assert "# TYPE apex_tpu_serving_ttft_s summary" in text
    # non-`_s` instruments stay reservoir-only: no _bucket series
    assert "apex_tpu_unbounded_things_hist_bucket" not in text
    rec.close()


def test_render_nonfinite_values(tmp_path):
    """A NaN/inf gauge (an overflow-skipped window's loss) renders as
    the legal Prometheus literals instead of crashing the textfile
    into self-disable (regression: int(NaN) raised)."""
    rec = telemetry.start(str(tmp_path / "r.jsonl"))
    rec.metrics.gauge("loss").set(float("nan"))
    rec.metrics.gauge("hi").set(float("inf"))
    rec.metrics.gauge("lo").set(float("-inf"))
    text = tel_export.render(rec)
    assert "apex_tpu_loss NaN" in text
    assert "apex_tpu_hi +Inf" in text
    assert "apex_tpu_lo -Inf" in text
    rec.close()


def test_render_sanitizes_names(tmp_path):
    rec = telemetry.start(str(tmp_path / "r.jsonl"))
    rec.metrics.gauge("weird-name.with/chars").set(1)
    text = tel_export.render(rec)
    assert "apex_tpu_weird_name_with_chars 1" in text
    rec.close()


def test_run_info_label_values_escape(tmp_path):
    # run_info values are free-form caller strings (ISSUE 13 review):
    # quotes/backslashes/newlines must escape per the exposition format
    # or one bad label invalidates the whole scrape
    rec = telemetry.start(str(tmp_path / "r.jsonl"))
    rec.run_info["kv_cache_dtype"] = "int8"
    rec.run_info["build"] = 'rev "dirty"\\x\n'
    text = tel_export.render(rec)
    assert 'kv_cache_dtype="int8"' in text
    assert 'build="rev \\"dirty\\"\\\\x\\n"' in text
    assert 'rev "dirty"' not in text          # raw value never leaks
    rec.close()


def test_watchdog_alerts_render(tmp_path):
    rec = telemetry.start(str(tmp_path / "r.jsonl"), watchdog=True)
    # a memory event under the headroom floor fires the new rule
    rec.event("memory", phase="harvest", peak_bytes=99,
              bytes_limit=100, headroom_pct=1.0)
    text = tel_export.render(rec)
    assert "apex_tpu_watchdog_ok 0" in text
    assert ('apex_tpu_watchdog_rule_alerts_total'
            '{rule="memory_headroom"} 1') in text
    rec.close()


# -- textfile -----------------------------------------------------------------

def test_textfile_written_and_atomic(tmp_path):
    tf = str(tmp_path / "m.prom")
    rec = telemetry.start(str(tmp_path / "r.jsonl"),
                          export_textfile=tf, export_every_s=0.01)
    rec.metrics.counter("c").inc()
    import time
    time.sleep(0.02)
    rec.event("marker", op="tick")       # tick rides the event write
    assert os.path.exists(tf)
    assert not os.path.exists(tf + ".tmp")   # replaced, not left behind
    body = open(tf).read()
    assert body.endswith("\n")
    assert "apex_tpu_c_total 1" in body
    renders_before_close = rec.exporter.renders
    rec.close()                           # final render on close
    assert rec.exporter.renders == renders_before_close + 1


def test_unwritable_textfile_disables_itself(tmp_path, capsys):
    rec = telemetry.start(str(tmp_path / "r.jsonl"),
                          export_textfile=str(tmp_path / "no" / "m.prom"),
                          export_every_s=0.0)
    import time
    time.sleep(0.01)
    rec.event("marker", op="tick")
    assert rec.exporter.textfile is None      # disabled, not poisoned
    rec.event("marker", op="tick2")           # stream keeps working
    rec.close()
    events = timeline.load_events(str(tmp_path / "r.jsonl"))
    assert sum(1 for e in events if e["kind"] == "marker") == 2


# -- http endpoint ------------------------------------------------------------

def test_scrape_under_load(tmp_path):
    """Concurrent scrapes while the training loop emits: every response
    is complete exposition text carrying the loop's instruments."""
    rec = telemetry.start(str(tmp_path / "r.jsonl"), watchdog=True,
                          export_port=0)
    url = f"http://localhost:{rec.exporter.port}/metrics"
    bodies, errors = [], []

    def scrape():
        try:
            for _ in range(5):
                bodies.append(urllib.request.urlopen(url, timeout=10)
                              .read().decode())
        except Exception as e:            # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=scrape) for _ in range(3)]
    for t in threads:
        t.start()
    _run_loop()                            # emits while scrapes fly
    for t in threads:
        t.join()
    # one more scrape after the loop, while the recorder is still open:
    # the exposition must carry the loop's own instruments by now
    final = urllib.request.urlopen(url, timeout=10).read().decode()
    rec.close()
    assert not errors
    assert len(bodies) == 15
    for b in bodies:
        assert "apex_tpu_run_info" in b
        assert b.endswith("\n")
    assert "apex_tpu_steps_dispatched_total" in final
    assert "apex_tpu_window_dispatch_s_count" in final
    # endpoint is gone after close
    with pytest.raises(Exception):
        urllib.request.urlopen(url, timeout=2)


def test_http_404_off_path(tmp_path):
    rec = telemetry.start(str(tmp_path / "r.jsonl"), export_port=0)
    url = f"http://localhost:{rec.exporter.port}/other"
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(url, timeout=10)
    rec.close()


# -- disabled-path identity ---------------------------------------------------

def test_disabled_path_bitwise_identity(tmp_path):
    """Exporter-on vs telemetry-off: bitwise-identical parameters."""
    params_off = _run_loop()
    rec = telemetry.start(str(tmp_path / "r.jsonl"), watchdog=True,
                          export_textfile=str(tmp_path / "m.prom"),
                          export_port=0, export_every_s=0.01)
    params_on = _run_loop()
    rec.close()
    for a, b in zip(jax.tree_util.tree_leaves(params_off),
                    jax.tree_util.tree_leaves(params_on)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # the instrumented run actually exported something
    assert os.path.exists(tmp_path / "m.prom")
    body = open(tmp_path / "m.prom").read()
    assert "apex_tpu_steps_per_s" in body
    assert "apex_tpu_loader" not in body or True   # loader gauges optional


# -- env vars (ISSUE 10 satellite) --------------------------------------------

def test_start_from_env_unset_is_none(monkeypatch):
    monkeypatch.delenv("APEX_TPU_TELEMETRY", raising=False)
    assert telemetry.start_from_env(example="t") is None
    assert telemetry.get_recorder() is None


def test_start_requires_a_path(monkeypatch):
    monkeypatch.delenv("APEX_TPU_TELEMETRY", raising=False)
    with pytest.raises(ValueError, match="APEX_TPU_TELEMETRY"):
        telemetry.start()


def test_start_from_env_full_config(tmp_path, monkeypatch):
    path = str(tmp_path / "envrun.jsonl")
    tf = str(tmp_path / "env.prom")
    monkeypatch.setenv("APEX_TPU_TELEMETRY", path)
    monkeypatch.setenv("APEX_TPU_WATCHDOG", "1")
    monkeypatch.setenv("APEX_TPU_METRICS_TEXTFILE", tf)
    monkeypatch.setenv("APEX_TPU_METRICS_PORT", "0")
    rec = telemetry.start_from_env(example="env")
    assert rec is not None
    assert telemetry.get_recorder() is rec
    assert rec.watchdog is not None
    assert rec.exporter is not None
    assert rec.exporter.textfile == tf
    assert rec.exporter.port not in (None, 0)    # ephemeral port bound
    rec.close()
    events = timeline.load_events(path)
    assert events[0]["kind"] == "run"
    assert events[0]["meta"]["example"] == "env"


def test_env_watchdog_off_beats_default(tmp_path, monkeypatch):
    monkeypatch.setenv("APEX_TPU_WATCHDOG", "0")
    rec = telemetry.start(str(tmp_path / "r.jsonl"))
    assert rec.watchdog is None
    rec.close()
    # explicit argument beats the env var
    monkeypatch.setenv("APEX_TPU_WATCHDOG", "0")
    rec = telemetry.start(str(tmp_path / "r2.jsonl"), watchdog=True)
    assert rec.watchdog is not None
    rec.close()


# -- rotation (ISSUE 10 satellite) --------------------------------------------

def test_rotation_seals_segments(tmp_path):
    path = str(tmp_path / "rot.jsonl")
    rec = telemetry.start(path, max_bytes=500, example="rot")
    for i in range(60):
        rec.event("marker", op=f"m{i}")
    rec.close()
    segs = sorted(p for p in os.listdir(tmp_path)
                  if p.startswith("rot.jsonl."))
    assert segs, "rotation never happened"
    # every sealed segment ends with a rotate event and is bounded
    for seg in segs:
        lines = open(tmp_path / seg).read().splitlines()
        last = json.loads(lines[-1])
        assert last["kind"] == "rotate"
        assert os.path.getsize(tmp_path / seg) < 500 + 400
    # every segment AFTER the first opens with a self-describing run
    # continuation (same run_id, its own segment number)
    run0 = json.loads(open(tmp_path / segs[0]).readline())
    for seg in segs[1:] + ["rot.jsonl"]:
        head = json.loads(open(tmp_path / seg).readline())
        assert head["kind"] == "run"
        assert head["run_id"] == run0["run_id"]
        assert head["segment"] > 0


def test_rotated_set_reassembles(tmp_path):
    path = str(tmp_path / "rot.jsonl")
    rec = telemetry.start(path, max_bytes=400)
    n_markers = 50
    for i in range(n_markers):
        rec.event("marker", op=f"m{i}", seq=i)
    rec.close()
    events = timeline.load_events(path)     # base path finds the set
    markers = [e for e in events if e["kind"] == "marker"]
    assert len(markers) == n_markers
    assert [m["seq"] for m in markers] == list(range(n_markers))
    ts = [e["t"] for e in events]
    assert ts == sorted(ts)                  # one unbroken clock
    # a glob spelling works too
    events2 = timeline.load_events(str(tmp_path / "rot.jsonl*"))
    assert ([e for e in events2 if e["kind"] == "marker"]
            == markers)
    # summary landed in the LIVE file (the last segment)
    assert any(e["kind"] == "summary" for e in events)


def test_rotation_never_splits_mid_line(tmp_path):
    path = str(tmp_path / "rot.jsonl")
    rec = telemetry.start(path, max_bytes=300)
    for i in range(40):
        rec.event("marker", op="x" * 50, i=i)
    rec.close()
    for p in [path] + [str(tmp_path / s) for s in os.listdir(tmp_path)
                       if s.startswith("rot.jsonl.")]:
        for line in open(p):
            json.loads(line)                # every line parses whole
