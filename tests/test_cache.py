"""Warm-start engine tests (ISSUE 7): apex_tpu.cache persistent-cache
setup + AOT warmup of the StepPipeline device loop.

The acceptance pin: with ``cache.enable`` + ``pipe.warmup`` there are
ZERO compiles (and zero jit traces) after step 0 — every dispatch goes
through the AOT executable — and the trajectory is bitwise-identical to
a cold pipeline's.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import cache, runtime, training
from apex_tpu.prof import assert_trace_count, trace_count
from apex_tpu.training import make_train_step

K = 4


def _loss_fn(p, b):
    x, y = b
    return jnp.mean((x @ p["w"] - y) ** 2)


def _fresh_state(init_fn):
    return init_fn({"w": jnp.ones((8, 4))})


def _window(rng, k=K):
    return (jnp.asarray(rng.randn(k, 16, 8), jnp.float32),
            jnp.asarray(rng.randn(k, 16, 4), jnp.float32))


@pytest.fixture
def tx_pipe():
    init_fn, step_fn = make_train_step(_loss_fn, training.sgd(0.1),
                                       opt_level="O0")
    return init_fn, step_fn


def test_enable_sets_config_and_creates_dir(tmp_path):
    d = cache.enable(str(tmp_path / "xla_cache"))
    assert os.path.isdir(d)
    assert cache.is_enabled() and cache.cache_dir() == d
    assert jax.config.jax_compilation_cache_dir == d
    assert cache.enable(d) == d                      # idempotent


def test_persistent_cache_populates_on_compile(tmp_path):
    d = cache.enable(str(tmp_path / "xla_cache"))

    @jax.jit
    def f(x):
        return jnp.tanh(x) @ x

    np.testing.assert_allclose(np.asarray(f(jnp.eye(17))),
                               np.tanh(np.eye(17)) @ np.eye(17),
                               atol=1e-6)
    assert len(os.listdir(d)) > 0, (
        "persistent compilation cache wrote no entries — "
        "jax_compilation_cache_dir not honored on this backend")


def test_abstractify_pins_only_committed_shardings():
    x = jnp.ones((4, 4))                             # uncommitted
    y = jax.device_put(jnp.ones((4,)), jax.devices()[0])   # committed
    sx, sy = cache.abstractify((x, y))
    assert isinstance(sx, jax.ShapeDtypeStruct)
    assert sx.shape == (4, 4) and sx.sharding is None
    assert sy.sharding == y.sharding
    # non-array leaves ride through untouched
    assert cache.abstractify((3, x))[0] == 3


def test_signature_matches_runtime_retrace_signature():
    win = (jnp.zeros((2, 3), jnp.float32), np.ones((2,), np.int32))
    sig = cache.signature(win)
    assert sig == ("float32[2, 3]", "int32[2]")
    assert cache.signature(win) == sig               # stable


def test_warmup_zero_traces_and_bitwise_parity(tx_pipe):
    """The acceptance pin: zero jit traces after warmup (hot AND ragged
    tail), and the warmed trajectory is bitwise the cold one."""
    init_fn, step_fn = tx_pipe
    rng = np.random.RandomState(0)
    win = _window(rng)

    def run(warm):
        state = _fresh_state(init_fn)
        pipe = runtime.StepPipeline(step_fn, K, donate_window=False)
        if warm:
            pipe.warmup(state, win, tail=True)
        for _ in range(3):
            state, _ = pipe.step_window(state, win)
        state, metrics = pipe.step_window(state, win, K - 1)   # ragged
        return pipe, np.asarray(state.params["w"]), jax.device_get(metrics)

    warm_pipe, w_warm, m_warm = run(True)
    assert_trace_count(warm_pipe.loop, 0)
    assert_trace_count(warm_pipe.tail_loop, 0)
    cold_pipe, w_cold, m_cold = run(False)
    assert trace_count(cold_pipe.loop) >= 1
    np.testing.assert_array_equal(w_warm, w_cold)
    np.testing.assert_array_equal(np.ravel(m_warm["loss"]),
                                  np.ravel(m_cold["loss"]))


def test_warmup_from_shape_dtype_structs(tx_pipe):
    """The declared-(K, shape) form: warmup from ShapeDtypeStructs, no
    example window materialized (what real-data examples do)."""
    init_fn, step_fn = tx_pipe
    state = _fresh_state(init_fn)
    pipe = runtime.StepPipeline(step_fn, K, donate_window=False)
    win_sds = (jax.ShapeDtypeStruct((K, 16, 8), jnp.float32),
               jax.ShapeDtypeStruct((K, 16, 4), jnp.float32))
    pipe.warmup(state, win_sds)
    win = _window(np.random.RandomState(1))
    state, _ = pipe.step_window(state, win)
    state, _ = pipe.step_window(state, win)
    assert_trace_count(pipe.loop, 0)


def test_unwarmed_signature_falls_back_to_jit(tx_pipe):
    """A window shape never warmed is a lookup miss, not an error: the
    jit path traces for it while warmed shapes stay AOT."""
    init_fn, step_fn = tx_pipe
    state = _fresh_state(init_fn)
    pipe = runtime.StepPipeline(step_fn, K, donate_window=False)
    win = _window(np.random.RandomState(2))
    pipe.warmup(state, win)
    state, _ = pipe.step_window(state, win)
    assert trace_count(pipe.loop) == 0
    other = (jnp.asarray(np.random.RandomState(3).randn(K, 32, 8),
                         jnp.float32),
             jnp.asarray(np.random.RandomState(4).randn(K, 32, 4),
                         jnp.float32))
    state, metrics = pipe.step_window(state, other)  # jit path compiles
    assert trace_count(pipe.loop) == 1
    assert np.isfinite(np.ravel(jax.device_get(metrics)["loss"])).all()


def test_warm_cache_plus_warmup_end_to_end(tmp_path, tx_pipe):
    """cache.enable + warmup together: the full warm-start recipe the
    imagenet example ships behind --compilation-cache/--aot-warmup."""
    cache.enable(str(tmp_path / "xla_cache"))
    init_fn, step_fn = tx_pipe
    state = _fresh_state(init_fn)
    pipe = runtime.StepPipeline(step_fn, K, donate_window=False)
    win = _window(np.random.RandomState(5))
    pipe.warmup(state, win)
    for _ in range(2):
        state, _ = pipe.step_window(state, win)
    assert_trace_count(pipe.loop, 0)
    assert len(os.listdir(cache.cache_dir())) > 0


# -- static bucket params in the AOT key (ISSUE 11 satellite) -----------------

def test_signature_static_params_distinguish_buckets():
    """Two calls with identical array signatures but different static
    bucket params must key to DIFFERENT AOT entries."""
    win = (jnp.zeros((2, 3), jnp.float32),)
    s64 = cache.signature(win, static=(64,))
    s128 = cache.signature(win, static=(128,))
    assert s64 != s128
    assert s64[:-1] == s128[:-1] == cache.signature(win)
    # deterministic and order-sensitive; mixed types are legal keys
    assert cache.signature(win, static=(64,)) == s64
    assert cache.signature(win, static=("prefill", 64)) \
        != cache.signature(win, static=(64, "prefill"))


def test_static_bucket_aot_table_lookup_miss_falls_back():
    """The per-bucket AOT-table contract the serving engine relies on:
    warmed buckets dispatch through the compiled executable, an
    un-warmed bucket is a clean lookup miss that the jit path serves
    (one compile) with identical numerics."""
    def step(x, n_mask):
        # n_mask is a static python int riding the closure per bucket
        return jnp.tanh(x) * (jnp.arange(x.shape[-1]) < n_mask)

    x = jnp.asarray(np.random.RandomState(0).randn(4, 8), jnp.float32)
    aot = {}
    jits = {}

    def run(bucket):
        key = cache.signature((x,), static=(bucket,))
        fn = aot.get(key)
        if fn is None:                         # lookup miss -> jit path
            jfn = jits.setdefault(
                bucket, jax.jit(lambda x: step(x, bucket)))
            return jfn(x), False
        return fn(x), True

    # warm bucket 4 only
    jits[4] = jax.jit(lambda x: step(x, 4))
    aot[cache.signature((x,), static=(4,))] = cache.warmup(jits[4], x)

    out4, hit4 = run(4)
    assert hit4
    with assert_trace_count(jits[4], 0):       # AOT hit: zero jit traces
        out4b, _ = run(4)
    np.testing.assert_array_equal(np.asarray(out4), np.asarray(out4b))

    out6, hit6 = run(6)                        # never warmed: clean miss
    assert not hit6
    np.testing.assert_allclose(
        np.asarray(out6),
        np.tanh(np.asarray(x)) * (np.arange(8) < 6), atol=1e-6)
