"""Pallas NHWC implicit-GEMM conv (ISSUE 18): interpret-mode parity vs
the ``lax.conv_general_dilated`` oracle, fused-epilogue equivalence on a
real ResNet block, tune-dispatch bitwise parity, and the zero-retrace
warmup pin.  ``interpret=True`` runs the REAL kernels on CPU."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.conv import (conv2d, conv2d_ref, PallasConv,
                               conv_dispatch_stats,
                               reset_conv_dispatch_stats)
from apex_tpu.normalization.fused_bn_act import bn_act_epilogue_ref
from apex_tpu.prof import assert_trace_count


def _mk(rs, *shape, dtype=jnp.float32):
    return jnp.asarray(rs.randn(*shape), jnp.float32).astype(dtype)


# -- forward / backward parity vs the oracle ----------------------------------

_MATRIX = [
    # (x_shape, w_shape, stride, padding, dilation)
    ((2, 8, 8, 16), (3, 3, 16, 32), 1, "SAME", 1),      # the stage conv
    ((2, 9, 7, 8), (3, 3, 8, 16), 2, "SAME", 1),        # odd + stride
    ((2, 8, 8, 8), (1, 1, 8, 16), 1, "VALID", 1),       # pointwise
    ((2, 8, 8, 8), (1, 1, 8, 16), 2, "VALID", 1),       # strided 1x1
    ((2, 12, 12, 8), (3, 3, 8, 16), 1, "VALID", 2),     # dilated
    ((1, 14, 14, 8), (7, 7, 8, 16), 2, ((3, 3), (3, 3)), 1),  # stem-like
]


@pytest.mark.parametrize("case", range(len(_MATRIX)))
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_interpret_forward_parity(case, dtype):
    xs, ws, s, p, d = _MATRIX[case]
    rs = np.random.RandomState(case)
    x, w = _mk(rs, *xs, dtype=dtype), _mk(rs, *ws, dtype=dtype)
    out = conv2d(x, w, stride=s, padding=p, dilation=d, interpret=True)
    ref = conv2d_ref(x, w, stride=s, padding=p, dilation=d)
    assert out.shape == ref.shape and out.dtype == ref.dtype
    tol = 1e-1 if dtype == jnp.bfloat16 else 1e-4
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("case", range(len(_MATRIX)))
def test_interpret_dgrad_wgrad_parity(case):
    """The custom-VJP backward (dgrad via rotated-weight forward
    machinery, wgrad via the dedicated accumulation kernel) against
    jax's autodiff of the oracle."""
    xs, ws, s, p, d = _MATRIX[case]
    rs = np.random.RandomState(10 + case)
    x, w = _mk(rs, *xs), _mk(rs, *ws)

    def loss_k(x, w):
        return jnp.sum(jnp.sin(conv2d(x, w, stride=s, padding=p,
                                      dilation=d, interpret=True)))

    def loss_r(x, w):
        return jnp.sum(jnp.sin(conv2d_ref(x, w, stride=s, padding=p,
                                          dilation=d)))

    gx, gw = jax.grad(loss_k, argnums=(0, 1))(x, w)
    rx, rw = jax.grad(loss_r, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               rtol=1e-4, atol=1e-4)


# -- fused epilogue -----------------------------------------------------------

def _epilogue_operands(rs, o, n, oh, ow, with_z):
    mean = _mk(rs, o)
    invstd = jnp.abs(_mk(rs, o)) + 0.5
    scale, bias = _mk(rs, o), _mk(rs, o)
    z = _mk(rs, n, oh, ow, o) if with_z else None
    return mean, invstd, scale, bias, z


@pytest.mark.parametrize("with_z", [False, True])
def test_fused_epilogue_matches_explicit_chain(with_z):
    """conv+bn+relu(+z) in ONE kernel vs conv kernel then the epilogue
    reference — forward and every cotangent (x, w, mean, invstd, scale,
    bias, z).  Same conv feeds both sides, so only instruction-fusion
    epsilon separates them (the test_fused_bn_act tolerance)."""
    rs = np.random.RandomState(3)
    x, w = _mk(rs, 2, 8, 8, 16), _mk(rs, 3, 3, 16, 32)
    mean, invstd, scale, bias, z = _epilogue_operands(rs, 32, 2, 8, 8,
                                                      with_z)
    ep = (mean, invstd, scale, bias) + ((z,) if with_z else ())

    def fused(x, w, mean, invstd, scale, bias, z=None):
        return jnp.sum(jnp.sin(conv2d(
            x, w, mean=mean, invstd=invstd, scale=scale, bias=bias, z=z,
            relu=True, interpret=True)))

    def chain(x, w, mean, invstd, scale, bias, z=None):
        y = conv2d(x, w, interpret=True)
        return jnp.sum(jnp.sin(bn_act_epilogue_ref(
            y, mean, invstd, scale, bias, z, True)))

    args = (x, w) + ep
    nargs = len(args)
    f = fused(*args)
    c = chain(*args)
    np.testing.assert_allclose(float(f), float(c), rtol=1e-5, atol=1e-4)
    gf = jax.grad(fused, argnums=tuple(range(nargs)))(*args)
    gc = jax.grad(chain, argnums=tuple(range(nargs)))(*args)
    for a, r in zip(gf, gc):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=1e-4, rtol=1e-4)


def test_epilogue_argument_validation():
    x, w = jnp.ones((1, 4, 4, 8)), jnp.ones((3, 3, 8, 8))
    with pytest.raises(ValueError, match="together"):
        conv2d(x, w, mean=jnp.zeros(8))
    with pytest.raises(ValueError, match="epilogue"):
        conv2d(x, w, relu=True)
    with pytest.raises(ValueError, match="together"):
        conv2d(x, w, mean=jnp.zeros(8), invstd=jnp.ones(8),
               scale=jnp.ones(8))
    with pytest.raises(ValueError, match="output shape"):
        conv2d(x, w, mean=jnp.zeros(8), invstd=jnp.ones(8),
               z=jnp.ones((1, 2, 2, 8)))


# -- ResNet block via the conv_cls hook ---------------------------------------

def _tiny_resnet(conv_cls):
    from apex_tpu.models import ResNet18
    return ResNet18(num_classes=10, dtype=jnp.float32, sync_bn=True,
                    conv_cls=conv_cls)


def test_resnet_conv_cls_matches_nn_conv():
    """The conv_cls= hook is routing, not math: a PallasConv ResNet has
    the IDENTICAL param/stat pytree (same checkpoint) and matches the
    nn.Conv model's forward, grads, and BN stats on the same params."""
    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(2, 32, 32, 3), jnp.float32)
    m_pallas, m_plain = _tiny_resnet(PallasConv), _tiny_resnet(None)
    variables = m_pallas.init(jax.random.PRNGKey(0), x, train=True)
    v2 = m_plain.init(jax.random.PRNGKey(0), x, train=True)
    assert (jax.tree_util.tree_structure(variables)
            == jax.tree_util.tree_structure(v2))
    for a, b in zip(jax.tree_util.tree_leaves(variables),
                    jax.tree_util.tree_leaves(v2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def fwd(model, p):
        y, upd = model.apply({"params": p,
                              "batch_stats": variables["batch_stats"]},
                             x, train=True, mutable=["batch_stats"])
        return jnp.sum(y ** 2), upd

    (y_a, upd_a), g_a = jax.value_and_grad(
        lambda p: fwd(m_pallas, p), has_aux=True)(variables["params"])
    (y_b, upd_b), g_b = jax.value_and_grad(
        lambda p: fwd(m_plain, p), has_aux=True)(variables["params"])
    np.testing.assert_allclose(float(y_a), float(y_b), rtol=1e-6)
    for a, r in zip(jax.tree_util.tree_leaves(g_a),
                    jax.tree_util.tree_leaves(g_b)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=1e-5, rtol=1e-4)
    for a, r in zip(jax.tree_util.tree_leaves(upd_a),
                    jax.tree_util.tree_leaves(upd_b)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=1e-5, rtol=1e-4)


def test_depthwise_falls_back_and_is_counted():
    """Grouped/depthwise convs are outside the kernel's contract: the
    module routes them to XLA per site and the stats name the reason."""
    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(2, 8, 8, 16), jnp.float32)
    reset_conv_dispatch_stats()
    m = PallasConv(features=16, kernel_size=(3, 3), feature_group_count=16,
                   use_bias=False)
    import flax.linen as nn
    ref = nn.Conv(features=16, kernel_size=(3, 3), feature_group_count=16,
                  use_bias=False)
    v = m.init(jax.random.PRNGKey(0), x)
    vr = ref.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(np.asarray(m.apply(v, x)),
                               np.asarray(ref.apply(vr, x)),
                               rtol=1e-5, atol=1e-5)
    stats = conv_dispatch_stats()
    assert stats["fallback_sites"] >= 1
    assert stats["fallback_reasons"].get("groups", 0) >= 1
    reset_conv_dispatch_stats()


# -- dispatch & tuning --------------------------------------------------------

def test_dispatch_gates():
    x, w = jnp.ones((1, 4, 4, 8)), jnp.ones((3, 3, 8, 8))
    with pytest.raises(ValueError, match="impl"):
        conv2d(x, w, impl="bogus")
    # off-TPU, impl="pallas" still routes to the jnp reference (the
    # TPU gate wins) — same shape/result, no crash
    out = conv2d(x, w, impl="pallas")
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(conv2d_ref(x, w)))


def test_tuned_blocks_match_default_bitwise():
    """The tune sweep's correctness premise (exact=True in the
    registry): block partitioning never reorders an output element's
    tap/K reduction, so ANY legal (block_m, block_n) is bitwise equal
    to the defaults in fp32."""
    rs = np.random.RandomState(6)
    x, w = _mk(rs, 2, 10, 10, 16), _mk(rs, 3, 3, 16, 32)
    mean, invstd, scale, bias, z = _epilogue_operands(rs, 32, 2, 10, 10,
                                                      True)
    kw = dict(mean=mean, invstd=invstd, scale=scale, bias=bias, z=z,
              relu=True, interpret=True)
    base = conv2d(x, w, **kw)
    for bm, bn in ((128, 128), (256, 512), (1024, 128)):
        tuned = conv2d(x, w, block_m=bm, block_n=bn, **kw)
        assert np.array_equal(np.asarray(base), np.asarray(tuned)), \
            (bm, bn)


def test_zero_retrace_after_warmup():
    """One compile on warmup, zero on steady-state repeats — the
    trace-count pin behind the StepPipeline.warmup acceptance."""
    rs = np.random.RandomState(7)
    x, w = _mk(rs, 2, 8, 8, 16), _mk(rs, 3, 3, 16, 32)
    mean, invstd, scale, bias, z = _epilogue_operands(rs, 32, 2, 8, 8,
                                                      True)

    @jax.jit
    def step(x, w, mean, invstd, scale, bias, z):
        out, grads = jax.value_and_grad(
            lambda x, w: jnp.sum(conv2d(x, w, mean=mean, invstd=invstd,
                                        scale=scale, bias=bias, z=z,
                                        relu=True, interpret=True) ** 2),
            argnums=(0, 1))(x, w)
        return out, grads

    with assert_trace_count(step, 1):
        step(x, w, mean, invstd, scale, bias, z)
    with assert_trace_count(step, 0):
        for _ in range(3):
            step(x, w, mean, invstd, scale, bias, z)
