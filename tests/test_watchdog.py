"""Run-health watchdog (ISSUE 6): every rule fires on a synthetic
stream, debounce bounds the alert rate, and the disabled path stays a
bitwise no-op.

The watchdog folds events ON the recorder thread — these tests drive it
both synthetically (events injected straight through ``Recorder.event``,
so each rule's trigger shape is pinned exactly) and through a real
:class:`~apex_tpu.runtime.StepPipeline` loop (instrumentation-wiring
proof + the bitwise-identity acceptance pin).
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import runtime, telemetry, training
from apex_tpu.prof import assert_trace_count
from apex_tpu.telemetry import watchdog as wdog
from apex_tpu.training import make_train_step


@pytest.fixture(autouse=True)
def _clean_recorder():
    telemetry.set_recorder(None)
    yield
    telemetry.set_recorder(None)


def _recorder(tmp_path, **kw):
    rec = telemetry.Recorder(str(tmp_path / "run.jsonl"))
    wd = wdog.attach(rec, **kw)
    return rec, wd


def _alerts(rec):
    rec.close()
    with open(rec.path) as f:
        return [e for e in (json.loads(line) for line in f
                            if line.strip())
                if e["kind"] == "alert"]


# -- individual rules ---------------------------------------------------------

def test_nonfinite_rule_fires_with_global_step(tmp_path):
    rec, wd = _recorder(tmp_path)
    rec.event("metrics", step=8, n_valid=4,
              loss=[1.0, 2.0, float("nan"), 1.0])
    alerts = _alerts(rec)
    assert [a["rule"] for a in alerts] == ["nonfinite"]
    assert alerts[0]["step"] == 10                 # 8 + offset 2
    assert alerts[0]["severity"] == "critical"


def test_nonfinite_inf_counts_too(tmp_path):
    rec, wd = _recorder(tmp_path)
    rec.event("metrics", step=0, n_valid=1, loss=[float("inf")])
    assert [a["rule"] for a in _alerts(rec)] == ["nonfinite"]


def test_scale_collapse_on_consecutive_skips(tmp_path):
    rec, wd = _recorder(tmp_path)
    for s in range(10, 14):                       # 4 consecutive skips
        rec.event("scale", event="skip", step=s, scale=4096.0)
    alerts = _alerts(rec)
    assert [a["rule"] for a in alerts] == ["scale_collapse"]
    assert alerts[0]["step"] == 13
    assert "consecutive" in alerts[0]["message"]


def test_scale_collapse_isolated_skips_are_benign(tmp_path):
    """Dynamic scaling EXPECTS occasional skips: non-consecutive ones
    (and growth in between) must not alert."""
    rec, wd = _recorder(tmp_path)
    for s in (10, 40, 80):
        rec.event("scale", event="skip", step=s, scale=4096.0)
        rec.event("scale", event="grow", step=s + 16, scale=8192.0)
    assert _alerts(rec) == []
    assert wd.health()["ok"]


def test_scale_collapse_on_floor(tmp_path):
    rec, wd = _recorder(tmp_path)
    rec.event("scale", event="skip", step=5, scale=1.0)
    alerts = _alerts(rec)
    assert [a["rule"] for a in alerts] == ["scale_collapse"]
    assert "floor" in alerts[0]["message"]


def test_loader_stall_from_final_snapshot(tmp_path):
    rec, wd = _recorder(tmp_path)
    rec.event("loader", phase="exhausted",
              stats={"loader_stall_pct": 45.0})
    alerts = _alerts(rec)
    assert [a["rule"] for a in alerts] == ["loader_stall"]
    assert alerts[0]["value"] == 45.0


def test_loader_stall_rolling_window_synthetic():
    """A rolling window of loader_wait events exceeding the stall
    threshold alerts DURING the run (before any final snapshot);
    timestamps are synthetic so the fraction is deterministic."""
    rule = wdog._LoaderStall(stall_pct=30.0, window=8)
    hit = None
    for i in range(9):
        hit = rule.observe({"kind": "loader_wait", "t": i * 0.1,
                            "dur": 0.06}) or hit
    assert hit is not None and hit["value"] > 30.0
    # healthy loader: 1 ms waits over the same wall never alerts
    rule2 = wdog._LoaderStall(stall_pct=30.0, window=8)
    for i in range(20):
        assert rule2.observe({"kind": "loader_wait", "t": i * 0.1,
                              "dur": 0.001}) is None


def test_loader_stall_no_false_positive_after_window_fills():
    """Review regression pin: after the measurement window fills, the
    wait sum and the wall anchor reset TOGETHER — a healthy loader
    (1 ms waits every 100 ms, true stall 1%) must never alert, no
    matter how many windows elapse."""
    rule = wdog._LoaderStall(stall_pct=30.0, window=8)
    for i in range(100):
        assert rule.observe({"kind": "loader_wait", "t": i * 0.1,
                             "dur": 0.001}) is None
    # and a genuinely stalling stretch STILL alerts after clean windows
    hit = None
    for i in range(100, 109):
        hit = rule.observe({"kind": "loader_wait", "t": i * 0.1,
                            "dur": 0.06}) or hit
    assert hit is not None and hit["value"] > 30.0


def test_step_time_anomaly_vs_rolling_baseline(tmp_path):
    rec, wd = _recorder(tmp_path)
    for i in range(12):
        rec.event("window", step=i, k=1, n_valid=1, dur=0.01, gap=0.0,
                  program="hot")
    rec.event("window", step=12, k=1, n_valid=1, dur=0.2, gap=0.0,
              program="hot")
    alerts = _alerts(rec)
    assert [a["rule"] for a in alerts] == ["step_time"]
    assert alerts[0]["step"] == 12
    assert "x the rolling median" in alerts[0]["message"]


def test_step_time_waits_for_baseline(tmp_path):
    """Compile-sized windows BEFORE the baseline fills (min_samples)
    must not alert — warmup is not an anomaly."""
    rec, wd = _recorder(tmp_path)
    rec.event("window", step=0, k=1, n_valid=1, dur=3.0, gap=0.0,
              program="hot")                       # the compile call
    for i in range(1, 6):
        rec.event("window", step=i, k=1, n_valid=1, dur=0.01, gap=0.0,
                  program="hot")
    assert _alerts(rec) == []


def test_retrace_storm_counts_only_true_retraces(tmp_path):
    rec, wd = _recorder(tmp_path)
    # first compiles and benign re-specializations never count
    rec.event("retrace", program="hot", step=0, n_traces=1, first=True,
              new_sig=True, sig="a")
    rec.event("retrace", program="hot", step=1, n_traces=2, first=False,
              new_sig=False, sig="a")
    for i in range(3):                             # the storm
        rec.event("retrace", program="hot", step=10 + i,
                  n_traces=3 + i, first=False, new_sig=True,
                  sig=f"s{i}")
    alerts = _alerts(rec)
    assert [a["rule"] for a in alerts] == ["retrace_storm"]
    assert alerts[0]["value"] == 3


def test_checkpoint_stall_on_slow_snapshot(tmp_path):
    """ISSUE 9: the async engine's contract is a cheap snapshot trigger
    — a snapshot span over the threshold alerts; fast ones (and the
    background serialize/commit spans, however long) stay silent."""
    rec, wd = _recorder(tmp_path, ckpt_stall_s=0.5)
    rec.event("checkpoint", phase="snapshot", step=10, dur=0.01,
              bytes=100)
    rec.event("checkpoint", phase="serialize", step=10, dur=30.0,
              bytes=100)                       # writer thread: fine
    rec.event("checkpoint", phase="commit", step=10, dur=30.0)
    assert _alerts(rec) == []

    rec2, wd2 = _recorder(tmp_path, ckpt_stall_s=0.5)
    rec2.event("checkpoint", phase="snapshot", step=20, dur=1.7,
               bytes=100)
    alerts = _alerts(rec2)
    assert [a["rule"] for a in alerts] == ["checkpoint_stall"]
    assert alerts[0]["step"] == 20
    assert "snapshot" in alerts[0]["message"]


def test_checkpoint_stall_on_writer_backlog(tmp_path):
    rec, wd = _recorder(tmp_path)
    rec.event("checkpoint", phase="backlog", step=30, value=2)
    alerts = _alerts(rec)
    assert [a["rule"] for a in alerts] == ["checkpoint_stall"]
    assert "backlog" in alerts[0]["message"]


def test_checkpoint_failed_is_critical(tmp_path):
    rec, wd = _recorder(tmp_path)
    rec.event("checkpoint", phase="error", step=40,
              error="OSError: disk full")
    alerts = _alerts(rec)
    assert [a["rule"] for a in alerts] == ["checkpoint_failed"]
    assert alerts[0]["severity"] == "critical"
    assert "disk full" in str(alerts[0]["value"])


def test_checkpoint_rules_are_debounced(tmp_path):
    """A wedged writer failing every save gets one alert per debounce
    window, not one per failure."""
    rec, wd = _recorder(tmp_path, debounce_steps=64)
    for step in range(0, 200, 4):
        rec.event("checkpoint", phase="error", step=step, error="boom")
    alerts = [a for a in _alerts(rec) if a["rule"] == "checkpoint_failed"]
    assert 2 <= len(alerts) <= 5


def test_manager_snapshot_stall_reaches_watchdog(tmp_path):
    """End to end: a real CheckpointManager save under an attached
    watchdog with a zero threshold folds its own snapshot event into a
    checkpoint_stall alert — the wiring, not just the rule."""
    from apex_tpu.checkpoint import CheckpointManager

    rec = telemetry.Recorder(str(tmp_path / "run.jsonl"))
    wd = wdog.attach(rec, ckpt_stall_s=0.0)
    telemetry.set_recorder(rec)
    try:
        with CheckpointManager(str(tmp_path / "ck")) as mgr:
            mgr.save(1, {"w": jnp.ones((8,))}, block=True)
    finally:
        telemetry.set_recorder(None)
    alerts = _alerts(rec)
    assert "checkpoint_stall" in [a["rule"] for a in alerts]


# -- debounce -----------------------------------------------------------------

def test_debounce_bounds_alert_rate(tmp_path):
    """A wedged run triggering every window gets ONE alert per rule per
    debounce window, not one per event."""
    rec, wd = _recorder(tmp_path, debounce_steps=64)
    for step in range(0, 200, 4):
        rec.event("metrics", step=step, n_valid=4,
                  loss=[float("nan")] * 4)
    alerts = _alerts(rec)
    # steps 0..196: debounce at 64 -> alerts near steps 0/64/128/192
    assert 3 <= len(alerts) <= 4
    steps = [a["step"] for a in alerts]
    assert all(b - a >= 64 for a, b in zip(steps, steps[1:]))


def test_debounce_is_per_rule(tmp_path):
    """One rule firing must not suppress a DIFFERENT rule."""
    rec, wd = _recorder(tmp_path, debounce_steps=1000)
    rec.event("metrics", step=0, n_valid=1, loss=[float("nan")])
    rec.event("scale", event="skip", step=1, scale=1.0)
    assert sorted(a["rule"] for a in _alerts(rec)) \
        == ["nonfinite", "scale_collapse"]


# -- stream + summary integration ---------------------------------------------

def test_alerts_land_in_stream_summary_and_analyzer(tmp_path):
    rec, wd = _recorder(tmp_path)
    rec.event("metrics", step=3, n_valid=1, loss=[float("nan")])
    rec.close()
    with open(rec.path) as f:
        events = [json.loads(line) for line in f if line.strip()]
    kinds = [e["kind"] for e in events]
    assert "alert" in kinds
    summary = events[-1]
    assert summary["kind"] == "summary"
    assert summary["health"]["ok"] is False
    assert summary["health"]["by_rule"] == {"nonfinite": 1}
    assert summary["events"]["alert"] == 1
    from apex_tpu.prof import timeline
    a = timeline.analyze(events)
    assert a["alerts"] == {"total": 1, "by_rule": {"nonfinite": 1},
                           "steps": [3]}
    assert "watchdog alert" in timeline.format_report(a)


def test_health_line_formats(tmp_path):
    rec, wd = _recorder(tmp_path)
    assert wd.format_line() == "ok (0 alerts)"
    rec.event("metrics", step=0, n_valid=1, loss=[float("nan")])
    line = wd.format_line()
    assert line.startswith("CRITICAL") and "nonfinite x1" in line
    rec.close()


def test_telemetry_start_watchdog_kwarg(tmp_path):
    rec = telemetry.start(str(tmp_path / "r.jsonl"), watchdog=True,
                          example="t")
    assert isinstance(rec.watchdog, telemetry.Watchdog)
    rec.close()
    rec2 = telemetry.start(str(tmp_path / "r2.jsonl"), example="t")
    assert rec2.watchdog is None
    rec2.close()


# -- through the real pipeline ------------------------------------------------

def _loss_fn(p, batch):
    x, y = batch
    return jnp.mean((x @ p["w"] - y) ** 2)


def _batches(n, bad_step=None):
    rng = np.random.RandomState(0)
    out = [(rng.randn(8, 4).astype(np.float32),
            rng.randn(8, 2).astype(np.float32)) for _ in range(n)]
    if bad_step is not None:
        x, y = out[bad_step]
        out[bad_step] = (x, np.full_like(y, np.inf))
    return out


def _run_pipeline(batches, rec=None):
    init_fn, step_fn = make_train_step(
        _loss_fn, training.sgd(lr=0.1), opt_level="O2",
        loss_scale="dynamic")
    pipe = runtime.StepPipeline(step_fn, k=4, telemetry=rec)
    state = init_fn({"w": jnp.ones((4, 2), jnp.float32)})
    with assert_trace_count(pipe.loop, 1):
        state, reader = pipe.run(
            state, runtime.window_batches(iter(batches), 4),
            on_metrics=lambda wm: wm.fetch())
    return state


def test_disabled_path_bitwise_identical_with_watchdog(tmp_path):
    """The acceptance pin: a telemetry+watchdog-enabled run produces
    BITWISE-identical parameters to the disabled run, with the hot
    program compiled exactly once (asserted inside _run_pipeline)."""
    batches = _batches(12, bad_step=5)
    off = _run_pipeline(batches, rec=None)
    rec = telemetry.Recorder(str(tmp_path / "run.jsonl"))
    wdog.attach(rec)
    on = _run_pipeline(batches, rec=rec)
    rec.close()
    for a, b in zip(jax.tree_util.tree_leaves(off.params),
                    jax.tree_util.tree_leaves(on.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_clean_pipeline_run_raises_no_alerts(tmp_path):
    rec = telemetry.Recorder(str(tmp_path / "run.jsonl"))
    wd = wdog.attach(rec)
    _run_pipeline(_batches(8), rec=rec)
    rec.close()
    assert wd.health()["ok"], wd.alerts


def test_nan_loss_through_pipeline_alerts(tmp_path):
    """End to end: a poisoned batch -> deferred fetch -> metrics event
    -> nonfinite alert in the stream, at the right global step."""
    rec = telemetry.Recorder(str(tmp_path / "run.jsonl"))
    wd = wdog.attach(rec)
    _run_pipeline(_batches(12, bad_step=6), rec=rec)
    rec.close()
    nonfin = [a for a in wd.alerts if a["rule"] == "nonfinite"]
    assert nonfin and nonfin[0]["step"] == 6


def test_serving_queue_stall_rule(tmp_path):
    """ISSUE 11: an admit event whose queue wait exceeds the threshold
    alerts; fast admissions and non-admit serving events stay silent;
    debounce bounds repeats."""
    rec, wd = _recorder(tmp_path, serving_stall_s=0.5)
    rec.event("serving", phase="submit", queue_depth=3)
    rec.event("serving", phase="admit", slot=0, queue_wait=0.1)   # fast
    rec.event("serving", phase="decode", active=1, dur=0.01)
    rec.event("serving", phase="admit", slot=1, queue_wait=1.7)   # stall
    for _ in range(5):                              # debounced repeats
        rec.event("serving", phase="admit", slot=2, queue_wait=2.0)
    alerts = _alerts(rec)
    assert [a["rule"] for a in alerts] == ["serving_queue_stall"]
    assert alerts[0]["severity"] == "warning"
    assert alerts[0]["value"] == 1.7


def test_serving_queue_stall_threshold_kwarg(tmp_path):
    rec, wd = _recorder(tmp_path, serving_stall_s=10.0)
    rec.event("serving", phase="admit", queue_wait=3.0)
    assert _alerts(rec) == []
