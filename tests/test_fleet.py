"""Fleet observability (ISSUE 10): multi-host stream merge, clock
alignment, straggler attribution, and the per-host Chrome pid lanes.

The contracts tier-1 pins here:

* **merge ordering** — N per-host streams (globs, explicit paths, and
  rotated sets) load as one fleet, attributed by the ``run`` event's
  ``process_index`` stamp, with rotated segments re-assembled in
  sequence order;
* **clock alignment** — the aligner recovers a known injected
  wall-anchor skew from the window dispatch indices alone (the anchor
  gets the streams within coarse range; the per-step median closes it);
* **straggler attribution** — the injected slow host is named slowest
  on EVERY window of the synthetic 4-host fixture (the bench gate's
  exact criterion), loader-stall asymmetry names the stalling host;
* **fleet Chrome trace** — one ``pid`` lane per host, process_name
  metadata per lane, events shifted onto the aligned clock.

Everything here is pure host-side JSON — it rides the tier matrix
(docker/run_matrix.sh FAST) because every degradation tier must
analyze identical fixtures identically.
"""

import json
import os

import pytest

from apex_tpu import telemetry
from apex_tpu.prof import fleet


@pytest.fixture(autouse=True)
def _clean_recorder():
    telemetry.set_recorder(None)
    yield
    telemetry.set_recorder(None)


SLOW = 2
CLOCK_ERR = (0.040, -0.040, 0.080, -0.080)


@pytest.fixture
def fixture_dir(tmp_path):
    fleet.synthetic_fleet(4, 12, 4, slow_host=SLOW,
                          clock_err_s=CLOCK_ERR, dir=str(tmp_path))
    return tmp_path


# -- merge / load -------------------------------------------------------------

def test_load_fleet_glob_and_explicit(fixture_dir):
    via_glob = fleet.load_fleet([str(fixture_dir / "host*.jsonl")])
    explicit = fleet.load_fleet(
        [str(fixture_dir / f"host{h}.jsonl") for h in range(4)])
    assert [s.host for s in via_glob] == [0, 1, 2, 3]
    assert [s.host for s in explicit] == [0, 1, 2, 3]
    for s in via_glob:
        assert s.run_id == "fleet-fixture-0"
        assert s.process_count == 4
        assert s.anchor_unix is not None
        assert len(s.windows) == 12


def test_load_fleet_nothing_matched(tmp_path):
    with pytest.raises(ValueError, match="no telemetry events"):
        fleet.load_fleet([str(tmp_path / "nope*.jsonl")])


def test_load_fleet_duplicate_process_index(tmp_path):
    """Two streams stamped with the same index must stay two hosts —
    folding them together would corrupt every skew number."""
    events = fleet.synthetic_fleet(2, 4, 4, slow_host=1,
                                   clock_err_s=(0.0, 0.0))
    for name, evs in (("a.jsonl", events[0]), ("b.jsonl", events[0])):
        with open(tmp_path / name, "w") as f:
            for e in evs:
                f.write(json.dumps(e) + "\n")
    streams = fleet.load_fleet([str(tmp_path / "a.jsonl"),
                                str(tmp_path / "b.jsonl")])
    assert len({s.host for s in streams}) == 2


def test_merge_accepts_rotated_set(tmp_path):
    """A host whose stream rotated mid-run merges from its segments in
    order — same windows as the unrotated stream."""
    events = fleet.synthetic_fleet(2, 6, 4, slow_host=1,
                                   clock_err_s=(0.0, 0.0))
    # host0: write an artificially rotated set (segment split mid-way)
    base = tmp_path / "host0.jsonl"
    seg = tmp_path / "host0.jsonl.1"
    cut = len(events[0]) // 2
    with open(seg, "w") as f:
        for e in events[0][:cut]:
            f.write(json.dumps(e) + "\n")
    with open(base, "w") as f:
        for e in events[0][cut:]:
            f.write(json.dumps(e) + "\n")
    with open(tmp_path / "host1.jsonl", "w") as f:
        for e in events[1]:
            f.write(json.dumps(e) + "\n")
    streams = fleet.load_fleet([str(tmp_path / "host*.jsonl")])
    assert len(streams) == 2
    h0 = next(s for s in streams if s.host == 0)
    assert len(h0.windows) == 6          # both segments contributed
    ts = [e["t"] for e in h0.events]
    assert ts == sorted(ts)              # segment order preserved


# -- clock alignment ----------------------------------------------------------

def test_clock_alignment_recovers_injected_skew(fixture_dir):
    streams = fleet.load_fleet([str(fixture_dir / "host*.jsonl")])
    align = fleet.align_clocks(streams)
    for h in range(4):
        expected_ms = (CLOCK_ERR[h] - CLOCK_ERR[0]) * 1e3
        got_ms = 1e3 * align[h]["clock_skew_s"]
        assert abs(got_ms - expected_ms) <= 5.0, (h, got_ms, expected_ms)
        assert align[h]["anchored"]
        assert align[h]["common_windows"] == 12


def test_alignment_without_anchors(tmp_path):
    """Streams that predate the anchor stamp still align (windows
    alone), and are flagged unanchored."""
    events = fleet.synthetic_fleet(2, 6, 4, slow_host=1,
                                   clock_err_s=(0.0, 0.0))
    for h, evs in enumerate(events):
        with open(tmp_path / f"host{h}.jsonl", "w") as f:
            for e in evs:
                e = dict(e)
                e.pop("anchor_unix", None)
                f.write(json.dumps(e) + "\n")
    streams = fleet.load_fleet([str(tmp_path / "host*.jsonl")])
    align = fleet.align_clocks(streams)
    assert not align[0]["anchored"] and not align[1]["anchored"]
    a = fleet.analyze_fleet(streams)
    assert a["straggler"]["host"] == 1


# -- straggler attribution ----------------------------------------------------

def test_straggler_identified_every_window(fixture_dir):
    streams = fleet.load_fleet([str(fixture_dir / "host*.jsonl")])
    a = fleet.analyze_fleet(streams)
    assert a["n_hosts"] == 4
    assert len(a["windows"]) == 12
    assert all(w["slowest_host"] == SLOW for w in a["windows"])
    st = a["straggler"]
    assert st["host"] == SLOW
    assert st["windows_slowest"] == st["windows_total"] == 12
    assert st["consistent"]
    assert st["mean_skew_ms"] > 0


def test_no_consistent_straggler_when_balanced(tmp_path):
    """With no injected slow host the slowest rotates with the seeded
    jitter — nobody should be called the consistent straggler."""
    fleet.synthetic_fleet(4, 12, 4, slow_host=0, slow_factor=1.0,
                          stall_host=0, clock_err_s=(0, 0, 0, 0),
                          dir=str(tmp_path))
    streams = fleet.load_fleet([str(tmp_path / "host*.jsonl")])
    a = fleet.analyze_fleet(streams)
    assert not a["straggler"]["consistent"]


def test_loader_asymmetry_and_skew_table(fixture_dir):
    streams = fleet.load_fleet([str(fixture_dir / "host*.jsonl")])
    a = fleet.analyze_fleet(streams)
    lo = a["loader"]
    assert lo["worst_host"] == SLOW
    assert lo["asymmetric"]
    assert lo["spread_pct_points"] > 10
    hosts = {h["host"]: h for h in a["hosts"]}
    assert hosts[SLOW]["loader_stall_pct"] == 35.0
    # per-host rows carry per-host timeline numbers
    assert all(h["steps"] == 48 for h in a["hosts"])


def test_wait_vs_wire_split(fixture_dir):
    streams = fleet.load_fleet([str(fixture_dir / "host*.jsonl")])
    a = fleet.analyze_fleet(streams, ici_gb_s=100.0)
    co = a["collectives"]
    assert co["by_op"], "fixture's psum must appear"
    c = co["by_op"][0]
    assert c["op"] == "psum"
    assert c["bytes_per_step"] == 4_000_000
    # ring all-reduce at N=4: 2(N-1)/N = 1.5x the payload per link,
    # 4 MB * 1.5 at 100 GB/s = 0.06 ms wire
    assert c["wire_factor"] == 1.5
    assert abs(c["wire_ms_modeled"] - 0.06) < 1e-6
    assert c["wait_ms_modeled"] > 0
    assert 0 <= c["wait_pct"] <= 100
    assert c["participants"] == 4


def test_schema_version_rides_fleet_json(fixture_dir):
    from apex_tpu.prof.timeline import SCHEMA_VERSION, \
        check_schema_version
    streams = fleet.load_fleet([str(fixture_dir / "host*.jsonl")])
    a = fleet.analyze_fleet(streams)
    assert a["schema_version"] == SCHEMA_VERSION
    check_schema_version(a, "fleet")     # round-trips its own schema


# -- chrome export ------------------------------------------------------------

def test_fleet_chrome_pid_lanes(fixture_dir, tmp_path):
    streams = fleet.load_fleet([str(fixture_dir / "host*.jsonl")])
    out = str(tmp_path / "fleet_trace.json")
    n = fleet.to_fleet_chrome_trace(streams, out)
    assert n > 0
    with open(out) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    assert {e["pid"] for e in events} == {0, 1, 2, 3}
    names = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names == {f"host {h} of 4" for h in range(4)}
    # every lane carries real slices, on a shared non-negative clock
    for h in range(4):
        slices = [e for e in events if e["pid"] == h and e["ph"] == "X"
                  and e["name"].startswith("window@")]
        assert len(slices) == 12
        assert all(e["ts"] >= 0 for e in slices)


# -- CLI ----------------------------------------------------------------------

def test_cli_report_and_json(fixture_dir, tmp_path, capsys):
    rc = fleet.main([str(fixture_dir / "host*.jsonl")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "CONSISTENT straggler" in out
    assert "host 2" in out
    rc = fleet.main([str(fixture_dir / "host*.jsonl"), "--json",
                     "--chrome", str(tmp_path / "t.json")])
    cap = capsys.readouterr()
    assert rc == 0
    a = json.loads(cap.out)
    assert a["straggler"]["host"] == SLOW
    assert os.path.exists(tmp_path / "t.json")
    assert "pid lanes" in cap.err


def test_cli_no_match_exits_2(tmp_path, capsys):
    rc = fleet.main([str(tmp_path / "none*.jsonl")])
    assert rc == 2
    assert "error" in capsys.readouterr().err


# -- end-to-end: real recorders, merged ---------------------------------------

def test_real_recorders_merge(tmp_path):
    """Four REAL Recorders (explicit process stamps) round-trip through
    the merge: host identity, window matching, per-host analysis."""
    import time
    for h in range(4):
        rec = telemetry.Recorder(str(tmp_path / f"h{h}.jsonl"),
                                 meta={"example": "t"},
                                 run_id="merged-run",
                                 process_index=h, process_count=4)
        for w in range(3):
            rec.event("window", step=w * 2, k=2, n_valid=2,
                      dur=0.010 * (2 if h == 3 else 1), gap=0.001)
        rec.close()
    streams = fleet.load_fleet([str(tmp_path / "h*.jsonl")])
    assert [s.host for s in streams] == [0, 1, 2, 3]
    assert all(s.run_id == "merged-run" for s in streams)
    a = fleet.analyze_fleet(streams)
    assert len(a["windows"]) == 3
    assert all(w["slowest_host"] == 3 for w in a["windows"])


def test_collectives_attributed_per_axis(fixture_dir):
    """ISSUE 12 satellite: the axis names riding each collective event
    split the fleet wire model per mesh axis instead of one pool."""
    streams = fleet.load_fleet([str(fixture_dir / "host*.jsonl")])
    a = fleet.analyze_fleet(streams, ici_gb_s=100.0)
    by_axis = a["collectives"]["by_axis"]
    assert "data" in by_axis
    d = by_axis["data"]
    assert d["bytes_per_step"] == 4_000_000
    assert "psum" in d["ops"]
    # the per-axis wire model is the sum of that axis's per-op rows
    want = round(sum(c["wire_ms_modeled"]
                     for c in a["collectives"]["by_op"]
                     if c["axis"] == ["data"] or c["axis"] == "data"), 4)
    assert d["wire_ms_modeled"] == want
