"""Fault-injection harness (ISSUE 9): kill a training subprocess at
randomized steps — SIGTERM (graceful drain) and SIGKILL (hard crash,
possibly mid-checkpoint-write) — resume it, and assert the resumed
trajectory is BITWISE identical to an uninterrupted run.

Two halves:

* the **child trainer** (``python tests/faultinject.py --dir ...``): a
  deterministic little amp-O2 training loop on the real runtime stack —
  :class:`apex_tpu.runtime.StepPipeline` windows,
  :class:`apex_tpu.checkpoint.CheckpointManager` every ``--save-every``
  steps, :class:`apex_tpu.runtime.GracefulShutdown` drain — whose batch
  for global step *s* is a pure function of *s*, so any resume point
  replays the identical remaining stream.  Progress lines (``step N``)
  let the parent target a kill step; the final state serializes to
  ``--out`` with a ``FINAL N`` marker.
* the **harness functions** (:func:`run_child`, :func:`run_and_kill`)
  used by ``tests/test_faultinject.py`` — they launch the child with
  ``JAX_PLATFORMS=cpu``, watch stdout, deliver the signal at the chosen
  step, and return the transcript.

Window alignment note: the child keeps every checkpointable step on the
K-step window grid (``--save-every`` a multiple of ``--spc``, total
steps too), so a resumed run rebuilds the same full windows the
uninterrupted run executed — the bit-parity claim then compares the
same compiled programs over the same data.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


# -- harness (parent side) ----------------------------------------------------

def child_argv(**kw):
    """argv for one child trainer invocation."""
    argv = [sys.executable, os.path.join(REPO, "tests", "faultinject.py")]
    for k, v in kw.items():
        if v is None or v is False:
            continue
        flag = "--" + k.replace("_", "-")
        if v is True:
            argv.append(flag)
        else:
            argv.extend([flag, str(v)])
    return argv


def _spawn(argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)          # the child needs no virtual mesh
    return subprocess.Popen(argv, cwd=REPO, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def run_child(timeout=240, **kw):
    """Run the child trainer to completion; returns (returncode, stdout)."""
    proc = _spawn(child_argv(**kw))
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        raise AssertionError(f"faultinject child timed out:\n{out}")
    return proc.returncode, out


def _wait_for_valid_checkpoint(ck_dir, timeout=30.0):
    """Poll until ``ck_dir`` holds at least one PUBLISHED checkpoint —
    the async writer publishes a few ms after the save trigger, but a
    loaded CI box can reorder the parent's signal ahead of it; a kill
    delivered before ANY publish just tests a fresh start, not
    recovery.  Filesystem-only on purpose (importing jax here would
    stall the parent for seconds and let the child finish first): a
    manifest part is atomically renamed into place as the commit point,
    so its presence next to its shard file means published."""
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            for step in os.listdir(ck_dir):
                sdir = os.path.join(ck_dir, step)
                if not step.startswith("step_") or not os.path.isdir(sdir):
                    continue
                names = os.listdir(sdir)
                if any(n.startswith("manifest_") and n.endswith(".json")
                       for n in names) \
                        and any(n.startswith("shard_")
                                and n.endswith(".npz") for n in names):
                    return
        except OSError:
            pass
        time.sleep(0.01)
    raise AssertionError(
        f"no valid checkpoint appeared under {ck_dir} in {timeout}s")


def run_and_kill(sig, kill_after_step, timeout=240, **kw):
    """Run the child, deliver ``sig`` once a ``step N`` progress line
    reaches ``kill_after_step`` AND one valid checkpoint exists (so the
    kill exercises recovery, not a fresh start), and wait for exit.
    Returns ``(returncode, stdout_so_far)`` — for SIGTERM the child
    drains (rc 0, ``DRAINED`` marker); for SIGKILL it just dies
    (rc -9), possibly mid-checkpoint-write."""
    proc = _spawn(child_argv(**kw))
    lines = []
    sent = False
    t0 = time.time()
    try:
        for line in proc.stdout:
            lines.append(line)
            if time.time() - t0 > timeout:
                raise AssertionError(
                    "faultinject child outran the kill timeout:\n"
                    + "".join(lines))
            if not sent and line.startswith("step "):
                try:
                    step = int(line.split()[1])
                except (IndexError, ValueError):
                    continue
                if step >= kill_after_step:
                    _wait_for_valid_checkpoint(kw["dir"])
                    proc.send_signal(sig)
                    sent = True
                    if sig == signal.SIGKILL:
                        break
        proc.wait(timeout=timeout)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert sent, ("child finished before the kill step "
                  f"{kill_after_step}:\n" + "".join(lines))
    return proc.returncode, "".join(lines)


# -- child trainer (subprocess side) ------------------------------------------

def _child_main(argv=None):
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--dir", required=True)
    p.add_argument("--out", default=None)
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--spc", type=int, default=2)
    p.add_argument("--save-every", type=int, default=2)
    p.add_argument("--keep", type=int, default=3)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--step-delay", type=float, default=0.0,
                   help="host sleep per window so the parent's signal "
                        "can land mid-run")
    p.add_argument("--sync-writes", action="store_true",
                   help="CheckpointManager(async_write=False) — the "
                        "bench's synchronous baseline shape")
    args = p.parse_args(argv)

    sys.path.insert(0, REPO)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu import checkpoint, runtime, training
    from apex_tpu.training import make_train_step

    def batch_for(step: int):
        """The step's batch as a pure function of the GLOBAL step index
        — the whole determinism argument in one line."""
        rs = np.random.RandomState(1000 + step)
        return (rs.randn(8, 16).astype(np.float32),
                rs.randn(8, 4).astype(np.float32))

    params = {"w1": jnp.ones((16, 32), jnp.float32) * 0.05,
              "b1": jnp.zeros((32,), jnp.float32),
              "w2": jnp.ones((32, 4), jnp.float32) * 0.1}

    def loss_fn(prm, batch):
        x, y = batch
        h = jnp.tanh(x @ prm["w1"] + prm["b1"])
        return jnp.mean((h @ prm["w2"] - y) ** 2)

    init_fn, step_fn = make_train_step(
        loss_fn, training.adam(lr=1e-2), opt_level="O2",
        loss_scale="dynamic", keep_batchnorm_fp32=False)
    state = init_fn(params)

    k = max(1, args.spc)
    pipe = runtime.StepPipeline(step_fn, k)
    mgr = checkpoint.CheckpointManager(
        args.dir, every_steps=args.save_every, keep=args.keep,
        async_write=not args.sync_writes)
    start = 0
    if args.resume:
        restored = mgr.restore(like=state)
        if restored is not None:
            state = restored.state
            start = restored.step
            print(f"RESUMED {start}", flush=True)
    stop = runtime.GracefulShutdown().install()

    done = start
    drained = False
    while done < args.steps:
        if stop.draining:
            mgr.save(done, state, block=True)
            print(f"DRAINED {done}", flush=True)
            drained = True
            break
        n = min(k, args.steps - done)
        bs = [batch_for(done + j) for j in range(n)]
        bs += [bs[-1]] * (k - n)          # ragged tail pad (n_valid gates)
        window = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *bs)
        state, metrics = pipe.step_window(state, window, n)
        done += n
        # Fence the window before reporting progress: a kill landing
        # after this line can lose at most un-checkpointed steps, never
        # report steps that did not happen.
        runtime.WindowMetrics(0, n, metrics).fetch()
        print(f"step {done}", flush=True)
        mgr.maybe_save(done, state)
        if args.step_delay:
            time.sleep(args.step_delay)
    if not drained and stop.draining:
        mgr.save(done, state, block=True)
        print(f"DRAINED {done}", flush=True)
        drained = True
    mgr.close()
    stop.uninstall()
    if not drained and done >= args.steps and args.out:
        checkpoint.save_checkpoint(args.out, state, step=done)
        print(f"FINAL {done}", flush=True)


if __name__ == "__main__":
    _child_main()
