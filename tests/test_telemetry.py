"""Run-telemetry engine (ISSUE 5): event stream, metrics registry,
instrumentation wiring, and the offline timeline analyzer.

The contracts tier-1 pins here:

* **strict no-op when disabled** — with no recorder installed the
  instrumented loop produces BITWISE-identical parameters and traces
  exactly once (instrumentation causes zero retraces — the acceptance
  criterion's trace-count pin);
* **zero extra syncs** — device-side values enter the stream only
  through the one-dispatch-behind ``WindowMetrics.fetch`` the loop
  already pays;
* **single-snapshot loader attribution** — the ``loader`` event carries
  the same ``LoaderStats.as_dict()`` dict ``format_loader_line``
  prints, so the analyzer's stall number and the example's printed
  number cannot diverge (runs under the native/no-native tier matrix,
  like the bucket engine: the events are pure host Python, so tier-2
  must behave identically);
* the analyzer reconstructs step counts, loss-scale skip steps, retrace
  counts, and per-collective byte totals from the stream alone.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import data as apex_data
from apex_tpu import runtime, telemetry, training
from apex_tpu.prof import assert_trace_count, timeline
from apex_tpu.training import make_train_step

NDEV = 8


@pytest.fixture(autouse=True)
def _clean_recorder():
    """Never leak an active recorder across tests."""
    telemetry.set_recorder(None)
    yield
    telemetry.set_recorder(None)


@pytest.fixture(params=["native-default", "no-native"])
def native_tier(request, monkeypatch):
    """The loader telemetry path is pure host Python; the tier-2
    (no-native) install must emit identical event shapes."""
    if request.param == "no-native":
        monkeypatch.setenv("APEX_TPU_DISABLE_NATIVE", "1")
    return request.param


def _loss_fn(p, batch):
    x, y = batch
    return jnp.mean((x @ p["w"] - y) ** 2)


def _params():
    return {"w": jnp.ones((4, 2), jnp.float32)}


def _batches(n, seed=0, bad_step=None):
    rng = np.random.RandomState(seed)
    out = [(rng.randn(8, 4).astype(np.float32),
            rng.randn(8, 2).astype(np.float32)) for _ in range(n)]
    if bad_step is not None:
        x, y = out[bad_step]
        out[bad_step] = (x, np.full_like(y, np.inf))
    return out


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _kinds(events):
    return [e["kind"] for e in events]


# -- metrics registry ---------------------------------------------------------

def test_registry_counter_gauge_histogram():
    reg = telemetry.MetricsRegistry()
    reg.counter("n").inc()
    reg.counter("n").inc(3)
    reg.gauge("g").set(2.5)
    h = reg.histogram("h")
    for v in range(100):
        h.observe(float(v))
    snap = reg.snapshot()
    assert snap["counters"]["n"] == 4
    assert snap["gauges"]["g"] == 2.5
    hs = snap["histograms"]["h"]
    assert hs["count"] == 100 and hs["min"] == 0.0 and hs["max"] == 99.0
    assert 40.0 <= hs["p50"] <= 60.0
    assert hs["p99"] >= hs["p90"] >= hs["p50"]


def test_registry_reservoir_bounded_and_deterministic():
    a = telemetry.Histogram(reservoir=64, seed=7)
    b = telemetry.Histogram(reservoir=64, seed=7)
    for v in range(10_000):
        a.observe(v)
        b.observe(v)
    assert len(a._res) == 64
    assert a.percentiles() == b.percentiles()     # same seed, same answer
    p50 = a.percentiles((50.0,))[0]
    assert 2_000 <= p50 <= 8_000                  # uniform-ish sample


def test_registry_disabled_is_noop():
    reg = telemetry.MetricsRegistry(enabled=False)
    reg.counter("n").inc()
    reg.gauge("g").set(1.0)
    reg.histogram("h").observe(3.0)
    snap = reg.snapshot()
    assert snap == {"counters": {}, "gauges": {}, "histograms": {}}


# -- recorder core ------------------------------------------------------------

def test_recorder_jsonl_stream_and_summary(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with telemetry.Recorder(path, meta={"example": "test"}) as rec:
        rec.event("window", step=0, dur=0.25)
        with rec.span("opt_step", step=0):
            pass
        rec.metrics.counter("steps_dispatched").inc(4)
    ev = _events(path)
    assert _kinds(ev) == ["run", "window", "opt_step", "summary"]
    assert ev[0]["meta"] == {"example": "test"}
    assert all(e["t"] >= 0 for e in ev)
    assert ev[2]["dur"] >= 0
    summary = ev[-1]
    assert summary["events"]["window"] == 1
    assert summary["metrics"]["counters"]["steps_dispatched"] == 4


def test_recorder_close_idempotent_and_drops_late_events(tmp_path):
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.Recorder(path)
    rec.close()
    rec.close()
    rec.event("window", step=0)       # dropped, not an error
    assert _kinds(_events(path)) == ["run", "summary"]


def test_start_installs_and_close_clears_active(tmp_path):
    rec = telemetry.start(str(tmp_path / "r.jsonl"))
    assert telemetry.get_recorder() is rec
    rec.close()
    assert telemetry.get_recorder() is None


def test_recorder_tolerates_exotic_values(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with telemetry.Recorder(path) as rec:
        rec.event("marker", arr=np.arange(3), scalar=jnp.float32(1.5),
                  obj=object())
    ev = _events(path)          # every line parsed back as valid JSON
    assert ev[1]["arr"] == [0, 1, 2]
    assert ev[1]["scalar"] == 1.5


# -- StepPipeline / DeferredMetrics instrumentation ---------------------------

def _run_pipeline(k, batches, rec=None, fetch=True):
    init_fn, step_fn = make_train_step(
        _loss_fn, training.sgd(lr=0.1), opt_level="O2",
        loss_scale="dynamic", scale_window=4)
    pipe = runtime.StepPipeline(step_fn, k=k, telemetry=rec)
    state = init_fn(_params())
    with assert_trace_count(pipe.loop, 1):
        state, reader = pipe.run(
            state, runtime.window_batches(iter(batches), k),
            on_metrics=(lambda wm: wm.fetch()) if fetch else None)
    if not fetch:
        reader.last()
    return state


def test_pipeline_emits_window_and_metrics_events(tmp_path):
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.Recorder(path)
    _run_pipeline(4, _batches(8), rec=rec)
    rec.close()
    ev = _events(path)
    windows = [e for e in ev if e["kind"] == "window"]
    metrics = [e for e in ev if e["kind"] == "metrics"]
    assert [w["step"] for w in windows] == [0, 4]
    assert all(w["k"] == 4 and w["n_valid"] == 4 and w["dur"] >= 0
               and w["gap"] >= 0 for w in windows)
    assert windows[0]["program"] == "hot"
    assert {m["step"] for m in metrics} == {0, 4}
    m0 = metrics[0]
    assert len(m0["loss"]) == 4 and len(m0["loss_scale"]) == 4
    # the hot program compiled exactly once, recorded as first=True
    retraces = [e for e in ev if e["kind"] == "retrace"]
    assert len(retraces) == 1 and retraces[0]["first"] is True
    assert "float32" in retraces[0]["sig"]


def test_instrumentation_zero_retraces_and_bitwise_identical(tmp_path):
    """The acceptance pin: enabling telemetry changes neither the trace
    count (asserted inside _run_pipeline) nor a single parameter bit."""
    batches = _batches(12, bad_step=5)       # include an overflow skip
    off = _run_pipeline(4, batches, rec=None)
    rec = telemetry.Recorder(str(tmp_path / "run.jsonl"))
    on = _run_pipeline(4, batches, rec=rec)
    rec.close()
    for a, b in zip(jax.tree_util.tree_leaves(off.params),
                    jax.tree_util.tree_leaves(on.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(off.scaler.loss_scale) == float(on.scaler.loss_scale)


def test_scale_skip_and_growth_events(tmp_path):
    """An overflow mid-run lands a ``scale skip`` event at the global
    step index; a small scale_window lands ``grow`` events after clean
    windows — both derived from the one-dispatch-behind fetches."""
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.Recorder(path)
    _run_pipeline(4, _batches(12, bad_step=5), rec=rec)
    rec.close()
    ev = _events(path)
    skips = [e for e in ev if e["kind"] == "scale"
             and e["event"] == "skip"]
    assert [e["step"] for e in skips] == [5]
    grows = [e for e in ev if e["kind"] == "scale"
             and e["event"] == "grow"]
    assert grows, "scale_window=4 over 12 steps must grow at least once"
    summary = ev[-1]
    assert summary["metrics"]["counters"]["loss_scale_skips"] == 1


def test_double_fetch_does_not_double_scale_events(tmp_path):
    """The warmup pattern fetches the same window twice (drain + print);
    the recorder's high-water guard must not re-derive its events."""
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.Recorder(path)
    init_fn, step_fn = make_train_step(
        _loss_fn, training.sgd(lr=0.1), opt_level="O2",
        loss_scale="dynamic")
    pipe = runtime.StepPipeline(step_fn, k=4, telemetry=rec)
    reader = runtime.DeferredMetrics(telemetry=rec)
    state = init_fn(_params())
    for window, n in runtime.window_batches(
            iter(_batches(8, bad_step=2)), 4):
        state, metrics = pipe.step_window(state, window, n)
        prev = reader.push(metrics, n)
        if prev is not None:
            prev.fetch()
            prev.fetch()                       # the double-fetch
    reader.last()
    rec.close()
    skips = [e for e in _events(path) if e["kind"] == "scale"
             and e["event"] == "skip"]
    assert [e["step"] for e in skips] == [2]


def test_deferred_metrics_flush_returns_each_window_once():
    reader = runtime.DeferredMetrics()
    seen = []
    for i in range(3):
        prev = reader.push({"loss": jnp.float32(i)}, 4)
        if prev is not None:
            seen.append(prev.step)
    seen += [wm.step for wm in reader.flush()]
    assert seen == [0, 4, 8]
    assert reader.flush() == []               # idempotent until next push
    prev = reader.push({"loss": jnp.float32(3)}, 4)
    assert prev.step == 8
    assert [wm.step for wm in reader.flush()] == [12]


# -- loader instrumentation ---------------------------------------------------

def test_loader_events_and_single_snapshot(tmp_path, native_tier):
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.start(path)
    batches = [(np.full((2, 3), i, np.float32),) for i in range(6)]
    loader = apex_data.PrefetchLoader(iter(batches), depth=2, workers=2)
    n = sum(1 for _ in loader)
    assert n == 6
    rec.close()
    ev = _events(path)
    waits = [e for e in ev if e["kind"] == "loader_wait"]
    stages = [e for e in ev if e["kind"] == "stage"]
    loaders = [e for e in ev if e["kind"] == "loader"]
    assert len(waits) == 6 and all(w["dur"] >= 0 for w in waits)
    assert sorted(s["seq"] for s in stages) == list(range(6))
    assert len(loaders) == 1 and loaders[0]["phase"] == "exhausted"
    # the event's snapshot IS as_dict(): same keys, including the
    # derived stall pct the examples print via format_loader_line
    snap = loaders[0]["stats"]
    assert set(snap) == set(loader.stats.as_dict())
    line = apex_data.format_loader_line(snap)
    assert line.startswith(f"loader: stall {snap['loader_stall_pct']:.2f}%")


def test_loader_close_emits_final_snapshot(tmp_path, native_tier):
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.start(path)
    batches = [(np.zeros((2,), np.float32),) for _ in range(16)]
    loader = apex_data.PrefetchLoader(iter(batches), depth=1)
    it = iter(loader)
    next(it)
    loader.close()                  # abandoned mid-stream
    rec.close()
    loaders = [e for e in _events(path) if e["kind"] == "loader"]
    assert [e["phase"] for e in loaders] == ["close"]
    assert loaders[0]["stats"]["batches"] >= 1


def test_as_dict_snapshot_consistent_fields():
    stats = apex_data.LoaderStats()
    stats._start()
    stats._add("consumer_wait_s", 0.5)
    stats._delivered(2)
    d = stats.as_dict()
    s = stats.snapshot()                 # the alias: same read, same keys
    assert set(d) == set(s)
    for k in ("batches", "staged", "produce_s", "consumer_wait_s",
              "mean_queue_depth"):
        assert d[k] == s[k]
    assert d["batches"] == 1 and d["consumer_wait_s"] == 0.5


# -- collective byte events ---------------------------------------------------

def test_reduce_gradients_records_psum_bytes(tmp_path):
    from apex_tpu.parallel import import_shard_map
    from apex_tpu.parallel.distributed import reduce_gradients

    shard_map = import_shard_map()
    mesh = Mesh(np.array(jax.devices("cpu")[:NDEV]), ("data",))
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.start(path)
    grads = jnp.arange(NDEV * 4, dtype=jnp.float32).reshape(NDEV, 4)
    f = shard_map(lambda g: reduce_gradients({"w": g}, "data")["w"],
                  mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))
    jax.block_until_ready(f(grads))
    rec.close()
    colls = [e for e in _events(path) if e["kind"] == "collective"]
    assert colls, "trace-time psum bytes must be recorded"
    c = colls[0]
    assert c["op"] == "psum" and c["axis"] == ["data"]
    assert c["bytes"] == 4 * 4 and c["n"] == 1   # [4] f32 per-shard leaf
    assert c["dtype"] == "float32"


def test_zero1_records_collective_pair(tmp_path):
    from apex_tpu.parallel import import_shard_map
    from apex_tpu.parallel.zero import zero1, zero1_partition_spec

    shard_map = import_shard_map()
    mesh = Mesh(np.array(jax.devices("cpu")[:NDEV]), ("data",))
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.start(path)
    tx = zero1(training.adam(1e-2), "data", num_shards=NDEV)
    params = {"w": jnp.ones((NDEV * 2,), jnp.float32)}
    state = tx.init(params)
    sspec = zero1_partition_spec(state, "data")

    def step(params, state, grads):
        return tx.update(grads, state, params)

    f = shard_map(step, mesh=mesh, in_specs=(P(), sspec, P()),
                  out_specs=(P(), sspec))
    grads = {"w": jnp.ones((NDEV * 2,), jnp.float32)}
    jax.block_until_ready(f(params, state, grads)[0]["w"])
    rec.close()
    ops = {e["op"] for e in _events(path) if e["kind"] == "collective"}
    assert {"psum_scatter", "all_gather"} <= ops


# -- chrome export + timeline analyzer ----------------------------------------

def test_chrome_trace_export(tmp_path):
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.Recorder(path)
    _run_pipeline(4, _batches(8), rec=rec)
    rec.close()
    out = str(tmp_path / "trace.json")
    n = telemetry.to_chrome_trace(path, out)
    assert n > 0
    with open(out) as f:
        trace = json.load(f)
    phases = {e["ph"] for e in trace["traceEvents"]}
    assert "M" in phases and "X" in phases
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert all(e["ts"] >= 0 and e["dur"] > 0 for e in xs)


def test_timeline_analyze_end_to_end(tmp_path):
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.start(path)
    init_fn, step_fn = make_train_step(
        _loss_fn, training.sgd(lr=0.1), opt_level="O2",
        loss_scale="dynamic")
    pipe = runtime.StepPipeline(step_fn, k=4)
    state = init_fn(_params())
    state, reader = pipe.run(
        state, runtime.stage_windows(iter(_batches(12, bad_step=6)), 4),
        on_metrics=lambda wm: wm.fetch())
    rec.close()
    a = timeline.analyze(timeline.load_events(path))
    assert a["steps"] == 12 and a["windows"] == 3
    assert a["retraces"]["retraces"] == 0
    assert a["loss_scale"]["skip_steps"] == [6]
    att = a["attribution"]
    assert 0.0 <= att["dispatch_gap_pct"] <= 100.0
    assert att["loader_stall_pct"] == a["loader"]["loader_stall_pct"]
    st = a["step_time"]
    assert st["samples"] == 8 and st["p50_ms"] is not None
    assert st["p99_ms"] >= st["p50_ms"]
    report = timeline.format_report(a)
    assert "skips at steps [6]" in report
    assert "loader stall" in report


def test_timeline_cli_main(tmp_path, capsys):
    path = str(tmp_path / "run.jsonl")
    rec = telemetry.Recorder(path)
    _run_pipeline(2, _batches(4), rec=rec)
    rec.close()
    chrome = str(tmp_path / "trace.json")
    assert timeline.main([path, "--chrome", chrome]) == 0
    out = capsys.readouterr().out
    assert "telemetry timeline" in out and "steps: 4" in out
    assert os.path.exists(chrome)
    assert timeline.main([path, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["steps"] == 4


def test_timeline_collective_totals():
    """Analyzer collective math from a synthetic stream: the hot and
    tail compiles each re-record the same per-step collectives (divide
    by observed compiles), but two genuinely distinct same-signature
    reduce calls inside one step must SURVIVE the division."""
    base = [
        {"t": 0.0, "kind": "run", "meta": {}},
        {"t": 0.04, "kind": "retrace", "program": "hot", "step": 0,
         "n_traces": 1, "first": True, "new_sig": True, "sig": "s"},
        {"t": 0.1, "kind": "window", "step": 0, "k": 4, "n_valid": 4,
         "dur": 0.05, "gap": 0.0, "program": "hot"},
        {"t": 0.14, "kind": "retrace", "program": "tail", "step": 4,
         "n_traces": 1, "first": True, "new_sig": True, "sig": "s"},
        {"t": 0.2, "kind": "window", "step": 4, "k": 4, "n_valid": 2,
         "dur": 0.05, "gap": 0.01, "program": "tail"},
    ]
    coll = {"kind": "collective", "op": "psum", "axis": ["data"],
            "bytes": 1000, "n": 2, "dtype": "float32"}
    # one reduce per step, recorded by both compiles -> divides to 1
    a = timeline.analyze(base + [dict(coll, t=0.05), dict(coll, t=0.15)])
    assert a["steps"] == 6
    assert a["collectives"]["per_step_bytes"] == 1000
    assert a["collectives"]["total_gb"] == round(1000 * 6 / 1e9, 4)
    assert a["retraces"] == {"compiles": 2, "respecializations": 0,
                             "retraces": 0, "by_signature": [],
                             "compile_s": 0.0}
    # TWO identical reduces per step (e.g. twin G/D trees), two compiles
    # -> four events divide to multiplicity 2, not 1
    a2 = timeline.analyze(base + [dict(coll, t=t)
                                  for t in (0.05, 0.06, 0.15, 0.16)])
    assert a2["collectives"]["per_step_bytes"] == 2000


def test_timeline_respecialization_not_a_retrace():
    """The known-benign call-1 re-specialization (same signature, cache
    grew) is reported separately from true retraces (new signature)."""
    events = [
        {"t": 0.0, "kind": "run", "meta": {}},
        {"t": 0.1, "kind": "window", "step": 0, "k": 1, "n_valid": 1,
         "dur": 0.05, "gap": 0.0, "program": "hot"},
        {"t": 0.05, "kind": "retrace", "program": "hot", "step": 0,
         "n_traces": 1, "first": True, "new_sig": True, "sig": "a"},
        {"t": 0.15, "kind": "retrace", "program": "hot", "step": 1,
         "n_traces": 2, "first": False, "new_sig": False, "sig": "a"},
        {"t": 0.25, "kind": "retrace", "program": "hot", "step": 2,
         "n_traces": 3, "first": False, "new_sig": True, "sig": "b"},
    ]
    rt = timeline.analyze(events)["retraces"]
    assert rt["compiles"] == 1
    assert rt["respecializations"] == 1
    assert rt["retraces"] == 1 and rt["by_signature"] == ["b"]


def test_timeline_tolerates_torn_tail_line(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with open(path, "w") as f:
        f.write('{"t": 0.0, "kind": "run", "meta": {}}\n')
        f.write('{"t": 0.1, "kind": "window", "step": 0, "k": 1, '
                '"n_valid": 1, "dur": 0.01, "gap": 0.0}\n')
        f.write('{"t": 0.2, "kind": "wind')      # killed mid-write
    a = timeline.analyze(timeline.load_events(path))
    assert a["steps"] == 1


def test_timeline_collectives_attribute_per_axis():
    """ISSUE 12 satellite: per-collective byte totals split per mesh
    axis (dp vs fsdp vs joint) instead of one undifferentiated pool."""
    events = [
        {"t": 0.0, "kind": "run", "meta": {}},
        {"t": 0.04, "kind": "retrace", "program": "hot", "step": 0,
         "n_traces": 1, "first": True, "new_sig": True, "sig": "s"},
        {"t": 0.1, "kind": "window", "step": 0, "k": 4, "n_valid": 4,
         "dur": 0.05, "gap": 0.0, "program": "hot"},
        {"t": 0.05, "kind": "collective", "op": "all_gather",
         "axis": "fsdp", "bytes": 4000, "n": 1, "dtype": "float32"},
        {"t": 0.06, "kind": "collective", "op": "reduce_scatter",
         "axis": "fsdp", "bytes": 4000, "n": 1, "dtype": "float32"},
        {"t": 0.07, "kind": "collective", "op": "psum",
         "axis": "dp", "bytes": 500, "n": 1, "dtype": "float32"},
        {"t": 0.08, "kind": "collective", "op": "psum",
         "axis": ["dp", "fsdp"], "bytes": 64, "n": 1,
         "dtype": "float32"},
    ]
    a = timeline.analyze(events)
    by_axis = a["collectives"]["by_axis"]
    assert set(by_axis) == {"fsdp", "dp", "dp+fsdp"}
    assert by_axis["fsdp"]["bytes_per_step"] == 8000
    assert by_axis["fsdp"]["ops"] == ["all_gather", "reduce_scatter"]
    assert by_axis["dp"]["bytes_per_step"] == 500
    assert by_axis["dp+fsdp"]["bytes_per_step"] == 64
    assert (a["collectives"]["per_step_bytes"]
            == sum(v["bytes_per_step"] for v in by_axis.values()))
