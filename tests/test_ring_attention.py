"""Sequence-parallel attention tests on the 8-device CPU mesh: ring and
Ulysses attention must match single-device (blockwise and naive) attention,
forward and backward, causal and not."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from apex_tpu.ops.attention import blockwise_attention, dot_product_attention
from apex_tpu.parallel.ring_attention import ring_attention, ulysses_attention

NDEV = 8
B, T, H, D = 2, 64, 8, 16


def _mesh():
    return Mesh(np.array(jax.devices("cpu")[:NDEV]), ("sp",))


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D) * 0.5, dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_naive(causal):
    q, k, v = _qkv()
    out_blk = blockwise_attention(q, k, v, causal=causal, block_size=16)
    out_ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out_blk), np.asarray(out_ref),
                               atol=2e-5)


@pytest.mark.slow
def test_blockwise_grads_match_naive():
    q, k, v = _qkv(1)

    gb = jax.grad(lambda a: jnp.sum(
        blockwise_attention(a, k, v, causal=True, block_size=16) ** 2))(q)
    gr = jax.grad(lambda a: jnp.sum(
        dot_product_attention(a, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gr), atol=2e-4)


def test_blockwise_nondivisible_block_size():
    """Regression: tk % block_size != 0 must stream a remainder block, not
    materialize full scores — and stay numerically exact."""
    q, k, v = _qkv(7)
    out = blockwise_attention(q, k, v, causal=True, block_size=24)  # 64%24!=0
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_sub_blocking():
    """Regression: ring_attention honors block_size (sub-blocks each shard)."""
    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("sp",))
    q, k, v = _qkv(8)
    f = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=True,
                          block_size=8),          # t_local=32 -> 4 sub-blocks
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"))
    out = jax.jit(f)(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_single_device(causal):
    mesh = _mesh()
    q, k, v = _qkv(2)

    f = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=causal),
        mesh=mesh, in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"))
    out = jax.jit(f)(q, k, v)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_backward():
    mesh = _mesh()
    q, k, v = _qkv(3)

    def loss_ring(a, b, c):
        f = shard_map(
            functools.partial(ring_attention, axis_name="sp", causal=True),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"))
        return jnp.sum(f(a, b, c) ** 2)

    def loss_ref(a, b, c):
        return jnp.sum(dot_product_attention(a, b, c, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf), atol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_single_device(causal):
    mesh = _mesh()
    q, k, v = _qkv(4)

    f = shard_map(
        functools.partial(ulysses_attention, axis_name="sp", causal=causal),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"))
    out = jax.jit(f)(q, k, v)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = _mesh()
    q = jnp.ones((B, T, 6, D))  # 6 heads, 8 ranks
    with pytest.raises(ValueError, match="divisible"):
        f = shard_map(
            functools.partial(ulysses_attention, axis_name="sp"),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"))
        jax.jit(f)(q, q, q)


@pytest.mark.slow
def test_bert_ring_matches_full_on_dp_sp_mesh():
    """BERT-tiny with ring attention + mean pooling on a 4x2 (data x sp)
    mesh must produce the same logits as the single-device model with the
    same pooling, and a full O2 train step must run and stay finite."""
    from apex_tpu import training
    from apex_tpu.models import bert_tiny
    from apex_tpu.training import make_train_step

    dp, sp = 4, 2
    mesh = Mesh(np.array(jax.devices("cpu")[:8]).reshape(dp, sp),
                ("data", "sp"))
    seq = 16
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 1024,
                                                       (2 * dp, seq)))
    ring_model = bert_tiny(attention_impl="ring", sp_axis="sp")
    plain_model = bert_tiny()
    variables = plain_model.init(jax.random.PRNGKey(0), ids[:2])

    # --- forward parity: the sp model recovers the true [CLS] via masked
    # psum, so ring logits must match the plain full-attention model with
    # the SAME params exactly (modulo blockwise-softmax numerics).
    def fwd(ids_b):
        return ring_model.apply({"params": variables["params"]}, ids_b)

    f = shard_map(fwd, mesh=mesh, in_specs=P("data", "sp"),
                  out_specs=P("data"))
    logits = jax.jit(f)(ids)
    assert logits.shape == (2 * dp, 2)
    want = plain_model.apply(variables, ids)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want),
                               atol=2e-4)

    # --- full train step over the 2-D mesh
    labels = jnp.asarray(np.arange(2 * dp) % 2)

    def loss_fn(p, batch):
        ids_b, yb = batch
        lg = ring_model.apply({"params": p}, ids_b)
        logp = jax.nn.log_softmax(lg.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))

    init_fn, step_fn = make_train_step(loss_fn, training.adam(lr=1e-3),
                                       opt_level="O2",
                                       axis_name=("data", "sp"))
    state = init_fn(variables["params"])
    sharded = shard_map(step_fn, mesh=mesh,
                        in_specs=(P(), (P("data", "sp"), P("data"))),
                        out_specs=(P(), P()))
    new_state, metrics = jax.jit(sharded)(state, (ids, labels))
    assert np.isfinite(float(metrics["loss"]))

    # oracle step: single device, same loss via plain blockwise model with
    # identical pooling semantics — checked via gradient consistency:
    # replicas across BOTH axes must remain bitwise identical, which
    # shard_map's replicated out_spec already enforces structurally.
    leaves = jax.tree_util.tree_leaves(new_state.params)
    assert all(bool(jnp.all(jnp.isfinite(l.astype(jnp.float32))))
               for l in leaves)


def test_ring_attention_bf16():
    mesh = _mesh()
    q, k, v = _qkv(5, jnp.bfloat16)
    f = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"))
    out = jax.jit(f)(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


def test_ring_flash_default_vma_dispatch_matches_oracle():
    """``ring_flash_attention`` must be callable under shard_map's DEFAULT
    vma tracking (VERDICT r2 weak #2 asked for no ``check_vma=False``
    requirement).  On CPU the interpret-mode kernels cannot run under the
    tracker (jax hlo-interpreter limitation), so this exercises the
    documented jnp fallback — the on-chip Mosaic kernel path under the
    same default shard_map is asserted in test_pallas_tpu.py."""
    from apex_tpu.parallel.ring_attention import ring_flash_attention

    mesh = _mesh()
    q, k, v = _qkv(4)

    f = shard_map(
        functools.partial(ring_flash_attention, axis_name="sp", causal=True,
                          interpret=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"))
    out = jax.jit(f)(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    g = jax.jit(jax.grad(lambda a, b, c: jnp.sum(f(a, b, c) ** 2),
                         argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(
        dot_product_attention(a, b, c, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)
