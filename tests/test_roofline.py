"""Roofline attribution engine + regression differ (ISSUE 6).

Contracts tier-1 pins here:

* **cost harvest correctness** — ``harvest_costs`` matmul/conv FLOPs
  match hand-computed counts exactly on the jaxpr path, and the XLA
  ``cost_analysis`` path (when the API exists) agrees with the walk;
* **old-jax fallback parity** — with the XLA API unavailable the
  harvest degrades to the jaxpr totals, same regions, same matmul
  count;
* **region attribution** — FLOPs group under the ``prof.capture``
  scope names, through NESTED scopes and through the backward pass
  (``transpose(jvp(...))`` wrappers peel to the forward region);
* **zero retraces** — harvesting never touches a training step's own
  jit cache (``prof.assert_trace_count`` pin);
* **MFU ledger** — boundedness classification against the ridge point,
  modeled times normalized onto the measured step, gap attribution
  read from a timeline analysis;
* **schema + differ** — timeline ``--json`` carries ``schema_version``,
  future majors are rejected with a clear error, and ``prof.regress``
  exits 0 on a self-diff and non-zero on a synthetically degraded
  summary (the acceptance criterion verbatim).
"""

import copy
import json
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.prof import assert_trace_count, capture, roofline, timeline
from apex_tpu.prof import regress


# -- cost harvest -------------------------------------------------------------

def _matmul_fn():
    def f(x, w):
        return x @ w
    return f, (jnp.zeros((8, 16), jnp.float32),
               jnp.zeros((16, 32), jnp.float32))


def test_harvest_matmul_flops_exact_on_jaxpr_path():
    f, args = _matmul_fn()
    h = roofline.harvest_costs(f, *args, xla=False)
    assert h.source == "jaxpr"
    assert h.matmul_flops == 2 * 8 * 16 * 32
    assert h.flops == h.jaxpr_flops == h.matmul_flops
    # bytes: both operands read + output written, all fp32
    assert h.jaxpr_bytes == (8 * 16 + 16 * 32 + 8 * 32) * 4


def test_harvest_conv_flops_hand_computed():
    def f(x, k):
        return jax.lax.conv_general_dilated(
            x, k, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    x = jnp.zeros((2, 8, 8, 3), jnp.float32)
    k = jnp.zeros((3, 3, 3, 4), jnp.float32)
    h = roofline.harvest_costs(f, x, k, xla=False)
    out_elems = 2 * 6 * 6 * 4
    assert h.matmul_flops == 2 * out_elems * (3 * 3) * 3


def test_harvest_xla_path_agrees_with_walk():
    f, args = _matmul_fn()
    h = roofline.harvest_costs(f, *args, xla=True)
    if h.source == "jaxpr":
        pytest.skip("no XLA cost_analysis API on this jax")
    assert h.source in ("xla_lowered", "xla_compiled")
    # XLA charges the same 2mnk for a plain dot
    assert h.flops == pytest.approx(h.jaxpr_flops, rel=0.25)
    # the matmul split ALWAYS comes from the walk (stable numerator)
    assert h.matmul_flops == 2 * 8 * 16 * 32


def test_harvest_old_jax_fallback_parity(monkeypatch):
    """With the XLA cost API gone (old jax), the harvest must degrade
    to the jaxpr totals — same matmul count, same regions."""
    f, args = _matmul_fn()
    ref = roofline.harvest_costs(f, *args, xla=True)
    monkeypatch.setattr(roofline, "_xla_cost", lambda *a, **k: None)
    h = roofline.harvest_costs(f, *args, xla=True)
    assert h.source == "jaxpr"
    assert h.flops == h.jaxpr_flops == ref.jaxpr_flops
    assert h.matmul_flops == ref.matmul_flops
    assert h.by_region == ref.by_region


def _scoped_model():
    def f(x, w1, w2):
        with capture.scope("blockA"):
            with capture.scope("mm"):
                h = x @ w1
        with capture.scope("blockB"):
            return jnp.tanh(h) @ w2
    return f, (jnp.zeros((4, 8), jnp.float32),
               jnp.zeros((8, 8), jnp.float32),
               jnp.zeros((8, 2), jnp.float32))


def test_region_attribution_nested_scopes():
    f, args = _scoped_model()
    h = roofline.harvest_costs(f, *args, xla=False)
    assert set(h.by_region) == {"blockA", "blockB"}
    assert h.by_region["blockA"]["matmul_flops"] == 2 * 4 * 8 * 8
    assert h.by_region["blockB"]["matmul_flops"] == 2 * 4 * 8 * 2
    # depth=2 splits blockA into its nested scope
    h2 = roofline.harvest_costs(f, *args, xla=False, region_depth=2)
    assert "blockA/mm" in h2.by_region
    # every harvested flop is attributed to some region
    assert h.coverage_pct == pytest.approx(100.0)


def test_region_attribution_survives_backward_pass():
    """fwd and bwd ops of one region land in the SAME row: the
    transpose(jvp(...)) wrappers peel back to the forward scope."""
    f, args = _scoped_model()

    def train(x, w1, w2):
        return jnp.sum(f(x, w1, w2))

    g = jax.grad(train, argnums=(1, 2))
    h = roofline.harvest_costs(g, *args, xla=False)
    assert set(h.by_region) <= {"blockA", "blockB", "<unattributed>"}
    # bwd adds dgrad+wgrad: blockA's matmul flops are >= 2x forward
    assert h.by_region["blockA"]["matmul_flops"] >= 2 * (2 * 4 * 8 * 8)


def test_region_path_helper():
    assert capture.region_path("blockA/mm") == "blockA"
    assert capture.region_path("blockA/mm", depth=2) == "blockA/mm"
    assert capture.region_path("transpose(jvp(blockA))/mm") == "blockA"
    # pure call machinery yields no user region; a jit(<fn>) wrapper
    # peels to the function's own name (the best available label)
    assert capture.region_path("pjit/scan") == "<unattributed>"
    assert capture.region_path("jit(step)") == "step"
    assert capture.region_path("") == "<unattributed>"
    # review regression pin: bare machinery names drop by EXACT match —
    # user regions that merely START with one must survive
    for name in ("branch2a", "body_net", "scanner", "jitter", "condhead"):
        assert capture.region_path(f"{name}/mm") == name
    assert capture.region_path("custom_vjp_call") == "<unattributed>"
    # conv backward machinery peels like custom_*: dgrad/wgrad land on
    # the forward conv's ledger row instead of splitting off (ISSUE 18)
    assert capture.region_path(
        "transpose(jvp(stage1))/conv_general_dilated_transpose_lhs"
    ) == "stage1"
    assert capture.region_path(
        "stage1/conv_general_dilated_transpose_rhs", depth=2) == "stage1"
    assert capture.region_path(
        "conv_general_dilated_transpose_lhs/mm") == "mm"
    assert capture.region_path("conv_general_dilated") == "<unattributed>"


def test_harvest_never_retraces_the_training_step():
    """The acceptance trace-count pin: harvesting uses its own jit
    instance, so the step's cache neither grows nor is perturbed."""
    def step_fn(state, b):
        return state + jnp.sum(b), jnp.sum(b)

    step = jax.jit(step_fn)
    b = jnp.ones((4, 4), jnp.float32)
    with assert_trace_count(step, 1):
        s, _ = step(jnp.float32(0.0), b)
    with assert_trace_count(step, 0):
        roofline.harvest_costs(step_fn, jnp.float32(0.0), b)
        roofline.harvest_costs(step, jnp.float32(0.0), b, xla=False)
        s, _ = step(s, b)


# -- MFU ledger ---------------------------------------------------------------

def _toy_harvest():
    # two regions: one past the ridge (compute), one far below (memory)
    return roofline.CostHarvest(
        flops=2e9, bytes=2e7, source="jaxpr", matmul_flops=1.9e9,
        jaxpr_flops=2e9, jaxpr_bytes=2e7,
        by_region={
            "dense": {"flops": 1.9e9, "bytes": 4e6,
                      "matmul_flops": 1.9e9, "ops": 3},
            "norm": {"flops": 1e8, "bytes": 1.6e7,
                     "matmul_flops": 0.0, "ops": 7},
        })


def test_mfu_ledger_classification_and_normalization():
    peaks = {"flops": 100e12, "hbm_gb_s": 1000.0, "source": "test"}
    led = roofline.mfu_ledger(_toy_harvest(), step_time_s=1e-3,
                              peaks=peaks)
    assert led["schema_version"] == timeline.SCHEMA_VERSION
    by = {r["region"]: r for r in led["regions"]}
    # ridge = 100e12 / 1e12 = 100 flop/byte
    assert by["dense"]["bound"] == "compute"     # 1.9e9/4e6 = 475 > 100
    assert by["norm"]["bound"] == "memory"       # 1e8/1.6e7 = 6.25 < 100
    # modeled times normalized onto the measured step
    assert sum(r["modeled_ms"] for r in led["regions"]) \
        == pytest.approx(1.0, rel=0.01)
    t = led["total"]
    assert t["step_ms"] == 1.0
    assert t["achieved_tflops"] == pytest.approx(2.0, rel=0.01)
    assert t["mfu_pct"] == pytest.approx(100 * 1.9e9 / 1e-3 / 100e12,
                                         rel=0.01)
    assert led["coverage_pct"] == pytest.approx(100.0)


def test_mfu_ledger_top_truncation_and_json_clean():
    led = roofline.mfu_ledger(_toy_harvest(), step_time_s=1e-3,
                              peaks={"flops": 1e12, "hbm_gb_s": 100.0},
                              top=1)
    assert len(led["regions"]) == 1 and led["regions_dropped"] == 1
    json.dumps(led)                      # BENCH_EXTRA-safe
    assert "roofline ledger" in roofline.format_ledger(led)


def test_mfu_ledger_gap_attribution_from_timeline():
    events = [
        {"t": 0.0, "kind": "run", "meta": {}},
        {"t": 0.3, "kind": "retrace", "program": "hot", "step": 0,
         "n_traces": 1, "first": True, "new_sig": True, "sig": "s",
         "dur": 0.3},
        {"t": 0.3, "kind": "window", "step": 0, "k": 4, "n_valid": 4,
         "dur": 0.3, "gap": 0.0, "program": "hot"},
        {"t": 0.5, "kind": "loader_wait", "dur": 0.05, "qdepth": 0},
        {"t": 0.6, "kind": "window", "step": 4, "k": 4, "n_valid": 4,
         "dur": 0.1, "gap": 0.2, "program": "hot"},
        {"t": 0.9, "kind": "window", "step": 8, "k": 4, "n_valid": 4,
         "dur": 0.1, "gap": 0.2, "program": "hot"},
    ]
    ta = timeline.analyze(events)
    assert ta["retraces"]["compile_s"] == 0.3
    led = roofline.mfu_ledger(_toy_harvest(), timeline=ta,
                              peaks={"flops": 1e12, "hbm_gb_s": 100.0},
                              best_window_step_s=0.02)
    gap = led["gap"]
    assert gap["compile_pct"] is not None and gap["compile_pct"] > 0
    assert gap["dispatch_gap_pct"] == ta["attribution"]["dispatch_gap_pct"]
    assert gap["host_other_pct"] is not None
    # steady step from the stream (elapsed/steps), best window given
    assert 0 <= gap["steady_vs_best_pct"] <= 100
    # step time fell back to the stream's elapsed/steps
    assert led["total"]["step_ms"] == pytest.approx(
        ta["elapsed_s"] / ta["steps"] * 1e3, rel=0.01)


def test_load_peaks_reads_bench_extra(tmp_path):
    p = tmp_path / "BENCH_EXTRA.json"
    p.write_text(json.dumps({
        "measured_matmul_tflops": 127.4, "peak_bf16_tflops": 197.0,
        "resnet50": {"prof_measured": {"by_category": [
            {"category": "loop fusion", "gb_per_s": 881.0}]}}}))
    pk = roofline.load_peaks(str(p))
    assert pk["flops"] == pytest.approx(127.4e12)
    assert pk["hbm_gb_s"] == 881.0
    assert pk["bw_source"] == "measured_loop_fusion"
    # a directory works too, and a missing file degrades to defaults
    assert roofline.load_peaks(str(tmp_path))["flops"] \
        == pytest.approx(127.4e12)
    empty = roofline.load_peaks(str(tmp_path / "nope.json"))
    assert empty["flops"] > 0 and "default" in empty["source"]


def test_roofline_cli_json(tmp_path, capsys, monkeypatch):
    mod = tmp_path / "roofline_cli_target.py"
    # big enough that GFLOP rounding (3 decimals) keeps the signal
    mod.write_text(
        "import jax.numpy as jnp\n"
        "def entry():\n"
        "    def f(x, w):\n"
        "        return x @ w\n"
        "    return f, (jnp.zeros((256, 512)), jnp.zeros((512, 512)))\n")
    monkeypatch.syspath_prepend(str(tmp_path))
    rc = roofline.main(["--fn", "roofline_cli_target:entry", "--no-xla",
                        "--step-ms", "1.0", "--json"])
    assert rc == 0
    led = json.loads(capsys.readouterr().out)
    assert led["total"]["matmul_flops_g"] == pytest.approx(
        2 * 256 * 512 * 512 / 1e9, rel=0.01)
    assert led["schema_version"] == timeline.SCHEMA_VERSION


# -- schema versioning --------------------------------------------------------

def test_timeline_json_carries_schema_version():
    a = timeline.analyze([{"t": 0.0, "kind": "run", "meta": {}}])
    assert a["schema_version"] == timeline.SCHEMA_VERSION
    timeline.check_schema_version(a)          # current: accepted
    timeline.check_schema_version({})         # absent: accepted (old)
    timeline.check_schema_version({"schema_version": "0.9"})  # older major


def test_future_schema_major_rejected_with_clear_error():
    with pytest.raises(ValueError, match="FUTURE major"):
        timeline.check_schema_version({"schema_version": "99.0"},
                                      where="base.json")
    with pytest.raises(ValueError, match="unparseable"):
        timeline.check_schema_version({"schema_version": "banana"})


# -- prof.regress -------------------------------------------------------------

def _analysis():
    events = [
        {"t": 0.0, "kind": "run", "meta": {"example": "t"}},
        {"t": 0.05, "kind": "retrace", "program": "hot", "step": 0,
         "n_traces": 1, "first": True, "new_sig": True, "sig": "s",
         "dur": 0.04},
        {"t": 0.1, "kind": "window", "step": 0, "k": 4, "n_valid": 4,
         "dur": 0.05, "gap": 0.0, "program": "hot"},
        {"t": 0.2, "kind": "window", "step": 4, "k": 4, "n_valid": 4,
         "dur": 0.05, "gap": 0.01, "program": "hot"},
        {"t": 0.3, "kind": "window", "step": 8, "k": 4, "n_valid": 4,
         "dur": 0.05, "gap": 0.01, "program": "hot"},
    ]
    return timeline.analyze(events)


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def test_regress_self_diff_exits_zero(tmp_path, capsys):
    a = _analysis()
    rc = regress.main([_write(tmp_path, "a.json", a),
                       _write(tmp_path, "b.json", a)])
    assert rc == 0
    assert "0 regression(s)" in capsys.readouterr().out


def test_regress_degraded_exits_nonzero_and_names_metrics(tmp_path,
                                                          capsys):
    a = _analysis()
    bad = copy.deepcopy(a)
    bad["steps_per_s"] /= 2.0
    bad["step_time"]["p50_ms"] *= 3.0
    bad["retraces"]["retraces"] = 2
    rc = regress.main([_write(tmp_path, "a.json", a),
                       _write(tmp_path, "b.json", bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "steps_per_s" in out and "p50_ms" in out \
        and "retraces.retraces" in out


def test_regress_json_is_schema_versioned(tmp_path, capsys):
    """ISSUE 10 satellite: --json output is machine-readable for CI —
    schema-versioned like timeline --json, regressions as structured
    entries, and round-trips check_schema_version."""
    a = _analysis()
    bad = copy.deepcopy(a)
    bad["steps_per_s"] /= 2.0
    rc = regress.main([_write(tmp_path, "a.json", a),
                       _write(tmp_path, "b.json", bad), "--json"])
    assert rc == 1
    diff = json.loads(capsys.readouterr().out)
    assert diff["schema_version"] == timeline.SCHEMA_VERSION
    timeline.check_schema_version(diff, "regress --json")
    metrics = {e["metric"] for e in diff["regressions"]}
    assert "steps_per_s" in metrics
    entry = next(e for e in diff["regressions"]
                 if e["metric"] == "steps_per_s")
    assert set(entry) >= {"metric", "base", "cur", "ratio", "tol_pct",
                          "direction"}


def test_regress_rejects_future_schema_major(tmp_path, capsys):
    a = _analysis()
    fut = dict(a, schema_version="99.0")
    rc = regress.main([_write(tmp_path, "a.json", a),
                       _write(tmp_path, "b.json", fut)])
    assert rc == 2
    assert "FUTURE major" in capsys.readouterr().err


def test_regress_tolerance_override(tmp_path):
    a = _analysis()
    slower = copy.deepcopy(a)
    slower["steps_per_s"] *= 0.93          # 7% down: inside default 10%
    base, cur = (_write(tmp_path, "a.json", a),
                 _write(tmp_path, "b.json", slower))
    assert regress.main([base, cur]) == 0
    assert regress.main([base, cur, "--tol", "steps_per_s=2"]) == 1
    # loosening the other way passes a big hit
    slower2 = copy.deepcopy(a)
    slower2["steps_per_s"] *= 0.5
    cur2 = _write(tmp_path, "c.json", slower2)
    assert regress.main([base, cur2]) == 1
    assert regress.main([base, cur2, "--tol", "steps_per_s=60"]) == 0


def test_regress_bench_summary_inputs(tmp_path):
    base = {"resnet50": {"ms_per_step_o2": 50.0,
                         "images_per_sec_o2": 2560.0},
            "telemetry": {"overhead_ratio": 1.07}}
    cur = copy.deepcopy(base)
    cur["resnet50"]["ms_per_step_o2"] = 61.0
    rc = regress.main([_write(tmp_path, "a.json", base),
                       _write(tmp_path, "b.json", cur)])
    assert rc == 1
    # identical bench summaries self-diff clean
    assert regress.main([_write(tmp_path, "c.json", base),
                         _write(tmp_path, "d.json", base)]) == 0


def test_regress_pct_point_slack_absorbs_noise(tmp_path):
    """A 0.0 -> 0.3 stall-percentage wobble is noise, not a failure;
    an integer counter going 0 -> 1 still fails."""
    base = {"attribution": {"loader_stall_pct": 0.0},
            "retraces": {"retraces": 0}}
    noisy = {"attribution": {"loader_stall_pct": 0.3},
             "retraces": {"retraces": 0}}
    assert regress.main([_write(tmp_path, "a.json", base),
                         _write(tmp_path, "b.json", noisy)]) == 0
    worse = {"attribution": {"loader_stall_pct": 0.0},
             "retraces": {"retraces": 1}}
    assert regress.main([_write(tmp_path, "a2.json", base),
                         _write(tmp_path, "b2.json", worse)]) == 1


def test_regress_diff_summaries_direction_table():
    d = regress.diff_summaries(
        {"x_ms": 10.0, "y_per_s": 100.0, "mystery": 1.0},
        {"x_ms": 10.5, "y_per_s": 200.0, "mystery": 99.0})
    assert d["regressions"] == []
    assert [e["metric"] for e in d["improvements"]] == ["y_per_s"]
    assert d["skipped"] == 1               # unclassifiable never fails


# -- bench integration shape --------------------------------------------------

def test_bench_harvest_cross_check_shape():
    """The bench gate's contract in miniature: a harvested matmul count
    within 10% of a hand formula passes; the jaxpr walk on a BERT-like
    block reproduces 6*N*B*S for a dense tower."""
    B, S, H = 2, 8, 16

    def f(x, w1, w2):
        # two dense layers + their backward = 6 * (H*H * 2) * B*S flops
        h = jnp.tanh(x @ w1)
        return jnp.sum(h @ w2)

    g = jax.grad(f, argnums=(1, 2))
    x = jnp.zeros((B * S, H), jnp.float32)
    w = jnp.zeros((H, H), jnp.float32)
    h = roofline.harvest_costs(g, x, w, w, xla=False)
    # 5 dots of 2*(B*S)*H*H each: 2 fwd, w1/w2 wgrads, ONE dgrad (x is
    # an input, so layer 1 needs no dgrad) — the per-layer 6N rule
    # minus the first layer's missing dgrad
    analytic = 5 * (2 * H * H) * B * S
    assert h.matmul_flops == pytest.approx(analytic, rel=0.10)
