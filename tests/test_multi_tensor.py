"""Multi-tensor engine tests.

Mirrors reference ``tests/L0/run_amp/test_multi_tensor_scale.py`` /
``_axpby`` / ``_l2norm``: fuzz sizes around chunk boundaries, inject inf/nan
at the first/last element of each tensor, assert the overflow flag, and check
mixed in/out dtypes (bf16 <-> fp32 instead of fp16 <-> fp32).

The bucket matrix at the bottom re-runs the op contract through a
:class:`BucketStore` — parametrized over dtypes AND over
``APEX_TPU_DISABLE_NATIVE=1`` (tier-2), pinning the contract that the
flat-bucket engine is pure XLA with no native-runtime dependency (the
same matrix ``docker/run_matrix.sh`` runs per install tier).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import multi_tensor as mta

CHUNK = 2048 * 32
# Reference fuzz pattern: sizes straddling chunk boundaries (test_fuzz :88-126).
SIZES = [7, 256, CHUNK - 1, CHUNK, CHUNK + 1]


def _make_trees(sizes, dtype, val=4.0):
    return [jnp.full((s,), val, dtype=dtype) for s in sizes]


@pytest.mark.parametrize("in_dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("out_dtype", [jnp.float32, jnp.bfloat16])
def test_scale_values_and_dtypes(in_dtype, out_dtype):
    trees = _make_trees([33, 1025], in_dtype)
    out, overflow = mta.multi_tensor_scale(trees, 0.5, out_dtype=out_dtype)
    assert not bool(overflow)
    for o in out:
        assert o.dtype == jnp.dtype(out_dtype)
        np.testing.assert_allclose(np.asarray(o, np.float32), 2.0)


@pytest.mark.parametrize("size", SIZES)
@pytest.mark.parametrize("bad", [float("inf"), float("nan")])
@pytest.mark.parametrize("pos", ["first", "last"])
def test_scale_overflow_injection(size, bad, pos):
    x = np.full((size,), 1.0, np.float32)
    x[0 if pos == "first" else -1] = bad
    trees = [jnp.ones((17,), jnp.float32), jnp.asarray(x)]
    _, overflow = mta.multi_tensor_scale(trees, 1.0)
    assert bool(overflow)


def test_axpby():
    x = [jnp.full((100,), 2.0), jnp.full((CHUNK + 1,), 4.0)]
    y = [jnp.full((100,), 1.0), jnp.full((CHUNK + 1,), 1.0)]
    out, overflow = mta.multi_tensor_axpby(x, y, 0.5, 2.0)
    assert not bool(overflow)
    np.testing.assert_allclose(np.asarray(out[0]), 3.0)
    np.testing.assert_allclose(np.asarray(out[1]), 4.0)


def test_axpby_overflow():
    x = [jnp.asarray([1.0, np.nan, 1.0], jnp.float32)]
    y = [jnp.ones((3,), jnp.float32)]
    _, overflow = mta.multi_tensor_axpby(x, y, 1.0, 1.0)
    assert bool(overflow)


def test_l2norm_global_and_per_tensor():
    trees = [jnp.full((4,), 3.0), jnp.full((9,), 2.0)]
    # sqrt(4*9 + 9*4) = sqrt(72)
    g = mta.multi_tensor_l2norm(trees)
    np.testing.assert_allclose(float(g), np.sqrt(72.0), rtol=1e-6)
    g2, per = mta.multi_tensor_l2norm(trees, per_tensor=True)
    np.testing.assert_allclose(float(per[0]), 6.0, rtol=1e-6)
    np.testing.assert_allclose(float(per[1]), 6.0, rtol=1e-6)


def test_l2norm_works_on_pytrees():
    tree = {"a": jnp.ones((3, 3)), "b": {"c": jnp.ones((9,))}}
    np.testing.assert_allclose(float(mta.multi_tensor_l2norm(tree)),
                               np.sqrt(18.0), rtol=1e-6)


def test_maxnorm():
    trees = [jnp.asarray([1.0, -7.0]), jnp.asarray([3.0])]
    assert float(mta.multi_tensor_maxnorm(trees)) == 7.0


def test_flatten_unflatten_roundtrip():
    tensors = [jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
               jnp.arange(4, dtype=jnp.float32)]
    flat = mta.flatten(tensors)
    assert flat.shape == (10,)
    back = mta.unflatten(flat, tensors)
    for a, b in zip(back, tensors):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_applier_shim():
    out, flag = mta.multi_tensor_applier(
        mta.multi_tensor_scale, jnp.zeros((1,), jnp.int32),
        [[jnp.ones((8,))]], 2.0)
    np.testing.assert_allclose(np.asarray(out[0]), 2.0)


def test_jit_composability():
    @jax.jit
    def f(tree):
        out, overflow = mta.multi_tensor_scale(tree, 2.0)
        return mta.multi_tensor_l2norm(out), overflow

    norm, overflow = f([jnp.ones((16,))])
    np.testing.assert_allclose(float(norm), 8.0, rtol=1e-6)
    assert not bool(overflow)


# -- legacy two-stage LAMB (reference csrc/multi_tensor_lamb_stage_{1,2}.cu) --

def test_lamb_two_stage_matches_numpy_reference():
    from apex_tpu.multi_tensor import (multi_tensor_l2norm,
                                       multi_tensor_lamb_stage1,
                                       multi_tensor_lamb_stage2)
    rng = np.random.RandomState(0)
    shapes = [(4, 3), (5,), (2, 2)]
    params = [jnp.asarray(rng.randn(*s).astype(np.float32)) for s in shapes]
    grads = [jnp.asarray(rng.randn(*s).astype(np.float32)) for s in shapes]
    m = [jnp.zeros(s, jnp.float32) for s in shapes]
    v = [jnp.zeros(s, jnp.float32) for s in shapes]
    decay = [0.01, 0.0, 0.01]
    b1, b2, eps, lr, gnorm = 0.9, 0.999, 1e-6, 0.1, 2.0

    upd, m1, v1 = multi_tensor_lamb_stage1(
        grads, params, m, v, decay, beta1=b1, beta2=b2,
        beta1_correction=1 - b1, beta2_correction=1 - b2,
        epsilon=eps, clipped_global_grad_norm=gnorm)
    _, p_norms = multi_tensor_l2norm(params, per_tensor=True)
    _, u_norms = multi_tensor_l2norm(upd, per_tensor=True)
    new_p = multi_tensor_lamb_stage2(params, upd, p_norms, u_norms, lr)

    for g, p, d, u_got, p_got in zip(grads, params, decay, upd, new_p):
        g = np.asarray(g); p = np.asarray(p)
        sg = g / gnorm
        m_n = (1 - b1) * sg
        v_n = (1 - b2) * sg * sg
        u_ref = (m_n / (1 - b1)) / (np.sqrt(v_n / (1 - b2)) + eps) + d * p
        np.testing.assert_allclose(np.asarray(u_got), u_ref,
                                   atol=1e-5, rtol=1e-5)
        pn = np.linalg.norm(p); un = np.linalg.norm(u_ref)
        ratio = lr * pn / un if (pn != 0 and un != 0) else lr
        np.testing.assert_allclose(np.asarray(p_got), p - ratio * u_ref,
                                   atol=1e-5, rtol=1e-5)
    # moments updated in place semantics
    np.testing.assert_allclose(np.asarray(m1[0]),
                               (1 - b1) * np.asarray(grads[0]) / gnorm,
                               rtol=1e-6)
    assert np.all(np.asarray(v1[0]) >= 0)


# -- the bucket matrix (ISSUE 4) ----------------------------------------------
# Every op routed through a BucketStore must match its leafwise result,
# with the native tier disabled too: the engine is pure XLA, so the
# tier-2 (no-native) install keeps the identical numerics (the env knob
# is read per call by apex_tpu.native, never by the bucket paths).

@pytest.fixture(params=["native-default", "no-native"])
def native_tier(request, monkeypatch):
    if request.param == "no-native":
        monkeypatch.setenv("APEX_TPU_DISABLE_NATIVE", "1")
    return request.param


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_bucket_matrix_scale_axpby_finite(native_tier, dtype):
    sizes = [7, 33, 1025]
    tree = {f"t{i}": jnp.full((s,), 4.0, dtype) for i, s in enumerate(sizes)}
    store = mta.BucketStore(tree)
    assert store.n_buckets == 1 and store.sizes == (sum(sizes),)

    out, overflow = mta.multi_tensor_scale(tree, 0.5, store=store)
    assert not bool(overflow)
    for k, o in out.items():
        assert o.dtype == jnp.dtype(dtype)
        np.testing.assert_allclose(np.asarray(o, np.float32), 2.0)

    ones = {k: jnp.ones_like(v) for k, v in tree.items()}
    out, overflow = mta.multi_tensor_axpby(tree, ones, 0.5, 2.0, store=store)
    assert not bool(overflow)
    np.testing.assert_allclose(np.asarray(out["t0"], np.float32), 4.0)

    assert bool(mta.tree_finite(tree, store=store))
    bad = dict(tree, t1=tree["t1"].at[-1].set(jnp.nan))
    assert not bool(mta.tree_finite(bad, store=store))
    _, overflow = mta.multi_tensor_scale(bad, 1.0, store=store)
    assert bool(overflow)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_bucket_matrix_l2norm_matches_leafwise(native_tier, dtype):
    rng = np.random.RandomState(0)
    tree = {f"t{i}": jnp.asarray(rng.randn(s).astype(np.float32), dtype)
            for i, s in enumerate([5, 64, 257])}
    store = mta.BucketStore(tree)
    g_l, per_l = mta.multi_tensor_l2norm(tree, per_tensor=True)
    g_b, per_b = mta.multi_tensor_l2norm(tree, per_tensor=True, store=store)
    np.testing.assert_allclose(float(g_l), float(g_b), rtol=1e-5)
    for a, b in zip(per_l, per_b):
        np.testing.assert_allclose(float(a), float(b), rtol=1e-4)


def test_bucket_matrix_mixed_dtype_roundtrip(native_tier):
    tree = {"f32": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "bf16": jnp.arange(4, dtype=jnp.float32).astype(jnp.bfloat16),
            "ids": jnp.arange(3, dtype=jnp.int32)}
    store = mta.BucketStore(tree)
    assert store.n_buckets == 2
    back = store.unpack(store.pack(tree))
    for k in tree:
        assert back[k].dtype == tree[k].dtype
        np.testing.assert_array_equal(np.asarray(back[k], np.float32),
                                      np.asarray(tree[k], np.float32))
    # the knob really is set in the no-native leg (guards the fixture)
    if native_tier == "no-native":
        assert os.environ.get("APEX_TPU_DISABLE_NATIVE") == "1"
