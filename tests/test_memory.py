"""HBM memory ledger (ISSUE 10): harvest vs hand-computed buffer sizes,
the live-buffer walk, the roofline join, the runtime hook, and the
``memory_headroom`` watchdog rule.

The contracts tier-1 pins here:

* **known-matmul exactness** — on a flat matmul the walk's
  argument/output/peak bytes equal the hand-computed buffer sizes, and
  agree with ``memory_analysis()`` where the jax in use exposes it;
* **old-jax fallback** — with ``memory_analysis`` unavailable
  (monkeypatched away) the harvest degrades to the jaxpr walk with the
  same per-region attribution;
* **region attribution** — buffers live at the peak land in the
  ``prof.capture.scope`` region that produced them, fwd+bwd in one row;
* **roofline join** — ``mfu_ledger(memory=...)`` carries a nonzero
  ``total.peak_hbm_gb`` and per-region ``peak_hbm_mb`` columns;
* **watchdog** — ``memory_headroom`` fires below the floor and stays
  silent above it / with no limit.
"""

import io
import json
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import telemetry
from apex_tpu.prof import capture, memory, roofline


@pytest.fixture(autouse=True)
def _clean_recorder():
    telemetry.set_recorder(None)
    yield
    telemetry.set_recorder(None)


M, K, N = 128, 256, 64
A_BYTES = M * K * 4
B_BYTES = K * N * 4
OUT_BYTES = M * N * 4


def _mm(x, y):
    with capture.scope("mm"):
        return x @ y


def _mm_args():
    return (jnp.zeros((M, K), jnp.float32), jnp.zeros((K, N), jnp.float32))


# -- known matmul vs hand-computed sizes --------------------------------------

def test_matmul_hand_computed_sizes():
    h = memory.harvest_memory(_mm, *_mm_args())
    assert h.argument_bytes == A_BYTES + B_BYTES
    assert h.output_bytes == OUT_BYTES
    # peak: both operands + the result live together at the dot
    assert h.walk_peak_bytes == A_BYTES + B_BYTES + OUT_BYTES
    if h.source == "memory_analysis":
        # XLA's accounting agrees on this trivially-schedulable program
        assert abs(h.peak_bytes
                   - (A_BYTES + B_BYTES + OUT_BYTES)) \
            <= 0.1 * h.peak_bytes
    assert h.by_region.get("mm") == OUT_BYTES
    assert h.by_region.get("<arguments>") == A_BYTES + B_BYTES


def test_top_allocations_ranked():
    h = memory.harvest_memory(_mm, *_mm_args())
    sizes = [a["bytes"] for a in h.top_allocations]
    assert sizes == sorted(sizes, reverse=True)
    assert sizes[0] == A_BYTES                  # the biggest buffer
    shapes = {tuple(a["shape"]) for a in h.top_allocations}
    assert (M, K) in shapes and (M, N) in shapes


def test_chain_frees_dead_buffers():
    """y = relu(x @ w) @ v: the first product dies after its last use,
    so the walk peak is less than the sum of ALL buffers ever made."""
    def f(x, w, v):
        h1 = jax.nn.relu(x @ w)
        return h1 @ v
    x = jnp.zeros((64, 128), jnp.float32)
    w = jnp.zeros((128, 128), jnp.float32)
    v = jnp.zeros((128, 8), jnp.float32)
    h = memory.harvest_memory(f, x, w, v, xla=False)
    every_buffer = (64 * 128 + 128 * 128 + 128 * 8   # args
                    + 64 * 128 * 2                   # mm + relu
                    + 64 * 8) * 4                    # out
    assert h.walk_peak_bytes < every_buffer
    # floor: args + the larger intermediate + nothing freed early
    assert h.walk_peak_bytes >= (64 * 128 + 128 * 128 + 128 * 8
                                 + 64 * 128) * 4


def test_literal_outputs_survive_walk():
    """A jaxpr returning constant-folded literals (every real train
    step's metrics do) must not crash the liveness walk (regression:
    Literal is unhashable)."""
    def f(x):
        return x @ x, 1.0, jnp.float32(0)
    h = memory.harvest_memory(f, jnp.zeros((32, 32), jnp.float32),
                              xla=False)
    assert h.peak_bytes >= 2 * 32 * 32 * 4


def test_old_jax_fallback(monkeypatch):
    """memory_analysis unavailable -> jaxpr source, same attribution."""
    monkeypatch.setattr(memory, "_xla_memory", lambda *a, **k: None)
    h = memory.harvest_memory(_mm, *_mm_args())
    assert h.source == "jaxpr"
    assert h.peak_bytes == h.walk_peak_bytes \
        == A_BYTES + B_BYTES + OUT_BYTES
    assert h.by_region.get("mm") == OUT_BYTES


def test_fwd_bwd_share_region():
    """Grad of a scoped matmul: transpose(jvp(mm)) ops land in 'mm'."""
    def loss(w, x):
        with capture.scope("mm"):
            y = x @ w
        return jnp.sum(y * y)
    w = jnp.zeros((32, 16), jnp.float32)
    x = jnp.zeros((8, 32), jnp.float32)
    h = memory.harvest_memory(jax.grad(loss), w, x, xla=False)
    regions = set(h.by_region)
    assert "mm" in regions
    assert not any(r.startswith("transpose") or "jvp" in r
                   for r in regions)


# -- roofline join ------------------------------------------------------------

def test_mfu_ledger_memory_column():
    h_cost = roofline.harvest_costs(_mm, *_mm_args(), xla=False)
    h_mem = memory.harvest_memory(_mm, *_mm_args())
    ledger = roofline.mfu_ledger(
        h_cost, step_time_s=1e-3,
        peaks={"flops": 1e12, "hbm_gb_s": 100.0}, memory=h_mem)
    assert ledger["total"]["peak_hbm_gb"] > 0
    mem_sec = ledger["memory"]
    assert mem_sec["peak_hbm_gb"] == round(h_mem.peak_bytes / 1e9, 6)
    assert mem_sec["source"] == h_mem.source
    assert mem_sec["top_allocations"]
    mm_rows = [r for r in ledger["regions"] if r["region"] == "mm"]
    assert mm_rows and mm_rows[0]["peak_hbm_mb"] == round(
        OUT_BYTES / 1e6, 3)
    # the rendered report carries the new column
    text = roofline.format_ledger(ledger)
    assert "peak HBM" in text


def test_mfu_ledger_without_memory_unchanged():
    h_cost = roofline.harvest_costs(_mm, *_mm_args(), xla=False)
    ledger = roofline.mfu_ledger(
        h_cost, peaks={"flops": 1e12, "hbm_gb_s": 100.0})
    assert "memory" not in ledger
    assert "peak_hbm_gb" not in ledger["total"]


# -- runtime hook + stream ----------------------------------------------------

def _run_pipe(k=2, n=4, dim=16, warm=False):
    from apex_tpu import runtime, training
    from apex_tpu.training import make_train_step
    rs = np.random.RandomState(0)
    batches = [(rs.randn(4, dim).astype(np.float32),
                rs.randn(4, dim).astype(np.float32)) for _ in range(n)]

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2)

    init_fn, step_fn = make_train_step(loss_fn, training.sgd(lr=0.01))
    pipe = runtime.StepPipeline(step_fn, k)
    state = init_fn({"w": jnp.asarray(rs.randn(dim, dim)
                                      .astype(np.float32) / 7.0)})
    windows = list(runtime.window_batches(iter(batches), k))
    if warm:
        pipe.warmup(state, windows[0][0])
    state, reader = pipe.run(state, iter(windows))
    reader.last()
    return pipe


def test_pipeline_memory_stats_and_event():
    buf = io.StringIO()
    rec = telemetry.Recorder(buf)
    telemetry.set_recorder(rec)
    pipe = _run_pipe()
    stats = pipe.memory_stats()
    rec.close()
    if stats is None:
        pytest.skip("jax in use exposes no memory_analysis")
    assert stats["peak_bytes"] > 0
    events = [json.loads(l) for l in buf.getvalue().splitlines()]
    mem_ev = [e for e in events if e["kind"] == "memory"]
    assert len(mem_ev) == 1
    assert mem_ev[0]["peak_bytes"] == stats["peak_bytes"]
    assert rec.metrics.gauge("peak_hbm_bytes").value \
        == stats["peak_bytes"]
    # the timeline analyzer surfaces it
    from apex_tpu.prof import timeline
    a = timeline.analyze(events)
    assert a["memory"]["peak_hbm_gb"] == round(
        stats["peak_bytes"] / 1e9, 6)


def test_pipeline_memory_stats_uses_aot_executable():
    """A warmed pipeline reads memory off the held AOT executable — no
    relowering, and identical numbers to the relower path."""
    pipe_cold = _run_pipe(warm=False)
    pipe_warm = _run_pipe(warm=True)
    cold = pipe_cold.memory_stats(emit=False)
    warm = pipe_warm.memory_stats(emit=False)
    if cold is None or warm is None:
        pytest.skip("jax in use exposes no memory_analysis")
    assert warm == cold


def test_memory_stats_before_any_dispatch_is_none():
    from apex_tpu import runtime, training
    from apex_tpu.training import make_train_step
    _, step_fn = make_train_step(
        lambda p, b: jnp.sum(b[0] @ p["w"]), training.sgd(lr=0.1))
    pipe = runtime.StepPipeline(step_fn, 2)
    assert pipe.memory_stats() is None


# -- device gauges ------------------------------------------------------------

def test_device_memory_shape():
    devs = memory.device_memory()
    # CPU backends typically expose nothing; where present the dict
    # shape is pinned
    for d in devs:
        assert set(d) >= {"id", "kind", "bytes_in_use", "bytes_limit"}


def test_update_device_memory_gauges(monkeypatch, tmp_path):
    monkeypatch.setattr(
        memory, "device_memory",
        lambda: [{"id": 0, "kind": "fake", "bytes_in_use": 60,
                  "bytes_limit": 100, "peak_bytes_in_use": 70},
                 {"id": 1, "kind": "fake", "bytes_in_use": 20,
                  "bytes_limit": 100, "peak_bytes_in_use": 30}])
    rec = telemetry.start(str(tmp_path / "r.jsonl"))
    assert memory.update_device_memory_gauges(rec)
    assert rec.metrics.gauge("hbm_bytes_in_use").value == 80
    assert rec.metrics.gauge("hbm_bytes_limit").value == 200
    assert rec.metrics.gauge("hbm_headroom_pct").value == 60.0
    assert rec.metrics.gauge("hbm_peak_bytes_in_use").value == 100
    rec.close()


def test_peak_gauge_is_high_water_mark(tmp_path):
    """A smaller re-harvest must not shrink the run's recorded peak."""
    rec = telemetry.start(str(tmp_path / "r.jsonl"))
    memory.record_memory(rec, {"peak_bytes": 500, "source": "t"},
                         limit_bytes=1000)
    memory.record_memory(rec, {"peak_bytes": 200, "source": "t"},
                         limit_bytes=1000)
    assert rec.metrics.gauge("peak_hbm_bytes").value == 500
    rec.close()


# -- watchdog memory_headroom rule --------------------------------------------

def _wd_stream(events):
    from apex_tpu.telemetry import watchdog as wd_mod
    buf = io.StringIO()
    rec = telemetry.Recorder(buf)
    wd = wd_mod.attach(rec)
    for e in events:
        rec.event(e.pop("kind"), **e)
    rec.close()
    return wd, [json.loads(l) for l in buf.getvalue().splitlines()]


def test_memory_headroom_fires():
    wd, events = _wd_stream([
        {"kind": "memory", "phase": "harvest", "peak_bytes": 95,
         "bytes_limit": 100, "headroom_pct": 5.0, "source": "t"}])
    alerts = [e for e in events if e["kind"] == "alert"]
    assert len(alerts) == 1
    assert alerts[0]["rule"] == "memory_headroom"
    assert alerts[0]["severity"] == "warning"
    assert "5.0%" in alerts[0]["message"]
    assert not wd.health()["ok"]


def test_memory_headroom_derives_when_unlabelled():
    """No headroom_pct field: the rule derives it from bytes."""
    _, events = _wd_stream([
        {"kind": "memory", "phase": "device", "bytes_in_use": 97,
         "bytes_limit": 100}])
    assert any(e["kind"] == "alert"
               and e["rule"] == "memory_headroom" for e in events)


@pytest.mark.parametrize("ev", [
    # plenty of headroom
    {"kind": "memory", "phase": "harvest", "peak_bytes": 10,
     "bytes_limit": 100, "headroom_pct": 90.0},
    # no limit known (CPU): must stay silent, never divide by zero
    {"kind": "memory", "phase": "harvest", "peak_bytes": 10},
    # unrelated event kinds never fold
    {"kind": "window", "step": 0, "dur": 0.01, "gap": 0.0, "n_valid": 1},
])
def test_memory_headroom_negative_cases(ev):
    wd, events = _wd_stream([dict(ev)])
    assert not [e for e in events if e["kind"] == "alert"]
    assert wd.health()["ok"]


def test_memory_headroom_debounced():
    stream = [{"kind": "memory", "phase": "harvest", "peak_bytes": 95,
               "bytes_limit": 100, "headroom_pct": 5.0}
              for _ in range(50)]
    _, events = _wd_stream([dict(e) for e in stream])
    alerts = [e for e in events if e["kind"] == "alert"]
    assert 1 <= len(alerts) <= 2          # debounce holds the line


def test_rule_in_registry():
    from apex_tpu.telemetry.watchdog import RULE_NAMES, Watchdog
    assert "memory_headroom" in RULE_NAMES
    wd = Watchdog(min_headroom_pct=25.0)
    rule = next(r for r in wd.rules if r.name == "memory_headroom")
    assert rule.min_headroom_pct == 25.0


# -- CLI ----------------------------------------------------------------------

def test_cli_json(tmp_path, capsys, monkeypatch):
    import sys
    import types
    mod = types.ModuleType("_memtarget")
    mod.entry = lambda: (_mm, _mm_args())
    monkeypatch.setitem(sys.modules, "_memtarget", mod)
    rc = memory.main(["--fn", "_memtarget:entry", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["argument_bytes"] == A_BYTES + B_BYTES
    assert out["by_region"]["mm"] == OUT_BYTES
    rc = memory.main(["--fn", "_memtarget:entry", "--no-xla"])
    assert rc == 0
    assert "memory ledger (jaxpr)" in capsys.readouterr().out
